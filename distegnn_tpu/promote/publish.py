"""Candidate publishing: the trainer end of the promotion conveyor.

After each checkpoint rotation the trainer calls
:meth:`CandidatePublisher.publish` with the freshly written ``step_<n>.ckpt``.
The publisher copies it into the watched conveyor directory and then writes a
JSON *candidate manifest* next to it — both through the shard writer's
tmp+fsync+rename discipline, manifest strictly LAST — so the promoter's
watcher has one invariant to trust: **a manifest implies a complete,
checksummed checkpoint**. A trainer killed mid-publish leaves at worst a
stale ``.tmp.*`` file that the next publish sweeps up; it can never leave a
half-candidate that a promoter would try to canary.

Manifest fields (``step_<n>.json``)::

    {"step": n, "ckpt": "step_<n>.ckpt", "crc32": ..., "size": ...,
     "val_loss": ... | null, "config_hash": "..." | null, "time": ...}

``crc32``/``size`` cover the published checkpoint bytes; the promoter
re-verifies them before restoring (torn copies and bit-rot are rejected at
the conveyor, not at swap time).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import tempfile
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from distegnn_tpu import obs

_CAND_RE = re.compile(r"^step_(\d+)\.json$")


def candidate_manifest_name(step: int) -> str:
    return f"step_{int(step):010d}.json"


def config_hash(config: Optional[dict]) -> Optional[str]:
    """Stable short hash of a config mapping (sorted-key JSON, sha256/12):
    the promoter surfaces it so a fleet running candidate N is attributable
    to the exact training config that produced it."""
    if config is None:
        return None
    try:
        blob = json.dumps(config, sort_keys=True, default=repr)
    except (TypeError, ValueError):
        blob = repr(sorted(config.items()))
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def _write_atomic(path: str, blob: bytes) -> None:
    """tmp + fsync + rename in the target directory (same idiom as
    checkpoint._write_manifest / the shard writer): readers never observe a
    partial file, and a crash leaves only a ``.tmp.*`` orphan."""
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".tmp.")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class CandidatePublisher:
    """Atomically publish rotated checkpoints into the conveyor directory.

    Args:
      watch_dir: the conveyor directory the promoter polls. Created on
        first publish.
      history: candidates retained; older (step, ckpt, manifest) pairs are
        pruned after each publish — manifest FIRST, so a candidate is
        withdrawn before its bytes disappear.
    """

    def __init__(self, watch_dir: str, history: int = 4):
        self.watch_dir = str(watch_dir)
        self.history = max(int(history), 1)
        self.published = 0

    def publish(self, ckpt_path: str, step: int,
                val_loss: Optional[float] = None,
                config: Optional[dict] = None) -> str:
        """Copy ``ckpt_path`` into the conveyor and manifest it. Returns the
        manifest path. Raises on I/O failure — the caller (trainer) treats a
        failed publish as non-fatal: training never stops for the conveyor."""
        t0 = time.perf_counter()
        os.makedirs(self.watch_dir, exist_ok=True)
        self._sweep_tmp()
        with open(ckpt_path, "rb") as f:
            blob = f.read()
        step = int(step)
        name = f"step_{step:010d}.ckpt"
        dst = os.path.join(self.watch_dir, name)
        _write_atomic(dst, blob)
        manifest = {
            "step": step,
            "ckpt": name,
            "crc32": zlib.crc32(blob) & 0xFFFFFFFF,
            "size": len(blob),
            "val_loss": None if val_loss is None else float(val_loss),
            "config_hash": config_hash(config),
            "time": time.time(),
        }
        mpath = os.path.join(self.watch_dir, candidate_manifest_name(step))
        _write_atomic(mpath, json.dumps(manifest, indent=2).encode())
        self.published += 1
        obs.event("promote/publish", step=step, bytes=len(blob),
                  val_loss=manifest["val_loss"],
                  config_hash=manifest["config_hash"],
                  dur_s=round(time.perf_counter() - t0, 6))
        self._prune()
        return mpath

    def _sweep_tmp(self) -> None:
        """Remove orphaned ``.tmp.*`` files from a previous publisher that
        died mid-write (the trainer-kill chaos injection's residue)."""
        try:
            names = os.listdir(self.watch_dir)
        except OSError:
            return
        for n in names:
            if ".tmp." in n:
                try:
                    os.unlink(os.path.join(self.watch_dir, n))
                except OSError:
                    pass

    def _prune(self) -> None:
        steps = sorted(s for s, _ in _scan(self.watch_dir))
        for s in steps[:-self.history]:
            m = os.path.join(self.watch_dir, candidate_manifest_name(s))
            c = os.path.join(self.watch_dir, f"step_{s:010d}.ckpt")
            for path in (m, c):  # manifest first: withdraw, then delete
                try:
                    os.unlink(path)
                except OSError:
                    pass


def _scan(watch_dir: str) -> List[Tuple[int, str]]:
    try:
        names = os.listdir(watch_dir)
    except OSError:
        return []
    out = []
    for n in names:
        m = _CAND_RE.match(n)
        if m:
            out.append((int(m.group(1)), os.path.join(watch_dir, n)))
    return out


def list_candidates(watch_dir: str) -> List[int]:
    """Steps with a manifest present, ascending. Presence of the manifest is
    the publication event; the checkpoint itself is verified by
    :func:`read_candidate`."""
    return sorted(s for s, _ in _scan(watch_dir))


def read_candidate(watch_dir: str, step: int) -> Dict[str, Any]:
    """Load + verify one candidate: manifest parses, checkpoint exists, and
    its bytes match the manifest's crc32/size. Returns the manifest dict
    with an absolute ``ckpt_path`` added. Raises ValueError on any mismatch
    (the promoter rejects, it never canaries a torn candidate)."""
    mpath = os.path.join(watch_dir, candidate_manifest_name(step))
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as exc:
        raise ValueError(f"candidate step {step}: unreadable manifest "
                         f"{mpath}: {exc}") from None
    ckpt = os.path.join(watch_dir, str(manifest.get("ckpt", "")))
    try:
        with open(ckpt, "rb") as f:
            blob = f.read()
    except OSError as exc:
        raise ValueError(f"candidate step {step}: missing checkpoint "
                         f"{ckpt}: {exc}") from None
    if len(blob) != int(manifest.get("size", -1)):
        raise ValueError(f"candidate step {step}: size mismatch "
                         f"({len(blob)} != {manifest.get('size')})")
    if (zlib.crc32(blob) & 0xFFFFFFFF) != int(manifest.get("crc32", -1)):
        raise ValueError(f"candidate step {step}: crc32 mismatch")
    manifest["ckpt_path"] = ckpt
    return manifest


__all__ = ["CandidatePublisher", "candidate_manifest_name", "config_hash",
           "list_candidates", "read_candidate"]
