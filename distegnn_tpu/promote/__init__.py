"""Continuous train->serve promotion conveyor (docs/SERVING.md
"Continuous promotion").

The trainer end publishes every rotated checkpoint as a *candidate* —
``step_<n>.ckpt`` plus a JSON manifest — into a watched directory
(:mod:`distegnn_tpu.promote.publish`). The serving end runs a control loop
(:mod:`distegnn_tpu.promote.promoter`) that canaries each new candidate on
one quarantined replica, replays a shadow sample of live traffic against
it, and promotes fleet-wide or rolls back on two gates: the gateway's
rolling SLO window and the per-rung prediction-drift gauge
(:mod:`distegnn_tpu.promote.drift`).
"""

from distegnn_tpu.promote.drift import DriftGauge
from distegnn_tpu.promote.promoter import Promoter
from distegnn_tpu.promote.publish import (CandidatePublisher, config_hash,
                                          list_candidates, read_candidate)

__all__ = [
    "CandidatePublisher",
    "DriftGauge",
    "Promoter",
    "config_hash",
    "list_candidates",
    "read_candidate",
]
