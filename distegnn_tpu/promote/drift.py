"""Prediction-drift gauge: the promotion conveyor's second gate.

Every shadow replay produces a (live output, canary output) pair for one
warmed rung — the padded node-count bucket the request landed in. The gauge
accumulates the **relative L2 divergence** per rung::

    d = ||canary - live||_2 / (||live||_2 + eps)

and the promoter's verdict keys on the per-rung MEAN exceeding a configured
ceiling (mean, not max: one hard graph is noise, a shifted mean is a model
that disagrees with production). A candidate whose outputs are NaN/Inf on
any shadow pair drifts unconditionally — the engine's canary catches
non-finite on the warmed rungs, this catches it on real traffic shapes.

The gauge is cheap enough to sit in the shadow completion callback (two
norms on [n, 3] arrays) and thread-safe: shadow futures complete on
dispatcher threads while the promoter reads verdicts from its control loop.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np


class _RungStats:
    __slots__ = ("count", "total", "worst", "nonfinite")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.worst = 0.0
        self.nonfinite = 0


class DriftGauge:
    """Per-rung canary-vs-live output divergence with a configurable ceiling.

    Args:
      ceiling: per-rung mean relative divergence above which
        :meth:`drifted` reports True.
      min_samples: comparisons required before :meth:`decided` is True
        (summed across rungs — low-traffic canaries still reach a verdict).
    """

    def __init__(self, ceiling: float = 0.05, min_samples: int = 8):
        self.ceiling = float(ceiling)
        self.min_samples = max(int(min_samples), 1)
        self._lock = threading.Lock()
        self._rungs: Dict[str, _RungStats] = {}

    def observe(self, rung: str, live, canary) -> float:
        """Record one shadow comparison; returns the divergence recorded."""
        live = np.asarray(live, dtype=np.float64)
        canary = np.asarray(canary, dtype=np.float64)
        if (live.shape != canary.shape or not np.isfinite(canary).all()
                or not np.isfinite(live).all()):
            with self._lock:
                st = self._rungs.setdefault(str(rung), _RungStats())
                st.count += 1
                st.nonfinite += 1
            return float("inf")
        denom = float(np.linalg.norm(live)) + 1e-12
        d = float(np.linalg.norm(canary - live)) / denom
        with self._lock:
            st = self._rungs.setdefault(str(rung), _RungStats())
            st.count += 1
            st.total += d
            st.worst = max(st.worst, d)
        return d

    @property
    def samples(self) -> int:
        with self._lock:
            return sum(st.count for st in self._rungs.values())

    def decided(self) -> bool:
        """Enough evidence for a verdict: the sample floor is met, or any
        rung already drifted (no point waiting to reject)."""
        return self.samples >= self.min_samples or self.drifted()

    def drifted(self) -> bool:
        with self._lock:
            for st in self._rungs.values():
                if st.nonfinite:
                    return True
                if st.count and st.total / st.count > self.ceiling:
                    return True
        return False

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Per-rung {count, mean, max, nonfinite} for events and /readyz."""
        with self._lock:
            return {
                rung: {
                    "count": st.count,
                    "mean": round(st.total / st.count, 6) if st.count else 0.0,
                    "max": round(st.worst, 6),
                    "nonfinite": st.nonfinite,
                }
                for rung, st in self._rungs.items()
            }

    def export(self, registry, prefix: str = "promote/drift") -> None:
        """Push per-rung mean/max gauges into an obs MetricsRegistry so the
        drift verdict is reconstructible from a /metrics scrape alone."""
        if registry is None:
            return
        for rung, row in self.snapshot().items():
            registry.gauge(f"{prefix}_{rung}_mean").set(row["mean"])
            registry.gauge(f"{prefix}_{rung}_max").set(row["max"])

    def reset(self) -> None:
        with self._lock:
            self._rungs.clear()


__all__ = ["DriftGauge"]
