"""The promotion control loop — the serving end of the conveyor.

One :class:`Promoter` watches the conveyor directory the trainer publishes
into and walks each new candidate through the promotion lifecycle::

    candidate (manifest + CRC-verified ckpt)
      -> canary: quarantine ONE replica out of live round-robin, hot-swap
         the candidate onto it (the same restore/canary machinery a
         blue/green ``ModelEntry.swap`` uses, pinned to one replica)
      -> shadow: the gateway tees a sample of live predict traffic to the
         canary's queue; responses feed the drift gauge, never clients
      -> verdict: promote fleet-wide (``entry.swap`` — re-canaries every
         replica) or roll the canary back, purely on two gates:
           drift  — per-rung canary-vs-live divergence under the ceiling
           SLO    — the gateway's rolling window error rate stayed clean

A canary that dies mid-shadow (SIGKILLed worker, crashed dispatcher) rolls
back immediately: the supervisor restarts the replica as usual and the
rollback re-pins its params to the live version. A candidate that loses is
never retried — the trainer's next publish is the retry.

Structure mirrors :class:`~distegnn_tpu.serve.autoscale.ReplicaAutoscaler`:
module ``_DEFAULTS`` in lockstep with ``config._DEFAULTS["promote"]``
(scripts/check_config_keys.py asserts it), a public synchronous
``tick(now=...)`` for synthetic-clock tests, a daemon-thread loop, obs
events per decision, and a ``status()`` dict surfaced on ``/readyz``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from distegnn_tpu import obs
from distegnn_tpu.promote.drift import DriftGauge
from distegnn_tpu.promote.publish import list_candidates, read_candidate

# knob defaults — kept in lockstep with config._DEFAULTS["promote"]
# (scripts/check_config_keys.py asserts the config side; this dict is the
# in-code fallback for hand-built configs)
_DEFAULTS: Dict[str, Any] = {
    "enable": False,
    "publish": False,
    "watch_dir": "",
    "model": "",
    "interval_s": 1.0,
    "history": 4,
    "shadow_sample": 0.25,
    "min_shadow": 8,
    "max_shadow_inflight": 8,
    "gate_timeout_s": 30.0,
    "drift_ceiling": 0.05,
    "max_error_rate": 0.0,
}


class _CanaryRun:
    """One candidate's trip through canary + shadow."""

    __slots__ = ("step", "manifest", "ckpt_path", "entry_name", "replica",
                 "old_params", "gauge", "started", "shadow_errors",
                 "shadow_teed", "shadow_skipped")

    def __init__(self, step, manifest, ckpt_path, entry_name, replica,
                 old_params, gauge, started):
        self.step = step
        self.manifest = manifest
        self.ckpt_path = ckpt_path
        self.entry_name = entry_name
        self.replica = replica
        self.old_params = old_params
        self.gauge = gauge
        self.started = started
        self.shadow_errors = 0
        self.shadow_teed = 0
        self.shadow_skipped = 0


def replica_on_live_version(entry, replica) -> bool:
    """Is one replica serving the entry's live version? Process-backed
    replicas compare checkpoints (their params live in the child); thread
    replicas compare params object identity (flips share the object)."""
    ck = getattr(replica, "current_checkpoint", None)
    if ck is not None or getattr(replica, "_ckpt_lock", None) is not None:
        return str(ck) == str(entry.checkpoint)
    eng = getattr(replica, "engine", None)
    return eng is not None and eng.params is entry.engine.params


def fleet_coherent(entry) -> bool:
    """True when every replica serves the entry's live version — the
    /readyz coherence signal the promotion drill asserts on."""
    return all(replica_on_live_version(entry, r)
               for r in entry.replicas.replicas)


class Promoter:
    """Candidate watcher + canary/shadow/gate state machine for one model.

    Args:
      registry: the ModelRegistry whose entry promotes.
      monitor: the gateway's SLOMonitor (``window_snapshot`` source); None
        disables the SLO gate (drift still decides).
      config: the ``promote:`` mapping (missing keys take defaults).
      metrics_registry: obs MetricsRegistry for the conveyor gauges (None
        skips gauge export).
    """

    def __init__(self, registry, monitor=None, *,
                 config: Optional[dict] = None, metrics_registry=None):
        knobs = dict(_DEFAULTS)
        knobs.update(dict(config or {}))
        self.enable = bool(knobs["enable"]) and bool(
            str(knobs["watch_dir"]).strip())
        self.watch_dir = str(knobs["watch_dir"])
        self.model = str(knobs["model"])
        self.interval_s = float(knobs["interval_s"])
        self.shadow_sample = float(knobs["shadow_sample"])
        self.min_shadow = max(1, int(knobs["min_shadow"]))
        self.max_shadow_inflight = max(1, int(knobs["max_shadow_inflight"]))
        self.gate_timeout_s = float(knobs["gate_timeout_s"])
        self.drift_ceiling = float(knobs["drift_ceiling"])
        self.max_error_rate = float(knobs["max_error_rate"])
        self.registry = registry
        self.monitor = monitor
        self._reg = metrics_registry
        self._lock = threading.Lock()   # one tick at a time (loop vs tests)
        self._canary: Optional[_CanaryRun] = None
        self._shadow_inflight = 0
        self._tee_seen = 0
        self.last_step = -1             # highest candidate step resolved
        self.fleet_step: Optional[int] = None
        self.promoted = 0
        self.rolled_back = 0
        self.rejected = 0
        self.results: List[dict] = []   # bounded decision history
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> "Promoter":
        if self._thread is not None or not self.enable:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-promoter", daemon=True)
        self._thread.start()
        return self

    def stop(self, join_timeout_s: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=join_timeout_s)
        self._thread = None
        # leave no replica stranded out of rotation on shutdown
        with self._lock:
            run = self._canary
            if run is not None:
                self._rollback(run, reason="promoter_stopped")

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as exc:  # the loop must outlive any one tick
                obs.log(f"promote: tick failed: {exc!r}")
            self._stop.wait(self.interval_s)

    # ---- the control loop body -------------------------------------------
    def tick(self, now: Optional[float] = None) -> None:
        """One synchronous evaluation. ``now`` overrides the clock for the
        gate-timeout bookkeeping AND the SLO window snapshot, so tests
        drive the state machine with a synthetic clock."""
        with self._lock:
            t = time.monotonic() if now is None else float(now)
            if self._canary is None:
                self._scan(t)
            else:
                self._evaluate(t, now)
            self._export()

    def _entry(self):
        names = self.registry.names()
        if not names:
            return None
        name = self.model or names[0]
        try:
            return self.registry.get(name)
        except KeyError:
            return None

    def _scan(self, t: float) -> None:
        entry = self._entry()
        if entry is None or entry.state != "ready":
            return
        steps = [s for s in list_candidates(self.watch_dir)
                 if s > self.last_step]
        if not steps:
            return
        step = max(steps)
        if len(steps) > 1:
            obs.event("promote/candidates_skipped", model=entry.name,
                      skipped=steps[:-1], chosen=step)
        try:
            manifest = read_candidate(self.watch_dir, step)
        except ValueError as exc:
            self._resolve(None, entry, step, "rejected",
                          reason=f"verify: {exc}")
            return
        from distegnn_tpu.train.checkpoint import restore_params

        old_params = entry.engine.params
        try:
            new_params = restore_params(manifest["ckpt_path"], old_params)
        except Exception as exc:
            self._resolve(None, entry, step, "rejected",
                          reason=f"restore: {exc!r}"[:300])
            return
        replica = self._pick_canary(entry)
        if replica is None or not entry.replicas.quarantine(replica.idx):
            # single-replica fleet: no slice to spare — fall through to the
            # plain blue/green swap (its own canary still gates the flip)
            self._direct_promote(entry, step, manifest)
            return
        try:
            checked = replica.swap_params(manifest["ckpt_path"], new_params,
                                          list(entry.warmed))
        except Exception as exc:
            entry.replicas.release(replica.idx)
            self._resolve(None, entry, step, "rejected",
                          reason=f"canary: {exc!r}"[:300])
            return
        gauge = DriftGauge(ceiling=self.drift_ceiling,
                           min_samples=self.min_shadow)
        self._canary = _CanaryRun(step, manifest, manifest["ckpt_path"],
                                  entry.name, replica, old_params, gauge, t)
        self._tee_seen = 0
        obs.event("promote/canary_begin", model=entry.name, step=step,
                  replica=replica.idx, rungs=checked,
                  val_loss=manifest.get("val_loss"),
                  config_hash=manifest.get("config_hash"))

    def _pick_canary(self, entry):
        """Highest-index healthy replica that isn't replica 0 (the
        registry's engine handle stays live)."""
        cands = [r for r in entry.replicas.replicas
                 if r.healthy() and r is not entry.replicas.replicas[0]]
        return cands[-1] if cands else None

    def _evaluate(self, t: float, now: Optional[float]) -> None:
        run = self._canary
        entry = self._entry()
        if entry is None:
            self._rollback(run, reason="entry_gone")
            return
        if not run.replica.healthy():
            # the chaos case: canary SIGKILLed/crashed mid-promotion. Roll
            # back NOW — the supervisor restart re-enters through the
            # replica's normal lifecycle and the rollback re-pins the live
            # version; the candidate is spent.
            self._rollback(run, reason="canary_died")
            return
        if run.gauge.drifted():
            self._rollback(run, reason="drift")
            return
        timed_out = t - run.started >= self.gate_timeout_s
        if run.gauge.samples >= self.min_shadow or timed_out:
            if run.gauge.samples == 0:
                self._rollback(run, reason="insufficient_shadow")
                return
            if not self._slo_ok(now):
                self._rollback(run, reason="slo")
                return
            self._promote(run, entry)

    def _slo_ok(self, now: Optional[float]) -> bool:
        if self.monitor is None:
            return True
        snap = self.monitor.window_snapshot(now=now)
        return float(snap.get("error_rate", 0.0)) <= self.max_error_rate

    # ---- verdicts --------------------------------------------------------
    def _promote(self, run: _CanaryRun, entry) -> None:
        from distegnn_tpu.serve.registry import (SwapError,
                                                 SwapInProgressError)
        try:
            result = entry.swap(run.ckpt_path)
        except SwapInProgressError:
            return  # a manual swap holds the lock; retry next tick
        except SwapError as exc:
            run.replica.swap_rollback(run.old_params)
            entry.replicas.release(run.replica.idx)
            self._resolve(run, entry, run.step, "rolled_back",
                          reason=f"fleet_swap: {exc}"[:300])
            return
        entry.replicas.release(run.replica.idx)
        self.fleet_step = run.step
        self._resolve(run, entry, run.step, "promoted",
                      version=result["version"])

    def _rollback(self, run: _CanaryRun, reason: str) -> None:
        try:
            run.replica.swap_rollback(run.old_params)
        except Exception as exc:
            obs.log(f"promote: canary rollback raised {exc!r}; the "
                    "supervisor restart restores the live version")
        entry = self._entry()
        if entry is not None:
            entry.replicas.release(run.replica.idx)
        self._resolve(run, entry, run.step, "rolled_back", reason=reason)

    def _resolve(self, run: Optional[_CanaryRun], entry, step: int,
                 outcome: str, **extra) -> None:
        self.last_step = max(self.last_step, int(step))
        self._canary = None
        if outcome == "promoted":
            self.promoted += 1
        elif outcome == "rolled_back":
            self.rolled_back += 1
        else:
            self.rejected += 1
        rec = {"step": int(step), "outcome": outcome, **extra}
        if run is not None:
            rec["shadow"] = {"teed": run.shadow_teed,
                             "errors": run.shadow_errors,
                             "skipped": run.shadow_skipped,
                             "drift": run.gauge.snapshot()}
        self.results.append(rec)
        del self.results[:-16]
        obs.event(f"promote/{outcome}",
                  model=None if entry is None else entry.name, **rec)

    def _direct_promote(self, entry, step: int, manifest: dict) -> None:
        from distegnn_tpu.serve.registry import (SwapError,
                                                 SwapInProgressError)
        try:
            result = entry.swap(manifest["ckpt_path"])
        except SwapInProgressError:
            return  # retry next tick; last_step untouched
        except SwapError as exc:
            self._resolve(None, entry, step, "rolled_back",
                          reason=f"direct_swap: {exc}"[:300])
            return
        self.fleet_step = step
        self._resolve(None, entry, step, "promoted",
                      version=result["version"], direct=True)

    # ---- the shadow tee (called from the gateway's predict hot path) ------
    def tee(self, model: str, graph: dict, bucket, request_id: str,
            live_out) -> None:
        """Mirror one live predict to the canary. Sampled, bounded, and
        silent: nothing that happens here may perturb the live response
        (the caller already holds the client's result)."""
        run = self._canary
        if run is None or run.entry_name != model:
            return
        try:
            self._tee_seen += 1
            stride = max(1, round(1.0 / self.shadow_sample))
            if (self._tee_seen - 1) % stride:
                run.shadow_skipped += 1
                return
            if self._shadow_inflight >= self.max_shadow_inflight:
                run.shadow_skipped += 1
                return
            if bucket is not None:
                rung = f"n{bucket.n}"
            else:
                # plain predicts reach the queue unbucketed; rung by the
                # raw node count so the gauge still resolves per size
                loc = graph.get("loc") if isinstance(graph, dict) else None
                rung = f"g{len(loc)}" if loc is not None else "n?"
            fut = run.replica.queue.submit(
                graph, bucket=bucket, request_id=f"shadow-{request_id}")
            self._shadow_inflight += 1
            run.shadow_teed += 1
            fut.add_done_callback(
                lambda f, run=run, rung=rung, live=live_out:
                self._on_shadow_done(run, rung, live, f))
        except Exception:
            run.shadow_skipped += 1  # canary full/dying — never the client's
            # problem; the gate's evidence just accumulates slower

    def _on_shadow_done(self, run: _CanaryRun, rung: str, live, fut) -> None:
        with self._lock:
            self._shadow_inflight = max(0, self._shadow_inflight - 1)
            if self._canary is not run:
                return  # verdict already landed; late shadow is noise
            exc = fut.exception()
            if exc is not None:
                run.shadow_errors += 1
                return
            try:
                run.gauge.observe(rung, live, fut.result())
            except Exception as e:
                obs.log(f"promote: drift observe failed: {e!r}")
                run.shadow_errors += 1

    # ---- health / metrics surfaces ---------------------------------------
    def status(self) -> Dict[str, Any]:
        """Promotion state for /readyz: conveyor position, verdict counts,
        and the fleet-version coherence bit the drill asserts on."""
        entry = self._entry()
        run = self._canary
        out = {
            "enable": self.enable,
            "state": "canary" if run is not None else "idle",
            "watch_dir": self.watch_dir,
            "last_step": self.last_step,
            "fleet_step": self.fleet_step,
            "promoted": self.promoted,
            "rolled_back": self.rolled_back,
            "rejected": self.rejected,
            "results": list(self.results[-4:]),
        }
        if entry is not None:
            out["model"] = entry.name
            out["params_version"] = entry.params_version
            out["fleet_coherent"] = (run is None
                                     and fleet_coherent(entry))
        if run is not None:
            out["canary"] = {"step": run.step, "replica": run.replica.idx,
                             "teed": run.shadow_teed,
                             "errors": run.shadow_errors,
                             "samples": run.gauge.samples,
                             "drift": run.gauge.snapshot()}
        return out

    def export(self) -> None:
        """Refresh the conveyor gauges (called by the gateway's /metrics
        render so a scrape never sees stale verdict counters)."""
        with self._lock:
            self._export()

    def _export(self) -> None:
        if self._reg is None:
            return
        self._reg.gauge("promote/fleet_step").set(
            -1 if self.fleet_step is None else self.fleet_step)
        self._reg.gauge("promote/last_step").set(self.last_step)
        self._reg.gauge("promote/canary_active").set(
            0 if self._canary is None else 1)
        self._reg.gauge("promote/promoted_total").set(self.promoted)
        self._reg.gauge("promote/rolled_back_total").set(self.rolled_back)
        self._reg.gauge("promote/rejected_total").set(self.rejected)
        if self._canary is not None:
            self._canary.gauge.export(self._reg)


def watch_dir_from_config(cfg) -> str:
    """Resolve the conveyor directory from a config mapping (empty when
    promotion is unconfigured)."""
    pm = (cfg.get("promote") or {}) if hasattr(cfg, "get") else {}
    return str(pm.get("watch_dir", "") or "")


__all__ = ["Promoter", "fleet_coherent", "replica_on_live_version",
           "watch_dir_from_config"]
