"""Config system — YAML schema + CLI overrides + derived fields.

Mirrors the reference's config flow (main.py:96-157): a four-section YAML
(model/data/train/log + seed), an argparse layer that overrides 11 chosen
fields one-by-one, and derived fields injected at load time (world_size,
exp_name). The reference uses EasyDict with zero validation; here we add a
defaults/validation layer (SURVEY.md §5.6 flags its absence as a gap) while
keeping the exact same YAML schema so reference configs load unchanged.

TPU deltas:
  - ``train.device`` (a CUDA ordinal in the reference) is accepted but ignored;
    device placement is the mesh's job (distegnn_tpu.parallel.mesh).
  - ``data.world_size`` derives from ``len(jax.devices())`` (reference:
    torch.cuda.device_count(), main.py:143) but may be overridden for
    CPU-simulated meshes.
"""

from __future__ import annotations

import argparse
import copy
import time
from typing import Any, Mapping, Optional

import yaml


class ConfigDict(dict):
    """dict with attribute access, recursively (the EasyDict role)."""

    def __init__(self, data: Optional[Mapping] = None):
        super().__init__()
        for k, v in (data or {}).items():
            self[k] = ConfigDict(v) if isinstance(v, Mapping) and not isinstance(v, ConfigDict) else v

    def __getattr__(self, name: str) -> Any:
        try:
            return self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    def __setattr__(self, name: str, value: Any) -> None:
        self[name] = ConfigDict(value) if isinstance(value, Mapping) and not isinstance(value, ConfigDict) else value

    def __deepcopy__(self, memo):
        return ConfigDict({k: copy.deepcopy(v, memo) for k, v in self.items()})

    def to_dict(self) -> dict:
        return {k: v.to_dict() if isinstance(v, ConfigDict) else v for k, v in self.items()}


# Defaults merged under the YAML (YAML wins). Field set = union of the five
# reference configs (config/*.yaml) — same names, same sections.
_DEFAULTS: dict = {
    "seed": 43,
    "model": {
        "model_name": "FastEGNN",
        "normalize": False,
        "hidden_nf": 64,
        "n_layers": 4,
        "virtual_channels": 3,
        "node_feat_nf": 2,
        "node_attr_nf": 0,
        "edge_attr_nf": 2,
        "checkpoint": None,
        # TPU knobs: 'bf16' runs invariant-channel MLPs at MXU-native
        # precision (geometry stays f32 — see docs/PERFORMANCE.md); remat
        # recomputes each layer in backward, trading FLOPs for HBM headroom
        "compute_dtype": None,
        "remat": False,
        # lowering of the blocked edge ops (only used when data.edge_block>0):
        # 'einsum' (one-hot materialized once per forward, aggregations and
        # gathers become batched MXU dots — default) or 'pallas' (one-hot
        # built in VMEM per kernel) — see ops/blocked.py
        "blocked_impl": "einsum",
        # FastEGNN + FastSchNet: evaluate the edge MLPs' first Dense on the
        # node axis (FastEGNN's phi_e; FastSchNet's phi_e AND its SchNet
        # coordinate gate) — same math, E/N x fewer matmul rows. Flipping it
        # changes those models' param trees (checkpoints are incompatible
        # across the flag; restore fails with a clear error)
        "hoist_edge_mlp": True,
        # plain-layout aggregation lowering (see ops/segment.py; Fast*
        # families only): 'scatter' = XLA sorted scatter (bit-exact),
        # 'cumsum' = scatter-free prefix-sum differences (f32-rounded),
        # 'ell' = scatter-free fixed-degree gathers (exact).
        "segment_impl": "scatter",
        # one packed aggregation pass per EGCL layer (translations + edge
        # features + count in a single segment sum; EdgeOps.agg_rows_pair)
        "fuse_agg": True,
        # packed-aggregation stream dtype: null (f32) or 'bf16' (halves the
        # dominant read bytes; f32 accumulation; rounds geometry columns —
        # measured opt-in, see docs/PERFORMANCE.md round-4 attack)
        "agg_dtype": None,
        # real-edge lowering (FastEGNN): 'plain' (EdgeOps streams, any
        # layout) or 'fused' (one Pallas pass per layer over the blocked
        # in-window edges + compact remote tail, ops/edge_pipeline).
        # 'fused' requires data.edge_block >= 512 (multiple of 512) and
        # edge_attr_nf == 2; loaders then build split_remote batches.
        "edge_impl": "plain",
    },
    "data": {
        "data_dir": "./data",
        "dataset_name": "nbody_100",
        "max_samples": 5000,
        "batch_size": 1,
        "accelerate_mode": "cutoff_edges",  # or 'distribute'
        # cutoff_edges mode:
        "radius": -1.0,
        "cutoff_rate": 0.0,
        # distribute mode:
        "outer_radius": None,
        "inner_radius": None,
        "split_mode": "metis",
        # per-dataset frame selection:
        "frame_0": 30,
        "frame_T": 40,
        "delta_t": 20,
        "backbone": True,
        "test_rot": False,
        "test_trans": False,
        # spatial node relabeling for edge-op locality (TPU-only knob;
        # ops/order.py): 'none' or 'morton' (Z-curve sort of positions —
        # model-equivalent up to permutation, cache-friendly gathers)
        "node_order": "none",
        # padding buckets (TPU-only knobs; static-shape batching):
        "node_bucket": 8,
        "edge_bucket": 128,
        # blocked edge layout for the MXU aggregation kernels (ops/blocked.py):
        # 0 = off; 256 = recommended for large graphs (>= a few thousand nodes)
        "edge_block": 0,
        # mesh data axis (TPU-only): graphs-per-step = batch_size *
        # data_parallel, sharded over DATA_AXIS; devices used =
        # world_size * data_parallel (distegnn_tpu/parallel/mesh.py)
        "data_parallel": 1,
        # input pipeline (data/stream.py): prefetch_depth batches produced
        # ahead by a background thread (0 = synchronous blocking put);
        # stream_shard_cache decoded shards resident per StreamedGraphDataset
        # when a dataset path is a shard directory (scripts/shard_dataset.py)
        "prefetch_depth": 2,
        "stream_shard_cache": 4,
    },
    "train": {
        "learning_rate": 5e-4,
        "weight_decay": 1e-12,
        "epochs": 2500,
        "early_stop": 2500,
        "device": None,  # accepted for reference-config compat; unused on TPU
        "mmd": {"sigma": 1.5, "weight": 0.03, "samples": 3},
        "accumulation_steps": 1,
        "warmup_epochs": 0,
        "scheduler": "None",
        # TPU-only: 'auto'|True|False — run each epoch as ONE lax.scan program
        # over a device-resident dataset (train/scan_epoch.py). 'auto' enables
        # it for single-process cutoff_edges runs whose dataset fits in HBM.
        "scan_epochs": "auto",
        # resilience layer (docs/ROBUSTNESS.md):
        # resume: null (fresh run) | 'auto' (scan log.log_dir for the newest
        # CHECKSUM-VALID checkpoint, skipping corrupt/truncated files) | an
        # explicit checkpoint path (fails loudly if corrupt)
        "resume": None,
        # mid-epoch wall-clock checkpoint cadence in seconds (0 = only the
        # best/last eval-epoch saves); step_<n>.ckpt files rotate, keeping
        # the newest keep_checkpoints
        "checkpoint_interval_s": 0,
        "keep_checkpoints": 3,
        # non-finite epoch loss: roll back to the last finite state, multiply
        # the LR by divergence_lr_decay, retry — up to divergence_retries
        # times before declaring the run dead in log.json (0 = old
        # stop-on-NaN behavior)
        "divergence_retries": 2,
        "divergence_lr_decay": 0.5,
    },
    # serving layer (distegnn_tpu/serve, docs/SERVING.md) — the bucket
    # ladder, micro-batcher, and compile cache of the inference engine
    "serve": {
        # geometric (N, E) shape ladder: rung k = floor * growth^k rounded
        # to the multiples; requests above the caps are rejected (admission
        # control), not compiled
        "node_floor": 64,
        "edge_floor": 256,
        "growth": 2.0,
        "node_multiple": 8,
        "edge_multiple": 128,
        "max_nodes": 65536,
        "max_edges": 1 << 20,
        # micro-batcher: coalesce same-bucket requests up to max_batch or
        # until the oldest has waited batch_deadline_ms; every compiled
        # program runs at EXACTLY max_batch (one executable per rung)
        "max_batch": 8,
        "batch_deadline_ms": 5.0,
        # bounded ingress (submits beyond it fail fast = backpressure) and
        # per-request queued-time deadline
        "queue_capacity": 256,
        "request_timeout_ms": 1000.0,
        # compile-cache LRU size (live executables) and input-buffer
        # donation: 'auto' = donate on TPU only (CPU ignores donation)
        "cache_size": 32,
        "donate": "auto",
        # hard-deadline headroom on top of request_timeout_ms: a no-timeout
        # ServeFuture.result() waits at most timeout+margin, so a wedged
        # dispatcher surfaces as RequestTimeoutError (the gateway's 504)
        "result_margin_s": 30.0,
        # optional K-step rollout serving (rollout.make_rollout_fn kwargs);
        # null disables the rollout endpoint
        "rollout": None,
        # session-affinity graph-prep cache (serve/prep.py): capacity of the
        # per-model LRU keyed on the client session_id; 0 disables. A hit
        # skips Morton relabel + blocked re-pack + remote classify for
        # repeat-topology requests (prep_ms ~ gather-only).
        "session_cache": 64,
        # byte bound for the same cache (plan nbytes accounting, evict-to-
        # fit): tile plans for million-node scenes are MBs each, so the
        # entry-count bound alone could pin GBs. 0 = unbounded by bytes.
        "session_cache_bytes": 1 << 30,
        # tiled giant-scene executor (serve/tiled.py): requests above
        # serve.max_nodes serve through a scan over fixed-shape tiles with
        # host-side halo exchange instead of 413-rejecting. Defaults match
        # serve/tiled.py TILED_DEFAULTS (keep in sync); enable: false keeps
        # the hard 413 behavior.
        "tiled": {
            "enable": False,
            # admission bound for the tiled path itself (TiledOverflowError
            # beyond it — still a 413, naming this knob)
            "max_nodes": 4_194_304,
            # own-node slots per tile; tile rung axes (halo, edges) are
            # geometric above their floors so every giant scene lands on a
            # small set of compiled tile shapes
            "tile_nodes": 65536,
            "halo_floor": 1024,
            "edge_floor": 8192,
            "growth": 2.0,
            # tiled requests run L x n_tiles invocations: their queue/result
            # deadlines stretch by this factor over request_timeout_ms
            "timeout_factor": 8.0,
            # device-parallel tile rounds (serve/mesh_tiled.py): 'auto'
            # takes every local device, N is clamped to what exists, 1
            # keeps the sequential single-device tile loop. Plans are
            # device-count-independent, so this can change per deploy
            # without invalidating session-cached tile plans.
            "devices": 1,
        },
        # shared-nothing engine replicas per model (serve/replica.py): each
        # replica owns its own engine + dispatcher queue behind one
        # round-robin ReplicaSet; >= 2 enables failover of in-flight
        # requests when a replica crashes or wedges
        "replicas": 1,
        # replica execution backend: 'thread' keeps every replica's engine
        # in the gateway process; 'process' moves each replica into an
        # out-of-process worker child (serve/worker.py — crash/OOM/GIL
        # isolation under the same supervision contract; predictions stay
        # bitwise-identical to the thread backend)
        "workers": "thread",
        # process-worker knobs (only read when workers: process): spawn
        # handshake budget (child jax import + engine build + warm rungs),
        # child heartbeat cadence, and the SIGTERM->SIGKILL escalation grace
        "worker": {
            "spawn_timeout_s": 120.0,
            "heartbeat_s": 0.5,
            "kill_grace_s": 3.0,
        },
        # replica supervisor knobs (serve/supervisor.py): heartbeat cadence,
        # wedge (no batch progress) deadline, worker heartbeat-staleness
        # deadline (process backend only), restart exponential backoff,
        # and the per-replica circuit breaker. Keys are splatted into
        # ReplicaSupervisor(**...), so only these eight are accepted.
        "supervisor": {
            "heartbeat_s": 0.25,
            "wedge_timeout_s": 60.0,
            "worker_heartbeat_timeout_s": 10.0,
            "backoff_base_s": 0.5,
            "backoff_max_s": 30.0,
            "breaker_threshold": 3,
            "breaker_cooldown_s": 30.0,
            "healthy_reset_s": 60.0,
        },
        # SLO-driven replica autoscaler (serve/autoscale.py): a per-model
        # control loop over the windowed SLO gauges (queue depth, shed rate,
        # p99) that grows/shrinks the ReplicaSet live. Disabled by default —
        # a static fleet stays exactly as configured.
        "autoscale": {
            "enable": False,
            "min_replicas": 1,
            "max_replicas": 4,
            # control-loop cadence and per-direction cooldowns (a scale
            # action suppresses further actions in the SAME direction for
            # its cooldown; up may still interrupt a down-calm streak)
            "interval_s": 0.5,
            "scale_up_cooldown_s": 2.0,
            "scale_down_cooldown_s": 10.0,
            # replicas added/retired per decision
            "step": 1,
            # scale-up triggers: queued requests per healthy replica, window
            # shed-rate fraction, optional absolute predict-p99 ceiling (ms,
            # null = p99 does not trigger)
            "queue_high": 4.0,
            "shed_high": 0.01,
            "p99_high_ms": None,
            # scale-down gate: per-replica depth below queue_low AND zero
            # window shed for idle_rounds consecutive evaluations
            "queue_low": 0.5,
            "idle_rounds": 3,
            # drain budget when retiring a replica (in-flight work finishes
            # before the queue stops — at-most-once is never sacrificed)
            "drain_timeout_s": 30.0,
        },
        # priority admission (serve/transport.py): interactive predicts
        # outrank bulk rollouts when the gateway saturates. Bulk work only
        # uses up to bulk_max_inflight_frac of the inflight budget, and is
        # deferred outright while the SLO window is degraded (shed rate
        # past degrade_shed_rate, or predict p99 past degrade_p99_ms).
        # Clients override the class with the priority header.
        "priority": {
            "enable": True,
            "header": "X-Priority",
            "bulk_max_inflight_frac": 0.75,
            "degrade_shed_rate": 0.05,
            "degrade_p99_ms": None,
            # Retry-After multiplier for deferred/shed bulk requests
            "bulk_retry_factor": 4.0,
            # predicts whose body is >= this many bytes default to the bulk
            # class (tiled giant scenes); 0 disables the size heuristic
            "bulk_content_bytes": 4_194_304,
        },
        # chunked streaming rollouts (POST .../rollout?stream=1): the steps
        # axis executes as successive chunk_steps-length compiled scans with
        # the carry threaded between, so the first chunk arrives after
        # ~chunk_steps/K of the work and a client disconnect cancels the
        # remaining chunks. Non-streaming requests are untouched.
        "stream": {
            "chunk_steps": 8,
        },
        # multi-model routing (serve/registry.py): null = one model from
        # THIS config; else a list of {name, config_path?, overrides?}
        # entries, each owning its own engine + queue + warmup
        "models": None,
        # HTTP transport front-end (serve/transport.py,
        # scripts/serve_gateway.py): bind address, gateway-level inflight
        # shed gate (429 before the queue sees the request), drain grace,
        # and the synthetic node counts warmed per model at startup
        "gateway": {
            "host": "127.0.0.1",
            "port": 8008,
            "max_inflight": 64,
            "drain_grace_s": 10.0,
            "warmup_nodes": [48, 96],
        },
    },
    # mesh layout (distegnn_tpu/parallel/mesh.py): the 3D device mesh
    # (data, graph, tensor). data/graph null = derive from data.data_parallel
    # and the device count (the legacy 2D behavior); tensor = hidden-dim
    # tensor parallelism degree T (NeutronTP-style feature split; FastEGNN
    # only, model.hidden_nf % T == 0, data*graph*tensor == devices used).
    # Omitting the section (or tensor: 1) is bitwise-identical to the 2D mesh.
    "parallel": {
        "mesh": {
            "data": None,
            "graph": None,
            "tensor": 1,
        },
    },
    # observability (distegnn_tpu/obs, docs/OBSERVABILITY.md) — structured
    # tracing + run metrics + JAX compile/memory probes. Default-on: spans
    # and events cost ~1us each and the writer is buffered; `enable: false`
    # is the kill switch (no event files, all hooks become no-ops).
    "obs": {
        "enable": True,
        # process 0 writes <exp_dir>/obs/events.jsonl; per_host gives every
        # process its own events_p<i>.jsonl (load-imbalance hunts)
        "per_host": False,
        # install the jax.monitoring compile watcher (recompiles-after-warmup
        # are the #1 silent perf bug; see scripts/obs_report.py --check)
        "jax_probe": True,
        # per-step train/step events from the host epoch loop (scan-epoch
        # runs never have them; epoch events are always emitted)
        "step_events": True,
        # writer buffering: flush every N events or T seconds
        "buffer_events": 256,
        "flush_interval_s": 2.0,
    },
    # service-level objectives (distegnn_tpu/obs/slo.py): declarative
    # thresholds scored against the event stream (obs_report --slo) or a
    # live GET /metrics scrape (scripts/traffic_gen.py). Null thresholds
    # declare no objective; window_s sizes the gateway's rolling-window
    # slo/window_* gauges.
    "slo": {
        "enable": True,
        "window_s": 60.0,
        # per-route latency ceilings on SUCCESSFUL responses, e.g.
        #   routes: {predict: {p99_ms: 250.0}, rollout: {p99_ms: 2000.0}}
        "routes": {},
        "error_rate_max": None,   # 5xx fraction ceiling (incl. 504)
        "shed_rate_max": None,    # 429 fraction ceiling
        "batch_fill_min": None,   # floor on filled/capacity slots
        "session_hit_min": None,  # floor on session prep-cache hit rate
    },
    # continuous train->serve promotion (distegnn_tpu/promote,
    # docs/SERVING.md "Continuous promotion"): the trainer publishes each
    # rotated checkpoint as a candidate into watch_dir; the gateway's
    # Promoter canaries it on one quarantined replica, replays a shadow
    # sample of live traffic against it, and promotes fleet-wide or rolls
    # back on the SLO window + prediction-drift gates.
    "promote": {
        "enable": False,          # gateway-side promoter control loop
        "publish": False,         # trainer-side candidate publishing
        "watch_dir": "",          # conveyor directory (shared by both ends)
        "model": "",              # registry entry to promote ("" = first)
        "interval_s": 1.0,        # promoter poll cadence
        "history": 4,             # candidates retained in watch_dir
        "shadow_sample": 0.25,    # fraction of live predicts teed to canary
        "min_shadow": 8,          # shadow comparisons required per verdict
        "max_shadow_inflight": 8, # outstanding shadow submits ceiling
        "gate_timeout_s": 30.0,   # max canary window before forced verdict
        "drift_ceiling": 0.05,    # per-rung mean relative divergence ceiling
        "max_error_rate": 0.0,    # SLO-window 5xx ceiling during canary
    },
    "log": {
        "log_dir": "./logs",
        "test_interval": 2,
        # run parallel/checks.assert_replicated on eval epochs (the reference's
        # startup broadcast+allclose rank check, made continuous)
        "check_consistency": True,
        # capture a jax.profiler trace of this epoch (0 = off) into
        # <exp_dir>/trace/ — open with TensorBoard/Perfetto/xprof. The
        # reference's profiling story is a no-op shim (SURVEY.md §5.1); here
        # it is a first-class flag on the training surface.
        "trace_epoch": 0,
        "wandb": {"enable": False, "offline": True, "api_key": "", "project": "", "entity": ""},
    },
}

_VALID_SPLIT_MODES = ("random", "metis", "spectral", "kmeans")
_VALID_ACCEL_MODES = ("cutoff_edges", "distribute")


def _merge(base: dict, override: Mapping) -> dict:
    out = copy.deepcopy(base)
    for k, v in override.items():
        if v is None and isinstance(out.get(k), dict):
            continue  # bare `section:` header in YAML — keep the defaults
        if isinstance(v, Mapping) and isinstance(out.get(k), dict):
            out[k] = _merge(out[k], v)
        else:
            out[k] = v
    return out


def load_config(path: str, overrides: Optional[Mapping] = None) -> ConfigDict:
    """Load YAML, merge over defaults, apply overrides, validate, derive."""
    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    cfg = ConfigDict(_merge(_DEFAULTS, raw))
    if overrides:
        apply_overrides(cfg, overrides)
    validate_config(cfg)
    return cfg


# CLI-overridable fields: name -> (section path, type). Parity with the
# reference's argparse block (main.py:96-140) minus the torch device plumbing.
_CLI_FIELDS = {
    "lr": ("train.learning_rate", float),
    "seed": ("seed", int),
    "model_name": ("model.model_name", str),
    "batch_size": ("data.batch_size", int),
    "split_mode": ("data.split_mode", str),
    "early_stop": ("train.early_stop", int),
    "checkpoint": ("model.checkpoint", str),
    "cutoff_rate": ("data.cutoff_rate", float),
    "outer_radius": ("data.outer_radius", float),
    "inner_radius": ("data.inner_radius", float),
    "virtual_channels": ("model.virtual_channels", int),
    "epochs": ("train.epochs", int),
    "world_size": ("data.world_size", int),
    # TPU-only extension: mesh data axis size (not a reference flag)
    "data_parallel": ("data.data_parallel", int),
    # TPU-only extension: hidden-dim tensor parallelism degree T
    # (parallel.mesh.tensor; mesh grows a third axis when > 1)
    "tensor_parallel": ("parallel.mesh.tensor", int),
    # resilience: 'auto' or an explicit checkpoint path (train.resume)
    "resume": ("train.resume", str),
    # real-edge lowering: plain | fused | fused_stack (model.edge_impl)
    "edge_impl": ("model.edge_impl", str),
}


def _set_path(cfg: ConfigDict, dotted: str, value: Any) -> None:
    node = cfg
    parts = dotted.split(".")
    for p in parts[:-1]:
        node = node[p]
    node[parts[-1]] = value


def apply_overrides(cfg: ConfigDict, overrides: Mapping) -> None:
    """Apply {field: value} overrides; None values are skipped (reference
    semantics: only explicitly-passed CLI flags override, main.py:117-140)."""
    for name, value in overrides.items():
        if value is None:
            continue
        if name == "multihost":
            continue  # consumed by main.py before config handling
        if name == "wandb":
            if value:
                # explicit --wandb means "log online": enable AND go online
                # (reference configs ship enable=True so its flag only flips
                # offline, main.py:118; ours ship enable=False by default)
                cfg.log.wandb.enable = True
                cfg.log.wandb.offline = False
            continue
        if name not in _CLI_FIELDS:
            raise KeyError(f"unknown override {name!r}; valid: {sorted(_CLI_FIELDS)}")
        _set_path(cfg, _CLI_FIELDS[name][0], value)


def build_arg_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description="DistEGNN-TPU trainer")
    parser.add_argument("--config_path", type=str, required=True)
    parser.add_argument("--wandb", action="store_true")
    # multi-host pods: call jax.distributed.initialize() before any backend
    # use (replaces the reference's torchrun+NCCL process-group init,
    # main.py:159-163). See docs/MULTIHOST.md.
    parser.add_argument("--multihost", action="store_true")
    for name, (_, typ) in _CLI_FIELDS.items():
        parser.add_argument(f"--{name}", type=typ, default=None)
    return parser


def validate_config(cfg: ConfigDict) -> None:
    if cfg.data.accelerate_mode not in _VALID_ACCEL_MODES:
        raise ValueError(f"data.accelerate_mode must be one of {_VALID_ACCEL_MODES}")
    if cfg.data.accelerate_mode == "distribute":
        if cfg.data.split_mode not in _VALID_SPLIT_MODES:
            raise ValueError(f"data.split_mode must be one of {_VALID_SPLIT_MODES}")
        if cfg.data.outer_radius is None or cfg.data.inner_radius is None:
            raise ValueError("distribute mode requires data.outer_radius and data.inner_radius")
    if not 0.0 <= float(cfg.data.cutoff_rate) < 1.0:
        raise ValueError("data.cutoff_rate must be in [0, 1)")
    if int(cfg.data.get("prefetch_depth", 2)) < 0:
        raise ValueError("data.prefetch_depth must be >= 0 (0 = synchronous)")
    if int(cfg.data.get("stream_shard_cache", 4)) < 1:
        raise ValueError("data.stream_shard_cache must be >= 1")
    if cfg.train.accumulation_steps < 1:
        raise ValueError("train.accumulation_steps must be >= 1")
    resume = cfg.train.get("resume")
    if resume is not None and not isinstance(resume, str):
        raise ValueError("train.resume must be null, 'auto', or a checkpoint path")
    if float(cfg.train.get("checkpoint_interval_s", 0) or 0) < 0:
        raise ValueError("train.checkpoint_interval_s must be >= 0")
    if int(cfg.train.get("keep_checkpoints", 3)) < 1:
        raise ValueError("train.keep_checkpoints must be >= 1")
    if int(cfg.train.get("divergence_retries", 0) or 0) < 0:
        raise ValueError("train.divergence_retries must be >= 0")
    if not 0.0 < float(cfg.train.get("divergence_lr_decay", 0.5)) <= 1.0:
        raise ValueError("train.divergence_lr_decay must be in (0, 1]")
    if cfg.model.virtual_channels < 1:
        raise ValueError("model.virtual_channels must be >= 1")
    edge_impl = cfg.model.get("edge_impl", "plain")
    if edge_impl not in ("plain", "fused", "fused_stack"):
        raise ValueError(
            "model.edge_impl must be 'plain', 'fused', or 'fused_stack'")
    if edge_impl in ("fused", "fused_stack"):
        from distegnn_tpu.ops.edge_pipeline import OH_CHUNK

        blk = int(cfg.data.edge_block)
        if blk < OH_CHUNK or blk % OH_CHUNK:
            raise ValueError(
                f"model.edge_impl='{edge_impl}' requires data.edge_block >= "
                f"{OH_CHUNK} and a multiple of {OH_CHUNK} (got {blk})")
        if int(cfg.model.edge_attr_nf) != 2:
            raise ValueError(f"model.edge_impl='{edge_impl}' requires "
                             "edge_attr_nf == 2 "
                             "(the kernel's scalar lane layout is fixed)")
        if bool(cfg.model.normalize):
            raise ValueError(f"model.edge_impl='{edge_impl}' does not support "
                             "model.normalize (flagship EGCL only)")
    if edge_impl == "fused_stack":
        # fused's constraints PLUS a layer-grid + VMEM-residency contract:
        # the megakernel grid is (n_layers,) and the whole blocked graph
        # must fit the per-core VMEM budget — the residency estimate is
        # shape-dependent, so the hard gate lives at trace time
        # (ops/layer_pipeline raises StackVmemBudgetError naming the bound);
        # here we validate what the config alone can know.
        if int(cfg.model.n_layers) < 1:
            raise ValueError(
                "model.edge_impl='fused_stack' requires model.n_layers >= 1 "
                "(the megakernel grid runs one step per layer)")
        budget = int(cfg.model.get("stack_vmem_budget", 0) or 0)
        if budget < 0:
            raise ValueError(
                "model.stack_vmem_budget must be >= 0 bytes (0 = the "
                "16 MiB/core default; the fused_stack megakernel raises "
                "StackVmemBudgetError at trace time when the VMEM-resident "
                "graph exceeds this bound)")
    par = cfg.get("parallel")
    mesh = par.get("mesh") if par is not None else None
    if mesh is not None:
        if not isinstance(mesh, Mapping):
            raise ValueError("parallel.mesh must be a mapping with optional "
                             "keys data/graph/tensor")
        for key in mesh:
            if key not in ("data", "graph", "tensor"):
                raise ValueError(f"parallel.mesh: unknown key {key!r} "
                                 "(valid: data, graph, tensor)")
        for key in ("data", "graph", "tensor"):
            val = mesh.get(key, None if key != "tensor" else 1)
            if val is not None and int(val) < 1:
                raise ValueError(f"parallel.mesh.{key} must be >= 1")
        tensor = int(mesh.get("tensor", 1) or 1)
        if tensor > 1:
            hidden = int(cfg.model.hidden_nf)
            if hidden % tensor:
                raise ValueError(
                    f"parallel.mesh.tensor={tensor} must divide "
                    f"model.hidden_nf={hidden} (each chip owns a contiguous "
                    f"1/T hidden slice)")
            if cfg.model.model_name != "FastEGNN":
                raise ValueError(
                    f"parallel.mesh.tensor > 1 is only implemented for "
                    f"FastEGNN, not model.model_name="
                    f"{cfg.model.model_name!r}")
            if not bool(cfg.model.get("hoist_edge_mlp", True)):
                raise ValueError(
                    "parallel.mesh.tensor > 1 requires "
                    "model.hoist_edge_mlp=true (phi_e's tensor collective is "
                    "the node-level gather of the hoisted products)")
            if bool(cfg.model.get("tanh", False)):
                raise ValueError(
                    "parallel.mesh.tensor > 1 does not support model.tanh "
                    "(phi_x's psum is deferred through linear ops only)")
        mdata = mesh.get("data")
        dp = int(cfg.data.data_parallel)
        if mdata is not None and dp != 1 and int(mdata) != dp:
            raise ValueError(
                f"parallel.mesh.data={int(mdata)} conflicts with "
                f"data.data_parallel={dp} — set one of them")
    o = cfg.get("obs")
    if o is not None:
        for flag in ("enable", "per_host", "jax_probe", "step_events"):
            if not isinstance(o.get(flag, False), bool):
                raise ValueError(f"obs.{flag} must be a boolean")
        if int(o.get("buffer_events", 256)) < 1:
            raise ValueError("obs.buffer_events must be >= 1")
        if float(o.get("flush_interval_s", 2.0)) < 0:
            raise ValueError("obs.flush_interval_s must be >= 0")
    sl = cfg.get("slo")
    if sl is not None:
        if not isinstance(sl.get("enable", True), bool):
            raise ValueError("slo.enable must be a boolean")
        from distegnn_tpu.obs.slo import SLOSpec

        try:
            # SLOSpec.from_mapping owns the threshold/route validation;
            # surface its message under the config-section idiom
            SLOSpec.from_mapping(dict(sl))
        except ValueError as exc:
            raise ValueError(str(exc)) from None
    s = cfg.get("serve")
    if s is None:
        return  # hand-built config without the serving section
    if float(s.growth) <= 1.0:
        raise ValueError("serve.growth must be > 1")
    if int(s.max_batch) < 1 or int(s.cache_size) < 1:
        raise ValueError("serve.max_batch and serve.cache_size must be >= 1")
    if int(s.queue_capacity) < 1:
        raise ValueError("serve.queue_capacity must be >= 1")
    if float(s.batch_deadline_ms) < 0 or float(s.request_timeout_ms) <= 0:
        raise ValueError("serve.batch_deadline_ms must be >= 0 and "
                         "serve.request_timeout_ms > 0")
    if s.donate not in (True, False, "auto"):
        raise ValueError("serve.donate must be true, false, or 'auto'")
    if float(s.get("result_margin_s", 30.0)) <= 0:
        raise ValueError("serve.result_margin_s must be > 0")
    if int(s.get("session_cache", 0)) < 0:
        raise ValueError("serve.session_cache must be >= 0 (0 disables)")
    if int(s.get("session_cache_bytes", 0) or 0) < 0:
        raise ValueError("serve.session_cache_bytes must be >= 0 "
                         "(0 = unbounded by bytes)")
    t = s.get("tiled")
    if t is not None:
        if not isinstance(t, Mapping):
            raise ValueError("serve.tiled must be null or a mapping of "
                             "tiled-executor knobs")
        tknown = ("enable", "max_nodes", "tile_nodes", "halo_floor",
                  "edge_floor", "growth", "timeout_factor", "devices")
        for key in t:
            if key not in tknown:
                raise ValueError(f"serve.tiled: unknown key {key!r} "
                                 f"(accepted: {', '.join(tknown)})")
        if not isinstance(t.get("enable", False), bool):
            raise ValueError("serve.tiled.enable must be a boolean")
        for key in ("max_nodes", "tile_nodes", "halo_floor", "edge_floor"):
            if int(t.get(key, 1)) < 1:
                raise ValueError(f"serve.tiled.{key} must be >= 1")
        if int(t.get("tile_nodes", 65536)) > int(t.get("max_nodes",
                                                       4_194_304)):
            raise ValueError("serve.tiled.tile_nodes must be <= "
                             "serve.tiled.max_nodes")
        if float(t.get("growth", 2.0)) <= 1.0:
            raise ValueError("serve.tiled.growth must be > 1")
        if float(t.get("timeout_factor", 8.0)) < 1.0:
            raise ValueError("serve.tiled.timeout_factor must be >= 1")
        td = t.get("devices", 1)
        if td != "auto" and (isinstance(td, bool) or not isinstance(td, int)
                             or td < 1):
            raise ValueError("serve.tiled.devices must be 'auto' or an "
                             "int >= 1")
    r = s.get("rollout")
    if r is not None:
        if not isinstance(r, Mapping):
            raise ValueError("serve.rollout must be null or a mapping of "
                             "make_rollout_fn kwargs (radius, max_degree, ...)")
        if float(r.get("radius", 0.0)) <= 0:
            raise ValueError("serve.rollout.radius must be > 0")
        if int(r.get("max_degree", 0)) < 1:
            raise ValueError("serve.rollout.max_degree must be >= 1")
        if int(r.get("max_per_cell", 16)) < 1:
            raise ValueError("serve.rollout.max_per_cell must be >= 1")
        if (int(r.get("max_degree", 0))
                * int(r.get("edge_block", 256))) % 512:
            raise ValueError("serve.rollout: max_degree * edge_block must be "
                             "a multiple of 512 (the kernel edge tile)")
    if int(s.get("replicas", 1) or 1) < 1:
        raise ValueError("serve.replicas must be >= 1")
    if str(s.get("workers", "thread") or "thread") not in ("thread",
                                                           "process"):
        raise ValueError("serve.workers must be 'thread' or 'process'")
    w = s.get("worker")
    if w is not None:
        if not isinstance(w, Mapping):
            raise ValueError("serve.worker must be null or a mapping of "
                             "process-worker knobs")
        wknown = ("spawn_timeout_s", "heartbeat_s", "kill_grace_s")
        for key in w:
            if key not in wknown:
                raise ValueError(f"serve.worker: unknown key {key!r} "
                                 f"(accepted: {', '.join(wknown)})")
        for key in wknown:
            if key in w and float(w[key]) <= 0:
                raise ValueError(f"serve.worker.{key} must be > 0")
    sup = s.get("supervisor")
    if sup is not None:
        if not isinstance(sup, Mapping):
            raise ValueError("serve.supervisor must be null or a mapping of "
                             "ReplicaSupervisor kwargs")
        known = ("heartbeat_s", "wedge_timeout_s",
                 "worker_heartbeat_timeout_s", "backoff_base_s",
                 "backoff_max_s", "breaker_threshold", "breaker_cooldown_s",
                 "healthy_reset_s")
        for key in sup:
            if key not in known:
                raise ValueError(f"serve.supervisor: unknown key {key!r} "
                                 f"(accepted: {', '.join(known)})")
        for key in known:
            if key in sup and float(sup[key]) <= 0:
                raise ValueError(f"serve.supervisor.{key} must be > 0")
        if int(sup.get("breaker_threshold", 3)) < 1:
            raise ValueError("serve.supervisor.breaker_threshold must be >= 1")
    a = s.get("autoscale")
    if a is not None:
        if not isinstance(a, Mapping):
            raise ValueError("serve.autoscale must be null or a mapping of "
                             "ReplicaAutoscaler knobs")
        aknown = ("enable", "min_replicas", "max_replicas", "interval_s",
                  "scale_up_cooldown_s", "scale_down_cooldown_s", "step",
                  "queue_high", "shed_high", "p99_high_ms", "queue_low",
                  "idle_rounds", "drain_timeout_s")
        for key in a:
            if key not in aknown:
                raise ValueError(f"serve.autoscale: unknown key {key!r} "
                                 f"(accepted: {', '.join(aknown)})")
        if not isinstance(a.get("enable", False), bool):
            raise ValueError("serve.autoscale.enable must be a boolean")
        lo = int(a.get("min_replicas", 1))
        hi = int(a.get("max_replicas", 4))
        if lo < 1 or hi < lo:
            raise ValueError("serve.autoscale needs 1 <= min_replicas "
                             "<= max_replicas")
        if int(a.get("step", 1)) < 1 or int(a.get("idle_rounds", 3)) < 1:
            raise ValueError("serve.autoscale.step and "
                             "serve.autoscale.idle_rounds must be >= 1")
        for key in ("interval_s", "drain_timeout_s", "queue_high"):
            if float(a.get(key, 1.0)) <= 0:
                raise ValueError(f"serve.autoscale.{key} must be > 0")
        for key in ("scale_up_cooldown_s", "scale_down_cooldown_s",
                    "shed_high", "queue_low"):
            if float(a.get(key, 0.0)) < 0:
                raise ValueError(f"serve.autoscale.{key} must be >= 0")
        if a.get("p99_high_ms") is not None and float(a["p99_high_ms"]) <= 0:
            raise ValueError("serve.autoscale.p99_high_ms must be null "
                             "or > 0")
    p = s.get("priority")
    if p is not None:
        if not isinstance(p, Mapping):
            raise ValueError("serve.priority must be null or a mapping of "
                             "priority-admission knobs")
        pknown = ("enable", "header", "bulk_max_inflight_frac",
                  "degrade_shed_rate", "degrade_p99_ms", "bulk_retry_factor",
                  "bulk_content_bytes")
        for key in p:
            if key not in pknown:
                raise ValueError(f"serve.priority: unknown key {key!r} "
                                 f"(accepted: {', '.join(pknown)})")
        if not isinstance(p.get("enable", True), bool):
            raise ValueError("serve.priority.enable must be a boolean")
        if not str(p.get("header", "X-Priority")).strip():
            raise ValueError("serve.priority.header must be non-empty")
        frac = float(p.get("bulk_max_inflight_frac", 0.75))
        if not 0.0 < frac <= 1.0:
            raise ValueError("serve.priority.bulk_max_inflight_frac must be "
                             "in (0, 1]")
        if float(p.get("degrade_shed_rate", 0.05)) < 0:
            raise ValueError("serve.priority.degrade_shed_rate must be >= 0")
        if (p.get("degrade_p99_ms") is not None
                and float(p["degrade_p99_ms"]) <= 0):
            raise ValueError("serve.priority.degrade_p99_ms must be null "
                             "or > 0")
        if float(p.get("bulk_retry_factor", 4.0)) < 1:
            raise ValueError("serve.priority.bulk_retry_factor must be >= 1")
        if int(p.get("bulk_content_bytes", 0) or 0) < 0:
            raise ValueError("serve.priority.bulk_content_bytes must be "
                             ">= 0 (0 disables)")
    st = s.get("stream")
    if st is not None:
        if not isinstance(st, Mapping):
            raise ValueError("serve.stream must be null or a mapping of "
                             "streaming-rollout knobs")
        for key in st:
            if key not in ("chunk_steps",):
                raise ValueError(f"serve.stream: unknown key {key!r} "
                                 f"(accepted: chunk_steps)")
        if int(st.get("chunk_steps", 8)) < 1:
            raise ValueError("serve.stream.chunk_steps must be >= 1")
    models = s.get("models")
    if models is not None:
        if not isinstance(models, (list, tuple)) or not models:
            raise ValueError("serve.models must be null or a non-empty list "
                             "of {name, config_path?, overrides?} entries")
        seen = set()
        for item in models:
            if not isinstance(item, Mapping) or not item.get("name"):
                raise ValueError("each serve.models entry needs a 'name'")
            name = str(item["name"])
            if name in seen:
                raise ValueError(f"duplicate serve.models name {name!r}")
            seen.add(name)
            for key in item:
                if key not in ("name", "config_path", "overrides"):
                    raise ValueError(f"serve.models[{name!r}]: unknown key "
                                     f"{key!r}")
            if item.get("overrides") is not None and not isinstance(
                    item["overrides"], Mapping):
                raise ValueError(f"serve.models[{name!r}].overrides must be "
                                 "a mapping")
    g = s.get("gateway")
    if g is not None:
        if int(g.get("max_inflight", 64)) < 1:
            raise ValueError("serve.gateway.max_inflight must be >= 1")
        if not 0 <= int(g.get("port", 8008)) <= 65535:
            raise ValueError("serve.gateway.port must be in [0, 65535]")
        if float(g.get("drain_grace_s", 10.0)) < 0:
            raise ValueError("serve.gateway.drain_grace_s must be >= 0")
        nodes = g.get("warmup_nodes", [48, 96])
        if (not isinstance(nodes, (list, tuple)) or not nodes
                or any(int(n) < 2 for n in nodes)):
            raise ValueError("serve.gateway.warmup_nodes must be a "
                             "non-empty list of node counts >= 2")
    lg = cfg.get("log")
    if lg is not None:
        if not isinstance(lg.get("log_dir", ""), str):
            raise ValueError("log.log_dir must be a string path")
        if int(lg.get("test_interval", 2)) < 1:
            raise ValueError("log.test_interval must be >= 1")
        if not isinstance(lg.get("check_consistency", True), bool):
            raise ValueError("log.check_consistency must be a boolean")
        if int(lg.get("trace_epoch", 0) or 0) < 0:
            raise ValueError("log.trace_epoch must be >= 0")
    pm = cfg.get("promote")
    if pm is not None:
        if not isinstance(pm, Mapping):
            raise ValueError("promote must be null or a mapping of "
                             "promotion-conveyor knobs")
        pmknown = ("enable", "publish", "watch_dir", "model", "interval_s",
                   "history", "shadow_sample", "min_shadow",
                   "max_shadow_inflight", "gate_timeout_s", "drift_ceiling",
                   "max_error_rate")
        for key in pm:
            if key not in pmknown:
                raise ValueError(f"promote: unknown key {key!r} "
                                 f"(accepted: {', '.join(pmknown)})")
        for flag in ("enable", "publish"):
            if not isinstance(pm.get(flag, False), bool):
                raise ValueError(f"promote.{flag} must be a boolean")
        for skey in ("watch_dir", "model"):
            if not isinstance(pm.get(skey, ""), str):
                raise ValueError(f"promote.{skey} must be a string")
        for key in ("interval_s", "gate_timeout_s", "drift_ceiling"):
            if float(pm.get(key, 1.0)) <= 0:
                raise ValueError(f"promote.{key} must be > 0")
        for key in ("history", "min_shadow", "max_shadow_inflight"):
            if int(pm.get(key, 1)) < 1:
                raise ValueError(f"promote.{key} must be >= 1")
        if not 0.0 < float(pm.get("shadow_sample", 0.25)) <= 1.0:
            raise ValueError("promote.shadow_sample must be in (0, 1]")
        if float(pm.get("max_error_rate", 0.0)) < 0:
            raise ValueError("promote.max_error_rate must be >= 0")
        if ((pm.get("enable") or pm.get("publish"))
                and not str(pm.get("watch_dir", "")).strip()):
            raise ValueError("promote.watch_dir is required when "
                             "promote.enable or promote.publish is set")


def derive_runtime_fields(cfg: ConfigDict, world_size: Optional[int] = None) -> ConfigDict:
    """Inject data.world_size and log.exp_name (reference main.py:143-157).

    exp_name encodes dataset/split/model/radii/world_size/channels/timestamp —
    the same recipe, so runs are identifiable the same way.
    """
    if world_size is None:
        world_size = cfg.data.get("world_size")
    if world_size is None:
        import jax
        world_size = len(jax.devices())
    cfg.data.world_size = int(world_size)

    d = cfg.data
    if d.accelerate_mode == "distribute":
        geo = f"{d.split_mode}_o{d.outer_radius}_i{d.inner_radius}"
    else:
        geo = f"r{d.radius}_cut{d.cutoff_rate}"
    stamp = time.strftime("%Y%m%d_%H%M%S")
    cfg.log.exp_name = (
        f"{d.dataset_name}_{geo}_{cfg.model.model_name}"
        f"_ws{cfg.data.world_size}_C{cfg.model.virtual_channels}_{stamp}"
    )
    return cfg
