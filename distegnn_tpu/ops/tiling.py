"""Morton-ordered fixed-shape tile plans — serving scenes above the ladder.

A scene too large for one padded executable is cut into contiguous segments
of the Morton curve ("tiles"). Each tile owns a node range plus the edges
RECEIVED by those nodes; senders outside the range form a compact *halo* —
the only cross-tile coupling, because every per-edge quantity in the EGCL
layer reads sender state from the LAYER INPUT (see models/fast_egnn.py).
Executing layer l over all tiles, then exchanging halo features host-side,
is therefore exactly the monolithic forward in a different summation order.

Shape discipline is the whole point: every tile of every scene pads to ONE
(tile_nodes + halo_pad, edge_pad) shape whose free axes are quantized to a
geometric ladder (growth-rung from fixed floors, like serve/buckets.py), so
the compiled tile executable is scene-independent — a fleet serving many
giant scenes compiles one program per tile rung, not per scene.

Work balance reuses the data/partition.py model (``node_work``: a + b*deg):
tile boundaries sweep the Morton order accumulating work until the
per-tile budget is met, so a dense cluster lands in more, smaller-span
tiles instead of one overloaded one (the NeutronTP skew argument, applied
to the serving axis).
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Tuple

import numpy as np

from distegnn_tpu.data.partition import node_work
from distegnn_tpu.ops.order import morton_perm


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def shape_rung(size: int, floor: int, growth: float = 2.0,
               multiple: int = 1) -> int:
    """Smallest ``floor * growth^k`` (rounded up to ``multiple``) admitting
    ``size`` — the scene-independent quantizer for every free tile axis.
    Mirrors BucketLadder._rung without a cap: tiles never reject, they are
    the path requests land on AFTER the ladder cap rejected them."""
    size = max(int(size), 1)
    floor = max(int(floor), 1)
    k = max(0, math.ceil(math.log(size / floor, growth)))
    while floor * growth ** k < size:   # float-log fixup on exact powers
        k += 1
    r = int(math.ceil(floor * growth ** k))
    return _round_up(r, max(int(multiple), 1))


class TileSpec(NamedTuple):
    """One tile: a contiguous Morton-order node range + its received edges."""

    start: int                 # own range [start, stop) in Morton order
    stop: int
    halo: np.ndarray           # [h] int32 Morton-order ids of halo senders
    edge_index: np.ndarray     # [2, e] int32 tile-LOCAL (own: i-start;
                               #   halo sender: tile_nodes + halo rank)
    edge_attr: np.ndarray      # [e, D] float32

    @property
    def n_own(self) -> int:
        return self.stop - self.start

    @property
    def n_halo(self) -> int:
        return int(self.halo.shape[0])


class TilePlan(NamedTuple):
    """A scene's full tile decomposition + the ONE padded tile shape."""

    n_nodes: int
    n_edges: int
    perm: np.ndarray           # [n] Morton relabel, perm[new] = old
    inv_perm: np.ndarray       # [n] inverse (inv_perm[old] = new)
    tiles: Tuple[TileSpec, ...]
    tile_nodes: int            # own-node slots per tile (halo local base)
    halo_pad: int              # rung-quantized halo slots (common to tiles)
    edge_pad: int              # rung-quantized edge slots (plain layout)
    edge_block: int            # 0 = plain layout
    edge_tile: int             # blocked layouts: epb rounding quantum
    edges_per_block: int       # blocked layouts: pinned epb (0 when plain)
    remote_pad: int            # blocked layouts: pinned remote width
    halo_total: int            # sum of per-tile halo counts
    work_imbalance: float      # max/mean per-tile work under the node_work model

    @property
    def n_tiles(self) -> int:
        return len(self.tiles)

    @property
    def padded_nodes(self) -> int:
        """Per-tile padded node count — THE compiled node axis."""
        n = self.tile_nodes + self.halo_pad
        if self.edge_block:
            # fused kernel wants a block multiple and a full 3-block window
            n = max(_round_up(n, self.edge_block), 3 * self.edge_block)
        return n

    @property
    def halo_fraction(self) -> float:
        """Fraction of gathered node slots that are halo duplicates — the
        cross-tile traffic overhead vs. a monolithic executable."""
        return self.halo_total / max(self.halo_total + self.n_nodes, 1)

    @property
    def shape_key(self) -> tuple:
        """The compile-cache key axes: equal keys => one shared executable."""
        return (self.padded_nodes, self.edge_pad, self.edge_block,
                self.edges_per_block, self.remote_pad)


class RoundSchedule(NamedTuple):
    """Device-parallel execution order for one :class:`TilePlan`: the plan's
    tiles grouped into ``ceil(T / D)`` *rounds* of at most ``n_devices``
    tiles each. Every tile of a round runs simultaneously, one per device,
    through ONE shard-mapped tile executable (serve/mesh_tiled.py) — legal
    because all tiles share the plan's single padded shape, and exact
    because every tile reads LAYER-INPUT state (tile order never matters
    within a layer). Rounds are LPT-balanced on the plan's work model so the
    host-side halo gather + readback cost of the heaviest round never
    dominates; a round with fewer than ``n_devices`` tiles (``T % D != 0``)
    pads its free slots with zero-masked filler tiles, hard-masked by a
    per-slot validity flag."""

    rounds: Tuple[Tuple[int, ...], ...]   # tile indices per round, each <= D
    n_devices: int
    round_imbalance: float    # max/mean per-round work under the work model

    @property
    def n_rounds(self) -> int:
        return len(self.rounds)


def tile_work(plan: TilePlan) -> np.ndarray:
    """Per-tile work under the ``node_work`` model with unit costs
    (``a + b*deg`` summed over a tile = own nodes + received edges).
    Recomputed from the tile specs — NOT stored on the plan — so plans stay
    device-count-independent (a plan cached at ``devices: 1`` schedules at
    any D without a rebuild)."""
    return np.asarray(
        [s.n_own + s.edge_index.shape[1] for s in plan.tiles], np.float64)


def plan_rounds(plan: TilePlan, n_devices: int,
                work: Optional[np.ndarray] = None) -> RoundSchedule:
    """Group ``plan``'s tiles into ``ceil(T / D)`` rounds of at most
    ``n_devices`` via LPT (longest-processing-time-first): tiles in
    descending work order each land in the least-loaded round with a free
    slot. Deterministic (stable sort + first-min tie-break). The per-tile
    COMPUTE is shape-identical by construction; what LPT balances is the
    per-round host work — halo gather bytes and result readback scale with
    a round's real (unpadded) nodes + edges."""
    D = max(int(n_devices), 1)
    T = plan.n_tiles
    if work is None:
        work = tile_work(plan)
    work = np.asarray(work, np.float64)
    if work.shape[0] != T:
        raise ValueError(f"plan_rounds: work has {work.shape[0]} entries "
                         f"for {T} tiles")
    R = -(-T // D)
    loads = np.zeros(R, np.float64)
    slots: list = [[] for _ in range(R)]
    for t in np.argsort(-work, kind="stable"):
        free = [r for r in range(R) if len(slots[r]) < D]
        ri = min(free, key=lambda r: (loads[r], r))
        slots[ri].append(int(t))
        loads[ri] += work[t]
    rounds = tuple(tuple(sorted(s)) for s in slots)
    imb = float(loads.max() / max(loads.mean(), 1e-30))
    return RoundSchedule(rounds=rounds, n_devices=D, round_imbalance=imb)


def plan_tiles(edge_index: np.ndarray, loc: np.ndarray,
               edge_attr: Optional[np.ndarray] = None, *,
               tile_nodes: int = 65536, halo_floor: int = 1024,
               edge_floor: int = 8192, growth: float = 2.0,
               edge_block: int = 0, edge_tile: int = 512,
               bits: int = 16, work_node_cost: float = 1.0,
               work_edge_cost: float = 1.0) -> TilePlan:
    """Compute a work-balanced Morton tile plan for one scene.

    ``edge_index`` [2, E] (row=receiver, col=sender) and ``loc`` [n, 3] are
    the scene's ORIGINAL node ids; the plan carries the Morton relabel
    (``perm``/``inv_perm``) and every tile's edges in tile-local ids, so the
    executor only gathers. ``edge_block > 0`` plans for the blocked/fused
    layout and pins ``edges_per_block`` and the remote width across tiles —
    pad_graphs must not re-derive them per tile or every tile would compile
    its own program.
    """
    loc = np.asarray(loc)
    edge_index = np.asarray(edge_index)
    n = int(loc.shape[0])
    e_total = int(edge_index.shape[1])
    if n < 1:
        raise ValueError("plan_tiles: empty scene")
    if edge_attr is None:
        edge_attr = np.zeros((e_total, 0), np.float32)
    edge_attr = np.asarray(edge_attr, np.float32)
    tile_nodes = int(tile_nodes)
    if tile_nodes < 1:
        raise ValueError(f"plan_tiles: tile_nodes must be >= 1 (got {tile_nodes})")

    # Morton relabel: contiguous id ranges become compact curve segments, so
    # cross-tile (halo) edges stay a small fraction of E
    perm = morton_perm(loc, bits=bits)
    inv_perm = np.empty_like(perm)
    inv_perm[perm] = np.arange(n, dtype=perm.dtype)
    row = inv_perm[edge_index[0].astype(np.int64, copy=False)]
    col = inv_perm[edge_index[1].astype(np.int64, copy=False)]
    order = np.argsort(row, kind="stable")
    row, col = row[order], col[order]
    ea = np.ascontiguousarray(edge_attr[order])

    # tile boundaries: greedy work-budget sweep along the Morton order,
    # capped at tile_nodes own slots (the data/partition.py skew model)
    work = node_work(loc[perm], 0.0, a=work_node_cost, b=work_edge_cost,
                     edge_index=np.stack([row, col]))
    cum = np.cumsum(work)
    budget = cum[-1] / max(-(-n // tile_nodes), 1)
    starts = [0]
    while starts[-1] < n:
        s = starts[-1]
        base = cum[s - 1] if s else 0.0
        e = int(np.searchsorted(cum, base + budget, side="left")) + 1
        starts.append(min(max(e, s + 1), s + tile_nodes, n))

    # per-tile edge slices (rows are sorted) + halo extraction
    tiles = []
    halo_total = 0
    max_halo = max_edges = 0
    tile_work = []
    for s, t in zip(starts[:-1], starts[1:]):
        es, ee = np.searchsorted(row, s), np.searchsorted(row, t)
        r_t, c_t = row[es:ee], col[es:ee]
        outside = (c_t < s) | (c_t >= t)
        halo = np.unique(c_t[outside]).astype(np.int32)
        lrow = (r_t - s).astype(np.int32)
        lcol = np.where(outside,
                        tile_nodes + np.searchsorted(halo, c_t),
                        c_t - s).astype(np.int32)
        tiles.append(TileSpec(start=int(s), stop=int(t), halo=halo,
                              edge_index=np.stack([lrow, lcol]),
                              edge_attr=np.ascontiguousarray(ea[es:ee])))
        halo_total += int(halo.shape[0])
        max_halo = max(max_halo, int(halo.shape[0]))
        max_edges = max(max_edges, int(ee - es))
        base = cum[s - 1] if s else 0.0
        tile_work.append(cum[t - 1] - base)

    halo_pad = shape_rung(max(max_halo, 1), halo_floor, growth)
    edge_pad = shape_rung(max(max_edges, 1), edge_floor, growth)
    tw = np.asarray(tile_work, np.float64)
    imbalance = float(tw.max() / max(tw.mean(), 1e-30))

    epb = rpad = 0
    if edge_block:
        from distegnn_tpu.ops.blocked import max_block_degree
        from distegnn_tpu.ops.edge_pipeline import count_remote_edges

        padded = max(_round_up(tile_nodes + halo_pad, edge_block),
                     3 * edge_block)
        deg = max(max_block_degree(t.edge_index[0], padded, edge_block)
                  for t in tiles)
        epb = shape_rung(max(deg, 1), edge_tile, growth, multiple=edge_tile)
        rmax = max(count_remote_edges(t.edge_index, block=edge_block,
                                      n_nodes=padded) for t in tiles)
        rpad = shape_rung(max(rmax, 1), 128, growth, multiple=128)
        edge_pad = 0    # blocked layouts size edges via epb, not edge_pad

    return TilePlan(n_nodes=n, n_edges=e_total, perm=perm, inv_perm=inv_perm,
                    tiles=tuple(tiles), tile_nodes=tile_nodes,
                    halo_pad=halo_pad, edge_pad=edge_pad,
                    edge_block=int(edge_block), edge_tile=int(edge_tile),
                    edges_per_block=int(epb), remote_pad=int(rpad),
                    halo_total=halo_total, work_imbalance=imbalance)
