"""On-device radius graph — jit-compatible neighbor search (static shapes).

The host cell-list (ops/radius.py) is the right tool at preprocessing time,
but rollouts and on-device data generation need edges rebuilt from PREDICTED
positions every step without a host round-trip (the gap VERDICT r1 item 10 /
SURVEY §2.9 left open; the reference rebuilds with torch_cluster on GPU,
datasets/process_dataset.py:101,264). This is the XLA version:

  1. spatial hash: cell = floor((pos - min)/r), bucket = hash(cell) mod H
     (H static, ~2N buckets);
  2. one argsort groups nodes by bucket; searchsorted gives bucket ranges;
  3. each node probes its 27 neighboring cells, reading at most
     ``max_per_cell`` candidates per bucket (static bound) — hash-collision
     candidates are rejected by an exact integer cell-coordinate compare, so
     no duplicate or phantom edges;
  4. candidates are distance-filtered and sorted (valid first, nearest
     first); the first ``max_degree`` survive.

Everything is fixed-shape: [N, 27*max_per_cell] candidates, [N, max_degree]
neighbors, so the whole search lives inside one jit/scan with no recompiles.

Output doubles as a BLOCKED edge layout (ops/blocked.py): row-major
[2, N*max_degree] with per-node uniform slots means every node block owns a
fixed edge slice — exactly the invariant the MXU aggregation kernels need
(edges_per_block = max_degree * edge_block; keep max_degree even so it is a
multiple-of-512 slice at block 256). A rollout can therefore re-build the
graph AND run the model without ever leaving the device.

Capacity bounds (max_per_cell, max_degree) are static by design; overflow
DROPS the farthest neighbors silently, so callers size them from data and
check the returned ``overflow`` flags (host-side assert between rollouts, or
a one-time calibration pass — see tests/test_radius_dev.py).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

_P1, _P2, _P3 = 73856093, 19349663, 83492791  # classic spatial-hash primes


class DeviceRadiusGraph(NamedTuple):
    neighbors: jnp.ndarray   # [N, K] int32 — col index per slot (self when padded)
    nbr_mask: jnp.ndarray    # [N, K] float32 0/1
    # [N] int32 FOUND-neighbor count: exact iff cell_overflow is False (a
    # full cell truncates the candidate list before counting). Calibration:
    # raise max_per_cell until cell_overflow clears, THEN size max_degree
    # from max(degree).
    degree: jnp.ndarray
    cell_overflow: jnp.ndarray    # [] bool — some real cell exceeded max_per_cell
    degree_overflow: jnp.ndarray  # [] bool — some node exceeded max_degree


def radius_graph_dev(
    pos: jnp.ndarray,            # [N, 3]
    r: float,
    max_degree: int,
    max_per_cell: int = 8,
    node_mask: Optional[jnp.ndarray] = None,  # [N] 0/1; masked nodes isolated
    num_buckets: Optional[int] = None,
) -> DeviceRadiusGraph:
    """All neighbors within ``r`` (strict, like radius_graph_np), ELL layout."""
    N = pos.shape[0]
    H = num_buckets or max(1 << (2 * N - 1).bit_length(), 16)
    valid = (jnp.ones((N,), jnp.float32) if node_mask is None
             else node_mask.astype(jnp.float32))
    big = jnp.float32(1e30)

    # cells relative to the masked min corner
    anchor = jnp.min(jnp.where(valid[:, None] > 0, pos, big), axis=0)
    cell = jnp.floor((pos - anchor) / r).astype(jnp.int32)          # [N, 3]
    # each masked node gets its own unreachable cell: they never appear as
    # candidates AND never pile into one bucket (which would trip
    # cell_overflow spuriously on padded inputs)
    me = jnp.arange(N, dtype=jnp.int32)
    far = jnp.stack([-(1 << 20) - me, jnp.zeros_like(me), jnp.zeros_like(me)], -1)
    cell = jnp.where(valid[:, None] > 0, cell, far)

    def bucket_of(c):
        h = (c[..., 0] * _P1) ^ (c[..., 1] * _P2) ^ (c[..., 2] * _P3)
        return jnp.abs(h) % H

    bucket = bucket_of(cell)                                        # [N]
    order = jnp.argsort(bucket)                                     # [N]
    sorted_bucket = bucket[order]

    # 27 neighboring cells per node
    off = jnp.stack(jnp.meshgrid(*([jnp.arange(-1, 2)] * 3),
                                 indexing="ij"), axis=-1).reshape(27, 3)
    probe_cell = cell[:, None, :] + off[None, :, :]                 # [N, 27, 3]
    probe_bucket = bucket_of(probe_cell)                            # [N, 27]

    start = jnp.searchsorted(sorted_bucket, probe_bucket)           # [N, 27]
    end = jnp.searchsorted(sorted_bucket, probe_bucket, side="right")
    M = max_per_cell
    slots = start[..., None] + jnp.arange(M)[None, None, :]         # [N, 27, M]
    in_range = slots < end[..., None]
    cand = jnp.take(order, jnp.clip(slots, 0, N - 1), axis=0)       # [N, 27, M]

    # exact cell compare: kills hash-collision candidates (and duplicates)
    same_cell = jnp.all(cell[cand] == probe_cell[:, :, None, :], axis=-1)
    cand_ok = in_range & same_cell
    # only probes of REAL nodes count toward overflow
    cell_overflow = jnp.any(((end - start) > M) & (valid[:, None] > 0))

    cand = cand.reshape(N, 27 * M)
    cand_ok = cand_ok.reshape(N, 27 * M)
    d2 = jnp.sum((pos[:, None, :] - pos[cand]) ** 2, axis=-1)       # [N, 27M]
    hit = (cand_ok & (d2 < r * r) & (cand != me[:, None])
           & (valid[cand] > 0) & (valid[:, None] > 0))

    degree = jnp.sum(hit, axis=1).astype(jnp.int32)
    degree_overflow = jnp.any(degree > max_degree)

    # valid-first, nearest-first; keep the first max_degree
    key = jnp.where(hit, d2, big)
    sel = jnp.argsort(key, axis=1)[:, :max_degree]                  # [N, K]
    neighbors = jnp.take_along_axis(cand, sel, axis=1).astype(jnp.int32)
    nbr_mask = (jnp.take_along_axis(key, sel, axis=1) < big).astype(jnp.float32)
    neighbors = jnp.where(nbr_mask > 0, neighbors, me[:, None])

    return DeviceRadiusGraph(neighbors, nbr_mask, degree,
                             cell_overflow, degree_overflow)


def ell_to_edge_list(g: DeviceRadiusGraph):
    """[N, K] adjacency -> row-major edge list [2, N*K] + mask [N*K].

    Row-sorted with per-node uniform slots, so for any edge_block dividing N
    this already satisfies the blocked-layout invariant with
    edges_per_block = K * edge_block (see ops/blocked.py)."""
    N, K = g.neighbors.shape
    row = jnp.repeat(jnp.arange(N, dtype=jnp.int32), K)
    col = g.neighbors.reshape(-1)
    return jnp.stack([row, col]), g.nbr_mask.reshape(-1)
