"""Cross-layer fused megakernel — L EGCL layers with the graph VMEM-resident.

The per-layer fused pipeline (ops/edge_pipeline.py) streams the blocked edge
array through VMEM once per layer, but between layers every tensor — node
features, geometry, the blocked-CSR edge structure — round-trips HBM, so an
L-layer FastEGNN pays the O(E)-scale HBM traffic L times. This kernel runs
the WHOLE layer stack in one Pallas grid:

  grid = (L,)   # one sequential grid step per EGCL layer

  step l:
    read node state (h, x, X, Hv) from the parity-selected half of a
      double-buffered VMEM scratch window (layer boundary = a VMEM swap,
      not an HBM round-trip)
    write the layer-INPUT state to the l-indexed checkpoint output
      (the backward's remat anchors — O(L * N * H), never O(E))
    in-window edges: the same per-tile forward as ops/edge_pipeline
      (_edge_fwd_math + chunked one-hot MXU aggregation) over the
      VMEM-resident blocked edge stream — read from HBM ONCE for all L
      layers instead of once per layer
    remote tail: the out-of-window edge list (plain per-edge math, exactly
      EGCLVel's dense tail) evaluated in-kernel with exact f32 one-hot
      gathers/segment-dots — summed into the same aggregates per layer
    virtual-node section: phi_ev/phi_xv/phi_X/phi_v/phi_h/phi_hv (+phi_g)
      as raw matmuls over values, bit-matching the Flax module math
    write the updated state to the OTHER scratch half + the final outputs

  per-layer weights are stacked along a leading L axis and streamed one
  layer per grid step via (1, a, b) BlockSpecs — VMEM stays bounded in L.

HBM traffic per forward step (the fused_stack vs fused lever,
`hbm_bytes_per_step` below is the quantitative model):

  per-layer fused:  L x (edge stream + 4x node-window re-reads
                         + accumulator + boundary state)
  fused_stack:      1 x edge stream + L x (weights + checkpoint write)
                         + boundary state once

Differentiation: `fused_egnn_stack` is a custom_vjp. The forward kernel
checkpoints only the per-layer INPUT node state; the backward walks the
layers in reverse, re-running each layer through `_layer_ref` — a pure-JAX
single-layer reference whose in-window edge pass IS `fused_edge_layer`, so
the per-edge activations are rematerialized at tile scale inside its Pallas
backward and no O(E)-wide residual is ever saved. VMEM and residual memory
both stay bounded in L.

Scale contract: everything here must FIT — the whole graph (blocked edge
stream + node state + one layer of weights + remote one-hots) is
VMEM-resident. `estimate_stack_vmem_bytes` models the residency and
`fused_egnn_stack` raises a typed `StackVmemBudgetError` when the estimate
exceeds the declared budget instead of letting XLA spill silently. The
Fluid113K flagship does NOT fit by design — keep `edge_impl: fused` there;
fused_stack targets rung-scale serving graphs (serve/engine.py pads to
rungs), where one multi-layer executable per (rung, L) drops per-request
HBM traffic ~Lx. Under a (graph/tensor) mesh the layer-boundary collectives
cannot cross a Pallas grid, so FastEGNN falls back to the per-layer fused
path with the SAME param tree (models/fast_egnn.py) — the megakernel is the
single-chip serving/training lowering.

Parity contract (tests/test_layer_pipeline.py): interpret-mode forward
within 1e-6 and grads within 1e-5 of the per-layer fused path at
L in {1, 2, 4}, including remote tails and trailing empty blocks. The
in-window tile math is shared code (bitwise); the remote tail and the
virtual section differ only by f32 reassociation (one-hot dots vs
segment_sum order).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distegnn_tpu.ops.edge_pipeline import (
    OH_CHUNK, XL, EdgeWeights, _check_grid, _edge_fwd_math, _onehot_agg,
    _silu, _split2, _use_interpret, fused_edge_layer,
)

# Honest single-core VMEM budget (pallas_guide: ~16 MiB/core). The estimate
# below must stay under this (or an explicit per-model override) or the op
# refuses to trace.
DEFAULT_STACK_VMEM_BUDGET = 16 * 1024 * 1024


class StackVmemBudgetError(ValueError):
    """The megakernel's VMEM residency estimate exceeds the declared budget.

    Raised at trace time (typed, catchable) instead of letting the TPU
    compiler spill the resident graph to HBM silently — a spilled megakernel
    is strictly worse than the per-layer fused path it replaces. Fix: use
    `edge_impl: fused` for this shape, shrink the rung, or raise the budget
    knowingly via StackConfig.vmem_budget."""


class StackConfig(NamedTuple):
    """Static (hashable) megakernel configuration — custom_vjp nondiff arg."""

    n_layers: int
    block: int               # node block == edge tile T (edge_pipeline contract)
    hidden: int              # H
    channels: int            # C virtual channels
    node_attr_nf: int = 0    # A (0 = batch carries no node_attr)
    has_gravity: bool = False
    residual: bool = True
    coords_mean: bool = True  # coords_agg == 'mean'
    dtype_name: str = "f32"   # 'f32' | 'bf16' (message-MLP compute dtype)
    vmem_budget: int = DEFAULT_STACK_VMEM_BUDGET


SCALAR_NF = 3  # radial + 2 edge attrs — the fused kernel's scalar lanes


def stack_weight_shapes(cfg: StackConfig) -> Dict[str, Tuple[int, int]]:
    """Per-layer 2-D shapes of every stacked weight, keyed by kernel name.

    The stacked container is {key: [L, a, b]} — a RUNTIME VIEW of the same
    param tree the per-layer fused path declares (models/fast_egnn.py
    stacks/reshapes the Flax leaves; checkpoints are identical across
    edge_impl 'fused' <-> 'fused_stack')."""
    H, C, A = cfg.hidden, cfg.channels, cfg.node_attr_nf
    shapes = {
        # phi_e + phi_x head (edge_pipeline EdgeWeights layout: row biases,
        # e_w4 pre-transposed to [1, H])
        "e_w1": (2 * H + SCALAR_NF, H), "e_b1": (1, H),
        "e_w2": (H, H), "e_b2": (1, H),
        "e_w3": (H, H), "e_b3": (1, H), "e_w4": (1, H),
        # phi_ev: MLP([H, H], act_last=True) on [h, Hv, |vcd|, m_X]
        "ev_k0": (2 * H + 1 + C, H), "ev_b0": (1, H),
        "ev_k1": (H, H), "ev_b1": (1, H),
        # phi_xv / phi_X: CoordMLP (no last bias)
        "xv_k0": (H, H), "xv_b0": (1, H), "xv_k1": (H, 1),
        "X_k0": (H, H), "X_b0": (1, H), "X_k1": (H, 1),
        # phi_v: MLP([H, 1])
        "v_k0": (H, H), "v_b0": (1, H), "v_k1": (H, 1), "v_b1": (1, 1),
        # phi_h: MLP([H, H]) on [h, agg_h, agg_v(, node_attr)]
        "h_k0": (3 * H + A, H), "h_b0": (1, H),
        "h_k1": (H, H), "h_b1": (1, H),
        # phi_hv: MLP([H, H]) on [Hv^T, agg_Hv]
        "hv_k0": (2 * H, H), "hv_b0": (1, H),
        "hv_k1": (H, H), "hv_b1": (1, H),
    }
    if cfg.has_gravity:
        shapes.update({"g_k0": (H, H), "g_b0": (1, H),
                       "g_k1": (H, 1), "g_b1": (1, 1)})
    return shapes


# ------------------------------------------------------------ memory models

def estimate_stack_vmem_bytes(cfg: StackConfig, *, n_nodes: int,
                              n_edges: int, remote_pad: int) -> int:
    """Model of the megakernel's peak VMEM residency in bytes.

    Everything with a constant-index BlockSpec is resident for the whole
    grid; per-layer weights and checkpoints stream one block at a time, so
    the estimate is (by design) bounded in L — the L-dependence lives in HBM
    traffic, not VMEM. Conservative where it matters: temporaries that
    coexist (edge-tile intermediates, remote one-hots, the virtual-section
    activations) are all counted."""
    H, C, A = cfg.hidden, cfg.channels, cfg.node_attr_nf
    N, E, R = n_nodes, n_edges, remote_pad
    db = 2 if cfg.dtype_name == "bf16" else 4
    T = cfg.block
    w_bytes = 4 * sum(a * b for a, b in stack_weight_shapes(cfg).values())
    items = {
        # blocked edge stream (row_t + col_l + kblk are i32, scal is f32 XL)
        "edge_stream": E * (4 + 4 + 4 + XL * 4),
        # node inputs: x/v packed [N, XL] f32, h0 [N, H] f32, mask, attrs
        "node_inputs": N * (2 * XL * 4 + H * 4 + 4 + A * 4),
        # double-buffered state window (2x) + final outputs + one ckpt block
        "state_scratch": 2 * N * (XL + H) * 4,
        "outputs": 2 * N * (XL + H) * 4,
        # one layer of stacked weights, x2 for the streamed double buffer
        "layer_weights": 2 * w_bytes,
        # hoisted products hr/hc + packed pk per layer
        "hoisted": N * 4 * H * db,
        # per-block [T, H+8] f32 accumulator assembled to [N, H+8]
        "accumulator": N * (H + 8) * 4 + T * (H + 8) * 4,
        # remote tail: compact arrays + the two [R, N] f32 one-hot gathers
        "remote": R * (XL * 4 + 8 + 3 * H * db) + 2 * R * N * 4,
        # virtual section activations: v_in + vef (+ vcd/trans_X f32)
        "virtual": N * C * ((2 * H + 1 + C) + 2 * H) * db + 2 * N * 3 * C * 4,
    }
    return int(sum(items.values()))


def check_stack_vmem(cfg: StackConfig, *, n_nodes: int, n_edges: int,
                     remote_pad: int) -> int:
    """Raise StackVmemBudgetError if the estimate exceeds cfg.vmem_budget."""
    est = estimate_stack_vmem_bytes(cfg, n_nodes=n_nodes, n_edges=n_edges,
                                    remote_pad=remote_pad)
    if est > cfg.vmem_budget:
        raise StackVmemBudgetError(
            f"fused_stack megakernel needs ~{est / 2**20:.1f} MiB VMEM-resident "
            f"state (N={n_nodes}, E={n_edges}, R={remote_pad}, H={cfg.hidden}, "
            f"block={cfg.block}) but the budget is "
            f"{cfg.vmem_budget / 2**20:.1f} MiB — the graph must fit on-chip "
            f"for the cross-layer fusion to pay. Use edge_impl='fused' for "
            f"this shape, shrink the serving rung, or raise "
            f"StackConfig.vmem_budget explicitly")
    return est


def hbm_bytes_per_step(impl: str, *, n_nodes: int, n_edges: int, hidden: int,
                       channels: int, n_layers: int, remote_pad: int = 0,
                       node_attr_nf: int = 0,
                       dtype_name: str = "f32") -> Dict[str, int]:
    """Analytic HBM-bytes-per-forward-step model for the three edge lowerings.

    This is the CPU-trace-era evidence model (docs/PERFORMANCE.md): derived
    purely from shapes, reproducible from `scripts/microbench_ops.py`, and
    NOT a hardware measurement. Assumptions: every HBM operand is read or
    written exactly once per use-site (infinite cache within one kernel, no
    reuse across kernels), weights are re-read per layer, remote arrays are
    i32/f32 compact lists. Returns {"total": bytes, ...itemized}.
    """
    N, E, H, C, L, R, A = (n_nodes, n_edges, hidden, channels, n_layers,
                           remote_pad, node_attr_nf)
    db = 2 if dtype_name == "bf16" else 4
    edge_stream = E * (4 + 4 + 4 + XL * 4)      # row_t/col/kblk + scal
    remote_stream = R * (8 + XL * 4)
    state = N * (XL * 4 + XL * 4 + H * 4 + 4 + A * 4)   # x, v, h, mask, attr
    cfg = StackConfig(n_layers=L, block=OH_CHUNK, hidden=H, channels=C,
                      node_attr_nf=A, has_gravity=False,
                      dtype_name=dtype_name)
    w_layer = 4 * sum(a * b for a, b in stack_weight_shapes(cfg).values())
    virt = N * C * H * db                        # vef spill per layer (XLA)
    if impl == "fused_stack":
        items = {
            "edge_stream_once": edge_stream,
            "remote_once": remote_stream,
            "state_io": 2 * state,
            "weights_L": L * w_layer,
            "ckpt_writes": L * N * (XL + H) * 4,
        }
    elif impl == "fused":
        # per layer: edge stream + 4 node-window re-read passes + accumulator
        # + the layer-boundary state round-trip + the XLA virtual section
        per_layer = (edge_stream + remote_stream
                     + 4 * N * (XL * 4 + 2 * H * db)
                     + N * (H + 8) * 4
                     + 2 * state + w_layer + 2 * virt)
        items = {"per_layer_x_L": L * per_layer}
    elif impl == "plain":
        # per layer: edge-wide [E, H] intermediates round-trip ~5x (gather
        # hr, gather hc, edge_feat write+read, trans) + aggregation read
        per_layer = (E * H * db * 5 + E * 3 * 4 * 2 + 2 * state
                     + w_layer + 2 * virt)
        items = {"per_layer_x_L": L * per_layer}
    else:
        raise ValueError(f"unknown impl {impl!r}")
    items["total"] = int(sum(items.values()))
    return items


# ------------------------------------------------------- shared layer math
#
# Every helper below operates on VALUES (plain jnp arrays), so the SAME code
# runs inside the Pallas kernel (on ref[...] reads) and inside `_layer_ref`
# (the pure-JAX backward reference). That sharing is the parity argument.

def _cast(dt):
    return (lambda a: a.astype(dt)) if dt is not None else (lambda a: a)


def _dense(x, k, b, dt):
    """nn.Dense(dtype=dt) on values: promote inputs AND params to dt."""
    c = _cast(dt)
    y = c(x) @ c(k)
    if b is not None:
        y = y + c(b)
    return y


def _mlp2(x, k0, b0, k1, b1, dt, act_last=False):
    """MLP([s0, s1]) on values — TorchDense/TorchDense with silu between."""
    y = _silu(_dense(x, k0, b0, dt))
    y = _dense(y, k1, b1, dt)
    return _silu(y) if act_last else y


def _coord_head(x, k0, b0, k1, dt):
    """CoordMLP on values: Dense(H) -> silu -> Dense(1, no bias) -> f32."""
    y = _silu(_dense(x, k0, b0, dt))
    return _dense(y, k1, None, dt).astype(jnp.float32)


def _remote_edge_math(x_r, x_c, hr_r, hc_c, rattr, rm, w, H, dt):
    """Per-edge remote-tail math on pre-gathered values (EGCLVel's dense
    tail, models/fast_egnn.py): returns (cd_r [R,3], g_r [R,1], ef_r [R,H]).
    The caller chooses the gather/scatter lowering (segment_sum in XLA,
    exact f32 one-hot dots in-kernel)."""
    c = _cast(dt)
    cd_r = (x_r - x_c) * rm
    radial = jnp.sum(cd_r * cd_r, axis=-1, keepdims=True)
    sfeat = c(jnp.concatenate([radial, rattr[:, :2]], axis=-1))
    t1 = hr_r + hc_c + sfeat @ c(w["e_w1"][2 * H:]) + c(w["e_b1"])
    ef_r = _silu(_silu(t1) @ c(w["e_w2"]) + c(w["e_b2"]))
    y2 = _silu(ef_r @ c(w["e_w3"]) + c(w["e_b3"]))
    g_r = (y2.astype(jnp.float32) @ w["e_w4"].T) * rm
    return cd_r, g_r, ef_r


def _virtual_and_update(h, x, v, X, Hv, agg, agg_h, nm, nattr, gvec, w,
                        cfg: StackConfig):
    """The full post-aggregation EGCL section on unbatched values — virtual
    edges, coordinate/velocity/gravity updates, node + virtual feature
    updates. Exactly EGCLVel's math (models/fast_egnn.py:289-373) with the
    batch axis dropped and the Flax modules replaced by their raw matmuls.

    h [N,H] f32, x [N,3] f32, v [N,3] f32, X [3,C] f32, Hv [H,C] f32,
    agg [N,3] f32, agg_h [N,H] f32, nm [N,1] f32 node mask."""
    H, C = cfg.hidden, cfg.channels
    dt = None if cfg.dtype_name == "f32" else jnp.bfloat16
    N = h.shape[0]

    # virtual-edge geometry on the PRE-update coordinates
    vcd = X[None, :, :] - x[:, :, None]                       # [N, 3, C]
    virtual_radial = jnp.linalg.norm(vcd, axis=1, keepdims=True)  # [N, 1, C]

    # exact global coordinate mean over real nodes (global_node_mean,
    # axis_name=None — the mesh fallback handles the sharded case)
    cnt_n = jnp.maximum(jnp.sum(nm.astype(x.dtype)), 1.0)
    coord_mean = jnp.sum(x * nm, axis=0) / cnt_n              # [3]
    Xc = X - coord_mean[:, None]                              # [3, C]
    m_X = jnp.einsum("dc,de->ce", Xc, Xc)                     # [C, C]

    v_in = jnp.concatenate(
        [jnp.broadcast_to(h[:, None, :], (N, C, H)),
         jnp.broadcast_to(Hv.T[None, :, :], (N, C, H)),
         jnp.swapaxes(virtual_radial, 1, 2),                  # [N, C, 1]
         jnp.broadcast_to(m_X[None, :, :], (N, C, C))], axis=-1)
    vef = _mlp2(v_in, w["ev_k0"], w["ev_b0"], w["ev_k1"], w["ev_b1"], dt,
                act_last=True)                                # [N, C, H]
    vef = vef * nm[:, :, None].astype(vef.dtype)

    # real + virtual coordinate updates
    x = x + agg
    phi_xv = _coord_head(vef, w["xv_k0"], w["xv_b0"], w["xv_k1"], dt)
    x = x + jnp.mean(-vcd * jnp.swapaxes(phi_xv, 1, 2), axis=-1)
    x = x + _mlp2(h, w["v_k0"], w["v_b0"], w["v_k1"], w["v_b1"],
                  dt).astype(jnp.float32) * v
    if cfg.has_gravity:
        x = x + _mlp2(h, w["g_k0"], w["g_b0"], w["g_k1"], w["g_b1"],
                      dt).astype(jnp.float32) * gvec
    x = x * nm

    trans_X = vcd * jnp.swapaxes(
        _coord_head(vef, w["X_k0"], w["X_b0"], w["X_k1"], dt), 1, 2)
    X = X + jnp.sum(trans_X * nm[:, :, None], axis=0) / cnt_n  # [3, C]

    # node feature update
    agg_v = jnp.mean(vef, axis=1)                             # [N, H]
    n_in = [h, agg_h, agg_v]
    if cfg.node_attr_nf:
        n_in.append(nattr)
    out = _mlp2(jnp.concatenate([a.astype(jnp.float32) for a in n_in],
                                axis=-1),
                w["h_k0"], w["h_b0"], w["h_k1"], w["h_b1"], dt)
    h = (h + out) if cfg.residual else out * jnp.ones_like(h)
    h = h * nm

    # virtual feature update
    agg_Hv = jnp.sum(vef.astype(jnp.float32) * nm[:, :, None],
                     axis=0) / cnt_n                          # [C, H]
    hv_in = jnp.concatenate([Hv.T, agg_Hv], axis=-1)          # [C, 2H]
    out_v = _mlp2(hv_in, w["hv_k0"], w["hv_b0"], w["hv_k1"], w["hv_b1"],
                  dt).T                                       # [H, C]
    Hv = (Hv + out_v) if cfg.residual else out_v * jnp.ones_like(Hv)
    return h, x, X, Hv


def _inwindow_acc(xp, pk, row_t, col_l, kblk, scal, ew: EdgeWeights,
                  T, H, nb, nt, dtype):
    """In-window blocked edge pass on values — bitwise the fused_edge_layer
    forward (_fwd_kernel's tile loop with the grid unrolled in Python):
    returns the packed [N, H+8] f32 aggregate [trans_hi, trans_lo, count,
    pad, ef_sum]."""
    accs = []
    for b in range(nb):
        s = min(max(b - 1, 0), max(nb - 3, 0))
        xo = xp[b * T:(b + 1) * T]
        xw = tuple(xp[(s + k) * T:(s + k + 1) * T] for k in range(3))
        po = pk[b * T:(b + 1) * T]
        pw = tuple(pk[(s + k) * T:(s + k + 1) * T] for k in range(3))
        acc = jnp.zeros((T, H + 8), jnp.float32)
        for j in range(nt):
            t = b * nt + j
            rt = row_t[t][None, :]                            # [1, T]
            e0 = t * T
            mask, cd, _, _, _, _, ef, _, _, g = _edge_fwd_math(
                xo, xw, po, pw, rt, col_l[e0:e0 + T], kblk[e0:e0 + T],
                scal[e0:e0 + T], ew, T, H, dtype)
            trans = cd[:, 0:3] * g
            hi, lo = _split2(trans)
            data = jnp.concatenate(
                [hi, lo, mask.astype(jnp.bfloat16),
                 jnp.zeros((T, 1), jnp.bfloat16),
                 (ef * mask.astype(ef.dtype)).astype(jnp.bfloat16)], axis=1)
            acc = acc + _onehot_agg(rt, data)
        accs.append(acc)
    return jnp.concatenate(accs, axis=0)                      # [N, H+8]


def _onehot_rows(idx, n):
    """Exact f32 one-hot [R, n] of node indices — the in-kernel gather /
    segment-dot lowering for the remote tail (no scatter unit on TPU; f32
    0/1 entries keep gathers exact and sums f32-accumulated)."""
    cols = jax.lax.broadcasted_iota(jnp.int32, (idx.shape[0], n), 1)
    return (cols == idx[:, None]).astype(jnp.float32)


def _segdot(G, val):
    """G^T @ val without materializing the transpose: [R,N]^T [R,F] -> [N,F]."""
    return jax.lax.dot_general(G, val, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


# --------------------------------------------------------------- the kernel

def _stack_kernel(*refs, cfg: StackConfig, names, nb, nt):
    """One grid step == one EGCL layer. See module docstring for the plan."""
    n_in = len(names)
    d = dict(zip(names, refs[:n_in]))
    (out_h, out_x, out_X, out_Hv,
     ck_h, ck_x, ck_X, ck_Hv) = refs[n_in:n_in + 8]
    (hA, hB, xA, xB, XA, XB, HvA, HvB) = refs[n_in + 8:]

    l = pl.program_id(0)
    T, H, C = cfg.block, cfg.hidden, cfg.channels
    dt = None if cfg.dtype_name == "f32" else jnp.bfloat16
    dtype = jnp.float32 if dt is None else jnp.bfloat16
    N = d["h0"].shape[0]

    @pl.when(l == 0)
    def _():
        hA[...] = d["h0"][...]
        xA[...] = d["xp0"][...]
        XA[...] = d["X0"][...]
        HvA[...] = d["Hv0"][...]

    even = (l % 2) == 0
    h = jnp.where(even, hA[...], hB[...])
    xp = jnp.where(even, xA[...], xB[...])
    Xp = jnp.where(even, XA[...], XB[...])
    Hv = jnp.where(even, HvA[...], HvB[...])

    # checkpoint the layer-INPUT state (l-indexed blocks — the bwd anchors)
    ck_h[...] = h[None]
    ck_x[...] = xp[None]
    ck_X[...] = Xp[None]
    ck_Hv[...] = Hv[None]

    # this layer's weight slices ([1, a, b] blocks -> [a, b])
    w = {k: d["w:" + k][...][0] for k in stack_weight_shapes(cfg)}

    x3 = xp[:, 0:3]
    X = Xp[0:3, :]
    nm = d["nm"][...]
    v3 = d["vp"][...][:, 0:3]

    # hoisted phi_e node products (HoistedEdgeMLP algebra)
    c = _cast(dt)
    hr = c(h) @ c(w["e_w1"][:H])
    hc = c(h) @ c(w["e_w1"][H:2 * H])
    pk = jnp.concatenate([hr, hc], axis=1).astype(dtype)

    # in-window blocked edges — the shared tile math, edge stream VMEM-hot
    ew = EdgeWeights(ws=w["e_w1"][2 * H:], b1=w["e_b1"], w2=w["e_w2"],
                     b2=w["e_b2"], w3=w["e_w3"], b3=w["e_b3"], w4=w["e_w4"])
    acc = _inwindow_acc(xp, pk, d["row_t"][...], d["col_l"][...],
                        d["kblk"][...], d["scal"][...], ew, T, H, nb, nt,
                        dtype)
    trans_sum = acc[:, 0:3] + acc[:, 3:6]
    count = acc[:, 6:7]
    ef_sum = acc[:, 8:]

    # remote tail: exact one-hot gathers + f32 segment dots
    rr = d["rr"][...][:, 0]
    rc = d["rc"][...][:, 0]
    rsc = d["rsc"][...]
    Gr = _onehot_rows(rr, N)
    Gc = _onehot_rows(rc, N)
    x_r, x_c = Gr @ x3, Gc @ x3
    hr_r = (Gr @ hr.astype(jnp.float32)).astype(hr.dtype)
    hc_c = (Gc @ hc.astype(jnp.float32)).astype(hc.dtype)
    rm = rsc[:, 2:3]
    cd_r, g_r, ef_r = _remote_edge_math(x_r, x_c, hr_r, hc_c, rsc[:, 0:2],
                                        rm, w, H, dt)
    trans_sum = trans_sum + _segdot(Gr, cd_r * g_r)
    count = count + _segdot(Gr, rm)
    ef_sum = ef_sum + _segdot(Gr, ef_r.astype(jnp.float32) * rm)

    cnt = jnp.maximum(count, 1.0)
    agg = trans_sum / cnt if cfg.coords_mean else trans_sum
    agg_h = ef_sum / cnt

    gvec = d["gvec"][...][0, 0:3] if cfg.has_gravity else None
    nattr = d["nattr"][...] if cfg.node_attr_nf else None
    h2, x2, X2, Hv2 = _virtual_and_update(h, x3, v3, X, Hv, agg, agg_h, nm,
                                          nattr, gvec, w, cfg)

    xp2 = jnp.concatenate([x2, jnp.zeros((N, XL - 3), jnp.float32)], axis=1)
    Xp2 = jnp.concatenate([X2, jnp.zeros((XL - 3, C), jnp.float32)], axis=0)

    # swap: write the updated state into the OTHER buffer half
    @pl.when(even)
    def _():
        hB[...] = h2
        xB[...] = xp2
        XB[...] = Xp2
        HvB[...] = Hv2

    @pl.when(jnp.logical_not(even))
    def _():
        hA[...] = h2
        xA[...] = xp2
        XA[...] = Xp2
        HvA[...] = Hv2

    # finals (constant-index outputs: the last grid step's write survives)
    out_h[...] = h2
    out_x[...] = xp2
    out_X[...] = Xp2
    out_Hv[...] = Hv2


def _stack_fwd_impl(cfg: StackConfig, h0, x0, v, X0, Hv0, node_mask,
                    node_attr, gravity, edge_arrs, remote_arrs, wstack):
    """Build operands, run the megakernel, unpack results + checkpoints."""
    row_t, col_l, kblk, scal = edge_arrs
    rr, rc, rattr, rmask = remote_arrs
    N, H = h0.shape
    C = cfg.channels
    T = cfg.block
    L = cfg.n_layers
    nb = _check_grid(N, T)
    nt = row_t.shape[0] // nb
    E = col_l.shape[0]
    R = rr.shape[0]
    if L < 1:
        raise ValueError(f"fused_egnn_stack needs n_layers >= 1 (got {L})")
    check_stack_vmem(cfg, n_nodes=N, n_edges=E, remote_pad=R)

    xp0 = jnp.zeros((N, XL), jnp.float32).at[:, 0:3].set(x0)
    vp = jnp.zeros((N, XL), jnp.float32).at[:, 0:3].set(
        v.astype(jnp.float32))
    X0p = jnp.zeros((XL, C), jnp.float32).at[0:3, :].set(X0)
    nm = node_mask.astype(jnp.float32)[:, None]
    rsc = jnp.concatenate(
        [rattr[:, :2].astype(jnp.float32),
         rmask.astype(jnp.float32)[:, None],
         jnp.zeros((R, XL - 3), jnp.float32)], axis=1)

    wkeys = sorted(stack_weight_shapes(cfg))
    names = ["row_t", "col_l", "kblk", "scal", "xp0", "h0", "vp", "X0",
             "Hv0", "nm"]
    operands = [row_t, col_l, kblk, scal, xp0, h0.astype(jnp.float32), vp,
                X0p, Hv0.astype(jnp.float32), nm]
    if cfg.node_attr_nf:
        names.append("nattr")
        operands.append(node_attr.astype(jnp.float32))
    if cfg.has_gravity:
        names.append("gvec")
        operands.append(jnp.zeros((1, XL), jnp.float32).at[0, 0:3].set(
            gravity.astype(jnp.float32)))
    names += ["rr", "rc", "rsc"] + ["w:" + k for k in wkeys]
    operands += [rr.astype(jnp.int32)[:, None], rc.astype(jnp.int32)[:, None],
                 rsc] + [wstack[k] for k in wkeys]

    def const(shape):
        return pl.BlockSpec(shape, lambda l: (0,) * len(shape),
                            memory_space=pltpu.VMEM)

    def per_layer(shape):
        return pl.BlockSpec((1,) + shape,
                            lambda l: (l,) + (0,) * len(shape),
                            memory_space=pltpu.VMEM)

    in_specs = [const(op.shape) for op in operands[:len(names) - len(wkeys)]]
    in_specs += [per_layer(stack_weight_shapes(cfg)[k]) for k in wkeys]

    out_specs = (const((N, H)), const((N, XL)), const((XL, C)),
                 const((H, C)),
                 per_layer((N, H)), per_layer((N, XL)), per_layer((XL, C)),
                 per_layer((H, C)))
    out_shape = (jax.ShapeDtypeStruct((N, H), jnp.float32),
                 jax.ShapeDtypeStruct((N, XL), jnp.float32),
                 jax.ShapeDtypeStruct((XL, C), jnp.float32),
                 jax.ShapeDtypeStruct((H, C), jnp.float32),
                 jax.ShapeDtypeStruct((L, N, H), jnp.float32),
                 jax.ShapeDtypeStruct((L, N, XL), jnp.float32),
                 jax.ShapeDtypeStruct((L, XL, C), jnp.float32),
                 jax.ShapeDtypeStruct((L, H, C), jnp.float32))
    scratch = [pltpu.VMEM((N, H), jnp.float32),
               pltpu.VMEM((N, H), jnp.float32),
               pltpu.VMEM((N, XL), jnp.float32),
               pltpu.VMEM((N, XL), jnp.float32),
               pltpu.VMEM((XL, C), jnp.float32),
               pltpu.VMEM((XL, C), jnp.float32),
               pltpu.VMEM((H, C), jnp.float32),
               pltpu.VMEM((H, C), jnp.float32)]

    outs = pl.pallas_call(
        functools.partial(_stack_kernel, cfg=cfg, names=tuple(names),
                          nb=nb, nt=nt),
        grid=(L,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=_use_interpret(),
    )(*operands)
    (oh, ox, oX, oHv, ckh, ckx, ckX, ckHv) = outs
    out = (oh, ox[:, 0:3], oX[0:3, :], oHv)
    cks = (ckh, ckx[:, :, 0:3], ckX[:, 0:3, :], ckHv)
    return out, cks


# ------------------------------------------------- backward layer reference

def _layer_ref(cfg: StackConfig, h, x, v, X, Hv, node_mask, node_attr,
               gravity, edge_arrs, remote_arrs, w):
    """Pure-JAX single-layer reference — the backward rematerializes through
    this. Its in-window edge pass IS `fused_edge_layer`, whose Pallas
    backward recomputes the per-edge activations from the same VMEM windows
    (remat at tile scale), so differentiating this function never saves an
    O(E)-wide residual."""
    H = cfg.hidden
    dt = None if cfg.dtype_name == "f32" else jnp.bfloat16
    c = _cast(dt)
    row_t, col_l, kblk, scal = edge_arrs
    rr, rc, rattr, rmask = remote_arrs
    N = x.shape[0]

    w1 = w["e_w1"]
    hr = c(h) @ c(w1[:H])
    hc = c(h) @ c(w1[H:2 * H])
    ew = EdgeWeights(ws=w1[2 * H:], b1=w["e_b1"], w2=w["e_w2"], b2=w["e_b2"],
                     w3=w["e_w3"], b3=w["e_b3"], w4=w["e_w4"])
    trans_sum, count, ef_sum = fused_edge_layer(
        x, hr, hc, row_t, col_l, kblk, scal, ew, cfg.block, cfg.dtype_name)

    rm = rmask[:, None]
    x_r, x_c = jnp.take(x, rr, axis=0), jnp.take(x, rc, axis=0)
    hr_r, hc_c = jnp.take(hr, rr, axis=0), jnp.take(hc, rc, axis=0)
    cd_r, g_r, ef_r = _remote_edge_math(x_r, x_c, hr_r, hc_c, rattr, rm, w,
                                        H, dt)
    trans_sum = trans_sum + jax.ops.segment_sum(cd_r * g_r, rr,
                                                num_segments=N)
    count = count + jax.ops.segment_sum(rmask, rr, num_segments=N)
    ef_sum = ef_sum + jax.ops.segment_sum(ef_r.astype(jnp.float32) * rm, rr,
                                          num_segments=N)

    cnt = jnp.maximum(count, 1.0)[:, None]
    agg = trans_sum / cnt if cfg.coords_mean else trans_sum
    agg_h = ef_sum / cnt
    nm = node_mask.astype(jnp.float32)[:, None]
    return _virtual_and_update(h, x, v, X, Hv, agg, agg_h, nm, node_attr,
                               gravity, w, cfg)


# -------------------------------------------------------------- custom_vjp

@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def fused_egnn_stack(cfg: StackConfig, h0, x0, v, X0, Hv0, node_mask,
                     node_attr, gravity, edge_arrs, remote_arrs, wstack):
    """Run all L EGCL layers in one Pallas megakernel (single graph).

    Args:
      cfg        StackConfig (static)
      h0         [N, H] f32 embedded node features
      x0         [N, 3] f32 coordinates (Morton-ordered, block-padded)
      v          [N, 3] f32 velocities
      X0         [3, C] f32 initial virtual coordinates
      Hv0        [H, C] f32 initial virtual features
      node_mask  [N] f32
      node_attr  [N, A] f32 or None (cfg.node_attr_nf == 0)
      gravity    [3] f32 or None (cfg.has_gravity == False)
      edge_arrs  build_edge_blocks output (row_t, col_l, kblk, scal)
      remote_arrs (rr [R] i32, rc [R] i32, rattr [R, >=2] f32, rmask [R] f32)
      wstack     {key: [L, a, b]} stacked per-layer weights
                 (stack_weight_shapes layout — a runtime view of the same
                 param tree as the per-layer fused path)

    Returns (h [N,H], x [N,3], X [3,C], Hv [H,C]) after L layers.

    Cotangent contract: grads flow to h0/x0/v/X0/Hv0 and wstack; the
    batch-borne constants (masks, edge/remote arrays, node_attr, gravity)
    get zero cotangents — the `_fel_bwd` convention.
    """
    out, _ = _stack_fwd_impl(cfg, h0, x0, v, X0, Hv0, node_mask, node_attr,
                             gravity, edge_arrs, remote_arrs, wstack)
    return out


def _stack_fwd(cfg, h0, x0, v, X0, Hv0, node_mask, node_attr, gravity,
               edge_arrs, remote_arrs, wstack):
    out, cks = _stack_fwd_impl(cfg, h0, x0, v, X0, Hv0, node_mask, node_attr,
                               gravity, edge_arrs, remote_arrs, wstack)
    res = (cks, v, node_mask, node_attr, gravity, edge_arrs, remote_arrs,
           wstack)
    return out, res


def _stack_bwd(cfg, res, ct):
    (cks, v, node_mask, node_attr, gravity, edge_arrs, remote_arrs,
     wstack) = res
    ck_h, ck_x, ck_X, ck_Hv = cks
    dh, dx, dX, dHv = ct
    dv = jnp.zeros_like(v)
    dw_layers = []
    for l in reversed(range(cfg.n_layers)):
        wl = {k: wstack[k][l] for k in wstack}

        def f(h_, x_, v_, X_, Hv_, w_):
            return _layer_ref(cfg, h_, x_, v_, X_, Hv_, node_mask, node_attr,
                              gravity, edge_arrs, remote_arrs, w_)

        _, vjp = jax.vjp(f, ck_h[l], ck_x[l], v, ck_X[l], ck_Hv[l], wl)
        dh, dx, dv_l, dX, dHv, dwl = vjp((dh, dx, dX, dHv))
        dv = dv + dv_l
        dw_layers.append(dwl)
    dws = {k: jnp.stack([dwl[k] for dwl in reversed(dw_layers)])
           for k in wstack}
    zeros = lambda t: jax.tree.map(jnp.zeros_like, t)
    return (dh, dx, dv, dX, dHv, zeros(node_mask), zeros(node_attr),
            zeros(gravity), zeros(edge_arrs), zeros(remote_arrs), dws)


fused_egnn_stack.defvjp(_stack_fwd, _stack_bwd)
