"""Blocked-CSR edge aggregation — MXU kernels for the scatter/gather hot loop.

Round-2 profiling (BASELINE.md "Step-time breakdown") showed the LargeFluid
train step is NOT compute-bound: XLA's scatter-add runs one [E=1.6M, 64]
edge->node aggregation in 22-33 ms (~19 GB/s effective, vs ~800 GB/s HBM) and
gathers at ~43 GB/s, so the step spends >80% of its time in what the reference
does with CUDA scatter kernels (models/FastEGNN.py:322-337, torch_scatter).

The TPU-native fix is a LAYOUT, not a faster scatter. Edge lists are already
sorted by destination row (ops/graph.py pad_graphs); here we additionally pad
them so that every 256-node *block* owns a fixed-size slice of the edge axis:

    edge slice [b*epb, (b+1)*epb)  holds exactly the edges whose destination
    row lies in node block [b*256, (b+1)*256), padded with masked slots.

With that invariant, both hot ops become *block-local dense matmuls* against a
one-hot incidence tile generated in VMEM — pure MXU work, no scatter at all:

    aggregate:  out[block b] += onehot[tile, 256]^T @ data[tile, F]
    gather:     out[tile]     = onehot[tile, 256]   @ h[block b]

The one-hot tile never touches HBM (built from an iota compare inside the
kernel), so HBM traffic is one streaming read of the edge array and one write
of the node array — the bandwidth floor. FLOP cost is E*256*F ~ 52 GFLOP at
LargeFluid scale: noise for the MXU. The two kernels are exact adjoints, so
``jax.custom_vjp`` wires aggregate-backward = gather and gather-backward =
aggregate, killing the backward-pass scatters too (the round-2 profile's
biggest single line).

The blocked layout is still a valid row-sorted padded edge list, so every
existing code path (XLA fallback, other models, the distributed partitioner)
consumes it unchanged; the kernels are an opt-in fast path keyed on
``GraphBatch.edge_block``.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK = 256       # nodes per block = one-hot matmul N dimension
DEFAULT_EDGE_TILE = 512   # edges per grid step = one-hot matmul K dimension


# ---------------------------------------------------------------------------
# Host-side layout builder
# ---------------------------------------------------------------------------

def _blockify_plan(edge_index: np.ndarray, n_nodes_padded: int, epb: int,
                   block: int):
    """One vectorized pass: arbitrary edge order -> (src, dst, blocked index,
    mask). ``src`` are input-edge positions sorted stably by destination row
    (so already-sorted input keeps its order bit-for-bit); ``dst`` is each
    sorted edge's slot ``block_idx*epb + rank_within_block``. No per-block
    Python loop — the whole layout is two argsort/searchsorted sweeps plus
    fancy-index writes."""
    row = edge_index[0]
    e = int(row.shape[0])
    nb = n_nodes_padded // block
    src = np.argsort(row, kind="stable")
    rows = row[src]
    bounds = np.searchsorted(rows, np.arange(nb + 1) * block)
    counts = np.diff(bounds)
    if counts.max(initial=0) > epb:
        raise ValueError(f"blockify_edges: epb={epb} < max block degree {counts.max()}")
    if bounds[-1] != e:
        raise ValueError("blockify_edges: edge rows exceed n_nodes_padded")
    dst = (np.repeat(np.arange(nb, dtype=np.int64) * epb, counts)
           + np.arange(e, dtype=np.int64)
           - np.repeat(bounds[:-1].astype(np.int64), counts))
    E = nb * epb
    new_index = np.empty((2, E), np.int32)
    pad_rows = np.arange(1, nb + 1, dtype=np.int32) * block - 1
    new_index[0] = np.repeat(pad_rows, epb)
    new_index[1] = new_index[0]
    new_index[:, dst] = edge_index[:, src]
    new_mask = np.zeros((E,), np.float32)
    new_mask[dst] = 1.0
    return src, dst, new_index, new_mask


def blockify_edges(
    edge_index: np.ndarray,      # [2, e] int, ANY edge order
    edge_attr: Optional[np.ndarray],  # [e, D] or None
    n_nodes_padded: int,         # N, multiple of `block`
    epb: int,                    # edge slots per block (multiple of edge_tile)
    block: int = DEFAULT_BLOCK,
):
    """Re-lay one graph's edge list into per-block padded slices.

    Returns (edge_index' [2, NB*epb], edge_attr' [NB*epb, D], edge_mask'
    [NB*epb]). Padding slots carry row = col = (their block's last node) so the
    global row ordering stays ascending — the layout remains a legal
    ``edges_sorted`` edge list for the XLA fallback path. Vectorized (one
    NumPy pass, no per-block loop); row-sorted input reproduces the historic
    layout bit-for-bit, arbitrary order is stably row-sorted first.
    """
    src, dst, new_index, new_mask = _blockify_plan(
        edge_index, n_nodes_padded, epb, block)
    D = edge_attr.shape[1] if edge_attr is not None else 0
    new_attr = np.zeros((new_mask.shape[0], D), np.float32)
    if D and edge_attr is not None:
        new_attr[dst] = edge_attr[src]
    return new_index, new_attr, new_mask


class RepackPlan(NamedTuple):
    """Topology-only artifact of :func:`repack_blocked` — everything about a
    graph's blocked layout that does NOT depend on positions/attributes, so a
    session serving the same scene can re-apply it to fresh per-step arrays
    with two fancy-index gathers (the serve prep cache's hit path).

    perm[new] = old Morton node relabel (None when built without loc);
    edge_index/edge_mask are the blocked [2, NB*epb]/[NB*epb] arrays;
    src/dst map client edge k's payload to slot dst via attr'[dst] = attr[src].
    """
    perm: Optional[np.ndarray]
    edge_index: np.ndarray
    edge_mask: np.ndarray
    src: np.ndarray
    dst: np.ndarray
    stamp: tuple                 # (n_nodes_padded, epb, block)

    def apply_edge_attr(self, edge_attr: np.ndarray) -> np.ndarray:
        out = np.zeros((self.edge_mask.shape[0], edge_attr.shape[1]),
                       np.float32)
        out[self.dst] = edge_attr[self.src]
        return out


def repack_blocked(edge_index: np.ndarray, loc: Optional[np.ndarray] = None,
                   *, n_nodes_padded: int, epb: int,
                   block: int = DEFAULT_BLOCK, bits: int = 16) -> RepackPlan:
    """Arbitrary client edge order -> the kernels' Morton/blocked layout in
    one vectorized NumPy pass (sort-by-(block, row), no per-block loop).

    When ``loc`` is given the node ids are first relabeled along the Z-order
    curve (ops/order.py) so spatially-near nodes share blocks — the layout
    the fused kernel's locality analysis assumes. Returns a :class:`RepackPlan`
    whose ``src``/``dst`` index maps let position-dependent payloads
    (edge_attr) be re-laid later without redoing the sort.
    """
    ei = np.asarray(edge_index).astype(np.int64, copy=False)
    perm = None
    if loc is not None:
        from distegnn_tpu.ops.order import morton_perm

        perm = morton_perm(np.asarray(loc), bits=bits)
        inv = np.empty_like(perm)
        inv[perm] = np.arange(perm.shape[0], dtype=perm.dtype)
        ei = inv[ei]
    src, dst, new_index, new_mask = _blockify_plan(
        ei, n_nodes_padded, epb, block)
    return RepackPlan(perm=perm, edge_index=new_index, edge_mask=new_mask,
                      src=src, dst=dst,
                      stamp=(n_nodes_padded, epb, block))


def max_block_degree(rows_sorted: np.ndarray, n_nodes_padded: int,
                     block: int = DEFAULT_BLOCK) -> int:
    """Max number of edges landing in any single node block (sorted rows)."""
    nb = n_nodes_padded // block
    bounds = np.searchsorted(rows_sorted, np.arange(nb + 1) * block)
    return int(np.diff(bounds).max(initial=0))


def pairing_perm(edge_index: np.ndarray) -> Optional[np.ndarray]:
    """Reverse-edge involution P: edge_index[:, P[e]] == (col[e], row[e]).

    Radius graphs are symmetric (every (i,j) has its (j,i)), so the transpose
    of the sparse incidence is just a permutation of the edge axis. That lets
    the backward col-scatter — the one aggregation the blocked kernels can't
    reach directly — become gather-by-P + blocked row aggregation (see
    paired_col_gather). Returns None when the edge list isn't symmetric
    (caller falls back to XLA scatter). Works on blocked layouts too: padding
    slots carry row == col and pair among themselves.
    """
    r, c = edge_index[0], edge_index[1]
    by_rc = np.lexsort((c, r))
    by_cr = np.lexsort((r, c))
    pair = np.empty(r.shape[0], np.int64)
    pair[by_rc] = by_cr
    if not (np.array_equal(r[pair], c) and np.array_equal(c[pair], r)):
        return None
    return pair


def pairing_perm_fast(edge_index: np.ndarray) -> Optional[np.ndarray]:
    """:func:`pairing_perm` through the native fast path when available
    (native/blockify.cpp), numpy otherwise. Same contract: a verified
    reverse-edge permutation, or None when the list isn't symmetric."""
    from distegnn_tpu.native import native_pairing

    pair = native_pairing(edge_index)
    if pair is None:
        return pairing_perm(edge_index)
    return None if pair is False else pair


def prepare_blocked_graph(g: dict, n_nodes_padded: int, epb: int, block: int,
                          compute_pair: bool = True) -> dict:
    """Blockify one graph dict in place-of (returns a copy): row-sort if
    needed, re-lay edges per block, and attach the reverse-edge pairing.
    Idempotent: a dict already carrying the matching ``_blockified`` stamp is
    returned unchanged (loaders cache prepared graphs across epochs)."""
    stamp = (n_nodes_padded, epb, block)
    if g.get("_blockified") == stamp:
        return g
    g = dict(g)
    if g.get("_blockified") is not None and g.get("_edge_mask") is not None:
        # already blocked under DIFFERENT layout params (e.g. a session-cached
        # dict co-batched with a denser peer): recover the real edge list from
        # the mask before re-packing — padding slots must not become edges
        keep = g["_edge_mask"] > 0
        g["edge_index"] = g["edge_index"][:, keep]
        if g.get("edge_attr") is not None:
            g["edge_attr"] = g["edge_attr"][keep]
        for k in ("_edge_pair", "_edge_mask", "_blockified", "_remote_sel"):
            g.pop(k, None)
    if np.any(np.diff(g["edge_index"][0]) < 0):
        order = np.argsort(g["edge_index"][0], kind="stable")
        g["edge_index"] = g["edge_index"][:, order]
        if g.get("edge_attr") is not None:
            g["edge_attr"] = g["edge_attr"][order]
    # native fast path (native/blockify.cpp) with the numpy implementation as
    # the universal fallback — identical layout either way
    from distegnn_tpu.native import native_blockify

    nat = native_blockify(g["edge_index"].astype(np.int64),
                          g.get("edge_attr"), n_nodes_padded, epb, block)
    if nat is not None:
        ei, ea, em = nat
    else:
        ei, ea, em = blockify_edges(g["edge_index"].astype(np.int64),
                                    g.get("edge_attr"), n_nodes_padded, epb, block)
    g["edge_index"], g["edge_attr"], g["_edge_mask"] = ei, ea, em
    g["_edge_pair"] = pairing_perm_fast(ei) if compute_pair else None
    g["_blockified"] = stamp
    return g


def scan_dataset_for_blocking(dataset, n_nodes_padded: int, block: int):
    """One pass over a dataset: (max block degree, every-graph-symmetric).
    Both are layout decisions that must be made ONCE per dataset so every
    batch of a run shares a single pytree structure / compiled program."""
    deg, symmetric = 1, True
    for i in range(len(dataset)):
        ei = dataset[i]["edge_index"]
        deg = max(deg, max_block_degree(np.sort(ei[0]), n_nodes_padded, block))
        symmetric = symmetric and pairing_perm_fast(ei) is not None
    return deg, symmetric


def slot_ids(row: jnp.ndarray, edge_mask: jnp.ndarray, block: int, epb: int) -> jnp.ndarray:
    """Block-local destination ids with a sentinel for padding.

    row/edge_mask: [..., E] in blocked layout. Returns int32 [..., E] where a
    real edge at position k (block k//epb) gets ``row - block_idx*block`` in
    [0, block) and a masked slot gets ``block`` — which matches no one-hot
    column, so masked slots vanish from every kernel without a multiply.
    """
    E = row.shape[-1]
    blk = (jnp.arange(E, dtype=jnp.int32) // epb) * block
    local = row.astype(jnp.int32) - blk
    return jnp.where(edge_mask > 0, local, block)


# ---------------------------------------------------------------------------
# Pallas kernels (single graph; batched wrappers vmap them)
# ---------------------------------------------------------------------------

def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _precision_for(dtype):
    # f32 operands: 'highest' makes the MXU one-hot contraction exact (the
    # one-hot factor is 0/1, so only data truncation matters — 3-pass bf16
    # recovers full f32). bf16 operands: default single-pass.
    return (jax.lax.Precision.HIGHEST
            if jnp.dtype(dtype) == jnp.float32 else jax.lax.Precision.DEFAULT)


def _seg_sum_kernel(slot_ref, data_ref, out_ref, *, block, precision):
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    tile = slot_ref.shape[0]
    onehot = (slot_ref[:] == jax.lax.broadcasted_iota(jnp.int32, (tile, block), 1))
    out_ref[:] += jax.lax.dot_general(
        onehot.astype(data_ref.dtype), data_ref[:],
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision,
    )


def _gather_kernel(slot_ref, h_ref, out_ref, *, block, precision):
    tile = slot_ref.shape[0]
    onehot = (slot_ref[:] == jax.lax.broadcasted_iota(jnp.int32, (tile, block), 1))
    out_ref[:] = jax.lax.dot_general(
        onehot.astype(h_ref.dtype), h_ref[:],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision,
    ).astype(out_ref.dtype)


def _layout(E: int, n_nodes: int, block: int, tile: int):
    nb, rem = divmod(n_nodes, block)
    if rem:
        raise ValueError(f"n_nodes {n_nodes} not a multiple of block {block}")
    epb, rem = divmod(E, nb)
    if rem:
        raise ValueError(f"E {E} not a multiple of num_blocks {nb}")
    ept, rem = divmod(epb, tile)
    if rem:
        raise ValueError(f"edges/block {epb} not a multiple of tile {tile}")
    return nb, ept


@functools.partial(jax.jit, static_argnames=("n_nodes", "block", "tile"))
def _seg_sum_impl(data, slot, n_nodes: int, block: int, tile: int):
    """[E, F] + slots -> [N, F] float32 (blocked one-hot MXU aggregation)."""
    E, F = data.shape
    nb, ept = _layout(E, n_nodes, block, tile)
    kern = functools.partial(_seg_sum_kernel, block=block,
                             precision=_precision_for(data.dtype))
    return pl.pallas_call(
        kern,
        grid=(nb, ept),
        in_specs=[
            pl.BlockSpec((tile, 1), lambda b, t: (b * ept + t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, F), lambda b, t: (b * ept + t, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block, F), lambda b, t: (b, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_nodes, F), jnp.float32),
        interpret=_use_interpret(),
    )(slot[:, None], data)


@functools.partial(jax.jit, static_argnames=("block", "tile"))
def _gather_impl(h, slot, block: int, tile: int):
    """[N, F] + slots [E] -> [E, F] (blocked one-hot MXU gather)."""
    n_nodes, F = h.shape
    E = slot.shape[0]
    nb, ept = _layout(E, n_nodes, block, tile)
    kern = functools.partial(_gather_kernel, block=block,
                             precision=_precision_for(h.dtype))
    return pl.pallas_call(
        kern,
        grid=(nb, ept),
        in_specs=[
            pl.BlockSpec((tile, 1), lambda b, t: (b * ept + t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block, F), lambda b, t: (b, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile, F), lambda b, t: (b * ept + t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((E, F), h.dtype),
        interpret=_use_interpret(),
    )(slot[:, None], h)


# ---------------------------------------------------------------------------
# Differentiable single-graph ops (exact adjoint pair)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _seg_sum(data, slot, n_nodes, block, tile):
    return _seg_sum_impl(data, slot, n_nodes, block, tile)


def _seg_sum_fwd(data, slot, n_nodes, block, tile):
    out = _seg_sum_impl(data, slot, n_nodes, block, tile)
    return out, (slot, jnp.zeros((), data.dtype))


def _seg_sum_bwd(n_nodes, block, tile, res, g):
    slot, proto = res
    return _gather_impl(g.astype(proto.dtype), slot, block, tile), None


_seg_sum.defvjp(_seg_sum_fwd, _seg_sum_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _gather(h, slot, block, tile):
    return _gather_impl(h, slot, block, tile)


def _gather_fwd(h, slot, block, tile):
    return _gather_impl(h, slot, block, tile), (slot, jnp.zeros((0,) + h.shape[:1], h.dtype))


def _gather_bwd(block, tile, res, g):
    slot, proto = res
    n_nodes = proto.shape[1]
    return _seg_sum_impl(g, slot, n_nodes, block, tile).astype(proto.dtype), None


_gather.defvjp(_gather_fwd, _gather_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _paired_gather(h, col, pair, slot, block, tile):
    return jnp.take(h, col, axis=0)


def _paired_gather_fwd(h, col, pair, slot, block, tile):
    out = jnp.take(h, col, axis=0)
    return out, (pair, slot, jnp.zeros((0,) + h.shape[:1], h.dtype))


def _paired_gather_bwd(block, tile, res, g):
    pair, slot, proto = res
    n_nodes = proto.shape[1]
    grad_h = _seg_sum_impl(jnp.take(g, pair, axis=0), slot, n_nodes, block, tile)
    return grad_h.astype(proto.dtype), None, None, None


_paired_gather.defvjp(_paired_gather_fwd, _paired_gather_bwd)


# ---------------------------------------------------------------------------
# Einsum lowering of the same blocked contraction (impl='einsum')
#
# Identical math to the Pallas kernels, but the one-hot incidence factor is
# MATERIALIZED once per forward as a bf16 [B, nb, epb, block] tensor and every
# aggregation/gather is a plain batched dot XLA schedules itself. Rationale:
# the Pallas kernels run one small (tile x block x F) MXU dot per grid step —
# thousands of steps per call — and the first hardware run measured the
# per-step overhead swamping the dot (BASELINE.md round-2 status). The einsum
# form trades ~E*block*2 bytes of HBM traffic per op (abundant: ~1ms at v5e
# bandwidth for LargeFluid) for zero grid overhead and full XLA pipelining.
#
# f32 exactness without an f32 one-hot: the one-hot factor is exactly
# representable in bf16, so an f32 operand is split into 3 bf16 terms
# (hi/mid/lo, residual ~2^-24 relative) contracted separately and summed in
# f32 — the manual form of XLA's bf16_3x, paying 1x (not 3x) per extra
# operand pass because the one-hot side needs no splitting.
# ---------------------------------------------------------------------------

def onehot_blocks(slot: jnp.ndarray, epb: int, block: int) -> jnp.ndarray:
    """[..., E] slot ids (from :func:`slot_ids`) -> [..., nb, epb, block] bf16
    one-hot incidence. Sentinel slots (== block) match no column and vanish."""
    E = slot.shape[-1]
    nb = E // epb
    s = slot.reshape(slot.shape[:-1] + (nb, epb))
    return (s[..., None] == jnp.arange(block, dtype=jnp.int32)).astype(jnp.bfloat16)


def _bf16_terms(x: jnp.ndarray, n_terms: int = 3):
    """Split x into bf16 terms summing to x up to ~2^-24 relative error.
    bf16 input passes through unsplit."""
    if x.dtype == jnp.bfloat16:
        return [x]
    terms = []
    rem = x.astype(jnp.float32)
    for _ in range(n_terms - 1):
        t = rem.astype(jnp.bfloat16)
        terms.append(t)
        rem = rem - t.astype(jnp.float32)
    terms.append(rem.astype(jnp.bfloat16))
    return terms


def _ein_seg_sum_raw(data: jnp.ndarray, oh: jnp.ndarray) -> jnp.ndarray:
    """[..., E, F] x [..., nb, epb, block] -> [..., nb*block, F] float32."""
    *lead, E, F = data.shape
    nb, epb, block = oh.shape[-3:]
    d = data.reshape(*lead, nb, epb, F)
    out = None
    for t in _bf16_terms(d):
        part = jnp.einsum("...bek,...bef->...bkf", oh, t,
                          preferred_element_type=jnp.float32)
        out = part if out is None else out + part
    return out.reshape(*lead, nb * block, F)


def _ein_gather_raw(h: jnp.ndarray, oh: jnp.ndarray) -> jnp.ndarray:
    """[..., N, F] x [..., nb, epb, block] -> [..., E, F] float32 (blocked row
    gather; sentinel slots read as 0)."""
    *lead, N, F = h.shape
    nb, epb, block = oh.shape[-3:]
    hh = h.reshape(*lead, nb, block, F)
    out = None
    for t in _bf16_terms(hh):
        part = jnp.einsum("...bek,...bkf->...bef", oh, t,
                          preferred_element_type=jnp.float32)
        out = part if out is None else out + part
    return out.reshape(*lead, nb * epb, F)


# The raw forms are exact adjoints, but differentiating THROUGH the bf16 term
# split would bf16-round the cotangent (the transpose of an f32->bf16 cast
# rounds); these custom_vjps instead apply the split to the cotangent itself,
# keeping gradients f32-accurate — and, as with the Pallas pair, guaranteeing
# the backward pass contains no scatter.

@jax.custom_vjp
def einsum_segment_sum(data, oh):
    return _ein_seg_sum_raw(data, oh)


def _ein_seg_sum_fwd(data, oh):
    return _ein_seg_sum_raw(data, oh), (oh, jnp.zeros((), data.dtype))


def _ein_seg_sum_bwd(res, g):
    oh, proto = res
    return _ein_gather_raw(g, oh).astype(proto.dtype), None


einsum_segment_sum.defvjp(_ein_seg_sum_fwd, _ein_seg_sum_bwd)


@jax.custom_vjp
def einsum_gather(h, oh):
    return _ein_gather_raw(h, oh).astype(h.dtype)


def _ein_gather_fwd(h, oh):
    return _ein_gather_raw(h, oh).astype(h.dtype), (oh, jnp.zeros((), h.dtype))


def _ein_gather_bwd(res, g):
    oh, proto = res
    return _ein_seg_sum_raw(g, oh).astype(proto.dtype), None


einsum_gather.defvjp(_ein_gather_fwd, _ein_gather_bwd)


@jax.custom_vjp
def _paired_gather_ein(h, col, pair, oh):
    return jnp.take(h, col, axis=0)


def _paired_gather_ein_fwd(h, col, pair, oh):
    return jnp.take(h, col, axis=0), (pair, oh, jnp.zeros((), h.dtype))


def _paired_gather_ein_bwd(res, g):
    pair, oh, proto = res
    grad_h = _ein_seg_sum_raw(jnp.take(g, pair, axis=0), oh)
    return grad_h.astype(proto.dtype), None, None, None


_paired_gather_ein.defvjp(_paired_gather_ein_fwd, _paired_gather_ein_bwd)


# ---------------------------------------------------------------------------
# Public batched API (mirrors ops.segment signatures)
# ---------------------------------------------------------------------------

def blocked_segment_sum(data, slot, num_segments: int, block: int = DEFAULT_BLOCK,
                        tile: int = DEFAULT_EDGE_TILE):
    """Batched [B, E, F] -> [B, N, F] float32. ``slot`` from :func:`slot_ids`
    (masked slots carry the sentinel and contribute nothing)."""
    return jax.vmap(lambda d, s: _seg_sum(d, s, num_segments, block, tile))(data, slot)


def blocked_slot_inv_deg(g, impl: str = "einsum"):
    """(slot ids, 1/max(in-degree,1), one-hot incidence or None) for a blocked
    GraphBatch, or (None, None, None) when g is not blocked. Wrappers call
    this ONCE per forward — row/edge_mask are layer-invariant, so one pass
    serves L layers. ``impl``: 'pallas' (one-hot built in VMEM per kernel) or
    'einsum' (one-hot materialized, ops become plain batched dots)."""
    if g.edge_block <= 0:
        return None, None, None
    slot = slot_ids(g.row, g.edge_mask, g.edge_block, g.edges_per_block)
    if impl == "einsum":
        oh = onehot_blocks(slot, g.edges_per_block, g.edge_block)  # [B,nb,epb,blk]
        # in-degree is just a column sum of the incidence (masked slots carry
        # the sentinel and are all-zero one-hot rows already)
        deg = jnp.sum(oh, axis=-2, dtype=jnp.float32).reshape(
            oh.shape[0], g.max_nodes, 1)
    elif impl == "pallas":
        oh = None
        deg = blocked_segment_sum(g.edge_mask[..., None], slot, g.max_nodes,
                                  g.edge_block, g.edge_tile)
    else:
        raise ValueError(f"unknown blocked impl {impl!r}")
    return slot, 1.0 / jnp.maximum(deg, 1.0), oh


class EdgeOps:
    """The one definition of the edge-op dispatch all model families share:
    row/col gathers and per-destination aggregations, lowered as

      blocked   MXU one-hot kernels when the batch carries the blocked layout
                (with the reverse-edge pairing backward when available);
      cumsum    ``seg_impl='cumsum'`` on a plain row-sorted batch: prefix-sum
                differences with gather-only custom VJPs — no XLA scatter in
                forward OR backward (ops/segment.py cumsum block);
      ell       ``seg_impl='ell'`` on a plain row-sorted batch carrying
                max_in_degree: fixed-degree chained gathers — scatter-free
                AND exact (ops/segment.py ELL block);
      scatter   XLA sorted-scatter otherwise (bit-exact reference path).

    ``slot``/``inv_deg``/``oh`` come from :func:`blocked_slot_inv_deg`
    (hoisted once per forward; plain arrays, so layers stay remat-able).
    ``oh is not None`` selects the einsum lowering, otherwise the Pallas
    kernels."""

    def __init__(self, g, slot=None, inv_deg=None, oh=None,
                 seg_impl: str = "scatter"):
        self.g, self.slot, self.inv_deg, self.oh = g, slot, inv_deg, oh
        self.blocked = slot is not None
        if seg_impl not in ("scatter", "cumsum", "ell"):
            raise ValueError(f"unknown seg_impl {seg_impl!r}")
        # both scatter-free lowerings need ascending row ids (ELL also the
        # static max_in_degree); keep the exact scatter path when the batch
        # can't support the request
        self.cumsum = (seg_impl == "cumsum" and not self.blocked
                       and g.edges_sorted)
        self.ell = (seg_impl == "ell" and not self.blocked
                    and g.edges_sorted and g.max_in_degree > 0)

    def gather_rows(self, data):
        if self.blocked:
            if self.oh is not None:
                # the einsum ops are leading-dim polymorphic ('...' batch)
                return einsum_gather(data, self.oh)
            return blocked_gather(data, self.slot, self.g.edge_block,
                                  self.g.edge_tile)
        if self.cumsum:
            from distegnn_tpu.ops.segment import gather_rows_cs

            return jax.vmap(gather_rows_cs)(data, self.g.row)
        if self.ell:
            from distegnn_tpu.ops.segment import gather_rows_ell

            D = self.g.max_in_degree
            return jax.vmap(lambda h, r: gather_rows_ell(h, r, D))(data, self.g.row)
        return jnp.take_along_axis(data, self.g.row[..., None], axis=1)

    def gather_cols(self, data):
        g = self.g
        if self.blocked and g.edge_pair is not None:
            if self.oh is not None:
                return jax.vmap(_paired_gather_ein)(data, g.col, g.edge_pair,
                                                    self.oh)
            return paired_col_gather(data, g.col, g.edge_pair, self.slot,
                                     g.edge_block, g.edge_tile)
        if self.cumsum and g.edge_pair is not None:
            from distegnn_tpu.ops.segment import paired_gather_cols_cs

            return jax.vmap(paired_gather_cols_cs)(data, g.col, g.edge_pair,
                                                   g.row, g.edge_mask)
        if self.ell and g.edge_pair is not None:
            from distegnn_tpu.ops.segment import paired_gather_cols_ell

            D = g.max_in_degree
            return jax.vmap(lambda h, c, p, r, m: paired_gather_cols_ell(
                h, c, p, r, m, D))(data, g.col, g.edge_pair, g.row, g.edge_mask)
        return jnp.take_along_axis(data, g.col[..., None], axis=1)

    def _agg(self, data, mean: bool):
        from distegnn_tpu.ops.segment import (segment_mean, segment_mean_cs,
                                              segment_sum, segment_sum_cs)

        g = self.g
        N = g.max_nodes
        if self.blocked:
            if self.oh is not None:
                out = einsum_segment_sum(data, self.oh)
            else:
                out = blocked_segment_sum(data, self.slot, N, g.edge_block,
                                          g.edge_tile)
            if mean:
                out = out * self.inv_deg
            return out.astype(data.dtype)
        if self.cumsum:
            seg_cs = segment_mean_cs if mean else segment_sum_cs
            return jax.vmap(lambda t, r, m: seg_cs(t, r, N, mask=m))(
                data, g.row, g.edge_mask)
        if self.ell:
            from distegnn_tpu.ops.segment import (segment_mean_ell,
                                                  segment_sum_ell)

            seg_el = segment_mean_ell if mean else segment_sum_ell
            D = g.max_in_degree
            return jax.vmap(lambda t, r, m: seg_el(t, r, N, D, mask=m))(
                data, g.row, g.edge_mask)
        seg = segment_mean if mean else segment_sum
        return jax.vmap(lambda t, r, m: seg(
            t, r, N, mask=m, indices_are_sorted=g.edges_sorted))(
            data, g.row, g.edge_mask)

    def agg_rows_mean(self, data):
        """Per-destination mean over real edges (count clamped >= 1)."""
        return self._agg(data, mean=True)

    def agg_rows_sum(self, data):
        return self._agg(data, mean=False)

    def agg_rows_pair(self, a, b, a_mean: bool, agg_dtype=None):
        """Aggregate TWO edge streams in ONE pass: returns
        (agg_sum_or_mean(a), agg_mean(b)), both float32.

        The round-2 profile puts the step cost in the per-aggregation
        scatters/prefix passes, and every EGCL layer needs exactly two row
        aggregations (coordinate translations + edge features) plus a count.
        Packing them as columns of a single segment sum halves the number of
        aggregation passes per layer — for every lowering: one scatter
        instead of two scatters + a count (op-bound path), one prefix pass
        instead of two (bandwidth-bound cumsum path), one gather sweep
        instead of two (ELL).

        ``agg_dtype='bf16'`` casts the packed stream to bfloat16 before the
        pass, halving the dominant [E, 3+H] read bytes; accumulation stays
        f32 in every lowering (prefix_sum and the ELL reducer accumulate
        f32 by construction; the scatter path scatters into an f32 output).
        NOTE: bf16 rounds the GEOMETRY stream (a = coordinate translations),
        trading exact-at-math-level equivariance for bandwidth — off by
        default, a measured opt-in (VERDICT r3 #1 prepared attack).

        Blocked layouts keep their two-call path (mean is a free inv_deg
        multiply there)."""
        if self.blocked:
            # two-call path (mean is a free inv_deg multiply here), but the
            # stream-dtype knob still applies: bf16 operands run the one-hot
            # kernels single-pass instead of f32 precision=HIGHEST 6-pass —
            # the gen-2 blocked configuration (VERDICT r3 #1)
            if agg_dtype in ("bf16", jnp.bfloat16):
                a = a.astype(jnp.bfloat16)
                b = b.astype(jnp.bfloat16)
            out_a = self.agg_rows_sum(a) if not a_mean else self.agg_rows_mean(a)
            return (out_a.astype(jnp.float32),
                    self.agg_rows_mean(b).astype(jnp.float32))
        g = self.g
        B, E = b.shape[0], b.shape[1]
        sa = a.shape[-1]
        dt = jnp.bfloat16 if agg_dtype in ("bf16", jnp.bfloat16) else jnp.float32
        em = g.edge_mask[..., None]
        packed = jnp.concatenate(
            [a.astype(dt), b.astype(dt),
             jnp.ones((B, E, 1), dt)], axis=-1) * em.astype(dt)
        N = g.max_nodes
        if self.cumsum:
            from distegnn_tpu.ops.segment import sorted_segment_sum_cs

            out = jax.vmap(lambda t, r: sorted_segment_sum_cs(t, r, N).astype(
                jnp.float32))(packed, g.row)
        elif self.ell:
            from distegnn_tpu.ops.segment import sorted_segment_sum_ell

            D = g.max_in_degree
            out = jax.vmap(lambda t, r: sorted_segment_sum_ell(
                t, r, N, D).astype(jnp.float32))(packed, g.row)
        else:
            # f32 accumulator regardless of stream dtype (a bf16 scatter-add
            # accumulator saturates); XLA fuses the convert into the scatter
            # operand so the HBM read stays at stream width
            out = jax.vmap(lambda t, r: jnp.zeros(
                (N, t.shape[-1]), jnp.float32).at[r].add(
                    t.astype(jnp.float32),
                    indices_are_sorted=g.edges_sorted))(packed, g.row)
        cnt = jnp.maximum(out[..., -1:], 1.0)
        out_a = out[..., :sa] / cnt if a_mean else out[..., :sa]
        return out_a, out[..., sa:-1] / cnt


def blocked_gather(h, slot, block: int = DEFAULT_BLOCK, tile: int = DEFAULT_EDGE_TILE):
    """Batched [B, N, F] -> [B, E, F]; rows fetched block-locally (masked
    slots read as 0). Adjoint of :func:`blocked_segment_sum`."""
    return jax.vmap(lambda hh, s: _gather(hh, s, block, tile))(h, slot)


def paired_col_gather(h, col, pair, slot, block: int = DEFAULT_BLOCK,
                      tile: int = DEFAULT_EDGE_TILE):
    """Batched h[b, col[b, e]] whose BACKWARD is perm-gather + blocked row
    aggregation instead of an unsorted XLA scatter: the transpose of a
    symmetric graph's incidence is the edge permutation ``pair``
    (:func:`pairing_perm`), so grad_h = seg_sum(grad[pair], slot)."""
    return jax.vmap(lambda hh, c, p, s: _paired_gather(hh, c, p, s, block, tile))(
        h, col, pair, slot)
