"""Masked segment ops — the TPU replacement for torch_scatter / scatter_add_.

The reference implements message aggregation with CUDA scatter kernels
(reference models/FastEGNN.py:322-337, unsorted_segment_{sum,mean} via
``scatter_add_`` with ``count.clamp(min=1)``). On TPU we use XLA's native
scatter-add (``jnp.zeros(...).at[ids].add(data)``), which lowers to an
efficient sorted-segment reduction, and carry explicit edge/node masks so all
shapes stay static under jit.

All functions are single-graph (leading axis = elements); batch them with
``jax.vmap`` — the model code does exactly that.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def segment_sum(data, segment_ids, num_segments, mask=None, indices_are_sorted=False):
    """Sum ``data`` rows into ``num_segments`` buckets.

    data: [E, ...]; segment_ids: [E] int; mask: optional [E] (0/1 or bool).
    Returns [num_segments, ...]. Masked-out rows contribute nothing (they may
    carry arbitrary ids, e.g. padding pointing at segment 0).

    ``indices_are_sorted=True`` (pad_graphs emits row-sorted edge lists —
    GraphBatch.edges_sorted) lets XLA use its sorted-scatter lowering.
    """
    if mask is not None:
        m = mask.astype(data.dtype).reshape(mask.shape + (1,) * (data.ndim - 1))
        data = data * m
    out_shape = (num_segments,) + data.shape[1:]
    return jnp.zeros(out_shape, dtype=data.dtype).at[segment_ids].add(
        data, indices_are_sorted=indices_are_sorted)


def segment_mean(data, segment_ids, num_segments, mask=None, indices_are_sorted=False):
    """Mean of ``data`` rows per segment; empty segments yield 0.

    Parity: reference clamps counts to >=1 (models/FastEGNN.py:337) — same
    behavior here via ``maximum(count, 1)``.
    """
    total = segment_sum(data, segment_ids, num_segments, mask=mask,
                        indices_are_sorted=indices_are_sorted)
    # counts accumulate in f32 regardless of data dtype: a bf16 accumulator
    # saturates at 256 (ulp 2), silently inflating means of degree>=256 nodes
    if mask is None:
        ones = jnp.ones(data.shape[:1], dtype=jnp.float32)
    else:
        ones = mask.astype(jnp.float32)
    count = jnp.zeros((num_segments,), dtype=jnp.float32).at[segment_ids].add(
        ones, indices_are_sorted=indices_are_sorted)
    count = jnp.maximum(count, 1.0).astype(data.dtype)
    return total / count.reshape((num_segments,) + (1,) * (data.ndim - 1))


def segment_max(data, segment_ids, num_segments, mask=None, initial=-1e30):
    """Per-segment max; empty segments yield ``initial``. Masked rows are
    replaced by ``initial`` before the scatter so they never win."""
    if mask is not None:
        m = mask.reshape(mask.shape + (1,) * (data.ndim - 1)).astype(bool)
        data = jnp.where(m, data, initial)
    out_shape = (num_segments,) + data.shape[1:]
    return jnp.full(out_shape, initial, dtype=data.dtype).at[segment_ids].max(data)


def segment_softmax(scores, segment_ids, num_segments, mask=None):
    """Numerically-stable softmax over rows sharing a segment id (the TPU
    replacement for DGL's edge_softmax, reference modules.py:542). Masked rows
    get weight 0; segments with no rows produce all-zero weights."""
    if mask is not None:
        # mask BEFORE the exp: a masked row's raw score may exceed its
        # segment's real max, and exp(large) * 0 would be NaN
        m = mask.reshape(mask.shape + (1,) * (scores.ndim - 1)).astype(bool)
        scores = jnp.where(m, scores, -1e30)
    mx = segment_max(scores, segment_ids, num_segments)
    shifted = jnp.maximum(scores - mx[segment_ids], -80.0)
    e = jnp.where(scores > -1e29, jnp.exp(shifted), 0.0)
    denom = segment_sum(e, segment_ids, num_segments)
    return e / jnp.maximum(denom[segment_ids], 1e-30)


# --------------------------------------------------------------------------
# Scatter-free sorted-segment ops (``segment_impl='cumsum'``).
#
# XLA's TPU scatter-add runs far below HBM bandwidth at LargeFluid scale
# (BASELINE.md: 22-33 ms per [1.6M, 64] aggregation, ~4% of peak), and both
# blocked one-hot MXU lowerings measured slower end to end on hardware. This
# lowering uses only bandwidth-friendly primitives: for ascending segment ids
# (GraphBatch.edges_sorted), segment sums are exclusive-prefix differences
#
#     out[n] = cumsum(data)[end_n - 1] - cumsum(data)[start_n - 1]
#
# with the CSR bounds found by vectorized binary search. The accumulation runs
# in float32; the difference of two prefixes carries the rounding of the
# shared prefix (~|prefix| * eps), which is noise at bf16 compute precision
# but NOT bit-identical to the scatter path — strict-f32 parity paths should
# keep ``segment_impl='scatter'``.
#
# The custom VJP makes the backward exact and scatter-free: the cotangent of
# a segment sum is a plain row gather, so no transpose-of-scatter appears
# anywhere (the round-1 profile put ~2/3 of the step in those transposes).
# --------------------------------------------------------------------------

def _cs_bounds(segment_ids, num_segments):
    idx = jnp.arange(num_segments, dtype=segment_ids.dtype)
    starts = jnp.searchsorted(segment_ids, idx, side="left")
    ends = jnp.searchsorted(segment_ids, idx, side="right")
    return starts, ends


def _cs_sum_impl(data, segment_ids, num_segments):
    from distegnn_tpu.ops.cumsum import prefix_sum

    E = data.shape[0]
    c = prefix_sum(data.reshape(E, -1)).reshape((E,) + data.shape[1:])
    starts, ends = _cs_bounds(segment_ids, num_segments)
    tail = (1,) * (data.ndim - 1)
    hi = jnp.where((ends > 0).reshape((-1,) + tail),
                   jnp.take(c, jnp.maximum(ends - 1, 0), axis=0), 0.0)
    lo = jnp.where((starts > 0).reshape((-1,) + tail),
                   jnp.take(c, jnp.maximum(starts - 1, 0), axis=0), 0.0)
    return (hi - lo).astype(data.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def sorted_segment_sum_cs(data, segment_ids, num_segments):
    """Segment sum for ASCENDING ``segment_ids`` without any scatter.
    Rows to exclude must be zeroed by the caller (multiply by the mask
    before the call — that also routes the mask's gradient correctly)."""
    return _cs_sum_impl(data, segment_ids, num_segments)


def _cs_sum_fwd(data, segment_ids, num_segments):
    return _cs_sum_impl(data, segment_ids, num_segments), segment_ids


def _cs_sum_bwd(num_segments, segment_ids, g):
    # d out[n] / d data[e] = [segment_ids[e] == n]: the pull-back is a gather
    return jnp.take(g, segment_ids, axis=0), None


sorted_segment_sum_cs.defvjp(_cs_sum_fwd, _cs_sum_bwd)


def segment_sum_cs(data, segment_ids, num_segments, mask=None):
    """Drop-in for :func:`segment_sum` on sorted ids, cumsum lowering."""
    if mask is not None:
        m = mask.astype(data.dtype).reshape(mask.shape + (1,) * (data.ndim - 1))
        data = data * m
    return sorted_segment_sum_cs(data, segment_ids, num_segments)


def _packed_mean(sum_fn, data, segment_ids, num_segments, mask):
    """Segment mean as ONE packed call of ``sum_fn``: the count rides the
    same pass as the data (one extra column), clamp >= 1 (reference
    models/FastEGNN.py:337). Shared by the cumsum and ELL lowerings."""
    E = data.shape[0]
    flat = data.reshape(E, -1)
    if mask is not None:
        m = mask.astype(flat.dtype).reshape(E, 1)
        flat = flat * m
        ones = m
    else:
        ones = jnp.ones((E, 1), flat.dtype)
    packed = sum_fn(jnp.concatenate([flat, ones], axis=1), segment_ids,
                    num_segments)
    total, count = packed[:, :-1], packed[:, -1:]
    count = jnp.maximum(count.astype(jnp.float32), 1.0).astype(data.dtype)
    return (total / count).reshape((num_segments,) + data.shape[1:])


def segment_mean_cs(data, segment_ids, num_segments, mask=None):
    """Drop-in for :func:`segment_mean` on sorted ids, cumsum lowering."""
    return _packed_mean(sorted_segment_sum_cs, data, segment_ids,
                        num_segments, mask)


# --------------------------------------------------------------------------
# ELL lowering (``segment_impl='ell'``): fixed-degree gather + reduce.
#
# For ascending ids, segment n owns the contiguous slot range
# [start_n, end_n); padding every segment to the batch's max in-degree D
# turns the aggregation into D chained row gathers — no scatter, no prefix
# sum, read amplification N*D/E (~2.3x at radius-graph degree spread), and
# EXACT arithmetic (a plain <=D-term sum per node, same accuracy class as
# the scatter path — unlike the cumsum lowering's prefix cancellation).
# D comes from GraphBatch.max_in_degree (static; pad_graphs computes it).
# --------------------------------------------------------------------------

def _ell_sum_impl(data, segment_ids, num_segments, max_in_degree,
                  degree_chunk: int = 8):
    """Chunked over the degree axis: each chunk is ONE [N, K, F] gather +
    masked reduce (K = degree_chunk), bounding both the HLO count (D/K ops
    per aggregation instead of D) and the gathered intermediate (N*K*F)."""
    E = data.shape[0]
    starts, ends = _cs_bounds(segment_ids, num_segments)
    tail = (1,) * (data.ndim - 1)
    out = jnp.zeros((num_segments,) + data.shape[1:], jnp.float32)
    for d0 in range(0, max_in_degree, degree_chunk):
        k = min(degree_chunk, max_in_degree - d0)
        idx = starts[:, None] + jnp.arange(d0, d0 + k)          # [N, K]
        valid = (idx < ends[:, None]).reshape((-1, k) + tail)
        blk = jnp.take(data, jnp.minimum(idx, E - 1).reshape(-1), axis=0)
        blk = blk.reshape((num_segments, k) + data.shape[1:]).astype(jnp.float32)
        out = out + jnp.where(valid, blk, 0.0).sum(axis=1)
    return out.astype(data.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def sorted_segment_sum_ell(data, segment_ids, num_segments, max_in_degree):
    """Segment sum for ASCENDING ids via fixed-degree gathers. Rows to
    exclude must be zeroed by the caller (as with the cumsum lowering);
    ``max_in_degree`` must cover every segment's REAL row count — trailing
    same-id padding rows may overflow it only if their data is zeroed."""
    return _ell_sum_impl(data, segment_ids, num_segments, max_in_degree)


def _ell_sum_fwd(data, segment_ids, num_segments, max_in_degree):
    return _ell_sum_impl(data, segment_ids, num_segments, max_in_degree), segment_ids


def _ell_sum_bwd(num_segments, max_in_degree, segment_ids, g):
    return jnp.take(g, segment_ids, axis=0), None


sorted_segment_sum_ell.defvjp(_ell_sum_fwd, _ell_sum_bwd)


def segment_sum_ell(data, segment_ids, num_segments, max_in_degree, mask=None):
    if mask is not None:
        m = mask.astype(data.dtype).reshape(mask.shape + (1,) * (data.ndim - 1))
        data = data * m
    return sorted_segment_sum_ell(data, segment_ids, num_segments, max_in_degree)


def segment_mean_ell(data, segment_ids, num_segments, max_in_degree, mask=None):
    """Mean via one packed ELL pass (see :func:`_packed_mean`)."""
    return _packed_mean(
        lambda d, i, n: sorted_segment_sum_ell(d, i, n, max_in_degree),
        data, segment_ids, num_segments, mask)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def gather_rows_ell(h, rows_sorted, max_in_degree):
    """``h[rows_sorted]`` whose backward is the ELL segment sum."""
    return jnp.take(h, rows_sorted, axis=0)


def _gre_fwd(h, rows_sorted, max_in_degree):
    return jnp.take(h, rows_sorted, axis=0), (rows_sorted, h.shape[0])


def _gre_bwd(max_in_degree, res, g):
    rows_sorted, n = res
    return _ell_sum_impl(g, rows_sorted, n, max_in_degree), None


gather_rows_ell.defvjp(_gre_fwd, _gre_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(5,))
def paired_gather_cols_ell(h, cols, pair, rows_sorted, edge_mask, max_in_degree):
    """``h[cols]`` whose backward rides the reverse-edge involution + ELL
    segment sum (see :func:`paired_gather_cols_cs`)."""
    del pair, rows_sorted, edge_mask
    return jnp.take(h, cols, axis=0)


def _pge_fwd(h, cols, pair, rows_sorted, edge_mask, max_in_degree):
    return jnp.take(h, cols, axis=0), (pair, rows_sorted, edge_mask, h.shape[0])


def _pge_bwd(max_in_degree, res, g):
    return (_paired_bwd(
        lambda d, i, n: _ell_sum_impl(d, i, n, max_in_degree), res, g),
        None, None, None, None)


paired_gather_cols_ell.defvjp(_pge_fwd, _pge_bwd)


@jax.custom_vjp
def gather_rows_cs(h, rows_sorted):
    """``h[rows_sorted]`` whose BACKWARD is the cumsum segment sum instead of
    the transpose-of-gather scatter (ids ascending, so the pull-back
    ``sum_e g[e] -> node rows[e]`` is exactly :func:`sorted_segment_sum_cs`).
    Padding rows may point at any node slot; as with the plain gather, their
    cotangent lands on that slot — callers zero masked cotangents upstream
    (identical semantics to ``jnp.take``'s transpose)."""
    return jnp.take(h, rows_sorted, axis=0)


def _gr_fwd(h, rows_sorted):
    return jnp.take(h, rows_sorted, axis=0), (rows_sorted, h.shape[0])


def _gr_bwd(res, g):
    rows_sorted, n = res
    return _cs_sum_impl(g, rows_sorted, n), None


gather_rows_cs.defvjp(_gr_fwd, _gr_bwd)


@jax.custom_vjp
def paired_gather_cols_cs(h, cols, pair, rows_sorted, edge_mask):
    """``h[cols]`` for a symmetric edge list whose BACKWARD rides the sorted
    row axis: the transpose of the col-incidence is the reverse-edge
    permutation ``pair`` (ops/blocked.pairing_perm), so
    grad_h = sorted_segment_sum(g[pair] * mask, rows). Scatter-free in both
    directions."""
    del pair, rows_sorted, edge_mask
    return jnp.take(h, cols, axis=0)


def _pgc_fwd(h, cols, pair, rows_sorted, edge_mask):
    return jnp.take(h, cols, axis=0), (pair, rows_sorted, edge_mask, h.shape[0])


def _paired_bwd(sum_impl, res, g):
    """Shared backward of the paired col gathers: pull the cotangent through
    the reverse-edge involution, mask padding, then sorted segment sum."""
    pair, rows_sorted, edge_mask, n = res
    gp = jnp.take(g, pair, axis=0)
    m = edge_mask.astype(gp.dtype).reshape(edge_mask.shape + (1,) * (gp.ndim - 1))
    return sum_impl(gp * m, rows_sorted, n)


def _pgc_bwd(res, g):
    return (_paired_bwd(_cs_sum_impl, res, g), None, None, None, None)


paired_gather_cols_cs.defvjp(_pgc_fwd, _pgc_bwd)


def masked_sum(data, mask, axis):
    """Sum over ``axis`` counting only mask==1 elements. mask broadcasts from the left."""
    m = mask.astype(data.dtype).reshape(mask.shape + (1,) * (data.ndim - mask.ndim))
    return jnp.sum(data * m, axis=axis)


def masked_mean(data, mask, axis, eps_count: float = 1.0):
    """Mean over ``axis`` counting only mask==1 elements (count clamped >= eps_count)."""
    m = mask.astype(data.dtype).reshape(mask.shape + (1,) * (data.ndim - mask.ndim))
    total = jnp.sum(data * m, axis=axis)
    count = jnp.sum(m, axis=axis)
    return total / jnp.maximum(count, eps_count)
