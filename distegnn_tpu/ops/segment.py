"""Masked segment ops — the TPU replacement for torch_scatter / scatter_add_.

The reference implements message aggregation with CUDA scatter kernels
(reference models/FastEGNN.py:322-337, unsorted_segment_{sum,mean} via
``scatter_add_`` with ``count.clamp(min=1)``). On TPU we use XLA's native
scatter-add (``jnp.zeros(...).at[ids].add(data)``), which lowers to an
efficient sorted-segment reduction, and carry explicit edge/node masks so all
shapes stay static under jit.

All functions are single-graph (leading axis = elements); batch them with
``jax.vmap`` — the model code does exactly that.
"""

from __future__ import annotations

import jax.numpy as jnp


def segment_sum(data, segment_ids, num_segments, mask=None, indices_are_sorted=False):
    """Sum ``data`` rows into ``num_segments`` buckets.

    data: [E, ...]; segment_ids: [E] int; mask: optional [E] (0/1 or bool).
    Returns [num_segments, ...]. Masked-out rows contribute nothing (they may
    carry arbitrary ids, e.g. padding pointing at segment 0).

    ``indices_are_sorted=True`` (pad_graphs emits row-sorted edge lists —
    GraphBatch.edges_sorted) lets XLA use its sorted-scatter lowering.
    """
    if mask is not None:
        m = mask.astype(data.dtype).reshape(mask.shape + (1,) * (data.ndim - 1))
        data = data * m
    out_shape = (num_segments,) + data.shape[1:]
    return jnp.zeros(out_shape, dtype=data.dtype).at[segment_ids].add(
        data, indices_are_sorted=indices_are_sorted)


def segment_mean(data, segment_ids, num_segments, mask=None, indices_are_sorted=False):
    """Mean of ``data`` rows per segment; empty segments yield 0.

    Parity: reference clamps counts to >=1 (models/FastEGNN.py:337) — same
    behavior here via ``maximum(count, 1)``.
    """
    total = segment_sum(data, segment_ids, num_segments, mask=mask,
                        indices_are_sorted=indices_are_sorted)
    # counts accumulate in f32 regardless of data dtype: a bf16 accumulator
    # saturates at 256 (ulp 2), silently inflating means of degree>=256 nodes
    if mask is None:
        ones = jnp.ones(data.shape[:1], dtype=jnp.float32)
    else:
        ones = mask.astype(jnp.float32)
    count = jnp.zeros((num_segments,), dtype=jnp.float32).at[segment_ids].add(
        ones, indices_are_sorted=indices_are_sorted)
    count = jnp.maximum(count, 1.0).astype(data.dtype)
    return total / count.reshape((num_segments,) + (1,) * (data.ndim - 1))


def segment_max(data, segment_ids, num_segments, mask=None, initial=-1e30):
    """Per-segment max; empty segments yield ``initial``. Masked rows are
    replaced by ``initial`` before the scatter so they never win."""
    if mask is not None:
        m = mask.reshape(mask.shape + (1,) * (data.ndim - 1)).astype(bool)
        data = jnp.where(m, data, initial)
    out_shape = (num_segments,) + data.shape[1:]
    return jnp.full(out_shape, initial, dtype=data.dtype).at[segment_ids].max(data)


def segment_softmax(scores, segment_ids, num_segments, mask=None):
    """Numerically-stable softmax over rows sharing a segment id (the TPU
    replacement for DGL's edge_softmax, reference modules.py:542). Masked rows
    get weight 0; segments with no rows produce all-zero weights."""
    if mask is not None:
        # mask BEFORE the exp: a masked row's raw score may exceed its
        # segment's real max, and exp(large) * 0 would be NaN
        m = mask.reshape(mask.shape + (1,) * (scores.ndim - 1)).astype(bool)
        scores = jnp.where(m, scores, -1e30)
    mx = segment_max(scores, segment_ids, num_segments)
    shifted = jnp.maximum(scores - mx[segment_ids], -80.0)
    e = jnp.where(scores > -1e29, jnp.exp(shifted), 0.0)
    denom = segment_sum(e, segment_ids, num_segments)
    return e / jnp.maximum(denom[segment_ids], 1e-30)


def masked_sum(data, mask, axis):
    """Sum over ``axis`` counting only mask==1 elements. mask broadcasts from the left."""
    m = mask.astype(data.dtype).reshape(mask.shape + (1,) * (data.ndim - mask.ndim))
    return jnp.sum(data * m, axis=axis)


def masked_mean(data, mask, axis, eps_count: float = 1.0):
    """Mean over ``axis`` counting only mask==1 elements (count clamped >= eps_count)."""
    m = mask.astype(data.dtype).reshape(mask.shape + (1,) * (data.ndim - mask.ndim))
    total = jnp.sum(data * m, axis=axis)
    count = jnp.sum(m, axis=axis)
    return total / jnp.maximum(count, eps_count)
