"""One-pass prefix sum along the leading axis (TPU Pallas, XLA fallback).

The cumsum segment lowering (ops/segment.py) stands or falls with the cost
of the prefix sum itself: XLA lowers a length-E cumsum into O(log E) shifted
adds — ~21 full-array passes at LargeFluid scale (E=1.6M), which can burn
more HBM traffic than the scatter it replaces. A sequential Pallas kernel
does it in ONE pass: the TPU grid executes in order, so a [1, F] VMEM
scratch carries the running total from tile to tile (read data once, write
prefix once). This is the *right* shape of Pallas kernel for this chip —
long streaming reduction — unlike the tiny-dot one-hot kernels that
hardware measurement refuted (docs/PERFORMANCE.md).

`prefix_sum(x)` always returns float32 prefix sums (accumulation precision —
see the segment lowering's accuracy note). `impl='auto'` (default) picks the
Pallas kernel on TPU for long axes and XLA elsewhere; the env var
``DISTEGNN_PREFIX_IMPL=xla|pallas`` overrides it for A/B measurement
(scripts/microbench_segsum.py).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_TILE = 4096          # rows per grid step: [4096, 64] f32 = 1 MiB VMEM block
_MIN_PALLAS_ROWS = 32768  # below this the dispatch isn't worth it


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _prefix_kernel(x_ref, out_ref, carry_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        carry_ref[...] = jnp.zeros_like(carry_ref)

    # In-tile inclusive scan by log-step doubling: the Pallas TPU lowering
    # has no cumsum primitive (hardware-discovered 2026-08-02: "Unimplemented
    # primitive ... KernelType.TC: cumsum"), so build it from roll + masked
    # add — log2(tile) VPU passes over a VMEM-resident block, preserving the
    # kernel's one-HBM-pass contract.
    x = x_ref[...].astype(jnp.float32)
    rows = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    k = 1
    while k < x.shape[0]:
        shifted = pltpu.roll(x, k, axis=0)
        x = x + jnp.where(rows >= k, shifted, 0.0)
        k *= 2
    c = x + carry_ref[...]
    out_ref[...] = c
    carry_ref[...] = c[-1:]


def _suffix_kernel(x_ref, out_ref, carry_ref):
    # mirror of _prefix_kernel running the grid REVERSED (index_map maps
    # step i to tile n_tiles-1-i): in-tile suffix by doubling with upward
    # rolls; the carry flows from the last tile backwards. One HBM
    # read/write per element — no flip passes.
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        carry_ref[...] = jnp.zeros_like(carry_ref)

    x = x_ref[...].astype(jnp.float32)
    rows = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    T = x.shape[0]
    k = 1
    while k < T:
        # upward roll by k == pltpu.roll by T-k (pltpu.roll rejects negative
        # shifts); the rows < T-k mask zeroes the wrapped-around rows either way
        shifted = pltpu.roll(x, T - k, axis=0)
        x = x + jnp.where(rows < T - k, shifted, 0.0)
        k *= 2
    c = x + carry_ref[...]
    out_ref[...] = c
    carry_ref[...] = c[:1]


@functools.partial(jax.jit, static_argnames=("tile", "reverse"))
def _prefix_pallas(x, tile: int = _TILE, reverse: bool = False):
    """Inclusive prefix sum along axis 0; ``reverse=True`` gives the inclusive
    SUFFIX sum (out[i] = sum_{j>=i} x[j]) in the same single pass."""
    E, F = x.shape
    n_tiles = -(-E // tile)
    pad = n_tiles * tile - E
    if pad:
        # zero padding is neutral for both directions (suffix pads at the
        # tail, which contributes 0 to every real row's suffix)
        x = jnp.concatenate([x, jnp.zeros((pad, F), x.dtype)], axis=0)
    if reverse:
        kernel, index_map = _suffix_kernel, lambda i: (n_tiles - 1 - i, 0)
    else:
        kernel, index_map = _prefix_kernel, lambda i: (i, 0)
    out = pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=[pl.BlockSpec((tile, F), index_map,
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((tile, F), index_map,
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_tiles * tile, F), jnp.float32),
        scratch_shapes=[pltpu.VMEM((1, F), jnp.float32)],
        interpret=_use_interpret(),
    )(x)
    return out[:E] if pad else out


@jax.custom_vjp
def _prefix_pallas_diff(x):
    return _prefix_pallas(x)


def _prefix_pallas_fwd(x):
    # residual: zero-size token carrying the primal dtype (a bare np.dtype is
    # not a JAX type, and the cotangent must match the primal's dtype)
    return _prefix_pallas(x), jnp.zeros((0,), x.dtype)


def _prefix_pallas_bwd(token, g):
    # out_i = sum_{j<=i} x_j  =>  d/dx_j = sum_{i>=j} g_i: the cotangent is
    # the SUFFIX sum of g — the same one-pass kernel with a reversed grid
    # (no flip passes; each flip would be a full extra HBM read+write at
    # [1.6M, 64] scale). The pallas_call itself has no JVP rule (hardware
    # run 2026-08-02: AssertionError in _pallas_call_jvp_rule), so these
    # custom rules are what make ``prefix_sum`` differentiable at all on the
    # pallas path. prefix and suffix are each other's VJPs, so the mutual
    # recursion supports arbitrary differentiation order.
    return (_suffix_pallas_diff(g).astype(token.dtype),)


@jax.custom_vjp
def _suffix_pallas_diff(x):
    return _prefix_pallas(x, reverse=True)


def _suffix_pallas_fwd(x):
    return _prefix_pallas(x, reverse=True), jnp.zeros((0,), x.dtype)


def _suffix_pallas_bwd(token, g):
    return (_prefix_pallas_diff(g).astype(token.dtype),)


_prefix_pallas_diff.defvjp(_prefix_pallas_fwd, _prefix_pallas_bwd)
_suffix_pallas_diff.defvjp(_suffix_pallas_fwd, _suffix_pallas_bwd)


def prefix_sum(x: jnp.ndarray, impl: str = "auto") -> jnp.ndarray:
    """float32 cumulative sum of ``x`` [E, F] along axis 0."""
    impl = os.environ.get("DISTEGNN_PREFIX_IMPL", impl)
    if impl == "auto":
        impl = ("pallas" if jax.default_backend() == "tpu"
                and x.shape[0] >= _MIN_PALLAS_ROWS else "xla")
    if impl == "pallas":
        return _prefix_pallas_diff(x)
    if impl == "xla":
        return jnp.cumsum(x.astype(jnp.float32), axis=0)
    raise ValueError(f"unknown prefix_sum impl {impl!r}")
