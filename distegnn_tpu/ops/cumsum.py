"""One-pass prefix sum along the leading axis (TPU Pallas, XLA fallback).

The cumsum segment lowering (ops/segment.py) stands or falls with the cost
of the prefix sum itself: XLA lowers a length-E cumsum into O(log E) shifted
adds — ~21 full-array passes at LargeFluid scale (E=1.6M), which can burn
more HBM traffic than the scatter it replaces. A sequential Pallas kernel
does it in ONE pass: the TPU grid executes in order, so a [1, F] VMEM
scratch carries the running total from tile to tile (read data once, write
prefix once). This is the *right* shape of Pallas kernel for this chip —
long streaming reduction — unlike the tiny-dot one-hot kernels that
hardware measurement refuted (docs/PERFORMANCE.md).

`prefix_sum(x)` always returns float32 prefix sums (accumulation precision —
see the segment lowering's accuracy note). `impl='auto'` (default) picks the
Pallas kernel on TPU for long axes and XLA elsewhere; the env var
``DISTEGNN_PREFIX_IMPL=xla|pallas`` overrides it for A/B measurement
(scripts/microbench_segsum.py).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_TILE = 4096          # rows per grid step: [4096, 64] f32 = 1 MiB VMEM block
_MIN_PALLAS_ROWS = 32768  # below this the dispatch isn't worth it


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _prefix_kernel(x_ref, out_ref, carry_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        carry_ref[...] = jnp.zeros_like(carry_ref)

    c = jnp.cumsum(x_ref[...].astype(jnp.float32), axis=0) + carry_ref[...]
    out_ref[...] = c
    carry_ref[...] = c[-1:]


@functools.partial(jax.jit, static_argnames=("tile",))
def _prefix_pallas(x, tile: int = _TILE):
    E, F = x.shape
    n_tiles = -(-E // tile)
    pad = n_tiles * tile - E
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, F), x.dtype)], axis=0)
    out = pl.pallas_call(
        _prefix_kernel,
        grid=(n_tiles,),
        in_specs=[pl.BlockSpec((tile, F), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((tile, F), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_tiles * tile, F), jnp.float32),
        scratch_shapes=[pltpu.VMEM((1, F), jnp.float32)],
        interpret=_use_interpret(),
    )(x)
    return out[:E] if pad else out


def prefix_sum(x: jnp.ndarray, impl: str = "auto") -> jnp.ndarray:
    """float32 cumulative sum of ``x`` [E, F] along axis 0."""
    impl = os.environ.get("DISTEGNN_PREFIX_IMPL", impl)
    if impl == "auto":
        impl = ("pallas" if jax.default_backend() == "tpu"
                and x.shape[0] >= _MIN_PALLAS_ROWS else "xla")
    if impl == "pallas":
        return _prefix_pallas(x)
    if impl == "xla":
        return jnp.cumsum(x.astype(jnp.float32), axis=0)
    raise ValueError(f"unknown prefix_sum impl {impl!r}")
