"""Neighbor search & edge sparsification (host-side, preprocessing-time).

Replaces torch_cluster's CUDA ``radius_graph`` (used by the reference at
datasets/process_dataset.py:101,264 and datasets/distribute_graphs.py:43,65,79)
with a numpy cell-list (grid-bucket) search. Like the reference, graphs are
built ONCE at preprocessing time and cached; training epochs never rebuild
edges, so host numpy is the right tool (a Pallas on-device variant can serve
future on-device rollouts).

Conventions match the reference's consumers: directed edge (row, col) carries a
message TO node ``row`` FROM node ``col`` (aggregation over ``row``,
reference models/FastEGNN.py:171-173); radius graphs emit both directions.
"""

from __future__ import annotations

import numpy as np


def full_graph_np(n: int) -> np.ndarray:
    """All ordered pairs i != j — the reference's radius=-1 n-body graph
    (N=100 -> E=9900, dataset_generation/README.md:10-11)."""
    row, col = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    mask = row != col
    return np.stack([row[mask], col[mask]]).astype(np.int64)


def radius_graph_np(pos: np.ndarray, r: float, loop: bool = False) -> np.ndarray:
    """Edges (i, j) for all pairs with ||pos_i - pos_j|| < r, via a uniform grid.

    pos: [n, 3] float. Returns edge_index [2, E] int64, both directions included,
    ordered by (i, j). O(n * avg_neighbors) instead of O(n^2).
    """
    pos = np.asarray(pos, dtype=np.float64)
    n = pos.shape[0]
    if n == 0:
        return np.zeros((2, 0), np.int64)
    if r <= 0:
        return full_graph_np(n)

    cell = np.floor(pos / r).astype(np.int64)
    cell -= cell.min(axis=0)
    dims = cell.max(axis=0) + 1
    key = (cell[:, 0] * dims[1] + cell[:, 1]) * dims[2] + cell[:, 2]
    order = np.argsort(key, kind="stable")
    key_sorted = key[order]
    # cell id -> contiguous range in `order`
    uniq, starts = np.unique(key_sorted, return_index=True)
    ends = np.append(starts[1:], n)
    cell_lookup = {k: (s, e) for k, s, e in zip(uniq.tolist(), starts.tolist(), ends.tolist())}

    offsets = [(a, b, c) for a in (-1, 0, 1) for b in (-1, 0, 1) for c in (-1, 0, 1)]
    rows, cols = [], []
    r2 = r * r
    # one iteration per OCCUPIED CELL (not per node): gather the 27-cell
    # candidate set once, then a vectorized [members x candidates] distance check
    for k, s, e in zip(uniq.tolist(), starts.tolist(), ends.tolist()):
        members = order[s:e]
        kz = k % dims[2]
        ky = (k // dims[2]) % dims[1]
        kx = k // (dims[1] * dims[2])
        cand = []
        for ox, oy, oz in offsets:
            cx, cy, cz = kx + ox, ky + oy, kz + oz
            if not (0 <= cx < dims[0] and 0 <= cy < dims[1] and 0 <= cz < dims[2]):
                continue
            rng = cell_lookup.get((cx * dims[1] + cy) * dims[2] + cz)
            if rng is not None:
                cand.append(order[rng[0]:rng[1]])
        cand = np.concatenate(cand)
        d2 = np.sum((pos[members][:, None, :] - pos[cand][None, :, :]) ** 2, axis=-1)
        hit = d2 < r2
        if not loop:
            hit &= members[:, None] != cand[None, :]
        mi, ci = np.nonzero(hit)
        if mi.size:
            rows.append(members[mi])
            cols.append(cand[ci])
    if not rows:
        return np.zeros((2, 0), np.int64)
    row = np.concatenate(rows)
    col = np.concatenate(cols)
    perm = np.lexsort((col, row))
    return np.stack([row[perm], col[perm]])


def cutoff_edges_np(edge_index: np.ndarray, pos: np.ndarray, cutoff_rate: float):
    """Drop the longest ``cutoff_rate`` fraction of edges (FastEGNN's edge
    sparsification, reference datasets/process_dataset.py:300-305: sort by
    length, keep the shortest (1-rate) fraction)."""
    if cutoff_rate <= 0 or edge_index.shape[1] == 0:
        return edge_index
    d = np.linalg.norm(pos[edge_index[0]] - pos[edge_index[1]], axis=1)
    # int() truncation, matching the reference's `int(E * (1-rate))` exactly
    keep = int(edge_index.shape[1] * (1.0 - cutoff_rate))
    idx = np.argsort(d, kind="stable")[:keep]
    idx.sort()
    return edge_index[:, idx]
