"""Fused edge-pipeline kernel — one Pallas pass per EGCL layer over the edges.

The plain lowering of an EGCL layer round-trips HBM 4-6 times per layer at
[E, H] width: gather(hr), gather(hc), phi_e intermediates, trans, then the
aggregation read (docs/PERFORMANCE.md "Where the time goes" — the step is
memory-bound at ~1% MFU while the MXU idles). This kernel streams the sorted
edge array ONCE and keeps everything else in VMEM:

  per edge tile (node block b, tile j):
    gather x/hr/hc from a 3-block VMEM node window   (tpu.dynamic_gather)
    cd = x[row] - x[col]; radial = |cd|^2            (VPU, f32)
    phi_e: two H x H matmuls + silu                  (MXU, bf16)
    phi_x: CoordMLP -> per-edge scalar g             (MXU + VPU)
    trans = cd * g                                   (f32)
    segment-sum into the block accumulator           (one-hot MXU dot,
                                                      2-term bf16 split)

HBM traffic per layer: the edge int/scalar stream + 4x node-window re-reads
+ one [N, H+8] accumulator — ~10x less than the plain path's edge-wide
intermediates. FLOP price: the one-hot aggregation adds ~2*T*F bf16 MXU
work per edge — the cost of having no scatter unit (the reference leans on
CUDA scatter_add_ instead, models/FastEGNN.py:322-337).

Locality contract: node ids are Morton-ordered (ops/order.py) and edges are
the blocked layout (ops/graph.py pad_graphs(edge_block=NB)): edge slice
[b*epb, (b+1)*epb) holds the edges whose receiver row lies in node block b,
row-sorted. The VMEM window covers node blocks {s_b, s_b+1, s_b+2} with
s_b = clip(b-1, 0, nb-3). Measured at Fluid113K density (2026-08-02,
N=113140 Morton-ordered): a 3x2048 window captures ~92% of edges, 3x4096
~95.5%. Out-of-window edges are masked here and routed through the compact
`remote` plain-path arrays built by `split_remote_edges` (ordinary EdgeOps
work at ~5-8% of E).

Gather constraint: the Mosaic lowering of `jnp.take_along_axis(x, i, 0)`
(tpu.dynamic_gather) requires source, indices and output to share one 2-D
shape — so the edge tile T equals the node block NB and a 3-block window
costs 3 gathers + selects. One-hot tiles are chunked (OH_CHUNK) to bound
VMEM: a full [T, T] bf16 one-hot at T=2048 would be 8 MiB.

Numerics: geometry (x, cd, radial, trans) is f32; MLP compute is bf16 when
dtype='bf16' (the flagship compute_dtype); accumulation is ALWAYS f32 via
preferred_element_type — the f32 trans stream is split into two exact bf16
terms (hi+lo carries ~16 mantissa bits, strictly tighter than the
measured-acceptable agg_dtype='bf16' single-term stream).

Differentiation: `fused_edge_layer` is a custom_vjp. The backward is a
second Pallas kernel on the same grid that RECOMPUTES the per-edge forward
from the same VMEM windows (remat at tile scale — no edge-wide residual is
ever saved), then emits: block-local row-side grads, 3-slot window PARTIALS
for the col-side grads (combined by a tiny XLA block shift-add outside —
writing directly to neighbor blocks would race across grid steps), and
weight grads accumulated in constant-index output blocks across the grid.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK = 2048   # node block NB == edge tile T (gather shape contract)
OH_CHUNK = 512         # one-hot aggregation chunk (VMEM bound)
XL = 8                 # x lane padding: [N, 3] f32 stored as [N, 8]


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


class EdgeWeights(NamedTuple):
    """phi_e (hoisted first Dense, scalar part) + phi_x params, all f32.

    Row-vector convention: biases and the phi_x head are [1, H] so every
    in-kernel tensor is 2-D (TPU vregs are 2-D; 1-D values complicate the
    Mosaic layout for no gain).
    """

    ws: jnp.ndarray   # [S, H] scalar part of hoisted Dense (S = 1 + attr_nf)
    b1: jnp.ndarray   # [1, H]
    w2: jnp.ndarray   # [H, H] phi_e second Dense
    b2: jnp.ndarray   # [1, H]
    w3: jnp.ndarray   # [H, H] phi_x hidden Dense
    b3: jnp.ndarray   # [1, H]
    w4: jnp.ndarray   # [1, H] phi_x head (no bias, xavier gain 1e-3)


def _silu(x):
    return x * jax.nn.sigmoid(x)


def _dsilu(x):
    s = jax.nn.sigmoid(x)
    return s * (1.0 + x * (1.0 - s))


def _split2(x):
    """2-term bf16 split of f32 (hi+lo ~= 16 mantissa bits)."""
    hi = x.astype(jnp.bfloat16)
    lo = (x - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


# ---------------------------------------------------------------- layout

def _validate_block(block: int) -> None:
    """The one-hot aggregation is chunked at OH_CHUNK: a block smaller than
    one chunk would run ZERO chunks (all-zero aggregates) and a non-multiple
    would silently drop the tail edges of every tile — fail loudly instead."""
    if block < OH_CHUNK or block % OH_CHUNK:
        raise ValueError(
            f"edge_pipeline requires block to be a multiple of OH_CHUNK="
            f"{OH_CHUNK} and >= {OH_CHUNK} (got block={block}): the chunked "
            f"one-hot aggregation would drop edges otherwise")


def build_edge_blocks(row, col, edge_attr, edge_mask, *, block, n_nodes):
    """Blocked-layout [E] edge arrays -> the kernel's flat HBM layout.

    With T = block, nb = n_nodes/T, epb = E/nb, nt = epb/T tiles per block:
      row_t [nb*nt, T] int32 — block-LOCAL rows; masked slots carry T
                               (matches no one-hot lane)
      col_l [E, 1]     int32 — window-block-local col in [0, T)
      kblk  [E, 1]     int32 — which window slot (0..2) the col falls in
      scal  [E, XL]    f32   — [edge_attr[0:2], active-mask, 0, ...]
    Edges with cols outside the 3-block window are masked out (they belong
    to the remote path, `split_remote_edges`).
    """
    _validate_block(block)
    nb = n_nodes // block
    E = row.shape[0]
    epb = E // nb
    T = block
    if n_nodes % block or E % nb or epb % T:
        raise ValueError(f"layout mismatch: N={n_nodes} E={E} block={block}")
    nt = epb // T

    b_of_edge = jnp.arange(E, dtype=jnp.int32) // epb
    s = jnp.clip(b_of_edge - 1, 0, max(nb - 3, 0))
    row_local = row.astype(jnp.int32) - b_of_edge * T
    col_win = col.astype(jnp.int32) - s * T
    in_win = (col_win >= 0) & (col_win < 3 * T)
    mask = (edge_mask > 0) & in_win
    row_t = jnp.where(mask, row_local, T).reshape(nb * nt, T)
    col_win = jnp.clip(col_win, 0, 3 * T - 1)
    kblk = col_win // T
    col_l = col_win - kblk * T

    ea = edge_attr.astype(jnp.float32)
    scal = jnp.concatenate(
        [ea[:, :2], mask[:, None].astype(jnp.float32),
         jnp.zeros((E, XL - 3), jnp.float32)], axis=1)
    return row_t, col_l[:, None], kblk[:, None], scal


def _remote_sel(edge_index: np.ndarray, block: int, n_nodes: int) -> np.ndarray:
    """Boolean [e] mask of edges OUTSIDE the 3-block VMEM window — the single
    definition of the remote classification (mirrors build_edge_blocks)."""
    if n_nodes % block:
        raise ValueError(f"n_nodes={n_nodes} not a multiple of block={block}")
    row, col = edge_index[0], edge_index[1]
    br, bc = row // block, col // block
    nb = n_nodes // block
    s = np.clip(br - 1, 0, max(nb - 3, 0))
    return (bc < s) | (bc > s + 2)


def count_remote_edges(edge_index: np.ndarray, *, block: int,
                       n_nodes: int) -> int:
    """Number of out-of-window edges (loader scans use this to pick a
    dataset-stable remote pad without materializing the split)."""
    return int(_remote_sel(np.asarray(edge_index), block, n_nodes).sum())


def split_remote_edges(edge_index: np.ndarray, edge_attr: np.ndarray,
                       *, block: int, n_nodes: int,
                       n_pad: Optional[int] = None
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """numpy (loader-side): extract the out-of-window edges into a compact
    row-sorted plain edge list for the XLA remote path.

    ``n_nodes`` is the padded node count of the blocked layout; ``nb`` MUST
    be derived from it exactly as `build_edge_blocks` does (n_nodes // block),
    NOT inferred from the edges — with trailing node blocks that receive no
    edges the two would disagree on the window clamp near the top and an edge
    could be classified in-window by one function and remote by the other
    (double-counted or dropped once both paths are aggregated).

    Returns (remote_edge_index [2, Er], remote_edge_attr [Er, D],
    remote_mask [Er]) padded to ``n_pad`` (default: next multiple of 128).
    Padding points at node 0 with mask 0 — the pad_graphs convention.
    """
    r_idx = remote_selection(edge_index, block=block, n_nodes=n_nodes)
    return pad_remote_list(edge_index[:, r_idx], edge_attr[r_idx],
                           n_pad=n_pad)


def remote_selection(edge_index: np.ndarray, *, block: int,
                     n_nodes: int) -> np.ndarray:
    """Row-sorted indices of the out-of-window edges — the expensive half of
    :func:`split_remote_edges`, split out so the serve session cache can store
    it once per topology and re-gather fresh attrs per request."""
    remote = _remote_sel(edge_index, block, n_nodes)
    row = edge_index[0]
    r_idx = np.where(remote)[0]
    return r_idx[np.argsort(row[r_idx], kind="stable")]


def pad_remote_list(ei_r: np.ndarray, ea_r: np.ndarray,
                    n_pad: Optional[int] = None
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad a compact remote edge list to ``n_pad`` (default next multiple of
    128); padding points at node 0 with mask 0 — the pad_graphs convention."""
    er = ei_r.shape[1]
    if n_pad is None:
        n_pad = max(((er + 127) // 128) * 128, 128)
    if er > n_pad:
        raise ValueError(f"{er} remote edges exceed pad {n_pad}")
    ei = np.zeros((2, n_pad), np.int32)
    ea = np.zeros((n_pad, ea_r.shape[1]), ea_r.dtype)
    m = np.zeros((n_pad,), np.float32)
    ei[:, :er] = ei_r
    ea[:er] = ea_r
    m[:er] = 1.0
    return ei, ea, m


# ---------------------------------------------------------------- kernels

def _gather3(refs, idx_loc, kblk, T, lanes):
    """Select-gather from the 3 window blocks: refs are VMEM refs [T,lanes],
    idx_loc [T, 1] block-local rows, kblk [T, 1] in {0,1,2}."""
    idx = jnp.broadcast_to(idx_loc, (T, lanes))
    out = jnp.zeros((T, lanes), refs[0].dtype)
    for k in range(3):
        g = jnp.take_along_axis(refs[k][...], idx, axis=0)
        out = jnp.where(jnp.broadcast_to(kblk == k, (T, lanes)), g, out)
    return out


def _onehot_agg(seg_row, data):
    """[T, F] tile -> [T, F] f32 block rows: chunked one-hot MXU dots.
    seg_row [1, T] block-local rows (T == masked/no-op)."""
    T, F = data.shape
    out = jnp.zeros((T, F), jnp.float32)
    rows = jax.lax.broadcasted_iota(jnp.int32, (T, OH_CHUNK), 0)
    for c in range(T // OH_CHUNK):
        seg = jax.lax.dynamic_slice(seg_row, (0, c * OH_CHUNK), (1, OH_CHUNK))
        oh = (rows == jnp.broadcast_to(seg, (T, OH_CHUNK))).astype(jnp.bfloat16)
        chunk = jax.lax.dynamic_slice(data, (c * OH_CHUNK, 0), (OH_CHUNK, F))
        out = out + jax.lax.dot_general(
            oh, chunk.astype(jnp.bfloat16), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    return out


def _edge_fwd_math(x_own, x_win, p_own, p_win, row_t, col, kblk, scal,
                   w: EdgeWeights, T, H, dtype):
    """Shared per-tile forward math (the backward recomputes through this).

    Returns the per-edge intermediates needed by both directions."""
    mask = scal[:, 2:3]                                    # [T, 1] f32
    row_c = jnp.minimum(row_t, T - 1).reshape(T, 1)        # clip masked slots
    x_r = jnp.take_along_axis(x_own[...], jnp.broadcast_to(row_c, (T, XL)), 0)
    x_c = _gather3(x_win, col, kblk, T, XL)
    p_r = jnp.take_along_axis(p_own[...], jnp.broadcast_to(row_c, (T, 2 * H)), 0)
    p_c = _gather3(p_win, col, kblk, T, 2 * H)
    hr_e, hc_e = p_r[:, :H], p_c[:, H:]

    cd = (x_r - x_c) * mask                                # [T, XL] f32
    radial = jnp.sum(cd * cd, axis=1, keepdims=True)       # [T, 1] f32
    sfeat = jnp.concatenate([radial, scal[:, 0:2]], axis=1).astype(dtype)
    t1 = ((hr_e + hc_e).astype(dtype) + sfeat @ w.ws.astype(dtype)
          + w.b1.astype(dtype))
    y1 = _silu(t1)
    t2 = y1 @ w.w2.astype(dtype) + w.b2.astype(dtype)
    ef = _silu(t2)                                         # [T, H] edge_feat
    t3 = ef @ w.w3.astype(dtype) + w.b3.astype(dtype)
    y2 = _silu(t3)
    g = jnp.sum(y2.astype(jnp.float32) * w.w4, axis=1, keepdims=True) * mask
    return mask, cd, sfeat, t1, y1, t2, ef, t3, y2, g


def _fwd_kernel(row_t_ref, col_ref, kblk_ref, scal_ref,
                xo_ref, x0_ref, x1_ref, x2_ref,
                po_ref, p0_ref, p1_ref, p2_ref,
                ws_ref, b1_ref, w2_ref, b2_ref, w3_ref, b3_ref, w4_ref,
                out_ref, *, T, H, dtype):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    w = EdgeWeights(ws_ref[...], b1_ref[...], w2_ref[...], b2_ref[...],
                    w3_ref[...], b3_ref[...], w4_ref[...])
    row_t = row_t_ref[...]                                 # [1, T]
    mask, cd, _, _, _, _, ef, _, _, g = _edge_fwd_math(
        xo_ref, (x0_ref, x1_ref, x2_ref), po_ref, (p0_ref, p1_ref, p2_ref),
        row_t, col_ref[...], kblk_ref[...], scal_ref[...], w, T, H, dtype)

    trans = cd[:, 0:3] * g                                 # [T, 3] f32
    hi, lo = _split2(trans)
    data = jnp.concatenate(
        [hi, lo, mask.astype(jnp.bfloat16), jnp.zeros((T, 1), jnp.bfloat16),
         (ef * mask.astype(ef.dtype)).astype(jnp.bfloat16)], axis=1)
    out_ref[...] += _onehot_agg(row_t, data)               # [T, H+8]


def _bwd_kernel(row_t_ref, col_ref, kblk_ref, scal_ref,
                xo_ref, x0_ref, x1_ref, x2_ref,
                po_ref, p0_ref, p1_ref, p2_ref,
                ws_ref, b1_ref, w2_ref, b2_ref, w3_ref, b3_ref, w4_ref,
                gp_ref,
                drow_ref, dcol_ref, dws_ref, db1_ref, dw2_ref, db2_ref,
                dw3_ref, db3_ref, dw4_ref, *, T, H, dtype):
    b, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _():
        drow_ref[...] = jnp.zeros_like(drow_ref)
        dcol_ref[...] = jnp.zeros_like(dcol_ref)

    @pl.when(jnp.logical_and(b == 0, j == 0))
    def _():
        dws_ref[...] = jnp.zeros_like(dws_ref)
        db1_ref[...] = jnp.zeros_like(db1_ref)
        dw2_ref[...] = jnp.zeros_like(dw2_ref)
        db2_ref[...] = jnp.zeros_like(db2_ref)
        dw3_ref[...] = jnp.zeros_like(dw3_ref)
        db3_ref[...] = jnp.zeros_like(db3_ref)
        dw4_ref[...] = jnp.zeros_like(dw4_ref)

    w = EdgeWeights(ws_ref[...], b1_ref[...], w2_ref[...], b2_ref[...],
                    w3_ref[...], b3_ref[...], w4_ref[...])
    row_t = row_t_ref[...]
    col, kblk, scal = col_ref[...], kblk_ref[...], scal_ref[...]
    mask, cd, sfeat, t1, y1, t2, ef, t3, y2, g = _edge_fwd_math(
        xo_ref, (x0_ref, x1_ref, x2_ref), po_ref, (p0_ref, p1_ref, p2_ref),
        row_t, col, kblk, scal, w, T, H, dtype)

    # upstream per-edge grads: gather the own-block packed cotangent by row
    row_c = jnp.minimum(row_t, T - 1).reshape(T, 1)
    gt = jnp.take_along_axis(gp_ref[...], jnp.broadcast_to(row_c, (T, H + 8)), 0)
    d_trans = gt[:, 0:3] * mask                            # [T, 3] f32
    d_ef_up = gt[:, 8:] * mask                             # [T, H] f32

    # trans = cd[:, :3] * g
    d_g = jnp.sum(cd[:, 0:3] * d_trans, axis=1, keepdims=True)   # [T, 1]
    d_cd3 = d_trans * g                                    # [T, 3] f32

    # g = sum(y2 * w4) * mask
    d_y2 = (d_g * w.w4).astype(dtype)                      # [T, H]
    dw4_ref[...] += jnp.sum(y2.astype(jnp.float32) * d_g, axis=0,
                            keepdims=True)
    d_t3 = d_y2 * _dsilu(t3)
    d_ef = d_ef_up.astype(dtype) + jax.lax.dot_general(
        d_t3, w.w3.astype(dtype), (((1,), (1,)), ((), ())))      # @ w3^T
    dw3_ref[...] += jax.lax.dot_general(
        ef, d_t3, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                      # ef^T d_t3
    db3_ref[...] += jnp.sum(d_t3.astype(jnp.float32), axis=0, keepdims=True)

    d_t2 = d_ef * _dsilu(t2)
    d_y1 = jax.lax.dot_general(d_t2, w.w2.astype(dtype),
                               (((1,), (1,)), ((), ())))         # @ w2^T
    dw2_ref[...] += jax.lax.dot_general(
        y1, d_t2, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    db2_ref[...] += jnp.sum(d_t2.astype(jnp.float32), axis=0, keepdims=True)

    d_t1 = d_y1 * _dsilu(t1)                               # [T, H]
    dws_ref[...] += jax.lax.dot_general(
        sfeat, d_t1, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)[0:dws_ref.shape[0]]
    db1_ref[...] += jnp.sum(d_t1.astype(jnp.float32), axis=0, keepdims=True)

    d_sfeat = jax.lax.dot_general(d_t1, w.ws.astype(dtype),
                                  (((1,), (1,)), ((), ())))      # [T, S]
    d_radial = d_sfeat[:, 0:1].astype(jnp.float32) * mask
    # radial = sum(cd^2); cd rows are zero beyond lane 2, so the XL-wide
    # update only populates the real lanes
    d_cd = 2.0 * cd * d_radial
    d_cd = d_cd.at[:, 0:3].add(d_cd3) if hasattr(d_cd, "at") else d_cd
    # (jnp arrays always have .at — kept explicit for interpret clarity)

    # ---- aggregate: row side (own block), col side (3-slot window partials)
    d_t1m = d_t1 * mask.astype(d_t1.dtype)
    hi, lo = _split2(d_cd[:, 0:3])
    row_data = jnp.concatenate(
        [hi, lo, jnp.zeros((T, 2), jnp.bfloat16),
         d_t1m.astype(jnp.bfloat16)], axis=1)              # [T, H+8]
    drow_ref[...] += _onehot_agg(row_t, row_data)

    # col-side per-edge payload: d_hc = d_t1, d_x_col = -d_cd
    chi, clo = _split2(-d_cd[:, 0:3])
    col_data = jnp.concatenate(
        [chi, clo, jnp.zeros((T, 2), jnp.bfloat16),
         d_t1m.astype(jnp.bfloat16)], axis=1)              # [T, H+8]
    # mask out edges NOT in window slot k, then aggregate by col-local row;
    # masked/out-of-slot edges carry col row T via the same no-op trick
    for k in range(3):
        in_k = (kblk == k) & (mask > 0)
        seg = jnp.where(in_k, col, T).reshape(1, T)
        part = _onehot_agg(seg, col_data)
        dcol_ref[:, k * (H + 8):(k + 1) * (H + 8)] += part


# ---------------------------------------------------------------- wrappers

def _common_specs(T, H, nb, nt, wshapes):
    """in_specs shared by both kernels: edge blocks, node windows, weights."""
    def edge(spec_shape):
        return pl.BlockSpec(spec_shape, lambda b, j: (b * nt + j, 0),
                            memory_space=pltpu.VMEM)

    def own(lanes):
        return pl.BlockSpec((T, lanes), lambda b, j: (b, 0),
                            memory_space=pltpu.VMEM)

    def win(k, lanes):
        return pl.BlockSpec(
            (T, lanes),
            lambda b, j, k=k: (jnp.clip(b - 1, 0, max(nb - 3, 0)) + k, 0),
            memory_space=pltpu.VMEM)

    def const(shape):
        return pl.BlockSpec(shape, lambda b, j: (0, 0),
                            memory_space=pltpu.VMEM)

    return ([edge((1, T)), edge((T, 1)), edge((T, 1)), edge((T, XL)),
             own(XL), win(0, XL), win(1, XL), win(2, XL),
             own(2 * H), win(0, 2 * H), win(1, 2 * H), win(2, 2 * H)]
            + [const(s) for s in wshapes])


def _pack_inputs(x, hr, hc, weights, n_nodes, dtype):
    xp = jnp.zeros((n_nodes, XL), jnp.float32).at[:, 0:3].set(x)
    pk = jnp.concatenate([hr, hc], axis=1).astype(dtype)
    wlist = [weights.ws, weights.b1, weights.w2, weights.b2,
             weights.w3, weights.b3, weights.w4]
    return xp, pk, wlist


def _check_grid(n_nodes: int, block: int) -> int:
    """The win(k) BlockSpec index maps address node blocks s..s+2; with
    nb < 3 they would index past the array and rely on unspecified Mosaic
    out-of-bounds block clamping — reject small graphs loudly (route them
    through the plain EdgeOps path instead)."""
    _validate_block(block)
    nb = n_nodes // block
    if nb < 3:
        raise ValueError(
            f"fused_edge_layer needs at least 3 node blocks (n_nodes="
            f"{n_nodes}, block={block} -> nb={nb}): the 3-block VMEM window "
            f"would index out of bounds; use the plain EdgeOps path for "
            f"graphs smaller than {3 * block} padded nodes")
    return nb


def _fused_fwd_impl(x, hr, hc, row_t, col_l, kblk, scal, weights,
                    *, block, dtype_name):
    T = block
    n_nodes, H = hr.shape[0], hr.shape[1]
    nb = _check_grid(n_nodes, T)
    nt = row_t.shape[0] // nb
    dtype = jnp.bfloat16 if dtype_name == "bf16" else jnp.float32
    xp, pk, wlist = _pack_inputs(x, hr, hc, weights, n_nodes, dtype)

    out = pl.pallas_call(
        functools.partial(_fwd_kernel, T=T, H=H, dtype=dtype),
        grid=(nb, nt),
        in_specs=_common_specs(T, H, nb, nt, [w.shape for w in wlist]),
        out_specs=pl.BlockSpec((T, H + 8), lambda b, j: (b, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_nodes, H + 8), jnp.float32),
        interpret=_use_interpret(),
    )(row_t, col_l, kblk, scal, xp, xp, xp, xp, pk, pk, pk, pk, *wlist)
    trans = out[:, 0:3] + out[:, 3:6]       # 2-term bf16 recombine
    count = out[:, 6]
    ef_sum = out[:, 8:]
    return trans, count, ef_sum


def _fused_bwd_impl(x, hr, hc, row_t, col_l, kblk, scal, weights,
                    g_trans, g_ef, *, block, dtype_name):
    T = block
    n_nodes, H = hr.shape[0], hr.shape[1]
    nb = _check_grid(n_nodes, T)
    nt = row_t.shape[0] // nb
    dtype = jnp.bfloat16 if dtype_name == "bf16" else jnp.float32
    xp, pk, wlist = _pack_inputs(x, hr, hc, weights, n_nodes, dtype)
    g_pack = jnp.concatenate(
        [g_trans.astype(jnp.float32),
         jnp.zeros((n_nodes, XL - 3), jnp.float32),
         g_ef.astype(jnp.float32)], axis=1)                # [N, H+8]

    wshapes = [w.shape for w in wlist]
    gp_spec = pl.BlockSpec((T, H + 8), lambda b, j: (b, 0),
                           memory_space=pltpu.VMEM)
    out_specs = (
        pl.BlockSpec((T, H + 8), lambda b, j: (b, 0),
                     memory_space=pltpu.VMEM),              # row-side grads
        pl.BlockSpec((T, 3 * (H + 8)), lambda b, j: (b, 0),
                     memory_space=pltpu.VMEM),              # col window partials
    ) + tuple(pl.BlockSpec(s, lambda b, j: (0, 0), memory_space=pltpu.VMEM)
              for s in wshapes)
    out_shapes = (
        jax.ShapeDtypeStruct((n_nodes, H + 8), jnp.float32),
        jax.ShapeDtypeStruct((n_nodes, 3 * (H + 8)), jnp.float32),
    ) + tuple(jax.ShapeDtypeStruct(s, jnp.float32) for s in wshapes)

    drow, dcol, dws, db1, dw2, db2, dw3, db3, dw4 = pl.pallas_call(
        functools.partial(_bwd_kernel, T=T, H=H, dtype=dtype),
        grid=(nb, nt),
        in_specs=_common_specs(T, H, nb, nt, wshapes) + [gp_spec],
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=_use_interpret(),
    )(row_t, col_l, kblk, scal, xp, xp, xp, xp, pk, pk, pk, pk, *wlist,
      g_pack)

    # row-side: d_x (+cd side) and d_hr live in the own block
    d_x = drow[:, 0:3] + drow[:, 3:6]
    d_hr = drow[:, 8:]
    # col-side: window slot k of block b lands on node block s_b + k
    F = H + 8
    parts = dcol.reshape(nb, T, 3, F)
    s = np.clip(np.arange(nb) - 1, 0, max(nb - 3, 0))
    acc = jnp.zeros((nb, T, F), jnp.float32)
    for k in range(3):
        acc = acc.at[s + k].add(parts[:, :, k, :])
    acc = acc.reshape(n_nodes, F)
    d_x = d_x + acc[:, 0:3] + acc[:, 3:6]
    d_hc = acc[:, 8:]
    d_w = EdgeWeights(dws, db1, dw2, db2, dw3, db3, dw4)
    return d_x, d_hr, d_hc, d_w


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9))
def fused_edge_layer(x, hr, hc, row_t, col_l, kblk, scal, weights,
                     block: int = DEFAULT_BLOCK, dtype_name: str = "bf16"):
    """Fused phi_e + phi_x + row aggregation over the blocked edge arrays.

    Args:
      x    [N, 3] f32 coordinates (Morton-ordered, block-padded)
      hr   [N, H] hoisted row features (h @ W_row, node axis)
      hc   [N, H] hoisted col features
      row_t/col_l/kblk/scal — `build_edge_blocks` output
      weights — EdgeWeights
    Returns (trans_sum [N, 3] f32, count [N] f32, ef_sum [N, H] f32): the
    UN-normalized in-window segment sums; the caller adds the remote-path
    sums and normalizes (coords_agg mean) outside.
    """
    return _fused_fwd_impl(x, hr, hc, row_t, col_l, kblk, scal, weights,
                           block=block, dtype_name=dtype_name)


def _fel_fwd(x, hr, hc, row_t, col_l, kblk, scal, weights, block, dtype_name):
    out = _fused_fwd_impl(x, hr, hc, row_t, col_l, kblk, scal, weights,
                          block=block, dtype_name=dtype_name)
    return out, (x, hr, hc, row_t, col_l, kblk, scal, weights)


def _fel_bwd(block, dtype_name, res, g):
    x, hr, hc, row_t, col_l, kblk, scal, weights = res
    g_trans, _g_count, g_ef = g     # count is data-independent (mask sum)
    d_x, d_hr, d_hc, d_w = _fused_bwd_impl(
        x, hr, hc, row_t, col_l, kblk, scal, weights,
        g_trans, g_ef, block=block, dtype_name=dtype_name)
    zero = lambda a: jnp.zeros_like(a)
    return (d_x, d_hr.astype(hr.dtype), d_hc.astype(hc.dtype),
            zero(row_t), zero(col_l), zero(kblk), zero(scal), d_w)


fused_edge_layer.defvjp(_fel_fwd, _fel_bwd)
