"""GraphBatch — the static-shape batched graph container.

The reference carries ragged PyG ``Data(x, pos, vel, attr, target, loc_mean,
edge_index, edge_attr)`` objects concatenated along a flat node axis with a
``batch`` vector (reference datasets/process_dataset.py:114-115). XLA wants
static shapes, so we use a dense layout instead:

  node arrays  [B, N, ...]   padded to N = bucketed max nodes, with node_mask
  edge arrays  [B, E, ...]   padded edge list (local per-graph indices), with
                             edge_mask; padded edges point at node 0 and are
                             masked out of every aggregation
  graph arrays [B, ...]      e.g. loc_mean

This dense layout is what makes the model MXU-friendly: every MLP runs as one
big [B*N(*C), F] matmul, per-graph reductions are masked means over a fixed N,
and under the distributed mesh the N axis holds one spatial partition per
device (see distegnn_tpu.parallel).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class GraphBatch:
    """A batch of B padded graphs (or, distributed: B padded graph *partitions*).

    Shapes (F=node features, A=node attrs, D=edge attrs):
      node_feat [B, N, F] float   node_mask  [B, N]  float 0/1
      loc       [B, N, 3] float   edge_index [B, 2, E] int32 (row=receiver, col=sender)
      vel       [B, N, 3] float   edge_attr  [B, E, D] float
      target    [B, N, 3] float   edge_mask  [B, E] float 0/1
      node_attr [B, N, A] float (A may be 0)
      loc_mean  [B, 3]    float — GLOBAL mean of node positions per graph
                                  (across all partitions when distributed)

    ``edges_sorted`` (static) — True when every graph's edge rows are
    ascending, including the padded tail (padding points at node N-1, the
    last padded slot). Lets aggregations use XLA's sorted-scatter lowering.

    ``edge_block`` (static) — 0, or the node-block size of a blocked edge
    layout (see ops/blocked.py): N is a multiple of edge_block and edge slice
    [b*epb, (b+1)*epb) holds exactly the edges whose row is in node block b.
    Enables the MXU one-hot aggregation kernels; the layout is still a valid
    row-sorted edge list, so every non-kernel path works unchanged.
    """

    node_feat: jnp.ndarray
    node_attr: jnp.ndarray
    loc: jnp.ndarray
    vel: jnp.ndarray
    target: jnp.ndarray
    loc_mean: jnp.ndarray
    node_mask: jnp.ndarray
    edge_index: jnp.ndarray
    edge_attr: jnp.ndarray
    edge_mask: jnp.ndarray
    # [B, E] reverse-edge involution (symmetric graphs): lets backward
    # col-aggregations ride the MXU kernels (blocked layout, ops/blocked.py)
    # or the scatter-free cumsum path (plain sorted layout, ops/segment.py)
    edge_pair: Optional[jnp.ndarray] = None
    # Compact out-of-window edge list for the fused edge pipeline
    # (ops/edge_pipeline.split_remote_edges): [B, 2, R] int32 / [B, R, D] /
    # [B, R] 0-1. Padding points at node 0 with mask 0. Present only when the
    # batch was built with pad_graphs(split_remote=True); models with
    # edge_impl='fused' route these ~5-8% of edges through the plain EdgeOps
    # path and sum them with the in-window kernel accumulators.
    remote_edge_index: Optional[jnp.ndarray] = None
    remote_edge_attr: Optional[jnp.ndarray] = None
    remote_edge_mask: Optional[jnp.ndarray] = None
    edges_sorted: bool = struct.field(pytree_node=False, default=False)
    edge_block: int = struct.field(pytree_node=False, default=0)
    edge_tile: int = struct.field(pytree_node=False, default=0)
    # max REAL in-degree over the batch, rounded up to 8 (0 = not computed).
    # Static: enables the ELL aggregation lowering (segment_impl='ell',
    # ops/segment.py). Computed together with the plain pairing
    # (compute_pair=True) so scatter-only workflows keep one pytree identity.
    max_in_degree: int = struct.field(pytree_node=False, default=0)

    @property
    def batch_size(self) -> int:
        return self.node_feat.shape[0]

    @property
    def max_nodes(self) -> int:
        return self.node_feat.shape[1]

    @property
    def max_edges(self) -> int:
        return self.edge_index.shape[2]

    @property
    def n_node(self) -> jnp.ndarray:
        """[B] float — true node count per graph (per partition when sharded)."""
        return jnp.sum(self.node_mask, axis=1)

    @property
    def edges_per_block(self) -> int:
        """Edge slots per node block (blocked layout only)."""
        assert self.edge_block > 0, "not a blocked layout"
        return self.max_edges // (self.max_nodes // self.edge_block)

    @property
    def row(self) -> jnp.ndarray:
        return self.edge_index[:, 0, :]

    @property
    def col(self) -> jnp.ndarray:
        return self.edge_index[:, 1, :]


def _round_up(x: int, multiple: int) -> int:
    return ((x + multiple - 1) // multiple) * multiple


def pad_graphs(
    graphs: Sequence[dict],
    max_nodes: Optional[int] = None,
    max_edges: Optional[int] = None,
    node_bucket: int = 8,
    edge_bucket: int = 128,
    dtype=np.float32,
    edge_block: int = 0,
    edges_per_block: Optional[int] = None,
    edge_tile: int = 512,
    compute_pair: Optional[bool] = None,
    max_in_degree: Optional[int] = None,
    split_remote: bool = False,
    remote_pad: Optional[int] = None,
) -> "GraphBatch":
    """Pack a list of per-graph numpy dicts into one padded GraphBatch.

    Each dict has keys: node_feat [n,F], loc/vel/target [n,3], edge_index [2,e],
    edge_attr [e,D], optional node_attr [n,A], optional loc_mean [3].
    Bucketing rounds N/E up so nearby sizes share one compiled program.

    ``edge_block > 0`` emits the blocked layout (ops/blocked.py): N rounds up
    to a multiple of edge_block and each node block owns a fixed slice of
    ``edges_per_block`` edge slots (auto: max block degree over the batch,
    rounded to edge_tile; loaders pass a dataset-stable value to avoid
    per-batch recompiles). Requires row-sorted edge input (all in-tree
    builders emit it; unsorted input is stable-sorted here).

    loc_mean contract: when a dict omits loc_mean, it falls back to the mean of
    the dict's OWN positions — correct only for whole (unpartitioned) graphs.
    Partition pipelines MUST pass the global mean explicitly (the partitioners
    in distegnn_tpu.data do), since GraphBatch.loc_mean seeds the replicated
    virtual-node coordinates across devices.

    ``compute_pair`` — attach the reverse-edge involution (``edge_pair``) so
    backward col-aggregations stay scatter-free. ``None`` (auto) keeps the
    historical layouts: on for blocked batches, off for plain ones (the plain
    pairing only pays off with ``segment_impl='cumsum'``; loaders switch it on
    dataset-stably so every batch shares one pytree structure).

    ``split_remote`` (blocked layouts only) — additionally extract the edges
    whose sender falls OUTSIDE the fused kernel's 3-block VMEM window into the
    compact ``remote_edge_*`` arrays (ops/edge_pipeline.split_remote_edges),
    padded to ``remote_pad`` slots (auto: batch max rounded to 128; loaders
    pass a dataset-stable value so every batch shares one pytree structure).
    Required by models running ``edge_impl='fused'``.
    """
    bsz = len(graphs)
    n_max = max(g["loc"].shape[0] for g in graphs)
    if compute_pair is None:
        compute_pair = edge_block > 0
    if split_remote and not edge_block:
        raise ValueError("pad_graphs: split_remote requires edge_block > 0 "
                         "(the remote/in-window partition is defined by the "
                         "blocked layout)")
    if edge_block:
        from distegnn_tpu.ops.blocked import (max_block_degree,
                                              prepare_blocked_graph)

        if max_nodes is not None and max_nodes < n_max:
            raise ValueError(f"pad_graphs: max_nodes {max_nodes} < actual {n_max}")
        if max_edges is not None:
            raise ValueError("pad_graphs: max_edges is unsupported with "
                             "edge_block; pass edges_per_block instead")
        if edges_per_block is not None and edges_per_block % edge_tile:
            raise ValueError(f"pad_graphs: edges_per_block {edges_per_block} "
                             f"not a multiple of edge_tile {edge_tile}")
        N = _round_up(max(max_nodes or 0, n_max, 1), edge_block)
        if edges_per_block is None:
            deg = max(max_block_degree(np.sort(g["edge_index"][0]), N, edge_block)
                      for g in graphs)
            edges_per_block = _round_up(max(deg, 1), edge_tile)
        graphs = [prepare_blocked_graph(g, N, edges_per_block, edge_block,
                                        compute_pair=compute_pair)
                  for g in graphs]
        pairs = [g["_edge_pair"] for g in graphs]
        # all-or-nothing across the batch: one pytree structure per layout.
        # Loaders make this dataset-stable by scanning up front and passing
        # compute_pair accordingly (scan_dataset_for_blocking).
        edge_pair = (np.stack(pairs).astype(np.int32)
                     if all(p is not None for p in pairs) else None)
        E = (N // edge_block) * edges_per_block
        if split_remote:
            from distegnn_tpu.ops.edge_pipeline import (pad_remote_list,
                                                        split_remote_edges)

            # classify on each graph's REAL blockified edges (padding slots
            # carry row == col inside their own block — always in-window —
            # so filtering by mask only removes never-remote slots)
            outs = []
            for g in graphs:
                sel = g.get("_remote_sel")
                if (sel is not None
                        and g.get("_blockified") == (N, edges_per_block,
                                                     edge_block)):
                    # session-cached selection: the classify+sort was done at
                    # prep time; a gather of the current arrays suffices
                    outs.append(pad_remote_list(
                        g["edge_index"][:, sel], g["edge_attr"][sel],
                        n_pad=remote_pad))
                    continue
                keep = g["_edge_mask"] > 0
                outs.append(split_remote_edges(
                    g["edge_index"][:, keep], g["edge_attr"][keep],
                    block=edge_block, n_nodes=N, n_pad=remote_pad))
            R = max(o[0].shape[1] for o in outs)
            rei = np.zeros((bsz, 2, R), np.int32)
            rea = np.zeros((bsz, R, outs[0][1].shape[1]), dtype)
            rem = np.zeros((bsz, R), dtype)
            for b, (ei_r, ea_r, m_r) in enumerate(outs):
                r = ei_r.shape[1]
                rei[b, :, :r], rea[b, :r], rem[b, :r] = ei_r, ea_r, m_r
            remote = (rei, rea, rem)
        else:
            remote = None
    else:
        remote = None
        e_max = max(g["edge_index"].shape[1] for g in graphs)
        E = max_edges if max_edges is not None else _round_up(max(e_max, 1), edge_bucket)
        N = max_nodes if max_nodes is not None else _round_up(max(n_max, 1), node_bucket)
        if N < n_max or E < e_max:
            raise ValueError(f"pad_graphs: max_nodes/max_edges ({N},{E}) < actual ({n_max},{e_max})")
        edge_pair = None

    F = graphs[0]["node_feat"].shape[1]
    A = graphs[0].get("node_attr", np.zeros((0, 0))).shape[1] if graphs[0].get("node_attr") is not None else 0
    D = graphs[0]["edge_attr"].shape[1] if graphs[0].get("edge_attr") is not None else 0

    node_feat = np.zeros((bsz, N, F), dtype)
    node_attr = np.zeros((bsz, N, A), dtype)
    loc = np.zeros((bsz, N, 3), dtype)
    vel = np.zeros((bsz, N, 3), dtype)
    target = np.zeros((bsz, N, 3), dtype)
    loc_mean = np.zeros((bsz, 3), dtype)
    node_mask = np.zeros((bsz, N), dtype)
    # padded edges point at the LAST padded slot (N-1): it is masked out of
    # every aggregation anyway, and keeps row indices ascending so the model
    # can use XLA's sorted-scatter lowering (all in-tree edge builders emit
    # row-sorted edge lists — radius_graph_np lexsorts, full_graph_np is
    # row-major, cutoff_edges_np preserves order)
    edge_index = np.full((bsz, 2, E), N - 1, np.int32)
    edge_attr = np.zeros((bsz, E, D), dtype)
    edge_mask = np.zeros((bsz, E), dtype)
    edges_sorted = True

    for b, g in enumerate(graphs):
        n = g["loc"].shape[0]
        e = g["edge_index"].shape[1]
        node_feat[b, :n] = g["node_feat"]
        if A:
            node_attr[b, :n] = g["node_attr"]
        loc[b, :n] = g["loc"]
        vel[b, :n] = g["vel"]
        if g.get("target") is not None:
            target[b, :n] = g["target"]
        loc_mean[b] = g["loc_mean"] if g.get("loc_mean") is not None else g["loc"].mean(axis=0)
        node_mask[b, :n] = 1.0
        edge_index[b, :, :e] = g["edge_index"]
        if (not edge_block) and e and (np.any(np.diff(g["edge_index"][0]) < 0)
                                       or g["edge_index"][0][-1] > N - 1):
            edges_sorted = False  # blocked layouts are ascending by construction
        if D and g.get("edge_attr") is not None:
            edge_attr[b, :e] = g["edge_attr"]
        if edge_block:
            edge_mask[b, :e] = g["_edge_mask"]  # blocked layout: interior padding
        else:
            edge_mask[b, :e] = 1.0

    if not ((not edge_block) and compute_pair and edges_sorted):
        max_in_degree = 0
    else:
        # the static D of the ELL lowering (rounded to 8 so nearby batches
        # share a compiled program). Loaders pass a DATASET-stable value,
        # since a static field that varies across batches retraces the jitted
        # step (same concern as edges_per_block for the blocked layout);
        # an undersized value would silently drop edges, so it is validated.
        deg = max(int(np.bincount(g["edge_index"][0], minlength=1).max())
                  for g in graphs)
        if max_in_degree is None:
            max_in_degree = -(-max(deg, 1) // 8) * 8
        elif max_in_degree < deg:
            raise ValueError(f"pad_graphs: max_in_degree {max_in_degree} < "
                             f"actual batch max in-degree {deg}")
        # plain-layout reverse-edge involution. Computed on each graph's RAW
        # edge list and cached on the graph dict (it is deterministic and
        # index-stable — padding is appended after the real edges), so
        # loaders that re-pad every epoch sort each edge list once, not once
        # per epoch; padded tail slots are (N-1, N-1) self-pairs. All-or-
        # nothing across the batch so the pytree structure stays stable.
        from distegnn_tpu.ops.blocked import pairing_perm_fast

        pairs = []
        for g in graphs:
            e = g["edge_index"].shape[1]
            p = g.get("_plain_pair")
            if p is None or p.shape[0] != e:
                p = pairing_perm_fast(g["edge_index"].astype(np.int64))
                if p is not None:
                    g["_plain_pair"] = p
            if p is None:
                pairs = None
                break
            full = np.arange(E, dtype=np.int32)
            full[:e] = p
            pairs.append(full)
        edge_pair = np.stack(pairs).astype(np.int32) if pairs is not None else None

    return GraphBatch(
        node_feat=node_feat, node_attr=node_attr, loc=loc, vel=vel, target=target,
        loc_mean=loc_mean, node_mask=node_mask, edge_index=edge_index,
        edge_attr=edge_attr, edge_mask=edge_mask, edges_sorted=edges_sorted,
        edge_block=edge_block, edge_tile=edge_tile if edge_block else 0,
        edge_pair=edge_pair, max_in_degree=max_in_degree,
        remote_edge_index=remote[0] if remote else None,
        remote_edge_attr=remote[1] if remote else None,
        remote_edge_mask=remote[2] if remote else None,
    )


def batch_graphs(graphs: Sequence[dict], **kw) -> "GraphBatch":
    """Alias of pad_graphs (name mirrors a DataLoader collate step)."""
    return pad_graphs(graphs, **kw)
