"""Spatial node ordering — static locality preprocessing for the edge ops.

The LargeFluid step is bound by edge<->node data movement (BASELINE.md:
aggregations at ~19 GB/s effective, gathers at ~43 GB/s vs ~800 GB/s-class
HBM). Edge lists are destination(row)-sorted, so aggregation WRITES are
ordered — but with arbitrary node numbering the col-gather side reads node
rows in random order, and each node's CSR edge range references sources
scattered across the whole array.

Sorting nodes along a Z-order (Morton) curve of their positions makes
spatially-near nodes near in memory. Radius-graph neighbours are spatially
near by construction, so after the permutation every gather/scatter touches
a small contiguous region per node — cache- and DMA-friendly on both CPU
and TPU (VERDICT r3 #1 prepared attack: "edge-locality reordering").

This is a *relabeling*, not a model change: FastEGNN is permutation-
equivariant, so training trajectories are identical up to the node
permutation (tests/test_order.py pins this through the model). Applied once
per graph on the host (loader static preprocessing / dataset build), cost
O(n log n) numpy.

The reference has no counterpart (its CUDA scatter kernels hash-combine in
L2); the closest idea is the blocked layout's locality goal
(docs/PERFORMANCE.md) without changing the edge-op lowering at all.
"""

from __future__ import annotations

import numpy as np

# node-indexed arrays a graph dict may carry ([n, ...] leading axis)
_NODE_KEYS = ("node_feat", "node_attr", "loc", "vel", "target")


def morton_codes(loc: np.ndarray, bits: int = 16) -> np.ndarray:
    """Z-order curve code per row of ``loc`` [n, d<=3] -> uint64 [n].

    Coordinates are quantized to ``bits`` levels per axis over the cloud's
    bounding box; codes interleave the axis bits (x bit 0, y bit 0, z bit 0,
    x bit 1, ...), so sorting by code orders points along the Z curve."""
    loc = np.asarray(loc, np.float64)
    n, d = loc.shape
    if d > 3 or bits * d > 63:
        raise ValueError(f"morton_codes: unsupported shape/bits ({d}, {bits})")
    lo = loc.min(axis=0)
    span = np.maximum(loc.max(axis=0) - lo, 1e-300)
    q = ((loc - lo) / span * (2**bits - 1) + 0.5).astype(np.uint64)
    code = np.zeros(n, np.uint64)
    for b in range(bits):
        for ax in range(d):
            code |= ((q[:, ax] >> np.uint64(b)) & np.uint64(1)) << np.uint64(
                b * d + ax)
    return code


def morton_perm(loc: np.ndarray, bits: int = 16) -> np.ndarray:
    """Permutation (new order -> old index) sorting nodes along the Z curve."""
    return np.argsort(morton_codes(loc, bits), kind="stable")


def reorder_graph(g: dict, perm: np.ndarray) -> dict:
    """Apply a node permutation to a graph dict: permute node arrays, remap
    edge_index, and re-sort edges by (row, col) so the row-sorted invariant
    every lowering relies on (GraphBatch.edges_sorted) still holds.

    ``perm[new] = old``; graph-level keys (loc_mean, ...) pass through."""
    known = set(_NODE_KEYS) | {"loc_mean", "edge_index", "edge_attr"}
    for k, v in g.items():
        if k not in known and isinstance(v, np.ndarray):
            # refuse silently-inconsistent output: an unknown array might be
            # node-indexed and would keep its OLD order
            raise ValueError(f"reorder_graph: unknown array key {k!r} — add "
                             "it to _NODE_KEYS (node-indexed) or the "
                             "pass-through set")
    n = g["loc"].shape[0]
    inv = np.empty(n, dtype=np.int64)
    inv[perm] = np.arange(n)
    out = dict(g)
    for k in _NODE_KEYS:
        v = g.get(k)
        if v is not None:
            if v.shape[0] != n:
                raise ValueError(f"reorder_graph: {k} has leading dim "
                                 f"{v.shape[0]}, expected {n}")
            out[k] = np.ascontiguousarray(v[perm])
    ei = inv[np.asarray(g["edge_index"], np.int64)]
    order = np.lexsort((ei[1], ei[0]))
    out["edge_index"] = np.ascontiguousarray(ei[:, order]).astype(np.int32)
    ea = g.get("edge_attr")
    if ea is not None:
        out["edge_attr"] = np.ascontiguousarray(ea[order])
    return out


def morton_reorder_graph(g: dict, bits: int = 16) -> dict:
    """Convenience: reorder a graph dict along the Z curve of its positions."""
    return reorder_graph(g, morton_perm(g["loc"], bits))
