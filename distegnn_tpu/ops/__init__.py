from distegnn_tpu.ops.segment import (  # noqa: F401
    segment_sum,
    segment_mean,
    masked_mean,
    masked_sum,
)
from distegnn_tpu.ops.graph import GraphBatch, pad_graphs, batch_graphs  # noqa: F401
from distegnn_tpu.ops.radius import radius_graph_np, full_graph_np, cutoff_edges_np  # noqa: F401
from distegnn_tpu.ops.blocked import (  # noqa: F401
    blocked_gather,
    blocked_segment_sum,
    paired_col_gather,
    pairing_perm,
    slot_ids,
)
from distegnn_tpu.ops.radius_dev import radius_graph_dev, ell_to_edge_list  # noqa: F401
