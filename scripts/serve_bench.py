"""Serving benchmark: open-loop synthetic load through the serve stack.

Drives RequestQueue -> InferenceEngine with a fixed-rate arrival process
(OPEN loop: arrival k is scheduled at t0 + k/rate regardless of completions,
so queueing delay is measured honestly — a closed loop would self-throttle)
over graphs of several distinct sizes, then prints ONE BENCH-style JSON line:

  {"metric": "serve_throughput", "value": <req/s>, "unit": "req/s",
   "vs_baseline": null, "snapshot": {<ServeMetrics snapshot>}, ...}

CPU works (JAX_PLATFORMS=cpu); the same harness runs unchanged on TPU.

  python scripts/serve_bench.py --config_path configs/nbody_serve.yaml \
      --requests 64 --rate 200 --sizes 48,96,192

``--transport http`` runs the SAME open loop through a real socket: an
in-process HTTP gateway (serve/transport.py) on an ephemeral port, each
arrival a POST /v1/models/bench/predict from a client thread (base64 f32
payloads), so the BENCH line includes JSON+HTTP+routing overhead — the
number a network client actually sees. Stdout stays exactly one line.

``--workload rollout`` benches the K-step rollout path instead: it first
measures a sequential B=1 baseline (engine.rollout per scene), then drives
the same scenes through RequestQueue.submit_rollout so the micro-batcher
coalesces them into batched executables (engine.rollout_batch), and reports
batched scenes*steps/s with the B=1 number as the in-run baseline. Both
executables are compiled during warmup, so the timed windows compare
steady-state dispatch, not compiles.

Obs: the run's structured event stream (serve/batch, serve/execute,
jax/compile, ...) lands at --obs-dir/obs/events.jsonl (default
logs/serve_bench/, gitignored) so hw_session.sh can archive it next to the
BENCH line; render with `python scripts/obs_report.py <path>`. Stdout stays
EXACTLY one JSON line — the obs pointer goes to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build(cfg, sizes, seed):
    import jax

    from distegnn_tpu.models.registry import get_model
    from distegnn_tpu.serve import engine_from_config, synthetic_graph

    model = get_model(cfg.model, dataset_name=cfg.data.dataset_name)
    feat_nf = int(cfg.model.node_feat_nf)
    edge_nf = int(cfg.model.edge_attr_nf)
    graphs = [synthetic_graph(n, seed=seed + i, feat_nf=feat_nf,
                              edge_attr_nf=edge_nf)
              for i, n in enumerate(sizes)]
    engine, q = engine_from_config(cfg, model, params=None)
    b0 = engine.ladder.bucket_of_graph(graphs[0])
    init_batch, _ = engine.ladder.pad_batch([graphs[0]], b0, 1)
    engine.params = model.init(jax.random.PRNGKey(seed), init_batch)
    return engine, q, graphs


def _b64_field(a, dtype):
    import base64

    import numpy as np

    a = np.ascontiguousarray(a, dtype=dtype)
    return {"b64": base64.b64encode(a.tobytes()).decode("ascii"),
            "shape": list(a.shape)}


def _http_payload(g) -> bytes:
    return json.dumps({
        "positions": _b64_field(g["loc"], "<f4"),
        "velocities": _b64_field(g["vel"], "<f4"),
        "node_feat": _b64_field(g["node_feat"], "<f4"),
        "edge_attr": _b64_field(g["edge_attr"], "<f4"),
        "edge_index": _b64_field(g["edge_index"], "<i4"),
        "encoding": "b64",
    }).encode()


def _run_http(engine, q, graphs, requests, rate):
    """The same open loop, but every arrival is a POST through a live
    in-process gateway socket. Returns (wall_s, rejected_429, statuses)."""
    import threading
    import urllib.error
    import urllib.request

    from distegnn_tpu.serve.registry import ModelRegistry
    from distegnn_tpu.serve.transport import Gateway

    q.start()
    registry = ModelRegistry.single(
        "bench", engine, q, feat_nf=graphs[0]["node_feat"].shape[1],
        edge_attr_nf=graphs[0]["edge_attr"].shape[1])
    gw = Gateway(registry, port=0, max_inflight=max(64, requests))
    server = threading.Thread(target=gw.serve_forever,
                              name="bench-gateway", daemon=True)
    server.start()
    url = gw.url("/v1/models/bench/predict")
    payloads = [_http_payload(g) for g in graphs]
    statuses = [0] * requests

    def post(i, body):
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"},
            method="POST")
        try:
            with urllib.request.urlopen(req, timeout=120.0) as resp:
                statuses[i] = int(resp.status)
        except urllib.error.HTTPError as e:
            statuses[i] = int(e.code)
        except Exception:
            statuses[i] = -1

    threads = []
    t0 = time.perf_counter()
    for k in range(requests):
        target = t0 + k / rate
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        t = threading.Thread(target=post,
                             args=(k, payloads[k % len(payloads)]),
                             daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=180.0)
    wall = time.perf_counter() - t0
    gw.drain()               # also stops the queue (drain=True)
    server.join(timeout=30.0)
    gw.close()
    rejected = sum(1 for s in statuses if s == 429)
    return wall, rejected, statuses


def _run_rollout(engine, q, graphs, scenes_n, steps, rate, warmup=True):
    """Rollout workload: same-run B=1 baseline, then the batched path.

    The B=1 baseline is the serve path WITHOUT request coalescing: each
    scene still runs the rung's max_batch-padded executable (the
    one-executable-per-rung contract — same as predicts), filled by a
    single real scene. The batched window drives the same scenes through
    ``RequestQueue.submit_rollout`` so the micro-batcher fills the padded
    batches. A third (untimed-contract) number, ``solo``, is the unpadded
    single-scene executable — the pre-batching client API — reported for
    transparency.

    Returns (batched_rate, b1_rate, solo_rate, wall_batched, wall_b1,
    rejected) where rates are scenes*steps per second."""
    from distegnn_tpu.obs import jaxprobe

    scenes = [{"loc": graphs[i % len(graphs)]["loc"],
               "vel": graphs[i % len(graphs)]["vel"], "steps": steps}
              for i in range(scenes_n)]
    if warmup:
        # compile BOTH executables outside the timed windows
        engine.rollout(scenes[0]["loc"], scenes[0]["vel"], steps)
        engine.rollout_batch([scenes[0]])
    jaxprobe.mark_warmup_done()

    t0 = time.perf_counter()
    for s in scenes:
        engine.rollout(s["loc"], s["vel"], steps)
    wall_solo = time.perf_counter() - t0

    t0 = time.perf_counter()
    for s in scenes:
        engine.rollout_batch([s])    # fill=1: uncoalesced serve path
    wall_b1 = time.perf_counter() - t0

    rejected = 0
    completed = 0
    futures = []
    t0 = time.perf_counter()
    with q:
        for k, s in enumerate(scenes):
            target = t0 + k / rate
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            try:
                futures.append(q.submit_rollout(s))
            except Exception:    # QueueFullError: open loop sheds
                rejected += 1
        for f in futures:
            try:
                f.result(timeout=300.0)
                completed += 1
            except Exception:
                pass  # failures are visible in the snapshot counters
    wall_batched = time.perf_counter() - t0

    # the headline only credits scenes that actually finished — a queue that
    # sheds by timeout must not report the shed work as throughput
    work = scenes_n * steps
    return (completed * steps / max(wall_batched, 1e-9),
            work / max(wall_b1, 1e-9), work / max(wall_solo, 1e-9),
            wall_batched, wall_b1, rejected, completed)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="serve-stack open-loop bench")
    ap.add_argument("--config_path", type=str, default=None,
                    help="YAML with a serve: section (default: built-ins)")
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--rate", type=float, default=200.0,
                    help="arrival rate, req/s (open loop)")
    ap.add_argument("--sizes", type=str, default="48,96,192",
                    help="comma-separated node counts of the synthetic mix")
    ap.add_argument("--seed", type=int, default=43)
    ap.add_argument("--no-warmup", action="store_true",
                    help="include first-request compiles in the timed window")
    ap.add_argument("--obs-dir", type=str, default="logs/serve_bench",
                    help="event-stream sink dir (events land at <dir>/obs/"
                         "events.jsonl); '' disables tracing")
    ap.add_argument("--transport", choices=("inproc", "http"),
                    default="inproc",
                    help="inproc = RequestQueue.submit directly; http = "
                         "through a live gateway socket (serve/transport.py)")
    ap.add_argument("--workload", choices=("predict", "rollout"),
                    default="predict",
                    help="predict = one model step per request; rollout = "
                         "K-step scenes through the rollout batcher, with a "
                         "same-run B=1 baseline")
    ap.add_argument("--rollout-steps", type=int, default=8,
                    help="scan length K of each rollout scene")
    ap.add_argument("--rollout-scenes", type=int, default=8,
                    help="number of rollout scenes per timed window")
    ap.add_argument("--max-batch", type=int, default=None,
                    help="override serve.max_batch (compile-time bound of "
                         "every padded batch; smaller = faster CPU traces)")
    args = ap.parse_args(argv)

    from distegnn_tpu import obs
    from distegnn_tpu.config import ConfigDict, _DEFAULTS, load_config
    from distegnn_tpu.obs import jaxprobe

    cfg = (load_config(args.config_path) if args.config_path
           else ConfigDict(_DEFAULTS))
    if args.max_batch is not None:
        cfg.serve.max_batch = int(args.max_batch)
    if args.workload == "rollout" and not cfg.serve.get("rollout"):
        # the rollout path needs make_rollout_fn kwargs; default to the
        # synthetic_graph workload's geometry when the config has none.
        # max_degree must clear the DENSEST default scene (n=192 starts at
        # degree 44) plus drift headroom — an overflow aborts the bench.
        cfg.serve.rollout = {"radius": 0.35, "max_degree": 96,
                             "max_per_cell": 128, "edge_block": 256}
    if args.workload == "rollout":
        # the rollout bench measures coalescing, not SLO shedding: the
        # coalescing window must cover the whole submit ramp (scenes/rate)
        # and a K-step CPU batch can take minutes — a serving-tuned 1 s
        # request timeout would shed every queued scene mid-measure and
        # quietly turn the headline into a timeout benchmark
        ramp_ms = 1000.0 * args.rollout_scenes / max(args.rate, 1e-9)
        cfg.serve.batch_deadline_ms = max(
            float(cfg.serve.batch_deadline_ms), ramp_ms + 50.0)
        cfg.serve.request_timeout_ms = max(
            float(cfg.serve.request_timeout_ms), 600_000.0)
    if args.obs_dir:
        obs.configure_from_config(cfg, args.obs_dir,
                                  tags={"run": "serve_bench"})
    sizes = [int(s) for s in args.sizes.split(",") if s]
    engine, q, graphs = _build(cfg, sizes, args.seed)

    if args.workload == "rollout":
        if args.transport == "http":
            print("serve_bench: --workload rollout runs inproc "
                  "(submit_rollout); ignoring --transport http",
                  file=sys.stderr)  # noqa: obs-print
        obs.event("serve/bench_start", requests=args.rollout_scenes,
                  rate=args.rate, sizes=sizes, workload="rollout",
                  steps=args.rollout_steps)
        batched, base, solo, wall_b, wall_1, rejected, completed = \
            _run_rollout(
                engine, q, graphs, args.rollout_scenes, args.rollout_steps,
                args.rate, warmup=not args.no_warmup)
        snap = engine.metrics.snapshot()
        rec = {
            "metric": "serve_rollout_throughput",
            "value": round(batched, 3),
            "unit": "scenes*steps/s",
            # baseline_b1 = the uncoalesced serve path: one fill-1
            # max_batch-padded executable call per scene. baseline_solo =
            # the unpadded single-scene client API, for transparency.
            "vs_baseline": round(batched / max(base, 1e-9), 3),
            "baseline_b1": round(base, 3),
            "baseline_solo": round(solo, 3),
            "scenes": args.rollout_scenes,
            "scenes_completed": completed,
            "steps": args.rollout_steps,
            "max_batch": engine.max_batch,
            "rejected_at_submit": rejected,
            "offered_rate": args.rate,
            "sizes": sizes,
            "wall_s": round(wall_b, 4),
            "wall_b1_s": round(wall_1, 4),
            "platform": __import__("jax").default_backend(),
            "snapshot": snap,
        }
        print(json.dumps(rec, sort_keys=True))
        obs.event("bench/result", **rec)
        tracer = obs.get_tracer()
        tracer.flush()
        w = getattr(tracer, "writer", None)
        if w is not None:
            print(f"obs: events at {w.path}; render with "
                  f"python scripts/obs_report.py {w.path}",
                  file=sys.stderr, flush=True)  # noqa: obs-print
        return 0 if snap["requests_completed"] else 1

    if not args.no_warmup:
        engine.warmup([(g["loc"].shape[0], g["edge_index"].shape[1])
                       for g in graphs])
    # compiles past this point are regressions obs_report --check flags
    jaxprobe.mark_warmup_done()
    obs.event("serve/bench_start", requests=args.requests, rate=args.rate,
              sizes=sizes, warmup=not args.no_warmup,
              transport=args.transport)

    if args.transport == "http":
        wall, rejected, _statuses = _run_http(engine, q, graphs,
                                              args.requests, args.rate)
    else:
        futures, rejected = [], 0
        t0 = time.perf_counter()
        with q:
            for k in range(args.requests):
                target = t0 + k / args.rate
                delay = target - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                try:
                    futures.append(q.submit(graphs[k % len(graphs)]))
                except Exception:  # QueueFullError: open loop sheds
                    rejected += 1
            for f in futures:
                try:
                    f.result(timeout=60.0)
                except Exception:
                    pass  # failures are visible in the snapshot counters
        wall = time.perf_counter() - t0

    snap = engine.metrics.snapshot()
    completed = snap["requests_completed"]
    rec = {
        "metric": "serve_throughput",
        "value": round(completed / max(wall, 1e-9), 3),
        "unit": "req/s",
        "vs_baseline": None,
        "requests": args.requests,
        "rejected_at_submit": rejected,
        "offered_rate": args.rate,
        "sizes": sizes,
        "transport": args.transport,
        "wall_s": round(wall, 4),
        "platform": __import__("jax").default_backend(),
        "snapshot": snap,
    }
    print(json.dumps(rec, sort_keys=True))
    obs.event("bench/result", **rec)

    tracer = obs.get_tracer()
    tracer.flush()
    w = getattr(tracer, "writer", None)
    if w is not None:
        # stderr: stdout is contractually the single JSON line above
        print(f"obs: events at {w.path}; render with "
              f"python scripts/obs_report.py {w.path}",
              file=sys.stderr, flush=True)  # noqa: obs-print
    return 0 if completed else 1


if __name__ == "__main__":
    raise SystemExit(main())
