"""Mixed-traffic replay harness: open-loop predict/session/rollout load
against a live gateway socket.

Replays a configurable traffic mix (``--mix predict=0.6,session=0.3,
rollout=0.1``) across every served model, with heavy-tailed graph sizes
drawn from the shape ladder (``--sizes`` is the rung support; rung k is
picked with weight 1/(k+1)^--tail, so most traffic is small and the tail
is large) and BURSTY arrivals: a Poisson process (mean ``--rate`` req/s)
gated by an on/off modulator (exponential ON phases of mean
``--burst-on-s`` separated by exponential OFF gaps of mean
``--burst-off-s``; ``--burst-off-s 0`` degenerates to pure Poisson). The
loop is OPEN: arrival k fires at its scheduled time regardless of
completions, so queueing delay and shedding are measured honestly.

``--profile steady|ramp|spike10x`` replaces the burst modulator with a
phased schedule (steady: flat Poisson; ramp: 0.5x -> 1x -> 2x thirds;
spike10x: 1x -> 10x -> 1x with half the requests inside the spike), tags
every request with its phase, and adds per-phase p50/p99 — overall AND
interactive-only (predict+session; rollouts are the bulk class) — plus a
per-phase SLO verdict to the BENCH record: the elasticity drill's proof
that interactive latency held through the spike, phase by phase.
``--autoscale 'max_replicas=3,queue_high=2'`` turns the in-process
gateway's replica autoscaler on (keys from serve.autoscale:; bare
``--autoscale on`` enables it with config defaults) and
``--scale-settle-s`` holds the gateway open after the replay until the
fleet shrinks back to min_replicas, so one run's event stream shows the
full 1 -> N -> 1 cycle.

Traffic classes:
  predict   fresh synthetic graph per request -> POST .../predict
  session   requests drawn from a pool of --sessions sticky ids, each
            pinned to ONE fixed graph -> POST .../predict with
            ``session_id`` (exercises the prep/session cache)
  rollout   K-step scene (--rollout-steps) -> POST .../rollout; routed
            only to rollout-capable models (folded into predict, with a
            stderr note, when none is)

Every request carries ``X-Request-Id: tg-<seed>-<k>`` and records the
echoed id, so any request in the run can be replayed as a waterfall:
``python scripts/obs_report.py <events> --request tg-<seed>-<k>``.

Target: ``--url http://host:port`` drives an already-running gateway
(models discovered via GET /v1/models); without ``--url`` the script
boots an in-process gateway from ``--config_path`` (default built-ins)
on an ephemeral port and still drives it over the real socket.

Chaos: ``--chaos 'kill@0.3:replica=0;swap@1.0:ckpt=/p/b.ckpt'`` fires
serving faults at fixed offsets into the replay (semicolon-separated
``action@seconds[:key=val,...]``; actions kill / wedge / latency /
corrupt reach into the live replica pool via
distegnn_tpu.testing.serve_faults, swap POSTs the blue/green hot-swap
through the socket and then fires a fixed probe predict whose
prediction bytes land in a ``chaos/swap_probe`` event for bitwise
comparison). Under ``serve.workers: process`` (or ``--workers
process``) three process-level actions join in: kill9 SIGKILLs a
replica's worker child, sigstop freezes it (heartbeat-staleness wedge →
SIGKILL escalation), and spawn_fail arms the next respawn to fail so
the replica degrades to in-process serving instead of shedding. Chaos
needs the in-process gateway (no ``--url``).
Clients honor 429/503 ``Retry-After`` headers with bounded retries
(``--max-retries``), so a failover blip degrades latency instead of
losing accepted work.

Stdout is EXACTLY one BENCH JSON line:

  {"metric": "traffic_p99_ms", "value": <overall p99>, "unit": "ms",
   "classes": {<class>: {count, ok, p50_ms, p99_ms}}, "throughput_rps":
   ..., "shed": <429 fraction>, "batch_fill": ..., "slo": {<verdict>}}

plus the SLO verdict table on stderr (spec from ``--slo <file>``, else
the config's ``slo:`` section). A breach is REPORTED, not fatal — the
exit code is 0 iff any request completed; gate on the verdict with
``obs_report.py --slo``. The run's event stream lands at
``--obs-dir/obs/events.jsonl`` (default logs/traffic_gen/).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CLASSES = ("predict", "session", "rollout")


# ---- plan construction ------------------------------------------------------

def parse_mix(spec: str) -> dict:
    """'predict=0.6,session=0.3,rollout=0.1' -> normalized class weights."""
    mix = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, val = part.partition("=")
        name = name.strip()
        if name not in CLASSES:
            raise ValueError(f"unknown traffic class {name!r} "
                             f"(known: {', '.join(CLASSES)})")
        try:
            mix[name] = float(val)
        except ValueError:
            raise ValueError(f"bad mix weight for {name!r}: {val!r}") from None
        if mix[name] < 0:
            raise ValueError(f"mix weight for {name!r} must be >= 0")
    total = sum(mix.values())
    if total <= 0:
        raise ValueError(f"traffic mix {spec!r} has no positive weight")
    return {k: mix.get(k, 0.0) / total for k in CLASSES}


CHAOS_ACTIONS = ("kill", "wedge", "latency", "swap", "corrupt",
                 "kill9", "sigstop", "spawn_fail")


def parse_chaos(spec: str):
    """'kill@0.3:replica=0;swap@1.0:ckpt=/p/b.ckpt' -> events sorted by
    firing offset, each ``{action, at, kw}``. Args per action: every one
    takes ``model=`` (default: first served model); kill/wedge/latency
    take ``replica=`` (kill/wedge default 0, latency default ALL); wedge
    takes ``dur=`` seconds; latency takes ``s=`` seconds; swap/corrupt
    take ``ckpt=`` and corrupt ``mode=`` (truncate|garbage|headerless);
    kill9/sigstop take ``replica=`` (default 0) and need process-backed
    replicas; spawn_fail takes ``replica=`` and ``n=`` (default 1)
    respawn attempts to sabotage."""
    events = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        head, _, tail = part.partition(":")
        action, _, at = head.partition("@")
        action = action.strip()
        if action not in CHAOS_ACTIONS:
            raise ValueError(f"unknown chaos action {action!r} "
                             f"(known: {', '.join(CHAOS_ACTIONS)})")
        try:
            at_s = float(at)
        except ValueError:
            raise ValueError(
                f"chaos action {action!r} needs '@<seconds>'") from None
        kw = {}
        for item in tail.split(","):
            item = item.strip()
            if not item:
                continue
            key, eq, val = item.partition("=")
            if not eq:
                raise ValueError(f"bad chaos arg {item!r} (want key=value)")
            kw[key.strip()] = val.strip()
        if action in ("swap", "corrupt") and not kw.get("ckpt"):
            raise ValueError(f"chaos action {action!r} needs ckpt=<path>")
        events.append({"action": action, "at": at_s, "kw": kw})
    return sorted(events, key=lambda e: e["at"])


def parse_scale(spec: str) -> dict:
    """--autoscale value -> serve.autoscale overrides. 'on'/'true'/'1' is
    bare enablement; otherwise 'key=val,...' with keys from the autoscaler's
    knob set, coerced against the knob's default type. Passing the flag at
    all implies enable=true unless the spec says enable=false."""
    from distegnn_tpu.serve.autoscale import _DEFAULTS as knob_defaults

    spec = spec.strip()
    out: dict = {}
    if spec.lower() in ("on", "true", "1", "yes"):
        out["enable"] = True
        return out
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, eq, val = part.partition("=")
        key, val = key.strip(), val.strip()
        if not eq or key not in knob_defaults:
            raise ValueError(
                f"bad autoscale override {part!r} (want key=value with keys "
                f"{', '.join(sorted(knob_defaults))})")
        ref = knob_defaults[key]
        if isinstance(ref, bool):
            out[key] = val.lower() in ("1", "true", "yes", "on")
        elif isinstance(ref, int):
            out[key] = int(val)
        else:                         # float knobs, incl. None-able p99 gate
            out[key] = float(val)
    out.setdefault("enable", True)
    return out


def size_sampler(sizes, alpha: float, rng: random.Random):
    """Heavy-tailed draw over ascending ladder sizes: rung k gets weight
    1/(k+1)^alpha — most traffic at the floor, a power-law tail of big
    graphs."""
    sizes = sorted(set(int(s) for s in sizes))
    weights = [1.0 / (k + 1) ** alpha for k in range(len(sizes))]
    return lambda: rng.choices(sizes, weights=weights, k=1)[0]


def arrival_times(n: int, rate: float, on_s: float, off_s: float,
                  rng: random.Random):
    """n arrival offsets (seconds from t0): Poisson at ``rate`` during
    exponential ON phases (mean on_s), jumping exponential OFF gaps (mean
    off_s). off_s <= 0 -> a pure Poisson process."""
    out, t = [], 0.0
    on_left = rng.expovariate(1.0 / on_s) if off_s > 0 else float("inf")
    for _ in range(n):
        dt = rng.expovariate(rate)
        while off_s > 0 and dt > on_left:
            dt -= on_left
            t += on_left + rng.expovariate(1.0 / off_s)  # jump the OFF gap
            on_left = rng.expovariate(1.0 / on_s)
        on_left -= dt
        t += dt
        out.append(t)
    return out


# name -> ordered (phase, request_fraction, rate_multiplier); arrivals inside
# a phase are pure Poisson at rate * multiplier, phases laid back-to-back
PROFILES = {
    "steady": (("steady", 1.0, 1.0),),
    "ramp": (("low", 1 / 3, 0.5), ("mid", 1 / 3, 1.0), ("high", 1 / 3, 2.0)),
    "spike10x": (("pre", 0.25, 1.0), ("spike", 0.5, 10.0),
                 ("post", 0.25, 1.0)),
}


def profile_arrivals(profile: str, n: int, rate: float, rng: random.Random):
    """(arrival offsets, per-request phase tags) for a named load profile.
    Each phase gets its request share as a pure Poisson stream at
    rate*multiplier — the spike really is 10x denser wall-clock traffic,
    not the same arrivals relabeled."""
    segs = PROFILES[profile]
    counts = [int(n * frac) for _, frac, _ in segs]
    counts[-1] += n - sum(counts)            # rounding drift -> last phase
    offsets, phases, t = [], [], 0.0
    for (name, _, mult), count in zip(segs, counts):
        for _ in range(count):
            t += rng.expovariate(rate * mult)
            offsets.append(t)
            phases.append(name)
    return offsets, phases


def _b64_field(a, dtype):
    import base64

    import numpy as np

    a = np.ascontiguousarray(a, dtype=dtype)
    return {"b64": base64.b64encode(a.tobytes()).decode("ascii"),
            "shape": list(a.shape)}


def predict_payload(g, session_id=None) -> bytes:
    body = {
        "positions": _b64_field(g["loc"], "<f4"),
        "velocities": _b64_field(g["vel"], "<f4"),
        "node_feat": _b64_field(g["node_feat"], "<f4"),
        "edge_attr": _b64_field(g["edge_attr"], "<f4"),
        "edge_index": _b64_field(g["edge_index"], "<i4"),
        "encoding": "b64",
    }
    if session_id is not None:
        body["session_id"] = str(session_id)
    return json.dumps(body).encode()


def rollout_payload(g, steps: int) -> bytes:
    return json.dumps({
        "positions": _b64_field(g["loc"], "<f4"),
        "velocities": _b64_field(g["vel"], "<f4"),
        "steps": int(steps),
        "encoding": "b64",
    }).encode()


def build_plan(args, models, rollout_models, feat_nf, edge_attr_nf):
    """The full replay plan, deterministic under --seed: a list of
    ``{cls, model, path, body, rid}`` plus the arrival offsets."""
    from distegnn_tpu.serve.buckets import synthetic_graph

    rng = random.Random(args.seed)
    mix = parse_mix(args.mix)
    if mix["rollout"] > 0 and not rollout_models:
        print("traffic_gen: no rollout-capable model; folding the rollout "
              "share into predict", file=sys.stderr)  # noqa: obs-print
        mix["predict"] += mix["rollout"]
        mix["rollout"] = 0.0
    draw_size = size_sampler(args.size_list, args.tail, rng)

    # session pool: sticky id -> ONE fixed graph (same bytes every time, so
    # the prep cache's plan-reuse path is actually exercised)
    sessions = []
    for i in range(max(1, args.sessions)):
        n = draw_size()
        g = synthetic_graph(n, seed=10_000 + args.seed + i, feat_nf=feat_nf,
                            edge_attr_nf=edge_attr_nf)
        sessions.append((f"tg-sess-{i}", predict_payload(
            g, session_id=f"tg-sess-{i}")))

    names, weights = zip(*sorted(mix.items()))
    plan = []
    for k in range(args.requests):
        cls = rng.choices(names, weights=weights, k=1)[0]
        rid = f"tg-{args.seed}-{k}"
        if cls == "rollout":
            model = rng.choice(rollout_models)
            g = synthetic_graph(draw_size(), seed=args.seed + k,
                                feat_nf=feat_nf, edge_attr_nf=edge_attr_nf)
            body = rollout_payload(g, args.rollout_steps)
            path = f"/v1/models/{model}/rollout"
        elif cls == "session":
            model = rng.choice(models)
            _, body = sessions[rng.randrange(len(sessions))]
            path = f"/v1/models/{model}/predict"
        else:
            model = rng.choice(models)
            g = synthetic_graph(draw_size(), seed=args.seed + k,
                                feat_nf=feat_nf, edge_attr_nf=edge_attr_nf)
            body = predict_payload(g)
            path = f"/v1/models/{model}/predict"
        plan.append({"cls": cls, "model": model, "path": path, "body": body,
                     "rid": rid})
    if getattr(args, "profile", None):
        offsets, phases = profile_arrivals(args.profile, args.requests,
                                           args.rate, rng)
        for item, phase in zip(plan, phases):
            item["phase"] = phase
    else:
        offsets = arrival_times(args.requests, args.rate, args.burst_on_s,
                                args.burst_off_s, rng)
    return plan, offsets


# ---- target gateways --------------------------------------------------------

def discover_models(base_url: str, timeout: float = 10.0):
    """(all model names, rollout-capable names) from GET /v1/models."""
    import urllib.request

    with urllib.request.urlopen(base_url.rstrip("/") + "/v1/models",
                                timeout=timeout) as resp:
        desc = json.loads(resp.read().decode())
    models = [m["name"] for m in desc.get("models", [])]
    rollout = [m["name"] for m in desc.get("models", [])
               if m.get("rollout")]
    return models, rollout


def boot_gateway(args, cfg):
    """In-process gateway from the config, on an ephemeral port; returns
    (gateway, server_thread, registry)."""
    from distegnn_tpu.obs import jaxprobe
    from distegnn_tpu.serve.registry import ModelRegistry
    from distegnn_tpu.serve.transport import Gateway

    mix = parse_mix(args.mix)
    if mix["rollout"] > 0 and not cfg.serve.get("rollout"):
        # same geometry defaults as serve_bench's rollout workload
        cfg.serve.rollout = {"radius": 0.35, "max_degree": 96,
                             "max_per_cell": 128, "edge_block": 256}
    if mix["rollout"] > 0:
        # K-step CPU batches take seconds; a serving-tuned 1 s request
        # timeout would shed every queued scene and bench the timeout path
        cfg.serve.request_timeout_ms = max(
            float(cfg.serve.request_timeout_ms), 600_000.0)
    if args.max_batch is not None:
        cfg.serve.max_batch = int(args.max_batch)
    if args.replicas is not None:
        cfg.serve.replicas = int(args.replicas)
    if args.workers is not None:
        cfg.serve.workers = str(args.workers)

    registry = ModelRegistry.from_config(cfg).start()
    registry.warmup(args.size_list)
    jaxprobe.mark_warmup_done()
    slo_window = float((cfg.get("slo") or {}).get("window_s", 60.0) or 60.0)
    autoscale = dict(cfg.serve.autoscale)
    if getattr(args, "autoscale", None):
        autoscale.update(parse_scale(args.autoscale))
    gw = Gateway(registry, port=0,
                 max_inflight=max(64, args.requests),
                 slo_window_s=slo_window,
                 autoscale=autoscale,
                 priority=dict(cfg.serve.priority),
                 stream_chunk_steps=int(cfg.serve.stream.chunk_steps),
                 promote=dict(cfg.get("promote") or {}))
    server = threading.Thread(target=gw.serve_forever, name="tg-gateway",
                              daemon=True)
    server.start()
    return gw, server, registry


# ---- chaos ------------------------------------------------------------------

def _swap_over_socket(base_url: str, model: str, ckpt: str,
                      feat_nf: int, edge_attr_nf: int) -> dict:
    """POST the blue/green hot-swap through the live socket; on success
    fire one FIXED probe predict (n=24, seed=1234) and log its prediction
    bytes as a ``chaos/swap_probe`` event, so a test can compare them
    bitwise against a cold-started engine on the new checkpoint."""
    import urllib.error
    import urllib.request

    from distegnn_tpu import obs
    from distegnn_tpu.serve.buckets import synthetic_graph

    req = urllib.request.Request(
        base_url.rstrip("/") + f"/v1/models/{model}/swap",
        data=json.dumps({"checkpoint": str(ckpt)}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=120.0) as resp:
            status, body = int(resp.status), json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        status = int(e.code)
        try:
            body = json.loads(e.read().decode() or "{}")
        except ValueError:
            body = {}
    out = {"ckpt": str(ckpt), "status": status, "ok": status == 200,
           "swap": {k: body[k] for k in ("version", "stage", "rolled_back")
                    if k in body}}
    if status == 200:
        g = synthetic_graph(24, seed=1234, feat_nf=feat_nf,
                            edge_attr_nf=edge_attr_nf)
        preq = urllib.request.Request(
            base_url.rstrip("/") + f"/v1/models/{model}/predict",
            data=predict_payload(g),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(preq, timeout=120.0) as resp:
            pred = json.loads(resp.read().decode())["prediction"]
        obs.event("chaos/swap_probe", model=model, ckpt=str(ckpt), n=24,
                  seed=1234, prediction=pred)
    return out


def run_chaos(events, t0: float, registry, base_url: str, models,
              feat_nf: int, edge_attr_nf: int, record: list) -> None:
    """Fire the parsed chaos events at their offsets from ``t0``; every
    firing (or failure to fire) lands in ``record`` and as a
    ``chaos/inject`` obs event. Injection errors are recorded, never
    raised — the replay must finish and report regardless."""
    from distegnn_tpu import obs
    from distegnn_tpu.testing import serve_faults

    for ev in events:
        delay = (t0 + ev["at"]) - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        action, kw = ev["action"], ev["kw"]
        model = kw.get("model") or models[0]
        outcome = {"action": action, "at_s": ev["at"], "model": model}
        try:
            if action == "kill":
                rep = int(kw.get("replica", 0))
                serve_faults.kill_replica(registry, model, rep)
                outcome.update(replica=rep, ok=True)
            elif action == "kill9":
                rep = int(kw.get("replica", 0))
                pid = serve_faults.kill9_replica(registry, model, rep)
                outcome.update(replica=rep, pid=pid, ok=True)
            elif action == "sigstop":
                rep = int(kw.get("replica", 0))
                pid = serve_faults.sigstop_replica(registry, model, rep)
                outcome.update(replica=rep, pid=pid, ok=True)
            elif action == "spawn_fail":
                rep = int(kw.get("replica", 0))
                n = int(kw.get("n", 1))
                serve_faults.spawn_failure(registry, model, n, rep)
                outcome.update(replica=rep, n=n, ok=True)
            elif action == "wedge":
                rep = int(kw.get("replica", 0))
                dur = float(kw.get("dur", 5.0))
                serve_faults.wedge_replica(registry, model, dur, rep)
                outcome.update(replica=rep, dur_s=dur, ok=True)
            elif action == "latency":
                rep = int(kw["replica"]) if "replica" in kw else None
                sec = float(kw.get("s", 0.05))
                serve_faults.inject_execute_latency(registry, model, sec,
                                                    replica=rep)
                outcome.update(replica=rep, seconds=sec, ok=True)
            elif action == "corrupt":
                mode = kw.get("mode", "garbage")
                serve_faults.corrupt_swap_checkpoint(kw["ckpt"], mode)
                outcome.update(ckpt=kw["ckpt"], mode=mode, ok=True)
            elif action == "swap":
                outcome.update(_swap_over_socket(
                    base_url, model, kw["ckpt"], feat_nf, edge_attr_nf))
        except Exception as exc:
            outcome.update(ok=False, error=repr(exc))
        obs.event("chaos/inject", **outcome)
        record.append(outcome)


# ---- the promotion conveyor drill -------------------------------------------

def publish_child_main(spec_json: str) -> int:
    """The drill's stand-in trainer process: publish candidates through the
    REAL CandidatePublisher (tmp+fsync+rename, manifest last). A plan item
    with ``hang: true`` simulates dying INSIDE the atomic write — it leaves
    an orphan ``.tmp.`` file in the watch dir, announces itself on stdout,
    and waits for the parent's SIGKILL; the conveyor invariant under test is
    that no manifest ever points at a partial checkpoint."""
    import tempfile

    spec = json.loads(spec_json)
    watch = spec["watch_dir"]
    from distegnn_tpu.promote.publish import CandidatePublisher

    pub = CandidatePublisher(watch, history=int(spec.get("history", 4)))
    for item in spec["plan"]:
        delay = float(item.get("delay", 0.0))
        if delay > 0:
            time.sleep(delay)
        step = int(item["step"])
        if item.get("hang"):
            fd, _ = tempfile.mkstemp(
                dir=watch, prefix=f"step_{step:010d}.ckpt.tmp.")
            os.write(fd, b"partial-checkpoint-bytes")
            print(f"TG-PUBLISH-HANG {step}", flush=True)
            time.sleep(600.0)
            os.close(fd)
            return 3  # unreachable under the drill's SIGKILL
        pub.publish(item["ckpt"], step=step, val_loss=item.get("val_loss"))
        print(f"TG-PUBLISHED {step}", flush=True)
    return 0


def run_promote_drill(args, gw, registry, model, base_url, feat_nf,
                      edge_attr_nf, record) -> None:
    """The continuous-promotion chaos drill, run alongside the replay:

      1. a publisher CHILD PROCESS lands a good candidate -> it promotes
         fleet-wide through canary + shadow gates;
      2. a second publisher is SIGKILLed mid-publish (tmp file open, no
         manifest) -> the conveyor must not move;
      3. a third candidate's canary replica is killed mid-promotion
         (SIGKILL under process workers) -> immediate canary_died rollback,
         the supervisor restores the replica;
      4. a drift-injected candidate -> the drift gauge rolls it back.

    Fills ``record`` (the BENCH line's ``promote`` field) with per-phase
    outcomes, the orphan-sweep proof, and the /readyz fleet-coherence bit.
    Never raises — a wedged drill lands in ``record['error']``."""
    import signal
    import subprocess
    import urllib.error
    import urllib.request
    from types import SimpleNamespace

    import jax

    from distegnn_tpu import obs
    from distegnn_tpu.promote.publish import candidate_manifest_name
    from distegnn_tpu.serve.buckets import synthetic_graph
    from distegnn_tpu.testing import serve_faults
    from distegnn_tpu.train.checkpoint import save_checkpoint

    promoter = gw.promoter
    entry = registry.get(model)
    watch = promoter.watch_dir
    stage = os.path.join(os.path.dirname(watch) or ".", "promote_ckpts")
    os.makedirs(stage, exist_ok=True)
    record.update(ok=False, phases={}, published=0)
    children = []

    def save_scaled(name, scale, shift=0.0):
        params = jax.tree.map(lambda x: x * scale + shift,
                              entry.engine.params)
        path = os.path.join(stage, name)
        save_checkpoint(path, SimpleNamespace(params=params, opt_state={},
                                              step=0), epoch=0)
        return path

    def spawn(plan):
        spec = json.dumps({"watch_dir": watch, "plan": plan})
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--publish-child", spec],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
        children.append(proc)
        return proc

    probe_body = predict_payload(synthetic_graph(
        min(args.size_list), seed=4321, feat_nf=feat_nf,
        edge_attr_nf=edge_attr_nf))

    def probe():
        # gate fuel, not scored traffic: shadow evidence must keep
        # accumulating even after the replay plan runs dry
        req = urllib.request.Request(
            base_url.rstrip("/") + f"/v1/models/{model}/predict",
            data=probe_body,
            headers={"Content-Type": "application/json",
                     "X-Request-Id": "tg-promote-probe"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=30.0) as resp:
                resp.read()
        except Exception:
            pass

    def outcome_for(step):
        for r in promoter.results:
            if r.get("step") == step:
                return r
        return None

    def wait_for(pred, timeout_s, poke=False):
        deadline = time.perf_counter() + timeout_s
        while time.perf_counter() < deadline:
            if pred():
                return True
            if poke:
                probe()
            time.sleep(0.05)
        return bool(pred())

    def healthy_replicas():
        return sum(1 for r in entry.replicas.replicas if r.healthy())

    def readyz():
        try:
            with urllib.request.urlopen(
                    base_url.rstrip("/") + "/readyz", timeout=10.0) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            try:
                return json.loads(e.read().decode() or "{}")
            except ValueError:
                return {}
        except Exception:
            return {}

    try:
        good1 = save_scaled("good1.ckpt", 1.0001)
        good2 = save_scaled("good2.ckpt", 1.0002)
        # big enough to breach the drift ceiling by an order of magnitude,
        # small enough to stay finite (larger scales overflow the net and
        # get rejected by the canary finiteness check instead)
        drifted = save_scaled("drift.ckpt", 2.25)

        # phase 1: good candidate promotes fleet-wide
        proc = spawn([{"step": 10, "ckpt": good1, "val_loss": 0.5}])
        proc.wait(timeout=120)
        record["published"] += 1
        wait_for(lambda: outcome_for(10), 30.0, poke=True)
        o1 = dict(outcome_for(10) or {})
        record["phases"]["promote"] = o1
        promote_ok = o1.get("outcome") == "promoted"

        # phase 2: trainer SIGKILLed mid-publish — orphan tmp, no manifest,
        # conveyor position unchanged
        before = promoter.last_step
        proc = spawn([{"step": 20, "hang": True}])
        marker = proc.stdout.readline()
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=60)
        time.sleep(3 * promoter.interval_s + 0.1)
        orphan = any(".tmp." in f for f in os.listdir(watch))
        manifest20 = os.path.exists(
            os.path.join(watch, candidate_manifest_name(20)))
        kill_ok = orphan and not manifest20 and promoter.last_step == before
        record["phases"]["trainer_kill"] = {
            "marker": marker.strip(), "orphan_tmp": orphan,
            "manifest_appeared": manifest20,
            "conveyor_moved": promoter.last_step != before, "ok": kill_ok}

        # phase 3: kill the canary replica mid-promotion (SIGKILL when the
        # replica is a worker child) -> immediate canary_died rollback
        wait_for(lambda: healthy_replicas() >= 2, 30.0)
        hold = promoter.min_shadow
        promoter.min_shadow = 10 ** 6  # pin the canary open for the kill
        killed_via = None
        try:
            proc = spawn([{"step": 30, "ckpt": good2, "val_loss": 0.4}])
            proc.wait(timeout=120)
            record["published"] += 1

            def canary_up():
                c = promoter.status().get("canary")
                return c is not None and c["step"] == 30

            wait_for(canary_up, 20.0, poke=True)
            c = promoter.status().get("canary") or {}
            idx = c.get("replica")
            if idx is not None:
                rep = entry.replicas.replicas[idx]
                if getattr(rep, "_ckpt_lock", None) is not None:
                    serve_faults.kill9_replica(registry, model, idx)
                    killed_via = "kill9"
                else:
                    serve_faults.kill_replica(registry, model, idx)
                    killed_via = "kill"
            wait_for(lambda: outcome_for(30), 30.0)
        finally:
            promoter.min_shadow = hold
        o3 = dict(outcome_for(30) or {})
        o3["killed_via"] = killed_via
        record["phases"]["canary_kill"] = o3
        canary_ok = (o3.get("outcome") == "rolled_back"
                     and o3.get("reason") == "canary_died")

        # phase 4: drift-injected candidate auto-rolls back on the gauge
        wait_for(lambda: healthy_replicas() >= 2, 30.0)
        proc = spawn([{"step": 40, "ckpt": drifted, "val_loss": 0.1}])
        proc.wait(timeout=120)
        record["published"] += 1
        wait_for(lambda: outcome_for(40), 40.0, poke=True)
        o4 = dict(outcome_for(40) or {})
        record["phases"]["drift"] = o4
        drift_ok = (o4.get("outcome") == "rolled_back"
                    and o4.get("reason") == "drift")

        # phase-4's publisher swept phase-2's orphan on its way in
        record["tmp_swept"] = not any(".tmp." in f
                                      for f in os.listdir(watch))
        rz = readyz()
        record["readyz"] = rz.get("promote")
        coherent = bool((rz.get("promote") or {}).get("fleet_coherent"))
        record["status"] = promoter.status()
        record["ok"] = bool(promote_ok and kill_ok and canary_ok
                            and drift_ok and record["tmp_swept"]
                            and coherent)
        obs.event("chaos/promote_drill", ok=record["ok"],
                  published=record["published"],
                  phases={k: {kk: v.get(kk) for kk in ("outcome", "reason",
                                                       "ok")}
                          for k, v in record["phases"].items()})
    except Exception as exc:
        record["error"] = repr(exc)
    finally:
        for p in children:
            if p.poll() is None:
                try:
                    p.kill()
                except Exception:
                    pass


# ---- replay -----------------------------------------------------------------

def replay(base_url: str, plan, offsets, timeout_s: float,
           max_retries: int = 3):
    """Fire the plan open-loop; returns per-request result dicts
    ``{cls, status, ms, rid, retries}`` (status -1 = transport error) and
    wall_s. A 429/503 carrying Retry-After is retried after honoring the
    header (capped at 5 s per wait, ``max_retries`` attempts), so a
    failover blip shows up as latency, not lost work."""
    import urllib.error
    import urllib.request

    results = [None] * len(plan)

    def post(i, item):
        t_req = time.perf_counter()
        status, echoed, retries = -1, None, 0
        while True:
            req = urllib.request.Request(
                base_url.rstrip("/") + item["path"], data=item["body"],
                headers={"Content-Type": "application/json",
                         "X-Request-Id": item["rid"]},
                method="POST")
            try:
                with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                    status = int(resp.status)
                    echoed = resp.headers.get("X-Request-Id")
                break
            except urllib.error.HTTPError as e:
                status = int(e.code)
                echoed = e.headers.get("X-Request-Id")
                after = e.headers.get("Retry-After")
                if status in (429, 503) and after and retries < max_retries:
                    try:
                        wait = min(max(float(after), 0.0), 5.0)
                    except ValueError:
                        wait = 0.5
                    retries += 1
                    time.sleep(wait)
                    continue
                break
            except Exception:
                break
        results[i] = {"cls": item["cls"], "phase": item.get("phase"),
                      "status": status,
                      "ms": (time.perf_counter() - t_req) * 1e3,
                      "rid": echoed or item["rid"], "retries": retries}

    threads = []
    t0 = time.perf_counter()
    for k, (item, off) in enumerate(zip(plan, offsets)):
        delay = (t0 + off) - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        t = threading.Thread(target=post, args=(k, item), daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=timeout_s + 60.0)
    wall = time.perf_counter() - t0
    for i, item in enumerate(plan):   # a thread that never returned = error
        if results[i] is None:
            results[i] = {"cls": item["cls"], "phase": item.get("phase"),
                          "status": -1, "ms": timeout_s * 1e3,
                          "rid": item["rid"], "retries": 0}
    return results, wall


def scrape_metrics(base_url: str, timeout: float = 10.0) -> str:
    import urllib.request

    try:
        with urllib.request.urlopen(base_url.rstrip("/") + "/metrics",
                                    timeout=timeout) as resp:
            return resp.read().decode()
    except Exception:
        return ""


# ---- scoring ----------------------------------------------------------------

def class_stats(results):
    """Per-class {count, ok, p50_ms, p99_ms} + the overall p50/p99 over
    successful requests."""
    from distegnn_tpu.obs.metrics import percentile

    classes = {}
    ok_all = []
    for cls in CLASSES:
        rows = [r for r in results if r["cls"] == cls]
        if not rows:
            continue
        ok = sorted(r["ms"] for r in rows if 200 <= r["status"] < 400)
        ok_all.extend(ok)
        classes[cls] = {
            "count": len(rows),
            "ok": len(ok),
            "p50_ms": round(percentile(ok, 50), 3) if ok else None,
            "p99_ms": round(percentile(ok, 99), 3) if ok else None,
        }
    ok_all.sort()
    p50 = round(percentile(ok_all, 50), 3) if ok_all else None
    p99 = round(percentile(ok_all, 99), 3) if ok_all else None
    return classes, p50, p99


def phase_stats(results, spec=None):
    """Per-phase latency summary for profiled runs: overall AND
    interactive-only (predict+session) p50/p99, plus — when a spec is
    given — a per-phase SLO verdict over the phase's own route stats, so
    the BENCH line proves interactive latency held through EVERY load
    phase, not merely on average."""
    from distegnn_tpu.obs import slo as slomod
    from distegnn_tpu.obs.metrics import percentile

    order, rows_by = [], {}
    for r in results:
        phase = r.get("phase")
        if phase is None:
            continue
        if phase not in rows_by:
            order.append(phase)
            rows_by[phase] = []
        rows_by[phase].append(r)
    out = {}
    for phase in order:
        rows = rows_by[phase]
        ok = sorted(r["ms"] for r in rows if 200 <= r["status"] < 400)
        inter = sorted(r["ms"] for r in rows
                       if r["cls"] in ("predict", "session")
                       and 200 <= r["status"] < 400)
        rec = {
            "count": len(rows),
            "ok": len(ok),
            "p50_ms": round(percentile(ok, 50), 3) if ok else None,
            "p99_ms": round(percentile(ok, 99), 3) if ok else None,
            "interactive_p50_ms": (round(percentile(inter, 50), 3)
                                   if inter else None),
            "interactive_p99_ms": (round(percentile(inter, 99), 3)
                                   if inter else None),
        }
        if spec is not None:
            stats = {
                "error_rate": sum(1 for r in rows if r["status"] >= 500
                                  or r["status"] < 0) / len(rows),
                "shed_rate": sum(1 for r in rows
                                 if r["status"] == 429) / len(rows),
            }
            if inter:
                stats["predict_p50_ms"] = percentile(inter, 50)
                stats["predict_p99_ms"] = percentile(inter, 99)
            roll = sorted(r["ms"] for r in rows if r["cls"] == "rollout"
                          and 200 <= r["status"] < 400)
            if roll:
                stats["rollout_p50_ms"] = percentile(roll, 50)
                stats["rollout_p99_ms"] = percentile(roll, 99)
            rec["slo_pass"] = not slomod.breached(
                slomod.evaluate(spec, stats))
        out[phase] = rec
    return out


def slo_stats(results, prom_text: str):
    """Client-observed SLO stats vocabulary, merged with the scrape's
    server-side fill/session stats (the client can't see slot counters)."""
    from distegnn_tpu.obs import slo as slomod
    from distegnn_tpu.obs.metrics import percentile

    stats = {}
    # session requests ride the predict route; score them together
    by_route = {"predict": [r for r in results
                            if r["cls"] in ("predict", "session")],
                "rollout": [r for r in results if r["cls"] == "rollout"]}
    for route, rows in by_route.items():
        ok = sorted(r["ms"] for r in rows if 200 <= r["status"] < 400)
        if ok:
            stats[f"{route}_p50_ms"] = round(percentile(ok, 50), 3)
            stats[f"{route}_p99_ms"] = round(percentile(ok, 99), 3)
    if results:
        stats["error_rate"] = round(
            sum(1 for r in results if r["status"] >= 500
                or r["status"] < 0) / len(results), 6)
        stats["shed_rate"] = round(
            sum(1 for r in results if r["status"] == 429) / len(results), 6)
    scraped = slomod.stats_from_prometheus(prom_text) if prom_text else {}
    for key in ("batch_fill", "session_hit_rate"):
        if key in scraped:
            stats[key] = scraped[key]
    return stats


def load_slo_spec(args, cfg):
    from distegnn_tpu.obs import slo as slomod

    if args.slo:
        return slomod.SLOSpec.from_file(args.slo)
    sl = cfg.get("slo") if cfg is not None else None
    if sl and sl.get("enable", True):
        return slomod.SLOSpec.from_mapping(dict(sl))
    return slomod.SLOSpec()


# ---- entry ------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="mixed-traffic open-loop replay against a live gateway")
    ap.add_argument("--url", type=str, default=None,
                    help="base URL of a running gateway (default: boot an "
                         "in-process one and drive it over its socket)")
    ap.add_argument("--config_path", type=str, default=None,
                    help="YAML config for the in-process gateway / SLO spec")
    ap.add_argument("--requests", type=int, default=48)
    ap.add_argument("--rate", type=float, default=100.0,
                    help="mean arrival rate during ON phases, req/s")
    ap.add_argument("--mix", type=str,
                    default="predict=0.6,session=0.3,rollout=0.1",
                    help="class=weight list over predict/session/rollout")
    ap.add_argument("--sizes", type=str, default="24,48,96,192",
                    help="ladder-rung node counts the size tail draws from")
    ap.add_argument("--tail", type=float, default=1.5,
                    help="power-law exponent: rung k drawn with weight "
                         "1/(k+1)^tail (bigger = thinner tail)")
    ap.add_argument("--burst-on-s", type=float, default=0.5,
                    help="mean length of an ON burst, seconds")
    ap.add_argument("--burst-off-s", type=float, default=0.2,
                    help="mean OFF gap between bursts; 0 = pure Poisson")
    ap.add_argument("--sessions", type=int, default=4,
                    help="sticky session-id pool size for the session class")
    ap.add_argument("--rollout-steps", type=int, default=4)
    ap.add_argument("--seed", type=int, default=47)
    ap.add_argument("--timeout-s", type=float, default=300.0,
                    help="per-request client timeout")
    ap.add_argument("--max-batch", type=int, default=None,
                    help="override serve.max_batch (in-process gateway only)")
    ap.add_argument("--replicas", type=int, default=None,
                    help="override serve.replicas (in-process gateway only)")
    ap.add_argument("--workers", type=str, default=None,
                    choices=("thread", "process"),
                    help="override serve.workers (in-process gateway only): "
                         "'process' runs each replica in its own worker "
                         "child behind IPC supervision")
    ap.add_argument("--chaos", type=str, default=None,
                    help="serving fault schedule, e.g. 'kill@0.3:replica=0;"
                         "swap@1.0:ckpt=/p/b.ckpt' (in-process gateway only)")
    ap.add_argument("--promote", action="store_true",
                    help="run the continuous-promotion chaos drill alongside "
                         "the replay: publisher child processes land good / "
                         "drift candidates into the conveyor, the trainer is "
                         "SIGKILLed mid-publish, and the canary replica is "
                         "killed mid-promotion (in-process gateway only; "
                         "forces >= 3 replicas unless --replicas is given)")
    ap.add_argument("--publish-child", type=str, default=None,
                    help=argparse.SUPPRESS)  # internal: the drill's trainer
    ap.add_argument("--profile", type=str, default=None,
                    choices=tuple(PROFILES),
                    help="phased load shape (steady|ramp|spike10x); "
                         "replaces the burst modulator and adds per-phase "
                         "p50/p99 + SLO verdicts to the BENCH record")
    ap.add_argument("--autoscale", type=str, default=None,
                    help="enable the replica autoscaler on the in-process "
                         "gateway: 'on' or serve.autoscale overrides as "
                         "'key=val,...' (e.g. 'max_replicas=3,queue_high=2')")
    ap.add_argument("--scale-settle-s", type=float, default=0.0,
                    help="after the replay, wait up to this long for the "
                         "autoscaler to shrink back to min_replicas before "
                         "drain (one run then shows the full 1->N->1 cycle)")
    ap.add_argument("--max-retries", type=int, default=3,
                    help="client retries per request on 429/503 that carry "
                         "Retry-After (0 disables)")
    ap.add_argument("--slo", type=str, default=None,
                    help="SLO spec file; default: the config's slo: section")
    ap.add_argument("--obs-dir", type=str, default="logs/traffic_gen",
                    help="event sink dir (<dir>/obs/events.jsonl); '' off")
    args = ap.parse_args(argv)
    if args.publish_child:
        return publish_child_main(args.publish_child)
    args.size_list = [int(s) for s in args.sizes.split(",") if s.strip()]
    if not args.size_list:
        print("traffic_gen: --sizes is empty", file=sys.stderr)  # noqa: obs-print
        return 2
    try:
        chaos_events = parse_chaos(args.chaos) if args.chaos else []
    except ValueError as exc:
        print(f"traffic_gen: {exc}", file=sys.stderr)  # noqa: obs-print
        return 2
    if chaos_events and args.url:
        print("traffic_gen: --chaos needs the in-process gateway (the "
              "injectors reach into the live registry); drop --url",
              file=sys.stderr)  # noqa: obs-print
        return 2
    if args.promote and args.url:
        print("traffic_gen: --promote needs the in-process gateway (the "
              "drill reaches into the live promoter); drop --url",
              file=sys.stderr)  # noqa: obs-print
        return 2
    if args.autoscale:
        if args.url:
            print("traffic_gen: --autoscale configures the in-process "
                  "gateway; drop --url (a remote gateway scales itself)",
                  file=sys.stderr)  # noqa: obs-print
            return 2
        try:
            parse_scale(args.autoscale)
        except ValueError as exc:
            print(f"traffic_gen: {exc}", file=sys.stderr)  # noqa: obs-print
            return 2

    from distegnn_tpu import obs
    from distegnn_tpu.config import ConfigDict, _DEFAULTS, load_config
    from distegnn_tpu.obs import slo as slomod

    cfg = (load_config(args.config_path) if args.config_path
           else ConfigDict(_DEFAULTS))
    if args.obs_dir:
        obs.configure_from_config(cfg, args.obs_dir,
                                  tags={"run": "traffic_gen"})

    if args.promote:
        # drill-tuned conveyor knobs: tee every request, a small shadow
        # quorum, and a fast scan so the whole lifecycle fits one replay
        import tempfile

        pm = cfg.promote
        pm.enable = True
        pm.publish = False
        # always a FRESH conveyor dir: leftovers from a previous run would
        # be scanned as live candidates by this run's promoter
        root = args.obs_dir or None
        if root:
            os.makedirs(root, exist_ok=True)
        pm.watch_dir = tempfile.mkdtemp(prefix="promote_watch_", dir=root)
        pm.interval_s = 0.05
        pm.shadow_sample = 1.0
        pm.min_shadow = 3
        pm.gate_timeout_s = 20.0
        # CPU batch-shape compiles run seconds; a serving-tuned sub-second
        # timeout would 504 the warm-cache misses and trip the SLO gate on
        # compile noise rather than candidate quality
        cfg.serve.request_timeout_ms = max(
            float(cfg.serve.request_timeout_ms), 60_000.0)
        if args.replicas is None:
            # one replica to quarantine as the canary, two staying live so
            # the canary-kill phase still leaves a real slice to pick next
            args.replicas = max(3, int(cfg.serve.replicas))

    gw = server = registry = None
    if args.url:
        base_url = args.url
        models, rollout_models = discover_models(base_url)
        if not models:
            print(f"traffic_gen: {base_url} serves no models",
                  file=sys.stderr)  # noqa: obs-print
            return 2
    else:
        gw, server, registry = boot_gateway(args, cfg)
        base_url = gw.url("")
        models = registry.names()
        rollout_models = [n for n, e in registry.items() if e.rollout_enabled]

    feat_nf = int(cfg.model.node_feat_nf)
    edge_attr_nf = int(cfg.model.edge_attr_nf)
    plan, offsets = build_plan(args, models, rollout_models, feat_nf,
                               edge_attr_nf)
    obs.event("traffic/start", requests=args.requests, rate=args.rate,
              mix=args.mix, sizes=args.size_list, models=models,
              burst_on_s=args.burst_on_s, burst_off_s=args.burst_off_s,
              target=("remote" if args.url else "inproc"))

    chaos_record: list = []
    chaos_thread = None
    if chaos_events:
        obs.event("chaos/plan", events=[{"action": e["action"],
                                         "at_s": e["at"]}
                                        for e in chaos_events])
        chaos_thread = threading.Thread(
            target=run_chaos,
            args=(chaos_events, time.perf_counter(), registry, base_url,
                  models, feat_nf, edge_attr_nf, chaos_record),
            name="tg-chaos", daemon=True)
        chaos_thread.start()
    promote_record = None
    promote_thread = None
    if args.promote:
        promote_record = {}
        promote_thread = threading.Thread(
            target=run_promote_drill,
            args=(args, gw, registry, models[0], base_url, feat_nf,
                  edge_attr_nf, promote_record),
            name="tg-promote", daemon=True)
        promote_thread.start()
    results, wall = replay(base_url, plan, offsets, args.timeout_s,
                           max_retries=args.max_retries)
    if chaos_thread is not None:
        chaos_thread.join(timeout=args.timeout_s + 60.0)
    if promote_thread is not None:
        promote_thread.join(timeout=300.0)
    scale_state = None
    if gw is not None and gw.autoscaler.enable:
        # hold the gateway open while the calm-streak logic walks the fleet
        # back down, so this run's event stream carries scale_down too.
        # calm_rounds >= 1 guards the at-min check: it is 0 while an
        # up-trigger is firing or a grow (warmup included) is still inside
        # the tick lock, so the loop can't slip out mid-scale-up
        deadline = time.perf_counter() + max(0.0, args.scale_settle_s)
        while time.perf_counter() < deadline:
            if all(s["replicas"] <= s["min"] and s["calm_rounds"] >= 1
                   for s in gw.autoscaler.status().values()):
                break
            time.sleep(0.25)
        scale_state = gw.autoscaler.status()
    prom_text = scrape_metrics(base_url)
    if gw is not None:
        gw.drain()
        server.join(timeout=30.0)
        gw.close()

    classes, p50, p99 = class_stats(results)
    completed = sum(1 for r in results if 200 <= r["status"] < 400)
    stats = slo_stats(results, prom_text)
    spec = load_slo_spec(args, cfg)
    slo_results = slomod.evaluate(spec, stats)
    phases = phase_stats(results, spec) if args.profile else None
    print(slomod.verdict_table(slo_results, source="traffic_gen"),
          end="", file=sys.stderr)  # noqa: obs-print

    rec = {
        "metric": "traffic_p99_ms",
        "value": p99,
        "unit": "ms",
        "vs_baseline": None,
        "p50_ms": p50,
        "classes": classes,
        "requests": args.requests,
        "completed": completed,
        "throughput_rps": round(completed / max(wall, 1e-9), 3),
        "shed": round(sum(1 for r in results if r["status"] == 429)
                      / max(len(results), 1), 6),
        "errors": sum(1 for r in results if r["status"] >= 500
                      or r["status"] < 0),
        "lost": sum(1 for r in results if r["status"] < 0),
        "retries_total": sum(r.get("retries", 0) for r in results),
        "chaos": chaos_record or None,
        "promote": promote_record,
        "profile": args.profile,
        "phases": phases,
        "autoscale": scale_state,
        "batch_fill": stats.get("batch_fill"),
        "session_hit_rate": stats.get("session_hit_rate"),
        "offered_rate": args.rate,
        "mix": parse_mix(args.mix),
        "sizes": args.size_list,
        "models": models,
        "wall_s": round(wall, 4),
        "platform": __import__("jax").default_backend(),
        "slo": slomod.results_json(slo_results),
    }
    print(json.dumps(rec, sort_keys=True))
    obs.event("bench/result", **{k: v for k, v in rec.items()
                                 if k != "classes"}, classes=classes)

    tracer = obs.get_tracer()
    tracer.flush()
    w = getattr(tracer, "writer", None)
    if w is not None:
        print(f"obs: events at {w.path}; replay a request with "
              f"python scripts/obs_report.py {w.path} --request tg-"
              f"{args.seed}-0", file=sys.stderr, flush=True)  # noqa: obs-print
    return 0 if completed else 1


if __name__ == "__main__":
    raise SystemExit(main())
