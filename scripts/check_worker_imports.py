#!/usr/bin/env python
"""Lint: the serving worker child stays import-isolated.

``distegnn_tpu/serve/worker.py`` runs inside every worker child and is the
one module the parent's supervision stack must be able to trust blindly:

  - Its MODULE-LEVEL imports must be stdlib-only. The child's argparse /
    framing / signal plumbing has to come up even when jax or the model
    zoo is broken — a child that dies during ``import worker`` can't
    report the failure over the IPC channel, it just looks like a spawn
    timeout. Heavy imports (jax, the engine, obs) happen lazily inside
    the init handshake, where a failure is caught and sent back typed.
  - It must NEVER import the parent-side serving stack —
    ``serve.transport``, ``serve.registry``, ``serve.supervisor`` — at
    any level. The worker is the LEAF of the supervision tree; a child
    that could instantiate a registry or supervisor could recursively
    spawn workers, and a transport import would drag the HTTP stack into
    every child. The allowed surface is the engine side only
    (``serve.buckets``, ``serve.engine``, ``engine_with_params_from_config``).

Checked with ast (no regex false-positives on strings/comments), covering
lazy in-function imports too. Wired into tier-1 via
tests/test_worker.py::test_worker_import_isolation. Exit codes: 0 clean,
1 violations (one ``path:line: reason`` per offense).
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "distegnn_tpu", "serve", "worker.py")

# parent-side supervision stack: banned at ANY import depth in the child
_BANNED_MODULES = (
    "distegnn_tpu.serve.transport",
    "distegnn_tpu.serve.registry",
    "distegnn_tpu.serve.supervisor",
)
# lazy re-exports on the serve package namespace that resolve to the same
# banned modules: `from distegnn_tpu.serve import ModelRegistry` is the
# registry import wearing a different hat
_BANNED_SERVE_ATTRS = frozenset({
    "Gateway", "ModelRegistry", "ModelEntry", "ReplicaSupervisor",
    "ReplicaSet", "Replica", "WorkerReplica", "WorkerQueue",
})


def _stdlib_names() -> frozenset:
    names = getattr(sys, "stdlib_module_names", None)
    if names is None:  # < 3.10: close enough for the modules worker.py uses
        names = {"argparse", "atexit", "base64", "collections", "contextlib",
                 "dataclasses", "functools", "io", "itertools", "json",
                 "logging", "math", "os", "pickle", "re", "signal", "socket",
                 "struct", "subprocess", "sys", "tempfile", "threading",
                 "time", "traceback", "types", "typing", "zlib",
                 "__future__"}
    return frozenset(names)


def _imported_modules(node):
    """Module names an Import/ImportFrom pulls in (ImportFrom -> the module;
    Import -> each dotted name)."""
    if isinstance(node, ast.Import):
        return [a.name for a in node.names]
    if isinstance(node, ast.ImportFrom):
        return [node.module or ""]
    return []


def find_violations(path: str = WORKER):
    """[(lineno, reason)] for every import-isolation breach in the file."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    stdlib = _stdlib_names()
    out = []

    # 1) module level: stdlib only
    for node in tree.body:
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        for mod in _imported_modules(node):
            top = mod.split(".")[0]
            if top not in stdlib:
                out.append((node.lineno,
                            f"module-level import of {mod!r} is not stdlib "
                            "— the child must come up without it; import "
                            "lazily inside the init handshake"))

    # 2) anywhere: never the parent-side supervision stack
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for mod in _imported_modules(node):
                if any(mod == b or mod.startswith(b + ".")
                       for b in _BANNED_MODULES):
                    out.append((node.lineno,
                                f"import of parent-side module {mod!r} "
                                "(the worker is the supervision leaf)"))
        if (isinstance(node, ast.ImportFrom)
                and node.module == "distegnn_tpu.serve"):
            for alias in node.names:
                if alias.name in _BANNED_SERVE_ATTRS:
                    out.append((node.lineno,
                                f"'from distegnn_tpu.serve import "
                                f"{alias.name}' reaches the parent-side "
                                "stack through the package namespace"))
    return sorted(out)


def main(argv=None) -> int:
    rel = os.path.relpath(WORKER, REPO)
    violations = find_violations()
    for lineno, reason in violations:
        print(f"{rel}:{lineno}: {reason}")
    if violations:
        print(f"\n{len(violations)} worker import-isolation breach(es); "
              "see scripts/check_worker_imports.py docstring")
        return 1
    print("check_worker_imports: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
