"""Machine roofline probe: what can THIS chip (through THIS tunnel) actually
sustain, and how close is the train step to that ceiling?

Motivation (BASELINE.md round-4 hardware session): the step's effective
bandwidth (~57 GB/s from the [E,64] copy reference) is far below the v5e
spec sheet (~819 GB/s). Before investing in deeper fusion we need to know
whether that gap is (a) per-dispatch tunnel overhead, (b) the virtualized
chip's real memory ceiling, or (c) inefficiency in our kernels. The probe:

  1. copy at 4 sizes x {f32, bf16}: the slope of time-vs-bytes is the real
     streaming bandwidth; the intercept is fixed overhead per executable.
  2. matmul [8192,512]x[512,512] bf16 and f32: the MXU ceiling.
  3. gather / sorted-scatter at bench shape: achievable for OUR access
     patterns, as a fraction of the copy ceiling.
  4. an analytic byte count of the plain+fuse_agg train step (fwd+bwd
     [E,.] streams) -> step-time floor at the measured copy bandwidth,
     printed next to the measured step time (profile_step.py).

Artifact: --json <path> (committed under docs/artifacts/). Run on the real
chip via the hw_session queue; CPU runs are labeled and land nowhere.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

E, N, H = 1_639_080, 113_140, 64


def timed(fn, *args, warmup=2, steps=10):
    """Fetch-synced timing (block_until_ready under-reports on axon)."""
    import jax.numpy as jnp

    def sync(o):
        while isinstance(o, (tuple, list)):
            o = o[0]
        np.asarray(jnp.ravel(o)[0])

    out = None
    for _ in range(warmup):
        out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / steps * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", type=str, default=None)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    plat = dev.platform
    out: dict = {"platform": plat, "device": str(dev.device_kind)}
    rng = np.random.default_rng(0)

    # ---- 1. copy: time vs bytes -> slope (bandwidth) + intercept (overhead)
    copy_pts = []
    for dt_name, dt in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
        for rows in (E // 8, E // 4, E // 2, E):
            x = jnp.asarray(rng.normal(size=(rows, H)).astype(np.float32)).astype(dt)
            f = jax.jit(lambda d: d * 1.0001)
            ms = timed(f, x)
            bytes_moved = 2 * rows * H * x.dtype.itemsize  # read + write
            copy_pts.append({"dtype": dt_name, "rows": rows, "ms": ms,
                             "GB": bytes_moved / 1e9})
            print(f"copy {dt_name:4s} rows={rows:>8d}  {ms:8.2f} ms  "
                  f"({bytes_moved / 1e9 / (ms / 1e3):6.1f} GB/s apparent)")
    # least-squares slope/intercept over all points (bytes vs ms)
    xs = np.array([p["GB"] for p in copy_pts])
    ys = np.array([p["ms"] for p in copy_pts])
    slope, intercept = np.polyfit(xs, ys, 1)  # ms per GB, ms
    bw_gbps = 1e3 / slope if slope > 0 else float("nan")
    out["copy_points"] = copy_pts
    out["copy_stream_GBps"] = round(bw_gbps, 1)
    out["copy_overhead_ms"] = round(float(intercept), 3)
    print(f"\ncopy roofline: {bw_gbps:.1f} GB/s streaming, "
          f"{intercept:.2f} ms fixed overhead per dispatch")

    # ---- 2. MXU ceiling
    for dt_name, dt in (("bf16", jnp.bfloat16), ("f32", jnp.float32)):
        a = jnp.asarray(rng.normal(size=(8192, 512)).astype(np.float32)).astype(dt)
        b = jnp.asarray(rng.normal(size=(512, 512)).astype(np.float32)).astype(dt)
        # chain 32 dependent matmuls in one executable so dispatch overhead
        # amortizes and XLA cannot elide any of them
        @jax.jit
        def chain(a, b):
            for _ in range(32):
                a = jnp.dot(a, b, preferred_element_type=jnp.float32).astype(dt)
            return a
        ms = timed(chain, a, b)
        tflops = 32 * 2 * 8192 * 512 * 512 / (ms / 1e3) / 1e12
        out[f"matmul_{dt_name}_TFLOPs"] = round(tflops, 2)
        print(f"matmul {dt_name:4s}: {tflops:7.2f} TFLOP/s")

    # ---- 3. our access patterns at bench shape
    ids_np = np.sort(rng.integers(0, N, size=E)).astype(np.int32)
    ids = jnp.asarray(ids_np)
    for dt_name, dt in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
        xe = jnp.asarray(rng.normal(size=(E, H)).astype(np.float32)).astype(dt)
        xn = jnp.asarray(rng.normal(size=(N, H)).astype(np.float32)).astype(dt)
        g_ms = timed(jax.jit(lambda d, i: d[i]), xn, ids)
        s_ms = timed(jax.jit(lambda d, i: jnp.zeros((N, H), jnp.float32).at[i].add(
            d, indices_are_sorted=True)), xe, ids)
        # effective bandwidth relative to the bytes each op MUST move
        g_bytes = (E + N) * H * xn.dtype.itemsize + E * 4
        s_bytes = E * H * xe.dtype.itemsize + N * H * 4 + E * 4
        out[f"gather_{dt_name}_ms"] = round(g_ms, 2)
        out[f"scatter_{dt_name}_ms"] = round(s_ms, 2)
        out[f"gather_{dt_name}_GBps"] = round(g_bytes / 1e9 / (g_ms / 1e3), 1)
        out[f"scatter_{dt_name}_GBps"] = round(s_bytes / 1e9 / (s_ms / 1e3), 1)
        print(f"gather  {dt_name:4s}: {g_ms:7.2f} ms ({out[f'gather_{dt_name}_GBps']:6.1f} GB/s eff)")
        print(f"scatter {dt_name:4s}: {s_ms:7.2f} ms ({out[f'scatter_{dt_name}_GBps']:6.1f} GB/s eff)")

    # ---- 4. analytic step bytes (plain + fuse_agg + hoisted phi_e, L=4,
    # bf16 MLP streams, f32 geometry/aggregation) vs the measured ceiling.
    # Forward, per layer, [E,.] streams only (node-level [N,.] terms are
    # ~7% of E-level and ignored):
    #   gathers: pre_h rows+cols (2x[E,H] bf16), x rows+cols (2x[E,3] f32)
    #   phi_e dense2: read [E,H] bf16, write [E,H] bf16
    #   phi_x: read [E,H] bf16, write [E,1]; trans [E,3] f32 write
    #   packed agg: read [E,H+4] f32 (or bf16 with agg_dtype)
    f32, bf16 = 4, 2
    fwd_e_bytes = (2 * E * H * bf16 + 2 * E * 3 * f32
                   + 2 * E * H * bf16
                   + E * H * bf16 + E * 3 * f32
                   + E * (H + 4) * f32)
    # Backward without remat: re-read every saved [E,.] activation once on
    # the transpose path, plus weight-grad matmuls re-reading [E,H] inputs,
    # plus cotangent streams mirroring the forward writes. Empirical factor
    # ~2x forward traffic is the standard lower bound; we report both.
    L = 4
    step_bytes_lo = L * fwd_e_bytes * (1 + 2)
    floor_lo_ms = step_bytes_lo / (bw_gbps * 1e9) * 1e3
    out["analytic_fwd_E_bytes_per_layer"] = fwd_e_bytes
    out["analytic_step_bytes_3x"] = step_bytes_lo
    out["analytic_step_floor_ms_at_copy_bw"] = round(floor_lo_ms, 1)
    print(f"\nanalytic step floor (L=4, fwd+2x bwd E-streams at copy BW): "
          f"{floor_lo_ms:.1f} ms vs measured ~553-617 ms (profile/bench "
          f"2026-08-02)")

    if args.json and plat != "cpu":
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)
        print(f"wrote {args.json}")
    elif args.json:
        print(f"cpu run: NOT writing {args.json} (hardware artifact)")


if __name__ == "__main__":
    main()
