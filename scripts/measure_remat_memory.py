"""Remat memory-scaling evidence (VERDICT r3 #8).

FastEGNN's ``remat`` flag claims to trade recompute FLOPs for the O(E*H)
per-layer activation memory that bounds nodes/chip
(distegnn_tpu/models/fast_egnn.py). Two measurements:

1. PRIMARY (backend-independent, runs anywhere): the byte total of the
   ``jax.vjp`` closure — exactly the residual arrays autodiff saves between
   forward and backward. This is the memory rematerialization eliminates.
2. ``--xla-temp``: ``compiled.memory_analysis().temp_size_in_bytes`` of the
   jitted grad. CAVEAT, measured 2026-08-01: **XLA:CPU's buffer assignment
   reports identical temp with and without remat** (a minimal
   checkpoint-layer repro shows byte-identical arenas, i.e. the CPU
   pipeline undoes or ignores the rematerialization), so this mode is only
   meaningful on TPU — queued for a tunnel window alongside the bench race.

Usage:
  python scripts/measure_remat_memory.py [--nodes 20000 50000] [--json out]
"""

from __future__ import annotations

import argparse
import json

import numpy as np


def _model_and_loss(n_nodes: int, remat: bool, seg: str):
    import jax

    import bench
    from distegnn_tpu.models.fast_egnn import FastEGNN

    bench.N_NODES = n_nodes
    rng = np.random.default_rng(0)
    batch, n_edges = bench.make_fluid_batch(rng)
    model = FastEGNN(node_feat_nf=3, node_attr_nf=2, edge_attr_nf=2,
                     hidden_nf=64, virtual_channels=3, n_layers=4,
                     compute_dtype="bf16", segment_impl=seg, remat=remat)
    params = model.init(jax.random.PRNGKey(0), batch)

    def loss(p):
        loc, X = model.apply(p, batch)
        return ((loc - batch.target) ** 2 * batch.node_mask[..., None]).sum()

    return params, loss, n_edges


def vjp_residual_bytes(n_nodes: int, remat: bool, seg: str = "scatter") -> dict:
    import jax

    params, loss, n_edges = _model_and_loss(n_nodes, remat, seg)
    _, f_vjp = jax.vjp(loss, params)
    leaves = [x for x in jax.tree.leaves(f_vjp) if hasattr(x, "nbytes")]
    return {"n_nodes": n_nodes, "n_edges": n_edges, "remat": remat,
            "residual_bytes": int(sum(x.nbytes for x in leaves)),
            "residual_arrays": len(leaves)}


def xla_temp_bytes(n_nodes: int, remat: bool, seg: str = "scatter") -> dict:
    import jax

    params, loss, n_edges = _model_and_loss(n_nodes, remat, seg)
    ma = jax.jit(jax.grad(loss)).lower(params).compile().memory_analysis()
    return {"n_nodes": n_nodes, "n_edges": n_edges, "remat": remat,
            "temp_bytes": int(ma.temp_size_in_bytes)}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, nargs="+", default=[20000, 50000])
    ap.add_argument("--seg", default="scatter")
    ap.add_argument("--xla-temp", action="store_true",
                    help="also report jitted-grad XLA temp (TPU-meaningful)")
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    import jax

    rows = []
    for n in args.nodes:
        for remat in (False, True):
            r = vjp_residual_bytes(n, remat, args.seg)
            if args.xla_temp:
                r.update(xla_temp_bytes(n, remat, args.seg))
            rows.append(r)
            print(f"N={n:>7} remat={str(remat):5} "
                  f"residuals={r['residual_bytes'] / 2**30:.3f} GiB "
                  f"({r['residual_arrays']} arrays)"
                  + (f" xla_temp={r['temp_bytes'] / 2**30:.3f} GiB"
                     if args.xla_temp else ""))
        off, on = rows[-2]["residual_bytes"], rows[-1]["residual_bytes"]
        print(f"          -> remat residual reduction {off / max(on, 1):.1f}x")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"backend": jax.default_backend(),
                       "method": "jax.vjp closure bytes (saved residuals); "
                                 "xla temp only meaningful on TPU (see "
                                 "module docstring)",
                       "rows": rows}, f, indent=1)


if __name__ == "__main__":
    main()
