"""Micro-benchmarks for the hot aggregation/matmul primitives at LargeFluid
shape — decides which segment-op lowering and compute dtype the model uses.

Variants:
  scatter_unsorted   zeros.at[ids].add(x) with shuffled ids (round-1 behavior)
  scatter_sorted     same op, ids sorted ascending (what pad_graphs now emits)
  segsum_flag        jax.ops.segment_sum(indices_are_sorted=True)
  gather             the read side (x[ids]) for comparison
  matmul_f32 / bf16  the edge-MLP matmul [E,128]x[128,64]
"""

from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")

E, N, H = 1_639_080, 113_140, 64


def timed(fn, *args, warmup=2, steps=10):
    """block_until_ready alone under-reports on the axon tunnel; force a
    1-element device->host fetch of the final result instead."""
    import jax.numpy as jnp
    import numpy as np

    def sync(o):
        np.asarray(jnp.ravel(o)[0])

    for _ in range(warmup):
        out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / steps * 1e3


def main():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    ids_sorted = np.sort(rng.integers(0, N, size=E)).astype(np.int32)
    ids_shuf = rng.permutation(ids_sorted).astype(np.int32)
    x = jnp.asarray(rng.normal(size=(E, H)).astype(np.float32))
    a = jnp.asarray(rng.normal(size=(E, 2 * H)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(2 * H, H)).astype(np.float32))
    ids_s = jnp.asarray(ids_sorted)
    ids_u = jnp.asarray(ids_shuf)

    f_scatter = jax.jit(lambda d, i: jnp.zeros((N, H), d.dtype).at[i].add(d))
    f_segsum_flag = jax.jit(lambda d, i: jax.ops.segment_sum(
        d, i, num_segments=N, indices_are_sorted=True))
    f_gather = jax.jit(lambda d, i: d[i[:N]])
    f_mm = jax.jit(lambda d, k: d @ k)
    f_mm_bf16 = jax.jit(lambda d, k: (d.astype(jnp.bfloat16) @ k.astype(jnp.bfloat16)).astype(jnp.float32))

    print(f"scatter_unsorted   {timed(f_scatter, x, ids_u):8.2f} ms")
    print(f"scatter_sorted     {timed(f_scatter, x, ids_s):8.2f} ms")
    print(f"segsum_flag_sorted {timed(f_segsum_flag, x, ids_s):8.2f} ms")
    print(f"gather             {timed(f_gather, x, ids_s):8.2f} ms")
    print(f"matmul_f32         {timed(f_mm, a, w):8.2f} ms")
    print(f"matmul_bf16        {timed(f_mm_bf16, a, w):8.2f} ms")


if __name__ == "__main__":
    main()
