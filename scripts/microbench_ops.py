"""Micro-benchmarks for the hot aggregation/matmul primitives at LargeFluid
shape — decides which segment-op lowering and compute dtype the model uses.

Variants:
  scatter_unsorted   zeros.at[ids].add(x) with shuffled ids (round-1 behavior)
  scatter_sorted     same op, ids sorted ascending (what pad_graphs now emits)
  segsum_flag        jax.ops.segment_sum(indices_are_sorted=True)
  gather             the read side (x[ids]) for comparison
  matmul_f32 / bf16  the edge-MLP matmul [E,128]x[128,64]
  fused_edge_layer   the whole per-layer edge pipeline in ONE Pallas pass
                     (ops/edge_pipeline.py) — geometry + phi_e + coord gate +
                     all three aggregations; compare against the SUM of the
                     unfused primitives above to see the traffic it removes.
                     Off-TPU it runs interpret mode at a toy shape (the full
                     shape would take hours interpreted).
"""

from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")

E, N, H = 1_639_080, 113_140, 64


def timed(fn, *args, warmup=2, steps=10):
    """block_until_ready alone under-reports on the axon tunnel; force a
    1-element device->host fetch of the final result instead."""
    import jax.numpy as jnp
    import numpy as np

    def sync(o):
        np.asarray(jnp.ravel(o)[0])

    for _ in range(warmup):
        out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / steps * 1e3


def main():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    ids_sorted = np.sort(rng.integers(0, N, size=E)).astype(np.int32)
    ids_shuf = rng.permutation(ids_sorted).astype(np.int32)
    x = jnp.asarray(rng.normal(size=(E, H)).astype(np.float32))
    a = jnp.asarray(rng.normal(size=(E, 2 * H)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(2 * H, H)).astype(np.float32))
    ids_s = jnp.asarray(ids_sorted)
    ids_u = jnp.asarray(ids_shuf)

    f_scatter = jax.jit(lambda d, i: jnp.zeros((N, H), d.dtype).at[i].add(d))
    f_segsum_flag = jax.jit(lambda d, i: jax.ops.segment_sum(
        d, i, num_segments=N, indices_are_sorted=True))
    f_gather = jax.jit(lambda d, i: d[i[:N]])
    f_mm = jax.jit(lambda d, k: d @ k)
    f_mm_bf16 = jax.jit(lambda d, k: (d.astype(jnp.bfloat16) @ k.astype(jnp.bfloat16)).astype(jnp.float32))

    print(f"scatter_unsorted   {timed(f_scatter, x, ids_u):8.2f} ms")
    print(f"scatter_sorted     {timed(f_scatter, x, ids_s):8.2f} ms")
    print(f"segsum_flag_sorted {timed(f_segsum_flag, x, ids_s):8.2f} ms")
    print(f"gather             {timed(f_gather, x, ids_s):8.2f} ms")
    print(f"matmul_f32         {timed(f_mm, a, w):8.2f} ms")
    print(f"matmul_bf16        {timed(f_mm_bf16, a, w):8.2f} ms")
    fused_edge_bench(rng)


def fused_edge_bench(rng):
    import jax
    import jax.numpy as jnp

    from distegnn_tpu.ops.edge_pipeline import (EdgeWeights, build_edge_blocks,
                                                fused_edge_layer)

    block = 512
    on_tpu = jax.default_backend() == "tpu"
    n_pad = (-(-N // block) * block) if on_tpu else 3 * block
    nb = n_pad // block
    per_block = -(-E // nb)  # ceil: worst block's share of the edges
    epb = (-(-per_block // block) * block) if on_tpu else 3 * block
    # blocked layout built directly: block b owns epb row-local edge slots,
    # cols within one block of the row (always inside the 3-block window)
    rows, cols = [], []
    for b in range(nb):
        r = np.sort(rng.integers(b * block, (b + 1) * block, size=epb))
        c = np.clip(r + rng.integers(-block, block, size=epb), 0, n_pad - 1)
        rows.append(r)
        cols.append(c)
    row = jnp.asarray(np.concatenate(rows).astype(np.int32))
    col = jnp.asarray(np.concatenate(cols).astype(np.int32))
    e_tot = int(row.shape[0])
    attr = jnp.asarray(rng.normal(size=(e_tot, 2)).astype(np.float32))
    mask = jnp.ones((e_tot,), jnp.float32)
    row_t, col_l, kblk, scal = jax.jit(
        lambda r, c, a, m: build_edge_blocks(r, c, a, m, block=block,
                                             n_nodes=n_pad))(row, col, attr, mask)
    xc = jnp.asarray(rng.normal(size=(n_pad, 3)).astype(np.float32))
    hr = jnp.asarray(rng.normal(size=(n_pad, H)).astype(np.float32))
    hc = jnp.asarray(rng.normal(size=(n_pad, H)).astype(np.float32))
    wts = EdgeWeights(
        ws=jnp.asarray(rng.normal(size=(3, H)).astype(np.float32)),
        b1=jnp.zeros((1, H)), w2=jnp.asarray(rng.normal(size=(H, H)).astype(np.float32)),
        b2=jnp.zeros((1, H)), w3=jnp.asarray(rng.normal(size=(H, H)).astype(np.float32)),
        b3=jnp.zeros((1, H)), w4=jnp.asarray(rng.normal(size=(1, H)).astype(np.float32)))
    def run(*args):
        # scalar touching all three accumulators so none is DCE'd and the
        # timed() sync fetch stays 1 element
        t, cnt, ef = fused_edge_layer(*args, wts, block, "bf16")
        return t[0, 0] + cnt[0] + ef[0, 0]

    f = jax.jit(run)
    ms = timed(f, xc, hr, hc, row_t, col_l, kblk, scal)
    tag = "" if on_tpu else " (interpret, toy shape)"
    print(f"fused_edge_layer   {ms:8.2f} ms  [N={n_pad}, E={e_tot}]{tag}")


if __name__ == "__main__":
    main()
