"""Micro-benchmarks for the hot aggregation/matmul primitives at LargeFluid
shape — decides which segment-op lowering and compute dtype the model uses.

Variants:
  scatter_unsorted   zeros.at[ids].add(x) with shuffled ids (round-1 behavior)
  scatter_sorted     same op, ids sorted ascending (what pad_graphs now emits)
  segsum_flag        jax.ops.segment_sum(indices_are_sorted=True)
  gather             the read side (x[ids]) for comparison
  matmul_f32 / bf16  the edge-MLP matmul [E,128]x[128,64]
  fused_edge_layer   the whole per-layer edge pipeline in ONE Pallas pass
                     (ops/edge_pipeline.py) — geometry + phi_e + coord gate +
                     all three aggregations; compare against the SUM of the
                     unfused primitives above to see the traffic it removes.
                     Off-TPU it runs interpret mode at a toy shape (the full
                     shape would take hours interpreted).
  fused_egnn_stack   the cross-layer megakernel (ops/layer_pipeline.py): ALL
                     L layers in one Pallas grid with the graph VMEM-resident.
                     Runs at the VMEM-capped shape (the stack must fit the 16
                     MiB budget), and prints the analytic HBM-bytes-per-step
                     model for plain / fused / fused_stack at both the capped
                     and flagship shapes — the traffic ratio is the claim the
                     megakernel makes, so the numbers and their assumptions
                     are emitted next to the timing.
"""

from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")

E, N, H = 1_639_080, 113_140, 64


def timed(fn, *args, warmup=2, steps=10):
    """block_until_ready alone under-reports on the axon tunnel; force a
    1-element device->host fetch of the final result instead."""
    import jax.numpy as jnp
    import numpy as np

    def sync(o):
        np.asarray(jnp.ravel(o)[0])

    for _ in range(warmup):
        out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / steps * 1e3


def main():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    ids_sorted = np.sort(rng.integers(0, N, size=E)).astype(np.int32)
    ids_shuf = rng.permutation(ids_sorted).astype(np.int32)
    x = jnp.asarray(rng.normal(size=(E, H)).astype(np.float32))
    a = jnp.asarray(rng.normal(size=(E, 2 * H)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(2 * H, H)).astype(np.float32))
    ids_s = jnp.asarray(ids_sorted)
    ids_u = jnp.asarray(ids_shuf)

    f_scatter = jax.jit(lambda d, i: jnp.zeros((N, H), d.dtype).at[i].add(d))
    f_segsum_flag = jax.jit(lambda d, i: jax.ops.segment_sum(
        d, i, num_segments=N, indices_are_sorted=True))
    f_gather = jax.jit(lambda d, i: d[i[:N]])
    f_mm = jax.jit(lambda d, k: d @ k)
    f_mm_bf16 = jax.jit(lambda d, k: (d.astype(jnp.bfloat16) @ k.astype(jnp.bfloat16)).astype(jnp.float32))

    print(f"scatter_unsorted   {timed(f_scatter, x, ids_u):8.2f} ms")
    print(f"scatter_sorted     {timed(f_scatter, x, ids_s):8.2f} ms")
    print(f"segsum_flag_sorted {timed(f_segsum_flag, x, ids_s):8.2f} ms")
    print(f"gather             {timed(f_gather, x, ids_s):8.2f} ms")
    print(f"matmul_f32         {timed(f_mm, a, w):8.2f} ms")
    print(f"matmul_bf16        {timed(f_mm_bf16, a, w):8.2f} ms")
    fused_edge_bench(rng)
    fused_stack_bench(rng)
    tiled_exec_bench(rng)


def fused_edge_bench(rng):
    import jax
    import jax.numpy as jnp

    from distegnn_tpu.ops.edge_pipeline import (EdgeWeights, build_edge_blocks,
                                                fused_edge_layer)

    block = 512
    on_tpu = jax.default_backend() == "tpu"
    n_pad = (-(-N // block) * block) if on_tpu else 3 * block
    nb = n_pad // block
    per_block = -(-E // nb)  # ceil: worst block's share of the edges
    epb = (-(-per_block // block) * block) if on_tpu else 3 * block
    # blocked layout built directly: block b owns epb row-local edge slots,
    # cols within one block of the row (always inside the 3-block window)
    rows, cols = [], []
    for b in range(nb):
        r = np.sort(rng.integers(b * block, (b + 1) * block, size=epb))
        c = np.clip(r + rng.integers(-block, block, size=epb), 0, n_pad - 1)
        rows.append(r)
        cols.append(c)
    row = jnp.asarray(np.concatenate(rows).astype(np.int32))
    col = jnp.asarray(np.concatenate(cols).astype(np.int32))
    e_tot = int(row.shape[0])
    attr = jnp.asarray(rng.normal(size=(e_tot, 2)).astype(np.float32))
    mask = jnp.ones((e_tot,), jnp.float32)
    row_t, col_l, kblk, scal = jax.jit(
        lambda r, c, a, m: build_edge_blocks(r, c, a, m, block=block,
                                             n_nodes=n_pad))(row, col, attr, mask)
    xc = jnp.asarray(rng.normal(size=(n_pad, 3)).astype(np.float32))
    hr = jnp.asarray(rng.normal(size=(n_pad, H)).astype(np.float32))
    hc = jnp.asarray(rng.normal(size=(n_pad, H)).astype(np.float32))
    wts = EdgeWeights(
        ws=jnp.asarray(rng.normal(size=(3, H)).astype(np.float32)),
        b1=jnp.zeros((1, H)), w2=jnp.asarray(rng.normal(size=(H, H)).astype(np.float32)),
        b2=jnp.zeros((1, H)), w3=jnp.asarray(rng.normal(size=(H, H)).astype(np.float32)),
        b3=jnp.zeros((1, H)), w4=jnp.asarray(rng.normal(size=(1, H)).astype(np.float32)))
    def run(*args):
        # scalar touching all three accumulators so none is DCE'd and the
        # timed() sync fetch stays 1 element
        t, cnt, ef = fused_edge_layer(*args, wts, block, "bf16")
        return t[0, 0] + cnt[0] + ef[0, 0]

    f = jax.jit(run)
    ms = timed(f, xc, hr, hc, row_t, col_l, kblk, scal)
    tag = "" if on_tpu else " (interpret, toy shape)"
    print(f"fused_edge_layer   {ms:8.2f} ms  [N={n_pad}, E={e_tot}]{tag}")


def fused_stack_bench(rng):
    import jax
    import jax.numpy as jnp

    from distegnn_tpu.ops.edge_pipeline import build_edge_blocks
    from distegnn_tpu.ops.layer_pipeline import (StackConfig,
                                                 fused_egnn_stack,
                                                 hbm_bytes_per_step,
                                                 stack_weight_shapes)

    block, L, C = 512, 4, 3
    # VMEM-capped shape on EVERY backend: the whole stack must be resident,
    # and the flagship shape exceeds the 16 MiB budget by design.
    n_pad = 3 * block
    nb = n_pad // block
    epb = 3 * block
    rows, cols = [], []
    for b in range(nb):
        r = np.sort(rng.integers(b * block, (b + 1) * block, size=epb))
        c = np.clip(r + rng.integers(-block, block, size=epb), 0, n_pad - 1)
        rows.append(r)
        cols.append(c)
    row = jnp.asarray(np.concatenate(rows).astype(np.int32))
    col = jnp.asarray(np.concatenate(cols).astype(np.int32))
    e_tot = int(row.shape[0])
    attr = jnp.asarray(rng.normal(size=(e_tot, 2)).astype(np.float32))
    mask = jnp.ones((e_tot,), jnp.float32)
    edge_arrs = jax.jit(
        lambda r, c, a, m: build_edge_blocks(r, c, a, m, block=block,
                                             n_nodes=n_pad))(row, col, attr,
                                                             mask)
    R = 128  # masked-off remote tail: the pad path, zero live remote edges
    remote_arrs = (jnp.zeros((R,), jnp.int32), jnp.zeros((R,), jnp.int32),
                   jnp.zeros((R, 2), jnp.float32), jnp.zeros((R,), jnp.float32))
    cfg = StackConfig(n_layers=L, block=block, hidden=H, channels=C,
                      dtype_name="bf16")
    wstack = {k: jnp.asarray(
        rng.normal(size=(L,) + s).astype(np.float32) * 0.05)
        for k, s in stack_weight_shapes(cfg).items()}
    h0 = jnp.asarray(rng.normal(size=(n_pad, H)).astype(np.float32))
    x0 = jnp.asarray(rng.normal(size=(n_pad, 3)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(n_pad, 3)).astype(np.float32) * 0.01)
    X0 = jnp.asarray(rng.normal(size=(3, C)).astype(np.float32))
    Hv0 = jnp.asarray(rng.normal(size=(H, C)).astype(np.float32))
    nmask = jnp.ones((n_pad,), jnp.float32)

    def run(*args):
        h, x, X, Hv = fused_egnn_stack(cfg, *args, None, None, edge_arrs,
                                       remote_arrs, wstack)
        return h[0, 0] + x[0, 0] + X[0, 0] + Hv[0, 0]

    f = jax.jit(run)
    on_tpu = jax.default_backend() == "tpu"
    ms = timed(f, h0, x0, v, X0, Hv0, nmask)
    tag = "" if on_tpu else " (interpret, VMEM-capped shape)"
    print(f"fused_egnn_stack   {ms:8.2f} ms  [N={n_pad}, E={e_tot}, L={L}]{tag}")

    # Analytic HBM-bytes-per-step model (ops/layer_pipeline.hbm_bytes_per_step)
    # — CPU-evidence-only until a hardware profile confirms it. Assumptions:
    # bf16 compute streams, f32 state/checkpoints, remote tail at the padded
    # width, every array read/written exactly as many times as the lowering's
    # dataflow implies (no cache modeling).
    print("hbm_bytes_per_step model (analytic; CPU evidence only):")
    for label, (n, e, rp) in (
            (f"capped  N={n_pad} E={e_tot}", (n_pad, e_tot, R)),
            ("flagship N=113152 E=1639424", (113_152, 1_639_424, 8192))):
        per = {impl: hbm_bytes_per_step(
            impl, n_nodes=n, n_edges=e, hidden=H, channels=C, n_layers=L,
            remote_pad=rp, node_attr_nf=2, dtype_name="bf16")["total"]
            for impl in ("plain", "fused", "fused_stack")}
        ratio = per["fused"] / per["fused_stack"]
        print(f"  {label}: plain {per['plain'] / 1e9:7.3f} GB | "
              f"fused {per['fused'] / 1e9:7.3f} GB | "
              f"fused_stack {per['fused_stack'] / 1e9:7.3f} GB | "
              f"fused/fused_stack = {ratio:.2f}x")


def tiled_exec_bench(rng):
    """Tile-executor unit (serve/tiled.py): plan cost, per-(tile, layer)
    invocation time, and the measured H2D-overlap stall fraction at a small
    multi-tile shape. The per-invocation number is the one that multiplies
    by tiles x layers for a giant scene; the plan cost is the host-side
    prep a session-cache hit amortizes away."""
    import jax

    from distegnn_tpu.models.fast_egnn import FastEGNN
    from distegnn_tpu.ops.graph import pad_graphs
    from distegnn_tpu.ops.tiling import plan_tiles
    from distegnn_tpu.serve.buckets import synthetic_graph
    from distegnn_tpu.serve.engine import InferenceEngine
    from distegnn_tpu.serve.tiled import TiledExecutor

    on_tpu = jax.default_backend() == "tpu"
    n, tile = (65_536, 16_384) if on_tpu else (1_500, 512)
    g = synthetic_graph(n, radius=0.35 * (1_500 / n) ** (1 / 3), seed=0)

    t0 = time.perf_counter()
    plan = plan_tiles(g["edge_index"], g["loc"], g["edge_attr"],
                      tile_nodes=tile)
    plan_ms = (time.perf_counter() - t0) * 1e3

    model = FastEGNN(node_feat_nf=1, edge_attr_nf=2, hidden_nf=H,
                     virtual_channels=3, n_layers=4)
    params = model.init(jax.random.PRNGKey(0),
                        pad_graphs([{k: v[:32] if v.ndim and v.shape[0] == n
                                     else v for k, v in g.items()
                                     if k != "edge_index"}
                                    | {"edge_index": np.array([[0, 1],
                                                               [1, 0]],
                                                              np.int32),
                                       "edge_attr": g["edge_attr"][:2]}],
                                   node_bucket=1, edge_bucket=1))
    tx = TiledExecutor(InferenceEngine(model, params),
                       {"tile_nodes": tile})
    out = tx.predict(dict(g))               # warmup: compiles + first pass
    t0 = time.perf_counter()
    out = tx.predict(dict(g), plan=plan)
    pass_ms = (time.perf_counter() - t0) * 1e3
    per_inv = pass_ms / (out["tiles"] * out["layers"])
    print(f"tiled_plan         {plan_ms:8.2f} ms  "
          f"[N={n}, tiles={out['tiles']}, halo={out['halo_fraction']:.3f}]")
    print(f"tiled_tile_layer   {per_inv:8.2f} ms  "
          f"[pass={pass_ms:.1f} ms over {out['tiles']}x{out['layers']} "
          f"invocations, h2d_stall={out['stall_fraction']:.3f}]")


if __name__ == "__main__":
    main()
