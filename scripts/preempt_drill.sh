#!/usr/bin/env bash
# Preemption drill (docs/ROBUSTNESS.md): prove the full SIGTERM contract —
# the trainer finishes the in-flight step, writes preempt_model.ckpt + the
# PREEMPTED marker, exits 75 (EX_TEMPFAIL), and `--resume auto` reproduces
# the uninterrupted control run's final train loss to 1e-6.
#
# Two modes:
#   --fast  (default for tier-1, tests/test_cli_e2e.py): the victim raises
#           SIGTERM in itself after exactly N train steps via the fault
#           injector (testing/faults.py inject_at_call) — deterministic,
#           no timing races, ~30s on CPU.
#   (slow)  without --fast, a real external SIGTERM is sent to a
#           backgrounded victim — exercises the genuine signal delivery
#           path, but the kill lands at a nondeterministic step.
#
# Usage: bash scripts/preempt_drill.sh [--fast] [--workdir DIR]
set -euo pipefail
cd "$(dirname "$0")/.."
FAST=0
WORK=""
while [ $# -gt 0 ]; do
  case "$1" in
    --fast) FAST=1 ;;
    --workdir) WORK=$2; shift ;;
    *) echo "unknown arg: $1 (usage: preempt_drill.sh [--fast] [--workdir DIR])"; exit 2 ;;
  esac
  shift
done
WORK=${WORK:-$(mktemp -d /tmp/preempt_drill.XXXXXX)}
mkdir -p "$WORK"
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
# slow mode needs enough epochs that the external SIGTERM lands mid-training
EPOCHS=4; [ "$FAST" -eq 1 ] || EPOCHS=200
TINY=(python -u -m distegnn_tpu.testing.tiny_run --epochs "$EPOCHS" --interval-s 0.001)

echo "== control (uninterrupted) =="
"${TINY[@]}" --log-dir "$WORK/control" | tee "$WORK/control.log"
CONTROL=$(grep '^RESULT ' "$WORK/control.log" | tail -1 | cut -d' ' -f2-)

echo "== victim (preempted) =="
rc=0
if [ "$FAST" -eq 1 ]; then
  "${TINY[@]}" --log-dir "$WORK/victim" --sigterm-at-step 6 \
    | tee "$WORK/victim.log" || rc=$?
else
  "${TINY[@]}" --log-dir "$WORK/victim" >"$WORK/victim.log" 2>&1 &
  VPID=$!
  sleep 8  # past jit warmup, into the epoch loop
  kill -TERM "$VPID" 2>/dev/null \
    || { echo "DRILL FAIL: victim finished before SIGTERM — raise epochs"; exit 1; }
  wait "$VPID" || rc=$?
  cat "$WORK/victim.log"
fi
[ "$rc" -eq 75 ] || { echo "DRILL FAIL: victim exit $rc, want 75 (EX_TEMPFAIL)"; exit 1; }
grep -q 'PREEMPTED' "$WORK/victim.log" || { echo "DRILL FAIL: no PREEMPTED line in victim log"; exit 1; }

echo "== resume (--resume auto over the victim's log dir) =="
"${TINY[@]}" --log-dir "$WORK/victim" --resume auto | tee "$WORK/resume.log"
grep -q 'resume: restored' "$WORK/resume.log" \
  || { echo "DRILL FAIL: resumed run did not restore a checkpoint"; exit 1; }
RESUMED=$(grep '^RESULT ' "$WORK/resume.log" | tail -1 | cut -d' ' -f2-)

python - "$CONTROL" "$RESUMED" <<'EOF'
import json, sys
c, r = (json.loads(a) for a in sys.argv[1:3])
dc, dr = c["final_train_loss"], r["final_train_loss"]
delta = abs(dc - dr)
print(f"control={dc!r} resumed={dr!r} |delta|={delta:.3e}")
assert delta <= 1e-6, f"final train losses differ by {delta} > 1e-6"
EOF
echo "DRILL PASS: resumed final loss matches control (atol 1e-6)"
echo "workdir: $WORK"
