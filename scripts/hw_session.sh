#!/usr/bin/env bash
# One-shot hardware measurement pass for a flaky TPU tunnel window.
#
# The axon tunnel wedges unpredictably (BASELINE.md), so when a window opens
# every pending measurement should run unattended, serially, with the host
# otherwise idle. IMPORTANT: SIGTERM/SIGKILL of a live TPU client strands the
# remote claim and wedges the tunnel for everyone (observed 2026-07-29 and
# again 2026-07-30 when a 25-min `timeout` killed profile_step) — so items
# run with NO kill timeout; a wedged tunnel hangs the queue instead of
# corrupting it, and the probe guards entry.
#
# This script:
#   1. SIGSTOPs any running n-body generator (host contention degrades step
#      timing ~4x — BASELINE.md measurement discipline), resuming it on exit;
#   2. runs the measurement queue, appending output to $LOG. Every item is
#      probe-gated (scripts/tpu_probe.sh: 90 s timeout x 3 attempts with
#      150 s spacing — worst case ~9.5 min before declaring the tunnel down)
#      and records a done-marker in $DONE_DIR on success, so a re-fired
#      queue resumes instead of repeating completed hours of work;
#   3. finishes the n-body dataset on-chip and hands off to the convergence
#      run (scripts/convergence_session.sh) — the remaining MSE-parity
#      evidence (BASELINE.md round-2 status).
#
# Usage: bash scripts/hw_session.sh [logfile]   (default /tmp/hw_session.log)

set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/hw_session.log}
# Done-markers survive across invocations so a re-fired queue resumes, not
# repeats. To force a FRESH measurement pass (e.g. after editing bench or
# the profile scripts): rm -rf /tmp/hw_done
DONE_DIR=${DONE_DIR:-/tmp/hw_done}
mkdir -p "$DONE_DIR"

# Persistent XLA compilation cache (honored by jax 0.9 via env): bench auto
# runs three child processes that each compile near-identical LargeFluid
# programs (~minutes apiece), and a re-fired queue repeats them — cache the
# compiles across processes so only the first pays.
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_cache}

# Single instance only: two overlapping queues would run concurrent live TPU
# clients and SIGSTOP/CONT each other's background processes mid-measurement.
# fd 8 is deliberately inherited by queue children: if this shell dies while
# an untimeouted TPU client still runs, the orphan KEEPS the lock, which is
# correct — firing a new queue next to an orphaned live client is the
# tunnel-wedging scenario (BASELINE.md). Recovery from that state is manual.
exec 8>/tmp/hw_session.lock
flock -n 8 || { echo "another hw_session is running; exiting" >>"$LOG"; exit 4; }

# Shared probe (scripts/tpu_probe.sh): retries with spacing because the
# tunnel releases a client's claim slowly — a probe fired right after
# another client exits can hang even when the tunnel is healthy.
probe() {
  bash scripts/tpu_probe.sh "$LOG"
}

echo "=== hw_session $(date -u +%FT%TZ) ===" >>"$LOG"

# Recover competitors a SIGKILLed bench left SIGSTOPped (shared helper;
# ADVICE r3, medium). We hold the queue lock, so no queue-managed bench is
# running; the helper skips if a driver-invoked bench.py is live.
. scripts/lib_resume_paused.sh  # script already cd'd to repo root
resume_orphaned_paused "$LOG"

# Contending host processes to pause during measurement (a concurrent suite
# degraded step timing ~4x — BASELINE.md). CRITICAL: the agent-driver
# process embeds the whole task prompt in its command line, which contains
# the literal strings "pytest" and "main.py --config_path" — a bare pgrep -f
# matches it and SIGSTOPs the driver itself (this froze the controlling
# session for the entire 6 h hung queue on 2026-07-31). Filter to real
# python invocations: argv[0] must be a python executable.
pgrep_py() {  # pgrep -f, restricted to processes whose argv[0] is python
  for p in $(pgrep -f "$1" || true); do
    head -zc 200 "/proc/$p/cmdline" 2>/dev/null | tr '\0' ' ' \
      | grep -Eq "^[^ ]*python[0-9.]* " && echo "$p"
  done
  true
}
# SIGSTOPping a LIVE TPU client is the tunnel-wedging hazard this queue
# exists to avoid — so only pause processes that are provably CPU-bound:
# their startup environment pins JAX to CPU, or their command line carries
# --platform cpu. A main.py launched without pinning defaults to the tunnel
# TPU; leave it alone and log (manual on-chip runs should go through this
# queue so they hold /tmp/hw_session.lock).
# Measuring NEXT TO a live TPU client is as bad as freezing it (host/device
# contention degrades step timing ~4x, and two clients contend for the
# claim), so a detected possibly-live client aborts the whole queue — the
# watcher re-fires when it is gone. The flag is a file because cpu_only
# runs inside $() subshells, where a shell variable would not propagate.
TPU_SEEN_FLAG=/tmp/hw_session.tpu_client_seen.$$
rm -f "$TPU_SEEN_FLAG"
cpu_only() {
  local out="" p
  for p in $1; do
    if tr '\0' '\n' <"/proc/$p/environ" 2>/dev/null \
         | grep -Eq "^(JAX_PLATFORMS|BENCH_PLATFORM)=cpu" \
       || tr '\0' ' ' <"/proc/$p/cmdline" 2>/dev/null \
         | grep -q -- "--platform cpu"; then
      out="$out $p"
    else
      echo "pid $p is not provably CPU-pinned; may be a live TPU client" >>"$LOG"
      touch "$TPU_SEEN_FLAG"
    fi
  done
  echo "$out"
}
# The chunked generator defaults to --platform cpu, so absence of an
# explicit tpu/auto flag means CPU — the inverse test of cpu_only.
gen_cpu_pids() {
  local out="" p
  for p in $(pgrep_py 'generate_nbody_chunked'); do
    if tr '\0' ' ' <"/proc/$p/cmdline" 2>/dev/null \
         | grep -Eq -- "--platform[= ](tpu|auto)"; then
      echo "generator pid $p runs on TPU — possibly a live client" >>"$LOG"
      touch "$TPU_SEEN_FLAG"
    else
      out="$out $p"
    fi
  done
  echo "$out"
}
GEN_PIDS=$(gen_cpu_pids)
# The snapshot is taken NOW, so this session's own convergence run (started
# below) is never self-paused. pytest is always CPU (tests/conftest.py pins
# JAX_PLATFORMS=cpu before jax import) so it needs no cpu_only filtering —
# but main.py does.
PYTEST_PIDS="$(pgrep_py 'pytest') $(cpu_only "$(pgrep_py 'main\.py --config_path')")"
# A possibly-live TPU client that we can neither pause (wedge hazard) nor
# measure beside (contention) aborts the queue; the watcher re-fires (with
# a long back-off) once it is gone.
if [ -f "$TPU_SEEN_FLAG" ]; then
  rm -f "$TPU_SEEN_FLAG"
  echo "=== aborting queue: possibly-live TPU client present (see above) ===" >>"$LOG"
  # rc=9 (not 3): the watcher backs off much longer for a live client than
  # for a tunnel flap — re-firing probes every PERIOD next to a live
  # measurement session is the contention this abort exists to avoid.
  exit 9
fi
resume() {
  rm -f "$TPU_SEEN_FLAG"
  [ -n "${GEN_PIDS// /}" ] && kill -CONT $GEN_PIDS 2>/dev/null
  [ -n "${PYTEST_PIDS// /}" ] && kill -CONT $PYTEST_PIDS 2>/dev/null
}
trap resume EXIT
[ -n "${GEN_PIDS// /}" ] && kill -STOP $GEN_PIDS 2>/dev/null
[ -n "${PYTEST_PIDS// /}" ] && kill -STOP $PYTEST_PIDS 2>/dev/null

# Per-leg wall budget for items that are PROVABLY bounded when healthy:
# bench.py guarantees its own exit inside BENCH_BUDGET_S (2400 s) and now
# clamps every race child to BENCH_LEG_BUDGET_S, and the microbench/profile
# scripts finish in minutes. The only way such an item overruns this bound is
# a client wedged in acquire/reconnect — which holds NO remote claim, so a
# TERM is safe under the same contract as the probe (the no-kill rule in the
# header protects LIVE measuring clients; those items stay unbounded).
# On 2026-08-02 four consecutive sessions (BENCH_r02-r05) each hung a whole
# window on one wedged leg and recorded zero measurements.
HW_LEG_BUDGET_S=${HW_LEG_BUDGET_S:-3000}

ITEMS=()
run() {  # run <label> <cmd...> — NO kill timeout (see header)
  local label=$1; shift
  ITEMS+=("$label")  # single source for the final completeness check
  if [ -f "$DONE_DIR/$label" ]; then
    echo "--- $label already done (marker $DONE_DIR/$label); skipping ---" >>"$LOG"
    return 0
  fi
  # Probe-gate every item: on 2026-07-31 the tunnel died right after the
  # entry probe and the queue burned ~6 h of wall clock hanging in the axon
  # client's reconnect loop across 5 items. The shared probe retries with
  # spacing (slow claim release after the previous item's client exits);
  # if it still fails, abort the whole queue so a watcher can re-fire it
  # when the tunnel returns.
  if ! probe; then
    echo "--- $label SKIPPED: tunnel probe failed; aborting queue ($(date -u +%T)) ---" >>"$LOG"
    exit 3
  fi
  # Let the probe client's claim release before the untimeouted item starts
  # (claim release took >25 s once; a healthy tunnel just makes the item
  # wait in acquire, but don't start the wait mid-release on purpose).
  sleep 30
  echo "--- $label ($(date -u +%T)) ---" >>"$LOG"
  local rc=0
  "$@" >>"$LOG" 2>&1 || rc=$?
  echo "--- $label rc=$rc ---" >>"$LOG"
  [ "$rc" -eq 0 ] && touch "$DONE_DIR/$label"
}

run_bounded() {  # run_bounded <label> <cmd...> — HW_LEG_BUDGET_S clamp
  # Only for items bounded-by-construction when healthy (see HW_LEG_BUDGET_S
  # above): an overrun means wedged-in-acquire, not a live claim. TERM first,
  # KILL 30 s later only if the wedge ignores it.
  local label=$1; shift
  ITEMS+=("$label")
  if [ -f "$DONE_DIR/$label" ]; then
    echo "--- $label already done (marker $DONE_DIR/$label); skipping ---" >>"$LOG"
    return 0
  fi
  if ! probe; then
    echo "--- $label SKIPPED: tunnel probe failed; aborting queue ($(date -u +%T)) ---" >>"$LOG"
    exit 3
  fi
  sleep 30
  echo "--- $label ($(date -u +%T), budget ${HW_LEG_BUDGET_S}s) ---" >>"$LOG"
  local rc=0
  # bash -c indirection lets timeout run exported shell functions; GNU
  # timeout signals the child's whole process group, so the python
  # grandchildren get the TERM too.
  timeout --signal=TERM --kill-after=30 "$HW_LEG_BUDGET_S" \
    bash -c '"$@"' _ "$@" >>"$LOG" 2>&1 || rc=$?
  if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    echo "--- $label WEDGED: exceeded ${HW_LEG_BUDGET_S}s leg budget (rc=$rc); continuing queue ---" >>"$LOG"
  else
    echo "--- $label rc=$rc ---" >>"$LOG"
  fi
  [ "$rc" -eq 0 ] && touch "$DONE_DIR/$label"
}

# bench.py always exits 0 and prints a failure JSON (value 0.0) when its
# children die, so the done-marker must key on a real measurement.
bench_and_check() {
  # BENCH_PROBE=0: run() already probe-gated this item and slept out the
  # claim release — bench's own probe child would just burn ~2 min of the
  # window re-proving it.
  # BENCH_CALLER_PROBED attests WHAT run()'s probe verified — without it
  # bench treats BENCH_PROBE=0 as unverified and routes results to the CPU
  # artifact instead of stamping hardware evidence. The value comes from the
  # probe's own jax.devices() report (tpu_probe.sh writes it), never a
  # literal: a jax that silently fell back to CPU must attest 'cpu'
  # (code-review r4).
  # No fallback literal: if the probe's platform record is missing the
  # attestation stays EMPTY and bench routes the run to the CPU artifact —
  # failing safe instead of stamping hardware evidence (code-review r4 #2).
  BENCH_PROBE=0 BENCH_CALLER_PROBED="$(cat /tmp/tpu_probe.platform 2>/dev/null || true)" \
    python bench.py | tee /tmp/bench_last.json
  # Validate AND persist: extract the single measurement JSON line (stdout
  # may carry warnings) and, if it is a real measurement, write it as a
  # tracked artifact — the driver's own end-of-round bench may land on a
  # dead tunnel, and then this is the only hardware evidence (commit it when
  # recording results in BASELINE.md). temp + same-fs rename so a crash
  # can't truncate prior good evidence.
  mkdir -p docs/artifacts
  python - <<'EOF' || return 1
import json, os
line = [l for l in open('/tmp/bench_last.json') if l.strip().startswith('{')][-1]
rec = json.loads(line)
if rec['value'] <= 0:
    raise SystemExit(1)
# clean copy for the dated immutable archive; the rolling file carries a
# DO_NOT_CITE field so nobody quotes a number a later race will overwrite
with open('/tmp/bench_headline_clean.json', 'w') as f:
    f.write(line)
rolling = {'DO_NOT_CITE': 'rolling file, overwritten by every race — cite '
                          'the dated docs/artifacts/bench_*_<stamp> archives '
                          'instead'}
rolling.update(rec)
tmp = 'docs/artifacts/bench_r3_measured.json.tmp'
with open(tmp, 'w') as f:
    json.dump(rolling, f)
os.replace(tmp, 'docs/artifacts/bench_r3_measured.json')
EOF
  # Immutable dated archives (ADVICE r4): the rolling headline/race files
  # are overwritten by every session — BASELINE.md must cite these instead.
  local stamp
  stamp=$(date -u +%Y%m%dT%H%M%S)
  cp /tmp/bench_headline_clean.json \
     "docs/artifacts/bench_headline_$stamp.json" 2>/dev/null || true
  STAMP="$stamp" python - <<'EOF' || true
import json, os
with open('docs/artifacts/bench_race_last.json') as f:
    rec = json.load(f)
rec.pop('DO_NOT_CITE', None)   # the dated archive IS citable
with open(f"docs/artifacts/bench_race_{os.environ['STAMP']}.json", 'w') as f:
    json.dump(rec, f, indent=1)
EOF
}

# The chunked generator deletes chunks/ after the final merge, so re-invoking
# it on a complete dataset would regenerate everything from scratch — guard
# on the merged output instead. It also exits 0 on a PARTIAL pass, so
# success is "merged train file exists", not the generator's rc.
export NBODY_DONE=data/n_body_system/nbody_100/loc_train_charged100_0_0_1.npy
nbody_gen_and_check() {
  if [ ! -f "$NBODY_DONE" ]; then
    python scripts/generate_nbody_chunked.py \
      --path data/n_body_system/nbody_100 --n_isolated 100 \
      --num-train 5000 --num-valid 2000 --num-test 2000 --seed 43 \
      --budget 100000 --platform tpu
  fi
  test -f "$NBODY_DONE"
}

# Priority order for a short window (the tunnel rarely stays up long):
# the never-hardware-measured fused edge pipeline first, then the headline
# bench race, then the convergence evidence, microbench/profile detail last.
# 0. fused edge-pipeline leg (model.edge_impl='fused'): the one lowering with
#    no hardware number yet — the highest-information minutes of the window.
#    The auto race (item 1) also stages it first, but an explicit item leaves
#    a dated artifact even if a later race leg wedges the tunnel.
fused_leg_and_check() {
  python bench.py --layout fused | tee /tmp/bench_fused_last.json
  python - <<'EOF' || return 1
import json
line = [l for l in open('/tmp/bench_fused_last.json') if l.strip().startswith('{')][-1]
raise SystemExit(0 if json.loads(line)['value'] > 0 else 1)
EOF
  mkdir -p docs/artifacts
  cp /tmp/bench_fused_last.json \
     "docs/artifacts/bench_fused_$(date -u +%Y%m%dT%H%M%S).json"
}
# 0a. cross-layer megakernel leg (model.edge_impl='fused_stack'): also never
#     hardware-measured. bench.py self-caps the node count to the VMEM budget
#     (BENCH_STACK_NODES, default 1536), so this leg is an A/B against the
#     fused leg at the capped shape — bounded and dated like every other leg.
stack_leg_and_check() {
  python bench.py --layout fused_stack | tee /tmp/bench_fused_stack_last.json
  python - <<'EOF' || return 1
import json
line = [l for l in open('/tmp/bench_fused_stack_last.json') if l.strip().startswith('{')][-1]
raise SystemExit(0 if json.loads(line)['value'] > 0 else 1)
EOF
  mkdir -p docs/artifacts
  cp /tmp/bench_fused_stack_last.json \
     "docs/artifacts/bench_fused_stack_$(date -u +%Y%m%dT%H%M%S).json"
}
# 0b. 3-axis mesh leg: the tensor-parallel hidden-dim split (parallel.mesh,
#     docs/PERFORMANCE.md "3D mesh") timed on real chips — data=1 x graph=1 x
#     tensor=2 so it fits any 2+-chip tunnel slice. Bounded like every other
#     leg; failure (single-chip slice, wedge) records in seconds and the
#     queue moves on. CPU parity for the same leg lives in tier-1
#     (tests/test_bench_unlosable.py + tests/test_tensor_parallel.py).
mesh3d_leg_and_check() {
  python bench.py --mesh 1x1x2 | tee /tmp/bench_mesh3d_last.json
  python - <<'EOF' || return 1
import json
line = [l for l in open('/tmp/bench_mesh3d_last.json') if l.strip().startswith('{')][-1]
raise SystemExit(0 if json.loads(line)['value'] > 0 else 1)
EOF
  mkdir -p docs/artifacts
  cp /tmp/bench_mesh3d_last.json \
     "docs/artifacts/bench_mesh3d_$(date -u +%Y%m%dT%H%M%S).json"
}
# 0b2. tiled-serving leg (serve/tiled.py): giant-scene inference nodes/sec
#      through the fixed-shape tile executor, with tile count, halo
#      fraction and the H2D-overlap stall fraction measured on real chips —
#      the hardware evidence for the million-node serving path. The check
#      requires a real throughput AND that double-buffered staging actually
#      overlapped (stall fraction < 0.5 of the pass).
tiled_leg_and_check() {
  python bench.py --layout tiled | tee /tmp/bench_tiled_last.json
  python - <<'EOF' || return 1
import json
line = [l for l in open('/tmp/bench_tiled_last.json') if l.strip().startswith('{')][-1]
rec = json.loads(line)
raise SystemExit(0 if rec['value'] > 0 and rec['tiles'] >= 2
                 and rec['h2d_stall_fraction'] < 0.5 else 1)
EOF
  mkdir -p docs/artifacts
  cp /tmp/bench_tiled_last.json \
     "docs/artifacts/bench_tiled_$(date -u +%Y%m%dT%H%M%S).json"
}
# 0b3. multi-chip tiled leg (serve/mesh_tiled.py): the SAME giant scene at
#      D=1 and D=min(8, chips, tiles) device-parallel rounds — the first
#      real-hardware scaling_efficiency for the round scheduler. The check
#      requires the sweep to have actually run (devices > 1, rounds < tiles)
#      and the D-device throughput to beat the sequential anchor — on real
#      chips parallel rounds must not lose (CPU gets no such gate; virtual
#      devices share one host).
tiled_mesh_leg_and_check() {
  BENCH_TILED_DEVICES=8 python bench.py --layout tiled \
    | tee /tmp/bench_tiled_mesh_last.json
  python - <<'EOF' || return 1
import json
line = [l for l in open('/tmp/bench_tiled_mesh_last.json') if l.strip().startswith('{')][-1]
rec = json.loads(line)
raise SystemExit(0 if rec['value'] > 0 and rec['devices'] > 1
                 and rec['tiled_rounds'] < rec['tiles']
                 and rec['value'] > rec['seq_nodes_per_sec'] else 1)
EOF
  mkdir -p docs/artifacts
  cp /tmp/bench_tiled_mesh_last.json \
     "docs/artifacts/bench_tiled_mesh_$(date -u +%Y%m%dT%H%M%S).json"
}
# 0c. input-pipeline leg (data/stream.py): streamed-shard prefetch vs
#     blocking put, graphs/s + data/stall_s fractions on THIS host's disk.
#     The check requires the prefetch stall to not exceed the blocking stall
#     — the direct acceptance evidence for the out-of-core pipeline.
io_leg_and_check() {
  python bench.py --layout io | tee /tmp/bench_io_last.json
  python - <<'EOF' || return 1
import json
line = [l for l in open('/tmp/bench_io_last.json') if l.strip().startswith('{')][-1]
rec = json.loads(line)
raise SystemExit(0 if rec['value'] > 0
                 and rec['stall_s'] <= rec['stall_s_blocking'] else 1)
EOF
  mkdir -p docs/artifacts
  cp /tmp/bench_io_last.json \
     "docs/artifacts/bench_io_$(date -u +%Y%m%dT%H%M%S).json"
}
export -f mesh3d_leg_and_check fused_leg_and_check stack_leg_and_check \
          tiled_leg_and_check tiled_mesh_leg_and_check io_leg_and_check \
          bench_and_check  # run_bounded's bash -c needs them
run_bounded bench_fused fused_leg_and_check
run_bounded bench_fused_stack stack_leg_and_check
run_bounded bench_mesh3d mesh3d_leg_and_check
run_bounded bench_tiled tiled_leg_and_check
run_bounded bench_tiled_mesh tiled_mesh_leg_and_check
run_bounded bench_io io_leg_and_check
# 1. headline bench: auto races fused / plain-cumsum stacks / plain-scatter
#    anchor in child processes (bench.RACE_ORDER) and reports the fastest
#    real measurement
run_bounded bench_auto bench_and_check
# 2. finish the n-body dataset on-chip (resumes any CPU-generated chunks)
#    and run the convergence session (MSE-parity evidence). The CPU generator
#    is SIGSTOPped: queue TERM first, then CONT so it can die (a TERM alone
#    stays pending on a stopped process forever); chunk writes are atomic
#    (tmp + rename), so termination mid-chunk cannot corrupt the dataset.
if [ -n "$GEN_PIDS" ]; then
  kill -TERM $GEN_PIDS 2>/dev/null
  kill -CONT $GEN_PIDS 2>/dev/null
  sleep 2
  GEN_PIDS=""
fi
# If the CPU generator already finished the dataset, seed the marker so the
# item costs no probe + settle at all. Conversely, INVALIDATE a stale marker
# whose artifact is gone (container reset wipes data/ but /tmp/hw_done can
# survive the other way round too — a marker without the dataset would skip
# generation and fail every convergence stage until the fire cap).
if [ -f "$NBODY_DONE" ]; then
  touch "$DONE_DIR/nbody_gen_tpu"
else
  rm -f "$DONE_DIR/nbody_gen_tpu"
fi
run nbody_gen_tpu nbody_gen_and_check

# 3. one real LargeFluid epoch on chip, end to end (VERDICT r4 #4): the
#     flagship largefluid_distegnn.yaml through main.py — 113,140 nodes,
#     metis partition shards, grad accum 4, MMD, remat, distribute mode.
#     Data: the synthetic Fluid113K-format generator at full particle count
#     (honestly labeled — real bytes are egress-blocked; format and scale
#     are authentic). Validates scan policy + remat headroom at scale and
#     logs per-epoch time_cost.
largefluid_epoch_and_check() {
  if ! ls data/LargeFluid/Fluid113K/sim_0001_*.msgpack.zst >/dev/null 2>&1; then
    nice -n 5 env PYTHONPATH=/root/repo JAX_PLATFORMS=cpu \
      python scripts/generate_fluid_synthetic.py --out data/LargeFluid \
      --particles 113140 --frames 28 --sims-train 1 --sims-valid 1 \
      --sims-test 1 || return 1
  fi
  python -u main.py --config_path configs/largefluid_distegnn.yaml \
    --epochs 1 2>&1 | tee /tmp/largefluid_epoch.log
  L=$(ls -t logs/largefluid/*/log/log.json 2>/dev/null | head -1) || return 1
  [ -n "$L" ] || return 1
  mkdir -p docs/artifacts
  cp "$L" docs/artifacts/largefluid_epoch_log.json
  # obs event stream (step/stall/compile timeline) next to the log artifact —
  # scripts/obs_report.py renders it; --check would flag recompiles-after-
  # warmup on the real backend
  E=$(ls -t logs/largefluid/*/obs/events.jsonl 2>/dev/null | head -1)
  [ -n "$E" ] && cp "$E" \
    "docs/artifacts/largefluid_epoch_events_$(date -u +%Y%m%dT%H%M%S).jsonl"
}
run largefluid_epoch largefluid_epoch_and_check

# 3a. gateway serving leg: mixed-traffic replay (scripts/traffic_gen.py —
#     predict/session/rollout, heavy-tailed sizes, bursty arrivals) against
#     an in-process gateway, then the SLO gate re-derived from the event
#     stream ALONE (obs_report --slo), so the verdict is reproducible from
#     the archived events.jsonl after the window closes. Bounded by
#     construction: open-loop plan of fixed size + per-request timeout.
gateway_traffic_and_check() {
  local stamp obsdir
  stamp=$(date -u +%Y%m%dT%H%M%S)
  obsdir=logs/traffic_gen/hw_$stamp
  python scripts/traffic_gen.py --config_path configs/nbody_serve.yaml \
    --requests 48 --rate 60 --mix "predict=0.6,session=0.3,rollout=0.1" \
    --sizes 24,48,96,192 --sessions 4 --seed 47 --timeout-s 300 \
    --slo configs/slo_default.yaml --obs-dir "$obsdir" \
    | tee /tmp/traffic_last.json || return 1
  # done-marker keys on a real measurement (the BENCH contract line with a
  # nonzero p99 and full completion), mirroring bench_and_check
  python - <<'EOF' || return 1
import json
line = [l for l in open('/tmp/traffic_last.json') if l.strip().startswith('{')][-1]
rec = json.loads(line)
ok = rec.get('value', 0) > 0 and rec.get('completed', 0) == rec.get('requests', -1)
raise SystemExit(0 if ok else 1)
EOF
  mkdir -p docs/artifacts
  cp /tmp/traffic_last.json "docs/artifacts/traffic_gateway_$stamp.json"
  # the gate: breach in the archived event stream fails the leg (no marker),
  # so a re-fired queue re-measures instead of citing a breached run
  python scripts/obs_report.py "$obsdir/obs/events.jsonl" \
    --slo configs/slo_default.yaml
}
export -f gateway_traffic_and_check  # run_bounded's bash -c needs it
run_bounded gateway_traffic gateway_traffic_and_check

# 3a'. gateway chaos drill: the same replay against a 2-replica in-process
#      gateway with a scheduled dispatcher kill + wedge mid-run
#      (docs/ROBUSTNESS.md "Serving fault tolerance"). The done-marker keys
#      on zero lost accepted requests and full completion through the
#      faults; the SLO gate re-derives the verdict from the archived event
#      stream alone, same as 3a. Bounded by construction (fixed plan +
#      per-request timeout + Retry-After-capped retries).
chaos_gateway_and_check() {
  local stamp obsdir
  stamp=$(date -u +%Y%m%dT%H%M%S)
  obsdir=logs/traffic_gen/hw_chaos_$stamp
  python scripts/traffic_gen.py --config_path configs/nbody_serve.yaml \
    --requests 48 --rate 60 --mix "predict=0.8,session=0.2" \
    --sizes 24,48,96 --sessions 4 --seed 53 --timeout-s 300 \
    --replicas 2 --chaos "kill@0.5:replica=0;wedge@2.0:replica=1,dur=2" \
    --slo configs/slo_default.yaml --obs-dir "$obsdir" \
    | tee /tmp/chaos_last.json || return 1
  python - <<'EOF' || return 1
import json
line = [l for l in open('/tmp/chaos_last.json') if l.strip().startswith('{')][-1]
rec = json.loads(line)
ok = (rec.get('value', 0) > 0
      and rec.get('completed', 0) == rec.get('requests', -1)
      and rec.get('lost', 1) == 0
      and all(c.get('ok') for c in rec.get('chaos') or []))
raise SystemExit(0 if ok else 1)
EOF
  mkdir -p docs/artifacts
  cp /tmp/chaos_last.json "docs/artifacts/chaos_gateway_$stamp.json"
  python scripts/obs_report.py "$obsdir/obs/events.jsonl" \
    --slo configs/slo_default.yaml
}
export -f chaos_gateway_and_check
run_bounded chaos_gateway chaos_gateway_and_check

# 3a''. process-worker chaos drill: the same replay with each replica's
#       engine in its OWN child process (serve.workers: process,
#       docs/SERVING.md "Worker processes"). kill9 SIGKILLs one child
#       mid-replay and sigstop freezes the other — the two faults a thread
#       backend cannot survive — so the done-marker proves detect →
#       failover → SIGTERM→SIGKILL escalation → respawn with zero lost
#       accepted requests on real hardware. Bounded like 3a'.
chaos_workers_and_check() {
  local stamp obsdir
  stamp=$(date -u +%Y%m%dT%H%M%S)
  obsdir=logs/traffic_gen/hw_chaos_workers_$stamp
  python scripts/traffic_gen.py --config_path configs/nbody_serve.yaml \
    --requests 48 --rate 60 --mix "predict=0.8,session=0.2" \
    --sizes 24,48,96 --sessions 4 --seed 53 --timeout-s 300 \
    --replicas 2 --workers process \
    --chaos "kill9@0.5:replica=0;sigstop@2.5:replica=1" \
    --slo configs/slo_default.yaml --obs-dir "$obsdir" \
    | tee /tmp/chaos_workers_last.json || return 1
  python - <<'EOF' || return 1
import json
line = [l for l in open('/tmp/chaos_workers_last.json') if l.strip().startswith('{')][-1]
rec = json.loads(line)
ok = (rec.get('value', 0) > 0
      and rec.get('completed', 0) == rec.get('requests', -1)
      and rec.get('lost', 1) == 0
      and all(c.get('ok') for c in rec.get('chaos') or []))
raise SystemExit(0 if ok else 1)
EOF
  mkdir -p docs/artifacts
  cp /tmp/chaos_workers_last.json "docs/artifacts/chaos_workers_$stamp.json"
  python scripts/obs_report.py "$obsdir/obs/events.jsonl" \
    --slo configs/slo_default.yaml
}
export -f chaos_workers_and_check
run_bounded chaos_workers chaos_workers_and_check

# 3a'''. elasticity spike drill: a spike10x replay (docs/SERVING.md
#        "Elasticity & streaming") with execute-latency chaos against a
#        1-replica fleet with the autoscaler on. The done-marker keys on
#        the full 1->N->1 cycle in ONE event stream (scale_up strictly
#        before scale_down), per-phase interactive SLO verdicts all
#        passing, and zero lost/errored requests — elasticity reacted to
#        the spike without sacrificing work. Bounded like 3a (fixed plan +
#        per-request timeout + --scale-settle-s cap on the shrink wait).
elasticity_spike_and_check() {
  local stamp obsdir
  stamp=$(date -u +%Y%m%dT%H%M%S)
  obsdir=logs/traffic_gen/hw_spike_$stamp
  python scripts/traffic_gen.py --config_path configs/nbody_serve_spike.yaml \
    --requests 64 --rate 20 --mix "predict=0.8,session=0.2" \
    --sizes 24,48 --sessions 4 --seed 61 --timeout-s 300 \
    --profile spike10x \
    --autoscale "max_replicas=3,queue_high=0.5,scale_up_cooldown_s=0.5,interval_s=0.1,scale_down_cooldown_s=1.0,idle_rounds=3,queue_low=2" \
    --scale-settle-s 30 --chaos "latency@0.0:s=0.12" \
    --slo configs/slo_default.yaml --obs-dir "$obsdir" \
    | tee /tmp/spike_last.json || return 1
  OBSDIR="$obsdir" python - <<'EOF' || return 1
import json, os
line = [l for l in open('/tmp/spike_last.json') if l.strip().startswith('{')][-1]
rec = json.loads(line)
phases = rec.get('phases') or {}
events = [json.loads(l) for l in
          open(os.path.join(os.environ['OBSDIR'], 'obs', 'events.jsonl'))]
ups = [e['ts'] for e in events if e.get('name') == 'gateway/scale_up']
downs = [e['ts'] for e in events if e.get('name') == 'gateway/scale_down']
ok = (rec.get('value', 0) > 0
      and rec.get('completed', 0) == rec.get('requests', -1)
      and rec.get('lost', 1) == 0
      and set(phases) == {'pre', 'spike', 'post'}
      and all(p.get('slo_pass') for p in phases.values())
      and ups and downs and min(ups) < max(downs))
raise SystemExit(0 if ok else 1)
EOF
  mkdir -p docs/artifacts
  cp /tmp/spike_last.json "docs/artifacts/elasticity_spike_$stamp.json"
  python scripts/obs_report.py "$obsdir/obs/events.jsonl" \
    --slo configs/slo_default.yaml
}
export -f elasticity_spike_and_check
run_bounded elasticity_spike elasticity_spike_and_check

# 3a''''. promotion conveyor drill: the continuous train->serve promotion
#         path (docs/SERVING.md "Continuous promotion") with process
#         workers, all inside ONE traffic_gen run — candidates published
#         under live traffic, the first promoting through canary + shadow,
#         a trainer SIGKILL mid-publish (orphan tmp only, no torn
#         candidate), a real canary-worker SIGKILL mid-promotion
#         (immediate rollback), and an injected-drift candidate rolled
#         back on the gauge. The done-marker keys on the drill's own
#         verdict plus zero lost requests and a coherent fleet version.
promote_and_check() {
  local stamp obsdir
  stamp=$(date -u +%Y%m%dT%H%M%S)
  obsdir=logs/traffic_gen/hw_promote_$stamp
  python scripts/traffic_gen.py --config_path configs/nbody_promote.yaml \
    --promote --requests 80 --rate 20 --mix "predict=0.8,session=0.2" \
    --sizes 24,48 --sessions 4 --seed 7 --timeout-s 300 \
    --workers process \
    --obs-dir "$obsdir" \
    | tee /tmp/promote_last.json || return 1
  python - <<'EOF' || return 1
import json
line = [l for l in open('/tmp/promote_last.json') if l.strip().startswith('{')][-1]
rec = json.loads(line)
pr = rec.get('promote') or {}
ph = pr.get('phases') or {}
ok = (pr.get('ok') is True
      and (ph.get('promote') or {}).get('outcome') == 'promoted'
      and (ph.get('trainer_kill') or {}).get('ok') is True
      and (ph.get('canary_kill') or {}).get('reason') == 'canary_died'
      and (ph.get('drift') or {}).get('reason') == 'drift'
      and (pr.get('readyz') or {}).get('fleet_coherent') is True
      and rec.get('completed', 0) == rec.get('requests', -1)
      and rec.get('lost', 1) == 0)
raise SystemExit(0 if ok else 1)
EOF
  mkdir -p docs/artifacts
  cp /tmp/promote_last.json "docs/artifacts/promote_drill_$stamp.json"
  python scripts/obs_report.py "$obsdir/obs/events.jsonl"
}
export -f promote_and_check
run_bounded promote promote_and_check

# 3b. machine roofline probe (minutes): copy/matmul/gather/scatter ceilings
#     + analytic step floor — pairs with the new hbm_gbps field in the bench
#     line (VERDICT r4 #7) to place every lowering on the memory roofline.
run_bounded microbench_roofline python scripts/microbench_roofline.py \
  --json docs/artifacts/roofline_tpu.json

# 3c. detail (cheap, minutes): isolate the segment-sum lowerings + step
#     breakdowns — the per-primitive evidence behind the bench race.
run_bounded microbench_segsum python scripts/microbench_segsum.py
run_bounded microbench_segsum_bf16 python scripts/microbench_segsum.py --bf16
run_bounded profile_cumsum python scripts/profile_step.py --bf16 --seg cumsum
run_bounded profile_plain python scripts/profile_step.py --bf16

# 3d. remat memory on the REAL backend: XLA:CPU provably discards
#     rematerialization in buffer assignment (docs/PERFORMANCE.md), so the
#     compiled-temp comparison only means something here. Session-B measured
#     remat as a 1.65x STEP-TIME win too (BASELINE.md round-4 session B).
run_bounded remat_xla_temp python scripts/measure_remat_memory.py --nodes 113140 \
  --xla-temp --json docs/artifacts/remat_memory_tpu.json

# 4. convergence in STAGES: at ~15 s/epoch on-chip the full 2500-epoch
#    protocol is ~10 h — longer than any observed tunnel window. Each stage
#    resumes from the previous stage's last_model.ckpt and captures
#    artifacts at its end, so every window that closes leaves committed-able
#    evidence.
#    CAVEAT: staging is only protocol-equivalent to one long run because
#    nbody_fastegnn.yaml has scheduler: None — a cosine schedule would be
#    rebuilt from each stage's own --epochs budget and diverge — and because
#    early_stop == epochs (2500): a resumed stage resets patience (best
#    tracking restarts at start_epoch), so early_stop < the full budget
#    would behave differently staged; convergence_session.sh guards this.
run convergence_100 env CALLER_PROBED=1 bash scripts/convergence_session.sh 100
run convergence_400 env CALLER_PROBED=1 bash scripts/convergence_session.sh 400
run convergence env CALLER_PROBED=1 bash scripts/convergence_session.sh

# The queue "drained" only if every item holds a done-marker — an item can
# fail (rc!=0, no marker) without aborting the queue, and the watcher exits
# for good on rc=0, so propagate incompleteness.
missing=0
done_items="" missing_items=""
for item in "${ITEMS[@]}"; do
  if [ -f "$DONE_DIR/$item" ]; then
    done_items="$done_items $item"
  else
    echo "incomplete: $item" >>"$LOG"
    missing_items="$missing_items $item"
    missing=$((missing + 1))
  fi
done
# One-line degraded-coverage summary naming what DID measure: the single
# line to read after a wedged window, instead of diffing the marker dir
# against the script (BENCH_r02-r05 left no such record).
echo "=== coverage: measured [${done_items# }] | missing [${missing_items# }] ===" >>"$LOG"
echo "=== hw_session done $(date -u +%FT%TZ), $missing item(s) incomplete ===" >>"$LOG"
[ "$missing" -gt 0 ] && exit 5
exit 0
