#!/usr/bin/env bash
# One-shot hardware measurement pass for a flaky TPU tunnel window.
#
# The axon tunnel wedges unpredictably (BASELINE.md), so when a window opens
# every pending measurement should run unattended, serially, with the host
# otherwise idle. IMPORTANT: SIGTERM/SIGKILL of a live TPU client strands the
# remote claim and wedges the tunnel for everyone (observed 2026-07-29 and
# again 2026-07-30 when a 25-min `timeout` killed profile_step) — so items
# run with NO kill timeout; a wedged tunnel hangs the queue instead of
# corrupting it, and the probe guards entry.
#
# This script:
#   1. probes the TPU (60 s timeout; a never-acquired client is safe to kill)
#      and exits 2 if wedged;
#   2. SIGSTOPs any running n-body generator (host contention degrades step
#      timing ~4x — BASELINE.md measurement discipline), resuming it on exit;
#   3. runs the measurement queue, appending output to $LOG;
#   4. finishes the n-body dataset on-chip and hands off to the convergence
#      run (scripts/convergence_session.sh) — the remaining MSE-parity
#      evidence (BASELINE.md round-2 status).
#
# Usage: bash scripts/hw_session.sh [logfile]   (default /tmp/hw_session.log)

set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/hw_session.log}

probe() {
  timeout 90 python -c "
import jax, jax.numpy as jnp
print('probe ok', float((jnp.ones((256,256))@jnp.ones((256,256))).sum()))" \
    >>"$LOG" 2>&1
}

echo "=== hw_session $(date -u +%FT%TZ) ===" >>"$LOG"
# The tunnel releases a client's claim slowly: a probe immediately after
# another client exits can hang even when the tunnel is healthy (observed
# twice 2026-07-30: manual probe ok, script probe 25 s later 'wedged').
# Retry a few times with spacing before giving up.
ok=""
for attempt in 1 2 3; do
  if probe; then ok=1; break; fi
  echo "probe attempt $attempt failed" >>"$LOG"
  [ "$attempt" -lt 3 ] && sleep 150
done
if [ -z "$ok" ]; then
  echo "TPU wedged; aborting" >>"$LOG"
  exit 2
fi

GEN_PIDS=$(pgrep -f "generate_nbody_chunked" || true)
# pytest / a CPU training run contend for the single host core too (a
# concurrent suite degraded step timing ~4x — BASELINE.md); pause them for
# the measurement window. The snapshot is taken NOW, so this session's own
# convergence run (started below) is never self-paused.
PYTEST_PIDS=$(pgrep -f "pytest|main\.py --config_path" || true)
resume() {
  [ -n "$GEN_PIDS" ] && kill -CONT $GEN_PIDS 2>/dev/null
  [ -n "$PYTEST_PIDS" ] && kill -CONT $PYTEST_PIDS 2>/dev/null
}
trap resume EXIT
[ -n "$GEN_PIDS" ] && kill -STOP $GEN_PIDS 2>/dev/null
[ -n "$PYTEST_PIDS" ] && kill -STOP $PYTEST_PIDS 2>/dev/null

run() {  # run <label> <cmd...> — NO kill timeout (see header)
  local label=$1; shift
  echo "--- $label ($(date -u +%T)) ---" >>"$LOG"
  "$@" >>"$LOG" 2>&1
  echo "--- $label rc=$? ---" >>"$LOG"
}

# 1. isolate the segment-sum lowerings (decides bench's default path)
run microbench_segsum python scripts/microbench_segsum.py
run microbench_segsum_bf16 python scripts/microbench_segsum.py --bf16
# 2. headline bench: auto = plain-cumsum vs plain-scatter in child processes
run bench_auto python bench.py
# 3. step breakdown on both plain lowerings
run profile_cumsum python scripts/profile_step.py --bf16 --seg cumsum
run profile_plain python scripts/profile_step.py --bf16

# 4. finish the n-body dataset on-chip (resumes any CPU-generated chunks)
#    and run the convergence session (MSE-parity evidence). The CPU generator
#    is SIGSTOPped: queue TERM first, then CONT so it can die (a TERM alone
#    stays pending on a stopped process forever); chunk writes are atomic
#    (tmp + rename), so termination mid-chunk cannot corrupt the dataset.
if [ -n "$GEN_PIDS" ]; then
  kill -TERM $GEN_PIDS 2>/dev/null
  kill -CONT $GEN_PIDS 2>/dev/null
  sleep 2
  GEN_PIDS=""
fi
run nbody_gen_tpu python scripts/generate_nbody_chunked.py \
  --path data/n_body_system/nbody_100 --n_isolated 100 \
  --num-train 5000 --num-valid 2000 --num-test 2000 --seed 43 \
  --budget 100000 --platform tpu
run convergence bash scripts/convergence_session.sh

echo "=== hw_session done $(date -u +%FT%TZ) ===" >>"$LOG"
