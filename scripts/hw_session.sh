#!/usr/bin/env bash
# One-shot hardware measurement pass for a flaky TPU tunnel window.
#
# The axon tunnel wedges unpredictably (BASELINE.md), so when a window opens
# every pending measurement should run unattended, serially, with the host
# otherwise idle. This script:
#   1. probes the TPU (60 s timeout) and exits 2 if wedged;
#   2. SIGSTOPs any running n-body generator (host contention degrades step
#      timing ~4x — BASELINE.md measurement discipline), resuming it on exit;
#   3. runs the measurement queue, appending JSON/readable output to $LOG.
#
# Usage: bash scripts/hw_session.sh [logfile]   (default /tmp/hw_session.log)

set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/hw_session.log}

probe() {
  timeout 60 python -c "
import jax, jax.numpy as jnp
print('probe ok', float((jnp.ones((256,256))@jnp.ones((256,256))).sum()))" \
    >>"$LOG" 2>&1
}

echo "=== hw_session $(date -u +%FT%TZ) ===" >>"$LOG"
if ! probe; then
  echo "TPU wedged; aborting" >>"$LOG"
  exit 2
fi

GEN_PIDS=$(pgrep -f "generate_nbody_chunked" || true)
resume() { [ -n "$GEN_PIDS" ] && kill -CONT $GEN_PIDS 2>/dev/null; }
trap resume EXIT
[ -n "$GEN_PIDS" ] && kill -STOP $GEN_PIDS 2>/dev/null

run() {  # run <label> <timeout_s> <cmd...>
  local label=$1 to=$2; shift 2
  echo "--- $label ($(date -u +%T)) ---" >>"$LOG"
  timeout "$to" "$@" >>"$LOG" 2>&1
  echo "--- $label rc=$? ---" >>"$LOG"
}

# 1. isolate the primitives: Pallas tile sweep + einsum variants
run microbench 2400 python scripts/microbench_blocked.py
# 2. headline bench: einsum blocked (256 and 128), plain control
run bench_einsum_256 1200 python bench.py --layout blocked --impl einsum
run bench_einsum_128 1200 env BENCH_EDGE_BLOCK=128 \
  python bench.py --layout blocked --impl einsum
run bench_plain 1200 python bench.py --layout plain
# 3. step breakdown on the best-known layout
run profile_einsum 1200 python scripts/profile_step.py --bf16 --edge-block 256
run profile_plain 1200 python scripts/profile_step.py --bf16

echo "=== hw_session done $(date -u +%FT%TZ) ===" >>"$LOG"
