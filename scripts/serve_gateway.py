"""Serving gateway: config -> ModelRegistry -> HTTP serve loop.

Boots every model named by the config's ``serve.models:`` list (or one
model from the config itself when the list is absent), warms each engine's
rungs, then serves the JSON predict API plus operational endpoints
(docs/SERVING.md "Transport"):

  POST /v1/models/<name>/predict    GET /v1/models
  GET  /metrics   GET /healthz   GET /readyz

SIGTERM/SIGINT drain gracefully: /readyz flips to 503, in-flight queues
flush (every accepted request gets a real response), then the process exits
0 — the serving-edge mirror of the trainer's preemption contract.

  python scripts/serve_gateway.py --config_path configs/nbody_serve.yaml

CPU works (JAX_PLATFORMS=cpu); the same gateway runs unchanged on TPU.
``--port 0`` binds an ephemeral port (printed in the listening line — the
smoke drill in tests/test_cli_e2e.py parses it). Obs events land at
``--obs-dir/obs/events.jsonl``; warmup is marked done after all models
warm, so ``python scripts/obs_report.py <stream> --check`` flags any
steady-state recompile.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="distegnn serving gateway")
    ap.add_argument("--config_path", type=str, default=None,
                    help="YAML with serve:/serve.models: sections "
                         "(default: built-ins)")
    ap.add_argument("--host", type=str, default=None,
                    help="bind host (default: serve.gateway.host)")
    ap.add_argument("--port", type=int, default=None,
                    help="bind port, 0 = ephemeral "
                         "(default: serve.gateway.port)")
    ap.add_argument("--max-inflight", type=int, default=None,
                    help="gateway shed gate (default: "
                         "serve.gateway.max_inflight)")
    ap.add_argument("--warmup-nodes", type=str, default=None,
                    help="comma-separated node counts warmed per model "
                         "(default: serve.gateway.warmup_nodes)")
    ap.add_argument("--obs-dir", type=str, default="logs/serve_gateway",
                    help="event-stream sink dir (events land at <dir>/obs/"
                         "events.jsonl); '' disables tracing")
    args = ap.parse_args(argv)

    from distegnn_tpu import obs
    from distegnn_tpu.config import ConfigDict, _DEFAULTS, load_config
    from distegnn_tpu.obs import jaxprobe
    from distegnn_tpu.serve.registry import ModelRegistry
    from distegnn_tpu.serve.transport import Gateway

    cfg = (load_config(args.config_path) if args.config_path
           else ConfigDict(_DEFAULTS))
    if args.obs_dir:
        obs.configure_from_config(cfg, args.obs_dir,
                                  tags={"run": "serve_gateway"})
    g = cfg.serve.gateway
    warmup_nodes = ([int(n) for n in args.warmup_nodes.split(",") if n]
                    if args.warmup_nodes else [int(n) for n in
                                               g.warmup_nodes])

    registry = ModelRegistry.from_config(cfg)
    registry.start()
    obs.log(f"gateway: warming {len(registry)} model(s) at node sizes "
            f"{warmup_nodes}")
    registry.warmup(warmup_nodes)
    # compiles past this point are regressions obs_report --check flags
    jaxprobe.mark_warmup_done()
    jaxprobe.set_phase("serve/http")

    s = cfg.serve
    gateway = Gateway(
        registry,
        host=args.host if args.host is not None else str(g.host),
        port=args.port if args.port is not None else int(g.port),
        max_inflight=(args.max_inflight if args.max_inflight is not None
                      else int(g.max_inflight)),
        drain_grace_s=float(g.drain_grace_s),
        slo_window_s=float((cfg.get("slo") or {}).get("window_s", 60.0)
                           or 60.0),
        autoscale=dict(s.autoscale),
        priority=dict(s.priority),
        stream_chunk_steps=int(s.stream.chunk_steps),
        promote=dict(cfg.get("promote") or {}))
    gateway.install_signal_handlers()
    host, port = gateway.address
    obs.log(f"gateway: listening on http://{host}:{port} "
            f"(models: {', '.join(registry.names())}; "
            f"ready={gateway.ready()})")
    gateway.serve_forever()          # returns after a signal-driven drain

    gateway.close()
    registry.stop(drain=True)        # idempotent: drain already ran this
    obs.log("gateway: drained and stopped; exiting 0")
    obs.get_tracer().flush()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
