"""Micro-benchmarks for the blocked one-hot MXU kernels (ops/blocked.py) vs
the XLA sorted-scatter path, at LargeFluid shape.

The round-2 prediction (docs/PERFORMANCE.md) was that the blocked kernels
bound the hot aggregations near HBM bandwidth; the first hardware run of the
full step measured SLOWER than the plain path (BASELINE.md). This isolates
the primitives to find out which one lies: times blocked_segment_sum /
blocked_gather across (dtype, tile) against scatter/segment-sum/gather on the
same data, plus the paired backward-gather path.

Usage: python scripts/microbench_blocked.py [--quick]
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = 113_152          # 442 blocks of 256
BLOCK = 256
H = 64
AVG_DEG = 14.5       # bench workload: E ~ 1.64M


def timed(fn, *args, warmup=2, steps=10):
    import jax.numpy as jnp

    def sync(o):
        np.asarray(jnp.ravel(o)[0])

    for _ in range(warmup):
        out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / steps * 1e3


def main():
    import jax
    import jax.numpy as jnp

    from distegnn_tpu.ops.blocked import (
        blockify_edges, pairing_perm, slot_ids, _gather, _seg_sum,
    )

    quick = "--quick" in sys.argv
    rng = np.random.default_rng(0)

    # synthetic symmetric radius-like graph: undirected pairs, both directions
    E_half = int(N * AVG_DEG) // 2
    src = rng.integers(0, N, size=E_half)
    dst = (src + rng.integers(1, 200, size=E_half)) % N   # mild locality
    ei = np.concatenate([np.stack([src, dst]), np.stack([dst, src])], axis=1)
    order = np.argsort(ei[0], kind="stable")
    ei = ei[:, order].astype(np.int64)
    E_real = ei.shape[1]

    results = {}
    for tile in (512,) if quick else (512, 1024, 2048):
        epb_raw = -(-int(np.diff(np.searchsorted(ei[0], np.arange(0, N + 1, BLOCK))).max()) // tile) * tile
        bei, _, bmask = blockify_edges(ei, None, N, epb_raw, BLOCK)
        E_blk = bei.shape[1]
        slot = np.asarray(slot_ids(jnp.asarray(bei[0]), jnp.asarray(bmask), BLOCK, epb_raw))
        pair = pairing_perm(bei)
        slot_j = jnp.asarray(slot)
        for dt in (jnp.float32, jnp.bfloat16):
            x = jnp.asarray(rng.normal(size=(E_blk, H)).astype(np.float32)).astype(dt)
            h = jnp.asarray(rng.normal(size=(N, H)).astype(np.float32)).astype(dt)
            f_seg = jax.jit(lambda d, s, t=tile: _seg_sum(d, s, N, BLOCK, t))
            f_gat = jax.jit(lambda hh, s, t=tile: _gather(hh, s, BLOCK, t))
            key = f"tile{tile}_{dt.__name__}"
            results[f"blocked_seg_{key}"] = timed(f_seg, x, slot_j)
            results[f"blocked_gather_{key}"] = timed(f_gat, h, slot_j)
        if pair is not None:
            g32 = jnp.asarray(rng.normal(size=(E_blk, H)).astype(np.float32))
            pair_j = jnp.asarray(pair)
            f_pb = jax.jit(lambda g, p, s, t=tile: _seg_sum(jnp.take(g, p, axis=0), s, N, BLOCK, t))
            results[f"paired_bwd_tile{tile}_f32"] = timed(f_pb, g32, pair_j, slot_j)
        print(f"# tile={tile}: E_real={E_real} E_blocked={E_blk} "
              f"(pad waste {(E_blk / E_real - 1) * 100:.0f}%)", flush=True)

        # einsum lowering on the same layout (tile-independent; once is enough)
        if tile == 512:
            from distegnn_tpu.ops.blocked import (
                _ein_gather_raw, _ein_seg_sum_raw, onehot_blocks,
            )

            f_oh = jax.jit(lambda s: onehot_blocks(s, epb_raw, BLOCK))
            oh = f_oh(slot_j)
            results["einsum_onehot_build"] = timed(f_oh, slot_j)
            f_eseg = jax.jit(_ein_seg_sum_raw)
            f_egat = jax.jit(_ein_gather_raw)
            for dt in (jnp.float32, jnp.bfloat16):
                x = jnp.asarray(rng.normal(size=(E_blk, H)).astype(np.float32)).astype(dt)
                h = jnp.asarray(rng.normal(size=(N, H)).astype(np.float32)).astype(dt)
                nm = dt.__name__
                results[f"einsum_seg_{nm}"] = timed(f_eseg, x, oh)
                results[f"einsum_gather_{nm}"] = timed(f_egat, h, oh)

    # XLA reference points on the same (unblocked) sorted edge list
    ids = jnp.asarray(ei[0].astype(np.int32))
    for dt in (jnp.float32, jnp.bfloat16):
        x = jnp.asarray(rng.normal(size=(E_real, H)).astype(np.float32)).astype(dt)
        h = jnp.asarray(rng.normal(size=(N, H)).astype(np.float32)).astype(dt)
        nm = dt.__name__
        results[f"xla_scatter_sorted_{nm}"] = timed(
            jax.jit(lambda d, i: jnp.zeros((N, H), d.dtype).at[i].add(d)), x, ids)
        results[f"xla_segsum_flag_{nm}"] = timed(
            jax.jit(lambda d, i: jax.ops.segment_sum(d, i, num_segments=N,
                                                     indices_are_sorted=True)), x, ids)
        results[f"xla_gather_{nm}"] = timed(jax.jit(lambda hh, i: hh[i]), h, ids)

    for k, v in results.items():
        print(f"{k:36s} {v:8.2f} ms")


if __name__ == "__main__":
    main()
