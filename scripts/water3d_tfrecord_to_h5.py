"""Convert DeepMind learning_to_simulate Water-3D tfrecords to the h5 layout
the Water-3D pipeline reads (reference dataset_generation/Water-3D/
tfrecord_to_h5.py — which depends on DeepMind's reading_utils; this version
parses the tf.SequenceExample format directly and is otherwise equivalent:
one h5 group per trajectory with `particle_type` [N] and `position` [T, N, 3]).

Requires tensorflow (read-only use). Usage:
  python scripts/water3d_tfrecord_to_h5.py --dataset-path data/simulate/Water-3D
"""

from __future__ import annotations

import argparse
import json
import os

import h5py
import numpy as np


def convert(dataset_path: str, file_name: str, dim: int = 3) -> str:
    import tensorflow as tf

    path = os.path.join(dataset_path, file_name)
    print(f"Converting {path} -> h5")
    out_path = path[:-len(".tfrecord")] + ".h5"

    context_desc = {
        "key": tf.io.FixedLenFeature([], tf.int64, default_value=0),
        "particle_type": tf.io.VarLenFeature(tf.string),
    }
    seq_desc = {"position": tf.io.VarLenFeature(tf.string)}

    with h5py.File(out_path, "w") as hf:
        for i, record in enumerate(tf.data.TFRecordDataset([path])):
            context, seq = tf.io.parse_single_sequence_example(
                record, context_features=context_desc, sequence_features=seq_desc)
            ptype = np.frombuffer(
                tf.sparse.to_dense(context["particle_type"]).numpy()[0], dtype=np.int64)
            pos_bytes = tf.sparse.to_dense(seq["position"]).numpy()
            position = np.stack([
                np.frombuffer(b[0], dtype=np.float32).reshape(-1, dim) for b in pos_bytes
            ])
            traj = str(i).zfill(5)
            hf.create_dataset(f"{traj}/particle_type", data=ptype)
            hf.create_dataset(f"{traj}/position", data=position,
                              dtype=np.float32, compression="gzip")
    print(f"Wrote {out_path}")
    return out_path


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--dataset-path", type=str, required=True)
    args = parser.parse_args()

    files = [f for f in os.listdir(args.dataset_path) if f.endswith(".tfrecord")]
    for f in files:
        convert(args.dataset_path, f)

    # record num_particles_max in metadata.json (reference does the same)
    meta_path = os.path.join(args.dataset_path, "metadata.json")
    if os.path.exists(meta_path):
        with open(meta_path) as fp:
            metadata = json.load(fp)
        max_particles = 0
        for f in os.listdir(args.dataset_path):
            if f.endswith(".h5"):
                with h5py.File(os.path.join(args.dataset_path, f), "r") as hf:
                    for v in hf.values():
                        max_particles = max(int(v["particle_type"].shape[0]), max_particles)
        metadata["num_particles_max"] = max_particles
        metadata["periodic_boundary_conditions"] = [False, False, False]
        with open(meta_path, "w") as fp:
            json.dump(metadata, fp)


if __name__ == "__main__":
    main()
