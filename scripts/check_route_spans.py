#!/usr/bin/env python
"""Lint: every transport route handler runs inside a ``serve/http`` span
carrying a ``request_id``.

The request-tracing contract (docs/OBSERVABILITY.md "Request tracing")
hangs off one chokepoint: ``Gateway.dispatch`` mints the request id and
opens the ``serve/http`` span, and every ``do_*`` HTTP verb method on the
handler class forwards straight to it. A handler that answers on its own
("bare") produces requests that are invisible to the waterfall stitcher
and the SLO monitor — exactly the silent hole this lint exists to catch.

Checked, by AST walk over distegnn_tpu/serve/transport.py:
  1. every ``do_*`` method on every request-handler class is a pure
     forward: its only statement is a ``....dispatch(self, ...)`` call;
  2. every ``dispatch`` method that do_* methods forward to
     - calls ``mint_request_id`` and assigns ``<handler>.request_id``,
     - opens ``with obs.span("serve/http", ..., request_id=...)``,
     - performs its route handling (the ``_handle`` call) INSIDE that
       span, so the span's duration and status cover the whole request.

Wired into tier-1 via tests/test_tracing.py::test_route_span_lint_clean.
Exit codes: 0 clean, 1 violations (one ``path:line: text`` per finding).
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRANSPORT = os.path.join(REPO, "distegnn_tpu", "serve", "transport.py")


def _is_dispatch_forward(stmt: ast.stmt) -> bool:
    """True for ``<anything>.dispatch(self, ...)`` as a bare statement."""
    if not isinstance(stmt, ast.Expr) or not isinstance(stmt.value, ast.Call):
        return False
    fn = stmt.value.func
    if not (isinstance(fn, ast.Attribute) and fn.attr == "dispatch"):
        return False
    args = stmt.value.args
    return bool(args) and isinstance(args[0], ast.Name) and args[0].id == "self"


def _span_call(node: ast.AST):
    """The ``obs.span("serve/http", ...)`` Call under a with-item, if any."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    named_span = (isinstance(fn, ast.Attribute) and fn.attr == "span") or \
                 (isinstance(fn, ast.Name) and fn.id == "span")
    if not named_span or not node.args:
        return None
    first = node.args[0]
    if isinstance(first, ast.Constant) and first.value == "serve/http":
        return node
    return None


def _calls_name(tree: ast.AST, name: str) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            if (isinstance(fn, ast.Name) and fn.id == name) or \
                    (isinstance(fn, ast.Attribute) and fn.attr == name):
                return True
    return False


def _assigns_request_id(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and \
                        tgt.attr == "request_id":
                    return True
    return False


def _check_dispatch(fn: ast.FunctionDef, rel: str):
    """Violations for one dispatch method."""
    out = []
    if not _calls_name(fn, "mint_request_id"):
        out.append((rel, fn.lineno,
                    f"{fn.name} never mints a request id "
                    "(mint_request_id call missing)"))
    if not _assigns_request_id(fn):
        out.append((rel, fn.lineno,
                    f"{fn.name} never stashes handler.request_id "
                    "(the X-Request-Id echo reads it)"))
    span_withs = []
    for node in ast.walk(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                call = _span_call(item.context_expr)
                if call is not None:
                    span_withs.append((node, call))
    if not span_withs:
        out.append((rel, fn.lineno,
                    f"{fn.name} opens no obs.span(\"serve/http\") — "
                    "requests here are invisible to the waterfall/SLOs"))
        return out
    for _, call in span_withs:
        if not any(kw.arg == "request_id" for kw in call.keywords):
            out.append((rel, call.lineno,
                        "serve/http span carries no request_id= attr"))
    # the route handling must happen INSIDE the span, or its duration and
    # status cover nothing
    handled_inside = any(_calls_name(w, "_handle") for w, _ in span_withs)
    if _calls_name(fn, "_handle") and not handled_inside:
        out.append((rel, fn.lineno,
                    f"{fn.name} calls _handle OUTSIDE the serve/http span"))
    return out


def find_violations(path: str = TRANSPORT):
    """[(relpath, lineno, message)] against the tracing contract."""
    rel = os.path.relpath(path, REPO).replace(os.sep, "/")
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)

    out = []
    do_methods, dispatches = [], []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if item.name.startswith("do_"):
                    do_methods.append(item)
                elif item.name == "dispatch":
                    dispatches.append(item)

    if not do_methods:
        out.append((rel, 1, "no do_* HTTP verb methods found — transport "
                            "layout changed under the lint; update "
                            "scripts/check_route_spans.py"))
    for m in do_methods:
        body = [s for s in m.body
                if not (isinstance(s, ast.Expr)
                        and isinstance(s.value, ast.Constant))]  # docstring
        if len(body) != 1 or not _is_dispatch_forward(body[0]):
            out.append((rel, m.lineno,
                        f"bare handler {m.name}: must forward to "
                        "gateway.dispatch(self, ...) and nothing else"))

    if not dispatches:
        out.append((rel, 1, "no dispatch method found — the serve/http "
                            "span chokepoint is gone"))
    for d in dispatches:
        out.extend(_check_dispatch(d, rel))
    return out


def main(argv=None) -> int:
    violations = find_violations()
    for rel, lineno, msg in violations:
        print(f"{rel}:{lineno}: {msg}")
    if violations:
        print(f"\n{len(violations)} route-span violation(s); see "
              "scripts/check_route_spans.py docstring for the contract")
        return 1
    print("check_route_spans: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
