"""Resumable chunked n-body generation (single-core hosts, bounded runtime).

Writes chunks of trajectories to <path>/chunks/{split}_{i:04d}.npz, skipping
chunks that already exist, and exits cleanly after --budget seconds. When all
chunks are present it merges them into the reference .npy layout
(generate_dataset.py:86-118) and removes the chunk dir. Re-invoke until it
prints DONE. Same physics as scripts/generate_nbody.py (batched integrator,
distegnn_tpu/data/nbody_sim.py); each chunk seeds its own RNG from
(seed, split, chunk index) so resumption is deterministic.

Deliberate delta from generate_nbody_files: integrates and stores float32
(half the time and disk on a bandwidth-starved host; the training pipeline
casts to f32 at graph build anyway). For reference-dtype (float64) output use
scripts/generate_nbody.py.

  python scripts/generate_nbody_chunked.py --path data/n_body_system/nbody_100 \
      --n_isolated 100 --num-train 5000 --num-valid 2000 --num-test 2000 \
      --seed 43 --budget 480
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

# Pin the backend BEFORE it initializes. Default is CPU: on axon-tunnel hosts
# the env var JAX_PLATFORMS alone does not stop the tunnel backend from
# initializing (its get_backend hook initializes all discovered platforms) —
# a wedged tunnel then hangs this offline generator. config.update is honored;
# same pattern as tests/conftest.py. ``--platform tpu`` opts back into the
# chip when the tunnel is alive (the jitted scan integrator makes the full
# 9,000-trajectory dataset a ~3-minute job there vs hours on one CPU core).
import jax  # noqa: E402

_plat = "cpu"
for _i, _a in enumerate(sys.argv):
    if _a == "--platform":
        if _i + 1 >= len(sys.argv):
            sys.exit("--platform requires a value (cpu|tpu|auto)")
        _plat = sys.argv[_i + 1]
    elif _a.startswith("--platform="):
        _plat = _a.split("=", 1)[1]
# "tpu" must NOT pin jax_platforms="tpu": on axon-tunnel hosts the chip is
# served by the experimental "axon" platform, and requesting "tpu" tries a
# local TPU init that dies with "No jellyfish device found" (hardware run
# 2026-08-02). Default platform resolution prefers any available accelerator,
# which is the intent of --platform tpu on every host we run on.
if _plat not in ("auto", "tpu"):
    jax.config.update("jax_platforms", _plat)
if _plat in ("auto", "tpu"):
    # default resolution can silently land on CPU (e.g. dead tunnel) — say
    # what we actually got, and fail the explicit-tpu request loudly rather
    # than run a ~3-minute accelerator job for hours on one core
    _got = jax.devices()[0].platform
    print(f"generate_nbody_chunked: backend={_got} "
          f"({jax.devices()[0].device_kind})", flush=True)
    if _plat == "tpu" and _got == "cpu":
        sys.exit("--platform tpu requested but only CPU is available "
                 "(tunnel down?); use --platform cpu to run on CPU anyway")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distegnn_tpu.data.nbody_sim import simulate_trajectories_batched  # noqa: E402

CHUNK = 256


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--path", type=str, required=True)
    p.add_argument("--num-train", type=int, default=5000)
    p.add_argument("--num-valid", type=int, default=2000)
    p.add_argument("--num-test", type=int, default=2000)
    p.add_argument("--length", type=int, default=5000)
    p.add_argument("--sample-freq", type=int, default=100)
    p.add_argument("--n_isolated", type=int, default=100)
    p.add_argument("--clusters", type=int, default=1)
    p.add_argument("--seed", type=int, default=43)
    p.add_argument("--budget", type=float, default=480.0)
    p.add_argument("--platform", type=str, default="cpu",
                   help="jax backend: cpu (default, tunnel-safe) | tpu | auto")
    args = p.parse_args()

    tag = f"charged{args.n_isolated}_0_0_{args.clusters}"
    chunk_dir = os.path.join(args.path, "chunks")
    os.makedirs(chunk_dir, exist_ok=True)
    t0 = time.perf_counter()

    splits = [("train", args.num_train), ("valid", args.num_valid), ("test", args.num_test)]
    todo = done = 0
    for split, num in splits:
        n_chunks = (num + CHUNK - 1) // CHUNK
        for ci in range(n_chunks):
            f = os.path.join(chunk_dir, f"{split}_{ci:04d}.npz")
            if os.path.exists(f):
                done += 1
                continue
            if time.perf_counter() - t0 > args.budget:
                todo += 1
                continue
            n = min(CHUNK, num - ci * CHUNK)
            split_id = {"train": 0, "valid": 1, "test": 2}[split]
            rng = np.random.default_rng([args.seed, split_id, ci])
            loc, vel, ch, ed = simulate_trajectories_batched(
                rng, n, args.length, args.sample_freq,
                n_isolated=args.n_isolated, clusters=args.clusters,
                dtype="float32")
            np.savez(f + ".tmp.npz", loc=loc, vel=vel, charges=ch, edges=ed)
            os.replace(f + ".tmp.npz", f)
            done += 1
            print(f"chunk {split}/{ci} ({n} traj) done "
                  f"[{time.perf_counter() - t0:.0f}s]", flush=True)

    if todo:
        print(f"PARTIAL: {done} chunks done, {todo} remaining — re-invoke to continue")
        return

    for split, num in splits:
        n_chunks = (num + CHUNK - 1) // CHUNK
        parts = [np.load(os.path.join(chunk_dir, f"{split}_{ci:04d}.npz"))
                 for ci in range(n_chunks)]
        for key, name in (("loc", "loc"), ("vel", "vel"),
                          ("charges", "charges"), ("edges", "edges")):
            arr = np.concatenate([p[key] for p in parts])[:num]
            np.save(os.path.join(args.path, f"{name}_{split}_{tag}.npy"), arr)
        print(f"merged {split}: {num} trajectories", flush=True)
    for f in os.listdir(chunk_dir):
        os.remove(os.path.join(chunk_dir, f))
    os.rmdir(chunk_dir)
    print("DONE")


if __name__ == "__main__":
    main()
