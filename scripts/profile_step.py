"""Step-time breakdown + MFU for the bench workload (VERDICT r1 item 1).

Times the LargeFluid-shape FastEGNN train step end-to-end and in pieces
(forward, forward+loss, grad, MMD on/off), reports XLA cost-analysis FLOPs and
an MFU estimate, and optionally captures a jax.profiler trace.

Usage:
  python scripts/profile_step.py [--trace DIR] [--steps 10]

Prints a JSON breakdown; paste the table into BASELINE.md.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

# TPU v5e (v5 lite) peak: 197 TFLOP/s bf16, ~98.5 TFLOP/s fp32 (public spec).
PEAK_FLOPS = {"bf16": 197e12, "f32": 98.5e12}


def timed(fn, *args, warmup=3, steps=10):
    """Sync via a 1-element device->host fetch — block_until_ready alone
    under-reports on the axon tunnel (see scripts/microbench_ops.py)."""
    import jax
    import jax.numpy as jnp

    def sync(o):
        leaf = jax.tree.leaves(o)[0]
        np.asarray(jnp.ravel(leaf)[0])

    out = None
    for _ in range(warmup):
        out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / steps


def cost_flops(jitted, *args):
    try:
        an = jitted.lower(*args).compile().cost_analysis()
        if isinstance(an, list):
            an = an[0]
        return float(an.get("flops", float("nan")))
    except Exception:
        return float("nan")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", default=None, help="dir for jax.profiler trace")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--nodes", type=int, default=113_140)
    ap.add_argument("--bf16", action="store_true", help="compute_dtype='bf16'")
    ap.add_argument("--edge-block", type=int, default=0,
                    help="blocked edge layout (0 = plain)")
    ap.add_argument("--impl", default="einsum", choices=["einsum", "pallas"],
                    help="blocked-op lowering (with --edge-block)")
    ap.add_argument("--seg", default="scatter", choices=["scatter", "cumsum", "ell"],
                    help="plain-layout aggregation lowering")
    args = ap.parse_args()

    import jax

    from bench import HIDDEN, LAYERS, CHANNELS, make_fluid_batch
    import bench as bench_mod

    bench_mod.N_NODES = args.nodes
    from distegnn_tpu.models.fast_egnn import FastEGNN
    from distegnn_tpu.train import TrainState, make_optimizer, make_train_step
    from distegnn_tpu.train.loss import masked_mse, mmd_loss

    rng = np.random.default_rng(0)
    batch, n_edges = make_fluid_batch(rng, edge_block=args.edge_block,
                                      pairing=(args.seg in ("cumsum", "ell")))
    dev = jax.devices()[0]
    batch = jax.device_put(batch, dev)

    model = FastEGNN(node_feat_nf=3, node_attr_nf=2, edge_attr_nf=2,
                     hidden_nf=HIDDEN, virtual_channels=CHANNELS, n_layers=LAYERS,
                     compute_dtype="bf16" if args.bf16 else None,
                     blocked_impl=args.impl, segment_impl=args.seg)
    params = model.init(jax.random.PRNGKey(0), batch)
    tx = make_optimizer(5e-4, weight_decay=1e-12, clip_norm=0.3)
    state = TrainState.create(params, tx)
    key = jax.random.PRNGKey(7)

    fwd = jax.jit(model.apply)
    step_mmd = jax.jit(make_train_step(model, tx, mmd_weight=0.01, mmd_sigma=3.0,
                                       mmd_samples=50))
    step_nommd = jax.jit(make_train_step(model, tx, mmd_weight=0.0, mmd_sigma=3.0,
                                         mmd_samples=50))

    def loss_only(p, b, k):
        pred, vloc = model.apply(p, b)
        return masked_mse(pred, b.target, b.node_mask) + 0.01 * mmd_loss(
            vloc, b.target, b.node_mask, k, 3.0, 50)

    grad_fn = jax.jit(jax.grad(loss_only))
    mmd_only = jax.jit(lambda v, b, k: mmd_loss(v, b.target, b.node_mask, k, 3.0, 50))

    vloc = jnp_zeros = None
    import jax.numpy as jnp
    vloc = jnp.zeros((1, 3, CHANNELS))

    from bench import layout_tag

    res = {"n_nodes": args.nodes, "n_edges": int(n_edges),
           "platform": dev.platform, "device": str(dev.device_kind),
           "layout": layout_tag(args.edge_block, args.impl, args.seg)}
    res["t_forward_ms"] = timed(fwd, params, batch, steps=args.steps) * 1e3
    res["t_grad_ms"] = timed(grad_fn, params, batch, key, steps=args.steps) * 1e3
    res["t_step_full_ms"] = timed(step_mmd, state, batch, key, steps=args.steps) * 1e3
    res["t_step_nommd_ms"] = timed(step_nommd, state, batch, key, steps=args.steps) * 1e3
    res["t_mmd_only_ms"] = timed(mmd_only, vloc, batch, key, steps=args.steps) * 1e3
    res["t_optimizer_ms"] = res["t_step_full_ms"] - res["t_grad_ms"] - res["t_mmd_only_ms"]

    res["flops_forward"] = cost_flops(fwd, params, batch)
    res["flops_step"] = cost_flops(step_mmd, state, batch, key)
    step_s = res["t_step_full_ms"] / 1e3
    res["achieved_tflops"] = res["flops_step"] / step_s / 1e12
    res["mfu_vs_f32_peak"] = res["flops_step"] / step_s / PEAK_FLOPS["f32"]
    res["mfu_vs_bf16_peak"] = res["flops_step"] / step_s / PEAK_FLOPS["bf16"]
    res["nodes_per_sec"] = args.nodes / step_s

    if args.trace:
        with jax.profiler.trace(args.trace):
            for i in range(3):
                state, m = step_mmd(state, batch, jax.random.PRNGKey(i))
            jax.block_until_ready(m["loss"])
        res["trace_dir"] = args.trace

    print(json.dumps({k: (round(v, 3) if isinstance(v, float) else v)
                      for k, v in res.items()}, indent=2))


if __name__ == "__main__":
    main()
