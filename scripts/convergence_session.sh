#!/usr/bin/env bash
# N-body convergence run + artifact capture (BASELINE.md MSE-parity evidence).
#
# Run on a live TPU tunnel (CPU epochs are ~15+ min on this host; TPU epochs
# with scan_epochs are sub-second). Produces:
#   - logs/nbody/<exp>/log/log.json        (loss curves, best MSEs, time_cost)
#   - docs/artifacts/nbody_fastegnn_log.json  (tracked copy; logs/ is ignored)
#   - docs/artifacts/nbody_rollout_mse.json   (rollout MSE with the best ckpt)
#
# Usage: bash scripts/convergence_session.sh [epochs]   (default: full 2500)

# pipefail: main.py is piped through tee below — without it a training crash
# is masked by tee's rc=0 and stale artifacts would be copied as evidence.
set -euo pipefail
cd "$(dirname "$0")/.."
EPOCHS=${1:-2500}

# Probe unless the caller (hw_session run()) already probe-gated this item —
# its probe cycle is up to ~9.5 min and a second one wastes the window.
if [ -z "${CALLER_PROBED:-}" ]; then
  bash scripts/tpu_probe.sh /dev/stdout \
    || { echo "TPU wedged; aborting (do not run this on CPU)"; exit 2; }
  sleep 30  # let the probe client's claim release before main.py acquires
fi

# Dataset sentinel: overridable by the caller (hw_session exports the same
# path) so the tag literal lives in one place per invocation chain.
NBODY_DONE=${NBODY_DONE:-data/n_body_system/nbody_100/loc_train_charged100_0_0_1.npy}
test -f "$NBODY_DONE" \
  || { echo "dataset missing; run scripts/generate_nbody_chunked.py first"; exit 3; }

# Resume a previously aborted run (tunnel death mid-training) instead of
# restarting: the trainer writes last_model.ckpt every test_interval epochs
# and main.py --checkpoint restores state + start_epoch. The resumed run
# logs to a fresh exp dir; its log.json covers the resumed span. A FINISHED
# prior run (early-stopped, or full epoch budget: log.json = [best, log,
# cfg], "early_stop" in best or len(loss_train) >= epochs) must NOT be
# resumed — main.py would run zero epochs and never write log.json; capture
# its artifacts directly instead (covers a crash between training and
# artifact capture as well).
run_finished() {  # run_finished <last_model.ckpt> <log.json> <epochs>
  # The ckpt's stored epoch is authoritative (a resumed run's own log.json
  # covers only the resumed span, so log length would under-count). The
  # trainer writes last_model.ckpt only on eval epochs, so a finished run's
  # newest ckpt records the LAST EVAL epoch — compare against that, not the
  # raw epoch budget (else epochs not divisible by test_interval resume
  # forever).
  python - "$1" "$2" "$3" <<'EOF'
import json, pickle, sys
payload = pickle.load(open(sys.argv[1], "rb"))
epochs = int(sys.argv[3])
interval = int(payload["config"]["log"]["test_interval"])
best = json.load(open(sys.argv[2]))[0]
done = "early_stop" in best or payload["epoch"] >= epochs - (epochs % interval)
raise SystemExit(0 if done else 1)
EOF
}

CKPT_ARGS=()
RUN_TRAINING=1
LAST=$(ls -dt logs/nbody/*/state_dict/last_model.ckpt 2>/dev/null | head -1 || true)
if [ -n "$LAST" ]; then
  PREV_EXP=$(dirname "$(dirname "$LAST")")
  if [ -f "$PREV_EXP/log/log.json" ] && run_finished "$LAST" "$PREV_EXP/log/log.json" "$EPOCHS"; then
    echo "previous run $PREV_EXP already finished — capturing artifacts only"
    RUN_TRAINING=0
  else
    echo "resuming from $LAST"
    CKPT_ARGS=(--checkpoint "$LAST")
  fi
fi

if [ "$RUN_TRAINING" -eq 1 ]; then
  python -u main.py --config_path configs/nbody_fastegnn.yaml --epochs "$EPOCHS" \
    ${CKPT_ARGS[@]+"${CKPT_ARGS[@]}"} \
    2>&1 | tee /tmp/convergence_run.log
fi

# Capture artifacts from the run dir with the BEST valid loss across all
# runs, not just the newest: a resumed run restarts best-tracking in a fresh
# exp dir, so its best ckpt covers only the resumed span — the pre-abort run
# may hold the true best. (To force a completely FRESH convergence run after
# code/config changes: rm -rf logs/nbody AND /tmp/hw_done.)
EXP=$(python - <<'EOF'
import glob, json, os
best = (None, float("inf"))
for log in glob.glob("logs/nbody/*/log/log.json"):
    try:
        lv = json.load(open(log))[0]["loss_valid"]
    except Exception:
        continue
    if lv < best[1]:
        best = (os.path.dirname(os.path.dirname(log)), lv)
if best[0] is None:
    raise SystemExit("no run with a log.json found under logs/nbody")
print(best[0])
EOF
)
mkdir -p docs/artifacts
# trainer writes the log under <exp>/log/log.json (trainer.py log_dir)
cp "$EXP/log/log.json" docs/artifacts/nbody_fastegnn_log.json.tmp
mv docs/artifacts/nbody_fastegnn_log.json.tmp docs/artifacts/nbody_fastegnn_log.json
CKPT="$EXP/state_dict/best_model.ckpt"
if [ -f "$CKPT" ]; then
  # temp + rename on the SAME filesystem: a crash mid-eval (or mid-copy)
  # must not truncate previously-good evidence
  python scripts/evaluate_rollout.py --config_path configs/nbody_fastegnn.yaml \
    --checkpoint "$CKPT" --samples 200 \
    > docs/artifacts/nbody_rollout_mse.json.tmp
  mv docs/artifacts/nbody_rollout_mse.json.tmp docs/artifacts/nbody_rollout_mse.json
fi
echo "artifacts written under docs/artifacts/ — record the best MSEs in BASELINE.md and commit"
