#!/usr/bin/env bash
# N-body convergence run + artifact capture (BASELINE.md MSE-parity evidence).
#
# Run on a live TPU tunnel (CPU epochs are ~15+ min on this host; TPU epochs
# with scan_epochs are sub-second). Produces:
#   - logs/nbody/<exp>/log/log.json        (loss curves, best MSEs, time_cost)
#   - docs/artifacts/nbody_fastegnn_log.json  (tracked copy; logs/ is ignored)
#   - docs/artifacts/nbody_rollout_mse.json   (rollout MSE with the best ckpt)
#
# Usage: bash scripts/convergence_session.sh [epochs]   (default: full 2500)

# pipefail: main.py is piped through tee below — without it a training crash
# is masked by tee's rc=0 and stale artifacts would be copied as evidence.
set -euo pipefail
cd "$(dirname "$0")/.."
EPOCHS=${1:-2500}

# Probe unless the caller (hw_session run()) already probe-gated this item —
# its probe cycle is up to ~9.5 min and a second one wastes the window.
if [ -z "${CALLER_PROBED:-}" ]; then
  bash scripts/tpu_probe.sh /dev/stdout \
    || { echo "TPU wedged; aborting (do not run this on CPU)"; exit 2; }
  sleep 30  # let the probe client's claim release before main.py acquires
fi

# Dataset sentinel: overridable by the caller (hw_session exports the same
# path) so the tag literal lives in one place per invocation chain.
NBODY_DONE=${NBODY_DONE:-data/n_body_system/nbody_100/loc_train_charged100_0_0_1.npy}
test -f "$NBODY_DONE" \
  || { echo "dataset missing; run scripts/generate_nbody_chunked.py first"; exit 3; }

# Staged-resume soundness guard: a resumed stage resets early-stop patience
# (the trainer reinitializes best.epoch_index to start_epoch), so staged
# execution is early-stop-equivalent to one long run ONLY when the config's
# early_stop covers its full epoch budget (nbody_fastegnn.yaml: 2500/2500).
# If someone lowers early_stop, refuse partial stages instead of silently
# changing the protocol.
python - "$EPOCHS" <<'EOF' || exit 7
import sys, yaml
cfg = yaml.safe_load(open("configs/nbody_fastegnn.yaml"))["train"]
stage, full = int(sys.argv[1]), int(cfg["epochs"])
if stage < full and int(cfg["early_stop"]) < full:
    print(f"REFUSING staged run: early_stop {cfg['early_stop']} < full epoch "
          f"budget {full}; staging would reset patience at each resume. "
          "Run the full budget in one invocation or raise early_stop.")
    raise SystemExit(1)
EOF

# Resume a previously aborted run (tunnel death mid-training) instead of
# restarting: the trainer writes last_model.ckpt every test_interval epochs
# and main.py --checkpoint restores state + start_epoch. The resumed run
# logs to a fresh exp dir; its log.json covers the resumed span. A FINISHED
# prior run (early-stopped, or full epoch budget: log.json = [best, log,
# cfg], "early_stop" in best or len(loss_train) >= epochs) must NOT be
# resumed — main.py would run zero epochs and never write log.json; capture
# its artifacts directly instead (covers a crash between training and
# artifact capture as well).
run_finished() {  # run_finished <last_model.ckpt> <log.json> <epochs>
  # The ckpt's stored epoch is authoritative (a resumed run's own log.json
  # covers only the resumed span, so log length would under-count). The
  # trainer writes last_model.ckpt only on eval epochs, so a finished run's
  # newest ckpt records the LAST EVAL epoch — compare against that, not the
  # raw epoch budget (else epochs not divisible by test_interval resume
  # forever).
  python - "$1" "$2" "$3" <<'EOF'
import json, pickle, sys
payload = pickle.load(open(sys.argv[1], "rb"))
epochs = int(sys.argv[3])
interval = int(payload["config"]["log"]["test_interval"])
best = json.load(open(sys.argv[2]))[0]
done = ("early_stop" in best or "diverged" in best
        or payload["epoch"] >= epochs - (epochs % interval))
raise SystemExit(0 if done else 1)
EOF
}

CKPT_ARGS=()
RUN_TRAINING=1
. scripts/lib_resume_paused.sh   # newest_resumable_ckpt
LAST=$(newest_resumable_ckpt logs/nbody || true)
if [ -n "$LAST" ]; then
  case "$LAST" in
    */preempt_model.ckpt|*/step_*.ckpt)
      # Preempted (SIGTERM handler) or mid-epoch cadence save: by
      # construction the run died mid-training (a finished run's newest
      # checkpoint is always its last_model), so skip the run_finished
      # probe and restore the full (epoch, step, optimizer, RNG seed)
      # coordinates through the trainer's resume path.
      echo "resuming preempted/mid-epoch checkpoint $LAST"
      CKPT_ARGS=(--resume "$LAST")
      ;;
    *)
      PREV_EXP=$(dirname "$(dirname "$LAST")")
      if [ -f "$PREV_EXP/log/log.json" ] && run_finished "$LAST" "$PREV_EXP/log/log.json" "$EPOCHS"; then
        echo "previous run $PREV_EXP already finished — capturing artifacts only"
        RUN_TRAINING=0
      else
        echo "resuming from $LAST"
        CKPT_ARGS=(--resume "$LAST")
      fi
      ;;
  esac
fi

if [ "$RUN_TRAINING" -eq 1 ]; then
  python -u main.py --config_path configs/nbody_fastegnn.yaml --epochs "$EPOCHS" \
    ${CKPT_ARGS[@]+"${CKPT_ARGS[@]}"} \
    2>&1 | tee /tmp/convergence_run.log
fi

# Capture artifacts from the run dir with the BEST valid loss across all
# runs, not just the newest: a resumed run restarts best-tracking in a fresh
# exp dir, so its best ckpt covers only the resumed span — the pre-abort run
# may hold the true best. (To force a completely FRESH convergence run after
# code/config changes: rm -rf logs/nbody AND /tmp/hw_done.)
EXP=$(python - <<'EOF'
import glob, json, os
best = (None, float("inf"))
for log in glob.glob("logs/nbody/*/log/log.json"):
    try:
        lv = json.load(open(log))[0]["loss_valid"]
    except Exception:
        continue
    if lv < best[1]:
        best = (os.path.dirname(os.path.dirname(log)), lv)
if best[0] is None:
    raise SystemExit("no run with a log.json found under logs/nbody")
print(best[0])
EOF
)
mkdir -p docs/artifacts
# Publish a MERGED artifact covering every stage's epochs, not just the best
# run's span: after staged resumes (100/400/2500) any single log.json covers
# only its own stage, under-representing the full curve. Eval-epoch numbers
# are absolute (trainer logs `epoch`, and resumed runs start at start_epoch),
# so stages concatenate cleanly; keep the [best, log, cfg] triple layout and
# append a stage manifest at index 3.
python - "$EXP" <<'EOF'
import glob, json, os, sys
best_exp = sys.argv[1]
stages = []
for log in sorted(glob.glob("logs/nbody/*/log/log.json"),
                  key=lambda p: os.path.getmtime(p)):
    try:
        b, ld, cfg = json.load(open(log))
    except Exception:
        continue
    stages.append({"exp": os.path.dirname(os.path.dirname(log)),
                   "best": b, "log": ld, "cfg": cfg})
if not stages:
    raise SystemExit("no stage logs found")
chosen = next((s for s in stages if s["exp"] == best_exp), None)
if chosen is None:
    # best_exp came from the first-pass scan; if its log.json failed to
    # parse here (or lies outside the glob) publishing would silently pair
    # the wrong best/cfg with the merged curve — refuse loudly (ADVICE r3).
    raise SystemExit(f"best run {best_exp} missing from parsed stages; "
                     "inspect its log/log.json before publishing")


def stage_key(cfg):
    # Stages of ONE staged protocol differ only in the epoch budget (CLI
    # --epochs), the resume --checkpoint, and the timestamped exp_name;
    # anything else differing (LR, seed, data scale...) is an unrelated
    # experiment that must not be merged into the published curve.
    import copy
    c = copy.deepcopy(cfg)
    c.get("train", {}).pop("epochs", None)
    c.get("train", {}).pop("resume", None)
    c.get("model", {}).pop("checkpoint", None)
    c.get("log", {}).pop("exp_name", None)
    return json.dumps(c, sort_keys=True)


key = stage_key(chosen["cfg"])
skipped = [s["exp"] for s in stages if stage_key(s["cfg"]) != key]
if skipped:
    print(f"merge: skipping {len(skipped)} run(s) with non-matching config: "
          f"{skipped}")
stages = [s for s in stages if stage_key(s["cfg"]) == key]
# Dedup EVERY per-epoch array by absolute epoch number (later stages
# override): a crash-resume re-runs the epochs after the last eval ckpt, so
# plain concatenation would double-count them. loss_train/epoch_time carry
# no epoch column; their absolute epoch is start_epoch+1+i (trainer records
# start_epoch in the log dict; old logs without it are whole runs from 0).
seen, seen_tr, seen_dt = {}, {}, {}
for s in stages:
    ld = s["log"]
    for e, l in zip(ld.get("epochs", []), ld.get("loss", [])):
        seen[e] = l
    e0 = int(ld.get("start_epoch", 0))
    for i, (tr, dt) in enumerate(zip(ld.get("loss_train", []),
                                     ld.get("epoch_time", []))):
        seen_tr[e0 + 1 + i] = tr
        seen_dt[e0 + 1 + i] = dt
merged = {"epochs": sorted(seen),
          "loss": [seen[e] for e in sorted(seen)],
          "train_epochs": sorted(seen_tr),
          "loss_train": [seen_tr[e] for e in sorted(seen_tr)],
          "epoch_time": [seen_dt[e] for e in sorted(seen_dt)]}
manifest = [{"exp": s["exp"],
             "eval_epoch_span": [min(s["log"]["epochs"]), max(s["log"]["epochs"])]
             if s["log"].get("epochs") else None,
             "best": s["best"]} for s in stages]
out = [chosen["best"], merged, chosen["cfg"], {"stages": manifest}]
tmp = "docs/artifacts/nbody_fastegnn_log.json.tmp"
with open(tmp, "w") as f:
    json.dump(out, f, indent=4)
os.replace(tmp, "docs/artifacts/nbody_fastegnn_log.json")
EOF
CKPT="$EXP/state_dict/best_model.ckpt"
if [ -f "$CKPT" ]; then
  # temp + rename on the SAME filesystem: a crash mid-eval (or mid-copy)
  # must not truncate previously-good evidence
  python scripts/evaluate_rollout.py --config_path configs/nbody_fastegnn.yaml \
    --checkpoint "$CKPT" --samples 200 \
    > docs/artifacts/nbody_rollout_mse.json.tmp
  mv docs/artifacts/nbody_rollout_mse.json.tmp docs/artifacts/nbody_rollout_mse.json
fi
echo "artifacts written under docs/artifacts/ — record the best MSEs in BASELINE.md and commit"
