#!/usr/bin/env bash
# N-body convergence run + artifact capture (BASELINE.md MSE-parity evidence).
#
# Run on a live TPU tunnel (CPU epochs are ~15+ min on this host; TPU epochs
# with scan_epochs are sub-second). Produces:
#   - logs/nbody/<exp>/log.json            (loss curves, best MSEs, time_cost)
#   - docs/artifacts/nbody_fastegnn_log.json  (tracked copy; logs/ is ignored)
#   - docs/artifacts/nbody_rollout_mse.json   (rollout MSE with the best ckpt)
#
# Usage: bash scripts/convergence_session.sh [epochs]   (default: full 2500)

# pipefail: main.py is piped through tee below — without it a training crash
# is masked by tee's rc=0 and stale artifacts would be copied as evidence.
set -euo pipefail
cd "$(dirname "$0")/.."
EPOCHS=${1:-2500}

# Probe unless the caller (hw_session run()) already probe-gated this item —
# its probe cycle is up to ~9.5 min and a second one wastes the window.
if [ -z "${CALLER_PROBED:-}" ]; then
  bash scripts/tpu_probe.sh /dev/stdout \
    || { echo "TPU wedged; aborting (do not run this on CPU)"; exit 2; }
  sleep 30  # let the probe client's claim release before main.py acquires
fi

# Dataset sentinel: overridable by the caller (hw_session exports the same
# path) so the tag literal lives in one place per invocation chain.
NBODY_DONE=${NBODY_DONE:-data/n_body_system/nbody_100/loc_train_charged100_0_0_1.npy}
test -f "$NBODY_DONE" \
  || { echo "dataset missing; run scripts/generate_nbody_chunked.py first"; exit 3; }

python -u main.py --config_path configs/nbody_fastegnn.yaml --epochs "$EPOCHS" \
  2>&1 | tee /tmp/convergence_run.log

# newest run dir under logs/nbody
EXP=$(ls -dt logs/nbody/*/ | head -1)
mkdir -p docs/artifacts
cp "$EXP/log.json" docs/artifacts/nbody_fastegnn_log.json
CKPT="$EXP/state_dict/best_model.ckpt"
if [ -f "$CKPT" ]; then
  # temp + mv: a crash mid-eval must not truncate previously-good evidence
  python scripts/evaluate_rollout.py --config_path configs/nbody_fastegnn.yaml \
    --checkpoint "$CKPT" --samples 200 \
    > /tmp/nbody_rollout_mse.json.tmp
  mv /tmp/nbody_rollout_mse.json.tmp docs/artifacts/nbody_rollout_mse.json
fi
echo "artifacts written under docs/artifacts/ — record the best MSEs in BASELINE.md and commit"
