#!/usr/bin/env bash
# N-body convergence run + artifact capture (BASELINE.md MSE-parity evidence).
#
# Run on a live TPU tunnel (CPU epochs are ~15+ min on this host; TPU epochs
# with scan_epochs are sub-second). Produces:
#   - logs/nbody/<exp>/log.json            (loss curves, best MSEs, time_cost)
#   - docs/artifacts/nbody_fastegnn_log.json  (tracked copy; logs/ is ignored)
#   - docs/artifacts/nbody_rollout_mse.json   (rollout MSE with the best ckpt)
#
# Usage: bash scripts/convergence_session.sh [epochs]   (default: full 2500)

set -eu
cd "$(dirname "$0")/.."
EPOCHS=${1:-2500}

timeout 60 python -c "
import jax, jax.numpy as jnp
print('probe ok', float((jnp.ones((256,256))@jnp.ones((256,256))).sum()))" \
  || { echo "TPU wedged; aborting (do not run this on CPU)"; exit 2; }

test -f data/n_body_system/nbody_100/loc_train_charged100_0_0_1.npy \
  || { echo "dataset missing; run scripts/generate_nbody_chunked.py first"; exit 3; }

python -u main.py --config_path configs/nbody_fastegnn.yaml --epochs "$EPOCHS" \
  2>&1 | tee /tmp/convergence_run.log

# newest run dir under logs/nbody
EXP=$(ls -dt logs/nbody/*/ | head -1)
mkdir -p docs/artifacts
cp "$EXP/log.json" docs/artifacts/nbody_fastegnn_log.json
CKPT="$EXP/state_dict/best_model.ckpt"
if [ -f "$CKPT" ]; then
  python scripts/evaluate_rollout.py --config_path configs/nbody_fastegnn.yaml \
    --checkpoint "$CKPT" --samples 200 \
    > docs/artifacts/nbody_rollout_mse.json
fi
echo "artifacts written under docs/artifacts/ — record the best MSEs in BASELINE.md and commit"
