#!/usr/bin/env python
"""Generate random SPlisHSPlasH fluid scenes (Fluid113K generation stage 1).

In-tree port of the reference's create_physics_scenes.py CLI
(dataset_generation/Fluid113K/create_physics_scenes.py:439-497): synthesizes
the scene directory (scene.json + box/fluid bgeo) with numpy-only mesh
sampling, then — when a SPlisHSPlasH ``DynamicBoundarySimulator`` binary is
available (--simulator-bin or $SIMULATOR_BIN) — runs the simulation so
``scripts/pack_fluid_records.py`` can pack the exported frames. Without the
binary the scene directories are still complete and portable.

The reference generates sims 1..140 (train 1-100 / valid 101-120 /
test 121-140, fluid113k.SIM_SPLITS) with ~113k particles each:

    for seed in $(seq 1 140); do
        python scripts/generate_fluid_scenes.py --output data/fluid_scenes \
            --seed $seed --simulator-bin $SIMULATOR_BIN
    done
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--output", required=True, help="output directory")
    p.add_argument("--seed", type=int, required=True, help="scene RNG seed (= sim id)")
    p.add_argument("--uniform-viscosity", action="store_true")
    p.add_argument("--log10-uniform-viscosity", action="store_true")
    p.add_argument("--default-viscosity", action="store_true")
    p.add_argument("--default-density", action="store_true")
    p.add_argument("--num-objects", type=int, default=0,
                   help="fluid object count; 0 = random 1-3")
    p.add_argument("--const-fluid-particles", type=int, default=0)
    p.add_argument("--max-fluid-particles", type=int, default=0)
    p.add_argument("--min-fluid-particles", type=int, default=100_000,
                   help="reject scenes below this budget (reference asserts >100k)")
    p.add_argument("--radius", type=float, default=0.025)
    p.add_argument("--simulator-bin", default=os.environ.get("SIMULATOR_BIN", ""),
                   help="SPlisHSPlasH DynamicBoundarySimulator path; scene-only if unset")
    args = p.parse_args()

    from distegnn_tpu.data.fluid_scenes import run_simulator, synthesize_scene

    os.makedirs(args.output, exist_ok=True)
    sim_dir = synthesize_scene(
        args.output, args.seed, radius=args.radius,
        num_objects=args.num_objects,
        uniform_viscosity=args.uniform_viscosity,
        log10_uniform_viscosity=args.log10_uniform_viscosity,
        default_viscosity=args.default_viscosity,
        default_density=args.default_density,
        const_fluid_particles=args.const_fluid_particles,
        max_fluid_particles=args.max_fluid_particles,
        min_fluid_particles=args.min_fluid_particles)
    print(f"scene written: {sim_dir}")

    if args.simulator_bin:
        rc = run_simulator(args.simulator_bin, sim_dir)
        print(f"simulator exit code {rc}; exports under {sim_dir}/partio/")
        return rc
    print("no --simulator-bin: scene-only mode (run SPlisHSPlasH elsewhere, "
          "then scripts/pack_fluid_records.py)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
