"""Micro-benchmark: scatter-free lowerings for the sorted segment-sum that
dominates the plain-path train step (BASELINE.md breakdown: 22-33 ms per
[E,64]->[N,64] aggregation at ~4% of HBM bandwidth; both blocked one-hot
lowerings measured SLOWER end to end than plain on hardware).

Candidates, all on row-sorted edge ids at LargeFluid shape:
  copy              elementwise [E,64] pass — the HBM bandwidth reference
  gather_rows       x[ids] [N,64]->[E,64] (read side, and the cheap VJP of
                    every segment-sum candidate)
  scatter_sorted    zeros.at[ids].add(x), indices_are_sorted — current path
  cumsum_diff       prefix-sum over E then c[ends-1]-c[starts-1] with
                    host-precomputed CSR row offsets: no scatter at all
  ell_gather_sum    fixed-degree CSR (ELL) padding [N, Dmax] built host-side
                    once: out[n] = sum_d x[ell_idx[n,d]] * ell_msk — pure
                    gather+reduce, exact, ~2x read amplification
  vjp(scatter)/vjp(cumsum)/vjp(ell): cotangent pull-back cost (the backward
                    half of the step is where the round-1 profile said the
                    time goes)

Run on the real chip: `python scripts/microbench_segsum.py [--bf16]`.
"""

from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")

E, N, H = 1_639_080, 113_140, 64


def timed(fn, *args, warmup=2, steps=10):
    """Fetch-synced timing (block_until_ready under-reports on axon)."""
    import jax.numpy as jnp

    def sync(o):
        while isinstance(o, (tuple, list)):
            o = o[0]
        np.asarray(jnp.ravel(o)[0])

    for _ in range(warmup):
        out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / steps * 1e3


def main():
    import jax
    import jax.numpy as jnp

    bf16 = "--bf16" in sys.argv
    dt = jnp.bfloat16 if bf16 else jnp.float32
    rng = np.random.default_rng(0)

    # degree-realistic sorted ids (radius-graph degrees ~ Poisson(14.5));
    # spread the sampling residual one edge per node so no single node's
    # degree (and hence the ELL dmax/read-amp) is distorted
    deg = rng.poisson(E / N, size=N).astype(np.int64)
    diff = E - deg.sum()
    if diff:
        idx = rng.choice(N, size=abs(diff), replace=abs(diff) > N)
        np.add.at(deg, idx, 1 if diff > 0 else -1)
        deg = np.maximum(deg, 0)
        deg[0] += E - deg.sum()  # at most a few leftovers from the clamp
    ids_np = np.repeat(np.arange(N), deg).astype(np.int32)
    starts_np = np.zeros(N + 1, np.int64)
    np.cumsum(deg, out=starts_np[1:])

    dmax = int(deg.max())
    ell_idx_np = np.zeros((N, dmax), np.int32)
    ell_msk_np = np.zeros((N, dmax), np.float32)
    for n in range(N):  # host-side, once per dataset — not on the step path
        k = deg[n]
        ell_idx_np[n, :k] = np.arange(starts_np[n], starts_np[n + 1])
        ell_msk_np[n, :k] = 1.0
    read_amp = N * dmax / E

    x = jnp.asarray(rng.normal(size=(E, H)).astype(np.float32)).astype(dt)
    xn = jnp.asarray(rng.normal(size=(N, H)).astype(np.float32)).astype(dt)
    ids = jnp.asarray(ids_np)
    starts = jnp.asarray(starts_np[:-1])
    ends = jnp.asarray(starts_np[1:])
    ell_idx = jnp.asarray(ell_idx_np)
    ell_msk = jnp.asarray(ell_msk_np).astype(dt)

    from distegnn_tpu.ops.cumsum import prefix_sum

    f_copy = jax.jit(lambda d: d * 1.0001)
    f_gather = jax.jit(lambda d, i: d[i])
    f_scatter = jax.jit(lambda d, i: jnp.zeros((N, H), d.dtype).at[i].add(
        d, indices_are_sorted=True))
    # the prefix pass in isolation, both lowerings (ops/cumsum.py): XLA emits
    # O(log E) shifted-add passes, the Pallas kernel a single sequential pass
    f_prefix_xla = jax.jit(lambda d: prefix_sum(d, impl="xla"))
    f_prefix_pl = jax.jit(lambda d: prefix_sum(d, impl="pallas"))

    def cumsum_diff(d, s, e, impl="auto"):
        c = prefix_sum(d, impl=impl)
        hi = c[e - 1]
        lo = jnp.where((s > 0)[:, None], c[jnp.maximum(s - 1, 0)], 0.0)
        return (hi - lo).astype(d.dtype)

    f_cumsum = jax.jit(lambda d, s, e: cumsum_diff(d, s, e, "xla"))
    f_cumsum_pl = jax.jit(lambda d, s, e: cumsum_diff(d, s, e, "pallas"))

    def ell_sum(d, idx, msk):
        return (d[idx] * msk[..., None]).sum(axis=1)

    f_ell = jax.jit(ell_sum)

    # numerical sanity vs the scatter reference
    ref = np.asarray(f_scatter(x.astype(jnp.float32), ids))
    for name, fn, args in (("cumsum_diff", f_cumsum, (x.astype(jnp.float32), starts, ends)),
                           ("ell", f_ell, (x.astype(jnp.float32), ell_idx,
                                           ell_msk.astype(jnp.float32)))):
        err = np.abs(np.asarray(fn(*args)) - ref).max()
        print(f"max|{name} - scatter| = {err:.3e}")

    # fused per-layer aggregation (EdgeOps.agg_rows_pair): the model's two
    # per-layer aggregations + mean count as ONE packed pass vs three
    # separate passes — the round-4 fuse_agg attack, isolated
    x3 = jnp.asarray(rng.normal(size=(E, 3)).astype(np.float32)).astype(dt)
    f_three = jax.jit(lambda a, b, i: (
        jnp.zeros((N, 3), jnp.float32).at[i].add(
            a.astype(jnp.float32), indices_are_sorted=True),
        jnp.zeros((N, H), jnp.float32).at[i].add(
            b.astype(jnp.float32), indices_are_sorted=True),
        jnp.zeros((N, 1), jnp.float32).at[i].add(
            jnp.ones((E, 1), jnp.float32), indices_are_sorted=True)))
    f_packed = jax.jit(lambda a, b, i: jnp.zeros((N, H + 4), jnp.float32).at[i].add(
        jnp.concatenate([a, b, jnp.ones((E, 1), a.dtype)],
                        axis=-1).astype(jnp.float32),
        indices_are_sorted=True))

    g_scatter = jax.jit(jax.grad(lambda d: f_scatter(d, ids).sum()))
    g_cumsum = jax.jit(jax.grad(lambda d: cumsum_diff(d, starts, ends).sum()))
    g_ell = jax.jit(jax.grad(lambda d: ell_sum(d, ell_idx, ell_msk).sum()))

    tag = "bf16" if bf16 else "f32"
    print(f"dtype={tag}  E={E} N={N} H={H}  ELL dmax={dmax} read_amp={read_amp:.2f}")
    print(f"copy_[E,{H}]       {timed(f_copy, x):8.2f} ms")
    print(f"gather_rows        {timed(f_gather, xn, ids):8.2f} ms")
    print(f"scatter_sorted     {timed(f_scatter, x, ids):8.2f} ms")
    print(f"prefix_xla         {timed(f_prefix_xla, x):8.2f} ms")
    print(f"prefix_pallas      {timed(f_prefix_pl, x):8.2f} ms")
    print(f"cumsum_diff_xla    {timed(f_cumsum, x, starts, ends):8.2f} ms")
    print(f"cumsum_diff_pallas {timed(f_cumsum_pl, x, starts, ends):8.2f} ms")
    print(f"ell_gather_sum     {timed(f_ell, x, ell_idx, ell_msk):8.2f} ms")
    print(f"three_scatters     {timed(f_three, x3, x, ids):8.2f} ms")
    print(f"packed_scatter     {timed(f_packed, x3, x, ids):8.2f} ms")
    print(f"vjp_scatter        {timed(g_scatter, x):8.2f} ms")
    print(f"vjp_cumsum         {timed(g_cumsum, x):8.2f} ms")
    print(f"vjp_ell            {timed(g_ell, x):8.2f} ms")


if __name__ == "__main__":
    main()
