"""Fetch the AdK equilibrium trajectory and cache it as npz (reference
dataset_generation/protein/mdanalysis.py + stage 1 of the protein pipeline).

Requires MDAnalysis/MDAnalysisData (not in the TPU image — run wherever they
are installed; the npz is what the training pipeline consumes).

Usage:
  python scripts/fetch_protein.py --data-dir data/protein [--no-backbone]
"""

from __future__ import annotations

import argparse

from distegnn_tpu.data.protein import extract_adk_npz


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data-dir", type=str, default="data/protein")
    p.add_argument("--no-backbone", action="store_true")
    args = p.parse_args()
    out = extract_adk_npz(args.data_dir, backbone=not args.no_backbone)
    print(f"Cached: {out}")


if __name__ == "__main__":
    main()
