#!/usr/bin/env python
"""Lint: every serving-control config key ships a typed default AND a
validation branch — the two halves of the schema cannot drift apart.

The config contract (docs/SERVING.md "Config") routes every serve-layer
knob through ``config._DEFAULTS`` (so hand-built ``ConfigDict(_DEFAULTS)``
configs always carry it) and through ``validate_config`` (so a typo'd or
out-of-range value fails at load time, not as a silent attribute miss deep
in the gateway). A key present in one side but not the other is exactly
the hole this lint exists to catch: a default nobody validates, or a
validator guarding a knob nobody can set.

Checked, by AST walk over distegnn_tpu/config.py, for each section in
``SECTIONS`` (the serve sub-mappings that own a known-key guard):
  1. the section exists in ``_DEFAULTS["serve"]`` and in
     ``validate_config`` (bound via ``<var> = s.get("<section>")``);
  2. the section's validator rejects unknown keys
     (``for key in <var>: if key not in <tuple>``);
  3. every default key is named by the validator (in the known-keys tuple
     or a ``<var>.get("key")`` / ``<var>["key"]`` access) — and every key
     the validator names has a default.
Plus one cross-module check: ``serve/autoscale.py``'s in-code ``_DEFAULTS``
fallback carries exactly the same knob set as the config section (its
docstring promises this file keeps them in lockstep).

Wired into tier-1 via tests/test_elasticity.py::test_config_key_lint_clean.
Exit codes: 0 clean, 1 violations (one ``path:line: text`` per finding).
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIG = os.path.join(REPO, "distegnn_tpu", "config.py")
AUTOSCALE = os.path.join(REPO, "distegnn_tpu", "serve", "autoscale.py")

# serve.<section> mappings whose validators own an unknown-key guard
SECTIONS = ("worker", "supervisor", "autoscale", "priority", "stream")


def _const_str(node: ast.AST):
    return node.value if (isinstance(node, ast.Constant)
                          and isinstance(node.value, str)) else None


def _str_tuple(node: ast.AST):
    """frozenset of element strings for a tuple/list of string constants,
    else None."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    vals = [_const_str(e) for e in node.elts]
    if vals and all(v is not None for v in vals):
        return frozenset(vals)
    return None


def _dict_get(node: ast.Dict, key: str):
    for k, v in zip(node.keys, node.values):
        if _const_str(k) == key:
            return v
    return None


def _defaults_sections(tree: ast.Module, rel: str):
    """{section: ({key: lineno}, section_lineno)} from _DEFAULTS['serve'],
    plus violations for missing structure."""
    out, violations = {}, []
    serve = None
    for node in tree.body:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target] if isinstance(node, ast.AnnAssign)
                   else [])
        if any(isinstance(t, ast.Name) and t.id == "_DEFAULTS"
               for t in targets):
            if isinstance(node.value, ast.Dict):
                serve = _dict_get(node.value, "serve")
            break
    if not isinstance(serve, ast.Dict):
        violations.append((rel, 1, "_DEFAULTS has no literal 'serve' "
                                   "mapping — config layout changed under "
                                   "the lint; update check_config_keys.py"))
        return out, violations
    for section in SECTIONS:
        sec = _dict_get(serve, section)
        if not isinstance(sec, ast.Dict):
            violations.append((rel, serve.lineno,
                               f"_DEFAULTS serve.{section} is missing or "
                               f"not a literal mapping"))
            continue
        keys = {}
        for k in sec.keys:
            name = _const_str(k)
            if name is not None:
                keys[name] = k.lineno
        out[section] = (keys, sec.lineno)
    return out, violations


def _find_validate(tree: ast.Module):
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and \
                node.name == "validate_config":
            return node
    return None


def _validated_sections(fn: ast.FunctionDef):
    """{section: (validated key set, has unknown-key guard, lineno)} by
    tracking ``<var> = s.get("<section>")`` bindings through the function."""
    # string-tuple environment: aknown = ("enable", ...), known = (...), ...
    env = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            vals = _str_tuple(node.value)
            if vals is not None:
                env[node.targets[0].id] = vals

    # section variable bindings: a = s.get("autoscale"), w = s.get("worker")
    var_of = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call):
            call = node.value
            if isinstance(call.func, ast.Attribute) and \
                    call.func.attr == "get" and call.args:
                section = _const_str(call.args[0])
                if section in SECTIONS:
                    var_of[section] = (node.targets[0].id, node.lineno)

    def _refs(tree: ast.AST, var: str) -> bool:
        return any(isinstance(n, ast.Name) and n.id == var
                   for n in ast.walk(tree))

    out = {}
    for section, (var, lineno) in var_of.items():
        validated, guarded = set(), False
        for node in ast.walk(fn):
            # <var>.get("key", ...) / <var>["key"]
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "get" and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == var and node.args:
                key = _const_str(node.args[0])
                if key is not None:
                    validated.add(key)
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == var:
                key = _const_str(node.slice)
                if key is not None:
                    validated.add(key)
            if not isinstance(node, ast.For):
                continue
            # for key in <var>: ... key not in <tuple> -> unknown-key guard
            if isinstance(node.iter, ast.Name) and node.iter.id == var:
                for cmp_ in ast.walk(node):
                    if isinstance(cmp_, ast.Compare) and any(
                            isinstance(op, ast.NotIn) for op in cmp_.ops):
                        comp = cmp_.comparators[0]
                        vals = _str_tuple(comp)
                        if vals is None and isinstance(comp, ast.Name):
                            vals = env.get(comp.id)
                        if vals is not None:
                            guarded = True
                            validated |= vals
            # for key in <known tuple>: ... <var>[key] range checks
            else:
                vals = _str_tuple(node.iter)
                if vals is None and isinstance(node.iter, ast.Name):
                    vals = env.get(node.iter.id)
                if vals is not None and _refs(node, var):
                    validated |= vals
        out[section] = (validated, guarded, lineno)
    return out


def _autoscale_module_keys(path: str):
    """Knob names of serve/autoscale.py's module-level _DEFAULTS dict."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in tree.body:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target] if isinstance(node, ast.AnnAssign)
                   else [])
        if any(isinstance(t, ast.Name) and t.id == "_DEFAULTS"
               for t in targets):
            value = node.value
            if isinstance(value, ast.Dict):
                keys = {_const_str(k) for k in value.keys}
                keys.discard(None)
                return keys, node.lineno
    return None, 1


def find_violations(config_path: str = CONFIG,
                    autoscale_path: str = AUTOSCALE):
    """[(relpath, lineno, message)] against the schema-lockstep contract."""
    rel = os.path.relpath(config_path, REPO).replace(os.sep, "/")
    with open(config_path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=config_path)

    defaults, out = _defaults_sections(tree, rel)

    validate = _find_validate(tree)
    if validate is None:
        out.append((rel, 1, "no validate_config function found"))
        return out
    validated = _validated_sections(validate)

    for section in SECTIONS:
        if section not in defaults:
            continue  # already reported by _defaults_sections
        keys, sec_line = defaults[section]
        if section not in validated:
            out.append((rel, validate.lineno,
                        f"validate_config never reads serve.{section} "
                        f"(expected <var> = s.get({section!r}))"))
            continue
        seen, guarded, v_line = validated[section]
        if not guarded:
            out.append((rel, v_line,
                        f"serve.{section} validator has no unknown-key "
                        f"rejection loop (for key in <var>: ... not in ...)"))
        for key in sorted(set(keys) - seen):
            out.append((rel, keys[key],
                        f"serve.{section}.{key} has a default but no "
                        f"validation branch in validate_config"))
        for key in sorted(seen - set(keys)):
            out.append((rel, v_line,
                        f"validate_config names serve.{section}.{key} but "
                        f"_DEFAULTS ships no typed default for it"))

    if autoscale_path and "autoscale" in defaults:
        arel = os.path.relpath(autoscale_path, REPO).replace(os.sep, "/")
        mod_keys, a_line = _autoscale_module_keys(autoscale_path)
        cfg_keys = set(defaults["autoscale"][0])
        if mod_keys is None:
            out.append((arel, a_line,
                        "no module-level _DEFAULTS dict found — the "
                        "autoscaler's in-code fallback knob set is gone"))
        elif mod_keys != cfg_keys:
            out.append((arel, a_line,
                        f"autoscale._DEFAULTS drifted from config "
                        f"serve.autoscale: only-in-module="
                        f"{sorted(mod_keys - cfg_keys)} only-in-config="
                        f"{sorted(cfg_keys - mod_keys)}"))
    return out


def main(argv=None) -> int:
    violations = find_violations()
    for rel, lineno, msg in violations:
        print(f"{rel}:{lineno}: {msg}")
    if violations:
        print(f"\n{len(violations)} config-key violation(s); see "
              "scripts/check_config_keys.py docstring for the contract")
        return 1
    print("check_config_keys: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
