#!/usr/bin/env python
"""Lint: every serving-control config key ships a typed default AND a
validation branch — the two halves of the schema cannot drift apart.

The config contract (docs/SERVING.md "Config") routes every serve-layer
knob through ``config._DEFAULTS`` (so hand-built ``ConfigDict(_DEFAULTS)``
configs always carry it) and through ``validate_config`` (so a typo'd or
out-of-range value fails at load time, not as a silent attribute miss deep
in the gateway). A key present in one side but not the other is exactly
the hole this lint exists to catch: a default nobody validates, or a
validator guarding a knob nobody can set.

Checked, by AST walk over distegnn_tpu/config.py, for each section in
``SECTIONS`` (the serve sub-mappings that own a known-key guard) and each
TOP-LEVEL section in ``TOP_SECTIONS`` (same contract, rooted at
``_DEFAULTS`` itself and bound via ``<var> = cfg.get("<section>")``):
  1. the section exists in the defaults mapping and in
     ``validate_config`` (bound via ``<var> = <recv>.get("<section>")``);
  2. the section's validator rejects unknown keys
     (``for key in <var>: if key not in <tuple>``);
  3. every default key is named by the validator (in the known-keys tuple
     or a ``<var>.get("key")`` / ``<var>["key"]`` access) — and every key
     the validator names has a default.
Plus two cross-module checks: ``serve/autoscale.py``'s and
``promote/promoter.py``'s in-code ``_DEFAULTS`` fallbacks carry exactly
the same knob set as their config sections (both docstrings promise this
file keeps them in lockstep).

Plus one coverage check over ``configs/*.yaml``: every top-level section
(mapping-valued key) a shipped config sets must be a ``_DEFAULTS`` section
that ``validate_config`` actually reads — a yaml section nobody validates
is a whole subtree of knobs that typo silently.

Wired into tier-1 via tests/test_elasticity.py::test_config_key_lint_clean
(config/module lockstep) and tests/test_promote.py (yaml coverage).
Exit codes: 0 clean, 1 violations (one ``path:line: text`` per finding).
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIG = os.path.join(REPO, "distegnn_tpu", "config.py")
AUTOSCALE = os.path.join(REPO, "distegnn_tpu", "serve", "autoscale.py")
PROMOTER = os.path.join(REPO, "distegnn_tpu", "promote", "promoter.py")
CONFIGS = os.path.join(REPO, "configs")

# serve.<section> mappings whose validators own an unknown-key guard
SECTIONS = ("worker", "supervisor", "autoscale", "priority", "stream",
            "tiled")

# top-level _DEFAULTS mappings with the same lockstep contract, bound in
# validate_config via <var> = cfg.get("<section>")
TOP_SECTIONS = ("promote",)


def _const_str(node: ast.AST):
    return node.value if (isinstance(node, ast.Constant)
                          and isinstance(node.value, str)) else None


def _str_tuple(node: ast.AST):
    """frozenset of element strings for a tuple/list of string constants,
    else None."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    vals = [_const_str(e) for e in node.elts]
    if vals and all(v is not None for v in vals):
        return frozenset(vals)
    return None


def _dict_get(node: ast.Dict, key: str):
    for k, v in zip(node.keys, node.values):
        if _const_str(k) == key:
            return v
    return None


def _find_defaults_dict(tree: ast.Module):
    """The literal _DEFAULTS dict node, or None."""
    for node in tree.body:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target] if isinstance(node, ast.AnnAssign)
                   else [])
        if any(isinstance(t, ast.Name) and t.id == "_DEFAULTS"
               for t in targets):
            return node.value if isinstance(node.value, ast.Dict) else None
    return None


def _section_keys(parent: ast.Dict, sections, label: str, rel: str):
    """{section: ({key: lineno}, section_lineno)} for each named sub-mapping
    of ``parent``, plus violations for missing/non-literal sections."""
    out, violations = {}, []
    for section in sections:
        sec = _dict_get(parent, section)
        if not isinstance(sec, ast.Dict):
            violations.append((rel, parent.lineno,
                               f"_DEFAULTS {label}{section} is missing or "
                               f"not a literal mapping"))
            continue
        keys = {}
        for k in sec.keys:
            name = _const_str(k)
            if name is not None:
                keys[name] = k.lineno
        out[section] = (keys, sec.lineno)
    return out, violations


def _defaults_sections(tree: ast.Module, rel: str):
    """serve sub-sections + top-level sections of _DEFAULTS:
    ({section: ...}, {section: ...}, top-level key set, violations)."""
    defaults = _find_defaults_dict(tree)
    if defaults is None:
        return {}, {}, None, [(rel, 1,
                               "no literal _DEFAULTS mapping — config "
                               "layout changed under the lint; update "
                               "check_config_keys.py")]
    top_keys = {_const_str(k) for k in defaults.keys} - {None}
    serve = _dict_get(defaults, "serve")
    if not isinstance(serve, ast.Dict):
        out, violations = {}, [(rel, 1, "_DEFAULTS has no literal 'serve' "
                                        "mapping — config layout changed "
                                        "under the lint; update "
                                        "check_config_keys.py")]
    else:
        out, violations = _section_keys(serve, SECTIONS, "serve.", rel)
    top, top_viol = _section_keys(defaults, TOP_SECTIONS, "", rel)
    return out, top, top_keys, violations + top_viol


def _find_validate(tree: ast.Module):
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and \
                node.name == "validate_config":
            return node
    return None


def _validated_sections(fn: ast.FunctionDef, sections):
    """{section: (validated key set, has unknown-key guard, lineno)} by
    tracking ``<var> = <recv>.get("<section>")`` bindings through the
    function, for any section name in ``sections``."""
    # string-tuple environment: aknown = ("enable", ...), known = (...), ...
    env = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            vals = _str_tuple(node.value)
            if vals is not None:
                env[node.targets[0].id] = vals

    # section variable bindings: a = s.get("autoscale"), w = s.get("worker")
    var_of = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call):
            call = node.value
            if isinstance(call.func, ast.Attribute) and \
                    call.func.attr == "get" and call.args:
                section = _const_str(call.args[0])
                if section in sections:
                    var_of[section] = (node.targets[0].id, node.lineno)

    def _refs(tree: ast.AST, var: str) -> bool:
        return any(isinstance(n, ast.Name) and n.id == var
                   for n in ast.walk(tree))

    out = {}
    for section, (var, lineno) in var_of.items():
        validated, guarded = set(), False
        for node in ast.walk(fn):
            # <var>.get("key", ...) / <var>["key"]
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "get" and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == var and node.args:
                key = _const_str(node.args[0])
                if key is not None:
                    validated.add(key)
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == var:
                key = _const_str(node.slice)
                if key is not None:
                    validated.add(key)
            if not isinstance(node, ast.For):
                continue
            # for key in <var>: ... key not in <tuple> -> unknown-key guard
            if isinstance(node.iter, ast.Name) and node.iter.id == var:
                for cmp_ in ast.walk(node):
                    if isinstance(cmp_, ast.Compare) and any(
                            isinstance(op, ast.NotIn) for op in cmp_.ops):
                        comp = cmp_.comparators[0]
                        vals = _str_tuple(comp)
                        if vals is None and isinstance(comp, ast.Name):
                            vals = env.get(comp.id)
                        if vals is not None:
                            guarded = True
                            validated |= vals
            # for key in <known tuple>: ... <var>[key] range checks
            else:
                vals = _str_tuple(node.iter)
                if vals is None and isinstance(node.iter, ast.Name):
                    vals = env.get(node.iter.id)
                if vals is not None and _refs(node, var):
                    validated |= vals
        out[section] = (validated, guarded, lineno)
    return out


def _module_defaults_keys(path: str):
    """Knob names of a module-level _DEFAULTS dict (autoscale/promoter
    in-code fallbacks)."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    node_value = _find_defaults_dict(tree)
    if node_value is not None:
        keys = {_const_str(k) for k in node_value.keys}
        keys.discard(None)
        return keys, node_value.lineno
    return None, 1


def _lockstep_module(out, module_path: str, section: str, cfg_keys):
    """Flag drift between a module's in-code _DEFAULTS fallback and the
    config section it mirrors."""
    mrel = os.path.relpath(module_path, REPO).replace(os.sep, "/")
    mod_keys, m_line = _module_defaults_keys(module_path)
    if mod_keys is None:
        out.append((mrel, m_line,
                    "no module-level _DEFAULTS dict found — the in-code "
                    "fallback knob set is gone"))
    elif mod_keys != set(cfg_keys):
        out.append((mrel, m_line,
                    f"module _DEFAULTS drifted from config {section}: "
                    f"only-in-module={sorted(mod_keys - set(cfg_keys))} "
                    f"only-in-config={sorted(set(cfg_keys) - mod_keys)}"))


def _validated_top_level(fn: ast.FunctionDef):
    """Top-level config sections validate_config reads: ``cfg.get("X")``
    bindings plus ``cfg.<section>`` attribute access (cfg = first param)."""
    if not fn.args.args:
        return set()
    cfg = fn.args.args[0].arg
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "get" and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == cfg and node.args:
            key = _const_str(node.args[0])
            if key is not None:
                out.add(key)
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == cfg:
            out.add(node.attr)
    out.discard("get")
    return out


def _yaml_top_sections(configs_dir: str):
    """[(relpath, section)] for every mapping-valued top-level key in
    configs/*.yaml — scalar keys (seed: 43) are not sections."""
    import yaml

    out = []
    for fname in sorted(os.listdir(configs_dir)):
        if not fname.endswith((".yaml", ".yml")):
            continue
        path = os.path.join(configs_dir, fname)
        with open(path, encoding="utf-8") as f:
            doc = yaml.safe_load(f) or {}
        if not isinstance(doc, dict):
            continue
        rel = os.path.relpath(path, REPO).replace(os.sep, "/")
        for key, value in doc.items():
            if isinstance(value, dict):
                out.append((rel, str(key)))
    return out


def _check_parity(out, rel, validate, validated, defaults, sections, label):
    """Default-vs-validator key parity for one family of sections; ``label``
    prefixes section names in messages ('serve.' or '')."""
    for section in sections:
        if section not in defaults:
            continue  # already reported by _defaults_sections
        keys, _sec_line = defaults[section]
        if section not in validated:
            out.append((rel, validate.lineno,
                        f"validate_config never reads {label}{section} "
                        f"(expected <var> = <recv>.get({section!r}))"))
            continue
        seen, guarded, v_line = validated[section]
        if not guarded:
            out.append((rel, v_line,
                        f"{label}{section} validator has no unknown-key "
                        f"rejection loop (for key in <var>: ... not in ...)"))
        for key in sorted(set(keys) - seen):
            out.append((rel, keys[key],
                        f"{label}{section}.{key} has a default but no "
                        f"validation branch in validate_config"))
        for key in sorted(seen - set(keys)):
            out.append((rel, v_line,
                        f"validate_config names {label}{section}.{key} but "
                        f"_DEFAULTS ships no typed default for it"))


def find_violations(config_path: str = CONFIG,
                    autoscale_path: str = AUTOSCALE,
                    promoter_path: str = PROMOTER,
                    configs_dir: str = CONFIGS):
    """[(relpath, lineno, message)] against the schema-lockstep contract.
    Pass None for autoscale_path / promoter_path / configs_dir to disable
    the cross-module and yaml-coverage checks."""
    rel = os.path.relpath(config_path, REPO).replace(os.sep, "/")
    with open(config_path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=config_path)

    defaults, top_defaults, top_keys, out = _defaults_sections(tree, rel)

    validate = _find_validate(tree)
    if validate is None:
        out.append((rel, 1, "no validate_config function found"))
        return out
    validated = _validated_sections(validate, SECTIONS + TOP_SECTIONS)

    _check_parity(out, rel, validate, validated, defaults, SECTIONS, "serve.")
    _check_parity(out, rel, validate, validated, top_defaults, TOP_SECTIONS,
                  "")

    if autoscale_path and "autoscale" in defaults:
        _lockstep_module(out, autoscale_path, "serve.autoscale",
                         defaults["autoscale"][0])
    if promoter_path and "promote" in top_defaults:
        _lockstep_module(out, promoter_path, "promote",
                         top_defaults["promote"][0])

    if configs_dir and top_keys is not None:
        vtop = _validated_top_level(validate)
        for yrel, section in _yaml_top_sections(configs_dir):
            if section not in top_keys:
                out.append((yrel, 1,
                            f"top-level section '{section}:' is not a "
                            f"_DEFAULTS section — hand-built configs will "
                            f"never carry it"))
            elif section not in vtop:
                out.append((yrel, 1,
                            f"top-level section '{section}:' has no "
                            f"registered validator (validate_config never "
                            f"reads cfg.{section})"))
    return out


def main(argv=None) -> int:
    violations = find_violations()
    for rel, lineno, msg in violations:
        print(f"{rel}:{lineno}: {msg}")
    if violations:
        print(f"\n{len(violations)} config-key violation(s); see "
              "scripts/check_config_keys.py docstring for the contract")
        return 1
    print("check_config_keys: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
