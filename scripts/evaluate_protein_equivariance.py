"""Protein test_rot / test_trans equivariance evaluation (VERDICT r3 #6).

The reference evaluates empirical E(3)-equivariance by REBUILDING the test
split with a random rotation (test_rot) or a box-scaled translation
(test_trans) injected into every frame (reference
datasets/process_dataset.py:162-174) and reporting test MSE on each variant.
An equivariant model scores the same MSE on all three (up to float noise);
a non-equivariant one degrades under the injection.

This script loads a trained checkpoint and reports the test MSE triple:

  python scripts/evaluate_protein_equivariance.py \
      --config_path configs/protein_cpu_slice.yaml \
      --checkpoint logs/protein_cpu_slice/<exp>/state_dict/best_model.ckpt \
      [--json out.json]
"""

from __future__ import annotations

import argparse
import json

import jax
import numpy as np

from distegnn_tpu.config import derive_runtime_fields, load_config
from distegnn_tpu.data import GraphDataset, GraphLoader
from distegnn_tpu.data.protein import process_protein_cutoff
from distegnn_tpu.models.registry import get_model
from distegnn_tpu.train import make_eval_step
from distegnn_tpu.train.checkpoint import restore_params
from distegnn_tpu.utils.seed import fix_seed


def test_mse(config, model, params, eval_step, variant: str) -> float:
    d = config.data
    paths = process_protein_cutoff(
        d.data_dir, d.dataset_name, d.max_samples, d.radius, d.delta_t,
        d.cutoff_rate, backbone=d.backbone,
        test_rot=(variant == "rot"), test_trans=(variant == "trans"),
        seed=config.seed)
    ds_test = GraphDataset(paths[2], node_order=d.node_order)
    loader = GraphLoader(ds_test, d.batch_size, shuffle=False,
                         seed=config.seed, node_bucket=d.node_bucket,
                         edge_bucket=d.edge_bucket)
    num, den = 0.0, 0.0
    for batch in loader:
        # node-weighted global MSE, accumulated the way the trainer does
        n_nodes = float(np.asarray(batch.node_mask).sum())
        num += float(eval_step(params, batch)) * n_nodes
        den += n_nodes
    return num / max(den, 1.0)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config_path", required=True)
    ap.add_argument("--checkpoint", required=True)
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    config = load_config(args.config_path)
    derive_runtime_fields(config, world_size=1)
    fix_seed(config.seed)

    model = get_model(config.model, world_size=1,
                      dataset_name=config.data.dataset_name)
    # init against one plain test batch to get the param structure
    d = config.data
    paths = process_protein_cutoff(
        d.data_dir, d.dataset_name, d.max_samples, d.radius, d.delta_t,
        d.cutoff_rate, backbone=d.backbone, seed=config.seed)
    ds = GraphDataset(paths[2], node_order=d.node_order)
    loader = GraphLoader(ds, d.batch_size, shuffle=False, seed=config.seed,
                         node_bucket=d.node_bucket, edge_bucket=d.edge_bucket)
    params = model.init(jax.random.PRNGKey(config.seed), next(iter(loader)))
    params = restore_params(args.checkpoint, params)
    eval_step = jax.jit(make_eval_step(model))

    out = {"checkpoint": args.checkpoint}
    for variant in ("plain", "rot", "trans"):
        out[f"test_mse_{variant}"] = test_mse(config, model, params,
                                              eval_step, variant)
        print(f"test MSE ({variant}):  {out[f'test_mse_{variant}']:.6f}")
    rel = max(abs(out["test_mse_rot"] - out["test_mse_plain"]),
              abs(out["test_mse_trans"] - out["test_mse_plain"]))
    out["max_abs_deviation"] = rel
    print(f"max |deviation| vs plain: {rel:.2e} "
          f"({'equivariant' if rel < 0.05 * out['test_mse_plain'] + 1e-6 else 'DEGRADED'})")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
