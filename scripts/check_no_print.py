#!/usr/bin/env python
"""Lint: no new bare ``print(`` calls inside distegnn_tpu/.

Runtime output goes through ``obs.log()`` (distegnn_tpu/obs/trace.py) — it
keeps stdout line-compatible, prefixes non-zero process indices, always
flushes, and mirrors every message into the structured event stream so
``scripts/obs_report.py`` sees it. A bare print does none of that and is
invisible to the run report.

Escape hatches, both deliberate and auditable:
  - a line comment ``# noqa: obs-print`` (the logger's own print, harness
    contract lines that tests parse from stdout);
  - the ``_ALLOWLIST`` below for whole files that are CLI harnesses rather
    than library code.

Wired into tier-1 via tests/test_obs.py::test_no_bare_prints. Exit codes:
0 clean, 1 violations (one ``path:line: text`` per offending line).
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "distegnn_tpu")

# `print(` not preceded by a word char or '.' (so `pprint(`, `x.print(` and
# def-lines don't match); comments are stripped line-wise before matching
_PRINT_RE = re.compile(r"(?<![\w.])print\s*\(")
_NOQA = "noqa: obs-print"

# whole-file allowlist: CLI harnesses whose stdout IS the interface
_ALLOWLIST = frozenset({
    "obs/trace.py",  # obs.log's own print lives here
})


def _strip_comment(line: str) -> str:
    """Drop a trailing # comment (good enough for a lint: '#' inside string
    literals can false-negative a match, never false-positive one)."""
    i = line.find("#")
    return line if i < 0 else line[:i]


def find_violations(package_dir: str = PACKAGE):
    """[(relpath, lineno, line)] of bare prints outside the escape hatches."""
    out = []
    for root, _dirs, files in os.walk(package_dir):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, package_dir).replace(os.sep, "/")
            if rel in _ALLOWLIST:
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if _NOQA in line:
                        continue
                    if _PRINT_RE.search(_strip_comment(line)):
                        out.append((rel, lineno, line.rstrip()))
    return out


def main(argv=None) -> int:
    violations = find_violations()
    for rel, lineno, line in violations:
        print(f"distegnn_tpu/{rel}:{lineno}: bare print — use obs.log() "
              f"(or '# noqa: obs-print'): {line.strip()}")
    if violations:
        print(f"\n{len(violations)} bare print(s); see scripts/check_no_print.py "
              "docstring for the escape hatches")
        return 1
    print("check_no_print: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
