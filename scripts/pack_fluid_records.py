#!/usr/bin/env python
"""Pack simulated fluid scenes into training shards (Fluid113K stage 2).

In-tree port of the reference's create_physics_records.py CLI
(dataset_generation/Fluid113K/create_physics_records.py:108-148): every
``sim_*/partio`` directory under --input becomes 16 ``sim_XXXX_YY.msgpack.zst``
shards under --output — exactly what ``distegnn_tpu.data.fluid113k.read_sim``
(and the reference trainer) consumes.

    python scripts/pack_fluid_records.py \
        --input data/fluid_scenes --output data/LargeFluid/Fluid113K
"""

import argparse
import glob
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--input", required=True, help="directory of sim_* scene dirs")
    p.add_argument("--output", required=True, help="shard output directory")
    p.add_argument("--splits", type=int, default=16,
                   help="shards per simulation (default 16 = fluid113k.SHARDS_PER_SIM)")
    p.add_argument("--radius", type=float, default=0.025)
    args = p.parse_args()

    from distegnn_tpu.data.fluid_scenes import pack_scene_records

    os.makedirs(args.output, exist_ok=True)
    scene_dirs = sorted(glob.glob(os.path.join(args.input, "sim_*")))
    if not scene_dirs:
        print(f"no sim_* directories under {args.input}", file=sys.stderr)
        return 1
    for scene_dir in scene_dirs:
        name = os.path.basename(scene_dir)
        try:
            shards = pack_scene_records(scene_dir, name,
                                        os.path.join(args.output, name),
                                        splits=args.splits, radius=args.radius)
        except FileNotFoundError as e:
            print(f"skipping {name}: {e}", file=sys.stderr)
            continue
        print(f"{name}: {len(shards)} shards")
    return 0


if __name__ == "__main__":
    sys.exit(main())
