#!/usr/bin/env python
"""Render an obs events.jsonl stream as a human-readable run report.

Usage:
  python scripts/obs_report.py <events.jsonl> [--json] [--check]
                               [--request <id>] [--slo <spec.yaml>]

  --json         emit the summary dict as one JSON object instead of text
  --check        CI gate: exit 1 if the stream has ZERO events (telemetry
                 dead) or ANY recompile after warmup (the silent
                 shape-ladder bug); failures are printed to stderr
  --request ID   render the queue -> batch -> compute waterfall for one
                 gateway request id instead of the run report; exit 1 when
                 the id is absent from the stream (with --json: the
                 stitched dict)
  --slo SPEC     evaluate an SLO spec (a YAML/JSON file: the `slo:` config
                 section, or a full config containing one) against the
                 event stream; renders the verdict table and exits 1 on
                 any breach (with --json: the results dict)

Sibling ``events_worker_*.jsonl`` files (written by process-backed serving
workers) are merged into the stream automatically, so a request served
across the process boundary still renders one complete waterfall.

The heavy lifting lives in distegnn_tpu.obs.report (pure functions over
parsed events) so tests drive it without a subprocess. Typical sources:
  <log_dir>/<exp_name>/obs/events.jsonl    (training, process 0)
  logs/serve_bench/obs/events.jsonl        (scripts/serve_bench.py)
  logs/traffic_gen/obs/events.jsonl        (scripts/traffic_gen.py)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from distegnn_tpu.obs.report import (check, load_run_events, render_request,
                                     render_text, request_ids_seen,
                                     stitch_request, summarize)


def _report_request(events, rid: str, source: str, as_json: bool) -> int:
    stitched = stitch_request(events, rid)
    if not stitched["records"]:
        known = request_ids_seen(events)
        print(f"obs_report: request {rid!r} not found in {source} "
              f"({len(known)} id(s) present"
              + (f", e.g. {known[0]!r}" if known else "") + ")",
              file=sys.stderr)
        return 1
    if as_json:
        print(json.dumps(stitched, sort_keys=True, default=str))
    else:
        print(render_request(stitched, source=source), end="")
    return 0


def _report_slo(events, spec_path: str, source: str, as_json: bool) -> int:
    from distegnn_tpu.obs import slo as slomod

    if not os.path.exists(spec_path):
        print(f"obs_report: no such SLO spec: {spec_path}", file=sys.stderr)
        return 2
    spec = slomod.SLOSpec.from_file(spec_path)
    results = slomod.evaluate(spec, slomod.stats_from_events(events))
    if as_json:
        print(json.dumps(slomod.results_json(results), sort_keys=True))
    else:
        print(slomod.verdict_table(results, source=source), end="")
    return 1 if slomod.breached(results) else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("events", help="path to an events.jsonl file")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the summary as JSON instead of text")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on zero events or recompiles after warmup")
    ap.add_argument("--request", metavar="ID", default=None,
                    help="render one request's waterfall instead of the "
                         "run report")
    ap.add_argument("--slo", metavar="SPEC", default=None,
                    help="evaluate an SLO spec file against the stream; "
                         "exit 1 on breach")
    args = ap.parse_args(argv)

    if not os.path.exists(args.events):
        print(f"obs_report: no such file: {args.events}", file=sys.stderr)
        return 2
    # the full run stream: the named file plus any sibling worker-child
    # sinks (events_worker_*.jsonl), so cross-process requests stitch
    events, bad, files = load_run_events(args.events)
    source = (args.events if len(files) == 1
              else f"{args.events} (+{len(files) - 1} worker stream(s))")

    if args.request is not None:
        return _report_request(events, args.request, source,
                               args.as_json)
    if args.slo is not None:
        return _report_slo(events, args.slo, source, args.as_json)

    summary = summarize(events)
    if args.as_json:
        print(json.dumps({**summary, "bad_lines": bad}, sort_keys=True))
    else:
        print(render_text(summary, source=source, bad_lines=bad), end="")

    if args.check:
        fails = check(summary)
        for f in fails:
            print(f"obs_report --check FAIL: {f}", file=sys.stderr)
        if fails:
            return 1
        print("obs_report --check: OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
