#!/usr/bin/env python
"""Render an obs events.jsonl stream as a human-readable run report.

Usage:
  python scripts/obs_report.py <events.jsonl> [--json] [--check]

  --json    emit the summary dict as one JSON object instead of text
  --check   CI gate: exit 1 if the stream has ZERO events (telemetry dead)
            or ANY recompile after warmup (the silent shape-ladder bug);
            failures are printed to stderr after the report

The heavy lifting lives in distegnn_tpu.obs.report (pure functions over
parsed events) so tests drive it without a subprocess. Typical sources:
  <log_dir>/<exp_name>/obs/events.jsonl    (training, process 0)
  logs/serve_bench/obs/events.jsonl        (scripts/serve_bench.py)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from distegnn_tpu.obs.report import check, load_events, render_text, summarize


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("events", help="path to an events.jsonl file")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the summary as JSON instead of text")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on zero events or recompiles after warmup")
    args = ap.parse_args(argv)

    if not os.path.exists(args.events):
        print(f"obs_report: no such file: {args.events}", file=sys.stderr)
        return 2
    events, bad = load_events(args.events)
    summary = summarize(events)
    if args.as_json:
        print(json.dumps({**summary, "bad_lines": bad}, sort_keys=True))
    else:
        print(render_text(summary, source=args.events, bad_lines=bad), end="")

    if args.check:
        fails = check(summary)
        for f in fails:
            print(f"obs_report --check FAIL: {f}", file=sys.stderr)
        if fails:
            return 1
        print("obs_report --check: OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
