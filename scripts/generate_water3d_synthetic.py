"""Synthetic Water-3D raw h5 generator (bounded e2e evidence runs).

The real Water-3D dataset is DeepMind's learning-to-simulate water scenes,
converted from tfrecord to h5 by scripts/water3d_tfrecord_to_h5.py
(format validated on authentic tf.train.SequenceExample bytes in
tests/test_water3d_tfrecord.py) — the bytes themselves are egress-blocked
in this container. This script writes the SAME h5 layout
(traj_<k>/position [T,N,3] + particle_type [N]) with the damped pseudo-SPH
dynamic of scripts/generate_fluid_synthetic.py at Water-3D edge density, so
the full cutoff pipeline (h5 -> per-frame graphs -> training) runs end to
end and leaves a loss-curve artifact. NOT physical water — pipeline and
training-behavior evidence only; swap in the converted real h5 for accuracy
work (docs/DATASETS.md).

Usage: python scripts/generate_water3d_synthetic.py --out data/simulate \
           [--particles 2000] [--frames 45] [--trajs 4]
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def synth_traj(rng: np.random.Generator, n: int, frames: int, radius: float):
    """Damped falling-particle cloud at ~15 neighbors within ``radius``."""
    vol = n * (4.0 / 3.0) * np.pi * radius**3 / 15.0
    side = vol ** (1.0 / 3.0)
    pos = rng.uniform(0, side, size=(n, 3)).astype(np.float32)
    # motion scaled so the delta_t=20 target displacement is a meaningful
    # fraction of the neighbourhood radius (~0.01-0.02 vs side ~0.29): a
    # first cut with ~100x weaker dynamics made the prediction task trivial
    # (loss floor 2e-7 by epoch 4 — no learning curve to show)
    vel = rng.normal(size=(n, 3)).astype(np.float32) * 0.02
    g = np.array([0.0, 0.0, -0.05], np.float32)
    poss = []
    for _ in range(frames):
        vel = 0.99 * vel + g * 0.01 + rng.normal(size=(n, 3)).astype(np.float32) * 2e-3
        pos = pos + vel * 0.02
        under, over = pos < 0, pos > side
        vel = np.where(under | over, -0.5 * vel, vel)
        pos = np.clip(pos, 0, side)
        poss.append(pos.copy())
    return np.stack(poss)


def main() -> None:
    import h5py

    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="data/simulate")
    ap.add_argument("--particles", type=int, default=2000)
    ap.add_argument("--frames", type=int, default=45)
    ap.add_argument("--trajs", type=int, default=4)
    ap.add_argument("--radius", type=float, default=0.035,
                    help="density target (reference water3d radius)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    rng = np.random.default_rng(args.seed)
    base = os.path.join(args.out, "Water-3D")
    os.makedirs(base, exist_ok=True)
    for split in ("train", "valid", "test"):
        with h5py.File(os.path.join(base, f"{split}.h5"), "w") as f:
            for k in range(args.trajs):
                g = f.create_group(f"traj_{k}")
                g["particle_type"] = np.full((args.particles,), 5.0)
                g["position"] = synth_traj(rng, args.particles, args.frames,
                                           args.radius)
        print(f"wrote {split}.h5: {args.trajs} trajs x [{args.frames}, "
              f"{args.particles}, 3]")


if __name__ == "__main__":
    main()
