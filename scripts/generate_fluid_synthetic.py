"""Synthetic Fluid113K-format data generator (pipeline validation at any
scale).

The reference produces Fluid113K by driving the external SPlisHSPlasH C++
simulator (dataset_generation/Fluid113K/create_physics_scenes.py:1-497 +
create_physics_records.py:1-148, ~930 LoC of scene synthesis around two
native binaries). That physics pipeline stays OFFLINE and out of the training
path; real data is downloadable (reference README.md:21, docs/DATASETS.md).

This script covers the other need those files served: producing data in the
exact on-disk format at a chosen scale, so the full distribute pipeline
(read_sim -> build_fluid_graph -> METIS partitioning -> ShardedGraphLoader ->
shard_map training) can be exercised end-to-end without the native simulator.
Particles follow a cheap damped pseudo-SPH dynamic (gravity + box bounce +
velocity noise) — NOT physical fluid; use it for plumbing and performance
work, never for accuracy claims.

  python scripts/generate_fluid_synthetic.py --out data/LargeFluid \
      --particles 113140 --sims-train 2 --sims-valid 1 --sims-test 1
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distegnn_tpu.data.fluid113k import SIM_SPLITS, write_fluid_sim  # noqa: E402


def synth_sim(rng: np.random.Generator, n: int, frames: int, radius: float):
    """Damped falling-particle cloud in a unit-ish box at a density giving
    ~15 neighbors within ``radius`` (the Fluid113K edge density)."""
    vol = n * (4.0 / 3.0) * np.pi * radius**3 / 15.0
    side = vol ** (1.0 / 3.0)
    pos = rng.uniform(0, side, size=(n, 3)).astype(np.float32)
    vel = rng.normal(size=(n, 3)).astype(np.float32) * 0.01
    g = np.array([0.0, 0.0, -0.05], np.float32)
    poss, vels = [], []
    for _ in range(frames):
        vel = 0.99 * vel + g * 0.01 + rng.normal(size=(n, 3)).astype(np.float32) * 1e-3
        pos = pos + vel * 0.01
        # bounce off the box walls
        under, over = pos < 0, pos > side
        vel = np.where(under | over, -0.5 * vel, vel)
        pos = np.clip(pos, 0, side)
        poss.append(pos.copy())
        vels.append(vel.copy())
    return np.stack(poss), np.stack(vels)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", type=str, required=True)
    p.add_argument("--dataset_name", type=str, default="Fluid113K")
    p.add_argument("--particles", type=int, default=113_140)
    p.add_argument("--frames", type=int, default=48)
    p.add_argument("--radius", type=float, default=0.075)
    p.add_argument("--sims-train", type=int, default=2)
    p.add_argument("--sims-valid", type=int, default=1)
    p.add_argument("--sims-test", type=int, default=1)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    rng = np.random.default_rng(args.seed)
    counts = {"train": args.sims_train, "valid": args.sims_valid, "test": args.sims_test}
    for split, (lo, _) in SIM_SPLITS.items():
        for k in range(counts[split]):
            pos, vel = synth_sim(rng, args.particles, args.frames, args.radius)
            visc = np.full((args.particles,), 0.01, np.float32)
            mass = np.full((args.particles,), 0.1, np.float32)
            write_fluid_sim(args.out, args.dataset_name, lo + k, pos, vel, visc, mass)
            print(f"wrote sim {lo + k} ({split}): {args.particles} particles x "
                  f"{args.frames} frames", flush=True)


if __name__ == "__main__":
    main()
