"""Autoregressive rollout MSE evaluation (the BASELINE.md "rollout MSE"
surface).

The reference evaluates one-step MSE only; this drives the framework's
on-device rollout (distegnn_tpu/rollout.py: predict -> rebuild the radius
graph on device -> next step, all inside one lax.scan) against ground-truth
trajectory frames and reports MSE per horizon.

Wired datasets (dispatch on config data.dataset_name):
  nbody*   — raw loc/vel/charges .npy trajectories; full graph emulated with
             a radius larger than the system; horizons keyed by FRAME index.
  Water-3D — h5 trajectories, multi-step (--max-steps) radius-graph rollout;
             horizons keyed by rollout STEP (each spanning delta_t frames);
             rollout displacement rescaled to the pipeline's one-frame
             velocity convention.
  Fluid113K — zstd/msgpack simulations (the BASELINE.md headline dataset);
             horizons keyed by rollout STEP; velocity convention converted
             with a data-estimated frame duration.

Usage:
  python scripts/evaluate_rollout.py --config_path configs/nbody_fastegnn.yaml \
      [--checkpoint logs/.../best_model.ckpt] [--samples 50] [--split test]

Prints one JSON line: {"metric": "rollout_mse", "horizons": {frame: mse}, ...}
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def evaluate_nbody_rollout(config, checkpoint=None, samples=50, split="test",
                           edge_block=256, seed=0):
    """Rollout the n-body test trajectories; returns {horizon_frame: mse}."""
    import jax
    import jax.numpy as jnp

    from distegnn_tpu.data.nbody import _find_tag
    from distegnn_tpu.models.registry import get_model
    from distegnn_tpu.ops.graph import _round_up
    from distegnn_tpu.rollout import make_rollout_fn

    base = os.path.join(config.data.data_dir, config.data.dataset_name)
    tag = _find_tag(base, split)
    loc = np.load(os.path.join(base, f"loc_{split}_{tag}.npy"))[:samples]
    vel = np.load(os.path.join(base, f"vel_{split}_{tag}.npy"))[:samples]
    charges = np.load(os.path.join(base, f"charges_{split}_{tag}.npy"))[:samples]
    num, T, n, _ = loc.shape
    f0, fT = config.data.frame_0, config.data.frame_T
    delta = fT - f0
    steps = max((T - 1 - f0) // delta, 1)
    horizons = [f0 + (k + 1) * delta for k in range(steps) if f0 + (k + 1) * delta < T]
    if not horizons:
        raise ValueError(
            f"trajectory too short to evaluate: T={T} frames, first horizon "
            f"would be frame {f0 + delta} (frame_0={f0}, delta={delta})")

    N = _round_up(n, edge_block)
    node_mask = np.zeros((N,), np.float32)
    node_mask[:n] = 1.0

    # full graph (radius -1) emulated with a radius larger than any system
    # extent; real radius configs pass through unchanged
    radius = float(config.data.radius)
    if radius <= 0:
        radius = float(np.abs(loc).max()) * 2.0 + 1.0
    max_degree = max(_round_up(n - 1, 2), 2)
    while (max_degree * edge_block) % 512:
        max_degree += 2

    model = get_model(config.model, dataset_name=config.data.dataset_name)
    rollout = jax.jit(
        make_rollout_fn(model, radius=radius, max_degree=max_degree,
                        max_per_cell=N,
                        feature_fn=_speed_plus_static_feature,
                        edge_block=edge_block),
        static_argnums=(4,))

    mask_j = jnp.asarray(node_mask)
    mse_acc = {h: 0.0 for h in horizons}
    params = _init_params(model, checkpoint, config, seed)
    for k in range(num):
        # charges passed per-sample as a rollout ARGUMENT (not a closure), so
        # the jitted rollout is compiled once and reused across samples;
        # normalization matches the training pipeline (build_nbody_graph:
        # charges / charges.max(), no abs)
        qn_pad = np.zeros((N, 1), np.float32)
        qn_pad[:n] = (charges[k] / charges[k].max()).astype(np.float32).reshape(n, 1)
        loc0 = np.zeros((N, 3), np.float32)
        vel0 = np.zeros((N, 3), np.float32)
        loc0[:n], vel0[:n] = loc[k, f0], vel[k, f0]

        traj, overflow = rollout(params, jnp.asarray(loc0), jnp.asarray(vel0),
                                 mask_j, steps, (jnp.asarray(qn_pad),))
        if bool(np.asarray(overflow).any()):
            raise RuntimeError(
                f"radius-graph capacity overflow on sample {k} — raise "
                "max_degree/max_per_cell; MSE from a truncated graph is invalid")
        for i, h in enumerate(horizons):
            pred = np.asarray(traj[i])[:n]
            mse_acc[h] += float(np.mean((pred - loc[k, h]) ** 2))
    return {h: mse_acc[h] / num for h in horizons}, steps, num


def _speed_plus_static_feature(v, static):
    """The shared rollout feature_fn: [|v|, static channel] — the canonical
    conventions live in the training pipelines (nbody.py build_nbody_graph:
    [|v|, q/q.max]; water3d.py build_water3d_graph: [|v|, type/type.max]);
    the static channel is precomputed per sample with exactly those
    normalizations and passed as a rollout feat_arg."""
    import jax.numpy as jnp

    speed = jnp.linalg.norm(v, axis=-1, keepdims=True)
    return jnp.concatenate([speed, static], axis=-1)


def evaluate_water3d_rollout(config, checkpoint=None, samples=4, split="test",
                             edge_block=256, seed=0, max_steps=5,
                             degree_margin=2.0):
    """Multi-step rollout over Water-3D h5 trajectories; returns
    ({step_index: mse}, steps, num_trajectories). Each rollout step spans
    ``delta_t`` frames starting at frame 0; velocities follow the training
    convention (one-frame position delta), so the rollout's displacement is
    rescaled by 1/delta_t."""
    import h5py
    import jax
    import jax.numpy as jnp

    from distegnn_tpu.models.registry import get_model
    from distegnn_tpu.ops.graph import _round_up
    from distegnn_tpu.rollout import make_rollout_fn

    radius = float(config.data.radius)
    delta = int(config.data.delta_t)
    trajs, t_min = [], None
    with h5py.File(os.path.join(config.data.data_dir, config.data.dataset_name,
                                f"{split}.h5"), "r") as f:
        for key in sorted(f.keys())[:samples]:
            T = f[key]["position"].shape[0]
            t_min = T if t_min is None else min(t_min, T)
            # partial read: a rollout of max_steps only touches the first
            # max_steps*delta + 1 frames (vel0 needs frame 1)
            pos = np.asarray(f[key]["position"][:max_steps * delta + 1], np.float32)
            trajs.append((pos, np.asarray(f[key]["particle_type"], np.float32)))
    if not trajs:
        raise ValueError("no trajectories in the h5 file")

    # a step k needs target frame k*delta and vel0 needs frame 1 (T >= 2)
    steps = min(max_steps, (t_min - 1) // delta)
    if steps < 1 or t_min < 2:
        raise ValueError(
            f"trajectories too short for one rollout step of delta_t={delta} "
            f"(shortest has {t_min} frames)")
    n_max = max(p.shape[1] for p, _ in trajs)
    N = _round_up(n_max, edge_block)

    max_degree, max_per_cell = _calibrate_degree(
        (pos[0] for pos, _ in trajs), radius, edge_block, degree_margin)

    model = get_model(config.model, dataset_name=config.data.dataset_name)
    rollout = jax.jit(
        make_rollout_fn(model, radius=radius, max_degree=max_degree,
                        max_per_cell=max_per_cell,
                        feature_fn=_speed_plus_static_feature,
                        edge_block=edge_block,
                        velocity_scale=1.0 / delta),
        static_argnums=(4,))

    params = _init_params(model, checkpoint, config, seed)
    mse_acc = {k: 0.0 for k in range(1, steps + 1)}
    for pos, ptype in trajs:
        n = pos.shape[1]
        mask = np.zeros((N,), np.float32)
        mask[:n] = 1.0
        tn = np.zeros((N, 1), np.float32)
        tn[:n, 0] = ptype / max(float(ptype.max()), 1e-12)
        loc0 = np.zeros((N, 3), np.float32)
        vel0 = np.zeros((N, 3), np.float32)
        loc0[:n] = pos[0]
        vel0[:n] = pos[1] - pos[0]
        traj, overflow = rollout(params, jnp.asarray(loc0), jnp.asarray(vel0),
                                 jnp.asarray(mask), steps, (jnp.asarray(tn),))
        if bool(np.asarray(overflow).any()):
            raise RuntimeError(_OVERFLOW_MSG)
        for k in range(1, steps + 1):
            pred = np.asarray(traj[k - 1])[:n]
            mse_acc[k] += float(np.mean((pred - pos[k * delta]) ** 2))
    num = len(trajs)
    return {k: v / num for k, v in mse_acc.items()}, steps, num


def _calibrate_degree(first_frames, radius, edge_block, margin):
    """(max_degree, max_per_cell) for the on-device radius graph, from the
    max observed first-frame degree x safety margin, 512-aligned for the
    blocked layout."""
    from distegnn_tpu.ops.graph import _round_up
    from distegnn_tpu.ops.radius import radius_graph_np

    deg0 = 1
    for pos0 in first_frames:
        ei = radius_graph_np(pos0, radius)
        deg = np.bincount(ei[0], minlength=pos0.shape[0]).max() if ei.size else 1
        deg0 = max(deg0, int(deg))
    max_degree = _round_up(int(deg0 * margin) + 1, 2)
    while (max_degree * edge_block) % 512:
        max_degree += 2
    return max_degree, max(int(deg0 * margin), 32)


_OVERFLOW_MSG = ("radius-graph capacity overflow — re-run with a larger "
                 "--degree-margin; MSE from a truncated graph is invalid")


def _static_plus_speed_feature(v, static):
    """Fluid113K's rollout feature_fn: [viscosity, mass, |v|] — static
    channels FIRST, matching build_fluid_graph (data/fluid113k.py:118-119)."""
    import jax.numpy as jnp

    speed = jnp.linalg.norm(v, axis=-1, keepdims=True)
    return jnp.concatenate([static, speed], axis=-1)


def evaluate_fluid113k_rollout(config, checkpoint=None, samples=2, split="test",
                               edge_block=256, seed=0, max_steps=5,
                               degree_margin=2.0):
    """Multi-step rollout over Fluid113K (LargeFluid) simulations — the
    BASELINE.md headline dataset. Horizons keyed by rollout step (delta_t
    frames each, starting at frame 0). The sim's own velocity field is the
    model input; the rollout's delta_t-frame displacement is converted back
    to that convention with a data-estimated frame duration."""
    import jax
    import jax.numpy as jnp

    from distegnn_tpu.data.fluid113k import SIM_SPLITS, read_sim
    from distegnn_tpu.models.registry import get_model
    from distegnn_tpu.ops.graph import _round_up
    from distegnn_tpu.rollout import make_rollout_fn

    delta = int(config.data.delta_t)
    radius = float(config.data.inner_radius or config.data.radius)
    lo, hi = SIM_SPLITS[split]
    sims = []
    for idx in range(lo, min(lo + samples, hi)):
        try:
            pos, vel, visc, mass = read_sim(config.data.data_dir,
                                            config.data.dataset_name, idx)
        except FileNotFoundError:
            break
        # keep only the frames a rollout touches (read_sim has no partial
        # read — shards are whole-file zstd — but the stacked tail can be
        # dropped immediately: frames 0..max_steps*delta)
        keep = max_steps * int(config.data.delta_t) + 1
        sims.append((pos[:keep] if pos.shape[0] > keep else pos,
                     vel[:1], visc, mass))
    if not sims:
        raise ValueError(f"no {split} simulations found under "
                         f"{config.data.data_dir}/{config.data.dataset_name}")

    t_min = min(pos.shape[0] for pos, _, _, _ in sims)
    steps = min(max_steps, (t_min - 1) // delta)
    if steps < 1:
        raise ValueError(
            f"simulations too short for one rollout step of delta_t={delta} "
            f"(shortest has {t_min} frames)")
    n_max = max(pos.shape[1] for pos, _, _, _ in sims)
    N = _round_up(n_max, edge_block)

    # frame duration estimated from the data: |pos[1]-pos[0]| ~ |vel[0]|*dt.
    # A degenerate estimate means the velocity convention cannot be recovered
    # and any MSE would be silently wrong — refuse, like the overflow path.
    dts = []
    for pos, vel, _, _ in sims:
        dx = np.linalg.norm(pos[1] - pos[0], axis=1)
        v0 = np.linalg.norm(vel[0], axis=1)
        ok = v0 > 1e-8
        if ok.any():
            dts.append(float(np.median(dx[ok] / v0[ok])))
    frame_dt = float(np.median(dts)) if dts else 0.0
    if not np.isfinite(frame_dt) or frame_dt <= 0:
        raise ValueError(
            "cannot estimate the frame duration from the data (static first "
            "frames or zero velocities) — the rollout velocity convention "
            "would be wrong; check the simulation dump")

    max_degree, max_per_cell = _calibrate_degree(
        (pos[0] for pos, _, _, _ in sims), radius, edge_block, degree_margin)

    model = get_model(config.model, dataset_name=config.data.dataset_name)
    rollout = jax.jit(
        make_rollout_fn(model, radius=radius, max_degree=max_degree,
                        max_per_cell=max_per_cell,
                        feature_fn=_static_plus_speed_feature,
                        edge_block=edge_block,
                        velocity_scale=1.0 / (delta * frame_dt)),
        static_argnums=(4,))

    params = _init_params(model, checkpoint, config, seed)
    mse_acc = {k: 0.0 for k in range(1, steps + 1)}
    for pos, vel, viscosity, mass in sims:
        n = pos.shape[1]
        mask = np.zeros((N,), np.float32)
        mask[:n] = 1.0
        attr = np.zeros((N, 2), np.float32)
        attr[:n, 0] = viscosity
        attr[:n, 1] = mass
        loc0 = np.zeros((N, 3), np.float32)
        vel0 = np.zeros((N, 3), np.float32)
        loc0[:n], vel0[:n] = pos[0], vel[0]
        attr_j = jnp.asarray(attr)
        # attr enters BOTH as node_feat channels (feature_fn) and as the
        # model's node_attr input (node_attr_nf=2 in the largefluid config)
        traj, overflow = rollout(params, jnp.asarray(loc0), jnp.asarray(vel0),
                                 jnp.asarray(mask), steps, (attr_j,),
                                 node_attr_now=attr_j)
        if bool(np.asarray(overflow).any()):
            raise RuntimeError(_OVERFLOW_MSG)
        for k in range(1, steps + 1):
            pred = np.asarray(traj[k - 1])[:n]
            mse_acc[k] += float(np.mean((pred - pos[k * delta]) ** 2))
    num = len(sims)
    return {k: v / num for k, v in mse_acc.items()}, steps, num


def _init_params(model, checkpoint, config, seed):
    """Params from a checkpoint when given, else fresh init (smoke mode)."""
    import jax

    # init on a minimal batch of the right feature widths (shape-polymorphic
    # flax init; the rollout batch differs only in N/E)
    from distegnn_tpu.ops.graph import pad_graphs

    rng = np.random.default_rng(seed)
    n = 4
    g = {
        "node_feat": rng.normal(size=(n, config.model.node_feat_nf)).astype(np.float32),
        "node_attr": np.ones((n, int(config.model.get("node_attr_nf", 0))), np.float32),
        "loc": rng.normal(size=(n, 3)).astype(np.float32),
        "vel": rng.normal(size=(n, 3)).astype(np.float32),
        "target": np.zeros((n, 3), np.float32),
        "edge_index": np.stack([np.arange(n), np.roll(np.arange(n), 1)]).astype(np.int64),
        "edge_attr": np.ones((n, config.model.edge_attr_nf), np.float32),
    }
    params = model.init(jax.random.PRNGKey(seed), pad_graphs([g]))
    if checkpoint:
        # params-only: evaluation must load checkpoints written with ANY
        # optimizer wrapping (grad accumulation changes the opt-state tree)
        from distegnn_tpu.train.checkpoint import restore_params

        params = restore_params(checkpoint, params)
    return params


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--config_path", required=True)
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--samples", type=int, default=50)
    ap.add_argument("--split", default="test")
    ap.add_argument("--max-steps", type=int, default=5,
                    help="rollout horizon cap (trajectory datasets)")
    ap.add_argument("--degree-margin", type=float, default=2.0,
                    help="radius-graph capacity = observed degree x margin")
    ap.add_argument("--platform", default=None,
                    help="pin a jax platform (e.g. cpu) before backend init")
    args = ap.parse_args(argv)

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from distegnn_tpu.config import load_config

    config = load_config(args.config_path)
    name = config.data.dataset_name
    if name.startswith("nbody"):
        horizons, steps, num = evaluate_nbody_rollout(
            config, checkpoint=args.checkpoint, samples=args.samples,
            split=args.split)
    elif name == "Water-3D":
        horizons, steps, num = evaluate_water3d_rollout(
            config, checkpoint=args.checkpoint, samples=args.samples,
            split=args.split, max_steps=args.max_steps,
            degree_margin=args.degree_margin)
    elif name == "Fluid113K":
        horizons, steps, num = evaluate_fluid113k_rollout(
            config, checkpoint=args.checkpoint, samples=args.samples,
            split=args.split, max_steps=args.max_steps,
            degree_margin=args.degree_margin)
    else:
        raise SystemExit(f"no rollout evaluator wired for dataset {name!r} "
                         "(supported: nbody*, Water-3D, Fluid113K)")
    print(json.dumps({
        "metric": "rollout_mse",
        "dataset": name,
        "split": args.split,
        "samples": num,
        "steps": steps,
        "checkpoint": args.checkpoint,
        # significant figures, not fixed decimals: fluid displacement targets
        # give MSEs of 1e-9 scale, which round(_, 6) flattened to 0.0
        "horizons": {str(k): float(f"{v:.4g}") for k, v in horizons.items()},
    }))


if __name__ == "__main__":
    main()
