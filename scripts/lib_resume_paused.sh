# Shared recovery for competitor processes a SIGKILLed bench.py left
# SIGSTOPped. bench.py writes the PIDs it is about to pause to
# /tmp/bench_paused.pids BEFORE stopping them (ADVICE r3, medium): if the
# bench is SIGKILLed (driver hard-timeout / OOM) its finally-resume never
# runs, and without this ledger the frozen training/generation processes
# would stall unattended work for the rest of the round.
#
# Single implementation sourced by BOTH scripts/tpu_watch.sh and
# scripts/hw_session.sh — the two hand-rolled copies had already diverged
# (one missed absolute-path interpreters) when this file was factored out.
#
# Caller contract: only call when no queue-managed bench can be running
# (watcher: hw_session.lock observed free; hw_session: holds the lock).
# NOTE: liveness scans /proc by argv, never bare pgrep -f — the
# agent-driver's cmdline embeds 'bench.py' and matching it is the
# session-freezing hazard (BASELINE.md).

newest_resumable_ckpt() {  # newest_resumable_ckpt <log_root>  -> path; rc 1 if none
  # Newest-by-mtime across the trainer's three resumable checkpoint kinds
  # (docs/ROBUSTNESS.md): preempt_model.ckpt (SIGTERM handler; its dir also
  # carries a PREEMPTED marker), mid-epoch step_*.ckpt cadence saves, and the
  # eval-epoch last_model.ckpt. mtime ordering makes stale preempt markers
  # harmless — a later finished/resumed run's checkpoints sort first.
  local root=${1:?usage: newest_resumable_ckpt <log_root>} best
  best=$(ls -t "$root"/*/state_dict/preempt_model.ckpt \
               "$root"/*/state_dict/step_*.ckpt \
               "$root"/*/state_dict/last_model.ckpt 2>/dev/null | head -1)
  [ -n "$best" ] || return 1
  printf '%s\n' "$best"
}

bench_py_live() {
  local p
  for p in /proc/[0-9]*; do
    # interpreter may be invoked bare ('python') or by absolute path
    # ('/usr/local/bin/python3.12') — same regex as hw_session's pgrep_py
    tr '\0' ' ' < "$p/cmdline" 2>/dev/null \
      | grep -Eq "^[^ ]*python[0-9.]* .*bench\.py" && return 0
  done
  return 1
}

proc_state() {
  # Single-letter process state. /proc/<pid>/stat field 2 is '(comm)' and
  # comm may contain spaces or parens, so whitespace field counting is
  # wrong; strip through the LAST ')' (greedy sed) — same reason bench.py
  # parses stat with split(') ').
  sed 's/^.*) //' "/proc/$1/stat" 2>/dev/null | awk '{print $1}'
}

resume_orphaned_paused() {  # resume_orphaned_paused [logfile]
  local f=/tmp/bench_paused.pids log=${1:-/dev/stdout} pid remaining=""
  [ -s "$f" ] || return 0
  bench_py_live && return 0  # a live bench's pause is intentional
  while read -r pid; do
    [ -n "$pid" ] || continue
    if [ "$(proc_state "$pid")" = "T" ]; then
      echo "$(date -u +%FT%TZ) resuming orphaned SIGSTOPped pid $pid (bench ledger)" >>"$log"
      kill -CONT "$pid" 2>/dev/null
    fi
  done < "$f"
  # Delete the ledger only once nothing it lists is still frozen — if a CONT
  # failed (or something re-stopped a pid) the record must survive for the
  # next recovery pass.
  while read -r pid; do
    [ -n "$pid" ] || continue
    [ "$(proc_state "$pid")" = "T" ] && remaining="$remaining $pid"
  done < "$f"
  if [ -n "$remaining" ]; then
    echo "$(date -u +%FT%TZ) pids still stopped after CONT:$remaining — keeping ledger" >>"$log"
  else
    rm -f "$f"
  fi
}
