"""Partition-quality measurement (VERDICT r2 next-round #5).

Compares the four partitioners (random / kmeans / spectral / native
metis-standin) on a Fluid113K-like particle cloud: edge-cut fraction
(the information the DistEGNN model LOSES — inter-partition edges are
dropped, global coupling flows only through virtual nodes), per-partition
node/edge spread (padding waste: every shard pads to the max), and wall
time. The reference reaches real libmetis via torch-sparse
(reference datasets/distribute_graphs.py:151-185); the in-tree C++
bisection+FM partitioner stands in, and this script is the evidence for
whether it is good enough (cut <= 1.5x spectral's) or needs multilevel
coarsening.

Usage: python scripts/partition_quality.py [--n 113140] [--parts 8]
       [--methods random,kmeans,metis] [--json out.json]
Spectral is O(N^2) affinity (sklearn) — include it only at --n <= ~20000.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

from distegnn_tpu.data.partition import assign_partitions  # noqa: E402
from distegnn_tpu.ops.radius import radius_graph_np  # noqa: E402

RADIUS = 0.075
TARGET_EDGES_PER_NODE = 15.0


def fluid_cloud(n: int, seed: int = 0) -> np.ndarray:
    """Uniform cloud at Fluid113K edge density (bench.py's workload)."""
    rng = np.random.default_rng(seed)
    vol = n * (4.0 / 3.0) * np.pi * RADIUS**3 / TARGET_EDGES_PER_NODE
    side = max(vol ** (1.0 / 3.0), 2.0 * RADIUS)
    return rng.uniform(0, side, size=(n, 3)).astype(np.float32)


def quality(labels: np.ndarray, edge_index: np.ndarray, n_parts: int) -> dict:
    row, col = edge_index
    cut = int((labels[row] != labels[col]).sum())
    nodes = np.bincount(labels, minlength=n_parts)
    # per-partition INNER edge count (what each shard keeps)
    same = labels[row] == labels[col]
    edges = np.bincount(labels[row[same]], minlength=n_parts)
    return {
        "cut_fraction": round(cut / max(edge_index.shape[1], 1), 4),
        "node_spread": f"{nodes.min()}..{nodes.max()}",
        "node_imbalance": round(float(nodes.max() / max(nodes.mean(), 1)), 3),
        "edge_spread": f"{edges.min()}..{edges.max()}",
        # padding waste: shards pad to the max edge count
        "edge_imbalance": round(float(edges.max() / max(edges.mean(), 1)), 3),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=113_140)
    ap.add_argument("--parts", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--methods", type=str, default="random,kmeans,metis")
    ap.add_argument("--json", type=str, default=None)
    args = ap.parse_args()

    loc = fluid_cloud(args.n, args.seed)
    t0 = time.perf_counter()
    edge_index = radius_graph_np(loc, RADIUS)
    print(f"N={args.n} E={edge_index.shape[1]} parts={args.parts} "
          f"(radius graph {time.perf_counter() - t0:.1f}s)", flush=True)

    results = {"n": args.n, "edges": int(edge_index.shape[1]),
               "parts": args.parts, "methods": {}}
    for method in args.methods.split(","):
        t0 = time.perf_counter()
        labels = assign_partitions(loc, args.parts, method,
                                   outer_radius=RADIUS, seed=args.seed)
        dt = time.perf_counter() - t0
        q = quality(labels, edge_index, args.parts)
        q["seconds"] = round(dt, 2)
        results["methods"][method] = q
        print(f"{method:9s} cut={q['cut_fraction']:.4f} "
              f"nodes {q['node_spread']} (x{q['node_imbalance']}) "
              f"edges {q['edge_spread']} (x{q['edge_imbalance']}) "
              f"[{dt:.1f}s]", flush=True)

    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
