#!/usr/bin/env python
"""Convert a processed pickle dataset (list of graph dicts) into the
out-of-core shard directory consumed by StreamedGraphDataset
(distegnn_tpu/data/stream.py): fixed-schema .npz shards + manifest.json with
per-shard maxima and CRC32 checksums.

Usage:
  python scripts/shard_dataset.py --input processed.pkl --out shards_dir \
      [--shard-size 64] [--node-order none|morton]

Point config.data paths at the output directory and launch.py streams it
instead of materializing the pickle (see docs/PERFORMANCE.md "Input
pipeline").
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--input", required=True,
                    help="processed dataset pickle (list of graph dicts)")
    ap.add_argument("--out", required=True, help="output shard directory")
    ap.add_argument("--shard-size", type=int, default=64,
                    help="graphs per shard (default 64)")
    ap.add_argument("--node-order", default="none", choices=["none", "morton"],
                    help="bake a node relabeling into the shards (morton: "
                         "Z-curve locality, ops/order.py)")
    args = ap.parse_args(argv)

    from distegnn_tpu.data.stream import write_shards

    with open(args.input, "rb") as f:
        graphs = pickle.load(f)
    manifest = write_shards(graphs, args.out, shard_size=args.shard_size,
                            node_order=args.node_order)
    print(json.dumps({
        "out": args.out,
        "n_graphs": manifest["n_graphs"],
        "n_shards": len(manifest["shards"]),
        "shard_size": manifest["shard_size"],
        "max_nodes": manifest["max_nodes"],
        "max_edges": manifest["max_edges"],
        "bytes": sum(s["bytes"] for s in manifest["shards"]),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
