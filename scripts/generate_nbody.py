"""CLI n-body dataset generator (reference dataset_generation/nbody/
generate_dataset.py). Writes reference-layout .npy files.

Example (the paper's 100-ball charged config, reference run.sh):
  python scripts/generate_nbody.py --path data/n_body_system/nbody_100 \
      --n_isolated 100 --num-train 5000 --num-valid 2000 --num-test 2000 --seed 43
"""

from __future__ import annotations

import argparse

from distegnn_tpu.data.nbody_sim import generate_nbody_files


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--path", type=str, default="data")
    p.add_argument("--num-train", type=int, default=5000)
    p.add_argument("--num-valid", type=int, default=2000)
    p.add_argument("--num-test", type=int, default=2000)
    p.add_argument("--length", type=int, default=5000)
    p.add_argument("--sample-freq", type=int, default=100)
    p.add_argument("--n_isolated", type=int, default=100)
    p.add_argument("--n_stick", type=int, default=0)
    p.add_argument("--n_hinge", type=int, default=0)
    p.add_argument("--clusters", type=int, default=1)
    p.add_argument("--seed", type=int, default=43)
    p.add_argument("--suffix", type=str, default="")
    p.add_argument("--box_size", type=float, default=None)
    args = p.parse_args()

    out = generate_nbody_files(
        args.path,
        n_isolated=args.n_isolated, n_stick=args.n_stick, n_hinge=args.n_hinge,
        clusters=args.clusters, num_train=args.num_train, num_valid=args.num_valid,
        num_test=args.num_test, length=args.length, sample_freq=args.sample_freq,
        seed=args.seed, suffix=args.suffix, box_size=args.box_size,
    )
    print(f"Generated: {out} -> {args.path}")


if __name__ == "__main__":
    main()
