"""Generate a SYNTHETIC AdK-shaped trajectory npz for the protein pipeline.

The real pipeline (distegnn_tpu/data/protein.py, mirroring reference
datasets/process_dataset.py:128-222) fetches the MDAnalysisData AdK
equilibrium trajectory — unavailable in a zero-egress container. This
script produces an npz with the SAME documented schema and scale so the
full protein path (npz -> per-frame graphs -> training -> test_rot /
test_trans equivariance evaluation) runs end to end on real-format data:

  positions  [T, N, 3] float32   T=4200 frames, N=856 backbone atoms
                                 (214 residues x N/CA/C/O — AdK backbone)
  charges    [N]       float32   CHARMM-like per-atom-type partial charges
  dimensions [3]       float32   box, scales the test_trans injection

Honesty note: the DYNAMICS are synthetic (a folded-globule random-walk
backbone animated by smooth low-frequency modes + small noise), not MD.
Artifacts produced from this npz validate the pipeline and equivariance
behavior, NOT MD accuracy parity. Swap in the genuine npz (see
extract_adk_npz) wherever MDAnalysis is available — every downstream path
is identical.

Usage: python scripts/generate_adk_synthetic.py [--out data/mdanalysis/protein]
"""

from __future__ import annotations

import argparse
import os

import numpy as np

N_RES = 214                      # AdK residues
ATOMS_PER_RES = 4                # backbone N, CA, C, O
T_FRAMES = 4200                  # reference protocol uses 4171 + delta_t
BOX = np.array([80.0, 80.0, 80.0], np.float32)
# CHARMM-ish backbone partial charges per atom type
CHARGES = np.array([-0.47, 0.07, 0.51, -0.51], np.float32)


def folded_backbone(rng) -> np.ndarray:
    """[N, 3] compact folded-chain starting structure: a persistent random
    walk of residue centers confined to a compact globule, with fixed small
    intra-residue offsets."""
    centers = np.zeros((N_RES, 3))
    direction = rng.standard_normal(3)
    direction /= np.linalg.norm(direction)
    for i in range(1, N_RES):
        # persistence + confinement toward the origin
        direction = 0.7 * direction + 0.6 * rng.standard_normal(3)
        direction -= 0.004 * centers[i - 1]
        direction /= np.linalg.norm(direction)
        centers[i] = centers[i - 1] + 3.8 * direction
    centers -= centers.mean(axis=0)
    # squash to backbone-realistic density: ~34 A extent puts the 10 A
    # contact degree near the real backbone's (~60), not a dense blob
    centers *= 34.0 / np.abs(centers).max()
    offsets = np.array([[-1.2, 0.4, 0.0], [0.0, 0.0, 0.0],
                        [1.3, 0.2, 0.3], [1.8, -0.9, 0.7]], np.float32)
    atoms = (centers[:, None, :] + offsets[None, :, :]).reshape(-1, 3)
    return atoms.astype(np.float32)


def animate(x0: np.ndarray, rng) -> np.ndarray:
    """[T, N, 3]: smooth low-frequency collective modes along the chain +
    small uncorrelated jitter. vel(t) = x(t+1) - x(t) is smooth, and
    x(t + delta) is predictable from (x, vel) beyond linear extrapolation —
    a learnable task of the same shape as the MD original."""
    n = x0.shape[0]
    res_idx = np.arange(n) // ATOMS_PER_RES
    t = np.arange(T_FRAMES, dtype=np.float64)
    pos = np.broadcast_to(x0, (T_FRAMES, n, 3)).astype(np.float64).copy()
    for k in range(12):
        period = rng.uniform(60.0, 1200.0)
        amp = rng.uniform(0.4, 1.8)
        phase = rng.uniform(0, 2 * np.pi)
        # spatial mode: smooth along the chain (hinge-like for low k)
        spatial = np.sin((k + 1) * np.pi * res_idx / N_RES
                         + rng.uniform(0, 2 * np.pi))
        axis = rng.standard_normal(3)
        axis /= np.linalg.norm(axis)
        wave = amp * np.sin(2 * np.pi * t / period + phase)      # [T]
        pos += wave[:, None, None] * spatial[None, :, None] * axis[None, None, :]
    pos += 0.05 * rng.standard_normal(pos.shape)
    return pos.astype(np.float32)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="data/mdanalysis/protein")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    rng = np.random.default_rng(args.seed)
    x0 = folded_backbone(rng)
    positions = animate(x0, rng)
    charges = np.tile(CHARGES, N_RES) + rng.normal(
        0, 0.02, N_RES * ATOMS_PER_RES).astype(np.float32)

    os.makedirs(args.out, exist_ok=True)
    out = os.path.join(args.out, "adk_backbone.npz")
    np.savez_compressed(out, positions=positions,
                        charges=charges.astype(np.float32), dimensions=BOX)
    step = np.linalg.norm(np.diff(positions[:50], axis=0), axis=-1).mean()
    print(f"wrote {out}: positions {positions.shape}, charges "
          f"{charges.shape}, |frame step| ~{step:.3f} A")


if __name__ == "__main__":
    main()
