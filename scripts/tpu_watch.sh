#!/usr/bin/env bash
# Watch for a live TPU-tunnel window and fire the measurement queue.
#
# The axon tunnel wedges for hours and recovers without notice (BASELINE.md).
# Probing is safe: a never-acquired client can be timeout-killed without
# stranding the remote claim (scripts/hw_session.sh header). So: probe every
# PERIOD seconds; when a probe succeeds, run hw_session.sh. hw_session is
# itself probe-gated per item and exits 3 if the tunnel dies mid-queue, in
# which case keep watching and re-fire on the next window. Exit 0 only when
# the full queue drains.
#
# Usage: nohup bash scripts/tpu_watch.sh >/tmp/tpu_watch.log 2>&1 &

set -u
cd "$(dirname "$0")/.."
PERIOD=${PERIOD:-120}
QUEUE_LOG=${QUEUE_LOG:-/tmp/hw_session.log}
MAX_FIRES=${MAX_FIRES:-6}
FIRES=0

# Single instance only (a second forgotten watcher would fire overlapping
# queues; hw_session has its own lock too, but don't even race the probes).
exec 9>/tmp/tpu_watch.lock
flock -n 9 || { echo "another tpu_watch is running; exiting"; exit 1; }

# Single-shot probe (the watcher loop itself provides the retry spacing).
probe() {
  ATTEMPTS=1 bash scripts/tpu_probe.sh /dev/null
}

while :; do
  # If a queue is already running (e.g. started by hand), don't even probe:
  # a probe client contends with the live measurement session for the host
  # core and for device acquire. flock test-and-release, no holding.
  if ! flock -n /tmp/hw_session.lock true 2>/dev/null; then
    echo "$(date -u +%FT%TZ) queue busy (hw_session.lock held)"
    sleep "$PERIOD"
    continue
  fi
  if probe; then
    echo "$(date -u +%FT%TZ) tunnel up — firing hw_session"
    # Let the probe client's claim release before the queue's first item
    # probes (>25 s release observed; same convention as hw_session run()).
    sleep 30
    # 9>&- : don't leak the watcher's lock fd into the queue and its
    # long-lived children — a dead watcher could then never be replaced
    # while the inherited fd held the lock.
    bash scripts/hw_session.sh "$QUEUE_LOG" 9>&-
    rc=$?
    FIRES=$((FIRES + 1))
    echo "$(date -u +%FT%TZ) hw_session rc=$rc (fire $FIRES/$MAX_FIRES)"
    [ "$rc" -eq 0 ] && exit 0
    # rc=3: tunnel died mid-queue — keep watching for the next window.
    # rc=5: some item failed without a marker; could be flake (re-fire will
    # skip completed items) or a deterministic bug — the fire cap below
    # bounds the burn in the latter case.
    if [ "$FIRES" -ge "$MAX_FIRES" ]; then
      echo "$(date -u +%FT%TZ) fire cap reached; giving up (inspect $QUEUE_LOG)"
      exit 6
    fi
  else
    echo "$(date -u +%FT%TZ) tunnel down"
  fi
  sleep "$PERIOD"
done
