#!/usr/bin/env bash
# Watch for a live TPU-tunnel window and fire the measurement queue.
#
# The axon tunnel wedges for hours and recovers without notice (BASELINE.md).
# Probing is safe: a never-acquired client can be timeout-killed without
# stranding the remote claim (scripts/hw_session.sh header). So: probe every
# PERIOD seconds; when a probe succeeds, run hw_session.sh. hw_session is
# itself probe-gated per item and exits 3 if the tunnel dies mid-queue, in
# which case keep watching and re-fire on the next window. Exit 0 only when
# the full queue drains.
#
# Usage: nohup bash scripts/tpu_watch.sh >/tmp/tpu_watch.log 2>&1 &

set -u
cd "$(dirname "$0")/.."
PERIOD=${PERIOD:-120}
QUEUE_LOG=${QUEUE_LOG:-/tmp/hw_session.log}
MAX_FIRES=${MAX_FIRES:-6}
FIRES=0
# Mid-queue tunnel deaths (rc=3) re-fire without counting toward MAX_FIRES;
# this separate generous cap only bounds a runaway flap loop.
MAX_TUNNEL_DEATHS=${MAX_TUNNEL_DEATHS:-50}
TUNNEL_DEATHS=0
MARKERS_SEEN=$(ls /tmp/hw_done 2>/dev/null | wc -l)

# Single instance only (a second forgotten watcher would fire overlapping
# queues; hw_session has its own lock too, but don't even race the probes).
exec 9>/tmp/tpu_watch.lock
flock -n 9 || { echo "another tpu_watch is running; exiting"; exit 1; }

# Recover competitors a SIGKILLed bench left SIGSTOPped (shared helper;
# ADVICE r3, medium). Same hw_session.lock-free guard as the in-loop call:
# an orphaned-but-live queue may still be measuring, and CONTing heavy CPU
# work beside it is the ~4x contention the pause exists to prevent.
. scripts/lib_resume_paused.sh  # script already cd'd to repo root
if flock -n /tmp/hw_session.lock true 2>/dev/null; then
  resume_orphaned_paused
fi

# Single-shot probe (the watcher loop itself provides the retry spacing).
# 9>&- : like every long-lived child here, the probe must not inherit the
# lock fd (a killed watcher's orphaned probe would hold the lock ~90 s).
probe() {
  ATTEMPTS=1 bash scripts/tpu_probe.sh /dev/null 9>&-
}

while :; do
  # If a queue is already running (e.g. started by hand), don't even probe:
  # a probe client contends with the live measurement session for the host
  # core and for device acquire. flock test-and-release, no holding.
  if ! flock -n /tmp/hw_session.lock true 2>/dev/null; then
    echo "$(date -u +%FT%TZ) queue busy (hw_session.lock held)"
    sleep "$PERIOD" 9>&-
    continue
  fi
  # lock is free -> no queue (and no queue-managed bench) is running; safe
  # to recover any competitors a killed direct-invoked bench left frozen
  resume_orphaned_paused
  if probe; then
    echo "$(date -u +%FT%TZ) tunnel up — firing hw_session"
    # Let the probe client's claim release before the queue's first item
    # probes (>25 s release observed; same convention as hw_session run()).
    sleep 30 9>&-
    # 9>&- : don't leak the watcher's lock fd into the queue and its
    # long-lived children — a dead watcher could then never be replaced
    # while the inherited fd held the lock.
    bash scripts/hw_session.sh "$QUEUE_LOG" 9>&-
    rc=$?
    # rc=3: the tunnel died mid-queue (or a live client was present) — a
    # genuine hardware event, NOT a bug in the queue. It does not count
    # toward MAX_FIRES: round-2 observed the tunnel flapping (up ~30 s then
    # dead), and counting flaps would exhaust the cap and leave the rest of
    # the round unwatched. TUNNEL_DEATHS has its own generous cap purely as
    # a runaway bound.
    # rc=5: some item failed without a marker; could be flake (re-fire will
    # skip completed items) or a deterministic bug — the fire cap bounds
    # the burn in the latter case.
    if [ "$rc" -eq 3 ] || [ "$rc" -eq 9 ]; then
      # Progress resets the cap: in a sustained-flap regime each short
      # window can still drain queue items (done-markers accrue), and a
      # watcher that is making headway must not give up.
      MARKERS=$(ls /tmp/hw_done 2>/dev/null | wc -l)
      if [ "$MARKERS" -gt "$MARKERS_SEEN" ]; then
        MARKERS_SEEN=$MARKERS
        TUNNEL_DEATHS=0
      fi
      TUNNEL_DEATHS=$((TUNNEL_DEATHS + 1))
      echo "$(date -u +%FT%TZ) hw_session rc=$rc (tunnel death/client $TUNNEL_DEATHS/$MAX_TUNNEL_DEATHS)"
      if [ "$TUNNEL_DEATHS" -ge "$MAX_TUNNEL_DEATHS" ]; then
        echo "$(date -u +%FT%TZ) tunnel-death cap reached; giving up (inspect $QUEUE_LOG)"
        exit 7
      fi
      if [ "$rc" -eq 9 ]; then
        # a live client is measuring: back off long — probing beside it
        # every PERIOD is contention, and manual sessions run for a while
        sleep 900 9>&-
      else
        sleep "$PERIOD" 9>&-
      fi
      continue
    fi
    FIRES=$((FIRES + 1))
    echo "$(date -u +%FT%TZ) hw_session rc=$rc (fire $FIRES/$MAX_FIRES)"
    [ "$rc" -eq 0 ] && exit 0
    if [ "$FIRES" -ge "$MAX_FIRES" ]; then
      echo "$(date -u +%FT%TZ) fire cap reached; giving up (inspect $QUEUE_LOG)"
      exit 6
    fi
  else
    echo "$(date -u +%FT%TZ) tunnel down"
  fi
  # 9>&- : a sleep must not inherit the lock fd — a killed watcher would
  # otherwise leave its orphaned sleep holding the lock for up to PERIOD,
  # blocking the replacement watcher.
  sleep "$PERIOD" 9>&-
done
