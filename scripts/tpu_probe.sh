#!/usr/bin/env bash
# Shared TPU-tunnel liveness probe (single source of the probe contract).
#
# Safety property: the probe client either completes a real round-trip
# (matmul + host fetch) or never acquires the device — and a never-acquired
# client is safe to timeout-kill without stranding the remote claim
# (BASELINE.md; killing a LIVE client wedges the tunnel for everyone).
#
# The tunnel releases a client's claim slowly: a probe fired immediately
# after another client exits can hang even when the tunnel is healthy
# (observed twice 2026-07-30). So retry ATTEMPTS times with SPACING seconds
# between attempts before declaring the tunnel down.
#
# Usage: bash scripts/tpu_probe.sh [logfile]     exit 0 = up, 1 = down
#        ATTEMPTS=1 bash scripts/tpu_probe.sh    single-shot (watcher mode)

set -u
LOG=${1:-/dev/null}
ATTEMPTS=${ATTEMPTS:-3}
SPACING=${SPACING:-150}

try() {
  timeout 90 python -c "
import jax, jax.numpy as jnp
print('probe ok', float((jnp.ones((256,256))@jnp.ones((256,256))).sum()))" \
    >>"$LOG" 2>&1
}

for attempt in $(seq 1 "$ATTEMPTS"); do
  try && exit 0
  echo "probe attempt $attempt failed" >>"$LOG"
  [ "$attempt" -lt "$ATTEMPTS" ] && sleep "$SPACING"
done
exit 1
