#!/usr/bin/env bash
# Shared TPU-tunnel liveness probe (single source of the probe contract).
#
# Safety property: the probe client either completes a real round-trip
# (matmul + host fetch) or never acquires the device — and a never-acquired
# client is safe to timeout-kill without stranding the remote claim
# (BASELINE.md; killing a LIVE client wedges the tunnel for everyone).
#
# The tunnel releases a client's claim slowly: a probe fired immediately
# after another client exits can hang even when the tunnel is healthy
# (observed twice 2026-07-30). So retry ATTEMPTS times with SPACING seconds
# between attempts before declaring the tunnel down.
#
# Usage: bash scripts/tpu_probe.sh [logfile]     exit 0 = up, 1 = down
#        ATTEMPTS=1 bash scripts/tpu_probe.sh    single-shot (watcher mode)
#
# On success, writes the probed backend platform (tpu/cpu/...) to
# /tmp/tpu_probe.platform so callers can attest WHAT they probed (a matmul
# succeeding proves liveness, not platform — on a host where jax silently
# falls back to CPU a platform-blind probe would let the queue stamp CPU
# numbers as hardware evidence; code-review r4).

set -u
LOG=${1:-/dev/null}
ATTEMPTS=${ATTEMPTS:-3}
SPACING=${SPACING:-150}
PLATFORM_FILE=${PLATFORM_FILE:-/tmp/tpu_probe.platform}

try() {
  timeout 90 python -c "
import jax, jax.numpy as jnp
plat = jax.devices()[0].platform
print('probe ok', plat, float((jnp.ones((256,256))@jnp.ones((256,256))).sum()))
open('$PLATFORM_FILE.tmp', 'w').write(plat)
import os; os.replace('$PLATFORM_FILE.tmp', '$PLATFORM_FILE')" \
    >>"$LOG" 2>&1
}

for attempt in $(seq 1 "$ATTEMPTS"); do
  try && exit 0
  echo "probe attempt $attempt failed" >>"$LOG"
  [ "$attempt" -lt "$ATTEMPTS" ] && sleep "$SPACING"
done
exit 1
