#!/usr/bin/env bash
# Capture committed-able artifacts from the round-4 CPU evidence runs:
#   - 1000-sample nbody convergence (configs/nbody_cpu_1000.yaml)
#   - bounded protein run + test_rot/test_trans equivariance triple
# Idempotent; safe to run at any time (snapshots whatever exists now).
# Heavy extras (rollout eval) are opt-in flags so a snapshot stays cheap.
#
# Usage: bash scripts/capture_cpu_runs.sh [--rollout]

set -u
cd "$(dirname "$0")/.."
mkdir -p docs/artifacts

snap() {  # snap <glob> <dest>
  local src
  src=$(ls -t $1 2>/dev/null | head -1)
  [ -n "$src" ] || { echo "skip: no match for $1"; return 0; }
  cp "$src" "$2.tmp" && mv "$2.tmp" "$2" && echo "captured $2 (from $src)"
}

snap "logs/nbody_cpu_1000/*/log/log.json" docs/artifacts/nbody1000_cpu_log.json
snap "logs/protein_cpu_slice/*/log/log.json" docs/artifacts/protein_cpu_slice_log.json
snap "logs/nbody_cpu_slice/*/log/log.json" docs/artifacts/nbody100_cpu_slice_log.json
snap "logs/water3d_cpu_slice/*/log/log.json" docs/artifacts/water3d_cpu_slice_log.json

# protein equivariance triple (cheap: 3 x 12 eval batches; pkl cache hits
# after the first run)
CKPT=$(ls -t logs/protein_cpu_slice/*/state_dict/best_model.ckpt 2>/dev/null | head -1)
if [ -n "$CKPT" ]; then
  env PYTHONPATH=/root/repo JAX_PLATFORMS=cpu python \
    scripts/evaluate_protein_equivariance.py \
    --config_path configs/protein_cpu_slice.yaml --checkpoint "$CKPT" \
    --json docs/artifacts/protein_equivariance_triple.json \
    && echo "captured protein_equivariance_triple.json"
fi

if [ "${1:-}" = "--rollout" ]; then
  CKPT=$(ls -t logs/nbody_cpu_1000/*/state_dict/best_model.ckpt 2>/dev/null | head -1)
  if [ -n "$CKPT" ]; then
    env PYTHONPATH=/root/repo JAX_PLATFORMS=cpu python \
      scripts/evaluate_rollout.py --config_path configs/nbody_cpu_1000.yaml \
      --checkpoint "$CKPT" --samples 200 \
      > docs/artifacts/nbody1000_cpu_rollout_mse.json.tmp \
      && mv docs/artifacts/nbody1000_cpu_rollout_mse.json.tmp \
            docs/artifacts/nbody1000_cpu_rollout_mse.json \
      && echo "captured nbody1000_cpu_rollout_mse.json"
  fi
fi
