"""Worker for the two-process multi-host test (run via subprocess by
tests/test_multihost.py, or imported for its problem builder).

Reproduces the reference's multi-process execution model (one process per
device group, reference main.py:159-163 NCCL init) the JAX way:
`jax.distributed.initialize(coordinator, num_processes, process_id)` on a CPU
backend with 4 local virtual devices per process -> 8 global devices, then the
SAME run_distributed machinery (global mesh, global_batch_putter, shard_map
step) as single-process. Deterministic by construction, so the parent can
compare its single-process result bit-for-bit-ish (rtol 1e-6).
"""

from __future__ import annotations

import sys

import numpy as np

DP, NPART = 2, 4  # 2 data shards x 4 graph partitions = 8 devices
STEPS = 2


def build_problem():
    """[D, P, B=1, ...] batch for a deterministic 2-graph 4-partition task."""
    import jax

    from distegnn_tpu.data import build_nbody_graph
    from distegnn_tpu.data.partition import split_graph
    from distegnn_tpu.ops.graph import pad_graphs

    rng = np.random.default_rng(11)
    per_d = []
    for d in range(DP):
        n = 24
        loc = rng.normal(size=(n, 3))
        vel = rng.normal(size=(n, 3))
        charges = rng.choice([1.0, -1.0], size=(n, 1))
        g = build_nbody_graph(loc, vel, charges, loc + 0.1 * vel, radius=-1.0)
        per_d.append(split_graph(g, NPART, "random", inner_radius=2.5, seed=5))
    n_max = max(p["loc"].shape[0] for parts in per_d for p in parts)
    e_max = max(p["edge_index"].shape[1] for parts in per_d for p in parts)
    stacks = []
    for parts in per_d:
        pbs = [pad_graphs([p], max_nodes=n_max + 2, max_edges=e_max + 8) for p in parts]
        stacks.append(jax.tree.map(lambda *xs: np.stack(xs, axis=0), *pbs))
    return jax.tree.map(lambda *xs: np.stack(xs, axis=0), *stacks)


def run(corrupt: bool = False):
    """Build the global mesh over ALL devices (local or cross-process), run
    STEPS train steps + one eval. Returns (train_loss, eval_loss,
    consistency_residual) floats — identical on every process because state
    is replicated.

    ``corrupt=True`` injects the failure the in-step consistency check exists
    to catch (VERDICT r2 weak #6): process 1 perturbs loc_mean of partition 0
    in ITS host copy before the global put — a host-data drift invisible to
    everything except the cross-rank check."""
    import jax

    from distegnn_tpu.models.fast_egnn import FastEGNN
    from distegnn_tpu.parallel.launch import global_batch_putter, make_distributed_steps
    from distegnn_tpu.parallel.mesh import GRAPH_AXIS, make_mesh
    from distegnn_tpu.train import TrainState, make_optimizer

    batch = build_problem()
    if corrupt and jax.process_index() == 1:
        lm = np.array(batch.loc_mean)
        lm[:, 0] += 0.25  # partition 0 only: within-axis divergence
        batch = batch.replace(loc_mean=lm)
    mesh = make_mesh(n_graph=NPART, n_data=DP, devices=jax.devices())
    model = FastEGNN(node_feat_nf=2, edge_attr_nf=2, hidden_nf=16,
                     virtual_channels=3, n_layers=2, axis_name=GRAPH_AXIS)
    params = model.copy(axis_name=None).init(
        jax.random.PRNGKey(0), jax.tree.map(lambda x: x[0, 0], batch))
    tx = make_optimizer(1e-3)
    state = TrainState.create(params, tx)
    train_step, eval_step = make_distributed_steps(
        model, tx, mesh, mmd_weight=0.03, mmd_sigma=1.5, mmd_samples=2)

    gb = global_batch_putter(mesh)(batch)
    for i in range(STEPS):
        state, metrics = train_step(state, gb, jax.random.PRNGKey(3 + i))
    return (float(metrics["loss"]), float(eval_step(state.params, gb)),
            float(metrics["batch_consistency"]))


def main():
    import os

    port, pid = sys.argv[1], int(sys.argv[2])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

    import jax

    # CPU cross-process collectives need the gloo transport; without it (and
    # with any extra PJRT plugin on PYTHONPATH) initialize() can hang — the
    # parent test also strips the TPU plugin path from the env.
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                               num_processes=2, process_id=pid)
    assert len(jax.devices()) == 8, jax.devices()
    assert len(jax.local_devices()) == 4
    corrupt = len(sys.argv) > 3 and sys.argv[3] == "corrupt"
    loss, ev, cons = run(corrupt=corrupt)
    print(f"RESULT {pid} {loss:.10f} {ev:.10f} {cons:.10f}", flush=True)


if __name__ == "__main__":
    main()
