"""Tiled giant-scene serving (ops/tiling.py + serve/tiled.py + the engine /
queue / gateway dispatch): exact parity of the tiled forward against the
monolithic engine for plain AND fused edge impls, the byte-bounded session
prep cache, the BucketLadder rung boundary contract, and — slow lane — a
million-node scene served end-to-end over HTTP through ONE compiled tile
executable (CompileWatcher-certified, no recompile after warmup, no 413)."""

import json
import threading
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from distegnn_tpu.models.fast_egnn import FastEGNN
from distegnn_tpu.obs.metrics import MetricsRegistry
from distegnn_tpu.ops.graph import pad_graphs
from distegnn_tpu.ops.tiling import plan_tiles
from distegnn_tpu.serve import (BucketLadder, BucketOverflowError,
                                InferenceEngine, RequestQueue, ServeMetrics,
                                SessionPrepCache, TiledExecutor,
                                TiledOverflowError, synthetic_graph)
from distegnn_tpu.serve.prep import nbytes_of
from distegnn_tpu.serve.registry import ModelRegistry
from distegnn_tpu.serve.transport import Gateway

pytestmark = pytest.mark.serve


def _model(impl="plain", n_layers=2):
    return FastEGNN(node_feat_nf=1, edge_attr_nf=2, hidden_nf=16,
                    virtual_channels=2, n_layers=n_layers, edge_impl=impl)


def _norm_err(pred, ref):
    return float(np.abs(pred - ref).max() / np.abs(ref).max())


# ------------------------------------------------------------ tile planning

def test_plan_tiles_covers_every_node_and_edge_once():
    g = synthetic_graph(500, radius=0.2, seed=11)
    plan = plan_tiles(g["edge_index"], g["loc"], g["edge_attr"],
                      tile_nodes=128, halo_floor=16, edge_floor=256)
    assert plan.n_tiles >= 2
    # the tiles partition [0, n) in Morton order
    covered = sorted((s.start, s.stop) for s in plan.tiles)
    assert covered[0][0] == 0 and covered[-1][1] == 500
    assert all(a[1] == b[0] for a, b in zip(covered, covered[1:]))
    # perm/inv_perm are inverse bijections
    assert (plan.perm[plan.inv_perm] == np.arange(500)).all()
    # every edge lands in exactly one tile (receiver's tile)
    assert sum(s.edge_index.shape[1] for s in plan.tiles) \
        == g["edge_index"].shape[1]
    assert 0.0 < plan.halo_fraction < 1.0
    # the single-executable invariant: ONE padded shape serves every tile
    assert all(s.n_halo <= plan.halo_pad for s in plan.tiles)
    assert all(s.edge_index.shape[1] <= plan.edge_pad for s in plan.tiles)
    assert plan.padded_nodes == plan.tile_nodes + plan.halo_pad  # plain layout
    assert isinstance(plan.shape_key, tuple)


# ----------------------------------------------------- tiled forward parity

def test_tiled_parity_plain():
    """Tiled executor == monolithic forward (1e-5 scale-normalized), halo
    edges and virtual-node aggregation included — plain edge impl."""
    model = _model("plain")
    g = synthetic_graph(400, radius=0.2, seed=5)
    tight = pad_graphs([g], node_bucket=1, edge_bucket=1)
    params = model.init(jax.random.PRNGKey(0), tight)
    ref = np.asarray(model.apply(params, tight)[0])[0]

    eng = InferenceEngine(model, params)
    tx = TiledExecutor(eng, {"tile_nodes": 128, "halo_floor": 16,
                             "edge_floor": 256})
    out = tx.predict(dict(g))
    assert out["tiles"] >= 2          # actually exercised halo exchange
    assert _norm_err(out["prediction"], ref) <= 1e-5


def test_tiled_parity_fused():
    """Same parity through the halo-aware fused edge pipeline (blocked
    layout, split_remote) — the reuse-fused_edge_layer leg of the tentpole."""
    model = _model("fused")
    g = synthetic_graph(900, radius=0.2, seed=5)
    batch = pad_graphs([dict(g)], max_nodes=1536, edge_block=512,
                       edge_tile=512, split_remote=True, compute_pair=False)
    params = model.init(jax.random.PRNGKey(0), batch)
    ref = np.asarray(model.apply(params, batch)[0])[0, :900]

    eng = InferenceEngine(model, params,
                          layout_opts={"edge_block": 512,
                                       "split_remote": True})
    tx = TiledExecutor(eng, {"tile_nodes": 256, "halo_floor": 64,
                             "edge_floor": 512})
    out = tx.predict(dict(g))
    assert out["tiles"] >= 2
    assert _norm_err(out["prediction"], ref) <= 1e-5


def test_tiled_overflow_is_typed_413_material():
    model = _model("plain")
    g = synthetic_graph(50, seed=0)
    params = model.init(jax.random.PRNGKey(0),
                        pad_graphs([g], node_bucket=1, edge_bucket=1))
    tx = TiledExecutor(InferenceEngine(model, params),
                       {"max_nodes": 40, "tile_nodes": 16})
    with pytest.raises(TiledOverflowError, match="serve.tiled.max_nodes"):
        tx.predict(dict(g))
    # subclasses BucketOverflowError: the gateway's 413 mapping rides free
    assert issubclass(TiledOverflowError, BucketOverflowError)


# ------------------------------------------- bucket ladder boundaries (sat 2)

def test_rung_exact_powers_of_growth():
    """Exact powers of the growth factor must land ON their rung, not one
    above — the float-log fixup at serve/buckets.py:_rung."""
    lad = BucketLadder(node_floor=64, edge_floor=256, growth=2.0,
                       node_multiple=8, edge_multiple=128)
    for k in range(0, 8):
        size = 64 * 2 ** k
        b = lad.bucket_for(size, 256)
        assert b.n == size, f"exact power {size} -> rung {b.n}"
    # one past the power steps up exactly one rung
    b = lad.bucket_for(64 * 2 ** 3 + 1, 256)
    assert b.n == 64 * 2 ** 4


def test_rung_admits_sizes_equal_to_caps():
    lad = BucketLadder(max_nodes=65536, max_edges=1 << 20)
    b = lad.bucket_for(65536, 1 << 20)     # == cap on both axes: admitted
    assert b.n == 65536 and b.e == 1 << 20


def test_rung_overflow_message_names_tiled_fallback():
    lad = BucketLadder(max_nodes=65536, max_edges=1 << 20)
    with pytest.raises(BucketOverflowError) as ei:
        lad.bucket_for(65537, 256)
    msg = str(ei.value)
    assert "serve.max_nodes" in msg and "serve.tiled" in msg
    with pytest.raises(BucketOverflowError) as ei:
        lad.bucket_for(64, (1 << 20) + 1)
    assert "serve.max_edges" in str(ei.value)


# --------------------------------------- byte-bounded session cache (sat 1)

def test_session_cache_bytes_evicts_to_fit():
    """serve.session_cache_bytes: nbytes accounting, LRU evict-to-fit, and
    the serve/session_cache_bytes gauge."""
    metrics = ServeMetrics()
    cache = SessionPrepCache(capacity=64, ladder=BucketLadder(),
                             metrics=metrics, max_bytes=4096)
    plan_bytes = 1500  # three fit (4500 > 4096 -> evict oldest)

    def build():
        return np.zeros(plan_bytes, np.uint8)

    g = synthetic_graph(10, seed=0)
    for sid in ("a", "b", "c"):
        cache.prepare_tile(sid, g, build)
    assert len(cache) == 2                 # "a" evicted to fit "c"
    assert cache.bytes_used <= 4096
    _, hit_b = cache.prepare_tile("b", g, build)
    assert hit_b is True
    _, hit_a = cache.prepare_tile("a", g, build)   # must rebuild
    assert hit_a is False
    snap = metrics.registry.gauge("serve/session_cache_bytes").value
    assert snap == cache.bytes_used > 0
    assert metrics.registry.counter("serve/session_evictions").value >= 2

    # same-session replacement is NOT an eviction and never over-counts
    ev_before = metrics.registry.counter("serve/session_evictions").value
    g2 = synthetic_graph(12, seed=1)       # new topology -> rebuild in place
    cache.prepare_tile("a", g2, build)
    assert metrics.registry.counter("serve/session_evictions").value \
        == ev_before


def test_nbytes_of_walks_nested_plans():
    arr = np.zeros((10, 3), np.float32)
    assert nbytes_of(arr) == 120
    assert nbytes_of({"a": arr, "b": [arr, arr]}) == 360
    assert nbytes_of(("fp", arr)) == 120   # non-arrays cost nothing
    assert nbytes_of(None) == 0


def test_prepare_tile_fingerprint_invalidation():
    cache = SessionPrepCache(capacity=8, ladder=BucketLadder())
    g = synthetic_graph(20, seed=3)
    calls = []

    def build():
        calls.append(1)
        return {"plan": np.ones(4)}

    p1, hit1 = cache.prepare_tile("s", g, build)
    p2, hit2 = cache.prepare_tile("s", g, build)
    assert (hit1, hit2) == (False, True) and len(calls) == 1
    g2 = dict(g)
    g2["edge_index"] = g["edge_index"][:, :-2]   # topology changed
    _, hit3 = cache.prepare_tile("s", g2, build)
    assert hit3 is False and len(calls) == 2


# --------------------------------------------------- gateway dispatch (e2e)

def _post(url, payload, timeout=180.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _payload(g, **extra):
    p = {"positions": g["loc"].tolist(), "velocities": g["vel"].tolist(),
         "node_feat": g["node_feat"].tolist(),
         "edge_index": g["edge_index"].tolist(),
         "edge_attr": g["edge_attr"].tolist()}
    p.update(extra)
    return p


@pytest.fixture()
def tiled_gateway():
    """Small ladder (cap 64) + tiled executor: a 300-node scene is above the
    cap and must dispatch to the tiled path instead of 413."""
    model = _model("plain")
    g = synthetic_graph(300, radius=0.2, seed=7)
    tight = pad_graphs([g], node_bucket=1, edge_bucket=1)
    params = model.init(jax.random.PRNGKey(0), tight)
    ref = np.asarray(model.apply(params, tight)[0])[0]
    metrics = ServeMetrics()
    eng = InferenceEngine(model, params, max_batch=2, metrics=metrics,
                          ladder=BucketLadder(max_nodes=64, max_edges=4096),
                          session_cache=4, session_cache_bytes=1 << 22,
                          tiled={"tile_nodes": 96, "halo_floor": 16,
                                 "edge_floor": 256})
    q = RequestQueue(eng, request_timeout_ms=120_000.0, metrics=metrics)
    reg = ModelRegistry.single("nbody", eng, q, feat_nf=1, edge_attr_nf=2)
    reg.start()
    gw = Gateway(reg, port=0, metrics_registry=MetricsRegistry())
    t = threading.Thread(target=gw.serve_forever, daemon=True)
    t.start()
    yield gw, g, ref, eng
    gw.drain()
    t.join(timeout=30.0)
    gw.close()


def test_gateway_dispatches_above_cap_to_tiled(tiled_gateway):
    gw, g, ref, eng = tiled_gateway
    status, body = _post(gw.url("/v1/models/nbody/predict"),
                         _payload(g, session_id="sc"))
    resp = json.loads(body)
    assert status == 200, body[:400]
    pred = np.asarray(resp["prediction"], np.float32)
    assert _norm_err(pred, ref) <= 1e-5
    assert resp["tiled"]["tiles"] >= 2
    assert 0.0 < resp["tiled"]["halo_fraction"] < 1.0
    assert resp["session"]["hit"] is False
    # repeat: the session cache serves the tile plan back
    status, body = _post(gw.url("/v1/models/nbody/predict"),
                         _payload(g, session_id="sc"))
    assert json.loads(body)["session"]["hit"] is True


def test_gateway_streams_per_tile_progress(tiled_gateway):
    gw, g, ref, eng = tiled_gateway
    status, body = _post(gw.url("/v1/models/nbody/predict?stream=1"),
                         _payload(g))
    assert status == 200, body[:400]
    lines = [json.loads(ln) for ln in body.strip().split("\n")]
    done = lines[-1]
    assert done["done"] is True and done["cancelled"] is False
    pred = np.asarray(done["prediction"], np.float32)
    assert _norm_err(pred, ref) <= 1e-5
    progress = [ln for ln in lines[:-1] if "tile" in ln]
    assert len(progress) == done["tiled"]["tiles"] * done["tiled"]["layers"]


def test_gateway_tiled_bound_is_413(tiled_gateway):
    gw, g, ref, eng = tiled_gateway
    eng.tiled.max_nodes = 200           # below the 300-node scene
    try:
        status, body = _post(gw.url("/v1/models/nbody/predict"), _payload(g))
    finally:
        eng.tiled.max_nodes = 4_194_304
    resp = json.loads(body)
    assert status == 413 and resp["type"] == "BucketOverflow"
    assert "serve.tiled.max_nodes" in resp["error"]


# ------------------------------------------------- million-node slow lane

def _lattice_scene(side):
    """side^3-node lattice with +/-x neighbor edges: million-node scale
    without an O(N log N) radius build. Locality-friendly by construction,
    so the Morton plan keeps halos small."""
    n = side ** 3
    idx = np.arange(n, dtype=np.int64)
    x, y, z = idx // (side * side), (idx // side) % side, idx % side
    loc = np.stack([x, y, z], axis=1).astype(np.float32)
    loc += np.random.default_rng(0).uniform(-0.1, 0.1, loc.shape
                                            ).astype(np.float32)
    has_right = x < side - 1
    src = idx[has_right]
    dst = src + side * side
    ei = np.concatenate([np.stack([src, dst]), np.stack([dst, src])],
                        axis=1).astype(np.int32)
    d = np.linalg.norm(loc[ei[0]] - loc[ei[1]], axis=1)[:, None]
    vel = np.zeros_like(loc)
    vel[:, 0] = 0.01
    return {"node_feat": np.ones((n, 1), np.float32), "loc": loc,
            "vel": vel, "edge_index": ei,
            "edge_attr": np.repeat(d, 2, axis=1).astype(np.float32)}


@pytest.mark.slow
def test_million_node_scene_serves_with_one_executable(tmp_path):
    """The acceptance gate: >= 1M nodes through POST /v1/models/<name>/
    predict on CPU with exactly ONE tile-layer executable compiled (no
    recompile after warmup — CompileWatcher-certified) and no 413."""
    import base64

    from distegnn_tpu.obs import jaxprobe

    side = 100                          # 1_000_000 nodes, ~1.98M edges
    g = _lattice_scene(side)
    assert g["loc"].shape[0] == 1_000_000

    model = _model("plain")
    tiny = synthetic_graph(20, seed=0)
    params = model.init(jax.random.PRNGKey(0),
                        pad_graphs([tiny], node_bucket=1, edge_bucket=1))
    metrics = ServeMetrics()
    eng = InferenceEngine(
        model, params, metrics=metrics,
        session_cache=4, session_cache_bytes=1 << 30,
        tiled={"tile_nodes": 262_144, "timeout_factor": 16.0})
    q = RequestQueue(eng, request_timeout_ms=600_000.0, metrics=metrics)
    reg = ModelRegistry.single("nbody", eng, q, feat_nf=1, edge_attr_nf=2)
    reg.start()
    gw = Gateway(reg, port=0, metrics_registry=MetricsRegistry())
    t = threading.Thread(target=gw.serve_forever, daemon=True)
    t.start()

    watcher = jaxprobe.install_compile_watcher()
    try:
        # warmup: one tiled pass in the serve_warmup phase compiles the
        # tile-rung executables
        jaxprobe.set_phase("serve_warmup")
        warm = eng.predict_tiled(dict(g))
        assert warm["tiles"] >= 2
        layer_keys = [k for k in eng._cache if k[0] == "tile_layer"]
        assert len(layer_keys) == 1     # ONE executable for all tiles/layers
        watcher.mark_warmup_done()

        def f32(a):
            a = np.ascontiguousarray(a, dtype="<f4")
            return {"b64": base64.b64encode(a.tobytes()).decode(),
                    "shape": list(a.shape)}

        ei = np.ascontiguousarray(g["edge_index"], dtype="<i4")
        payload = {"positions": f32(g["loc"]), "velocities": f32(g["vel"]),
                   "node_feat": f32(g["node_feat"]),
                   "edge_attr": f32(g["edge_attr"]),
                   "edge_index": {"b64":
                                  base64.b64encode(ei.tobytes()).decode(),
                                  "shape": list(ei.shape)},
                   "encoding": "b64", "session_id": "giant"}
        status, body = _post(gw.url("/v1/models/nbody/predict"), payload,
                             timeout=3600.0)
        resp = json.loads(body)
        assert status == 200, body[:400]              # served — not a 413
        shape = resp["prediction"]["shape"]
        assert shape == [1_000_000, 3]
        raw = base64.b64decode(resp["prediction"]["b64"])
        pred = np.frombuffer(raw, "<f4").reshape(shape)
        assert np.isfinite(pred).all()
        assert resp["tiled"]["tiles"] == warm["tiles"]
        # the warmed executables served the giant request: zero new compiles
        assert watcher.snapshot()["compiles_after_warmup"] == 0
        assert [k for k in eng._cache if k[0] == "tile_layer"] == layer_keys
    finally:
        jaxprobe.deactivate_compile_watcher()
        gw.drain()
        t.join(timeout=60.0)
        gw.close()
