"""SLO-driven elasticity (serve/autoscale.py + the streaming/priority
transport): the autoscaler's full decision table on synthetic clocks,
live add/retire of real replicas (at-most-once preserved), chunked
streaming rollouts over a real socket (parity, early first chunk,
disconnect-cancels-compute), priority admission (bulk capped + deferred
while the window is degraded), the SLO fill-counter reset regression, and
supervisor ticks over a dynamically-sized ReplicaSet — all CPU."""

import http.client
import json
import os
import queue as pyqueue
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import jax
import numpy as np
import pytest

from distegnn_tpu import obs
from distegnn_tpu.models.fast_egnn import FastEGNN
from distegnn_tpu.obs.metrics import MetricsRegistry
from distegnn_tpu.obs.slo import SLOMonitor
from distegnn_tpu.ops.graph import pad_graphs
from distegnn_tpu.serve import (InferenceEngine, RequestQueue, ServeMetrics,
                                synthetic_graph)
from distegnn_tpu.serve.autoscale import ReplicaAutoscaler
from distegnn_tpu.serve.queue import StreamSink
from distegnn_tpu.serve.registry import ModelRegistry
from distegnn_tpu.serve.replica import Replica, ReplicaSet
from distegnn_tpu.serve.transport import Gateway

pytestmark = pytest.mark.serve


# ---- synthetic fixtures for the decision table ------------------------------

class _FakeReplica:
    def __init__(self, idx):
        self.idx = idx
        self.state = "running"
        self.warmups = []

    def warmup(self, sizes):
        self.warmups.append(list(sizes))


class _FakeRSet:
    def __init__(self, n):
        self.replicas = [_FakeReplica(i) for i in range(n)]
        self.retired = []

    def available(self):
        return sum(r.state == "running" for r in self.replicas)

    def add_replica(self, build_fn, warm_sizes=None):
        r = build_fn(len(self.replicas))
        if warm_sizes:
            r.warmup(warm_sizes)
        self.replicas.append(r)
        return r

    def retire_replica(self, drain_timeout_s=30.0):
        running = [r for r in self.replicas if r.state == "running"]
        if len(running) <= 1 or running[-1] is self.replicas[0]:
            return None
        victim = running[-1]
        self.replicas.remove(victim)
        self.retired.append(victim)
        return victim


class _FakeEntry:
    def __init__(self, n=1, depth=0, warmed=()):
        self.replicas = _FakeRSet(n)
        self.queue = SimpleNamespace(depth=lambda: depth)
        self.warmed = [SimpleNamespace(n=w, e=8 * w) for w in warmed]
        self.replica_factory = _FakeReplica

    def add_replica(self, warm_sizes=None):
        # mirrors ModelEntry.add_replica's surface (the swap-lock re-pin
        # has no fake equivalent: there is no engine to version)
        if self.replica_factory is None:
            raise RuntimeError("no replica factory")
        return self.replicas.add_replica(self.replica_factory,
                                         warm_sizes=warm_sizes)

    def set_depth(self, depth):
        self.queue = SimpleNamespace(depth=lambda: depth)


class _FakeRegistry:
    def __init__(self, **entries):
        self.entries = entries

    def items(self):
        return self.entries.items()


class _FakeMonitor:
    def __init__(self, **snap):
        self.snap = snap

    def window_snapshot(self, now=None):
        return dict(self.snap)


@pytest.fixture()
def scale_events(monkeypatch):
    """Record the autoscaler's obs events without a tracer round-trip."""
    from distegnn_tpu.serve import autoscale as mod

    events = []

    def record(name, **attrs):
        if name.startswith("gateway/scale_"):
            events.append(dict(attrs, name=name))

    monkeypatch.setattr(mod.obs, "event", record)
    return events


def _scaler(registry, monitor=None, **knobs):
    cfg = dict(enable=True, min_replicas=1, max_replicas=3, step=1,
               queue_high=4.0, queue_low=0.5, shed_high=0.01,
               scale_up_cooldown_s=2.0, scale_down_cooldown_s=5.0,
               idle_rounds=2)
    cfg.update(knobs)
    return ReplicaAutoscaler(registry, monitor, config=cfg,
                             metrics_registry=MetricsRegistry())


# ---- autoscaler decision table ----------------------------------------------

def test_scale_up_on_queue_depth_then_cooldown_then_max(scale_events):
    entry = _FakeEntry(n=1, depth=30, warmed=(20,))
    sc = _scaler(_FakeRegistry(m=entry))
    sc.tick(now=0.0)
    assert len(entry.replicas.replicas) == 2
    assert scale_events[-1]["name"] == "gateway/scale_up"
    assert scale_events[-1]["triggers"] == ["queue_depth"]
    assert (scale_events[-1]["from_replicas"],
            scale_events[-1]["to_replicas"]) == (1, 2)
    # the new replica was warmed at the entry's warmed rungs
    assert entry.replicas.replicas[-1].warmups == [[(20, 160)]]

    sc.tick(now=0.5)                      # inside the up-cooldown
    assert len(entry.replicas.replicas) == 2
    assert scale_events[-1]["name"] == "gateway/scale_blocked"
    assert (scale_events[-1]["direction"],
            scale_events[-1]["reason"]) == ("up", "cooldown")

    sc.tick(now=3.0)                      # cooldown elapsed: grow again
    assert len(entry.replicas.replicas) == 3
    sc.tick(now=6.0)                      # at max_replicas: blocked
    assert len(entry.replicas.replicas) == 3
    assert scale_events[-1]["reason"] == "max_replicas"
    # triggering gauge values ride every event
    assert scale_events[-1]["depth"] == 30
    assert "per_replica_depth" in scale_events[-1]


def test_scale_up_on_shed_rate_and_p99_triggers(scale_events):
    entry = _FakeEntry(n=1, depth=0)
    sc = _scaler(_FakeRegistry(m=entry),
                 _FakeMonitor(shed_rate=0.2, predict_p99_ms=900.0),
                 p99_high_ms=500.0)
    sc.tick(now=0.0)
    assert scale_events[-1]["name"] == "gateway/scale_up"
    assert scale_events[-1]["triggers"] == ["shed_rate", "p99"]
    assert scale_events[-1]["shed_rate"] == 0.2
    assert scale_events[-1]["predict_p99_ms"] == 900.0


def test_scale_down_after_idle_rounds_with_cooldown(scale_events):
    entry = _FakeEntry(n=3, depth=0)
    sc = _scaler(_FakeRegistry(m=entry), idle_rounds=2,
                 scale_down_cooldown_s=5.0)
    sc.tick(now=0.0)                      # calm 1: nothing yet
    assert len(entry.replicas.replicas) == 3 and not scale_events
    sc.tick(now=1.0)                      # calm 2: retire one
    assert len(entry.replicas.replicas) == 2
    assert scale_events[-1]["name"] == "gateway/scale_down"
    assert (scale_events[-1]["from_replicas"],
            scale_events[-1]["to_replicas"]) == (3, 2)
    sc.tick(now=2.0)                      # calm 1 again (reset on action)
    sc.tick(now=3.0)                      # calm 2 but inside down-cooldown
    assert len(entry.replicas.replicas) == 2
    assert scale_events[-1]["name"] == "gateway/scale_blocked"
    assert (scale_events[-1]["direction"],
            scale_events[-1]["reason"]) == ("down", "cooldown")
    sc.tick(now=7.0)                      # cooldown elapsed: down to min
    assert len(entry.replicas.replicas) == 1
    sc.tick(now=20.0)                     # at min_replicas: no event, no-op
    assert len(entry.replicas.replicas) == 1
    assert scale_events[-1]["name"] == "gateway/scale_down"


def test_busy_tick_resets_calm_streak(scale_events):
    entry = _FakeEntry(n=2, depth=0)
    sc = _scaler(_FakeRegistry(m=entry), idle_rounds=2)
    sc.tick(now=0.0)                      # calm 1
    entry.set_depth(2)                    # not calm (>= queue_low), no trigger
    sc.tick(now=1.0)
    entry.set_depth(0)
    sc.tick(now=2.0)                      # calm 1 again — streak restarted
    assert len(entry.replicas.replicas) == 2
    sc.tick(now=3.0)                      # calm 2: now it retires
    assert len(entry.replicas.replicas) == 1


def test_scale_up_blocked_without_factory_and_on_spawn_failure(scale_events):
    entry = _FakeEntry(n=1, depth=10)
    entry.replica_factory = None
    sc = _scaler(_FakeRegistry(m=entry))
    sc.tick(now=0.0)
    assert scale_events[-1]["reason"] == "no_factory"
    assert len(entry.replicas.replicas) == 1

    def boom(idx):
        raise RuntimeError("no capacity")

    entry.replica_factory = boom
    sc.tick(now=10.0)
    assert scale_events[-1]["reason"] == "spawn_failed"
    assert "no capacity" in scale_events[-1]["error"]
    assert len(entry.replicas.replicas) == 1


def test_disabled_autoscaler_start_is_noop():
    sc = ReplicaAutoscaler(_FakeRegistry(), config={"enable": False})
    assert sc.start()._thread is None
    sc.stop()                             # idempotent on a never-started loop


def test_status_reports_fleet_shape():
    entry = _FakeEntry(n=2, depth=0)
    sc = _scaler(_FakeRegistry(m=entry), max_replicas=4)
    sc.tick(now=0.0)
    st = sc.status()["m"]
    assert st["replicas"] == 2 and st["available"] == 2
    assert st["min"] == 1 and st["max"] == 4
    assert st["calm_rounds"] == 1


# ---- live fleet: real replicas ----------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    model = FastEGNN(node_feat_nf=1, edge_attr_nf=2, hidden_nf=16,
                     virtual_channels=2, n_layers=2)
    graph = synthetic_graph(24, seed=5)
    tight = pad_graphs([graph], node_bucket=1, edge_bucket=1)
    params = model.init(jax.random.PRNGKey(0), tight)
    x, _ = model.apply(params, tight)
    return SimpleNamespace(model=model, graph=graph, params=params,
                           ref=np.asarray(x[0]))


def _mk_rset(tiny, n, name="m", **q_kw):
    metrics = ServeMetrics()
    kw = dict(batch_deadline_ms=2.0, queue_capacity=32,
              request_timeout_ms=30_000.0, result_margin_s=30.0)
    kw.update(q_kw)
    pairs = []
    for _ in range(n):
        eng = InferenceEngine(tiny.model, tiny.params, max_batch=2,
                              metrics=metrics,
                              rollout_opts={"radius": 0.35, "max_degree": 64,
                                            "max_per_cell": 64})
        pairs.append((eng, RequestQueue(eng, metrics=metrics, **kw)))
    return ReplicaSet(name, pairs,
                      supervisor_opts=dict(heartbeat_s=3600.0))


def _factory(tiny, metrics):
    def build(idx):
        eng = InferenceEngine(tiny.model, tiny.params, max_batch=2,
                              metrics=metrics)
        return Replica(idx, eng, RequestQueue(
            eng, metrics=metrics, batch_deadline_ms=2.0,
            request_timeout_ms=30_000.0, result_margin_s=30.0))
    return build


def test_add_then_retire_replica_live(tiny):
    """A 1 -> 2 -> 1 fleet cycle under live traffic: the added replica
    serves identical numbers, retirement drains before removal, replica 0
    is never the victim, and indices never alias across the cycle."""
    rset = _mk_rset(tiny, 1).start()
    try:
        added = rset.add_replica(_factory(tiny, rset.metrics))
        assert added.idx == 1 and len(rset.replicas) == 2
        futs = [rset.submit(dict(tiny.graph)) for _ in range(6)]
        for f in futs:
            np.testing.assert_allclose(f.result(timeout=60.0), tiny.ref,
                                       atol=1e-4, rtol=0)
        assert {f.meta["replica"] for f in futs} == {0, 1}

        victim = rset.retire_replica(drain_timeout_s=10.0)
        assert victim is added and victim.state == "stopped"
        assert [r.idx for r in rset.replicas] == [0]
        assert rset.retire_replica() is None      # floor: last replica stays
        # the next grow gets a FRESH index — no gauge/health aliasing
        again = rset.add_replica(_factory(tiny, rset.metrics))
        assert again.idx == 2
        assert rset.submit(dict(tiny.graph)).result(timeout=60.0).shape \
            == (24, 3)
    finally:
        rset.stop()


def test_retire_waits_for_inflight_then_fails_over_stragglers(tiny):
    """Scale-down vs in-flight: a wedged victim's tracked request is NOT
    lost — after the bounded drain it fails over to the survivor exactly
    once (the same claim protocol as the supervisor's)."""
    rset = _mk_rset(tiny, 2).start()
    try:
        victim = rset.replicas[1]
        victim.queue.wedge(2.0)           # park the dispatcher mid-flight
        futs = [rset.submit(dict(tiny.graph)) for _ in range(2)]
        assert victim.inflight_count() >= 1
        out = rset.retire_replica(drain_timeout_s=0.2)
        assert out is victim
        for f in futs:
            np.testing.assert_allclose(f.result(timeout=60.0), tiny.ref,
                                       atol=1e-4, rtol=0)
        assert len(rset.replicas) == 1
    finally:
        rset.stop()


def test_supervisor_ticks_dynamic_membership(tiny):
    """Satellite: the supervisor's tick iterates the LIVE list — a replica
    added mid-breaker is supervised immediately with its own counters, the
    set can shrink while another member's breaker is open, and after
    begin_stop() no tick revives a dead queue."""
    rset = _mk_rset(tiny, 2).start()
    sup = rset.supervisor
    try:
        # break replica 1: three crash/restart cycles open its breaker
        bad = rset.replicas[1]
        t = 100.0
        while bad.state != "broken":
            bad.queue.kill(reason="chaos")
            sup.tick(now=t)               # crash noticed
            if bad.state == "broken":
                break
            assert bad.state == "backoff"
            sup.tick(now=t + 60.0)        # backoff elapsed: fresh queue
            assert bad.state == "running"
            t += 100.0
        assert bad.failures == sup.breaker_threshold

        # grow while the breaker is open: the newcomer is supervised from
        # the very next tick, with no registration step and NO index or
        # failure-count aliasing against the broken member
        added = rset.add_replica(_factory(tiny, rset.metrics))
        assert added.idx == 2
        added.queue.kill(reason="chaos")
        sup.tick(now=t + 1.0)
        assert added.state == "backoff" and added.failures == 1
        assert bad.state == "broken"      # untouched by the newcomer's crash

        # both restart (bad goes half-open after its cooldown)
        sup.tick(now=t + 61.0)
        assert added.state == "running" and bad.state == "running"

        # shrink while serving: retire never picks replica 0, membership
        # shrinks mid-supervision, and the next tick walks the new list
        victim = rset.retire_replica(drain_timeout_s=5.0)
        assert victim is added
        assert [r.idx for r in rset.replicas] == [0, 1]
        sup.tick(now=t + 62.0)            # no stale-index touch, no throw

        # begin_stop(): a replica downed with a due restart stays down —
        # drain must never revive a queue
        bad.queue.kill(reason="chaos")
        sup.tick(now=t + 63.0)
        assert bad.state in ("backoff", "broken")
        rset.begin_stop()
        sup.tick(now=t + 10_000.0)
        assert not bad.queue.alive()
        assert bad.state != "running"
    finally:
        rset.stop()


# ---- streaming over the ReplicaSet ------------------------------------------

def test_streamed_rollout_chunks_match_buffered(tiny):
    rset = _mk_rset(tiny, 1).start()
    try:
        scene = {"loc": tiny.graph["loc"], "vel": tiny.graph["vel"],
                 "steps": 5, "chunk_steps": 2}
        sink = StreamSink()
        fut = rset.submit_rollout(dict(scene), stream=sink)
        chunks, summary = [], None
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            try:
                kind, a, b = sink.next(timeout=0.5)
            except pyqueue.Empty:
                continue
            if kind == "chunk":
                chunks.append((a, b))
            elif kind == "done":
                summary = a
                break
            else:
                raise a
        assert summary is not None and not summary["cancelled"]
        assert [c[0] for c in chunks] == [0, 2, 4]
        assert [c[1].shape[0] for c in chunks] == [2, 2, 1]
        streamed = np.concatenate([c[1] for c in chunks], axis=0)
        buffered = rset.submit_rollout(
            {"loc": tiny.graph["loc"], "vel": tiny.graph["vel"],
             "steps": 5}).result(timeout=120.0)
        np.testing.assert_allclose(streamed, buffered, atol=1e-5, rtol=0)
        assert fut.result(timeout=10.0)["steps_done"] == 5
    finally:
        rset.stop()


def test_cancelled_stream_skips_remaining_chunks(tiny):
    rset = _mk_rset(tiny, 1).start()
    try:
        sink = StreamSink()
        fut = rset.submit_rollout(
            {"loc": tiny.graph["loc"], "vel": tiny.graph["vel"],
             "steps": 40, "chunk_steps": 2}, stream=sink)
        kind, start, traj = sink.next(timeout=120.0)
        assert kind == "chunk" and start == 0
        sink.cancel()                     # client went away after chunk 1
        summary = fut.result(timeout=120.0)
        assert summary["cancelled"] is True
        assert summary["steps_done"] < summary["steps_total"] == 40
    finally:
        rset.stop()


# ---- the HTTP surface: streaming + priority ---------------------------------

class _Live:
    def __init__(self, **gw_kw):
        self.tiny = None
        model = FastEGNN(node_feat_nf=1, edge_attr_nf=2, hidden_nf=16,
                         virtual_channels=2, n_layers=2)
        self.graph = synthetic_graph(24, seed=5)
        tight = pad_graphs([self.graph], node_bucket=1, edge_bucket=1)
        self.params = model.init(jax.random.PRNGKey(0), tight)
        metrics = ServeMetrics()
        self.engine = InferenceEngine(
            model, self.params, max_batch=2, metrics=metrics,
            rollout_opts={"radius": 0.35, "max_degree": 64,
                          "max_per_cell": 64})
        self.queue = RequestQueue(self.engine, batch_deadline_ms=5.0,
                                  request_timeout_ms=60_000.0,
                                  metrics=metrics)
        self.registry = ModelRegistry.single("nbody", self.engine, self.queue,
                                             feat_nf=1, edge_attr_nf=2)
        self.registry.start()
        self.registry.warmup([24])
        kw = dict(port=0, max_inflight=16,
                  metrics_registry=MetricsRegistry(), stream_chunk_steps=2)
        kw.update(gw_kw)
        self.gw = Gateway(self.registry, **kw)
        self.thread = threading.Thread(target=self.gw.serve_forever,
                                       daemon=True)
        self.thread.start()
        self.url = self.gw.url

    def close(self):
        self.gw.drain()
        self.thread.join(timeout=30.0)
        self.gw.close()


@pytest.fixture(scope="module")
def live():
    env = _Live()
    yield env
    env.close()


def _post(url, payload, headers=None, timeout=120.0):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(url, data=json.dumps(payload).encode(),
                                 headers=hdrs, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.load(r), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e), dict(e.headers)


def _stream_lines(url, payload, timeout=120.0):
    """POST and read the chunked NDJSON response incrementally, stamping
    each line's arrival time."""
    req = urllib.request.Request(url, data=json.dumps(payload).encode(),
                                 headers={"Content-Type": "application/json"},
                                 method="POST")
    lines = []
    with urllib.request.urlopen(req, timeout=timeout) as r:
        assert r.status == 200
        assert r.headers.get("Content-Type") == "application/x-ndjson"
        while True:
            raw = r.readline()
            if not raw:
                break
            lines.append((time.monotonic(), json.loads(raw)))
    return lines


def test_streamed_rollout_http_parity_and_early_first_chunk(live):
    """?stream=1: NDJSON chunk lines concatenate to the exact buffered
    trajectory, the first line carries only chunk_steps of the total (the
    server answered before finishing), and the summary line closes it."""
    payload = {"positions": live.graph["loc"].tolist(),
               "velocities": live.graph["vel"].tolist(), "steps": 5,
               "chunk_steps": 2}
    lines = _stream_lines(live.url("/v1/models/nbody/rollout?stream=1"),
                          payload)
    body = [ln for _, ln in lines]
    assert body[-1]["done"] is True and body[-1]["cancelled"] is False
    assert body[-1]["steps"] == body[-1]["steps_total"] == 5
    chunks = body[:-1]
    assert [c["start_step"] for c in chunks] == [0, 2, 4]
    assert chunks[0]["steps"] == 2 < 5    # partial answer arrived first
    streamed = np.concatenate(
        [np.asarray(c["chunk"], np.float32) for c in chunks], axis=0)

    status, resp, _ = _post(live.url("/v1/models/nbody/rollout"),
                            {k: v for k, v in payload.items()
                             if k != "chunk_steps"})
    assert status == 200
    np.testing.assert_allclose(streamed,
                               np.asarray(resp["trajectory"], np.float32),
                               atol=1e-5, rtol=0)


def test_non_streaming_rollout_unchanged_by_query_flag(live):
    """stream=0 (and no query) keep the buffered single-JSON contract."""
    payload = {"positions": live.graph["loc"].tolist(), "steps": 2}
    for path in ("/v1/models/nbody/rollout",
                 "/v1/models/nbody/rollout?stream=0"):
        status, resp, _ = _post(live.url(path), payload)
        assert status == 200 and "trajectory" in resp and "done" not in resp


def test_stream_disconnect_cancels_remaining_compute(live, tmp_path):
    """Mid-stream disconnect: the server notices at the next chunk write,
    cancels the rollout (serve/stream_cancelled with steps skipped), and
    the admission slot frees."""
    from distegnn_tpu.obs import report, trace

    trace.configure(log_dir=str(tmp_path))
    try:
        host, port = live.gw.address
        conn = http.client.HTTPConnection(host, port, timeout=60.0)
        body = json.dumps({"positions": live.graph["loc"].tolist(),
                           "steps": 60, "chunk_steps": 2})
        conn.request("POST", "/v1/models/nbody/rollout?stream=1", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        first = resp.readline()           # one chunk consumed...
        assert json.loads(first)["start_step"] == 0
        conn.sock.close()                 # ...then the client vanishes
        conn.close()

        cancelled = None
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and cancelled is None:
            trace.flush()
            events = report.load_events(str(tmp_path / "events.jsonl"))[0]
            for e in events:
                if e.get("name") == "serve/stream_cancelled":
                    cancelled = e
            time.sleep(0.1)
    finally:
        trace.configure(log_dir=None)
    assert cancelled is not None, "no serve/stream_cancelled event"
    assert cancelled["steps_total"] == 60
    assert cancelled["steps_skipped"] > 0
    assert cancelled["steps_done"] + cancelled["steps_skipped"] == 60
    # the slot freed: the gateway still serves
    with live.gw._inflight_lock:
        assert live.gw._inflight == 0
    status, resp, _ = _post(live.url("/v1/models/nbody/rollout"),
                            {"positions": live.graph["loc"].tolist(),
                             "steps": 2})
    assert status == 200


# ---- priority admission -----------------------------------------------------

def test_priority_classes_and_header_override(live):
    gw = live.gw
    assert gw._priority_of(SimpleNamespace(headers={}), "predict") \
        == "interactive"
    assert gw._priority_of(SimpleNamespace(headers={}), "rollout") == "bulk"
    h = SimpleNamespace(headers={"X-Priority": "interactive"})
    assert gw._priority_of(h, "rollout") == "interactive"
    h = SimpleNamespace(headers={"X-Priority": "Bulk"})
    assert gw._priority_of(h, "predict") == "bulk"
    h = SimpleNamespace(headers={"X-Priority": "nonsense"})
    assert gw._priority_of(h, "rollout") == "bulk"     # bad value: default


def test_bulk_capped_below_interactive(live):
    """The bulk share of max_inflight is bounded; interactive still admits
    when every bulk slot is taken."""
    gw = live.gw
    cap = gw.bulk_max_inflight
    assert cap < gw.max_inflight
    taken = 0
    try:
        for _ in range(cap):
            assert gw._try_acquire("bulk")
            taken += 1
        assert not gw._try_acquire("bulk")            # bulk share exhausted
        assert gw._try_acquire("interactive")         # interactive admits
        gw._release("interactive")
    finally:
        for _ in range(taken):
            gw._release("bulk")
    with gw._inflight_lock:
        assert gw._inflight == 0 and gw._inflight_bulk == 0


def test_degraded_window_defers_bulk_not_interactive():
    """When the SLO window degrades past the shed threshold, bulk rollouts
    get 429 BulkDeferred with a class-scaled Retry-After while interactive
    (header-promoted) requests keep flowing."""
    env = _Live(priority={"degrade_shed_rate": 0.05,
                          "bulk_retry_factor": 4.0})
    try:
        # poison the window: 10 sheds out of 10 inference requests
        for _ in range(10):
            env.gw.slo_monitor.observe_http("predict", 1.0, 429)
        env.gw._degraded_cache = (0.0, False)   # force a re-check
        payload = {"positions": env.graph["loc"].tolist(), "steps": 2}
        status, resp, hdrs = _post(env.url("/v1/models/nbody/rollout"),
                                   payload)
        assert status == 429 and resp["type"] == "BulkDeferred"
        assert float(hdrs["Retry-After"]) >= 4.0    # 1.0 * factor
        assert resp["priority"] == "bulk"
        # the same request promoted to interactive is served
        status, resp, _ = _post(env.url("/v1/models/nbody/rollout"),
                                payload,
                                headers={"X-Priority": "interactive"})
        assert status == 200 and "trajectory" in resp
        # a predict is never deferred by the degrade gate
        status, _, _ = _post(env.url("/v1/models/nbody/predict"),
                             {"positions": env.graph["loc"].tolist(),
                              "radius": 0.8})
        assert status == 200
    finally:
        env.close()


def test_priority_disabled_restores_flat_admission():
    env = _Live(priority={"enable": False, "degrade_shed_rate": 0.0})
    try:
        for _ in range(10):
            env.gw.slo_monitor.observe_http("predict", 1.0, 429)
        env.gw._degraded_cache = (0.0, False)
        status, resp, _ = _post(env.url("/v1/models/nbody/rollout"),
                                {"positions": env.graph["loc"].tolist(),
                                 "steps": 2})
        assert status == 200              # no bulk class, no deferral
    finally:
        env.close()


def test_readyz_reports_autoscale_state():
    env = _Live(autoscale={"enable": True, "interval_s": 3600.0,
                           "max_replicas": 2})
    try:
        with urllib.request.urlopen(env.url("/readyz"), timeout=30.0) as r:
            body = json.load(r)
        assert body["ready"] is True
        st = body["autoscale"]["nbody"]
        assert st["replicas"] == 1 and st["max"] == 2
    finally:
        env.close()


# ---- SLO window regressions -------------------------------------------------

def test_fill_window_survives_counter_reset():
    """Satellite: a replica restart resets the cumulative slot counters;
    the windowed fill gauge must re-baseline instead of going negative."""
    mon = SLOMonitor(window_s=60.0)
    reg = MetricsRegistry()

    class _Metrics:
        def __init__(self, filled, slots):
            self.batch_slots_filled = filled
            self.batch_slots_total = slots

    class _Entry:
        def __init__(self, filled, slots):
            self.queue = SimpleNamespace(depth=lambda: 0)
            self.engine = SimpleNamespace(metrics=_Metrics(filled, slots))

    class _Reg:
        def __init__(self, entry):
            self.entry = entry

        def items(self):
            return [("m", self.entry)]

    e = _Entry(80, 100)
    mon.export(reg, _Reg(e), now=0.0)
    e.engine.metrics = _Metrics(90, 120)
    mon.export(reg, _Reg(e), now=1.0)
    assert reg.gauge("slo/window_model_m_fill").value == pytest.approx(0.5)

    # restart: counters fall back toward zero — the old diff would be
    # negative; the gauge must re-baseline and stay sane
    e.engine.metrics = _Metrics(4, 8)
    mon.export(reg, _Reg(e), now=2.0)
    e.engine.metrics = _Metrics(10, 16)
    mon.export(reg, _Reg(e), now=3.0)
    v = reg.gauge("slo/window_model_m_fill").value
    assert 0.0 <= v <= 1.0
    assert v == pytest.approx(6.0 / 8.0)


def test_window_snapshot_speaks_the_slo_vocabulary():
    mon = SLOMonitor(window_s=60.0)
    for ms, status in ((10.0, 200), (20.0, 200), (30.0, 429), (40.0, 500)):
        mon.observe_http("predict", ms, status, now=1.0)
    mon.observe_http("rollout", 100.0, 200, now=1.0)
    snap = mon.window_snapshot(now=2.0)
    assert snap["window_requests"] == 5.0
    assert snap["predict_p50_ms"] == pytest.approx(10.0)  # nearest-rank
    assert snap["rollout_p99_ms"] == pytest.approx(100.0)
    assert snap["shed_rate"] == pytest.approx(0.2)
    assert snap["error_rate"] == pytest.approx(0.2)
    # everything ages out of the window
    assert mon.window_snapshot(now=120.0)["window_requests"] == 0.0


# ---- config-key lint --------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _key_lint():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from check_config_keys import find_violations
    finally:
        sys.path.pop(0)
    return find_violations


def test_config_key_lint_clean():
    """Tier-1 wiring of scripts/check_config_keys.py: every serve-layer
    control knob ships a typed default AND a validation branch, and the
    autoscaler's in-code fallback knob set matches the config section."""
    violations = _key_lint()()
    assert violations == [], (
        "config schema halves drifted (default without validation, or "
        f"validator without default): {violations}")


def test_config_key_lint_catches_default_without_validation(tmp_path):
    bad = tmp_path / "config.py"
    bad.write_text(
        '_DEFAULTS: dict = {\n'
        '    "serve": {\n'
        '        "autoscale": {"enable": False, "bogus": 1},\n'
        '    },\n'
        '}\n'
        '\n'
        'def validate_config(cfg):\n'
        '    s = cfg.get("serve")\n'
        '    a = s.get("autoscale")\n'
        '    for key in a:\n'
        '        if key not in ("enable",):\n'
        '            raise ValueError(key)\n')
    violations = _key_lint()(config_path=str(bad), autoscale_path=None)
    assert any("bogus" in msg and "no validation branch" in msg
               for _, _, msg in violations), violations
    # the validated key is NOT flagged
    assert not any("autoscale.enable" in msg for _, _, msg in violations)


# ---- the elasticity spike drill ---------------------------------------------

@pytest.mark.slow
def test_spike_drill_autoscaled_fleet(tmp_path):
    """The end-to-end acceptance drill, all on CPU: a spike10x replay with
    execute-latency chaos against a 1-replica fleet with the autoscaler on.
    Interactive p99 holds its (generous) SLO through every phase, the fleet
    grows then shrinks back (scale_up before scale_down on the event
    stream), and zero accepted requests are lost or errored."""
    slo = tmp_path / "slo.yaml"
    slo.write_text("routes:\n"
                   "  predict: {p99_ms: 60000.0}\n"
                   "error_rate_max: 0.0\n")
    # generous per-request timeout: with injected execute latency plus CPU
    # jit compiles the 1s default would 504 legitimate spike traffic
    cfg = tmp_path / "serve.yaml"
    cfg.write_text("serve:\n  request_timeout_ms: 30000.0\n")
    logs = tmp_path / "logs"
    cmd = [
        sys.executable, os.path.join(REPO, "scripts", "traffic_gen.py"),
        "--config_path", str(cfg),
        "--requests", "40", "--rate", "20", "--seed", "7",
        "--mix", "predict=0.8,session=0.2", "--sizes", "24",
        "--profile", "spike10x",
        "--autoscale",
        "max_replicas=2,queue_high=0.5,scale_up_cooldown_s=0.5,"
        "interval_s=0.1,scale_down_cooldown_s=1.0,idle_rounds=3,"
        "queue_low=2",
        "--scale-settle-s", "30",
        "--chaos", "latency@0.0:s=0.12",
        "--slo", str(slo),
        "--obs-dir", str(logs),
    ]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                       env=env, timeout=900)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    rec = json.loads(r.stdout.strip().splitlines()[-1])

    # nothing lost, nothing errored — elasticity never sacrificed work
    assert rec["lost"] == 0, rec
    assert rec["errors"] == 0, rec
    assert rec["completed"] == rec["requests"], rec

    # interactive p99 held through every phase, spike included
    assert set(rec["phases"]) == {"pre", "spike", "post"}, rec["phases"]
    for phase, ps in rec["phases"].items():
        assert ps["slo_pass"] is True, (phase, ps)
        assert ps["interactive_p99_ms"] is not None, (phase, ps)

    # the fleet grew under the spike and shrank back before drain
    events = [json.loads(line) for line in
              (logs / "obs" / "events.jsonl").read_text().splitlines()]
    ups = [e for e in events if e.get("name") == "gateway/scale_up"]
    downs = [e for e in events if e.get("name") == "gateway/scale_down"]
    assert ups, "autoscaler never scaled up under a 10x spike"
    assert downs, "autoscaler never scaled back down after the spike"
    assert min(e["ts"] for e in ups) < max(e["ts"] for e in downs)
    assert ups[0]["to_replicas"] > ups[0]["from_replicas"]
    for state in rec["autoscale"].values():
        assert state["replicas"] == state["min"], rec["autoscale"]
