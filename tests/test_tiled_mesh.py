"""Device-parallel tiled serving (serve/mesh_tiled.py + ops/tiling.py round
scheduling): LPT round planning, mesh-vs-sequential exactness on 8 virtual
CPU devices (plain AND fused edge impls, ragged rounds included), the
round-boundary disconnect contract, tile-plan portability across a devices
reconfig, the one-executable-per-(shape_key, D) invariant, and — slow lane —
a million-node scene through rounds of 8 with zero recompiles after warmup.

Runs on 8 virtual CPU devices via ``--xla_force_host_platform_device_count``
(tests/conftest.py); real multi-chip numbers come from the hw_session
``bench_tiled_mesh`` leg.
"""

import json
import threading

import jax
import numpy as np
import pytest

from distegnn_tpu.models.fast_egnn import FastEGNN
from distegnn_tpu.obs.metrics import MetricsRegistry
from distegnn_tpu.ops.graph import pad_graphs
from distegnn_tpu.ops.tiling import plan_rounds, plan_tiles, tile_work
from distegnn_tpu.serve import (BucketLadder, InferenceEngine, RequestQueue,
                                ServeMetrics, SessionPrepCache, TiledExecutor,
                                synthetic_graph)
from distegnn_tpu.serve.mesh_tiled import resolve_devices
from distegnn_tpu.serve.prep import nbytes_of
from distegnn_tpu.serve.registry import ModelRegistry
from distegnn_tpu.serve.transport import Gateway

from test_tiled import _lattice_scene, _model, _norm_err, _payload, _post

pytestmark = pytest.mark.serve


# --------------------------------------------------------- round scheduling

def test_plan_rounds_covers_every_tile_once():
    g = synthetic_graph(500, radius=0.2, seed=11)
    plan = plan_tiles(g["edge_index"], g["loc"], g["edge_attr"],
                      tile_nodes=128, halo_floor=16, edge_floor=256)
    T = plan.n_tiles
    for D in (1, 2, 3, 8):
        sched = plan_rounds(plan, D)
        assert sched.n_devices == D
        assert sched.n_rounds == -(-T // D)
        flat = [t for r in sched.rounds for t in r]
        assert sorted(flat) == list(range(T))       # each tile exactly once
        assert all(len(r) <= D for r in sched.rounds)
        assert sched.round_imbalance >= 1.0


def test_plan_rounds_lpt_balances_skewed_work():
    """LPT over an adversarial work vector: the heavy tile must not share a
    round with the next-heaviest — imbalance stays far below the sorted-
    chunking assignment that pairs them."""
    g = synthetic_graph(600, radius=0.2, seed=3)
    plan = plan_tiles(g["edge_index"], g["loc"], g["edge_attr"],
                      tile_nodes=128, halo_floor=16, edge_floor=256)
    T = plan.n_tiles
    assert T >= 4
    work = np.ones(T)
    work[0] = 100.0
    work[1] = 90.0
    sched = plan_rounds(plan, 2, work=work)
    rounds_of = {t: i for i, r in enumerate(sched.rounds) for t in r}
    assert rounds_of[0] != rounds_of[1]             # heavies split apart
    naive_imb = (190.0 / (work.sum() / sched.n_rounds))
    assert sched.round_imbalance < naive_imb


def test_tile_work_matches_plan_model():
    g = synthetic_graph(400, radius=0.2, seed=5)
    plan = plan_tiles(g["edge_index"], g["loc"], g["edge_attr"],
                      tile_nodes=128, halo_floor=16, edge_floor=256)
    w = tile_work(plan)
    assert w.shape == (plan.n_tiles,)
    assert (w == [s.n_own + s.edge_index.shape[1]
                  for s in plan.tiles]).all()


def test_resolve_devices_auto_clamp_and_degenerate():
    avail = jax.local_device_count()
    assert avail == 8                       # conftest virtual-device contract
    assert resolve_devices("auto") == avail
    assert resolve_devices(4) == 4
    assert resolve_devices(99) == avail     # clamped, never an error
    assert resolve_devices("auto", n_tiles=1) == 1   # nothing to parallelize
    assert resolve_devices(4, n_tiles=0) == 1


# --------------------------------------------- mesh-vs-sequential exactness

def _seq_and_executor(impl="plain"):
    if impl == "fused":
        model = _model("fused")
        g = synthetic_graph(900, radius=0.2, seed=5)
        batch = pad_graphs([dict(g)], max_nodes=1536, edge_block=512,
                           edge_tile=512, split_remote=True,
                           compute_pair=False)
        params = model.init(jax.random.PRNGKey(0), batch)
        eng = InferenceEngine(model, params,
                              layout_opts={"edge_block": 512,
                                           "split_remote": True})
        tx = TiledExecutor(eng, {"tile_nodes": 256, "halo_floor": 64,
                                 "edge_floor": 512})
    else:
        model = _model("plain")
        g = synthetic_graph(400, radius=0.2, seed=5)
        tight = pad_graphs([g], node_bucket=1, edge_bucket=1)
        params = model.init(jax.random.PRNGKey(0), tight)
        eng = InferenceEngine(model, params)
        tx = TiledExecutor(eng, {"tile_nodes": 128, "halo_floor": 16,
                                 "edge_floor": 256})
    seq = tx.predict(dict(g))
    assert seq["tiles"] >= 2 and seq["devices"] == 1
    assert seq["rounds"] == seq["tiles"]    # sequential: one tile per round
    return g, tx, eng, seq


def test_mesh_parity_plain_even_rounds():
    """D divides the tile count: every round is full; parity is exact and
    the round count drops D-fold vs sequential on the SAME plan."""
    g, tx, eng, seq = _seq_and_executor("plain")
    T = seq["tiles"]
    D = 4
    assert T % D == 0
    tx.devices = D
    out = tx.predict(dict(g))
    assert out["devices"] == D
    assert out["rounds"] == T // D
    assert out["round_ms"] > 0 and out["halo_gather_ms"] >= 0
    assert _norm_err(out["prediction"], seq["prediction"]) <= 1e-6
    # gauges fed from the mesh run
    gv = eng.metrics.registry.gauge
    assert gv("serve/tiled_devices").value == D
    assert gv("serve/tiled_round_ms").value > 0


def test_mesh_parity_plain_ragged_round():
    """Tile count NOT divisible by D: the last round carries zero-masked
    filler slots whose partials must contribute exactly nothing."""
    g, tx, eng, seq = _seq_and_executor("plain")
    T = seq["tiles"]
    D = 3
    assert T % D != 0
    tx.devices = D
    out = tx.predict(dict(g))
    assert out["rounds"] == -(-T // D)
    assert _norm_err(out["prediction"], seq["prediction"]) <= 1e-6


def test_mesh_parity_fused_ragged_round():
    """Same exactness through the halo-aware fused edge pipeline (blocked
    layout, split_remote) under pmap, ragged last round included."""
    g, tx, eng, seq = _seq_and_executor("fused")
    T = seq["tiles"]
    D = 3
    assert T % D != 0
    tx.devices = D
    out = tx.predict(dict(g))
    assert out["devices"] == D and out["rounds"] == -(-T // D)
    assert _norm_err(out["prediction"], seq["prediction"]) <= 1e-6


def test_mesh_one_executable_per_shape_and_devices():
    """A mesh-only engine compiles exactly ONE tile-layer executable, keyed
    by the sequential rung key extended with D."""
    model = _model("plain")
    g = synthetic_graph(400, radius=0.2, seed=5)
    tight = pad_graphs([g], node_bucket=1, edge_bucket=1)
    params = model.init(jax.random.PRNGKey(0), tight)
    eng = InferenceEngine(model, params)
    tx = TiledExecutor(eng, {"tile_nodes": 128, "halo_floor": 16,
                             "edge_floor": 256, "devices": 4})
    out = tx.predict(dict(g))
    assert out["devices"] == 4
    keys = [k for k in eng._cache if k[0] == "tile_layer"]
    assert len(keys) == 1
    assert keys[0][-1] == 4                 # ...and it is the D-keyed one
    tx.predict(dict(g))                     # same rung, same D: cache hit
    assert [k for k in eng._cache if k[0] == "tile_layer"] == keys


# ------------------------------------------- round-boundary cancel contract

def test_mesh_disconnect_cancels_at_round_boundary():
    g, tx, eng, seq = _seq_and_executor("plain")
    tx.devices = 4
    seen = []

    def progress(**info):
        seen.append(info)
        return False                        # "client disconnected"

    out = tx.predict(dict(g), progress=progress)
    assert out["cancelled"] is True
    assert out["prediction"] is None
    assert len(seen) == 1                   # stopped after the FIRST round
    assert seen[0]["round"] == 0 and seen[0]["layer"] == 0
    assert seen[0]["n_rounds"] == seq["tiles"] // 4
    assert seen[0]["n_tiles"] == seq["tiles"]


# ----------------------------------- plan portability across devices change

def test_tile_plan_portable_across_devices_reconfig():
    """A plan session-cached at devices: 1 is reused BITWISE (cache hit, no
    rebuild) after the executor is reconfigured to devices: 4 — shape_key
    carries no device count — and nbytes_of still charges the plan."""
    model = _model("plain")
    g = synthetic_graph(400, radius=0.2, seed=5)
    tight = pad_graphs([g], node_bucket=1, edge_bucket=1)
    params = model.init(jax.random.PRNGKey(0), tight)
    eng = InferenceEngine(model, params)
    tx = TiledExecutor(eng, {"tile_nodes": 128, "halo_floor": 16,
                             "edge_floor": 256, "devices": 1})
    cache = SessionPrepCache(capacity=4, ladder=BucketLadder(),
                             max_bytes=1 << 22)
    builds = []

    def build():
        builds.append(1)
        return tx.plan(dict(g))

    plan1, hit1 = cache.prepare_tile("sess", g, build)
    seq = tx.predict(dict(g), plan=plan1)
    assert (hit1, len(builds)) == (False, 1)

    tx.devices = 4                          # deploy-time reconfig
    plan2, hit2 = cache.prepare_tile("sess", g, build)
    assert hit2 is True and len(builds) == 1    # no rebuild...
    assert plan2 is plan1                       # ...the SAME plan object
    assert tx._plan_ok(plan2, g["loc"].shape[0])
    out = tx.predict(dict(g), plan=plan2)       # and it serves at D=4
    assert out["devices"] == 4
    assert _norm_err(out["prediction"], seq["prediction"]) <= 1e-6
    assert nbytes_of(plan2) > 0                 # byte-charging still covers it


# --------------------------------------------------- gateway per-round e2e

@pytest.fixture()
def mesh_gateway():
    """Tiled gateway with serve.tiled.devices: 4 — the 300-node scene above
    the cap serves through device-parallel rounds."""
    model = _model("plain")
    g = synthetic_graph(300, radius=0.2, seed=7)
    tight = pad_graphs([g], node_bucket=1, edge_bucket=1)
    params = model.init(jax.random.PRNGKey(0), tight)
    ref = np.asarray(model.apply(params, tight)[0])[0]
    metrics = ServeMetrics()
    eng = InferenceEngine(model, params, max_batch=2, metrics=metrics,
                          ladder=BucketLadder(max_nodes=64, max_edges=4096),
                          session_cache=4, session_cache_bytes=1 << 22,
                          tiled={"tile_nodes": 96, "halo_floor": 16,
                                 "edge_floor": 256, "devices": 4})
    q = RequestQueue(eng, request_timeout_ms=120_000.0, metrics=metrics)
    reg = ModelRegistry.single("nbody", eng, q, feat_nf=1, edge_attr_nf=2)
    reg.start()
    gw = Gateway(reg, port=0, metrics_registry=MetricsRegistry())
    t = threading.Thread(target=gw.serve_forever, daemon=True)
    t.start()
    yield gw, g, ref
    gw.drain()
    t.join(timeout=30.0)
    gw.close()


def test_gateway_mesh_serves_and_reports_rounds(mesh_gateway):
    gw, g, ref = mesh_gateway
    status, body = _post(gw.url("/v1/models/nbody/predict"), _payload(g))
    resp = json.loads(body)
    assert status == 200, body[:400]
    pred = np.asarray(resp["prediction"], np.float32)
    assert _norm_err(pred, ref) <= 1e-5
    st = resp["tiled"]
    assert st["devices"] == 4
    assert st["rounds"] == -(-st["tiles"] // 4)
    assert st["round_ms"] > 0


def test_gateway_mesh_streams_per_round_progress(mesh_gateway):
    gw, g, ref = mesh_gateway
    status, body = _post(gw.url("/v1/models/nbody/predict?stream=1"),
                         _payload(g))
    assert status == 200, body[:400]
    lines = [json.loads(ln) for ln in body.strip().split("\n")]
    done = lines[-1]
    assert done["done"] is True and done["cancelled"] is False
    pred = np.asarray(done["prediction"], np.float32)
    assert _norm_err(pred, ref) <= 1e-5
    progress = [ln for ln in lines[:-1] if "round" in ln]
    assert len(progress) == done["tiled"]["rounds"] * done["tiled"]["layers"]
    assert all("tile" not in ln for ln in progress)   # per-ROUND lines
    assert progress[0]["n_rounds"] == done["tiled"]["rounds"]


# ------------------------------------------------- million-node slow lane

@pytest.mark.slow
def test_million_node_mesh_rounds_one_executable(tmp_path):
    """The mesh acceptance gate: 1M nodes through rounds of 8 virtual
    devices with exactly ONE tile-layer executable per (shape_key, D), zero
    recompiles after warmup (CompileWatcher-certified), and the round count
    dropped 8x vs the sequential tile walk of the same plan."""
    from distegnn_tpu.obs import jaxprobe

    side = 100                          # 1_000_000 nodes
    g = _lattice_scene(side)
    model = _model("plain")
    tiny = synthetic_graph(20, seed=0)
    params = model.init(jax.random.PRNGKey(0),
                        pad_graphs([tiny], node_bucket=1, edge_bucket=1))
    eng = InferenceEngine(
        model, params, session_cache=4, session_cache_bytes=1 << 30,
        tiled={"tile_nodes": 131_072, "timeout_factor": 16.0,
               "devices": 8})

    watcher = jaxprobe.install_compile_watcher()
    try:
        jaxprobe.set_phase("serve_warmup")
        warm = eng.predict_tiled(dict(g))
        assert warm["devices"] == 8
        assert warm["rounds"] == -(-warm["tiles"] // 8)
        assert warm["rounds"] * 8 < warm["tiles"] + 8   # ~8x fewer dispatches
        layer_keys = [k for k in eng._cache if k[0] == "tile_layer"]
        assert len(layer_keys) == 1 and layer_keys[0][-1] == 8
        watcher.mark_warmup_done()

        out = eng.predict_tiled(dict(g))
        assert np.isfinite(out["prediction"]).all()
        assert out["rounds"] == warm["rounds"]
        assert watcher.snapshot()["compiles_after_warmup"] == 0
        assert [k for k in eng._cache if k[0] == "tile_layer"] == layer_keys
    finally:
        jaxprobe.deactivate_compile_watcher()
