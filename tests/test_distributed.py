"""Distributed-path tests on the 8-virtual-CPU-device mesh (SURVEY.md §4:
multi-device tests that need no pod).

The core invariant: DistEGNN over P partitions must equal FastEGNN on the
union graph — the reference preserves this by construction (disjoint
partitions + 3 weighted allreduces per layer, models/FastEGNN.py:310-319);
here it is an executable test."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from distegnn_tpu.data import GraphDataset, ShardedGraphLoader, build_nbody_graph
from distegnn_tpu.data.partition import assign_partitions, split_graph
from distegnn_tpu.models.fast_egnn import FastEGNN
from distegnn_tpu.ops.graph import pad_graphs
from distegnn_tpu.parallel.compat import shard_map
from distegnn_tpu.parallel.launch import make_distributed_steps
from distegnn_tpu.parallel.mesh import GRAPH_AXIS, make_mesh
from distegnn_tpu.train import TrainState, make_eval_step, make_optimizer, make_train_step

NPARTS = 4


def _graph(rng, n=32):
    loc = rng.normal(size=(n, 3))
    vel = rng.normal(size=(n, 3))
    charges = rng.choice([1.0, -1.0], size=(n, 1))
    target = loc + 0.1 * vel
    return build_nbody_graph(loc, vel, charges, target, radius=-1.0, cutoff_rate=0.0)


def _union_of_parts(parts):
    """Re-assemble partition dicts into one whole-graph dict with the SAME
    edge set (each partition's local edges, indices offset)."""
    out = {k: None for k in parts[0]}
    offset = 0
    cat = {k: [] for k in ("node_feat", "node_attr", "loc", "vel", "target", "edge_attr")}
    eidx = []
    for p in parts:
        for k in cat:
            if p.get(k) is not None:
                cat[k].append(p[k])
        eidx.append(p["edge_index"] + offset)
        offset += p["loc"].shape[0]
    for k, v in cat.items():
        out[k] = np.concatenate(v, axis=0) if v else None
    out["edge_index"] = np.concatenate(eidx, axis=1)
    out["loc_mean"] = parts[0]["loc_mean"]
    return out


@pytest.mark.parametrize("method", ["random", "kmeans", "metis"])
def test_partition_covers_all_nodes_balanced(rng, method):
    g = _graph(rng, n=64)
    labels = assign_partitions(g["loc"], NPARTS, method, outer_radius=2.0, seed=0)
    assert labels.shape == (64,)
    counts = np.bincount(labels, minlength=NPARTS)
    assert counts.sum() == 64 and (counts > 0).all()
    if method == "random":
        assert counts.max() - counts.min() <= 1  # exact balance
    elif method == "metis":
        # like METIS, the refining partitioner trades exact balance for cut
        # quality within a small slack (+-1 per bisection level)
        assert counts.max() - counts.min() <= 2 * NPARTS.bit_length()
    parts = split_graph(g, NPARTS, method, inner_radius=1.5, outer_radius=2.0, seed=0)
    assert sum(p["loc"].shape[0] for p in parts) == 64
    for p in parts:
        np.testing.assert_allclose(p["loc_mean"], g["loc"].mean(axis=0), atol=1e-6)
        if p["edge_index"].shape[1]:
            d = np.linalg.norm(p["loc"][p["edge_index"][0]] - p["loc"][p["edge_index"][1]], axis=1)
            assert (d < 1.5).all()  # inner-radius edges only


@pytest.fixture(scope="module")
def dist_setup():
    rng = np.random.default_rng(7)
    g = _graph(rng, n=32)
    parts = split_graph(g, NPARTS, "random", inner_radius=2.5, outer_radius=None, seed=3)
    union = _union_of_parts(parts)

    model_1 = FastEGNN(node_feat_nf=2, hidden_nf=16, virtual_channels=3, n_layers=3)
    model_P = model_1.copy(axis_name=GRAPH_AXIS)
    union_batch = pad_graphs([union])
    params = model_1.init(jax.random.PRNGKey(0), union_batch)

    # stacked [P, B=1, ...] partition batch with shard-wide common padding
    n_max = max(p["loc"].shape[0] for p in parts)
    e_max = max(p["edge_index"].shape[1] for p in parts)
    part_batches = [pad_graphs([p], max_nodes=n_max + 2, max_edges=e_max + 8) for p in parts]
    stacked = jax.tree.map(lambda *xs: np.stack(xs, axis=0), *part_batches)
    mesh = make_mesh(n_graph=NPARTS, devices=jax.devices()[:NPARTS])
    return model_1, model_P, params, union_batch, stacked, mesh, parts


def test_distributed_forward_matches_union(dist_setup):
    model_1, model_P, params, union_batch, stacked, mesh, parts = dist_setup

    loc_1, X_1 = jax.jit(model_1.apply)(params, union_batch)

    fwd = jax.jit(shard_map(
        lambda pr, b: model_P.apply(pr, jax.tree.map(lambda x: x[0], b)),
        mesh=mesh, in_specs=(P(), P(GRAPH_AXIS)),
        out_specs=(P(GRAPH_AXIS), P()), check_vma=False,
    ))
    loc_P, X_P = fwd(params, stacked)

    # virtual nodes are global objects: identical across the mesh
    np.testing.assert_allclose(np.asarray(X_P), np.asarray(X_1), atol=1e-4)

    # real nodes: compare per-partition slices to the union's node blocks
    # (out_specs P(GRAPH_AXIS) concatenates per-device [B,N,3] on axis 0 -> [P*B,N,3])
    offset = 0
    loc_P = np.asarray(loc_P)
    loc_1 = np.asarray(loc_1)[0]
    for i, p in enumerate(parts):
        n = p["loc"].shape[0]
        np.testing.assert_allclose(loc_P[i, :n], loc_1[offset:offset + n], atol=1e-4)
        offset += n


def test_distributed_loss_and_grads_match_union(dist_setup):
    import optax

    model_1, model_P, params, union_batch, stacked, mesh, parts = dist_setup
    # SGD so the param delta is proportional to the gradient (Adam would
    # normalize away the gradient scale and amplify float noise)
    tx = optax.sgd(1e-2)

    step_1 = jax.jit(make_train_step(model_1, tx, mmd_weight=0.0, mmd_sigma=1.5, mmd_samples=3))
    train_P, eval_P = make_distributed_steps(model_P, tx, mesh, mmd_weight=0.0,
                                             mmd_sigma=1.5, mmd_samples=3)

    key = jax.random.PRNGKey(5)
    s1 = TrainState.create(params, tx)
    sP = TrainState.create(params, tx)
    s1_next, m1 = step_1(s1, union_batch, key)
    sP_next, mP = train_P(sP, stacked, key)

    np.testing.assert_allclose(float(mP["loss"]), float(m1["loss"]), rtol=1e-5)
    # identical global gradient -> identical replicated update on every device
    for a, b in zip(jax.tree.leaves(s1_next.params), jax.tree.leaves(sP_next.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    ev_1 = jax.jit(make_eval_step(model_1))
    np.testing.assert_allclose(float(eval_P(params, stacked)),
                               float(ev_1(params, union_batch)), rtol=1e-5)


def test_2d_mesh_matches_single_device(rng):
    """(data=2, graph=2) mesh: 2 different graphs, each split into 2 spatial
    partitions, one partition per device. One SGD step must equal the
    single-device step on the padded 2-graph union batch (VERDICT r1 item 4:
    the data axis in actual use). MMD off for exactness (its sample draw is
    per-device by design, reference utils/train.py:124-139)."""
    import optax

    from distegnn_tpu.parallel.mesh import DATA_AXIS

    D = Pn = 2
    graphs, unions, per_d = [], [], []
    for d in range(D):
        g = _graph(rng, n=20 + 4 * d)
        parts = split_graph(g, Pn, "random", inner_radius=2.5, outer_radius=None, seed=d)
        per_d.append(parts)
        unions.append(_union_of_parts(parts))
    n_max = max(p["loc"].shape[0] for parts in per_d for p in parts)
    e_max = max(p["edge_index"].shape[1] for parts in per_d for p in parts)
    stacks = []
    for parts in per_d:
        pbs = [pad_graphs([p], max_nodes=n_max + 2, max_edges=e_max + 8) for p in parts]
        stacks.append(jax.tree.map(lambda *xs: np.stack(xs, axis=0), *pbs))
    batch_2d = jax.tree.map(lambda *xs: np.stack(xs, axis=0), *stacks)  # [D, P, 1, ...]

    union_batch = pad_graphs(unions)  # [2, N, ...] — both graphs in one batch

    model_1 = FastEGNN(node_feat_nf=2, hidden_nf=16, virtual_channels=3, n_layers=3)
    model_P = model_1.copy(axis_name=GRAPH_AXIS)
    params = model_1.init(jax.random.PRNGKey(0), union_batch)
    tx = optax.sgd(1e-2)

    mesh = make_mesh(n_graph=Pn, n_data=D, devices=jax.devices()[:4])
    train_P, eval_P = make_distributed_steps(model_P, tx, mesh, mmd_weight=0.0,
                                             mmd_sigma=1.5, mmd_samples=2)
    step_1 = jax.jit(make_train_step(model_1, tx, mmd_weight=0.0, mmd_sigma=1.5,
                                     mmd_samples=2))

    key = jax.random.PRNGKey(9)
    s1, m1 = step_1(TrainState.create(params, tx), union_batch, key)
    sP, mP = train_P(TrainState.create(params, tx), batch_2d, key)

    np.testing.assert_allclose(float(mP["loss"]), float(m1["loss"]), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(sP.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    ev_1 = jax.jit(make_eval_step(model_1))
    np.testing.assert_allclose(float(eval_P(params, batch_2d)),
                               float(ev_1(params, union_batch)), rtol=1e-5)


def test_sharded_loader_data_parallel_layout(rng):
    """data_parallel=D splits each partition shard's draw into [D, P, B, ...]
    with consecutive graphs of the seeded order going to consecutive data
    shards."""
    parts = [_graph(rng, n=8) for _ in range(2)]
    shards = [GraphDataset([p] * 4) for p in parts]  # P=2 shards, 4 graphs each
    flat = ShardedGraphLoader(shards, batch_size=4, shuffle=False, seed=0)
    dp = ShardedGraphLoader(shards, batch_size=2, shuffle=False, seed=0, data_parallel=2)
    (b_flat,), (b_dp,) = list(flat), list(dp)
    assert b_dp.loc.shape[:3] == (2, 2, 2)  # [D, P, B]
    # graph g of shard p at flat position [p, d*B+b] lands at [d, p, b]
    np.testing.assert_array_equal(b_dp.loc[1, 0, 1], b_flat.loc[0, 3])
    np.testing.assert_array_equal(b_dp.loc[0, 1, 0], b_flat.loc[1, 0])


def test_sharded_loader_with_distributed_step(dist_setup):
    model_1, model_P, params, _, _, mesh, parts = dist_setup
    # loaders over P shards (each shard = a dataset of one partition per graph)
    shards = [GraphDataset([p, p]) for p in parts]
    sl = ShardedGraphLoader(shards, batch_size=2, shuffle=True, seed=1)
    sl.set_epoch(0)
    tx = make_optimizer(1e-3)
    train_P, _ = make_distributed_steps(model_P, tx, mesh, mmd_weight=0.03,
                                        mmd_sigma=1.5, mmd_samples=2)
    state = TrainState.create(params, tx)
    for batch in sl:
        state, metrics = train_P(state, batch, jax.random.PRNGKey(0))
        assert np.isfinite(float(metrics["loss"]))


def test_distributed_cumsum_matches_scatter(dist_setup):
    """segment_impl='cumsum' under shard_map (vmapped searchsorted/cumsum +
    psum virtual-node sync) matches the scatter lowering on the same
    partition stack."""
    _, model_P, params, _, _, mesh, parts = dist_setup
    n_max = max(p["loc"].shape[0] for p in parts)
    e_max = max(p["edge_index"].shape[1] for p in parts)
    part_batches = [pad_graphs([p], max_nodes=n_max + 2, max_edges=e_max + 8,
                               compute_pair=True) for p in parts]
    stacked = jax.tree.map(lambda *xs: np.stack(xs, axis=0), *part_batches)
    assert stacked.edge_pair is not None

    def fwd_of(m):
        return jax.jit(shard_map(
            lambda pr, b: m.apply(pr, jax.tree.map(lambda x: x[0], b)),
            mesh=mesh, in_specs=(P(), P(GRAPH_AXIS)),
            out_specs=(P(GRAPH_AXIS), P()), check_vma=False,
        ))

    loc_sc, X_sc = fwd_of(model_P)(params, stacked)
    loc_cs, X_cs = fwd_of(model_P.copy(segment_impl="cumsum"))(params, stacked)
    np.testing.assert_allclose(np.asarray(X_cs), np.asarray(X_sc), atol=1e-4)
    np.testing.assert_allclose(np.asarray(loc_cs), np.asarray(loc_sc), atol=1e-4)

    # the ELL lowering rides the same pairing + static max_in_degree
    assert stacked.max_in_degree > 0
    loc_el, X_el = fwd_of(model_P.copy(segment_impl="ell"))(params, stacked)
    np.testing.assert_allclose(np.asarray(X_el), np.asarray(X_sc), atol=1e-5)
    np.testing.assert_allclose(np.asarray(loc_el), np.asarray(loc_sc), atol=1e-5)


def test_metis_partition_quality_pinned():
    """Pin the native multilevel partitioner's quality on a Fluid113K-like
    cloud (VERDICT r2 #5 / r3 #5): since the round-4 multilevel rewrite
    (HEM coarsening + weighted FM + k-way uncoarsening refinement +
    coarsest restarts) metis BEATS kmeans at 113k/8-way (cut 0.0298 vs
    0.0360, docs/artifacts/partition_quality_113k_r4.json); at this test's
    reduced 5k scale allow parity-with-margin. Guards regressions in
    native/partition.cpp."""
    import scripts.partition_quality as pq
    from distegnn_tpu.ops.radius import radius_graph_np

    loc = pq.fluid_cloud(5000, seed=0)
    edge_index = radius_graph_np(loc, pq.RADIUS)
    q = {}
    for method in ("random", "kmeans", "metis"):
        labels = assign_partitions(loc, 8, method, outer_radius=pq.RADIUS, seed=0)
        q[method] = pq.quality(labels, edge_index, 8)
    assert q["metis"]["cut_fraction"] <= 1.15 * q["kmeans"]["cut_fraction"]
    assert q["metis"]["cut_fraction"] <= 0.25 * q["random"]["cut_fraction"]
    assert q["metis"]["node_imbalance"] <= 1.05
