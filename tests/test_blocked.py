"""Blocked-CSR MXU aggregation kernels (ops/blocked.py) — exactness vs the
XLA scatter path, adjoint gradients, and end-to-end FastEGNN parity on the
blocked layout. Kernels run in Pallas interpret mode off-TPU, so these tests
validate the same code path the TPU compiles."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distegnn_tpu.ops.blocked import (
    blocked_gather,
    blocked_segment_sum,
    blockify_edges,
    max_block_degree,
    slot_ids,
)
from distegnn_tpu.ops.graph import pad_graphs
from distegnn_tpu.ops.segment import segment_sum


BLOCK, TILE = 256, 512


def _random_blocked_case(rng, n_nodes=1024, e=6000, feat=8):
    row = np.sort(rng.integers(0, n_nodes - 77, e)).astype(np.int64)
    col = rng.integers(0, n_nodes, e).astype(np.int64)
    epb = -(-max_block_degree(row, n_nodes, BLOCK) // TILE) * TILE
    ei, _, em = blockify_edges(np.stack([row, col]), None, n_nodes, epb, BLOCK)
    slots = slot_ids(jnp.asarray(ei[0])[None], jnp.asarray(em)[None], BLOCK, epb)
    E = ei.shape[1]
    data = np.zeros((E, feat), np.float32)
    data[em > 0] = rng.normal(size=(e, feat)).astype(np.float32)
    return row, ei, em, slots, jnp.asarray(data)


def test_blockify_preserves_sorted_layout():
    rng = np.random.default_rng(0)
    row, ei, em, _, _ = _random_blocked_case(rng)
    assert np.all(np.diff(ei[0]) >= 0)          # still a legal sorted edge list
    assert np.array_equal(ei[0][em > 0], row)   # real edges in original order
    epb = ei.shape[1] // (1024 // BLOCK)
    blk = np.arange(ei.shape[1]) // epb
    assert np.all(ei[0] // BLOCK == blk)        # block invariant


def test_segment_sum_matches_scatter():
    rng = np.random.default_rng(1)
    row, ei, em, slots, data = _random_blocked_case(rng)
    ref = segment_sum(data, jnp.asarray(ei[0]), 1024, mask=jnp.asarray(em))
    out = blocked_segment_sum(data[None], slots, 1024, BLOCK, TILE)[0]
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_gather_matches_take():
    rng = np.random.default_rng(2)
    _, ei, em, slots, _ = _random_blocked_case(rng)
    h = jnp.asarray(rng.normal(size=(1024, 8)).astype(np.float32))
    ref = np.where(em[:, None] > 0, np.asarray(h)[ei[0]], 0.0)
    out = blocked_gather(h[None], slots, BLOCK, TILE)[0]
    np.testing.assert_allclose(out, ref, atol=0)


def test_adjoint_gradients():
    rng = np.random.default_rng(3)
    _, ei, em, slots, data = _random_blocked_case(rng)
    h = jnp.asarray(rng.normal(size=(1024, 8)).astype(np.float32))

    g_seg = jax.grad(lambda d: jnp.sum(
        blocked_segment_sum(d[None], slots, 1024, BLOCK, TILE) ** 2))(data)
    g_ref = jax.grad(lambda d: jnp.sum(
        segment_sum(d, jnp.asarray(ei[0]), 1024, mask=jnp.asarray(em)) ** 2))(data)
    np.testing.assert_allclose(g_seg, g_ref, atol=2e-4)

    g_gat = jax.grad(lambda hh: jnp.sum(
        blocked_gather(hh[None], slots, BLOCK, TILE) * data[None]))(h)
    g_gref = jax.grad(lambda hh: jnp.sum(
        jnp.where(jnp.asarray(em)[:, None] > 0, hh[jnp.asarray(ei[0])], 0.0)
        * data))(h)
    np.testing.assert_allclose(g_gat, g_gref, atol=2e-4)


def test_bf16_path():
    rng = np.random.default_rng(4)
    _, ei, em, slots, data = _random_blocked_case(rng)
    out = blocked_segment_sum(data.astype(jnp.bfloat16)[None], slots, 1024, BLOCK, TILE)[0]
    ref = segment_sum(data, jnp.asarray(ei[0]), 1024, mask=jnp.asarray(em))
    assert out.dtype == jnp.float32  # bf16 in, f32 accumulate out
    np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-1)


def _nbody_like_graphs(rng, n_graphs=2, n=300):
    graphs = []
    for _ in range(n_graphs):
        loc = rng.normal(size=(n, 3)).astype(np.float32)
        vel = rng.normal(size=(n, 3)).astype(np.float32)
        # symmetric radius-style graph, rows sorted
        d = np.linalg.norm(loc[:, None] - loc[None, :], axis=-1)
        row, col = np.nonzero((d < 1.2) & ~np.eye(n, dtype=bool))
        dist = d[row, col]
        graphs.append({
            "node_feat": np.linalg.norm(vel, axis=1, keepdims=True).astype(np.float32),
            "loc": loc, "vel": vel, "target": loc + 0.1 * vel,
            "edge_index": np.stack([row, col]).astype(np.int64),
            "edge_attr": np.repeat(dist[:, None], 2, axis=1).astype(np.float32),
        })
    return graphs


@pytest.mark.parametrize("blocked_impl", ["pallas", "einsum"])
@pytest.mark.parametrize("compute_dtype", [None, "bf16"])
def test_fastegnn_blocked_parity(compute_dtype, blocked_impl):
    """Same graphs, blocked vs plain layout -> same FastEGNN output + grads
    (both blocked lowerings: Pallas kernels and the einsum contraction)."""
    from distegnn_tpu.models.fast_egnn import FastEGNN

    rng = np.random.default_rng(5)
    graphs = _nbody_like_graphs(rng)
    plain = pad_graphs([dict(g) for g in graphs])
    blocked = pad_graphs([dict(g) for g in graphs], edge_block=BLOCK, edge_tile=TILE)
    assert blocked.edge_block == BLOCK

    model = FastEGNN(node_feat_nf=1, edge_attr_nf=2, hidden_nf=16,
                     virtual_channels=2, n_layers=2, compute_dtype=compute_dtype,
                     blocked_impl=blocked_impl)
    params = model.init(jax.random.PRNGKey(0), plain)

    tol = 1e-5 if compute_dtype is None else 5e-2
    xp, Xp = model.apply(params, plain)
    xb, Xb = model.apply(params, blocked)
    n = plain.max_nodes  # blocked pads N up to a block multiple
    np.testing.assert_allclose((xb * blocked.node_mask[..., None])[:, :n],
                               xp * plain.node_mask[..., None], atol=tol)
    np.testing.assert_allclose(Xb, Xp, atol=tol)

    def loss(p, g):
        x, _ = model.apply(p, g)
        return jnp.sum((x - g.target) ** 2 * g.node_mask[..., None])

    from jax.flatten_util import ravel_pytree

    gp = jax.grad(loss)(params, plain)
    gb = jax.grad(loss)(params, blocked)
    flat_p = ravel_pytree(gp)[0]
    flat_b = ravel_pytree(gb)[0]
    scale = jnp.maximum(jnp.abs(flat_p).max(), 1.0)
    np.testing.assert_allclose(flat_b / scale, flat_p / scale, atol=5 * tol)


def test_graph_loader_blocked_layout():
    """GraphLoader(edge_block=...) emits a dataset-stable blocked layout."""
    from distegnn_tpu.data.loader import GraphDataset, GraphLoader

    rng = np.random.default_rng(6)
    ds = GraphDataset(_nbody_like_graphs(rng, n_graphs=6, n=200))
    ld = GraphLoader(ds, batch_size=2, shuffle=True, seed=3, edge_block=BLOCK)
    batches = list(ld)
    assert len(batches) == 3
    for b in batches:
        assert b.edge_block == BLOCK
        assert b.max_nodes == ld.max_nodes and b.max_edges == ld.max_edges
        # block invariant on every batch
        epb = b.edges_per_block
        blk = np.arange(b.max_edges) // epb
        rows = np.asarray(b.row)
        assert np.all(rows // BLOCK == blk[None, :])


def test_einsum_ops_match_plain():
    """The einsum lowering's primitives: fwd + custom-VJP grads == plain XLA.
    The custom VJPs exist because differentiating through the bf16 term split
    would bf16-round the cotangent (~1e-2 error observed); with them the
    gradients must sit at f32 noise level."""
    from distegnn_tpu.ops.blocked import (
        _paired_gather_ein, einsum_gather, einsum_segment_sum, onehot_blocks,
        pairing_perm,
    )

    rng = np.random.default_rng(11)
    g = _nbody_like_graphs(rng, n_graphs=1, n=120)[0]
    ei = g["edge_index"]
    n = 120
    n_pad = -(-n // BLOCK) * BLOCK
    epb = -(-max_block_degree(np.sort(ei[0]), n_pad, BLOCK) // 8) * 8
    bei, _, em = blockify_edges(ei, None, n_pad, epb, BLOCK)
    pair = pairing_perm(bei)
    assert pair is not None
    slot = slot_ids(jnp.asarray(bei[0]), jnp.asarray(em), BLOCK, epb)
    oh = onehot_blocks(slot, epb, BLOCK)
    E = bei.shape[1]
    mask = jnp.asarray(em)[:, None]
    x = jnp.asarray(rng.normal(size=(E, 8)).astype(np.float32)) * mask
    h = jnp.asarray(rng.normal(size=(n_pad, 8)).astype(np.float32))

    # tolerances: f32 accumulation-order noise on sums of O(100) edges/node
    ref = segment_sum(x, jnp.asarray(bei[0]), n_pad, mask=jnp.asarray(em))
    np.testing.assert_allclose(einsum_segment_sum(x, oh), ref, rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(
        einsum_gather(h, oh), np.where(em[:, None] > 0, np.asarray(h)[bei[0]], 0.0),
        atol=2e-6)

    g1 = jax.grad(lambda hh: jnp.sum(jnp.sin(einsum_gather(hh, oh)) * mask))(h)
    g2 = jax.grad(lambda hh: jnp.sum(jnp.sin(hh[jnp.asarray(bei[0])]) * mask))(h)
    np.testing.assert_allclose(g1, g2, atol=1e-4)  # gather grad = a seg-sum

    col, pj = jnp.asarray(bei[1]), jnp.asarray(pair)
    g3 = jax.grad(lambda hh: jnp.sum(jnp.cos(_paired_gather_ein(hh, col, pj, oh)) * mask))(h)
    g4 = jax.grad(lambda hh: jnp.sum(jnp.cos(hh[col]) * mask))(h)
    np.testing.assert_allclose(g3, g4, atol=1e-4)

    g5 = jax.grad(lambda xx: jnp.sum(jnp.tanh(einsum_segment_sum(xx, oh))))(x)
    g6 = jax.grad(lambda xx: jnp.sum(jnp.tanh(
        segment_sum(xx, jnp.asarray(bei[0]), n_pad, mask=jnp.asarray(em)))))(x)
    np.testing.assert_allclose(g5 * mask, g6 * mask, atol=1e-4)


def test_pairing_perm():
    from distegnn_tpu.ops.blocked import pairing_perm

    rng = np.random.default_rng(8)
    g = _nbody_like_graphs(rng, n_graphs=1, n=120)[0]
    batch = pad_graphs([g], edge_block=BLOCK)
    assert batch.edge_pair is not None
    ei = np.asarray(batch.edge_index[0])
    pair = np.asarray(batch.edge_pair[0])
    assert np.array_equal(ei[0][pair], ei[1])
    assert np.array_equal(ei[1][pair], ei[0])

    # directed (asymmetric) list -> no pairing, model falls back
    ei_dir = g["edge_index"][:, g["edge_index"][0] < g["edge_index"][1]]
    assert pairing_perm(ei_dir) is None
    g2 = dict(g, edge_index=ei_dir,
              edge_attr=np.ones((ei_dir.shape[1], 2), np.float32))
    b2 = pad_graphs([g2], edge_block=BLOCK)
    assert b2.edge_pair is None


@pytest.mark.parametrize("edge_block", [0, BLOCK])
def test_remat_same_outputs_and_grads(edge_block):
    """model.remat recomputes activations; results must be identical —
    including through the blocked Pallas custom-VJP kernels."""
    from distegnn_tpu.models.fast_egnn import FastEGNN
    from jax.flatten_util import ravel_pytree

    rng = np.random.default_rng(9)
    kw_pad = dict(edge_block=edge_block) if edge_block else {}
    batch = pad_graphs(_nbody_like_graphs(rng, n_graphs=1, n=120), **kw_pad)
    kw = dict(node_feat_nf=1, edge_attr_nf=2, hidden_nf=16,
              virtual_channels=2, n_layers=2)
    m0, m1 = FastEGNN(**kw), FastEGNN(**kw, remat=True)
    params = m0.init(jax.random.PRNGKey(0), batch)

    def loss(m, p):
        x, _ = m.apply(p, batch)
        return jnp.sum((x - batch.target) ** 2 * batch.node_mask[..., None])

    np.testing.assert_allclose(loss(m1, params), loss(m0, params), rtol=1e-6)
    g0 = ravel_pytree(jax.grad(lambda p: loss(m0, p))(params))[0]
    g1 = ravel_pytree(jax.grad(lambda p: loss(m1, p))(params))[0]
    np.testing.assert_allclose(g1, g0, atol=1e-6)


@pytest.mark.parametrize("blocked_impl", ["pallas", "einsum"])
@pytest.mark.parametrize("model_name", ["FastRF", "FastSchNet"])
def test_other_fast_models_blocked_parity(model_name, blocked_impl):
    """FastRF / FastSchNet: blocked layout == plain layout (fwd + grads)."""
    from jax.flatten_util import ravel_pytree

    rng = np.random.default_rng(10)
    graphs = _nbody_like_graphs(rng)
    plain = pad_graphs([dict(g) for g in graphs])
    blocked = pad_graphs([dict(g) for g in graphs], edge_block=BLOCK)
    assert blocked.edge_pair is not None

    if model_name == "FastRF":
        from distegnn_tpu.models.fast_rf import FastRF

        model = FastRF(edge_attr_nf=2, hidden_nf=16, virtual_channels=2,
                       n_layers=2, blocked_impl=blocked_impl)
    else:
        from distegnn_tpu.models.fast_schnet import FastSchNet

        model = FastSchNet(node_feat_nf=1, edge_attr_nf=2, hidden_nf=16,
                           virtual_channels=2, n_layers=2, cutoff=2.0,
                           blocked_impl=blocked_impl)
    params = model.init(jax.random.PRNGKey(0), plain)

    xp, Xp = model.apply(params, plain)
    xb, Xb = model.apply(params, blocked)
    n = plain.max_nodes
    np.testing.assert_allclose((xb * blocked.node_mask[..., None])[:, :n],
                               xp * plain.node_mask[..., None], atol=1e-5)
    np.testing.assert_allclose(Xb, Xp, atol=1e-5)

    def loss(p, g):
        x, _ = model.apply(p, g)
        return jnp.sum((x - g.target) ** 2 * g.node_mask[..., None])

    gp = ravel_pytree(jax.grad(loss)(params, plain))[0]
    gb = ravel_pytree(jax.grad(loss)(params, blocked))[0]
    scale = jnp.maximum(jnp.abs(gp).max(), 1.0)
    np.testing.assert_allclose(gb / scale, gp / scale, atol=5e-5)


def test_gen2_shapes_big_tile_small_scale():
    """Gen-2 kernel configuration (block 512 x tile 2048, bf16 streams)
    scaled down to interpret-mode size: block > tile-disproportionate shapes
    and the bf16 single-pass path stay exact vs the scatter reference."""
    rng = np.random.default_rng(7)
    n_nodes, block, tile = 256, 64, 128
    e = 1500
    row = np.sort(rng.integers(0, n_nodes, e)).astype(np.int64)
    col = rng.integers(0, n_nodes, e).astype(np.int64)
    epb = -(-max_block_degree(row, n_nodes, block) // tile) * tile
    ei, _, em = blockify_edges(np.stack([row, col]), None, n_nodes, epb, block)
    slots = slot_ids(jnp.asarray(ei[0])[None], jnp.asarray(em)[None], block, epb)
    E = ei.shape[1]
    data = np.zeros((E, 8), np.float32)
    data[em > 0] = rng.normal(size=(e, 8)).astype(np.float32)
    db = jnp.asarray(data).astype(jnp.bfloat16)

    out = blocked_segment_sum(db[None], slots, n_nodes, block, tile)[0]
    ref = segment_sum(db.astype(jnp.float32), jnp.asarray(ei[0]), n_nodes,
                      mask=jnp.asarray(em))
    # bf16 inputs, f32 accumulation: error is input-rounding level only
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)

    h = jnp.asarray(rng.normal(size=(n_nodes, 8)).astype(np.float32)).astype(
        jnp.bfloat16)
    g_out = blocked_gather(h[None], slots, block, tile)[0]
    ref_g = jnp.where(jnp.asarray(em)[:, None] > 0,
                      jnp.take(h, jnp.asarray(ei[0]), axis=0), 0)
    np.testing.assert_allclose(
        np.asarray(g_out, np.float32),
        np.asarray(jnp.asarray(ref_g, jnp.float32)), rtol=0, atol=0)
