"""Fluid113K offline generation pipeline (distegnn_tpu/data/fluid_scenes.py,
bgeo.py) — the in-tree port of the reference's SPlisHSPlasH scene synthesis
(create_physics_scenes.py) and record packing (create_physics_records.py).
The external simulator is exercised with a synthetic partio export dir."""

import gzip
import json
import os

import numpy as np
import pytest

from distegnn_tpu.data.bgeo import (list_partio_frames, numpy_from_bgeo,
                                    read_bgeo, write_bgeo,
                                    write_bgeo_from_numpy)
from distegnn_tpu.data.fluid_scenes import (PARTICLE_RADIUS, box_mesh,
                                            find_valid_fluid_start_positions,
                                            load_obj, pack_scene_records,
                                            points_inside_mesh,
                                            random_rotation_matrix,
                                            rasterize_points, sample_surface,
                                            sample_volume, synthesize_scene,
                                            write_obj)

R_TEST = 0.1  # coarse particle radius so tests run in milliseconds


def test_bgeo_roundtrip(tmp_path, rng):
    pos = rng.standard_normal((37, 3)).astype(np.float32)
    vel = rng.standard_normal((37, 3)).astype(np.float32)
    dens = rng.random(37).astype(np.float32)
    ids = rng.permutation(37).astype(np.int64)
    path = str(tmp_path / "p.bgeo")
    write_bgeo(path, pos, {"velocity": vel, "density": dens, "id": ids})
    out = read_bgeo(path)
    np.testing.assert_allclose(out["position"], pos, rtol=1e-6)
    np.testing.assert_allclose(out["velocity"], vel, rtol=1e-6)
    np.testing.assert_allclose(out["density"], dens, rtol=1e-6)
    np.testing.assert_array_equal(out["id"], ids)


def test_bgeo_gzip_and_id_sort(tmp_path, rng):
    """numpy_from_bgeo restores id order (SPlisHSPlasH exports shuffle
    particles; reference physics_data_helper.py:42-57 sorts by id) and
    partio's transparent gzip is honored."""
    n = 20
    pos = rng.standard_normal((n, 3)).astype(np.float32)
    vel = rng.standard_normal((n, 3)).astype(np.float32)
    perm = rng.permutation(n)
    path = str(tmp_path / "f.bgeo")
    write_bgeo(path, pos[perm], {"velocity": vel[perm], "id": perm.astype(np.int64)})
    # gzip the same payload under a plain .bgeo name (partio sniffs magic)
    with open(path, "rb") as f:
        payload = f.read()
    gz_path = str(tmp_path / "g.bgeo")
    with open(gz_path, "wb") as f:
        f.write(gzip.compress(payload))
    for p in (path, gz_path):
        out_pos, out_vel = numpy_from_bgeo(p)
        np.testing.assert_allclose(out_pos, pos, rtol=1e-6)
        np.testing.assert_allclose(out_vel, vel, rtol=1e-6)


def test_bgeo_rejects_garbage(tmp_path):
    path = str(tmp_path / "bad.bgeo")
    with open(path, "wb") as f:
        f.write(b"not a bgeo file at all")
    with pytest.raises(ValueError, match="magic"):
        read_bgeo(path)


def test_obj_roundtrip(tmp_path):
    verts, tris = box_mesh((2.0, 3.0, 4.0))
    path = str(tmp_path / "box.obj")
    write_obj(path, verts, tris)
    v2, t2 = load_obj(path)
    np.testing.assert_allclose(v2, verts, atol=1e-6)
    np.testing.assert_array_equal(t2, tris)


def test_points_inside_mesh():
    verts, tris = box_mesh((2.0, 2.0, 2.0))  # x,z in [-1,1], y in [0,2]
    pts = np.array([[0, 1, 0], [0.9, 0.1, -0.9], [1.5, 1, 0], [0, 2.5, 0],
                    [0, -0.1, 0]], np.float64)
    np.testing.assert_array_equal(points_inside_mesh(pts, verts, tris),
                                  [True, True, False, False, False])


def test_sample_volume_grid_density():
    verts, tris = box_mesh((2.0, 2.0, 2.0))
    pts = sample_volume(verts, tris, radius=R_TEST)
    # 2r grid inset by r: floor((2 - 2r) / 2r) + 1 = 10 per axis
    assert pts.shape == (1000, 3)
    assert points_inside_mesh(pts.astype(np.float64), verts, tris).all()
    # scale shrinks the sampled volume with the mesh
    assert sample_volume(verts, tris, scale=0.5, radius=R_TEST).shape[0] < 300


def test_sample_surface_on_surface_inward_normals():
    verts, tris = box_mesh((2.0, 2.0, 2.0))
    pts, nrm = sample_surface(verts, tris, radius=R_TEST)
    area = 6 * 2.0 * 2.0
    target = int(1.9 * area / (np.pi * R_TEST**2))
    assert pts.shape[0] > 0.5 * target  # thinning keeps most of the budget
    # every sample lies on one of the six faces
    on_x = np.isclose(np.abs(pts[:, 0]), 1.0, atol=1e-5)
    on_y = np.isclose(pts[:, 1], 0.0, atol=1e-5) | np.isclose(pts[:, 1], 2.0, atol=1e-5)
    on_z = np.isclose(np.abs(pts[:, 2]), 1.0, atol=1e-5)
    assert (on_x | on_y | on_z).all()
    np.testing.assert_allclose(np.linalg.norm(nrm, axis=1), 1.0, atol=1e-5)
    # inward: stepping along the normal stays/enters the box interior
    inside = points_inside_mesh((pts + 0.05 * nrm).astype(np.float64), verts, tris)
    assert inside.mean() > 0.99


def test_random_rotation_is_rotation(rng):
    for _ in range(5):
        R = random_rotation_matrix(rng)
        np.testing.assert_allclose(R @ R.T, np.eye(3), atol=1e-5)
        assert np.linalg.det(R) == pytest.approx(1.0, abs=1e-5)


def test_rasterize_points_marks_extent(rng):
    pts = rng.uniform(0, 1, (50, 3)).astype(np.float32)
    arr_min, voxel, occ = rasterize_points(pts, 2.01 * R_TEST, R_TEST)
    assert occ.any()
    # every particle's own voxel is marked
    idx = np.floor_divide(pts, voxel).astype(np.int32) - arr_min
    assert occ[idx[:, 0], idx[:, 1], idx[:, 2]].all()
    with pytest.raises(ValueError):
        rasterize_points(pts, R_TEST, R_TEST)  # voxel too small


def test_find_valid_positions_lowest_and_carve(rng):
    # free space: 10^3 grid fully free; fluid: 3^3 block
    box = (np.zeros(3, np.int32), 0.5, np.ones((10, 10, 10), dtype=bool))
    fluid = (np.zeros(3, np.int32), 0.5, np.ones((3, 3, 3), dtype=bool))
    sel = find_valid_fluid_start_positions(box, fluid, rng)
    assert sel[1] == 0.0  # lowest feasible y in an empty box is the floor
    assert (~box[2]).sum() == 27  # chosen volume carved out of free space
    # a second, identical placement cannot overlap the carved region
    sel2 = find_valid_fluid_start_positions(box, fluid, rng)
    assert (~box[2]).sum() == 54
    assert not np.allclose(sel, sel2)


def test_find_valid_positions_too_large(rng):
    box = (np.zeros(3, np.int32), 0.5, np.ones((4, 4, 4), dtype=bool))
    fluid = (np.zeros(3, np.int32), 0.5, np.ones((6, 6, 6), dtype=bool))
    with pytest.raises(ValueError):
        find_valid_fluid_start_positions(box, fluid, rng)


@pytest.fixture(scope="module")
def scene_dir(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("scenes"))
    sim = synthesize_scene(out, seed=7, radius=R_TEST, num_objects=2,
                           min_fluid_particles=500)
    return sim


def test_synthesize_scene_layout(scene_dir):
    with open(os.path.join(scene_dir, "scene.json")) as f:
        scene = json.load(f)
    assert scene["Configuration"]["particleRadius"] == R_TEST
    assert scene["RigidBodies"][0]["geometryFile"] == "box.obj"
    assert len(scene["FluidModels"]) == 2
    box_pts, box_nrm = numpy_from_bgeo(os.path.join(scene_dir, "box.bgeo"))
    assert box_pts.shape == box_nrm.shape and box_pts.shape[0] > 100
    verts, tris = box_mesh()
    total = 0
    for fm in scene["FluidModels"]:
        fid = fm["id"]
        assert 0.01 <= scene[fid]["viscosity"]
        assert 500 <= scene[fid]["density0"] <= 2000
        pos, vel = numpy_from_bgeo(os.path.join(scene_dir, fm["particleFile"]))
        total += pos.shape[0]
        # placed fluid sits inside the container, above the floor
        assert points_inside_mesh(pos.astype(np.float64), verts, tris).mean() > 0.95
        # per-object constant start velocity within the reference bounds
        assert np.ptp(vel, axis=0).max() < 1e-6
        assert np.abs(vel[0, [0, 2]]).max() <= 4.0 and abs(vel[0, 1]) <= 1.0
    assert total >= 500


def test_synthesize_scene_deterministic(tmp_path, scene_dir):
    sim2 = synthesize_scene(str(tmp_path), seed=7, radius=R_TEST, num_objects=2,
                            min_fluid_particles=500)
    a, _ = numpy_from_bgeo(os.path.join(scene_dir, "fluid0.bgeo"))
    b, _ = numpy_from_bgeo(os.path.join(sim2, "fluid0.bgeo"))
    np.testing.assert_allclose(a, b)


def test_synthesize_scene_particle_budgets(tmp_path):
    sim = synthesize_scene(str(tmp_path), seed=11, radius=R_TEST, num_objects=2,
                           const_fluid_particles=900, min_fluid_particles=100)
    with open(os.path.join(sim, "scene.json")) as f:
        scene = json.load(f)
    total = sum(numpy_from_bgeo(os.path.join(sim, fm["particleFile"]))[0].shape[0]
                for fm in scene["FluidModels"])
    assert total == 900
    with pytest.raises(RuntimeError, match="particles"):
        synthesize_scene(str(tmp_path), seed=12, radius=R_TEST,
                         min_fluid_particles=10**9)


def test_pack_records_to_training_format(scene_dir, tmp_path, rng):
    """Synthetic partio exports -> shards -> read_sim: the full stage-2 path
    without the external simulator binary."""
    from distegnn_tpu.data.fluid113k import read_sim

    with open(os.path.join(scene_dir, "scene.json")) as f:
        scene = json.load(f)
    partio = os.path.join(scene_dir, "partio")
    os.makedirs(partio, exist_ok=True)
    T = 32
    truth = {}
    for fm in scene["FluidModels"]:
        fid = fm["id"]
        pos0, vel0 = numpy_from_bgeo(os.path.join(scene_dir, fm["particleFile"]))
        n = pos0.shape[0]
        frames = []
        for t in range(T):
            pos_t = pos0 + 0.01 * t * vel0
            perm = rng.permutation(n)  # simulator exports shuffle particles
            write_bgeo(os.path.join(partio, f"ParticleData_{fid}_{t}.bgeo"),
                       pos_t[perm], {"velocity": vel0[perm],
                                     "id": perm.astype(np.int64)})
            frames.append(pos_t)
        truth[fid] = np.stack(frames)

    out = str(tmp_path / "records")
    os.makedirs(out)
    shards = pack_scene_records(scene_dir, "sim_0007",
                                os.path.join(out, "sim_0001"), radius=R_TEST)
    assert len(shards) == 16 and all(os.path.isfile(s) for s in shards)

    pos, vel, visc, mass = read_sim(str(tmp_path), "records", 1)
    fluid_ids = sorted(truth)
    expect_pos = np.concatenate([truth[f] for f in fluid_ids], axis=1)
    assert pos.shape == (T, expect_pos.shape[1], 3)
    np.testing.assert_allclose(pos, expect_pos, atol=1e-5)
    # node constants: per-fluid viscosity and mass = density0 * (2r)^3
    expect_visc = np.concatenate(
        [np.full(truth[f].shape[1], scene[f]["viscosity"]) for f in fluid_ids])
    expect_mass = np.concatenate(
        [np.full(truth[f].shape[1], scene[f]["density0"] * (2 * R_TEST) ** 3)
         for f in fluid_ids])
    np.testing.assert_allclose(visc, expect_visc, rtol=1e-5)
    np.testing.assert_allclose(mass, expect_mass, rtol=1e-5)
    assert vel.shape == pos.shape


def test_list_partio_frames_ordering(tmp_path):
    d = str(tmp_path)
    for t in (10, 2, 0):  # out-of-order creation; numeric (not lexical) sort
        write_bgeo(os.path.join(d, f"ParticleData_fluid0_{t}.bgeo"),
                   np.zeros((1, 3), np.float32))
    frames = list_partio_frames(d)
    assert list(frames) == ["fluid0"]
    assert [os.path.basename(p) for p in frames["fluid0"]] == [
        "ParticleData_fluid0_0.bgeo", "ParticleData_fluid0_2.bgeo",
        "ParticleData_fluid0_10.bgeo"]
