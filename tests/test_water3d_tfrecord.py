"""Real-format Water-3D ingestion (VERDICT r2 next-round #7): write a GENUINE
DeepMind learning_to_simulate tfrecord (tf.train.SequenceExample records via
TFRecordWriter — byte-identical framing/proto layout to the public dataset,
reference dataset_generation/Water-3D/tfrecord_to_h5.py) and run the in-tree
converter on it. The zero-egress build host cannot download the real 15k-
trajectory dataset; this pins the FORMAT path so a user pointing the script
at the public files gets the documented h5 layout."""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")


def _write_tfrecord(path: str, trajs):
    with tf.io.TFRecordWriter(path) as w:
        for key, (ptype, pos) in enumerate(trajs):
            ex = tf.train.SequenceExample(
                context=tf.train.Features(feature={
                    "key": tf.train.Feature(
                        int64_list=tf.train.Int64List(value=[key])),
                    "particle_type": tf.train.Feature(
                        bytes_list=tf.train.BytesList(
                            value=[ptype.astype(np.int64).tobytes()])),
                }),
                feature_lists=tf.train.FeatureLists(feature_list={
                    "position": tf.train.FeatureList(feature=[
                        tf.train.Feature(bytes_list=tf.train.BytesList(
                            value=[frame.astype(np.float32).tobytes()]))
                        for frame in pos
                    ]),
                }),
            )
            w.write(ex.SerializeToString())


@pytest.mark.slow
def test_tfrecord_to_h5_roundtrip(tmp_path):
    import h5py

    from scripts.water3d_tfrecord_to_h5 import convert

    rng = np.random.default_rng(0)
    trajs = []
    for _ in range(2):
        n = int(rng.integers(20, 30))
        ptype = np.full(n, 5, np.int64)
        pos = rng.uniform(0.1, 0.9, size=(7, n, 3)).astype(np.float32)
        trajs.append((ptype, pos))
    _write_tfrecord(str(tmp_path / "valid.tfrecord"), trajs)

    out = convert(str(tmp_path), "valid.tfrecord")
    with h5py.File(out, "r") as hf:
        assert sorted(hf.keys()) == ["00000", "00001"]
        for i, (ptype, pos) in enumerate(trajs):
            g = hf[str(i).zfill(5)]
            np.testing.assert_array_equal(g["particle_type"][:], ptype)
            np.testing.assert_allclose(g["position"][:], pos, rtol=0)


@pytest.mark.slow
def test_converted_h5_feeds_water3d_pipeline(tmp_path):
    """The converted h5 must be readable by the Water-3D training pipeline —
    the full real-artifact path tfrecord -> h5 -> GraphDataset."""
    import h5py

    from scripts.water3d_tfrecord_to_h5 import convert

    rng = np.random.default_rng(1)
    n = 40
    trajs = []
    for _ in range(2):
        ptype = np.full(n, 5, np.int64)
        pos = rng.uniform(0.1, 0.9, size=(20, n, 3)).astype(np.float32)
        trajs.append((ptype, pos))
    d = tmp_path / "Water-3D"
    d.mkdir()
    for split in ("train", "valid", "test"):
        _write_tfrecord(str(d / f"{split}.tfrecord"), trajs)
        convert(str(d), f"{split}.tfrecord")

    from distegnn_tpu.data import GraphDataset
    from distegnn_tpu.data.water3d import process_water3d_cutoff

    paths = process_water3d_cutoff(str(tmp_path), "Water-3D", max_samples=4,
                                   radius=0.5, delta_t=3, cutoff_rate=0.0)
    ds = GraphDataset(paths[1])  # valid split
    assert len(ds) >= 1
    g = ds[0]
    assert g["loc"].shape == (n, 3) and np.isfinite(g["loc"]).all()
    assert g["edge_index"].shape[0] == 2 and g["edge_index"].shape[1] > 0
    assert np.isfinite(g["target"]).all()
