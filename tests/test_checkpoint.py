"""Durable-checkpoint unit contract (train/checkpoint.py, docs/ROBUSTNESS.md):
atomic writes that survive a kill mid-write, CRC-manifested verification with
typed corruption errors, rotation bounds, fallback past corrupt files, and
manifest-free portability of a moved checkpoint."""

from __future__ import annotations

import glob
import json
import os

import numpy as np
import pytest

from distegnn_tpu.testing.faults import corrupt_checkpoint, simulate_killed_save
from distegnn_tpu.train.checkpoint import (
    MANIFEST_NAME, PREEMPT_MARKER, CheckpointCorruptError, clear_preempt_marker,
    find_resume_checkpoint, read_manifest, restore_for_resume, restore_params,
    rotate_checkpoints, save_checkpoint, step_checkpoint_name,
    verify_checkpoint, write_preempt_marker)
from distegnn_tpu.train.step import TrainState, make_optimizer


def _state(scale=1.0):
    params = {"w": np.full((3, 2), scale, np.float32),
              "b": np.full((2,), scale * 0.5, np.float32)}
    return TrainState.create(params, make_optimizer(1e-3))


def _leaves_equal(a, b):
    import jax

    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------- atomicity

def test_save_sweeps_debris_of_killed_write(tmp_path):
    """A save killed between tmp-write and rename leaves only a *.tmp; the
    next save must sweep it, and restore must never consider it."""
    d = str(tmp_path)
    debris = simulate_killed_save(d, name="victim.ckpt")
    assert os.path.exists(debris)
    assert not os.path.exists(os.path.join(d, "victim.ckpt"))  # rename never ran

    path = os.path.join(d, "last_model.ckpt")
    save_checkpoint(path, _state(), epoch=3, seed=11)
    assert glob.glob(os.path.join(d, "*.tmp")) == []           # debris swept
    payload = verify_checkpoint(path)                          # intact + in manifest
    assert payload["epoch"] == 3 and payload["seed"] == 11
    entry = read_manifest(d)[os.path.basename(path)]
    assert entry["size"] > 0 and "crc32" in entry


def test_restore_roundtrips_state_and_coordinates(tmp_path):
    path = str(tmp_path / "last_model.ckpt")
    st = _state(scale=2.5)
    save_checkpoint(path, st, epoch=7, seed=5, step_in_epoch=3,
                    losses={"best_mse": 0.25})
    r = restore_for_resume(path, _state())    # fresh template, same structure
    assert (r.epoch, r.step_in_epoch, r.seed) == (7, 3, 5)
    assert r.losses["best_mse"] == 0.25
    _leaves_equal(r.state.params, st.params)
    _leaves_equal(r.state.opt_state, st.opt_state)


# ---------------------------------------------------------------- corruption

@pytest.mark.parametrize("mode", ["truncate", "garbage", "headerless"])
def test_corruption_raises_typed_error(tmp_path, mode):
    path = str(tmp_path / "last_model.ckpt")
    save_checkpoint(path, _state(), epoch=1)
    corrupt_checkpoint(path, mode=mode)
    with pytest.raises(CheckpointCorruptError) as ei:
        verify_checkpoint(path)
    assert ei.value.path == path and ei.value.reason


def test_truncation_detected_even_without_manifest(tmp_path):
    """The manifest is an aid, not a dependency: with it deleted, a torn
    pickle still surfaces as the typed error (unpickle layer)."""
    path = str(tmp_path / "last_model.ckpt")
    save_checkpoint(path, _state(), epoch=1)
    os.remove(str(tmp_path / MANIFEST_NAME))
    corrupt_checkpoint(path, mode="truncate")
    with pytest.raises(CheckpointCorruptError):
        verify_checkpoint(path)


def test_resume_falls_back_past_corrupt_newest(tmp_path, capsys):
    d = tmp_path / "exp" / "state_dict"
    older = str(d / step_checkpoint_name(4))
    newer = str(d / step_checkpoint_name(8))
    save_checkpoint(older, _state(scale=1.0), epoch=1, step_in_epoch=0)
    save_checkpoint(newer, _state(scale=9.0), epoch=2, step_in_epoch=0)
    os.utime(older, (1, 1))                   # force mtime order
    corrupt_checkpoint(newer, mode="garbage")

    r = find_resume_checkpoint(str(tmp_path), _state())
    assert r is not None and r.path == older and r.epoch == 1
    assert "resume: skipping" in capsys.readouterr().out

    corrupt_checkpoint(older, mode="truncate")
    assert find_resume_checkpoint(str(tmp_path), _state()) is None


# ---------------------------------------------------------------- rotation

def test_rotation_keeps_last_k_steps_and_all_named_checkpoints(tmp_path):
    d = str(tmp_path)
    for name in ("best_model.ckpt", "last_model.ckpt", "preempt_model.ckpt"):
        save_checkpoint(os.path.join(d, name), _state(), epoch=0)
    for step in range(1, 7):
        save_checkpoint(os.path.join(d, step_checkpoint_name(step)),
                        _state(), epoch=0, step_in_epoch=step)
        rotate_checkpoints(d, keep=3)
    steps = sorted(os.path.basename(p)
                   for p in glob.glob(os.path.join(d, "step_*.ckpt")))
    assert steps == [step_checkpoint_name(s) for s in (4, 5, 6)]
    for name in ("best_model.ckpt", "last_model.ckpt", "preempt_model.ckpt"):
        assert os.path.exists(os.path.join(d, name))   # never rotate
    # the next save drops manifest entries of rotated-away files
    save_checkpoint(os.path.join(d, "last_model.ckpt"), _state(), epoch=1)
    manifest = read_manifest(d)
    assert step_checkpoint_name(1) not in manifest
    assert step_checkpoint_name(6) in manifest


# ---------------------------------------------------------------- portability

def test_checkpoint_portable_when_moved_without_manifest(tmp_path):
    """A checkpoint copied out of its directory (no manifest alongside)
    restores anywhere — durability metadata never became a load dependency,
    and params leaves carry no world-size/wrapper prefix."""
    src = str(tmp_path / "a" / "last_model.ckpt")
    st = _state(scale=3.0)
    save_checkpoint(src, st, epoch=2, seed=1)
    dst_dir = tmp_path / "b"
    dst_dir.mkdir()
    dst = str(dst_dir / "moved.ckpt")
    os.rename(src, dst)
    r = restore_for_resume(dst, _state())
    assert r.epoch == 2
    _leaves_equal(r.state.params, st.params)
    _leaves_equal(restore_params(dst, _state().params), st.params)


def test_restore_rejects_architecture_mismatch(tmp_path):
    path = str(tmp_path / "last_model.ckpt")
    save_checkpoint(path, _state(), epoch=1)
    other = TrainState.create({"w": np.zeros((5, 5), np.float32)},
                              make_optimizer(1e-3))
    with pytest.raises(ValueError, match="incompatible with model"):
        restore_for_resume(path, other)


# ---------------------------------------------------------------- marker

def test_preempt_marker_roundtrip(tmp_path):
    d = str(tmp_path)
    write_preempt_marker(d, "preempt_model.ckpt", epoch=4, step_in_epoch=2)
    marker = os.path.join(d, PREEMPT_MARKER)
    info = json.load(open(marker))
    assert info["checkpoint"] == "preempt_model.ckpt"
    assert (info["epoch"], info["step_in_epoch"]) == (4, 2)
    clear_preempt_marker(d)
    assert not os.path.exists(marker)
    clear_preempt_marker(d)                   # idempotent on missing
