"""End-to-end parity of ``edge_impl='fused'`` (interpret-mode Pallas) against
``edge_impl='plain'`` on the same FastEGNN weights: forward positions, train
loss, and gradients, within the kernel's bf16-stream tolerance. The workload
is built so BOTH fused sub-paths are exercised: a non-empty remote-edge tail
AND a trailing node block with no real nodes or edges (the nb-inference
regression of ADVICE #1)."""

import copy

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distegnn_tpu.models.fast_egnn import FastEGNN
from distegnn_tpu.ops.graph import pad_graphs
from distegnn_tpu.train.step import TrainState, make_loss_fn, make_train_step

BLOCK = 512
N_REAL = 4 * BLOCK          # blocks 0-3 hold real nodes
N_PAD = 5 * BLOCK           # block 4 is ALL padding (trailing empty block)
H = 16


def _graph(seed):
    """Random graph whose edges are mostly near-diagonal (in-window) with a
    deliberate far-block minority (remote tail)."""
    rng = np.random.default_rng(seed)
    rows, cols = [], []
    for b in range(4):                       # <= 384 edges per 512-node block
        r = rng.integers(b * BLOCK, (b + 1) * BLOCK, size=384)
        near = rng.integers(max(0, (b - 1) * BLOCK),
                            min(N_REAL, (b + 2) * BLOCK), size=384)
        far_block = (b + 3) % 4              # outside the 3-block window
        far = rng.integers(far_block * BLOCK, (far_block + 1) * BLOCK, size=384)
        c = np.where(rng.uniform(size=384) < 0.1, far, near)
        rows.append(r)
        cols.append(c)
    row = np.concatenate(rows)
    col = np.concatenate(cols)
    order = np.argsort(row, kind="stable")
    ei = np.stack([row[order], col[order]]).astype(np.int64)
    e = ei.shape[1]
    return {
        "node_feat": rng.normal(size=(N_REAL, 2)).astype(np.float32),
        "loc": rng.uniform(0, 1, size=(N_REAL, 3)).astype(np.float32),
        "vel": (rng.normal(size=(N_REAL, 3)) * 0.05).astype(np.float32),
        "target": rng.uniform(0, 1, size=(N_REAL, 3)).astype(np.float32),
        "edge_index": ei,
        "edge_attr": rng.normal(size=(e, 2)).astype(np.float32),
    }


@pytest.fixture(scope="module")
def batch():
    gb = pad_graphs([_graph(0), _graph(1)], max_nodes=N_PAD, edge_block=BLOCK,
                    edge_tile=BLOCK, edges_per_block=BLOCK, compute_pair=False,
                    split_remote=True)
    # the workload must genuinely exercise both fused sub-paths
    assert gb.remote_edge_mask is not None and gb.remote_edge_mask.sum() > 0
    assert gb.max_nodes == N_PAD  # trailing all-padding node block present
    return gb


def _model(edge_impl):
    return FastEGNN(node_feat_nf=2, edge_attr_nf=2, hidden_nf=H,
                    virtual_channels=2, n_layers=2, edge_impl=edge_impl)


def _remap_gcl(gcl):
    """plain (hoisted phi_e + CoordMLP phi_x) -> fused raw-weight tree."""
    gcl = dict(gcl)
    pe = dict(gcl.pop("phi_e"))
    px = gcl.pop("phi_x")
    td = pe["TorchDense_0"]["Dense_0"]
    m0 = px["MLP_0"]
    gcl["phi_e_fused"] = {
        "w1": pe["kernel"], "b1": pe["bias"],
        "w2": td["kernel"], "b2": td["bias"],
        "w3": m0["TorchDense_0"]["Dense_0"]["kernel"],
        "b3": m0["TorchDense_0"]["Dense_0"]["bias"],
        "w4": m0["TorchDense_1"]["Dense_0"]["kernel"],
    }
    return gcl


def _to_fused(params):
    pp = dict(copy.deepcopy(jax.device_get(params))["params"])
    for k in list(pp):
        if k.startswith("gcl_"):
            pp[k] = _remap_gcl(pp[k])
    return {"params": pp}


@pytest.fixture(scope="module")
def params_pair(batch):
    p_plain = jax.device_get(_model("plain").init(jax.random.PRNGKey(0), batch))
    return p_plain, _to_fused(p_plain)


def test_fused_forward_matches_plain(batch, params_pair):
    p_plain, p_fused = params_pair
    x_p, X_p = _model("plain").apply(p_plain, batch)
    x_f, X_f = _model("fused").apply(p_fused, batch)
    m = np.asarray(batch.node_mask)[..., None]
    np.testing.assert_allclose(np.asarray(x_f) * m, np.asarray(x_p) * m,
                               atol=2e-3, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(X_f), np.asarray(X_p),
                               atol=2e-3, rtol=1e-3)


def test_fused_grads_match_plain(batch, params_pair):
    from jax.flatten_util import ravel_pytree

    p_plain, p_fused = params_pair

    def loss(model, p):
        x, _ = model.apply(p, batch)
        return jnp.sum((x - batch.target) ** 2 * batch.node_mask[..., None])

    g_p = _to_fused(jax.grad(lambda p: loss(_model("plain"), p))(p_plain))
    g_f = jax.device_get(jax.grad(lambda p: loss(_model("fused"), p))(p_fused))
    flat_p, _ = ravel_pytree(g_p)
    flat_f, _ = ravel_pytree(g_f)
    scale = max(float(np.abs(flat_p).max()), 1e-3)
    np.testing.assert_allclose(flat_f / scale, flat_p / scale, atol=2e-2)


def test_fused_full_train_step_matches_plain(batch, params_pair):
    """The acceptance gate: one FULL train step (loss + grads + optimizer
    update) runs under edge_impl='fused' on CPU interpret mode, with the
    logged loss matching the plain step within bf16 tolerance."""
    p_plain, p_fused = params_pair
    tx = optax.adam(1e-3)
    losses = {}
    for impl, p in (("plain", p_plain), ("fused", p_fused)):
        step = make_train_step(_model(impl), tx, mmd_weight=0.0, mmd_sigma=1.5,
                               mmd_samples=2)
        state = TrainState.create(p, tx)
        new_state, metrics = jax.jit(step)(state, batch, jax.random.PRNGKey(3))
        assert int(new_state.step) == 1
        assert np.isfinite(float(metrics["loss"]))
        losses[impl] = float(metrics["loss"])
    np.testing.assert_allclose(losses["fused"], losses["plain"],
                               rtol=2e-3, atol=2e-4)


def test_fused_requires_split_remote_batch(batch):
    gb = batch.replace(remote_edge_index=None, remote_edge_attr=None,
                       remote_edge_mask=None)
    p = _model("fused").init(jax.random.PRNGKey(0), batch)
    with pytest.raises(ValueError, match="split_remote"):
        _model("fused").apply(p, gb)
