"""Tests for the data layer: n-body simulator physics invariants, pipeline
caching, loader determinism (SURVEY.md §4: physics-simulator self-checks +
runtime invariants become real tests)."""

import numpy as np
import pytest

from distegnn_tpu.data import (
    ChargedSystem,
    GraphDataset,
    GraphLoader,
    ShardedGraphLoader,
    generate_nbody_files,
    process_nbody_cutoff,
    simulate_trajectory,
)


def test_simulator_momentum_isolated():
    # isolated charged balls: pairwise equal-and-opposite forces conserve momentum
    rng = np.random.default_rng(0)
    sys_ = ChargedSystem(rng, n_isolated=20, delta_t=0.001)
    p0 = sys_.V.sum(axis=0)
    for _ in range(200):
        sys_.step()
    p1 = sys_.V.sum(axis=0)
    np.testing.assert_allclose(p0, p1, atol=1e-8)


def test_simulator_stick_constraints_preserved():
    rng = np.random.default_rng(1)
    sys_ = ChargedSystem(rng, n_isolated=4, n_stick=3, delta_t=0.001)
    lengths = [s["length"] for s in sys_.sticks]
    for _ in range(500):
        sys_.step()
    sys_.check()  # raises on violation (reference physical_objects.py:135-145)
    for s, l0 in zip(sys_.sticks, lengths):
        i0, i1 = s["idx"]
        assert abs(np.linalg.norm(sys_.X[i1] - sys_.X[i0]) - l0) < 1e-6


def test_simulator_hinge_constraints_preserved():
    rng = np.random.default_rng(2)
    sys_ = ChargedSystem(rng, n_isolated=2, n_hinge=2, delta_t=0.001)
    for _ in range(300):
        sys_.step()
    sys_.check()


def test_trajectory_shapes():
    rng = np.random.default_rng(3)
    loc, vel, charges, edges = simulate_trajectory(rng, length=500, sample_freq=100, n_isolated=10)
    assert loc.shape == (5, 10, 3)
    assert vel.shape == (5, 10, 3)
    assert charges.shape == (10, 1)
    np.testing.assert_allclose(edges, charges @ charges.T)


@pytest.fixture(scope="module")
def nbody_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("nbody")
    generate_nbody_files(
        str(d / "nbody_10"), n_isolated=10, num_train=6, num_valid=3, num_test=3,
        length=500, sample_freq=100, seed=7,
    )
    return str(d)


def test_generate_reference_file_layout(nbody_dir):
    import os
    loc = np.load(os.path.join(nbody_dir, "nbody_10", "loc_train_charged10_0_0_1.npy"))
    assert loc.shape == (6, 5, 10, 3)


def test_process_and_load(nbody_dir):
    paths = process_nbody_cutoff(nbody_dir, "nbody_10", max_samples=6, radius=-1,
                                 frame_0=1, frame_T=3, cutoff_rate=0.0, tag="charged10_0_0_1")
    ds = GraphDataset(paths[0])
    assert len(ds) == 6
    g = ds[0]
    assert g["node_feat"].shape == (10, 2)
    assert g["edge_index"].shape == (2, 90)  # full graph: 10*9
    assert g["edge_attr"].shape == (90, 2)
    # caching: second call returns same paths without recompute
    assert process_nbody_cutoff(nbody_dir, "nbody_10", max_samples=6, radius=-1,
                                frame_0=1, frame_T=3, cutoff_rate=0.0, tag="charged10_0_0_1") == paths


def test_cutoff_rate_drops_edges(nbody_dir):
    paths = process_nbody_cutoff(nbody_dir, "nbody_10", max_samples=6, radius=-1,
                                 frame_0=1, frame_T=3, cutoff_rate=0.5, tag="charged10_0_0_1")
    ds = GraphDataset(paths[0])
    assert ds[0]["edge_index"].shape[1] == 45  # int(90 * 0.5)


def test_loader_determinism_and_drop_last(nbody_dir):
    paths = process_nbody_cutoff(nbody_dir, "nbody_10", max_samples=6, radius=-1,
                                 frame_0=1, frame_T=3, cutoff_rate=0.0, tag="charged10_0_0_1")
    ds = GraphDataset(paths[0])
    la = GraphLoader(ds, batch_size=4, shuffle=True, seed=5)
    lb = GraphLoader(ds, batch_size=4, shuffle=True, seed=5)
    la.set_epoch(3); lb.set_epoch(3)
    assert len(la) == 1  # drop_last: 6 // 4
    a = next(iter(la)); b = next(iter(lb))
    np.testing.assert_array_equal(np.asarray(a.loc), np.asarray(b.loc))  # identical across "hosts"
    la.set_epoch(4)
    c = next(iter(la))
    assert not np.array_equal(np.asarray(a.loc), np.asarray(c.loc))  # reshuffled next epoch


def test_sharded_loader_stacks_partitions(nbody_dir):
    paths = process_nbody_cutoff(nbody_dir, "nbody_10", max_samples=6, radius=-1,
                                 frame_0=1, frame_T=3, cutoff_rate=0.0, tag="charged10_0_0_1")
    ds = GraphDataset(paths[0])
    sl = ShardedGraphLoader([ds, ds], batch_size=2, shuffle=False)
    batch = next(iter(sl))
    assert batch.loc.shape[0] == 2  # leading partition axis
    np.testing.assert_array_equal(batch.loc[0], batch.loc[1])
