"""Model-family tests: SE(3)/E(n) equivariance + jit/finite checks for every
model the factory serves (reference test coverage was FastEGNN-only,
equivariant_test.py; SURVEY.md §4 asks us to generalize it)."""

import numpy as np
import jax
import pytest

from distegnn_tpu.config import ConfigDict
from distegnn_tpu.models.basic import EGNN, GNN, FullMLP, LinearDynamics, RFVel
from distegnn_tpu.models.fast_rf import FastRF
from distegnn_tpu.models.fast_schnet import FastSchNet
from distegnn_tpu.models.registry import get_model
from distegnn_tpu.models.schnet import SchNet
from distegnn_tpu.ops.graph import pad_graphs
from distegnn_tpu.utils.rotate import random_rotate
from tests.test_equivariance import _random_graph, _transform


def _pair(rng, **kw):
    g = _random_graph(rng, **kw)
    R = random_rotate(rng).astype(np.float32)
    t = (rng.normal(size=(3,)) * 5).astype(np.float32)
    gb = pad_graphs([g], node_bucket=1, edge_bucket=1)
    gb_r = pad_graphs([_transform(g, R, t)], node_bucket=1, edge_bucket=1)
    return gb, gb_r, R, t


MODELS = {
    "TFN": lambda: __import__("distegnn_tpu.models.se3.dynamics", fromlist=["TFNDynamics"]
                              ).TFNDynamics(nf=8, n_layers=2, num_degrees=2),
    "SE3Transformer": lambda: __import__(
        "distegnn_tpu.models.se3.dynamics", fromlist=["SE3TransformerDynamics"]
    ).SE3TransformerDynamics(nf=8, n_layers=2, num_degrees=2, n_heads=2),
    "FastTFN": lambda: __import__("distegnn_tpu.models.fast_tfn", fromlist=["FastTFN"]
                                  ).FastTFN(node_feat_nf=1, node_attr_nf=0, edge_attr_nf=1,
                                            hidden_nf=16, virtual_channels=2, n_layers=2),
    "EGHN": lambda: __import__("distegnn_tpu.models.eghn", fromlist=["EGHN"]).EGHN(
        in_node_nf=1, in_edge_nf=1, hidden_nf=16, n_cluster=3,
        layer_per_block=2, layer_pooling=2),
    "FastRF": lambda: FastRF(edge_attr_nf=1, hidden_nf=32, virtual_channels=3, n_layers=3),
    "FastSchNet": lambda: FastSchNet(node_feat_nf=1, edge_attr_nf=1, hidden_nf=32,
                                     virtual_channels=3, n_layers=2, cutoff=10.0),
    "SchNet": lambda: SchNet(hidden_channels=32, num_interactions=3, cutoff=10.0),
    "EGNN": lambda: EGNN(n_layers=3, in_node_nf=1, in_edge_nf=1, hidden_nf=32, with_v=True),
    "RF": lambda: RFVel(hidden_nf=32, edge_attr_nf=1, n_layers=3),
    "Linear": lambda: LinearDynamics(),
}


@pytest.mark.parametrize("name", sorted(MODELS))
def test_model_se3_equivariance(rng, name):
    model = MODELS[name]()
    gb, gb_r, R, t = _pair(rng)
    params = model.init(jax.random.PRNGKey(0), gb)
    out, _ = model.apply(params, gb)
    out_r, _ = model.apply(params, gb_r)
    np.testing.assert_allclose(np.asarray(out[0]) @ R + t, np.asarray(out_r[0]),
                               atol=1e-4, rtol=0)


@pytest.mark.parametrize("name", sorted(MODELS) + ["GNN", "MLP"])
def test_model_jits_and_is_finite(rng, name):
    builders = dict(MODELS,
                    GNN=lambda: GNN(n_layers=2, in_node_nf=1, in_edge_nf=1, hidden_nf=16),
                    MLP=lambda: FullMLP(hidden_nf=16))
    model = builders[name]()
    graphs = [_random_graph(rng, n=8, e=14) for _ in range(3)]
    gb = pad_graphs(graphs)
    params = model.init(jax.random.PRNGKey(1), gb)
    out, _ = jax.jit(model.apply)(params, gb)
    assert out.shape == (3, gb.max_nodes, 3)
    assert np.all(np.isfinite(np.asarray(out)))


def _remap_fused_mlp(node):
    """concat tree phi_e/TorchDense_0/Dense_0 (fused first Dense) +
    TorchDense_1 -> hoisted tree phi_e/{kernel,bias} + TorchDense_0."""
    return {
        "kernel": node["TorchDense_0"]["Dense_0"]["kernel"],
        "bias": node["TorchDense_0"]["Dense_0"]["bias"],
        "TorchDense_0": node["TorchDense_1"],
    }


def _assert_hoisted_equals_concat(m_h, m_c, gb, remap):
    """Shared hoisting-equivalence check: remap the fused params of the
    concat model into the hoisted tree, then compare outputs and per-leaf
    gradients (leaf-by-leaf through the SAME remap — catches misrouted
    cotangents that a scalar-sum comparison would let cancel)."""
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    p_c = jax.device_get(m_c.init(jax.random.PRNGKey(0), gb))
    p_h = remap(p_c)
    x_c, X_c = m_c.apply(p_c, gb)
    x_h, X_h = m_h.apply(p_h, gb)
    np.testing.assert_allclose(x_h, x_c, atol=1e-5)
    np.testing.assert_allclose(X_h, X_c, atol=1e-5)

    def loss(m, p):
        x, _ = m.apply(p, gb)
        return jnp.sum((x - gb.target) ** 2 * gb.node_mask[..., None])

    flat_c = ravel_pytree(remap(jax.grad(lambda p: loss(m_c, p))(p_c)))[0]
    flat_h = ravel_pytree(jax.grad(lambda p: loss(m_h, p))(p_h))[0]
    scale = np.maximum(np.abs(flat_c).max(), 1.0)
    np.testing.assert_allclose(flat_h / scale, flat_c / scale, atol=1e-5)


def test_hoisted_edge_mlp_equals_concat_mlp(rng):
    """FastEGNN hoist_edge_mlp=True computes the SAME function as the
    reference-shaped concat MLP."""
    from distegnn_tpu.models.fast_egnn import FastEGNN

    g = _random_graph(rng, n=40, e=120, feat_nf=1, edge_nf=2)
    gb = pad_graphs([g], node_bucket=1, edge_bucket=1)
    kw = dict(node_feat_nf=1, edge_attr_nf=2, hidden_nf=16,
              virtual_channels=2, n_layers=2)

    def remap(tree):
        out = jax.device_get(tree)
        for i in range(kw["n_layers"]):
            gcl = out["params"][f"gcl_{i}"]
            gcl["phi_e"] = _remap_fused_mlp(gcl["phi_e"])
        return out

    _assert_hoisted_equals_concat(FastEGNN(**kw, hoist_edge_mlp=True),
                                  FastEGNN(**kw, hoist_edge_mlp=False),
                                  gb, remap)


def test_fastschnet_hoisted_equals_concat(rng):
    """FastSchNet hoisting covers BOTH phi_e and the SchNet coordinate gate
    (concat orders differ: MLP is [h_row, h_col, scalars], gate is
    [gauss, h_row, h_col] — the hoisted modules slice to match, so the raw
    kernels map 1:1)."""
    g = _random_graph(rng, n=40, e=120, feat_nf=1, edge_nf=2)
    gb = pad_graphs([g], node_bucket=1, edge_bucket=1)
    kw = dict(node_feat_nf=1, edge_attr_nf=2, hidden_nf=16,
              virtual_channels=2, n_layers=2, cutoff=2.0)

    def remap(tree):
        out = jax.device_get(tree)
        for i in range(kw["n_layers"]):
            gcl = out["params"][f"gcl_{i}"]
            gcl["phi_e"] = _remap_fused_mlp(gcl["phi_e"])
            gate = gcl["schnet_coord_update"]["Dense_0"]
            gcl["schnet_coord_update"] = {"kernel": gate["kernel"],
                                          "bias": gate["bias"]}
        return out

    _assert_hoisted_equals_concat(FastSchNet(**kw, hoist_edge_mlp=True),
                                  FastSchNet(**kw, hoist_edge_mlp=False),
                                  gb, remap)


def test_fast_models_padding_invariance(rng):
    """Padded batches must give identical real-node outputs (masking audit
    for the new families, mirroring the FastEGNN test)."""
    for build in (MODELS["FastRF"], MODELS["FastSchNet"], MODELS["SchNet"],
                  MODELS["EGNN"], MODELS["RF"]):
        model = build()
        g = _random_graph(rng)
        tight = pad_graphs([g], node_bucket=1, edge_bucket=1)
        padded = pad_graphs([g], max_nodes=16, max_edges=64)
        params = model.init(jax.random.PRNGKey(0), tight)
        out_tight, _ = model.apply(params, tight)
        out_pad, _ = model.apply(params, padded)
        np.testing.assert_allclose(np.asarray(out_tight[0]), np.asarray(out_pad[0, :10]),
                                   atol=1e-4, rtol=0)


def test_fast_schnet_normalize_equivariance(rng):
    model = FastSchNet(node_feat_nf=1, edge_attr_nf=1, hidden_nf=32,
                       virtual_channels=3, n_layers=2, cutoff=10.0, normalize=True)
    gb, gb_r, R, t = _pair(rng)
    params = model.init(jax.random.PRNGKey(0), gb)
    out, _ = model.apply(params, gb)
    out_r, _ = model.apply(params, gb_r)
    np.testing.assert_allclose(np.asarray(out[0]) @ R + t, np.asarray(out_r[0]),
                               atol=1e-4, rtol=0)


def test_egcl_classic_and_egmn_run(rng):
    """Library classes outside the factory (reference E_GCL basic.py:69-164,
    EGMN basic.py:339-356) stay importable and equivariant-sane."""
    from distegnn_tpu.models.basic import EGCLClassic, EGMN

    g = _random_graph(rng)
    gb = pad_graphs([g], node_bucket=1, edge_bucket=1)
    layer = EGCLClassic(hidden_nf=16, edge_attr_nf=1)
    h0 = np.tile(gb.node_feat, (1, 1, 16)).astype(np.float32)
    params = layer.init(jax.random.PRNGKey(0), h0, gb.loc, gb)
    h1, x1 = layer.apply(params, h0, gb.loc, gb)
    assert np.all(np.isfinite(np.asarray(x1)))

    net = EGMN(n_layers=2, n_vector_input=2, hidden_dim=8)
    Z = [rng.normal(size=(5, 3)).astype(np.float32) for _ in range(2)]
    s = rng.normal(size=(5, 8)).astype(np.float32)
    p = net.init(jax.random.PRNGKey(1), Z, s)
    vec, sc = net.apply(p, Z, s)
    R = random_rotate(rng).astype(np.float32)
    vec_r, sc_r = net.apply(p, [z @ R for z in Z], s)
    np.testing.assert_allclose(np.asarray(vec) @ R, np.asarray(vec_r), atol=1e-5)


def test_equivariant_scalar_net(rng):
    """The O(n)-universal scalarization block (reference basic.py:194-238,
    serving EGMN/EGHN): output vector rotates with the inputs, scalar is
    invariant."""
    from distegnn_tpu.models.basic import EquivariantScalarNet

    net = EquivariantScalarNet(n_vector_input=2, hidden_dim=16)
    Z = rng.normal(size=(5, 3, 2)).astype(np.float32)
    s = rng.normal(size=(5, 4)).astype(np.float32)
    params = net.init(jax.random.PRNGKey(0), Z, s)
    vec, scal = net.apply(params, Z, s)
    R = random_rotate(rng).astype(np.float32)
    vec_r, scal_r = net.apply(params, np.einsum("ndk,de->nek", Z, R), s)
    np.testing.assert_allclose(np.asarray(vec) @ R, np.asarray(vec_r), atol=1e-5)
    np.testing.assert_allclose(np.asarray(scal), np.asarray(scal_r), atol=1e-5)


def test_registry_serves_all_families(rng):
    """get_model dispatch parity with reference main.py:58-92."""
    base = dict(model_name="FastEGNN", normalize=False, hidden_nf=16, n_layers=2,
                virtual_channels=2, node_feat_nf=1, node_attr_nf=0, edge_attr_nf=1,
                checkpoint=None)
    gb = pad_graphs([_random_graph(rng)])
    for name in ("FastEGNN", "FastRF", "FastSchNet", "SchNet", "EGNN", "RF", "Linear",
                 "TFN", "FastTFN", "SE3Transformer"):
        cfg = ConfigDict(dict(base, model_name=name))
        model = get_model(cfg, world_size=1, dataset_name="nbody_100")
        params = model.init(jax.random.PRNGKey(0), gb)
        out, _ = model.apply(params, gb)
        assert np.all(np.isfinite(np.asarray(out))), name
