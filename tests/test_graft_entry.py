"""The driver-facing hooks in __graft_entry__.py must stay runnable: the
round-end validation calls entry() (single-chip compile check) and
dryrun_multichip(n) (full distributed step on a virtual CPU mesh). A latent
static-metadata mismatch in the dryrun's batch construction once broke the
validation without any suite test noticing (2026-07-31) — pin both hooks
here under the same CPU-mesh conditions the driver uses."""

import sys
import os

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft_entry  # noqa: E402


def test_entry_forward_jits():
    fn, args = graft_entry.entry()
    out = jax.jit(fn)(*args)
    for leaf in jax.tree.leaves(out):
        assert np.all(np.isfinite(np.asarray(leaf)))


def test_dryrun_multichip_8():
    # asserts internally (finiteness, metis unevenness); conftest provides
    # the 8 virtual CPU devices the driver's env would. The dryrun's 3D-mesh
    # tensor-parity leg is skipped here ONLY because tier-1 already runs it
    # as dedicated cases (test_tensor_parallel.py parity tests) — paying for
    # it twice would push the suite past its wall budget.
    graft_entry.dryrun_multichip(8, tensor_parity=False)


def test_bench_cpu_competitors_classification(tmp_path):
    """bench.py's measurement-window pause must STOP only provably CPU-pinned
    repo workloads: an unpinned main.py (possibly a live TPU client) and the
    bench's own ancestors must never be candidates (SIGSTOPping a live
    client wedges the tunnel; freezing an ancestor deadlocks)."""
    import importlib.util
    import os
    import subprocess
    import sys
    import time

    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    fake = tmp_path / "fake_main.py"
    fake.write_text("import time; time.sleep(30)\n")
    env_cpu = dict(os.environ, JAX_PLATFORMS="cpu")
    env_tpu = {k: v for k, v in os.environ.items()
               if k not in ("JAX_PLATFORMS", "BENCH_PLATFORM")}
    cpu_proc = subprocess.Popen(
        [sys.executable, str(fake), "--config_path", "main.py --config_path x"],
        env=env_cpu)
    tpu_proc = subprocess.Popen(
        [sys.executable, str(fake), "--config_path", "main.py --config_path x"],
        env=env_tpu)
    try:
        time.sleep(0.5)
        pids, ambiguous = bench.cpu_competitors()
        assert cpu_proc.pid in pids          # CPU-pinned -> pausable
        assert tpu_proc.pid not in pids      # ambiguous -> untouchable
        assert tpu_proc.pid in ambiguous     # ...but surfaced as contention
        assert os.getpid() not in pids       # never our own process tree
        assert os.getppid() not in pids

        # already-stopped processes are not ours to resume -> not pausable
        os.kill(cpu_proc.pid, 19)  # SIGSTOP
        time.sleep(0.2)
        pids2, _ = bench.cpu_competitors()
        assert cpu_proc.pid not in pids2
    finally:
        cpu_proc.kill()
        tpu_proc.kill()
        cpu_proc.wait()
        tpu_proc.wait()
