"""The driver-facing hooks in __graft_entry__.py must stay runnable: the
round-end validation calls entry() (single-chip compile check) and
dryrun_multichip(n) (full distributed step on a virtual CPU mesh). A latent
static-metadata mismatch in the dryrun's batch construction once broke the
validation without any suite test noticing (2026-07-31) — pin both hooks
here under the same CPU-mesh conditions the driver uses."""

import sys
import os

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft_entry  # noqa: E402


def test_entry_forward_jits():
    fn, args = graft_entry.entry()
    out = jax.jit(fn)(*args)
    for leaf in jax.tree.leaves(out):
        assert np.all(np.isfinite(np.asarray(leaf)))


def test_dryrun_multichip_8():
    # asserts internally (finiteness, metis unevenness); conftest provides
    # the 8 virtual CPU devices the driver's env would
    graft_entry.dryrun_multichip(8)
