"""The promotion conveyor (distegnn_tpu/promote): publisher atomicity,
drift gauge math, the promoter's canary/shadow/gate state machine with a
synthetic clock, the trainer-side publish hook, the configs/*.yaml
coverage lint, and the end-to-end ``traffic_gen --promote`` chaos drill
(the PR's acceptance drill: two candidates under live traffic, a trainer
kill mid-publish, a canary kill mid-promotion, an injected-drift
rollback — zero lost requests and a coherent fleet version throughout).
"""

import json
import os
import subprocess
import sys
import time
from types import SimpleNamespace

import jax
import numpy as np
import pytest

from distegnn_tpu.models.fast_egnn import FastEGNN
from distegnn_tpu.obs.metrics import MetricsRegistry
from distegnn_tpu.promote.drift import DriftGauge
from distegnn_tpu.promote.promoter import (Promoter, fleet_coherent,
                                           watch_dir_from_config)
from distegnn_tpu.promote.publish import (CandidatePublisher,
                                          candidate_manifest_name,
                                          config_hash, list_candidates,
                                          read_candidate)
from distegnn_tpu.serve import InferenceEngine, RequestQueue
from distegnn_tpu.serve.buckets import synthetic_graph
from distegnn_tpu.serve.metrics import ServeMetrics
from distegnn_tpu.serve.registry import ModelEntry
from distegnn_tpu.train.checkpoint import save_checkpoint

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def tiny():
    from distegnn_tpu.ops.graph import pad_graphs

    model = FastEGNN(node_feat_nf=1, edge_attr_nf=2, hidden_nf=16,
                     virtual_channels=2, n_layers=2)
    g = synthetic_graph(26, seed=5)
    tight = pad_graphs([g], node_bucket=1, edge_bucket=1)
    params = model.init(jax.random.PRNGKey(0), tight)
    return SimpleNamespace(model=model, params=params, graph=g)


def _save_params(path, params):
    save_checkpoint(str(path),
                    SimpleNamespace(params=params, opt_state={}, step=0),
                    epoch=0)


def _mk_entry(tiny, n=2, name="m"):
    metrics = ServeMetrics()
    kw = dict(batch_deadline_ms=2.0, request_timeout_ms=30_000.0)
    engine = InferenceEngine(tiny.model, tiny.params, max_batch=2,
                             metrics=metrics)
    queue = RequestQueue(engine, metrics=metrics, **kw)
    extra = []
    for _ in range(n - 1):
        e2 = InferenceEngine(tiny.model, tiny.params, max_batch=2,
                             metrics=metrics)
        extra.append((e2, RequestQueue(e2, metrics=metrics, **kw)))
    return ModelEntry(name, engine, queue, feat_nf=1, edge_attr_nf=2,
                      extra_replicas=extra,
                      supervisor_opts=dict(heartbeat_s=3600.0))


# ---- publisher: atomicity, retention, verification --------------------------

def test_publish_writes_verified_candidate_with_no_tmp_residue(
        tiny, tmp_path):
    src = tmp_path / "src.ckpt"
    _save_params(src, tiny.params)
    watch = tmp_path / "conveyor"
    pub = CandidatePublisher(str(watch), history=4)
    mpath = pub.publish(str(src), step=12, val_loss=0.25,
                        config={"model": {"hidden_nf": 16}})
    assert os.path.basename(mpath) == candidate_manifest_name(12)
    assert list_candidates(str(watch)) == [12]
    assert not any(".tmp." in f for f in os.listdir(watch))
    man = read_candidate(str(watch), 12)
    assert man["step"] == 12 and man["val_loss"] == 0.25
    assert man["config_hash"] == config_hash({"model": {"hidden_nf": 16}})
    assert man["size"] == os.path.getsize(src)
    assert os.path.getsize(man["ckpt_path"]) == man["size"]


def test_publish_prunes_beyond_history_manifest_first(tiny, tmp_path):
    src = tmp_path / "src.ckpt"
    _save_params(src, tiny.params)
    watch = tmp_path / "conveyor"
    pub = CandidatePublisher(str(watch), history=2)
    for step in (1, 2, 3, 4):
        pub.publish(str(src), step=step)
    assert list_candidates(str(watch)) == [3, 4]
    # withdrawn candidates lose BOTH files, not just the manifest
    assert sorted(os.listdir(watch)) == [
        "step_0000000003.ckpt", candidate_manifest_name(3),
        "step_0000000004.ckpt", candidate_manifest_name(4)]


def test_publish_sweeps_orphan_tmp_from_a_killed_publisher(tiny, tmp_path):
    src = tmp_path / "src.ckpt"
    _save_params(src, tiny.params)
    watch = tmp_path / "conveyor"
    os.makedirs(watch)
    orphan = watch / "step_0000000007.ckpt.tmp.abc123"
    orphan.write_bytes(b"torn")
    CandidatePublisher(str(watch)).publish(str(src), step=8)
    assert not orphan.exists()
    assert list_candidates(str(watch)) == [8]


def test_read_candidate_rejects_torn_and_missing(tiny, tmp_path):
    src = tmp_path / "src.ckpt"
    _save_params(src, tiny.params)
    watch = tmp_path / "conveyor"
    pub = CandidatePublisher(str(watch))
    pub.publish(str(src), step=5)
    ckpt = watch / "step_0000000005.ckpt"
    blob = ckpt.read_bytes()

    ckpt.write_bytes(blob[:-16])                       # truncated
    with pytest.raises(ValueError, match="size mismatch"):
        read_candidate(str(watch), 5)
    ckpt.write_bytes(blob[:-1] + bytes([blob[-1] ^ 0xFF]))  # bit-rot
    with pytest.raises(ValueError, match="crc32 mismatch"):
        read_candidate(str(watch), 5)
    ckpt.unlink()                                      # withdrawn bytes
    with pytest.raises(ValueError, match="missing checkpoint"):
        read_candidate(str(watch), 5)
    (watch / candidate_manifest_name(5)).write_text("{not json")
    with pytest.raises(ValueError, match="unreadable manifest"):
        read_candidate(str(watch), 5)
    with pytest.raises(ValueError, match="unreadable manifest"):
        read_candidate(str(watch), 99)                 # never published


def test_config_hash_is_order_stable():
    assert config_hash({"a": 1, "b": 2}) == config_hash({"b": 2, "a": 1})
    assert config_hash({"a": 1}) != config_hash({"a": 2})
    assert config_hash(None) is None


# ---- drift gauge ------------------------------------------------------------

def test_drift_gauge_relative_l2_and_ceiling_verdict():
    g = DriftGauge(ceiling=0.05, min_samples=2)
    live = np.ones((8, 3))
    d = g.observe("n26", live, live * 1.01)
    assert d == pytest.approx(0.01, rel=1e-6)
    assert not g.drifted() and not g.decided()
    g.observe("n26", live, live * 1.02)
    assert g.samples == 2 and g.decided() and not g.drifted()
    snap = g.snapshot()["n26"]
    assert snap["count"] == 2 and snap["nonfinite"] == 0
    assert snap["mean"] == pytest.approx(0.015, abs=1e-6)
    assert snap["max"] == pytest.approx(0.02, abs=1e-6)
    # a third sample shifts the rung mean over the ceiling
    g.observe("n26", live, live * 1.2)
    assert g.drifted()


def test_drift_gauge_nonfinite_or_shape_mismatch_drifts():
    g = DriftGauge(ceiling=10.0, min_samples=100)
    live = np.ones((4, 3))
    bad = live.copy()
    bad[0, 0] = np.nan
    assert g.observe("n26", live, bad) == float("inf")
    assert g.drifted() and g.decided()   # no point waiting to reject

    g2 = DriftGauge(ceiling=10.0)
    g2.observe("n26", live, np.ones((5, 3)))
    assert g2.drifted()


def test_drift_gauge_exports_per_rung_gauges():
    g = DriftGauge(ceiling=0.05)
    g.observe("n26", np.ones((4, 3)), np.ones((4, 3)) * 1.01)
    reg = MetricsRegistry()
    g.export(reg)
    assert reg.gauge("promote/drift_n26_mean").value == pytest.approx(
        0.01, abs=1e-5)
    assert reg.gauge("promote/drift_n26_max").value == pytest.approx(
        0.01, abs=1e-5)


# ---- promoter state machine (synthetic clock, real replicas) ----------------

def _mk_promoter(entry, watch, monitor=None, **over):
    reg = SimpleNamespace(names=lambda: [entry.name],
                          get=lambda n: {entry.name: entry}[n])
    knobs = dict(enable=True, watch_dir=str(watch), interval_s=3600.0,
                 shadow_sample=1.0, min_shadow=2, gate_timeout_s=10.0,
                 drift_ceiling=0.05, max_error_rate=0.0)
    knobs.update(over)
    return Promoter(reg, monitor, config=knobs)


def _publish(tiny, tmp_path, watch, step, scale=1.0001, name="cand.ckpt"):
    params = jax.tree.map(lambda x: x * scale, tiny.params)
    src = tmp_path / name
    _save_params(src, params)
    CandidatePublisher(str(watch)).publish(str(src), step=step)


def _feed_shadows(pm, entry, tiny, n, live_scale=1.0):
    """Tee n live predicts (optionally with distorted live outputs, to
    force a drift verdict deterministically) and wait for the shadow
    futures to land in the gauge."""
    run = pm._canary
    for i in range(n):
        g = dict(tiny.graph)
        out = entry.queue.submit(dict(g)).result(timeout=60.0)
        pm.tee(entry.name, g, None, f"r{i}", np.asarray(out) * live_scale)
    deadline = time.monotonic() + 30.0
    while (run.gauge.samples + run.shadow_errors < n
           and time.monotonic() < deadline):
        time.sleep(0.02)


def test_promoter_promotes_through_canary_and_shadow(tiny, tmp_path):
    entry = _mk_entry(tiny, n=2)
    entry.start()
    entry.warmup([26])
    pm = _mk_promoter(entry, tmp_path / "conveyor")
    try:
        _publish(tiny, tmp_path, tmp_path / "conveyor", step=5)
        pm.tick(now=0.0)
        st = pm.status()
        assert st["state"] == "canary" and st["canary"]["step"] == 5
        # the canary slice is OUT of live rotation while shadowing
        cidx = st["canary"]["replica"]
        assert entry.replicas.quarantined() == {cidx}
        assert st["fleet_coherent"] is False   # mid-canary: undecided

        _feed_shadows(pm, entry, tiny, 2)
        pm.tick(now=1.0)
        assert pm.promoted == 1 and entry.params_version == 1
        assert pm.results[-1]["outcome"] == "promoted"
        assert pm.results[-1]["shadow"]["teed"] == 2
        assert not entry.replicas.quarantined()
        assert fleet_coherent(entry)
        st = pm.status()
        assert st["state"] == "idle" and st["fleet_step"] == 5
        assert st["fleet_coherent"] is True and st["last_step"] == 5
    finally:
        pm.stop()
        entry.stop()


def test_promoter_rolls_back_on_drift(tiny, tmp_path):
    entry = _mk_entry(tiny, n=2)
    entry.start()
    entry.warmup([26])
    pm = _mk_promoter(entry, tmp_path / "conveyor")
    old = entry.engine.params
    try:
        _publish(tiny, tmp_path, tmp_path / "conveyor", step=5)
        pm.tick(now=0.0)
        cidx = pm.status()["canary"]["replica"]
        # distorted "live" outputs: canary-vs-live divergence ~1, far over
        # the 0.05 ceiling, without depending on candidate param deltas
        _feed_shadows(pm, entry, tiny, 2, live_scale=2.0)
        pm.tick(now=1.0)
        assert pm.rolled_back == 1 and entry.params_version == 0
        assert pm.results[-1]["outcome"] == "rolled_back"
        assert pm.results[-1]["reason"] == "drift"
        # the canary replica is re-pinned to the live version and released
        assert entry.replicas.replicas[cidx].engine.params is old
        assert not entry.replicas.quarantined()
        assert fleet_coherent(entry)
    finally:
        pm.stop()
        entry.stop()


def test_promoter_rolls_back_when_canary_dies(tiny, tmp_path):
    entry = _mk_entry(tiny, n=2)
    entry.start()
    entry.warmup([26])
    pm = _mk_promoter(entry, tmp_path / "conveyor")
    try:
        _publish(tiny, tmp_path, tmp_path / "conveyor", step=5)
        pm.tick(now=0.0)
        run = pm._canary
        assert run is not None
        run.replica.healthy = lambda: False   # SIGKILL's observable effect
        pm.tick(now=0.5)
        assert pm.results[-1] == {"step": 5, "outcome": "rolled_back",
                                  "reason": "canary_died",
                                  "shadow": pm.results[-1]["shadow"]}
        assert not entry.replicas.quarantined()
        assert entry.params_version == 0
    finally:
        pm.stop()
        entry.stop()


def test_promoter_rolls_back_on_insufficient_shadow(tiny, tmp_path):
    entry = _mk_entry(tiny, n=2)
    entry.start()
    entry.warmup([26])
    pm = _mk_promoter(entry, tmp_path / "conveyor", gate_timeout_s=5.0)
    try:
        _publish(tiny, tmp_path, tmp_path / "conveyor", step=5)
        pm.tick(now=0.0)
        pm.tick(now=4.9)     # inside the gate window: still canarying
        assert pm.status()["state"] == "canary"
        pm.tick(now=5.1)     # timed out with ZERO shadow evidence
        assert pm.results[-1]["outcome"] == "rolled_back"
        assert pm.results[-1]["reason"] == "insufficient_shadow"
    finally:
        pm.stop()
        entry.stop()


def test_promoter_slo_gate_blocks_promotion(tiny, tmp_path):
    entry = _mk_entry(tiny, n=2)
    entry.start()
    entry.warmup([26])
    monitor = SimpleNamespace(
        window_snapshot=lambda now=None: {"error_rate": 0.5})
    pm = _mk_promoter(entry, tmp_path / "conveyor", monitor=monitor)
    try:
        _publish(tiny, tmp_path, tmp_path / "conveyor", step=5)
        pm.tick(now=0.0)
        _feed_shadows(pm, entry, tiny, 2)
        pm.tick(now=1.0)
        assert pm.results[-1]["outcome"] == "rolled_back"
        assert pm.results[-1]["reason"] == "slo"
        assert entry.params_version == 0 and fleet_coherent(entry)
    finally:
        pm.stop()
        entry.stop()


def test_promoter_rejects_torn_candidate_without_canarying(tiny, tmp_path):
    entry = _mk_entry(tiny, n=2)
    entry.start()
    entry.warmup([26])
    watch = tmp_path / "conveyor"
    pm = _mk_promoter(entry, watch)
    try:
        _publish(tiny, tmp_path, watch, step=5)
        ckpt = watch / "step_0000000005.ckpt"
        ckpt.write_bytes(ckpt.read_bytes()[:-8])
        pm.tick(now=0.0)
        assert pm.rejected == 1
        assert pm.results[-1]["outcome"] == "rejected"
        assert pm.results[-1]["reason"].startswith("verify:")
        # spent, never retried: the conveyor position moved past it
        assert pm.last_step == 5 and pm.status()["state"] == "idle"
        assert not entry.replicas.quarantined()
    finally:
        pm.stop()
        entry.stop()


def test_promoter_newest_candidate_wins(tiny, tmp_path, monkeypatch):
    entry = _mk_entry(tiny, n=2)
    entry.start()
    entry.warmup([26])
    watch = tmp_path / "conveyor"
    pm = _mk_promoter(entry, watch)
    events = []
    import distegnn_tpu.promote.promoter as pmod
    monkeypatch.setattr(pmod.obs, "event",
                        lambda name, **kw: events.append((name, kw)))
    try:
        _publish(tiny, tmp_path, watch, step=5, name="a.ckpt")
        _publish(tiny, tmp_path, watch, step=7, name="b.ckpt")
        pm.tick(now=0.0)
        assert pm.status()["canary"]["step"] == 7
        skips = [kw for n, kw in events if n == "promote/candidates_skipped"]
        assert skips and skips[0]["skipped"] == [5] and skips[0]["chosen"] == 7
    finally:
        pm.stop()
        entry.stop()


def test_promoter_single_replica_falls_through_to_direct_swap(
        tiny, tmp_path):
    entry = _mk_entry(tiny, n=1)
    entry.start()
    entry.warmup([26])
    pm = _mk_promoter(entry, tmp_path / "conveyor")
    try:
        _publish(tiny, tmp_path, tmp_path / "conveyor", step=5)
        pm.tick(now=0.0)
        # no slice to spare: the plain blue/green swap promoted directly
        assert pm.promoted == 1 and entry.params_version == 1
        assert pm.results[-1]["outcome"] == "promoted"
        assert pm.results[-1].get("direct") is True
        assert fleet_coherent(entry)
    finally:
        pm.stop()
        entry.stop()


def test_watch_dir_from_config():
    assert watch_dir_from_config({"promote": {"watch_dir": "/c"}}) == "/c"
    assert watch_dir_from_config({}) == ""
    assert watch_dir_from_config(SimpleNamespace()) == ""


# ---- trainer end: publish-on-rotation hook ----------------------------------

def test_cadence_saver_publishes_rotated_checkpoint(tiny, tmp_path):
    from distegnn_tpu.train.trainer import CadenceSaver

    watch = tmp_path / "conveyor"
    pub = CandidatePublisher(str(watch))
    saver = CadenceSaver(str(tmp_path / "ckpts"), interval_s=1e-9, keep=3,
                         config={"seed": 1}, seed=1, enabled=True,
                         publisher=pub)
    saver.last_val_loss = 0.125
    saver._last = float("-inf")
    state = SimpleNamespace(params=tiny.params, opt_state={}, step=42)
    saver.maybe_save(state, completed_epoch=0, step_in_epoch=3)
    assert saver.saves == 1
    assert list_candidates(str(watch)) == [42]
    man = read_candidate(str(watch), 42)
    assert man["val_loss"] == 0.125
    assert man["config_hash"] == config_hash({"seed": 1})


def test_cadence_saver_survives_publish_failure(tiny, tmp_path):
    from distegnn_tpu.train.trainer import CadenceSaver

    class _Exploding:
        def publish(self, *a, **kw):
            raise OSError("conveyor full")

    saver = CadenceSaver(str(tmp_path / "ckpts"), interval_s=1e-9, keep=3,
                         config=None, seed=1, enabled=True,
                         publisher=_Exploding())
    saver._last = float("-inf")
    state = SimpleNamespace(params=tiny.params, opt_state={}, step=7)
    saver.maybe_save(state, completed_epoch=0, step_in_epoch=0)  # no raise
    assert saver.saves == 1   # the checkpoint itself landed


def test_rotation_emits_obs_event(tiny, tmp_path, monkeypatch):
    import distegnn_tpu.train.checkpoint as ckpt_mod

    for step in (1, 2, 3):
        _save_params(tmp_path / f"step_{step:010d}.ckpt", tiny.params)
    events = []
    monkeypatch.setattr(ckpt_mod.obs, "event",
                        lambda name, **kw: events.append((name, kw)))
    removed = ckpt_mod.rotate_checkpoints(str(tmp_path), keep=1)
    assert len(removed) == 2
    rot = [kw for n, kw in events if n == "ckpt/rotate"]
    assert rot == [{"step": 3, "bytes": os.path.getsize(
        tmp_path / "step_0000000003.ckpt"), "kept": 1, "removed": 2}]


# ---- config lint: yaml section coverage -------------------------------------

def _find_violations():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from check_config_keys import find_violations
    finally:
        sys.path.pop(0)
    return find_violations


def test_yaml_lint_flags_unregistered_top_level_section(tmp_path):
    (tmp_path / "x.yaml").write_text(
        "seed: 1\npromote:\n  enable: true\nbogus_section:\n  a: 1\n")
    out = _find_violations()(autoscale_path=None, promoter_path=None,
                             configs_dir=str(tmp_path))
    msgs = [msg for _, _, msg in out]
    assert any("bogus_section" in m and "_DEFAULTS" in m for m in msgs)
    assert not any("'promote:'" in m for m in msgs)


def test_yaml_lint_accepts_all_shipped_configs():
    out = _find_violations()(autoscale_path=None, promoter_path=None)
    assert [msg for _, _, msg in out if "top-level section" in msg] == []


# ---- the acceptance drill ---------------------------------------------------

def _run_promote_drill(tmp_path, extra=()):
    obs_dir = tmp_path / "tg"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "traffic_gen.py"),
         "--config_path", os.path.join(REPO, "configs",
                                       "nbody_promote.yaml"),
         "--promote", "--requests", "80", "--rate", "20",
         "--mix", "predict=0.8,session=0.2", "--sizes", "24,48",
         "--seed", "7", "--obs-dir", str(obs_dir), *extra],
        capture_output=True, text=True, cwd=REPO, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr
    lines = [ln for ln in r.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, r.stdout
    return json.loads(lines[0])


def _assert_drill(rec):
    pr = rec["promote"]
    assert pr.get("error") is None, pr
    ph = pr["phases"]
    # phase 1: a published candidate promoted through canary + shadow
    assert ph["promote"]["outcome"] == "promoted"
    # phase 2: trainer SIGKILLed mid-publish — orphan tmp only, the
    # conveyor never saw a half-candidate
    assert ph["trainer_kill"]["ok"] is True
    assert ph["trainer_kill"]["orphan_tmp"] is True
    assert ph["trainer_kill"]["manifest_appeared"] is False
    # phase 3: canary killed mid-promotion — immediate rollback
    assert ph["canary_kill"]["outcome"] == "rolled_back"
    assert ph["canary_kill"]["reason"] == "canary_died"
    # phase 4: injected drift rolled back on the gauge
    assert ph["drift"]["outcome"] == "rolled_back"
    assert ph["drift"]["reason"] == "drift"
    assert pr["tmp_swept"] is True
    assert pr["readyz"]["fleet_coherent"] is True
    assert pr["ok"] is True
    # zero lost requests across every injection
    assert rec["lost"] == 0 and rec["errors"] == 0
    assert rec["completed"] == rec["requests"]


def test_promotion_conveyor_drill_thread_backend(tmp_path):
    """The PR's acceptance drill from ONE ``traffic_gen --promote`` run:
    candidates published under live traffic promote through canary +
    shadow, a trainer kill mid-publish leaves only a swept tmp orphan, a
    canary kill mid-promotion and an injected-drift candidate both roll
    back automatically, with zero lost requests and a coherent fleet
    version on /readyz at the end."""
    _assert_drill(_run_promote_drill(tmp_path))


@pytest.mark.slow
@pytest.mark.process
def test_promotion_conveyor_drill_process_workers(tmp_path):
    """Same drill with process-isolated workers: the canary kill is a real
    SIGKILL of the worker child."""
    rec = _run_promote_drill(tmp_path, extra=("--workers", "process"))
    _assert_drill(rec)
    assert rec["promote"]["phases"]["canary_kill"]["killed_via"] == "kill9"
