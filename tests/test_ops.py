"""Numerical parity tests for the ops library against small dense references
(SURVEY.md §4 implication (2))."""

import numpy as np
import jax.numpy as jnp

from distegnn_tpu.ops import (
    segment_sum, segment_mean, masked_mean,
    radius_graph_np, full_graph_np, cutoff_edges_np, pad_graphs,
)


def test_segment_sum_matches_dense(rng):
    data = rng.normal(size=(20, 4)).astype(np.float32)
    ids = rng.integers(0, 5, size=20)
    out = segment_sum(jnp.asarray(data), jnp.asarray(ids), 5)
    expect = np.zeros((5, 4), np.float32)
    for i, s in enumerate(ids):
        expect[s] += data[i]
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5)


def test_segment_mean_empty_segment_is_zero(rng):
    data = rng.normal(size=(6, 3)).astype(np.float32)
    ids = np.array([0, 0, 1, 1, 1, 3])  # segment 2 empty
    out = np.asarray(segment_mean(jnp.asarray(data), jnp.asarray(ids), 4))
    np.testing.assert_allclose(out[0], data[:2].mean(0), rtol=1e-5)
    np.testing.assert_allclose(out[1], data[2:5].mean(0), rtol=1e-5)
    np.testing.assert_allclose(out[2], 0.0)
    np.testing.assert_allclose(out[3], data[5], rtol=1e-5)


def test_segment_mean_respects_mask(rng):
    data = rng.normal(size=(8, 2)).astype(np.float32)
    ids = np.array([0, 0, 0, 1, 1, 0, 0, 0])
    mask = np.array([1, 1, 1, 1, 1, 0, 0, 0], np.float32)  # last 3 are padding
    out = np.asarray(segment_mean(jnp.asarray(data), jnp.asarray(ids), 2, mask=jnp.asarray(mask)))
    np.testing.assert_allclose(out[0], data[:3].mean(0), rtol=1e-5)
    np.testing.assert_allclose(out[1], data[3:5].mean(0), rtol=1e-5)


def test_masked_mean(rng):
    data = rng.normal(size=(2, 5, 3)).astype(np.float32)
    mask = np.array([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], np.float32)
    out = np.asarray(masked_mean(jnp.asarray(data), jnp.asarray(mask), axis=1))
    np.testing.assert_allclose(out[0], data[0, :3].mean(0), rtol=1e-5)
    np.testing.assert_allclose(out[1], data[1].mean(0), rtol=1e-5)


def test_full_graph_count():
    ei = full_graph_np(100)
    assert ei.shape == (2, 9900)  # reference n-body: N=100 -> E=9900
    assert not np.any(ei[0] == ei[1])


def test_radius_graph_matches_bruteforce(rng):
    pos = rng.uniform(0, 1, size=(60, 3))
    r = 0.3
    ei = radius_graph_np(pos, r)
    # brute force
    d = np.linalg.norm(pos[:, None] - pos[None, :], axis=-1)
    expect = np.argwhere((d < r) & ~np.eye(60, dtype=bool))
    got = set(map(tuple, ei.T.tolist()))
    assert got == set(map(tuple, expect.tolist()))


def test_cutoff_edges(rng):
    pos = rng.uniform(0, 1, size=(30, 3))
    ei = radius_graph_np(pos, 0.5)
    out = cutoff_edges_np(ei, pos, 0.4)
    # same truncation formula as the implementation (reference `int(E * (1-rate))`)
    assert out.shape[1] == int(ei.shape[1] * (1.0 - 0.4))
    d_all = np.linalg.norm(pos[ei[0]] - pos[ei[1]], axis=1)
    d_kept = np.linalg.norm(pos[out[0]] - pos[out[1]], axis=1)
    assert d_kept.max() <= np.sort(d_all)[out.shape[1] - 1] + 1e-12


def test_pad_graphs_shapes(rng):
    graphs = []
    for n, e in [(5, 12), (7, 20)]:
        graphs.append(dict(
            node_feat=rng.normal(size=(n, 2)).astype(np.float32),
            loc=rng.normal(size=(n, 3)).astype(np.float32),
            vel=rng.normal(size=(n, 3)).astype(np.float32),
            target=rng.normal(size=(n, 3)).astype(np.float32),
            edge_index=rng.integers(0, n, size=(2, e)),
            edge_attr=rng.normal(size=(e, 1)).astype(np.float32),
        ))
    gb = pad_graphs(graphs, node_bucket=8, edge_bucket=16)
    assert gb.node_feat.shape == (2, 8, 2)
    assert gb.edge_index.shape == (2, 2, 32)
    np.testing.assert_allclose(np.asarray(gb.n_node), [5, 7])
    np.testing.assert_allclose(np.asarray(gb.loc_mean[0]), graphs[0]["loc"].mean(0), rtol=1e-5)
    # padded edges masked out
    assert np.asarray(gb.edge_mask).sum() == 32
