"""The bench headline must be un-losable (VERDICT r4 #1): round 4 had a live
tunnel, finished 4 race legs, and still delivered `parsed: null` because the
only stdout print sat after the whole race and the driver's timeout hit
first. The contract now: after EVERY finished leg bench.py prints the
best-so-far headline JSON line (flushed), so killing the process at ANY
point after >=1 finished leg leaves a parseable headline in the captured
tail. This test runs a tiny CPU race, waits for the first headline line,
SIGKILLs the bench mid-race, and parses what was captured.

A second gate traces EVERY leg of bench.RACE_ORDER on CPU: a leg that cannot
even build its jitted step on a dev box would burn a hardware session slot
to discover the same crash (the round-2 failure mode)."""

from __future__ import annotations

import importlib.util
import json
import os
import shutil
import signal
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _race_order():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.RACE_ORDER


@pytest.mark.slow
@pytest.mark.parametrize("child_args,child_env", _race_order(),
                         ids=lambda v: " ".join(v) if isinstance(v, list)
                         else str(v))
def test_every_race_leg_traces_on_cpu(child_args, child_env):
    """Each race leg must run end-to-end (trace + execute one tiny step
    program) on CPU — same child invocation the auto race spawns."""
    env = dict(
        os.environ,
        BENCH_PLATFORM="cpu",
        BENCH_PAUSE="0",
        BENCH_NODES="1500",
        JAX_PLATFORMS="cpu",
        **(child_env or {}),
    )
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")] + child_args,
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env)
    assert out.returncode == 0, f"leg {child_args} died: {out.stderr[-800:]}"
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["value"] > 0
    if rec["metric"] == "io_pipeline_graphs_per_sec":
        # input-pipeline leg: graphs/s + the stall A/B fields, not nodes/sec
        assert "graphs/s" in rec["unit"]
        assert rec["stall_s_blocking"] >= 0 and rec["stall_s"] >= 0
        assert rec["vs_blocking"] > 0
    else:
        assert "nodes/sec" in rec["unit"]


def test_serve_bench_rollout_leg_traces_on_cpu(capsys):
    """The rollout BENCH line can never silently vanish: a tiny CPU trace of
    `serve_bench.py --workload rollout` must emit exactly ONE JSON line with
    the batched-vs-baseline fields. In-process (not a subprocess) so it runs
    in tier-1, matching test_serve.py's bench idiom."""
    from scripts.serve_bench import main as bench_main

    rc = bench_main(["--workload", "rollout", "--rollout-scenes", "2",
                     "--rollout-steps", "2", "--sizes", "24",
                     "--max-batch", "2", "--rate", "500", "--obs-dir", "",
                     "--seed", "7"])
    assert rc == 0
    lines = [ln for ln in capsys.readouterr().out.strip().splitlines() if ln]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["metric"] == "serve_rollout_throughput"
    assert rec["unit"] == "scenes*steps/s"
    assert rec["value"] > 0
    assert rec["baseline_b1"] > 0 and rec["baseline_solo"] > 0
    assert rec["vs_baseline"] > 0
    assert rec["max_batch"] == 2 and rec["steps"] == 2
    assert rec["scenes_completed"] == 2   # value credits only finished work
    assert rec["snapshot"]["requests_completed"] == 2


@pytest.mark.slow
def test_sigkill_mid_race_still_yields_headline(tmp_path):
    # bench.py resolves repo_dir (and its race-artifact paths) from its own
    # file location — run a COPY from tmp_path so the test can never clobber
    # the committed hardware/CPU race artifacts under docs/artifacts/.
    bench_copy = tmp_path / "bench.py"
    shutil.copy(os.path.join(REPO, "bench.py"), bench_copy)
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        BENCH_PLATFORM="cpu",   # no probe, no competitor pausing
        BENCH_PAUSE="0",
        BENCH_NODES="1500",     # tiny workload: first leg finishes in ~tens of s
        JAX_PLATFORMS="cpu",
    )
    proc = subprocess.Popen(
        [sys.executable, str(bench_copy)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, cwd=str(tmp_path), env=env,
    )
    lines = []
    try:
        # read until the first best-so-far headline appears, then kill the
        # race mid-flight — exactly the driver-timeout scenario
        import threading

        got_headline = threading.Event()

        def reader():
            for line in proc.stdout:
                lines.append(line)
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and rec.get("metric"):
                    got_headline.set()
                    return

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        assert got_headline.wait(timeout=600), (
            "no headline JSON line within 600s of race start; captured: "
            f"{lines!r}")
        proc.send_signal(signal.SIGKILL)
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=30)

    # the captured tail must contain a parseable headline with a real value
    parsed = [json.loads(l) for l in lines
              if l.lstrip().startswith("{")]
    headlines = [p for p in parsed if isinstance(p, dict) and p.get("metric")]
    assert headlines, f"no parseable headline in captured tail: {lines!r}"
    assert headlines[-1]["value"] > 0
    assert "nodes/sec" in headlines[-1]["unit"]
