"""End-to-end LargeFluid distribute run (VERDICT r1 item 5): the REAL
configs/largefluid_distegnn.yaml through run_distributed — synthetic
Fluid113K-format shards at moderate scale, METIS partitioning with uneven
partition sizes, ShardedGraphLoader, grad accumulation (4), MMD, 8-device
CPU mesh, >= 2 epochs. Mirrors the reference distribute flow
(datasets/process_dataset.py:441-578 + utils/train.py)."""

from __future__ import annotations

import os

import numpy as np
import pytest

N_PART = 1200
RADIUS = 0.16


@pytest.fixture(scope="module")
def fluid_dataset(tmp_path_factory):
    from distegnn_tpu.data.fluid113k import SIM_SPLITS, write_fluid_sim
    from scripts.generate_fluid_synthetic import synth_sim

    rng = np.random.default_rng(3)
    d = str(tmp_path_factory.mktemp("largefluid"))
    for split, (lo, _) in SIM_SPLITS.items():
        pos, vel = synth_sim(rng, N_PART, 26, RADIUS)
        write_fluid_sim(d, "Fluid113K", lo, pos, vel,
                        np.full((N_PART,), 0.01, np.float32),
                        np.full((N_PART,), 0.1, np.float32))
    return d


@pytest.mark.slow
@pytest.mark.parametrize("edge_block", [0, 256])
def test_largefluid_yaml_runs_distributed_metis(fluid_dataset, tmp_path, edge_block):
    from distegnn_tpu.config import load_config
    from distegnn_tpu.data import GraphDataset
    from distegnn_tpu.parallel.launch import run_distributed

    config = load_config(os.path.join(os.path.dirname(__file__), "..",
                                      "configs", "largefluid_distegnn.yaml"))
    config.data.data_dir = fluid_dataset
    config.data.max_samples = 3
    config.data.world_size = 8
    config.data.outer_radius = RADIUS   # scaled for N_PART density
    config.data.inner_radius = RADIUS
    config.data.delta_t = 3
    config.data.edge_block = edge_block  # 256: MXU kernel path under shard_map
    config.train.epochs = 2
    config.log.log_dir = str(tmp_path)
    assert config.data.split_mode == "metis"           # the yaml's real value
    assert config.train.accumulation_steps == 4        # exercises MultiSteps

    best = run_distributed(config)
    assert np.isfinite(best["loss_valid"]) and np.isfinite(best["loss_test"])

    # the metis shards really are uneven: partition node counts differ
    processed = os.path.join(fluid_dataset, "Fluid113K", "processed")
    shard_files = sorted(f for f in os.listdir(processed) if "_train_" in f)
    assert len(shard_files) == 8
    counts = []
    for f in shard_files:
        ds = GraphDataset(os.path.join(processed, f))
        counts.append(ds[0]["loc"].shape[0])
    assert sum(counts) == N_PART
    assert len(set(counts)) > 1, f"expected uneven metis partitions, got {counts}"

    # log.json artifact written by the shared trainer
    from tests.conftest import assert_run_artifacts

    assert_run_artifacts(tmp_path)


@pytest.mark.slow
@pytest.mark.parametrize("edge_block,seg", [(0, "scatter"), (0, "cumsum"), (256, "scatter")])
def test_largefluid_distributed_scan_epochs(fluid_dataset, tmp_path, edge_block, seg):
    """The same distribute flow with scan_epochs FORCED on (auto disables it
    on CPU): one shard_map(lax.scan) dispatch per epoch through the real
    run_distributed entry — the path the LargeFluid convergence run takes on
    TPU (VERDICT r2 weak #4)."""
    from distegnn_tpu.config import load_config
    from distegnn_tpu.parallel.launch import run_distributed

    config = load_config(os.path.join(os.path.dirname(__file__), "..",
                                      "configs", "largefluid_distegnn.yaml"))
    config.data.data_dir = fluid_dataset
    config.data.max_samples = 3
    config.data.world_size = 8
    config.data.outer_radius = RADIUS
    config.data.inner_radius = RADIUS
    config.data.delta_t = 3
    config.data.edge_block = edge_block
    config.model.segment_impl = seg   # cumsum: edge_pair rides the [P,G,...] stack
    config.train.epochs = 2
    config.train.scan_epochs = True
    config.log.log_dir = str(tmp_path)

    best = run_distributed(config)
    assert np.isfinite(best["loss_valid"]) and np.isfinite(best["loss_test"])

    from tests.conftest import assert_run_artifacts

    assert_run_artifacts(tmp_path)
