"""Training-runtime tests: loss parity vs dense numpy/torch references,
optimizer parity vs torch.Adam, grad accumulation, checkpoint roundtrip, and a
loss-goes-down smoke run (SURVEY.md §4: the test infrastructure the reference
lacks)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distegnn_tpu.data import GraphDataset, GraphLoader, build_nbody_graph
from distegnn_tpu.models.fast_egnn import FastEGNN
from distegnn_tpu.ops.graph import pad_graphs
from distegnn_tpu.train import (
    TrainState,
    make_eval_step,
    make_optimizer,
    make_train_step,
    masked_mse,
    mmd_loss,
    restore_checkpoint,
    save_checkpoint,
)


def _tiny_dataset(rng, n_graphs=8, n=10):
    graphs = []
    for _ in range(n_graphs):
        loc = rng.normal(size=(n, 3))
        vel = rng.normal(size=(n, 3))
        charges = rng.choice([1.0, -1.0], size=(n, 1))
        target = loc + 0.1 * vel
        graphs.append(build_nbody_graph(loc, vel, charges, target, radius=-1.0, cutoff_rate=0.0))
    return graphs


def test_masked_mse_matches_numpy(rng):
    pred = rng.normal(size=(2, 6, 3)).astype(np.float32)
    target = rng.normal(size=(2, 6, 3)).astype(np.float32)
    mask = np.array([[1, 1, 1, 1, 0, 0], [1, 1, 1, 1, 1, 1]], np.float32)
    got = float(masked_mse(jnp.asarray(pred), jnp.asarray(target), jnp.asarray(mask)))
    real = np.concatenate([(pred[0, :4] - target[0, :4]).ravel(), (pred[1] - target[1]).ravel()])
    np.testing.assert_allclose(got, np.mean(real**2), rtol=1e-5)


def test_mmd_loss_matches_dense_reference(rng):
    # With samples*C >= N every real node is drawn (Gumbel top-k over N nodes),
    # so the sampled set equals the node set and the loss is deterministic —
    # compare against a direct numpy transcription of reference kernel math
    # (utils/train.py:11-14,119-145).
    B, N, C, sigma, samples = 2, 4, 2, 1.5, 2  # num_sample = 4 = N
    V = rng.normal(size=(B, 3, C)).astype(np.float32)
    target = rng.normal(size=(B, N, 3)).astype(np.float32)
    mask = np.ones((B, N), np.float32)
    got = float(mmd_loss(jnp.asarray(V), jnp.asarray(target), jnp.asarray(mask),
                         jax.random.PRNGKey(0), sigma, samples))

    def k(x, y):
        d = np.linalg.norm(x[:, None] - y[None, :], axis=-1)
        return np.exp(-d / (2 * sigma * sigma))

    num_sample = samples * C
    l_vv = sum(k(V[b].T, V[b].T).sum() for b in range(B)) / B / C / C
    l_rv = 2 * sum(k(target[b], V[b].T).sum() for b in range(B)) / B / num_sample / C
    np.testing.assert_allclose(got, l_vv - l_rv, rtol=1e-4)


def test_optimizer_matches_torch_adam():
    # same quadratic, same init: optax chain must track torch.Adam(+wd) steps
    import torch

    w0 = np.array([1.0, -2.0, 3.0], np.float32)
    tw = torch.nn.Parameter(torch.tensor(w0))
    topt = torch.optim.Adam([tw], lr=1e-2, weight_decay=1e-2)
    for _ in range(5):
        topt.zero_grad()
        loss = (tw**2).sum()
        loss.backward()
        topt.step()

    tx = make_optimizer(1e-2, weight_decay=1e-2)
    params = jnp.asarray(w0)
    opt_state = tx.init(params)
    for _ in range(5):
        grads = jax.grad(lambda p: jnp.sum(p**2))(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = params + updates["params"] if isinstance(updates, dict) else params + updates
    np.testing.assert_allclose(np.asarray(params), tw.detach().numpy(), rtol=1e-5, atol=1e-6)


def test_grad_accumulation_equals_mean():
    # MultiSteps(k=2) applied to two micro-grads == single step on their mean
    tx_acc = make_optimizer(1e-2, accumulation_steps=2)
    tx_ref = make_optimizer(1e-2)
    p = jnp.asarray([1.0, 2.0])
    g1, g2 = jnp.asarray([0.5, -1.0]), jnp.asarray([1.5, 3.0])

    s = tx_acc.init(p)
    pa = p
    for g in (g1, g2):
        u, s = tx_acc.update(g, s, pa)
        pa = pa + u
    sr = tx_ref.init(p)
    u, _ = tx_ref.update((g1 + g2) / 2, sr, p)
    pr = p + u
    np.testing.assert_allclose(np.asarray(pa), np.asarray(pr), rtol=1e-6)


@pytest.fixture(scope="module")
def tiny_setup():
    rng = np.random.default_rng(0)
    graphs = _tiny_dataset(rng)
    batch = pad_graphs(graphs[:4])
    model = FastEGNN(node_feat_nf=2, hidden_nf=16, virtual_channels=3, n_layers=2)
    params = model.init(jax.random.PRNGKey(0), batch)
    return model, params, graphs


def test_train_step_loss_decreases(tiny_setup):
    model, params, graphs = tiny_setup
    tx = make_optimizer(5e-3)
    state = TrainState.create(params, tx)
    step = jax.jit(make_train_step(model, tx, mmd_weight=0.03, mmd_sigma=1.5, mmd_samples=3))
    ds = GraphDataset(graphs)
    loader = GraphLoader(ds, batch_size=4, shuffle=True, seed=1)
    first = last = None
    for epoch in range(15):
        loader.set_epoch(epoch)
        for i, batch in enumerate(loader):
            state, m = step(state, batch, jax.random.PRNGKey(epoch * 100 + i))
            if first is None:
                first = float(m["loss"])
            last = float(m["loss"])
    assert last < first * 0.5, f"loss did not decrease: {first} -> {last}"


def test_eval_step_runs(tiny_setup):
    model, params, graphs = tiny_setup
    ev = jax.jit(make_eval_step(model))
    batch = pad_graphs(graphs[:4])
    loss = float(ev(params, batch))
    assert np.isfinite(loss) and loss > 0


def test_early_stop_checked_every_epoch(tiny_setup, tmp_path):
    # reference checks the stop condition at the bottom of EVERY epoch
    # (utils/train.py:261-267), not only on eval epochs: with test_interval=10
    # and early_stop=3, the run must stop at epoch 3 before any eval happens.
    from distegnn_tpu.config import ConfigDict
    from distegnn_tpu.train.trainer import train

    model, params, graphs = tiny_setup
    tx = make_optimizer(1e-3)
    state = TrainState.create(params, tx)
    step = jax.jit(make_train_step(model, tx, mmd_weight=0.0, mmd_sigma=1.0, mmd_samples=1))
    ev = jax.jit(make_eval_step(model))
    loader = GraphLoader(GraphDataset(graphs), batch_size=4, shuffle=False, seed=0)
    config = ConfigDict({
        "seed": 0,
        "train": {"epochs": 50, "early_stop": 3},
        "log": {"test_interval": 10, "log_dir": str(tmp_path), "wandb": {"enable": False}},
    })
    _, _, best, log_dict = train(state, step, ev, loader, loader, loader, config, log=False)
    assert best["early_stop"] == 3
    assert len(log_dict["loss_train"]) == 3


def test_epoch_accumulates_on_device(tiny_setup):
    # run_epoch_train's average must equal the naive per-step float() average
    # (it now accumulates the scalar on device, one fetch per epoch)
    from distegnn_tpu.train.trainer import run_epoch_train

    model, params, graphs = tiny_setup
    tx = make_optimizer(1e-3)
    step = jax.jit(make_train_step(model, tx, mmd_weight=0.0, mmd_sigma=1.0, mmd_samples=1))
    loader = GraphLoader(GraphDataset(graphs), batch_size=4, shuffle=False, seed=0)

    state = TrainState.create(params, tx)
    _, avg = run_epoch_train(step, state, loader, seed=0, epoch=1)

    state2 = TrainState.create(params, tx)
    loader.set_epoch(1)
    total = cnt = 0.0
    for i, batch in enumerate(loader):
        key = jax.random.fold_in(jax.random.fold_in(jax.random.PRNGKey(0), 1), i)
        state2, m = step(state2, batch, key)
        total += float(m["loss"]) * batch.loc.shape[0]
        cnt += batch.loc.shape[0]
    np.testing.assert_allclose(avg, total / cnt, rtol=1e-6)


def test_checkpoint_roundtrip(tmp_path, tiny_setup):
    model, params, _ = tiny_setup
    tx = make_optimizer(1e-3, weight_decay=1e-8)
    state = TrainState.create(params, tx)
    path = str(tmp_path / "ckpt" / "best_model.ckpt")
    save_checkpoint(path, state, epoch=7, losses={"loss_valid": 0.5}, config={"a": 1})
    fresh = TrainState.create(params, tx)
    restored, epoch, losses = restore_checkpoint(path, fresh)
    assert epoch == 7 and losses["loss_valid"] == 0.5
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_rejects_mismatched_architecture(tmp_path, tiny_setup):
    """Restoring into a different param tree (e.g. hoist_edge_mlp flipped)
    must fail loudly, not zip mismatched leaves into garbage params."""
    import pytest

    from distegnn_tpu.models.fast_egnn import FastEGNN
    from distegnn_tpu.ops.graph import pad_graphs

    model, params, graphs = tiny_setup
    batch = pad_graphs(graphs[:4])
    tx = make_optimizer(1e-3, weight_decay=1e-8)
    state = TrainState.create(params, tx)
    path = str(tmp_path / "ckpt" / "best_model.ckpt")
    save_checkpoint(path, state, epoch=1)

    other = FastEGNN(node_feat_nf=model.node_feat_nf,
                     edge_attr_nf=model.edge_attr_nf,
                     hidden_nf=model.hidden_nf,
                     virtual_channels=model.virtual_channels,
                     n_layers=model.n_layers,
                     hoist_edge_mlp=not model.hoist_edge_mlp)
    p2 = other.init(jax.random.PRNGKey(0), batch)
    fresh = TrainState.create(p2, tx)
    with pytest.raises(ValueError, match="checkpoint incompatible"):
        restore_checkpoint(path, fresh)


def test_trace_epoch_writes_profile(tiny_setup, tmp_path):
    """log.trace_epoch=N captures a jax.profiler trace of epoch N into
    <exp_dir>/trace/ (SURVEY §5.1 observability at the training surface)."""
    import os

    from distegnn_tpu.config import ConfigDict
    from distegnn_tpu.train.trainer import train

    model, params, graphs = tiny_setup
    tx = make_optimizer(1e-3)
    state = TrainState.create(params, tx)
    step = jax.jit(make_train_step(model, tx, mmd_weight=0.0, mmd_sigma=1.0, mmd_samples=1))
    ev = jax.jit(make_eval_step(model))
    loader = GraphLoader(GraphDataset(graphs), batch_size=4, shuffle=False, seed=0)
    config = ConfigDict({
        "seed": 0,
        "train": {"epochs": 2, "early_stop": 10},
        "log": {"test_interval": 10, "log_dir": str(tmp_path), "exp_name": "tr",
                "trace_epoch": 2, "wandb": {"enable": False}},
    })
    train(state, step, ev, loader, loader, loader, config, log=True)
    trace_dir = os.path.join(str(tmp_path), "tr", "trace")
    files = [os.path.join(r, f) for r, _, fs in os.walk(trace_dir) for f in fs]
    assert files, "no profiler trace written"


def test_restore_params_ignores_optimizer_wrapping(tmp_path, tiny_setup):
    """A checkpoint written with grad-accumulation (MultiSteps wraps extra
    opt-state arrays) must load into a bare model for evaluation/rollout —
    restore_params is params-only (restore_checkpoint correctly refuses)."""
    from distegnn_tpu.train.checkpoint import (restore_checkpoint,
                                               restore_params,
                                               save_checkpoint)

    model, params, graphs = tiny_setup
    tx_acc = make_optimizer(1e-3, accumulation_steps=4)
    state = TrainState.create(params, tx_acc)
    path = str(tmp_path / "acc.ckpt")
    save_checkpoint(path, state, epoch=3, config={"model": {"x": 1}})

    restored = restore_params(path, params)
    for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    plain_state = TrainState.create(params, make_optimizer(1e-3))
    with pytest.raises(ValueError, match="incompatible"):
        restore_checkpoint(path, plain_state)


def test_resume_equals_uninterrupted(tiny_setup, tmp_path):
    """Interrupt-and-resume reproduces the uninterrupted run bitwise.

    Per-step PRNG keys derive from (seed, epoch, step) and the loader
    reshuffles from (seed, epoch) (trainer.run_epoch_train), so restoring
    last_model.ckpt at epoch k and continuing with start_epoch=k must yield
    the exact trajectory the unbroken run took — the property the reference's
    --checkpoint restart flow (main.py:208-220) provides and our convergence
    automation (scripts/convergence_session.sh) relies on after a mid-run
    abort."""
    from distegnn_tpu.config import ConfigDict
    from distegnn_tpu.train.trainer import train

    model, params, graphs = tiny_setup
    tx = make_optimizer(1e-3)
    step = jax.jit(make_train_step(model, tx, mmd_weight=0.03, mmd_sigma=1.5,
                                   mmd_samples=3))
    ev = jax.jit(make_eval_step(model))

    def mk_loader():
        return GraphLoader(GraphDataset(graphs), batch_size=4, shuffle=True, seed=0)

    def mk_config(dirname, epochs):
        return ConfigDict({
            "seed": 0,
            "train": {"epochs": epochs, "early_stop": 100},
            "log": {"test_interval": 2, "log_dir": str(tmp_path / dirname),
                    "exp_name": "run", "wandb": {"enable": False}},
        })

    # uninterrupted run: 6 epochs
    state_a = TrainState.create(params, tx)
    state_a, _, _, _ = train(state_a, step, ev, mk_loader(), mk_loader(),
                             mk_loader(), mk_config("full", 6))

    # interrupted at epoch 4 (last_model.ckpt written on eval epoch 4) ...
    state_b = TrainState.create(params, tx)
    train(state_b, step, ev, mk_loader(), mk_loader(), mk_loader(),
          mk_config("part", 4))
    ckpt = tmp_path / "part" / "run" / "state_dict" / "last_model.ckpt"
    fresh = TrainState.create(params, tx)
    restored, start_epoch, _ = restore_checkpoint(str(ckpt), fresh)
    assert start_epoch == 4

    # ... resumed for epochs 5..6
    state_c, _, _, _ = train(restored, step, ev, mk_loader(), mk_loader(),
                             mk_loader(), mk_config("resumed", 6),
                             start_epoch=start_epoch)

    for a, c in zip(jax.tree.leaves(state_a.params), jax.tree.leaves(state_c.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


def test_divergence_stops_training(tiny_setup, tmp_path):
    """SURVEY §5.3 failure detection: a non-finite train loss must stop the
    run immediately (unattended hardware sessions would otherwise burn the
    whole window training on NaN) and record the diagnosis in log.json."""
    import json
    import os

    from distegnn_tpu.config import ConfigDict
    from distegnn_tpu.train.trainer import train

    model, params, graphs = tiny_setup
    tx = make_optimizer(1e-3)

    calls = {"n": 0}

    def exploding_step(state, batch, key):
        calls["n"] += 1
        # diverge partway through epoch 2
        loss = jnp.float32(jnp.nan) if calls["n"] > 3 else jnp.float32(0.5)
        return state.replace(step=state.step + 1), {"loss": loss}

    config = ConfigDict({
        "seed": 0,
        "train": {"epochs": 10, "early_stop": 100},
        "log": {"test_interval": 2, "log_dir": str(tmp_path),
                "exp_name": "run", "wandb": {"enable": False}},
    })
    state = TrainState.create(params, tx)
    _, _, best, log_dict = train(
        state, exploding_step, lambda p, b: jnp.float32(0.1),
        GraphLoader(GraphDataset(graphs), batch_size=4, shuffle=True, seed=0),
        GraphLoader(GraphDataset(graphs), batch_size=4),
        GraphLoader(GraphDataset(graphs), batch_size=4),
        config)
    assert "diverged" in best
    assert len(log_dict["loss_train"]) < 10  # stopped early
    raw = open(os.path.join(tmp_path, "run", "log", "log.json")).read()
    logged = json.loads(raw, parse_constant=lambda c: pytest.fail(
        f"non-RFC-8259 token {c} in log.json"))  # strict: no bare NaN/Infinity
    assert "diverged" in logged[0]
    assert logged[1]["loss_train"][-1] is None  # NaN sanitized to null
