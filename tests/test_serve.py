"""Serving stack (distegnn_tpu/serve): bucket ladder, compile cache,
micro-batcher, metrics, and the bench harness — all CPU, in-process."""

import json
import threading

import jax
import numpy as np
import pytest

from distegnn_tpu.models.fast_egnn import FastEGNN
from distegnn_tpu.ops.graph import pad_graphs
from distegnn_tpu.serve import (Bucket, BucketLadder, BucketOverflowError,
                                InferenceEngine, QueueFullError, RequestQueue,
                                RequestTimeoutError, ServeMetrics,
                                synthetic_graph)

pytestmark = pytest.mark.serve


def _model():
    return FastEGNN(node_feat_nf=1, edge_attr_nf=2, hidden_nf=16,
                    virtual_channels=2, n_layers=2)


def _init(model, graph):
    tight = pad_graphs([graph], node_bucket=1, edge_bucket=1)
    return model.init(jax.random.PRNGKey(0), tight)


def _reference(model, params, graph):
    """Direct model.apply on the unpadded graph — the numerics oracle."""
    tight = pad_graphs([graph], node_bucket=1, edge_bucket=1)
    x, _ = model.apply(params, tight)
    return np.asarray(x[0])


# ---------------------------------------------------------------- ladder

def test_ladder_geometric_rungs():
    lad = BucketLadder(node_floor=64, edge_floor=256, growth=2.0,
                       node_multiple=8, edge_multiple=128,
                       max_nodes=1024, max_edges=4096)
    assert lad.bucket_for(1, 1) == Bucket(64, 256)
    assert lad.bucket_for(64, 256) == Bucket(64, 256)   # exact rung, no jump
    assert lad.bucket_for(65, 257) == Bucket(128, 512)
    assert lad.bucket_for(300, 2000) == Bucket(512, 2048)
    assert lad.bucket_for(1024, 4096) == Bucket(1024, 4096)
    # N and E bucket independently
    assert lad.bucket_for(65, 1) == Bucket(128, 256)


def test_ladder_overflow_rejected():
    lad = BucketLadder(max_nodes=256, max_edges=1024)
    with pytest.raises(BucketOverflowError):
        lad.bucket_for(257, 10)
    with pytest.raises(BucketOverflowError):
        lad.bucket_for(10, 1025)


def test_ladder_monotone_and_admitting():
    lad = BucketLadder(node_floor=16, edge_floor=32, growth=1.5,
                       max_nodes=2048, max_edges=8192)
    prev = Bucket(0, 0)
    for n, e in [(1, 1), (16, 32), (17, 33), (100, 500), (999, 4000)]:
        b = lad.bucket_for(n, e)
        assert b.n >= n and b.e >= e          # admits the request
        assert b.n >= prev.n and b.e >= prev.e  # monotone in request size
        prev = b


# ---------------------------------------------------------------- engine

def test_engine_predict_matches_direct_apply():
    model = _model()
    g = synthetic_graph(40, seed=1)
    params = _init(model, g)
    eng = InferenceEngine(model, params, max_batch=4)
    out = eng.predict(g)
    np.testing.assert_allclose(out, _reference(model, params, g),
                               atol=1e-4, rtol=0)


def test_engine_cache_hit_miss_eviction():
    model = _model()
    g1, g2, g3 = (synthetic_graph(n, seed=s)
                  for n, s in ((30, 1), (90, 2), (200, 3)))
    params = _init(model, g1)
    eng = InferenceEngine(model, params, max_batch=2, cache_size=2)
    eng.predict(g1)
    eng.predict(g1)           # hit
    eng.predict(g2)           # miss (second bucket)
    eng.predict(g3)           # miss + evicts the LRU entry (cache_size=2)
    st = eng.cache_stats()
    assert st["misses"] == 3 and st["hits"] == 1
    assert st["evictions"] == 1 and st["live"] == 2
    eng.predict(g1)           # evicted -> recompiles: miss again
    assert eng.cache_stats()["misses"] == 4


def test_engine_warmup_compiles_distinct_rungs_once():
    model = _model()
    g = synthetic_graph(50, seed=4)
    params = _init(model, g)
    eng = InferenceEngine(model, params, max_batch=2)
    sizes = [(50, g["edge_index"].shape[1])] * 3
    warmed = eng.warmup(sizes)
    assert len(warmed) == 1
    assert eng.cache_stats()["misses"] == 1


# ---------------------------------------------------------------- queue e2e

def test_queue_end_to_end_concurrent():
    """The acceptance run: >= 20 concurrent submissions, >= 3 distinct
    (N, E) sizes; every response matches direct apply on the unpadded
    graph; cache misses == distinct buckets; hits >= misses."""
    model = _model()
    base_graphs = [synthetic_graph(n, seed=s)
                   for n, s in ((40, 10), (90, 11), (180, 12))]
    sizes = {(g["loc"].shape[0], g["edge_index"].shape[1])
             for g in base_graphs}
    assert len(sizes) >= 3
    params = _init(model, base_graphs[0])
    metrics = ServeMetrics()
    eng = InferenceEngine(model, params, max_batch=2, metrics=metrics)
    refs = [_reference(model, params, g) for g in base_graphs]
    expected_buckets = {eng.ladder.bucket_of_graph(g) for g in base_graphs}

    n_req = 24
    jobs = [base_graphs[i % 3] for i in range(n_req)]
    futures = [None] * n_req
    errors = []

    with RequestQueue(eng, batch_deadline_ms=20.0, queue_capacity=64,
                      request_timeout_ms=30_000.0) as q:
        def submit(i):
            try:
                futures[i] = q.submit(jobs[i])
            except Exception as e:   # pragma: no cover - should not happen
                errors.append(e)

        threads = [threading.Thread(target=submit, args=(i,))
                   for i in range(n_req)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        results = [f.result(timeout=120.0) for f in futures]

    for i, out in enumerate(results):
        np.testing.assert_allclose(out, refs[i % 3], atol=1e-4, rtol=0,
                                   err_msg=f"request {i} diverged")

    snap = metrics.snapshot()
    assert snap["cache_misses"] == len(expected_buckets)
    assert snap["cache_hits"] >= snap["cache_misses"]
    assert snap["requests_completed"] == n_req
    assert snap["requests_failed"] == 0 and snap["requests_timeout"] == 0
    assert snap["batches_executed"] >= len(expected_buckets)
    assert 0 < snap["batch_fill_ratio"] <= 1
    assert snap["latency_p99_ms"] >= snap["latency_p50_ms"] > 0


def test_queue_backpressure_queue_full():
    model = _model()
    g = synthetic_graph(30, seed=5)
    params = _init(model, g)
    eng = InferenceEngine(model, params, max_batch=2)
    q = RequestQueue(eng, batch_deadline_ms=50.0, queue_capacity=2,
                     request_timeout_ms=10_000.0)
    # NOT started: the dispatcher never drains, so capacity fills
    q._started = True  # allow submits without a running dispatcher
    q.submit(g)
    q.submit(g)
    with pytest.raises(QueueFullError):
        q.submit(g)
    assert eng.metrics.snapshot()["requests_rejected"] == 1


def test_queue_overflow_graph_rejected_at_submit():
    model = _model()
    g = synthetic_graph(30, seed=6)
    params = _init(model, g)
    eng = InferenceEngine(model, params, max_batch=2,
                          ladder=BucketLadder(max_nodes=64, max_edges=4096))
    with RequestQueue(eng) as q:
        with pytest.raises(BucketOverflowError):
            q.submit(synthetic_graph(100, seed=7))


def test_queue_request_timeout_surfaced():
    model = _model()
    g = synthetic_graph(30, seed=8)
    params = _init(model, g)
    eng = InferenceEngine(model, params, max_batch=2)
    q = RequestQueue(eng, batch_deadline_ms=10_000.0, queue_capacity=8,
                     request_timeout_ms=30.0)
    q._started = True          # no dispatcher: requests age in the ingress
    fut = q.submit(g)
    import time

    time.sleep(0.06)
    q._started = False
    q._fail_all(RequestTimeoutError("drained"))
    with pytest.raises(RequestTimeoutError):
        fut.result(timeout=1.0)


def test_stop_drains_admitted_requests():
    model = _model()
    g = synthetic_graph(30, seed=9)
    params = _init(model, g)
    eng = InferenceEngine(model, params, max_batch=4)
    q = RequestQueue(eng, batch_deadline_ms=5_000.0, queue_capacity=16,
                     request_timeout_ms=60_000.0).start()
    futs = [q.submit(g) for _ in range(3)]
    q.stop(drain=True)   # long deadline: only the drain can flush these
    for f in futs:
        assert f.result(timeout=1.0).shape == (30, 3)


# ---------------------------------------------------------------- metrics

def test_metrics_snapshot_schema_and_json():
    m = ServeMetrics()
    m.submitted(5)
    m.batch_done(2, 4, [1.5, 2.5], [0.5, 0.7])
    m.cache_event(hit=False)
    m.cache_event(hit=True)
    snap = json.loads(m.to_json())
    assert snap["requests_submitted"] == 5
    assert snap["requests_completed"] == 2
    assert snap["batch_fill_ratio"] == 0.5
    assert snap["cache_hits"] == 1 and snap["cache_misses"] == 1
    assert snap["latency_p50_ms"] > 0
    for v in snap.values():
        assert isinstance(v, (int, float))


# ---------------------------------------------------------------- rollout

def test_engine_rollout_pads_and_unpads():
    model = _model()
    n = 100   # not a multiple of edge_block: engine must pad to 256
    g = synthetic_graph(n, seed=13)
    params = _init(model, g)
    eng = InferenceEngine(
        model, params, max_batch=1,
        rollout_opts={"radius": 0.35, "max_degree": 64, "max_per_cell": 64})
    traj = eng.rollout(g["loc"], g["vel"], steps=2)
    assert traj.shape == (2, n, 3)
    assert np.isfinite(traj).all()
    assert eng.cache_stats()["misses"] == 1
    eng.rollout(g["loc"], g["vel"], steps=2)   # same shape+steps: cache hit
    assert eng.cache_stats()["hits"] == 1


def test_rollout_batch_matches_sequential_b1():
    """The tentpole parity bar: batched rollouts return the SAME trajectories
    as sequential B=1 engine.rollout calls, to 1e-6 — vmapping over the scene
    axis changes throughput, never numbers."""
    model = _model()
    g = synthetic_graph(48, seed=14)
    params = _init(model, g)
    eng = InferenceEngine(
        model, params, max_batch=4,
        rollout_opts={"radius": 0.35, "max_degree": 64, "max_per_cell": 64})
    scenes = []
    for k in range(3):     # underfilled batch: 3 scenes, max_batch=4
        gk = synthetic_graph(48, seed=20 + k)
        scenes.append({"loc": gk["loc"], "vel": gk["vel"], "steps": 3})
    batched = eng.rollout_batch(scenes)
    assert len(batched) == 3
    for s, traj in zip(scenes, batched):
        assert traj.shape == (3, 48, 3)
        ref = eng.rollout(s["loc"], s["vel"], 3)
        np.testing.assert_allclose(traj, ref, atol=1e-6, rtol=0)


def test_rollout_batch_mixed_steps_typed_error():
    from distegnn_tpu.serve import MixedRolloutStepsError

    model = _model()
    g = synthetic_graph(32, seed=15)
    params = _init(model, g)
    eng = InferenceEngine(
        model, params, max_batch=4,
        rollout_opts={"radius": 0.35, "max_degree": 64, "max_per_cell": 64})
    scenes = [{"loc": g["loc"], "vel": g["vel"], "steps": 2},
              {"loc": g["loc"], "vel": g["vel"], "steps": 5}]
    with pytest.raises(MixedRolloutStepsError):
        eng.rollout_batch(scenes)


def test_queue_coalesces_rollouts_one_batch():
    """Co-submitted same-rung same-steps rollouts share ONE batched
    executable call, and every future resolves to its own scene's
    trajectory."""
    model = _model()
    g = synthetic_graph(40, seed=16)
    params = _init(model, g)
    eng = InferenceEngine(
        model, params, max_batch=4,
        rollout_opts={"radius": 0.35, "max_degree": 64, "max_per_cell": 64})
    q = RequestQueue(eng, batch_deadline_ms=150.0, queue_capacity=16,
                     request_timeout_ms=120_000.0)
    scenes = [{"loc": synthetic_graph(40, seed=30 + k)["loc"],
               "vel": synthetic_graph(40, seed=30 + k)["vel"], "steps": 2}
              for k in range(4)]
    with q:
        futures = [q.submit_rollout(s) for s in scenes]
        results = [f.result(timeout=180.0) for f in futures]
    batches = eng.metrics.snapshot()["batches_executed"]
    assert batches <= 2    # 4 co-arrivals into at most 2 batches (1 when
    #                        the deadline window catches all four)
    for s, traj in zip(scenes, results):
        ref = eng.rollout(s["loc"], s["vel"], 2)
        np.testing.assert_allclose(traj, ref, atol=1e-6, rtol=0)


# ---------------------------------------------------------------- bench

def test_serve_bench_cli_one_json_line(capsys):
    from scripts.serve_bench import main as bench_main

    rc = bench_main(["--requests", "12", "--rate", "500",
                     "--sizes", "24,48", "--seed", "7"])
    assert rc == 0
    lines = [ln for ln in capsys.readouterr().out.strip().splitlines() if ln]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["metric"] == "serve_throughput"
    assert rec["unit"] == "req/s"
    assert rec["value"] > 0
    assert rec["snapshot"]["requests_completed"] > 0
    assert rec["snapshot"]["cache_misses"] >= 1


# ------------------------------------------------- fused_stack executables

def test_fused_stack_one_executable_per_rung_and_obs_check(tmp_path):
    """Cross-layer megakernel serving gate: a warmed rung under
    ``edge_impl='fused_stack'`` serves every subsequent predict from exactly
    ONE multi-layer executable — the cache key carries (edge_impl, L), no
    per-layer entries exist, zero compiles land after warmup — and the obs
    stream passes ``obs_report --check``."""
    import os
    import subprocess
    import sys

    from distegnn_tpu.models.fast_egnn import FastEGNN as _FE
    from distegnn_tpu.obs import jaxprobe, trace

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    log_dir = str(tmp_path / "obs")
    trace.configure(log_dir=log_dir)
    watcher = jaxprobe.install_compile_watcher()
    try:
        model = _FE(node_feat_nf=1, edge_attr_nf=2, hidden_nf=16,
                    virtual_channels=2, n_layers=2, edge_impl="fused_stack")
        g = synthetic_graph(40, seed=1)
        layout = dict(edge_block=512, split_remote=True)
        eng = InferenceEngine(model, None, max_batch=2, layout_opts=layout)
        b0 = eng.ladder.bucket_of_graph(g)
        init_batch, _ = eng.ladder.pad_batch([g], b0, 2, **layout)
        eng.params = model.init(jax.random.PRNGKey(0), init_batch)

        warmed = eng.warmup([(40, g["edge_index"].shape[1])])
        assert len(warmed) == 1
        st = eng.cache_stats()
        assert st["live"] == 1 and st["misses"] == 1  # ONE executable, not L
        (key,) = list(eng._cache)
        assert key[-2:] == ("fused_stack", 2)  # the (rung, L) cache unit

        watcher.mark_warmup_done()
        for _ in range(3):
            out = eng.predict(g)
            assert out.shape == (40, 3) and np.isfinite(out).all()
        st = eng.cache_stats()
        assert st["live"] == 1 and st["misses"] == 1 and st["hits"] == 3
        assert watcher.snapshot()["compiles_after_warmup"] == 0
        trace.get_tracer().flush()
    finally:
        trace.configure(log_dir=None)
        jaxprobe.deactivate_compile_watcher()

    events = os.path.join(log_dir, "events.jsonl")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "obs_report.py"),
         events, "--check"],
        capture_output=True, text=True, cwd=repo,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "obs_report --check: OK" in r.stderr
