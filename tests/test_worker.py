"""Process-isolated serving workers (serve/worker.py + WorkerReplica):
the length-prefixed checksummed IPC framing, the import-isolation lint,
one real spawn proving bitwise parent/child parity and clean teardown,
spawn-failure degradation to in-process serving, and — under ``slow`` —
the full chaos drill (kill9 + live swap + sigstop under replayed traffic
with ``serve.workers: process``).

The ``process`` marker flags tests that spawn at least one real worker
child (a full interpreter + jax import each). Exactly one stays tier-1
as the smoke test; the drill matrix is additionally ``slow``.
"""

import json
import os
import signal
import socket
import struct
import sys
import threading
import time
import zlib
from types import SimpleNamespace

import jax
import numpy as np
import pytest

from distegnn_tpu.config import ConfigDict, _DEFAULTS
from distegnn_tpu.serve import synthetic_graph
from distegnn_tpu.serve import worker as wmod
from distegnn_tpu.serve.registry import ModelRegistry
from distegnn_tpu.train.checkpoint import save_checkpoint

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- IPC framing ------------------------------------------------------------

def _pair():
    a, b = socket.socketpair()
    return a, b


def test_frame_roundtrip_all_kinds():
    a, b = _pair()
    lock = threading.Lock()
    try:
        for kind, seq, obj in ((wmod.FRAME_REQUEST, 1, {"op": "ping"}),
                               (wmod.FRAME_RESPONSE, 1, {"ok": True,
                                                         "result": [1, 2]}),
                               (wmod.FRAME_HEARTBEAT, 0, {"ts": 1.5})):
            wmod.send_frame(a, lock, kind, seq, obj)
            k, s, payload = wmod.recv_frame(
                b, deadline=time.monotonic() + 5.0)
            assert (k, s, payload) == (kind, seq, obj)
    finally:
        a.close()
        b.close()


def test_frame_checksum_corruption_is_typed():
    """A flipped payload byte fails the crc32 check as FrameError — never a
    pickle of garbage bytes."""
    a, b = _pair()
    try:
        payload = __import__("pickle").dumps({"op": "predict"}, protocol=4)
        header = wmod._HEADER.pack(wmod._MAGIC, wmod.FRAME_REQUEST, 7,
                                   len(payload),
                                   zlib.crc32(payload) & 0xFFFFFFFF)
        corrupt = bytes([payload[0] ^ 0x40]) + payload[1:]
        a.sendall(header + corrupt)
        with pytest.raises(wmod.FrameError, match="checksum"):
            wmod.recv_frame(b, deadline=time.monotonic() + 5.0)
    finally:
        a.close()
        b.close()


def test_frame_bad_magic_is_typed():
    a, b = _pair()
    try:
        a.sendall(struct.pack("!2sBIII", b"XX", 1, 0, 0, 0))
        with pytest.raises(wmod.FrameError, match="magic"):
            wmod.recv_frame(b, deadline=time.monotonic() + 5.0)
    finally:
        a.close()
        b.close()


def test_frame_eof_and_deadline_are_typed():
    """A dead pipe is WorkerClosedError and a silent one WorkerTimeoutError
    — a parent blocked on a worker read NEVER hangs untyped."""
    a, b = _pair()
    a.close()
    try:
        with pytest.raises(wmod.WorkerClosedError):
            wmod.recv_frame(b, deadline=time.monotonic() + 5.0)
    finally:
        b.close()
    a, b = _pair()
    try:
        t0 = time.monotonic()
        with pytest.raises(wmod.WorkerTimeoutError):
            wmod.recv_frame(b, deadline=time.monotonic() + 0.2)
        assert time.monotonic() - t0 < 5.0
    finally:
        a.close()
        b.close()


# ---- lint: the worker child stays import-isolated ---------------------------

def test_worker_import_isolation():
    """Tier-1 wiring of scripts/check_worker_imports.py: worker.py keeps
    stdlib-only module-level imports (a broken jax must surface as a typed
    init failure, not an exec death) and never touches the parent-side
    transport/registry/supervisor stack."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from check_worker_imports import find_violations
    finally:
        sys.path.pop(0)
    violations = find_violations()
    assert violations == [], (
        "serve/worker.py broke import isolation — see "
        f"scripts/check_worker_imports.py: {violations}")


# ---- process-backed replicas ------------------------------------------------

def _small_cfg(**serve_kw):
    cfg = ConfigDict(_DEFAULTS)
    cfg.model.hidden_nf = 16
    cfg.model.n_layers = 2
    cfg.model.virtual_channels = 2
    cfg.serve.workers = "process"
    cfg.serve.replicas = 1
    cfg.serve.worker = {"spawn_timeout_s": 300.0, "heartbeat_s": 0.2,
                        "kill_grace_s": 2.0}
    for k, v in serve_kw.items():
        cfg.serve[k] = v
    return cfg


@pytest.mark.process
def test_worker_spawn_parity_and_clean_teardown():
    """The tier-1 worker smoke: one process-backed replica spawns (the
    handshake already asserted the child's params digest equals the
    parent's), serves a prediction BITWISE-identical to the parent
    engine's on the same graph, reports pid/heartbeat detail in health,
    and tears down leaving neither a live child nor a leaked handle."""
    cfg = _small_cfg()
    reg = ModelRegistry.from_config(cfg).start()
    pid = None
    try:
        e = reg.get("default")
        r = e.replicas.replicas[0]
        assert r.backend == "process" and not r.degraded
        pid = r.queue.pid
        assert pid is not None and os.path.exists(f"/proc/{pid}")
        g = synthetic_graph(24, seed=11,
                            feat_nf=int(cfg.model.node_feat_nf),
                            edge_attr_nf=int(cfg.model.edge_attr_nf))
        out = np.asarray(e.replicas.submit(dict(g)).result(timeout=300.0))
        ref = np.asarray(e.engine.predict(dict(g)))
        np.testing.assert_array_equal(out, ref)
        row = e.replicas.health()[0]
        assert row["backend"] == "process" and row["pid"] == pid
        assert row["heartbeat_age_s"] is not None
        workers = reg.health()["default"]["workers"]
        assert workers and workers[0]["pid"] == pid
        assert workers[0]["degraded"] is False
    finally:
        reg.stop()
    assert not wmod._LIVE, "a WorkerHandle leaked past registry.stop()"
    deadline = time.monotonic() + 10.0
    while os.path.exists(f"/proc/{pid}") and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not os.path.exists(f"/proc/{pid}"), "worker child outlived stop()"


def test_spawn_failure_degrades_to_in_process():
    """A spawn failure at start must DEGRADE, not shed: the replica falls
    back to an in-process queue on the parent's own params (bitwise the
    same predictions) and stays schedulable; health says degraded."""
    cfg = _small_cfg()
    reg = ModelRegistry.from_config(cfg)
    e = reg.get("default")
    r = e.replicas.replicas[0]
    r.fail_next_spawns(1)
    reg.start()
    try:
        assert r.degraded and r.queue.backend == "thread"
        assert r.queue.pid is None if hasattr(r.queue, "pid") else True
        g = synthetic_graph(24, seed=11,
                            feat_nf=int(cfg.model.node_feat_nf),
                            edge_attr_nf=int(cfg.model.edge_attr_nf))
        out = np.asarray(e.replicas.submit(dict(g)).result(timeout=300.0))
        ref = np.asarray(e.engine.predict(dict(g)))
        np.testing.assert_array_equal(out, ref)
        row = e.replicas.health()[0]
        assert row["degraded"] is True
    finally:
        reg.stop()
    assert not wmod._LIVE


# ---- the process chaos drill (slow) -----------------------------------------

def _save_params(path, params):
    save_checkpoint(str(path),
                    SimpleNamespace(params=params, opt_state={}, step=0),
                    epoch=0)


@pytest.mark.slow
@pytest.mark.process
def test_swap_racing_inflight_spawn_is_caught_up(tmp_path):
    """A hot-swap that defers WHILE a respawn is in flight must not strand
    the fresh worker on the pre-swap params. The respawn captured its
    checkpoint argument and expect_digest seconds before the swap landed
    (both pre-swap, so the parity handshake passes on OLD params); the
    post-spawn catch-up in start_queue must detect the divergence and swap
    the child over IPC before the replica goes back into rotation."""
    from distegnn_tpu.serve import engine_from_config

    cfg = _small_cfg()
    reg = ModelRegistry.from_config(cfg)
    e = reg.get("default")
    r = e.replicas.replicas[0]
    reg.start()
    try:
        params_b = jax.tree_util.tree_map(
            lambda x: x * 1.0625, e.engine.params)
        ck = tmp_path / "b.ckpt"
        _save_params(ck, params_b)
        g = synthetic_graph(6, seed=5,
                            feat_nf=int(cfg.model.node_feat_nf),
                            edge_attr_nf=int(cfg.model.edge_attr_nf))
        from distegnn_tpu.models.registry import get_model

        model_b = get_model(cfg.model, dataset_name=cfg.data.dataset_name)
        eng_b, _ = engine_from_config(cfg, model_b, params=params_b)
        ref_b = np.asarray(eng_b.predict(dict(g)))

        orig_spawn = r._spawn_worker

        def racing_spawn():
            # spawn captures checkpoint=None + the OLD expect_digest, then
            # the swap completes before start_queue's catch-up check runs
            h = orig_spawn()
            r.current_checkpoint = str(ck)
            e.engine.params = params_b
            return h

        r._spawn_worker = racing_spawn
        old_pid = r.queue.pid
        os.kill(old_pid, signal.SIGKILL)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and r.state == "running":
            time.sleep(0.05)
        while time.monotonic() < deadline and not (
                r.healthy() and r.state == "running"
                and getattr(r.queue, "pid", None) not in (None, old_pid)):
            time.sleep(0.1)
        w = r.queue.worker
        assert w is not None and w.checkpoint == str(ck), \
            "post-spawn catch-up did not move the worker to the swapped " \
            "checkpoint"
        out = np.asarray(e.replicas.submit(dict(g)).result(timeout=300.0))
        np.testing.assert_array_equal(out, ref_b)

        # residual window: a deferral that lands after the catch-up check is
        # healed by the supervisor-tick reconcile (parent-side compare only)
        w.checkpoint = None
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and \
                r.queue.worker.checkpoint != str(ck):
            time.sleep(0.1)
        assert r.queue.worker.checkpoint == str(ck)
    finally:
        reg.stop()
    assert not wmod._LIVE


@pytest.mark.slow
@pytest.mark.process
def test_chaos_drill_process_workers(tmp_path):
    """The PR's acceptance drill re-run under ``serve.workers: process``:
    2 worker children per model; kill9 SIGKILLs one mid-replay, a live
    blue/green swap crosses the IPC boundary, sigstop freezes the other
    child later. ZERO accepted requests lost, SLO PASS, the event stream
    shows detect → failover → escalate(SIGKILL) → respawn, the swap probe
    is bitwise-identical to a cold-started engine on the new checkpoint,
    and no worker process survives the run."""
    import base64
    import subprocess

    from distegnn_tpu.config import load_config
    from distegnn_tpu.serve import engine_from_config

    yaml_path = tmp_path / "drill.yaml"
    yaml_path.write_text(
        "model:\n"
        "  hidden_nf: 16\n"
        "  n_layers: 2\n"
        "  virtual_channels: 2\n"
        "serve:\n"
        "  workers: process\n"
        "  replicas: 2\n"
        "  request_timeout_ms: 120000\n"
        "  worker:\n"
        "    spawn_timeout_s: 300.0\n"
        "    heartbeat_s: 0.2\n"
        "    kill_grace_s: 2.0\n"
        "  supervisor:\n"
        "    heartbeat_s: 0.1\n"
        "    wedge_timeout_s: 30.0\n"
        "    worker_heartbeat_timeout_s: 1.5\n"
        "    backoff_base_s: 0.25\n"
        "    backoff_max_s: 2.0\n"
        "    breaker_threshold: 5\n"
        "    breaker_cooldown_s: 5.0\n"
        "    healthy_reset_s: 60.0\n"
        "seed: 43\n")
    cfg = load_config(str(yaml_path))
    # same deterministic init path the subprocess gateway runs, so the swap
    # checkpoint is structurally identical to the params being served
    entry = ModelRegistry.from_config(cfg).get("default")
    params_b = jax.tree.map(lambda x: x * 1.0625, entry.engine.params)
    ck = tmp_path / "b.ckpt"
    _save_params(ck, params_b)
    spec = tmp_path / "slo.yaml"
    spec.write_text("slo:\n"
                    "  routes:\n"
                    "    predict:\n"
                    "      p99_ms: 90000\n"
                    "  error_rate_max: 0.0\n")
    obs_dir = tmp_path / "tg"

    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "traffic_gen.py"),
         "--config_path", str(yaml_path),
         "--requests", "48", "--rate", "10", "--mix", "predict=1.0",
         "--sizes", "24", "--seed", "7", "--timeout-s", "240",
         "--chaos", (f"latency@0.05:s=0.05;kill9@0.5:replica=0;"
                     f"swap@2.5:ckpt={ck};sigstop@4.0:replica=1"),
         "--slo", str(spec), "--obs-dir", str(obs_dir)],
        capture_output=True, text=True, cwd=REPO, timeout=580,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr[-4000:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, r.stdout
    rec = json.loads(lines[0])

    # zero accepted requests lost through SIGKILL + SIGSTOP; SLO holds
    assert rec["completed"] == 48 and rec["lost"] == 0
    assert rec["errors"] == 0
    assert rec["slo"]["pass"] is True, rec["slo"]
    by_action = {c["action"]: c for c in rec["chaos"]}
    assert by_action["kill9"]["ok"] is True
    assert by_action["sigstop"]["ok"] is True
    assert by_action["swap"]["ok"] is True
    assert by_action["swap"]["swap"]["version"] == 1

    # detect -> failover -> escalate -> respawn, visible in the stream
    events = []
    with open(obs_dir / "obs" / "events.jsonl") as f:
        for line in f:
            events.append(json.loads(line))
    names = [e.get("name") for e in events]
    assert "gateway/worker_spawn" in names
    assert "gateway/replica_crash" in names       # kill9 detected
    assert "gateway/replica_wedge" in names       # sigstop: heartbeat stale
    exits = [e for e in events if e.get("name") == "gateway/worker_exit"]
    assert any(e.get("escalated") for e in exits), (
        "the SIGSTOPped child was never SIGKILL-escalated")
    assert "gateway/replica_restart" in names     # at least one respawn
    # the worker children produced their own stitched event streams
    worker_streams = [p for p in os.listdir(obs_dir / "obs")
                      if p.startswith("events_worker_")]
    assert worker_streams, "no worker-side event stream was written"

    # no orphan worker processes survive the run
    leftovers = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().decode(errors="replace")
        except OSError:
            continue
        if "distegnn_tpu.serve.worker" in cmd:
            leftovers.append(pid)
    assert leftovers == [], f"orphan worker processes: {leftovers}"

    # the swapped live gateway's probe prediction, bit for bit
    probe = next((e for e in events if e.get("name") == "chaos/swap_probe"),
                 None)
    assert probe is not None, "swap probe never fired"
    pd = probe["prediction"]
    live = np.frombuffer(base64.b64decode(pd["b64"]),
                         dtype="<f4").reshape(pd["shape"])
    g = synthetic_graph(24, seed=1234, feat_nf=int(cfg.model.node_feat_nf),
                        edge_attr_nf=int(cfg.model.edge_attr_nf))
    for k in ("loc", "vel", "node_feat", "edge_attr"):
        g[k] = np.ascontiguousarray(g[k], dtype="<f4")
    g["edge_index"] = np.ascontiguousarray(g["edge_index"], dtype="<i4")
    from distegnn_tpu.models.registry import get_model

    model = get_model(cfg.model, dataset_name=cfg.data.dataset_name)
    eng, q = engine_from_config(cfg, model, params=params_b)
    with q:
        cold = q.submit(g).result(timeout=240.0)
    np.testing.assert_array_equal(live, np.asarray(cold, dtype="<f4"))
