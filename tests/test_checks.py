"""Distributed consistency checks (parallel/checks.py)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distegnn_tpu.parallel.checks import assert_replicated, batch_fingerprint, tree_fingerprint


def _mesh():
    return Mesh(np.array(jax.devices()[:8]), ("d",))


def test_replicated_array_passes():
    mesh = _mesh()
    x = jnp.arange(64.0).reshape(8, 8)
    arr = jax.device_put(x, NamedSharding(mesh, P()))
    assert_replicated({"w": arr})


def test_diverged_copy_raises():
    mesh = _mesh()
    sharding = NamedSharding(mesh, P())
    # a "replicated" array whose device copies disagree — exactly the failure
    # mode the reference's broadcast+allclose check exists for
    base = np.arange(64.0, dtype=np.float32).reshape(8, 8)
    bufs = [jax.device_put(base + (1.0 if i == 3 else 0.0), d)
            for i, d in enumerate(mesh.devices.flat)]
    bad = jax.make_array_from_single_device_arrays((8, 8), sharding, bufs)
    with pytest.raises(AssertionError, match="diverged"):
        assert_replicated({"w": bad})


def test_sharded_leaf_skipped():
    mesh = _mesh()
    x = jnp.arange(64.0).reshape(8, 8)
    sharded = jax.device_put(x, NamedSharding(mesh, P("d")))
    assert_replicated({"w": sharded})  # not replicated -> not checked


def test_batch_fingerprint_is_order_sensitive():
    a = {"x": np.arange(10.0), "y": np.ones(3)}
    b = {"x": np.arange(10.0), "y": np.ones(3)}
    assert batch_fingerprint(a) == batch_fingerprint(b)
    b["x"] = b["x"][::-1].copy()
    assert batch_fingerprint(a) != batch_fingerprint(b)
    assert tree_fingerprint(a) == tree_fingerprint({"x": a["x"], "y": a["y"]})
