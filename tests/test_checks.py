"""Distributed consistency checks (parallel/checks.py)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distegnn_tpu.parallel.checks import assert_replicated, batch_fingerprint, tree_fingerprint


def _mesh():
    return Mesh(np.array(jax.devices()[:8]), ("d",))


def test_replicated_array_passes():
    mesh = _mesh()
    x = jnp.arange(64.0).reshape(8, 8)
    arr = jax.device_put(x, NamedSharding(mesh, P()))
    assert_replicated({"w": arr})


def test_diverged_copy_raises():
    mesh = _mesh()
    sharding = NamedSharding(mesh, P())
    # a "replicated" array whose device copies disagree — exactly the failure
    # mode the reference's broadcast+allclose check exists for
    base = np.arange(64.0, dtype=np.float32).reshape(8, 8)
    bufs = [jax.device_put(base + (1.0 if i == 3 else 0.0), d)
            for i, d in enumerate(mesh.devices.flat)]
    bad = jax.make_array_from_single_device_arrays((8, 8), sharding, bufs)
    with pytest.raises(AssertionError, match="diverged"):
        assert_replicated({"w": bad})


def test_sharded_leaf_skipped():
    mesh = _mesh()
    x = jnp.arange(64.0).reshape(8, 8)
    sharded = jax.device_put(x, NamedSharding(mesh, P("d")))
    assert_replicated({"w": sharded})  # not replicated -> not checked


def test_batch_fingerprint_is_order_sensitive():
    a = {"x": np.arange(10.0), "y": np.ones(3)}
    b = {"x": np.arange(10.0), "y": np.ones(3)}
    assert batch_fingerprint(a) == batch_fingerprint(b)
    b["x"] = b["x"][::-1].copy()
    assert batch_fingerprint(a) != batch_fingerprint(b)
    assert tree_fingerprint(a) == tree_fingerprint({"x": a["x"], "y": a["y"]})


def test_in_step_batch_consistency_detects_partition_drift():
    """The traced in-step loc_mean check (train/step.py): zero on clean data,
    nonzero when one partition's host data drifted; assert_batch_consistency
    raises on the nonzero residual (reference utils/train.py:55-61 parity)."""
    import jax
    import numpy as np
    import pytest
    from jax.sharding import PartitionSpec as P

    from distegnn_tpu.data import build_nbody_graph
    from distegnn_tpu.data.partition import split_graph
    from distegnn_tpu.models.fast_egnn import FastEGNN
    from distegnn_tpu.ops.graph import pad_graphs
    from distegnn_tpu.parallel.launch import global_batch_putter, make_distributed_steps
    from distegnn_tpu.parallel.mesh import GRAPH_AXIS, make_mesh
    from distegnn_tpu.train import TrainState, make_optimizer
    from distegnn_tpu.train.trainer import assert_batch_consistency

    rng = np.random.default_rng(0)
    n = 24
    loc = rng.normal(size=(n, 3))
    g = build_nbody_graph(loc, rng.normal(size=(n, 3)),
                          rng.choice([1.0, -1.0], size=(n, 1)),
                          loc * 1.01, radius=-1.0)
    parts = split_graph(g, 2, "random", inner_radius=2.5, seed=1)
    n_max = max(p["loc"].shape[0] for p in parts)
    e_max = max(p["edge_index"].shape[1] for p in parts)
    pbs = [pad_graphs([p], max_nodes=n_max + 2, max_edges=e_max + 8) for p in parts]
    batch = jax.tree.map(lambda *xs: np.stack(xs, axis=0), *pbs)

    mesh = make_mesh(n_graph=2, devices=jax.devices()[:2])
    model = FastEGNN(node_feat_nf=2, edge_attr_nf=2, hidden_nf=8,
                     virtual_channels=2, n_layers=1, axis_name=GRAPH_AXIS)
    params = model.copy(axis_name=None).init(
        jax.random.PRNGKey(0), jax.tree.map(lambda x: x[0], batch))
    tx = make_optimizer(1e-3)
    step, _ = make_distributed_steps(model, tx, mesh, mmd_weight=0.0,
                                     mmd_sigma=1.0, mmd_samples=2)
    put = global_batch_putter(mesh)

    state = TrainState.create(params, tx)
    _, metrics = step(state, put(batch), jax.random.PRNGKey(1))
    assert float(metrics["batch_consistency"]) == 0.0
    assert_batch_consistency(metrics["batch_consistency"], epoch=1)  # no raise

    lm = np.array(batch.loc_mean)
    lm[1] += 0.5  # partition 1's host copy drifts
    _, metrics = step(state, put(batch.replace(loc_mean=lm)), jax.random.PRNGKey(1))
    assert float(metrics["batch_consistency"]) > 0.1
    with pytest.raises(AssertionError, match="batch mismatch"):
        assert_batch_consistency(metrics["batch_consistency"], epoch=1)
