"""On-device rollout (distegnn_tpu/rollout.py): one scan step must equal a
hand-built host-graph model application, and multi-step runs stay finite."""

import numpy as np
import jax
import jax.numpy as jnp

from distegnn_tpu.models.fast_egnn import FastEGNN
from distegnn_tpu.ops.graph import pad_graphs
from distegnn_tpu.ops.radius import radius_graph_np
from distegnn_tpu.rollout import make_rollout_fn


def _setup():
    rng = np.random.default_rng(0)
    N = 256  # one edge_block
    loc = rng.uniform(0, 1, size=(N, 3)).astype(np.float32)
    vel = (rng.normal(size=(N, 3)) * 0.05).astype(np.float32)
    model = FastEGNN(node_feat_nf=1, edge_attr_nf=2, hidden_nf=16,
                     virtual_channels=2, n_layers=2)
    return rng, N, loc, vel, model


def test_one_step_matches_host_graph():
    rng, N, loc, vel, model = _setup()
    r = 0.18
    ei_host = radius_graph_np(loc, r)
    d = np.linalg.norm(loc[ei_host[0]] - loc[ei_host[1]], axis=1)
    graph = {
        "node_feat": np.linalg.norm(vel, axis=1, keepdims=True).astype(np.float32),
        "loc": loc, "vel": vel, "target": loc,
        "edge_index": ei_host,
        "edge_attr": np.repeat(d[:, None], 2, axis=1).astype(np.float32),
    }
    batch = pad_graphs([graph], edge_block=256)
    params = model.init(jax.random.PRNGKey(0), batch)
    x_ref, _ = model.apply(params, batch)

    rollout = make_rollout_fn(model, r, max_degree=32, max_per_cell=32)
    traj, over = jax.jit(rollout, static_argnums=(4,))(
        params, jnp.asarray(loc), jnp.asarray(vel), jnp.ones(N), 1)
    assert not bool(over.any())
    np.testing.assert_allclose(np.asarray(traj[0]), np.asarray(x_ref[0][:N]),
                               atol=5e-5)


def test_evaluate_rollout_cli(tmp_path, capsys):
    """scripts/evaluate_rollout.py end to end — main() with argv on
    synthesized tiny n-body trajectory files: emits one JSON line with a
    per-horizon MSE for every comparable frame."""
    import json

    import yaml

    from scripts.evaluate_rollout import main as eval_main

    rng = np.random.default_rng(1)
    num, T, n = 2, 50, 12
    base = tmp_path / "nbody_tiny"
    base.mkdir()
    loc = rng.normal(size=(num, T, n, 3)).astype(np.float32)
    vel = rng.normal(size=(num, T, n, 3)).astype(np.float32) * 0.1
    q = rng.choice([-1.0, 1.0], size=(num, n, 1)).astype(np.float32)
    for name, arr in (("loc", loc), ("vel", vel), ("charges", q)):
        np.save(base / f"{name}_test_tiny.npy", arr)

    cfg = {
        "model": {"model_name": "FastEGNN", "node_feat_nf": 2, "node_attr_nf": 0,
                  "edge_attr_nf": 2, "hidden_nf": 8, "virtual_channels": 2,
                  "n_layers": 1, "normalize": False},
        "data": {"data_dir": str(tmp_path), "dataset_name": "nbody_tiny",
                 "radius": -1.0, "frame_0": 30, "frame_T": 40},
    }
    cfg_path = tmp_path / "cfg.yaml"
    cfg_path.write_text(yaml.safe_dump(cfg))

    # --samples larger than the dataset: output must report the real count
    eval_main(["--config_path", str(cfg_path), "--samples", "5"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"] == "rollout_mse"
    assert rec["samples"] == 2 and rec["steps"] == 1
    assert np.isfinite(rec["horizons"]["40"])


def test_evaluate_water3d_rollout(tmp_path):
    """Water-3D multi-step rollout eval on a synthetic h5: per-step horizons,
    velocity convention rescaled by 1/delta_t."""
    import h5py

    from scripts.evaluate_rollout import evaluate_water3d_rollout
    from distegnn_tpu.config import ConfigDict

    rng = np.random.default_rng(2)
    base = tmp_path / "Water-3D"
    base.mkdir()
    T, n = 14, 20
    with h5py.File(base / "test.h5", "w") as f:
        for i in range(2):
            pos0 = rng.uniform(0, 0.4, size=(n, 3)).astype(np.float32)
            drift = rng.normal(size=(1, n, 3)).astype(np.float32) * 0.002
            pos = pos0[None] + drift * np.arange(T)[:, None, None]
            grp = f.create_group(f"traj_{i}")
            grp["position"] = pos
            grp["particle_type"] = np.full((n,), 5.0, np.float32)

    config = ConfigDict({
        "model": {"model_name": "FastEGNN", "node_feat_nf": 2, "node_attr_nf": 0,
                  "edge_attr_nf": 2, "hidden_nf": 8, "virtual_channels": 2,
                  "n_layers": 1, "normalize": False},
        "data": {"data_dir": str(tmp_path), "dataset_name": "Water-3D",
                 "radius": 0.12, "delta_t": 4},
    })
    horizons, steps, num = evaluate_water3d_rollout(config, samples=2,
                                                    max_steps=3)
    assert num == 2 and steps == 3 and sorted(horizons) == [1, 2, 3]
    assert all(np.isfinite(v) for v in horizons.values())


def test_evaluate_fluid113k_rollout(tmp_path):
    """Fluid113K (LargeFluid) rollout eval on format-identical synthetic
    shards: per-step horizons over the zstd/msgpack simulations."""
    from scripts.evaluate_rollout import evaluate_fluid113k_rollout
    from scripts.generate_fluid_synthetic import synth_sim
    from distegnn_tpu.config import ConfigDict
    from distegnn_tpu.data.fluid113k import SIM_SPLITS, write_fluid_sim

    rng = np.random.default_rng(3)
    n, frames, radius = 60, 13, 0.25
    lo, _ = SIM_SPLITS["test"]
    for i in range(2):
        pos, vel = synth_sim(rng, n, frames, radius)
        write_fluid_sim(str(tmp_path), "Fluid113K", lo + i, pos, vel,
                        np.full((n,), 0.01, np.float32),
                        np.full((n,), 0.1, np.float32))

    config = ConfigDict({
        "model": {"model_name": "FastEGNN", "node_feat_nf": 3, "node_attr_nf": 2,
                  "edge_attr_nf": 2, "hidden_nf": 8, "virtual_channels": 2,
                  "n_layers": 1, "normalize": False},
        "data": {"data_dir": str(tmp_path), "dataset_name": "Fluid113K",
                 "radius": radius, "inner_radius": radius, "delta_t": 4},
    })
    horizons, steps, num = evaluate_fluid113k_rollout(config, samples=2,
                                                      max_steps=2)
    assert num == 2 and steps == 2 and sorted(horizons) == [1, 2]
    assert all(np.isfinite(v) for v in horizons.values())

    # checkpoint path: a TRAINED largefluid-shaped model (node_attr_nf=2)
    # must restore into the evaluator's init tree — catches any width drift
    # between the rollout batch and the training batch (node_attr included)
    from distegnn_tpu.models.registry import get_model
    from distegnn_tpu.train import TrainState, make_optimizer
    from distegnn_tpu.train.checkpoint import save_checkpoint

    from distegnn_tpu.data.fluid113k import build_fluid_graph
    from distegnn_tpu.ops.graph import pad_graphs
    from distegnn_tpu.ops.radius import radius_graph_np

    pos, vel = synth_sim(rng, n, frames, radius)
    g = build_fluid_graph(pos[0], vel[0], np.full((n,), 0.01, np.float32),
                          np.full((n,), 0.1, np.float32), pos[4])
    g["edge_index"] = radius_graph_np(pos[0], radius)
    d = np.linalg.norm(pos[0][g["edge_index"][0]] - pos[0][g["edge_index"][1]], axis=1)
    g["edge_attr"] = np.repeat(d[:, None].astype(np.float32), 2, axis=1)
    model = get_model(config.model, dataset_name="Fluid113K")
    import jax as _jax

    params = model.init(_jax.random.PRNGKey(1), pad_graphs([g]))
    tx = make_optimizer(1e-3)
    ckpt = str(tmp_path / "ck" / "best_model.ckpt")
    save_checkpoint(ckpt, TrainState.create(params, tx), epoch=1)
    horizons2, _, _ = evaluate_fluid113k_rollout(config, checkpoint=ckpt,
                                                 samples=1, max_steps=1)
    assert np.isfinite(horizons2[1])


def test_multi_step_finite_and_overflow_reported():
    rng, N, loc, vel, model = _setup()
    batch_proto = pad_graphs([{
        "node_feat": np.linalg.norm(vel, axis=1, keepdims=True).astype(np.float32),
        "loc": loc, "vel": vel, "target": loc,
        "edge_index": radius_graph_np(loc, 0.18),
        "edge_attr": np.ones((radius_graph_np(loc, 0.18).shape[1], 2), np.float32),
    }], edge_block=256)
    params = model.init(jax.random.PRNGKey(1), batch_proto)

    rollout = make_rollout_fn(model, 0.18, max_degree=32, max_per_cell=32)
    traj, over = jax.jit(rollout, static_argnums=(4,))(
        params, jnp.asarray(loc), jnp.asarray(vel), jnp.ones(N), 4)
    assert traj.shape == (4, N, 3)
    assert np.isfinite(np.asarray(traj)).all()
    assert over.shape == (4,)
