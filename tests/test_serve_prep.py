"""Session-affinity prep cache (distegnn_tpu/serve/prep.py) and the online
blocked re-pack (ops.blocked.repack_blocked): hits are bitwise-identical to
misses, topology changes invalidate cleanly, eviction is LRU, and the
re-packed layout aggregates exactly like the raw edge list."""

import numpy as np
import pytest

from distegnn_tpu.ops.blocked import max_block_degree, repack_blocked
from distegnn_tpu.serve import (BucketLadder, ServeMetrics, SessionPrepCache,
                                synthetic_graph)

pytestmark = pytest.mark.serve


def _ladder():
    return BucketLadder(node_floor=64, edge_floor=256, growth=2.0,
                        node_multiple=8, edge_multiple=128,
                        max_nodes=4096, max_edges=65536)


def _assert_graph_equal(a, b):
    assert set(a) == set(b)
    for k in a:
        if a[k] is None:
            assert b[k] is None
        else:
            np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                          err_msg=f"key {k!r} differs")


# -------------------------------------------------------------- plain plans

def test_plain_hit_bitwise_identical_to_miss():
    cache = SessionPrepCache(4, ladder=_ladder(), metrics=ServeMetrics())
    g = synthetic_graph(40, seed=1)
    miss = cache.prepare("s1", g)
    hit = cache.prepare("s1", g)
    assert miss.hit is False and hit.hit is True
    assert miss.bucket == hit.bucket and miss.perm is None
    _assert_graph_equal(miss.graph, hit.graph)
    snap = cache.metrics.snapshot()
    assert snap["session_hits"] == 1 and snap["session_misses"] == 1


def test_plain_hit_with_moved_positions_not_invalidated():
    """Frames move, topology doesn't: new positions on the same edge_index
    stay a HIT, and the fresh positions flow through to the prepared dict."""
    cache = SessionPrepCache(4, ladder=_ladder())
    g = synthetic_graph(40, seed=2)
    cache.prepare("s", g)
    g2 = dict(g)
    g2["loc"] = g["loc"] + np.float32(0.01)
    res = cache.prepare("s", g2)
    assert res.hit is True
    np.testing.assert_array_equal(res.graph["loc"], g2["loc"])


def test_topology_change_clean_miss_not_eviction():
    m = ServeMetrics()
    cache = SessionPrepCache(4, ladder=_ladder(), metrics=m)
    g = synthetic_graph(40, seed=3)
    cache.prepare("s", g)
    g2 = dict(g)
    g2["edge_index"] = g["edge_index"][:, :-2]   # drop two edges
    g2["edge_attr"] = g["edge_attr"][:-2]
    res = cache.prepare("s", g2)
    assert res.hit is False                      # stale plan never replayed
    snap = m.snapshot()
    assert snap["session_misses"] == 2 and snap["session_evictions"] == 0
    assert len(cache) == 1                       # replaced in place


def test_lru_eviction_counts_and_drops_oldest():
    m = ServeMetrics()
    cache = SessionPrepCache(2, ladder=_ladder(), metrics=m)
    gs = {f"s{k}": synthetic_graph(40, seed=10 + k) for k in range(3)}
    cache.prepare("s0", gs["s0"])
    cache.prepare("s1", gs["s1"])
    cache.prepare("s2", gs["s2"])                # evicts s0
    assert len(cache) == 2
    assert m.snapshot()["session_evictions"] == 1
    assert cache.prepare("s0", gs["s0"]).hit is False   # s0 gone
    assert cache.prepare("s2", gs["s2"]).hit is True    # s2 kept


# ------------------------------------------------------------ blocked plans

@pytest.mark.parametrize("split_remote", [False, True])
def test_blocked_hit_bitwise_identical_and_stamped(split_remote):
    block = 512 if split_remote else 256
    cache = SessionPrepCache(
        4, ladder=_ladder(),
        layout_opts={"edge_block": block, "split_remote": split_remote})
    g = synthetic_graph(90, seed=4)
    miss = cache.prepare("s", g)
    hit = cache.prepare("s", g)
    assert miss.hit is False and hit.hit is True
    _assert_graph_equal(miss.graph, hit.graph)
    out = miss.graph
    assert out["_blockified"] is not None        # pad_graphs prep is a no-op
    assert out["_edge_pair"] is None
    assert miss.perm is not None and sorted(miss.perm) == list(range(90))
    if split_remote:
        assert out["_remote_sel"] is not None
    # the perm is undone by indexing: permuted loc at inverse matches raw
    np.testing.assert_array_equal(out["loc"], np.asarray(g["loc"])[miss.perm])


def test_blocked_plan_aggregation_parity():
    """The re-packed edge list computes the same per-node aggregate as the
    raw one: sum of edge_attr into rows, masked padding contributing zero."""
    g = synthetic_graph(90, seed=5)
    cache = SessionPrepCache(2, ladder=_ladder(),
                             layout_opts={"edge_block": 256})
    res = cache.prepare("s", g)
    out = res.graph
    ei, ea = np.asarray(g["edge_index"]), np.asarray(g["edge_attr"])
    # raw aggregate, relabeled into the plan's node order
    inv = np.empty_like(res.perm)
    inv[res.perm] = np.arange(len(res.perm))
    raw = np.zeros((len(res.perm), ea.shape[1]), np.float32)
    np.add.at(raw, inv[ei[0]], ea)
    packed = np.zeros_like(raw)
    m = np.asarray(out["_edge_mask"], bool)
    rows = np.asarray(out["edge_index"][0])[m]
    assert (rows < len(res.perm)).all()   # real rows are real nodes
    np.add.at(packed, rows, np.asarray(out["edge_attr"])[m])
    np.testing.assert_allclose(packed, raw, atol=1e-5, rtol=0)


def test_repack_blocked_invariants_direct():
    """repack_blocked alone: rows land inside their block's slice, padding
    slots are self-loops on the block's last node, and apply_edge_attr moves
    attrs to exactly the slots their edges moved to."""
    rng = np.random.default_rng(0)
    n, e, block, epb = 512, 900, 256, 512
    ei = np.stack([rng.integers(0, n, e), rng.integers(0, n, e)]).astype(np.int32)
    plan = repack_blocked(ei, None, n_nodes_padded=n, epb=epb, block=block)
    nb = n // block
    out_ei = np.asarray(plan.edge_index)
    mask = np.asarray(plan.edge_mask, bool)
    assert out_ei.shape == (2, nb * epb) and mask.sum() == e
    for b in range(nb):
        sl = slice(b * epb, (b + 1) * epb)
        rows = out_ei[0, sl]
        assert ((rows >= b * block) & (rows < (b + 1) * block)).all()
        # padding slots: row == col == the block's last node
        pad = ~mask[sl]
        assert (rows[pad] == (b + 1) * block - 1).all()
        assert (out_ei[1, sl][pad] == (b + 1) * block - 1).all()
    # attr transport: each real slot carries its source edge's attr
    attr = rng.normal(size=(e, 3)).astype(np.float32)
    moved = plan.apply_edge_attr(attr)
    # multiset equality per (row, col): sort both sides canonically
    raw = sorted(map(tuple, np.concatenate(
        [ei.T.astype(np.float32), attr], axis=1).tolist()))
    packed = sorted(map(tuple, np.concatenate(
        [out_ei.T[mask].astype(np.float32), moved[mask]], axis=1).tolist()))
    assert raw == packed
    # epb honored the block-degree floor
    assert epb >= max_block_degree(np.sort(ei[0]), n, block)
