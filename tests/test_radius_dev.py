"""On-device radius graph (ops/radius_dev.py) vs the host cell-list."""

import numpy as np
import jax
import jax.numpy as jnp

from distegnn_tpu.ops.radius import radius_graph_np
from distegnn_tpu.ops.radius_dev import ell_to_edge_list, radius_graph_dev


def _edge_set(ei, mask=None):
    ei = np.asarray(ei)
    if mask is not None:
        ei = ei[:, np.asarray(mask) > 0]
    return set(map(tuple, ei.T.tolist()))


def test_matches_host_cell_list():
    rng = np.random.default_rng(0)
    pos = rng.uniform(0, 1, size=(500, 3)).astype(np.float32)
    r = 0.12
    ref = _edge_set(radius_graph_np(pos, r))

    g = jax.jit(lambda p: radius_graph_dev(p, r, max_degree=32, max_per_cell=16))(
        jnp.asarray(pos))
    assert not bool(g.cell_overflow) and not bool(g.degree_overflow)
    ei, mask = ell_to_edge_list(g)
    assert _edge_set(ei, mask) == ref
    # degrees agree with the reference graph
    deg_ref = np.bincount(np.array(sorted(ref))[:, 0], minlength=500)
    np.testing.assert_array_equal(np.asarray(g.degree), deg_ref)


def test_node_mask_isolates():
    rng = np.random.default_rng(1)
    pos = rng.uniform(0, 1, size=(64, 3)).astype(np.float32)
    mask = (rng.uniform(size=64) > 0.3).astype(np.float32)
    g = radius_graph_dev(jnp.asarray(pos), 0.3, max_degree=64, max_per_cell=32,
                        node_mask=jnp.asarray(mask))
    ei, em = ell_to_edge_list(g)
    edges = _edge_set(ei, em)
    ref = _edge_set(radius_graph_np(pos[mask > 0], 0.3))
    # remap reference indices (built on the compacted array) to original ids
    ids = np.nonzero(mask > 0)[0]
    ref = {(ids[i], ids[j]) for i, j in ref}
    assert edges == ref


def test_overflow_flags():
    pos = np.zeros((20, 3), np.float32)  # everyone in one cell
    # generous cells, tight degree -> degree overflow only
    g = radius_graph_dev(jnp.asarray(pos), 0.5, max_degree=4, max_per_cell=32)
    assert not bool(g.cell_overflow) and bool(g.degree_overflow)
    # tight cells -> cell overflow (degree is counted post-truncation)
    g2 = radius_graph_dev(jnp.asarray(pos), 0.5, max_degree=32, max_per_cell=4)
    assert bool(g2.cell_overflow)


def test_blocked_layout_compatible():
    """ell_to_edge_list output feeds the MXU kernels directly."""
    from distegnn_tpu.ops.blocked import blocked_segment_sum, slot_ids
    from distegnn_tpu.ops.segment import segment_sum

    rng = np.random.default_rng(2)
    N, K, block, tile = 512, 16, 256, 512
    pos = rng.uniform(0, 1, size=(N, 3)).astype(np.float32)
    g = radius_graph_dev(jnp.asarray(pos), 0.15, max_degree=K, max_per_cell=32)
    assert not bool(g.degree_overflow)
    ei, em = ell_to_edge_list(g)
    epb = K * block  # per-node uniform slots -> blocked invariant by layout
    assert epb % tile == 0
    slots = slot_ids(ei[0][None], em[None], block, epb)
    data = jnp.asarray(rng.normal(size=(N * K, 8)).astype(np.float32))
    out = blocked_segment_sum(data[None], slots, N, block, tile)[0]
    ref = segment_sum(data, ei[0], N, mask=em)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_padded_nodes_no_spurious_overflow():
    """Many masked nodes must not trip cell_overflow (they'd all share one
    bucket otherwise) — the padded-rollout case."""
    rng = np.random.default_rng(3)
    pos = np.zeros((130, 3), np.float32)
    pos[:100] = rng.uniform(0, 1, size=(100, 3))
    mask = np.concatenate([np.ones(100), np.zeros(30)]).astype(np.float32)
    g = radius_graph_dev(jnp.asarray(pos), 0.2, max_degree=32, max_per_cell=8,
                        node_mask=jnp.asarray(mask))
    assert not bool(g.cell_overflow)
    assert np.all(np.asarray(g.nbr_mask)[100:] == 0)
