"""Tier-1 tests for the obs subsystem (distegnn_tpu/obs).

Covers the acceptance surface of the observability PR: span nesting and
timing into JSONL, event round-trip, the obs.enable kill switch (no files,
no-ops), recompile detection through a REAL forced shape change, metrics
primitives + the single nearest-rank percentile implementation, Prometheus
text rendering, the run-report summarize/render/check pipeline, and the
no-bare-print lint (scripts/check_no_print.py) wired into tier-1.

The global tracer is process state; every test that rebinds it goes through
the ``clean_obs`` fixture so it is restored to the sinkless default (and the
compile watcher deactivated) regardless of outcome.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from distegnn_tpu.config import ConfigDict, _DEFAULTS
from distegnn_tpu.obs import jaxprobe, report, trace
from distegnn_tpu.obs.metrics import (
    Counter,
    Gauge,
    LatencyReservoir,
    MetricsRegistry,
    percentile,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def clean_obs():
    """Restore the sinkless global tracer + no active compile watcher after
    a test that configures either."""
    yield
    trace.configure(log_dir=None)
    jaxprobe.deactivate_compile_watcher()


def read_events(path):
    events, bad = report.load_events(path)
    assert bad == 0, f"unparseable lines in {path}"
    return events


# ---- percentile: the single implementation (serve/metrics imports it) ------

@pytest.mark.parametrize("vals", [
    [1.0], [1.0, 2.0], [5.0, 1.0, 4.0, 2.0, 3.0],
    list(float(i) for i in range(100)),
])
@pytest.mark.parametrize("q", [0, 50, 99, 100])
def test_percentile_properties(vals, q):
    s = sorted(vals)
    p = percentile(s, q)
    assert p in s                      # nearest-rank: always a real sample
    assert s[0] <= p <= s[-1]
    if q == 0:
        assert p == s[0]
    if q == 100:
        assert p == s[-1]


def test_percentile_monotone_in_q():
    s = sorted(float(i) for i in range(37))
    ps = [percentile(s, q) for q in (0, 25, 50, 75, 99, 100)]
    assert ps == sorted(ps)


def test_percentile_empty_and_serve_reexport():
    assert percentile([], 50) == 0.0
    assert percentile([], 0) == 0.0
    # serve/metrics re-exports the same function (the old _percentile name)
    from distegnn_tpu.serve.metrics import _percentile
    assert _percentile is percentile


# ---- metrics primitives + registry -----------------------------------------

def test_registry_primitives_and_snapshot():
    reg = MetricsRegistry()
    reg.counter("a/count").add(3)
    reg.counter("a/count").add(2)          # get-or-create: same instance
    reg.gauge("b/depth").set(7)
    r = reg.reservoir("c/lat_ms")
    r.record_many([1.0, 2.0, 3.0, 4.0])
    r.record(5.0)

    snap = reg.snapshot()
    assert snap["a/count"] == 5
    assert snap["b/depth"] == 7
    assert snap["c/lat_ms_count"] == 5
    assert snap["c/lat_ms_sum"] == 15.0
    assert snap["c/lat_ms_p50"] == 3.0
    assert snap["c/lat_ms_p99"] == 5.0
    # snapshot is one JSON object
    assert json.loads(reg.to_json()) == snap


def test_registry_kind_mismatch_raises():
    reg = MetricsRegistry()
    reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")


def test_reservoir_bounded():
    r = LatencyReservoir(size=10)
    r.record_many([float(i) for i in range(100)])
    assert r.count == 100                  # total ever recorded
    assert len(r.values()) == 10           # reservoir keeps the tail
    assert r.values() == [float(i) for i in range(90, 100)]
    assert r.total == sum(range(100))


def test_render_prometheus_parses():
    reg = MetricsRegistry()
    reg.counter("data/stall_s").add(1.5)
    reg.gauge("queue-depth").set(3)        # '-' must be sanitized
    reg.reservoir("step/ms").record_many([1.0, 2.0, 3.0])
    text = reg.render_prometheus(prefix="distegnn")

    lines = [l for l in text.splitlines() if l]
    types = {}
    for l in lines:
        if l.startswith("# TYPE "):
            _, _, name, kind = l.split()
            types[name] = kind
        else:                              # sample line: name{labels}? value
            name = l.split("{")[0].split()[0]
            val = l.rsplit(" ", 1)[1]
            float(val)                     # every sample value parses
            base = name
            for suf in ("_sum", "_count"):
                if base.endswith(suf) and base[: -len(suf)] in types:
                    base = base[: -len(suf)]
            assert base in types, f"sample {name} missing # TYPE"
            # prometheus-legal metric name
            assert all(c.isalnum() or c in "_:" for c in name)
    assert types["distegnn_data_stall_s"] == "counter"
    assert types["distegnn_queue_depth"] == "gauge"
    assert types["distegnn_step_ms"] == "summary"
    assert 'distegnn_step_ms{quantile="0.50"} 2' in text


# ---- tracer: spans, events, JSONL round-trip -------------------------------

def test_span_nesting_and_timing(tmp_path, clean_obs):
    t = trace.configure(log_dir=str(tmp_path), tags={"run": "t"})
    assert t.enabled
    with t.span("outer", a=1):
        with t.span("inner") as sp:
            sp.set(detail="x")
    t.event("solo", n=3)
    t.flush()

    events = read_events(os.path.join(str(tmp_path), "events.jsonl"))
    assert [e["name"] for e in events] == ["inner", "outer", "solo"]
    inner, outer, solo = events
    assert inner["kind"] == "span" and inner["detail"] == "x"
    assert outer["a"] == 1
    assert 0.0 <= inner["dur_s"] <= outer["dur_s"]  # nested block is shorter
    for e in events:                       # every record carries the tags
        assert e["run"] == "t" and "proc" in e and "host" in e
    assert solo["kind"] == "event" and solo["n"] == 3


def test_span_records_error_and_jsonl_survives_weird_attrs(tmp_path, clean_obs):
    t = trace.configure(log_dir=str(tmp_path))
    with pytest.raises(ValueError):
        with t.span("boom"):
            raise ValueError("x")
    t.event("weird", obj=object(), nan=float("nan"))  # default=repr fallback
    t.flush()
    events = read_events(os.path.join(str(tmp_path), "events.jsonl"))
    assert events[0]["name"] == "boom" and events[0]["error"] == "ValueError"
    assert "object" in events[1]["obj"]


def test_log_is_stdout_compatible_and_mirrored(tmp_path, capsys, clean_obs):
    t = trace.configure(log_dir=str(tmp_path))
    t.log("Epoch 3 ok", epoch=3)
    t.flush()
    # stdout line identical to what the old print produced (process 0)
    assert capsys.readouterr().out == "Epoch 3 ok\n"
    events = read_events(os.path.join(str(tmp_path), "events.jsonl"))
    assert events[0]["kind"] == "log" and events[0]["msg"] == "Epoch 3 ok"
    assert events[0]["epoch"] == 3


def test_disabled_tracer_emits_nothing(tmp_path, capsys, clean_obs):
    """The obs.enable:false kill switch: no files, span/event no-ops, log
    still prints."""
    cfg = ConfigDict({**_DEFAULTS, "obs": {**_DEFAULTS["obs"], "enable": False}})
    t = trace.configure_from_config(cfg, str(tmp_path / "exp"))
    assert not t.enabled
    with t.span("x"):
        t.event("y")
    t.log("still prints")
    t.flush()
    assert not (tmp_path / "exp").exists()   # not even the directory
    assert capsys.readouterr().out == "still prints\n"
    assert jaxprobe.get_compile_watcher() is None  # probe not installed


def test_configure_from_config_defaults_on(tmp_path, clean_obs):
    cfg = ConfigDict(_DEFAULTS)
    t = trace.configure_from_config(cfg, str(tmp_path / "exp"), tags={"run": "r"})
    assert t.enabled
    assert t.writer.path == str(tmp_path / "exp" / "obs" / "events.jsonl")
    assert jaxprobe.get_compile_watcher() is not None
    t.event("one")
    t.flush()
    assert len(read_events(t.writer.path)) == 1
    # enabled_here=False (train(log=False) test runs) leaves no files either
    t2 = trace.configure_from_config(cfg, str(tmp_path / "exp2"),
                                     enabled_here=False)
    assert not t2.enabled and not (tmp_path / "exp2").exists()


def test_module_level_api_follows_reconfigure(tmp_path, clean_obs):
    from distegnn_tpu import obs
    obs.configure(log_dir=str(tmp_path))
    obs.event("a")
    with obs.span("b"):
        pass
    obs.flush()
    assert [e["name"] for e in
            read_events(str(tmp_path / "events.jsonl"))] == ["a", "b"]


def test_writer_truncates_on_reconfigure(tmp_path, clean_obs):
    trace.configure(log_dir=str(tmp_path))
    trace.event("old")
    trace.flush()
    trace.configure(log_dir=str(tmp_path))   # same dir: fresh stream
    trace.event("new")
    trace.flush()
    events = read_events(str(tmp_path / "events.jsonl"))
    assert [e["name"] for e in events] == ["new"]


# ---- recompile detection (forced shape change) -----------------------------

def test_compile_watcher_detects_forced_recompile(tmp_path, clean_obs):
    import jax
    import jax.numpy as jnp

    t = trace.configure(log_dir=str(tmp_path))
    reg = MetricsRegistry()
    w = jaxprobe.install_compile_watcher(t, reg)
    w.set_phase("warmup")

    f = jax.jit(lambda x: x * 2 + 1)
    f(jnp.ones((4,))).block_until_ready()
    warm = w.snapshot()
    assert warm["compiles"] >= 1 and warm["compiles_after_warmup"] == 0

    w.set_phase("steady")
    w.mark_warmup_done()
    f(jnp.ones((4,))).block_until_ready()    # cached: no new compile
    assert w.snapshot()["compiles_after_warmup"] == 0

    f(jnp.ones((8,))).block_until_ready()    # forced shape change: recompile
    snap = w.snapshot()
    assert snap["compiles_after_warmup"] >= 1
    assert reg.counter("jax/compiles_after_warmup").value >= 1

    t.flush()
    compiles = [e for e in read_events(str(tmp_path / "events.jsonl"))
                if e["name"] == "jax/compile"]
    assert any(c["after_warmup"] and c["phase"] == "steady" for c in compiles)
    assert all(not c["after_warmup"] for c in compiles
               if c["phase"] == "warmup")


def test_transfer_meter_and_memory_stats():
    import numpy as np
    reg = MetricsRegistry()
    m = jaxprobe.TransferMeter(reg)
    n = m.h2d({"a": np.ones((4, 3), np.float32), "b": np.ones(2, np.float64)})
    assert n == 4 * 3 * 4 + 2 * 8
    assert reg.counter("xfer/h2d_bytes").value == n
    assert isinstance(jaxprobe.device_memory_stats(), dict)  # {} on CPU


# ---- report: summarize / render / check ------------------------------------

def _ev(name, kind="event", **attrs):
    return {"ts": 100.0, "kind": kind, "name": name, "proc": 0,
            "host": "h", **attrs}


def _sample_events():
    evs = [_ev("train/run_start")]
    evs += [_ev("jax/compile", phase="warmup", dur_s=1.0, after_warmup=False)]
    for i in range(10):
        evs.append(_ev("train/step", epoch=0, step=i,
                       dur_s=0.010 + 0.001 * i, stall_s=0.002))
    evs.append(_ev("train/epoch", epoch=0, dur_s=0.5, stall_s=0.02,
                   loss_train=1.25))
    evs.append(_ev("ckpt/save", path="e0.ckpt", epoch=0, bytes=1000,
                   dur_s=0.01))
    evs.append(_ev("serve/batch", n=64, e=256, filled=3, capacity=4,
                   dur_s=0.004))
    return evs


def test_summarize_and_render():
    s = report.summarize(_sample_events())
    assert s["n_events"] == len(_sample_events())
    assert s["steps"]["count"] == 10
    assert s["steps"]["p50_ms"] == pytest.approx(14.0, abs=1.1)
    assert s["steps"]["p99_ms"] == pytest.approx(19.0, abs=0.1)
    assert s["stall"]["stall_s"] == pytest.approx(0.02)
    frac = 0.02 / (sum(0.010 + 0.001 * i for i in range(10)) + 0.02)
    assert s["stall"]["fraction"] == pytest.approx(frac, rel=1e-3)
    assert s["compiles"]["total"] == 1
    assert s["compiles"]["after_warmup"] == 0
    assert s["checkpoints"] == {"saves": 1, "save_bytes": 1000,
                                "save_s": 0.01, "restores": 0}
    assert s["serve"]["batches"] == 1
    assert s["faults"] == []

    text = report.render_text(s, source="x.jsonl")
    assert "steps: 10" in text and "AFTER WARMUP" in text
    assert "fault timeline: clean" in text
    assert report.check(s) == []


def test_summarize_stall_falls_back_to_epochs():
    """scan-epoch runs emit no per-step events — stall comes from the
    per-epoch aggregates."""
    evs = [_ev("train/epoch", epoch=0, dur_s=2.0, stall_s=0.5)]
    s = report.summarize(evs)
    assert s["stall"]["stall_s"] == 0.5
    assert s["stall"]["fraction"] == pytest.approx(0.25)


def test_check_gates():
    assert report.check(report.summarize([])) != []          # zero events
    bad = report.summarize([_ev("jax/compile", phase="epoch3", dur_s=2.0,
                                after_warmup=True)])
    fails = report.check(bad)
    assert any("recompile" in f for f in fails)
    # fault timeline ordering + rendering
    evs = [_ev("train/divergence", epoch=2, msg=None),
           _ev("train/rollback", epoch=2, lr_scale=0.5)]
    evs[0]["ts"], evs[1]["ts"] = 10.0, 11.0
    s = report.summarize(evs)
    assert [f["name"] for f in s["faults"]] == ["train/divergence",
                                                "train/rollback"]
    assert "fault timeline:" in report.render_text(s)


def test_load_events_tolerates_torn_line(tmp_path):
    p = tmp_path / "e.jsonl"
    p.write_text('{"ts": 1, "kind": "event", "name": "a"}\n{"ts": 2, "ki')
    events, bad = report.load_events(str(p))
    assert len(events) == 1 and bad == 1


def test_obs_report_cli(tmp_path, clean_obs):
    t = trace.configure(log_dir=str(tmp_path))
    for i in range(3):
        t.event("train/step", epoch=0, step=i, dur_s=0.01, stall_s=0.0)
    t.flush()
    path = str(tmp_path / "events.jsonl")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         path, "--check"], capture_output=True, text=True, env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr
    assert "steps: 3" in r.stdout
    assert "obs_report --check: OK" in r.stderr
    # --json emits one parseable object
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         path, "--json"], capture_output=True, text=True, env=env, cwd=REPO)
    assert json.loads(r.stdout)["steps"]["count"] == 3
    # an empty stream fails --check
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         str(empty), "--check"], capture_output=True, text=True, env=env,
        cwd=REPO)
    assert r.returncode == 1
    assert "zero events" in r.stderr


# ---- lint: no bare print( in distegnn_tpu/ ---------------------------------

def test_no_bare_prints():
    """Tier-1 wiring of scripts/check_no_print.py: runtime output goes
    through obs.log() so it reaches the event stream; escape hatches are
    '# noqa: obs-print' or the script's allowlist."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from check_no_print import find_violations
    finally:
        sys.path.pop(0)
    violations = find_violations()
    assert violations == [], (
        "bare print( in distegnn_tpu/ — use obs.log() or mark the line "
        f"'# noqa: obs-print': {violations}")
