"""Tier-1 tests for request-level tracing, SLO evaluation, and the
mixed-traffic replay harness.

Covers the acceptance surface of the tracing/SLO PR: request-id minting
and sanitization, the waterfall stitcher (queue -> batch -> compute from
events.jsonl alone), the SLO spec/evaluator (NO DATA semantics, breach
exit codes through the obs_report CLI), the EventWriter atexit-flush and
per_host multi-process satellites (real subprocesses), the route-span
lint (scripts/check_route_spans.py) wired into tier-1, and the
traffic_gen end-to-end drill: a short mixed workload against a live
in-process gateway socket, one BENCH line, and a complete per-request
waterfall reconstructed by ``obs_report.py --request``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from distegnn_tpu.obs import report, slo, trace
from distegnn_tpu.obs.metrics import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def clean_obs():
    yield
    trace.configure(log_dir=None)


def read_events(path):
    events, bad = report.load_events(path)
    assert bad == 0, f"unparseable lines in {path}"
    return events


def _ev(name, kind="event", ts=100.0, **attrs):
    return {"ts": ts, "kind": kind, "name": name, "proc": 0, "host": "h",
            **attrs}


# ---- request-id minting -----------------------------------------------------

def test_mint_request_id():
    from distegnn_tpu.serve.transport import mint_request_id

    assert mint_request_id("abc-123") == "abc-123"
    assert mint_request_id("  r id\n") == "rid"        # whitespace stripped
    assert len(mint_request_id("x" * 200)) == 64       # bounded
    generated = mint_request_id(None)                  # minted when absent
    assert len(generated) == 16 and generated != mint_request_id(None)
    assert mint_request_id("\x00\x01") != ""           # garbage -> minted


# ---- waterfall stitcher -----------------------------------------------------

def _request_events(rid="r1", t0=100.0):
    """A synthetic queue -> batch -> compute -> http record set. Span ts is
    EXIT time (start = ts - dur_s)."""
    return [
        _ev("serve/prep", ts=t0 + 0.001, request_id=rid, session="s", hit=True,
            dur_s=0.001),
        _ev("serve/execute", kind="span", ts=t0 + 0.019, dur_s=0.008,
            request_ids=[rid], n=64, e=256, filled=2, capacity=4),
        _ev("serve/batch", ts=t0 + 0.020, request_ids=[rid, "other"],
            queue_ms=[5.0, 3.0], dur_s=0.009, n=64, e=256, filled=2,
            capacity=4, workload="predict"),
        _ev("serve/http", kind="span", ts=t0 + 0.024, dur_s=0.022,
            route="predict", method="POST", status=200, request_id=rid),
    ]


def test_stitch_request_complete_waterfall():
    stitched = report.stitch_request(_request_events(), "r1")
    assert stitched["complete"]
    assert len(stitched["records"]) == 4
    ph = stitched["phases"]
    assert ph["queue_ms"] == pytest.approx(5.0)   # position-aligned list
    assert ph["prep_ms"] == pytest.approx(1.0)
    assert ph["compute_ms"] == pytest.approx(9.0)
    assert ph["http_ms"] == pytest.approx(22.0)
    assert stitched["stitched_ms"] == pytest.approx(15.0)
    assert stitched["stitched_ms"] <= ph["http_ms"]
    text = report.render_request(stitched, source="x.jsonl")
    assert "serve/http" in text and "[queue wait]" in text
    assert "complete" in text


def test_stitch_request_absent_and_membership():
    events = _request_events()
    assert report.stitch_request(events, "nope")["records"] == []
    # batch-level spans list member ids: "other" touches batch + execute
    # but has no http span -> incomplete
    other = report.stitch_request(events, "other")
    assert [r["name"] for r in other["records"]] == ["serve/batch"]
    assert not other["complete"]
    assert report.request_ids_seen(events)[0] == "r1"


# ---- SLO spec + evaluation --------------------------------------------------

def test_slo_spec_validation():
    spec = slo.SLOSpec.from_mapping({
        "slo": {"routes": {"predict": {"p99_ms": 100.0}},
                "error_rate_max": 0.01}})
    assert [r.stat for r in spec.rules()] == ["predict_p99_ms", "error_rate"]
    with pytest.raises(ValueError):
        slo.SLOSpec.from_mapping({"routes": {"metrics": {"p99_ms": 1.0}}})
    with pytest.raises(ValueError):
        slo.SLOSpec.from_mapping({"routes": {"predict": {"p42_ms": 1.0}}})
    with pytest.raises(ValueError):
        slo.SLOSpec.from_mapping({"error_rate_max": 1.5})   # rate not in [0,1]
    with pytest.raises(ValueError):
        slo.SLOSpec.from_mapping({"window_s": 0.0})
    with pytest.raises(ValueError):
        slo.SLOSpec.from_mapping({"no_such_key": 1})


def test_slo_evaluate_breach_and_no_data():
    spec = slo.SLOSpec.from_mapping({
        "routes": {"predict": {"p99_ms": 10.0}},
        "shed_rate_max": 0.1, "batch_fill_min": 0.5})
    results = slo.evaluate(spec, {"predict_p99_ms": 25.0, "shed_rate": 0.0})
    by_stat = {r.rule.stat: r for r in results}
    assert by_stat["predict_p99_ms"].ok is False          # ceiling breached
    assert by_stat["shed_rate"].ok is True
    assert by_stat["batch_fill"].ok is None               # NO DATA != breach
    assert slo.breached(results)
    table = slo.verdict_table(results, source="t")
    assert "BREACH" in table and "NO DATA" in table and "FAIL" in table
    j = slo.results_json(results)
    assert j["pass"] is False and j["breached"] == ["predict_p99_ms <= 10"]
    assert j["no_data"] == ["batch_fill >= 0.5"]


def test_slo_stats_from_events():
    events = [
        _ev("serve/http", kind="span", route="predict", status=200,
            dur_s=0.010),
        _ev("serve/http", kind="span", route="predict", status=200,
            dur_s=0.030),
        _ev("serve/http", kind="span", route="predict", status=429,
            dur_s=0.001),
        _ev("serve/http", kind="span", route="metrics", status=200,
            dur_s=5.0),                       # operational: excluded
        _ev("serve/batch", filled=3, capacity=4, dur_s=0.01),
        _ev("serve/prep", session="s", hit=True, dur_s=0.001),
        _ev("serve/prep", session="s", hit=False, dur_s=0.002),
    ]
    stats = slo.stats_from_events(events)
    assert stats["predict_p50_ms"] == pytest.approx(10.0)
    assert stats["predict_p99_ms"] == pytest.approx(30.0)  # 429 excluded
    assert stats["shed_rate"] == pytest.approx(1 / 3)
    assert stats["error_rate"] == 0.0
    assert stats["batch_fill"] == pytest.approx(0.75)
    assert stats["session_hit_rate"] == pytest.approx(0.5)
    assert "rollout_p99_ms" not in stats                   # NO DATA omitted


def test_slo_monitor_window_gauges():
    mon = slo.SLOMonitor(window_s=10.0)
    now = 1000.0
    mon.observe_http("predict", 10.0, 200, now=now)
    mon.observe_http("predict", 20.0, 200, now=now + 1)
    mon.observe_http("predict", 1.0, 429, now=now + 1)     # shed: no latency
    mon.observe_http("metrics", 99.0, 200, now=now + 1)    # ignored route
    reg = MetricsRegistry()
    mon.export(reg, now=now + 2)
    snap = reg.snapshot()
    assert snap["slo/window_requests"] == 3
    assert snap["slo/window_predict_p99_ms"] == pytest.approx(20.0)
    assert snap["slo/window_shed_rate"] == pytest.approx(1 / 3)
    # samples age out of the rolling window
    mon.observe_http("predict", 50.0, 200, now=now + 100)
    reg2 = MetricsRegistry()
    mon.export(reg2, now=now + 100)
    assert reg2.snapshot()["slo/window_requests"] == 1


def test_obs_report_slo_cli_breach_exit(tmp_path, clean_obs):
    t = trace.configure(log_dir=str(tmp_path))
    t._emit("span", "serve/http", route="predict", status=200, dur_s=0.5)
    t.flush()
    events_path = str(tmp_path / "events.jsonl")
    spec_ok = tmp_path / "ok.yaml"
    spec_ok.write_text("slo:\n  routes:\n    predict:\n      p99_ms: 5000\n")
    spec_bad = tmp_path / "bad.yaml"
    spec_bad.write_text("slo:\n  routes:\n    predict:\n      p99_ms: 1\n")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    script = os.path.join(REPO, "scripts", "obs_report.py")
    r = subprocess.run([sys.executable, script, events_path, "--slo",
                        str(spec_ok)], capture_output=True, text=True,
                       env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr
    assert "PASS" in r.stdout
    r = subprocess.run([sys.executable, script, events_path, "--slo",
                        str(spec_bad), "--json"], capture_output=True,
                       text=True, env=env, cwd=REPO)
    assert r.returncode == 1
    assert json.loads(r.stdout)["breached"] == ["predict_p99_ms <= 1"]


def test_slo_config_section_validated():
    from distegnn_tpu.config import ConfigDict, _DEFAULTS, validate_config

    cfg = ConfigDict(_DEFAULTS)
    validate_config(cfg)                       # defaults pass
    cfg.slo.routes = {"predict": {"p99_ms": -1.0}}
    with pytest.raises(ValueError):
        validate_config(cfg)


# ---- EventWriter atexit flush (subprocess satellites) -----------------------

_ATEXIT_PROG = """
import sys
sys.path.insert(0, {repo!r})
from distegnn_tpu.obs import trace
# buffer larger than the event count: nothing auto-flushes; only the
# atexit hook can make the file non-empty
t = trace.configure(log_dir={log_dir!r}, buffer_events=10_000,
                    flush_interval_s=3600.0)
for i in range(20):
    t.event("sub/tick", i=i)
sys.exit(0)
"""

_KILL_PROG = """
import os, sys, time
sys.path.insert(0, {repo!r})
from distegnn_tpu.obs import trace
t = trace.configure(log_dir={log_dir!r}, buffer_events=10_000,
                    flush_interval_s=3600.0)
for i in range(20):
    t.event("sub/tick", i=i)
t.flush()
print("FLUSHED", flush=True)
time.sleep(120)            # parent SIGKILLs us here
"""


def test_event_writer_flushes_at_interpreter_exit(tmp_path):
    """A run that never calls flush still leaves a complete stream behind:
    every EventWriter registers its own atexit close."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, "-c",
         _ATEXIT_PROG.format(repo=REPO, log_dir=str(tmp_path))],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stderr
    events = read_events(str(tmp_path / "events.jsonl"))
    assert [e["i"] for e in events if e["name"] == "sub/tick"] == list(range(20))


def test_event_stream_parseable_after_sigkill(tmp_path):
    """SIGKILL after a flush: whatever was flushed is complete lines — the
    file parses with zero bad lines (the buffered writer only ever appends
    whole records)."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    p = subprocess.Popen(
        [sys.executable, "-c",
         _KILL_PROG.format(repo=REPO, log_dir=str(tmp_path))],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        cwd=REPO)
    try:
        assert p.stdout.readline().strip() == "FLUSHED"
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=60)
    finally:
        if p.poll() is None:
            p.kill()
    assert p.returncode == -signal.SIGKILL
    events = read_events(str(tmp_path / "events.jsonl"))   # bad == 0
    assert len([e for e in events if e["name"] == "sub/tick"]) == 20


# ---- per_host: one stream per process (real multi-process) ------------------

_PER_HOST_PROG = """
import sys
sys.path.insert(0, {repo!r})
from distegnn_tpu.obs import trace
trace._process_index = lambda: {idx}        # what jax.process_index() returns
t = trace.configure(log_dir={log_dir!r}, per_host=True)
for i in range(5):
    t.event("proc/tick", i=i)
t.flush()
"""


def test_per_host_writes_one_stream_per_process(tmp_path):
    """obs.per_host: true — >=2 REAL processes, each landing its own
    events_p<i>.jsonl tagged with its proc index."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    procs = [subprocess.Popen(
        [sys.executable, "-c",
         _PER_HOST_PROG.format(repo=REPO, idx=i, log_dir=str(tmp_path))],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        cwd=REPO) for i in range(3)]
    for p in procs:
        _, err = p.communicate(timeout=120)
        assert p.returncode == 0, err
    for i in range(3):
        path = tmp_path / f"events_p{i}.jsonl"
        assert path.exists(), f"process {i} left no stream"
        events = read_events(str(path))
        ticks = [e for e in events if e["name"] == "proc/tick"]
        assert len(ticks) == 5
        assert all(e["proc"] == i for e in ticks)
    # without per_host, a non-zero process index writes NOTHING
    assert not (tmp_path / "events.jsonl").exists()


# ---- route-span lint --------------------------------------------------------

def _lint():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from check_route_spans import find_violations
    finally:
        sys.path.pop(0)
    return find_violations


def test_route_span_lint_clean():
    """Tier-1 wiring of scripts/check_route_spans.py: every transport route
    handler runs inside a serve/http span carrying a request_id."""
    violations = _lint()()
    assert violations == [], (
        "transport route handler outside the serve/http span contract: "
        f"{violations}")


def test_route_span_lint_catches_bare_handler(tmp_path):
    bad = tmp_path / "transport.py"
    bad.write_text(
        "class Handler:\n"
        "    def do_GET(self):\n"
        "        self.send_response(200)\n"     # bare: no dispatch forward
        "\n"
        "class Gateway:\n"
        "    def dispatch(self, handler, method):\n"
        "        self._handle(handler, method, '/', 'predict')\n"  # no span
        "    def _handle(self, h, m, p, r):\n"
        "        pass\n")
    msgs = [m for _, _, m in _lint()(str(bad))]
    assert any("bare handler do_GET" in m for m in msgs)
    assert any("serve/http" in m for m in msgs)


# ---- traffic_gen: the end-to-end drill --------------------------------------

def test_traffic_gen_e2e_bench_line_and_waterfall(tmp_path):
    """The PR's acceptance drill: a short mixed predict/session workload
    through a LIVE single-process gateway socket emits exactly one BENCH
    line (per-class p50/p99, throughput, shed, SLO verdict), and
    ``obs_report.py --request <id>`` reconstructs a complete
    queue -> batch -> compute waterfall from events.jsonl ALONE."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    obs_dir = tmp_path / "tg"
    spec = tmp_path / "slo.yaml"
    spec.write_text("slo:\n"
                    "  routes:\n"
                    "    predict:\n"
                    "      p99_ms: 60000\n"
                    "  error_rate_max: 0.0\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "traffic_gen.py"),
         "--requests", "14", "--rate", "60", "--mix",
         "predict=0.6,session=0.4", "--sizes", "24,48", "--max-batch", "2",
         "--sessions", "2", "--seed", "31", "--slo", str(spec),
         "--obs-dir", str(obs_dir)],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=420)
    assert r.returncode == 0, r.stderr

    # stdout: EXACTLY one BENCH JSON line
    lines = [ln for ln in r.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, r.stdout
    rec = json.loads(lines[0])
    assert rec["metric"] == "traffic_p99_ms"
    assert rec["completed"] == 14 and rec["throughput_rps"] > 0
    assert rec["shed"] == 0.0
    for cls in ("predict", "session"):
        assert rec["classes"][cls]["p50_ms"] > 0
        assert rec["classes"][cls]["p99_ms"] >= rec["classes"][cls]["p50_ms"]
    assert rec["slo"]["pass"] is True and rec["slo"]["rules"] == 2
    assert "overall: PASS" in r.stderr

    # every request's waterfall reconstructs from the events file alone
    events_path = str(obs_dir / "obs" / "events.jsonl")
    events = read_events(events_path)
    assert any(e["name"] == "bench/result" for e in events)
    stitched = report.stitch_request(events, "tg-31-0")
    assert stitched["complete"], stitched
    assert stitched["phases"]["queue_ms"] is not None
    assert stitched["phases"]["compute_ms"] > 0
    # ... and through the CLI, as an operator would
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         events_path, "--request", "tg-31-0"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert r2.returncode == 0, r2.stderr
    assert "complete" in r2.stdout and "serve/http" in r2.stdout
    # unknown ids fail with a hint
    r3 = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         events_path, "--request", "nope"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert r3.returncode == 1 and "not found" in r3.stderr


def test_traffic_gen_chaos_leg_slo_verdict_through_kill(tmp_path):
    """The chaos leg of the drill: a replica kill injected mid-replay
    (2 replicas) keeps the embedded SLO verdict green — zero lost
    requests, error rate inside the declared bound — and the injection
    plus failover land as typed events in the SAME stream the verdict
    was computed from."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    obs_dir = tmp_path / "tg"
    spec = tmp_path / "slo.yaml"
    spec.write_text("slo:\n"
                    "  routes:\n"
                    "    predict:\n"
                    "      p99_ms: 60000\n"
                    "  error_rate_max: 0.0\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "traffic_gen.py"),
         "--requests", "16", "--rate", "40", "--mix", "predict=1.0",
         "--sizes", "24", "--replicas", "2", "--seed", "13",
         "--chaos", "kill@0.2:replica=0",
         "--slo", str(spec), "--obs-dir", str(obs_dir)],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=420)
    assert r.returncode == 0, r.stderr

    lines = [ln for ln in r.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, r.stdout
    rec = json.loads(lines[0])
    assert rec["completed"] == 16 and rec["lost"] == 0
    assert rec["slo"]["pass"] is True and rec["slo"]["rules"] == 2
    assert rec["chaos"] == [{"action": "kill", "at_s": 0.2,
                             "model": "default", "replica": 0, "ok": True}]
    assert "overall: PASS" in r.stderr

    events = read_events(str(obs_dir / "obs" / "events.jsonl"))
    names = {e["name"] for e in events}
    assert "chaos/inject" in names and "bench/result" in names
