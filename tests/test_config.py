"""Config system tests — schema load, defaults, overrides, validation, derived
fields (reference behavior: main.py:96-157)."""

import pytest

from distegnn_tpu.config import (
    ConfigDict,
    apply_overrides,
    build_arg_parser,
    derive_runtime_fields,
    load_config,
)

CFG = "configs/nbody_fastegnn.yaml"


def test_load_reference_schema():
    cfg = load_config(CFG)
    assert cfg.model.model_name == "FastEGNN"
    assert cfg.model.hidden_nf == 64
    assert cfg.data.dataset_name == "nbody_100"
    assert cfg.data.batch_size == 250
    assert cfg.train.mmd.sigma == 1.5
    assert cfg.seed == 43
    # defaults fill fields the YAML omits
    assert cfg.data.split_mode == "metis"
    assert cfg.model.checkpoint is None


def test_cli_overrides_none_skipped():
    cfg = load_config(CFG, overrides={"lr": 1e-3, "seed": None, "virtual_channels": 5})
    assert cfg.train.learning_rate == 1e-3
    assert cfg.seed == 43  # None → untouched (reference main.py:119-120)
    assert cfg.model.virtual_channels == 5


def test_unknown_override_rejected():
    cfg = load_config(CFG)
    with pytest.raises(KeyError):
        apply_overrides(cfg, {"not_a_field": 1})


def test_validation_distribute_requires_radii():
    cfg = load_config(CFG)
    cfg.data.accelerate_mode = "distribute"
    cfg.data.outer_radius = None
    from distegnn_tpu.config import validate_config

    with pytest.raises(ValueError):
        validate_config(cfg)


def test_distribute_config_loads():
    cfg = load_config("configs/largefluid_distegnn.yaml")
    assert cfg.data.accelerate_mode == "distribute"
    assert cfg.data.outer_radius == 0.075
    assert cfg.train.accumulation_steps == 4
    assert cfg.train.mmd.samples == 50


def test_derived_fields():
    cfg = load_config(CFG)
    derive_runtime_fields(cfg, world_size=4)
    assert cfg.data.world_size == 4
    assert "nbody_100" in cfg.log.exp_name
    assert "ws4" in cfg.log.exp_name
    assert "C3" in cfg.log.exp_name


def test_arg_parser_roundtrip():
    parser = build_arg_parser()
    args = parser.parse_args(["--config_path", CFG, "--lr", "0.001", "--batch_size", "8"])
    cfg = load_config(args.config_path, overrides={k: v for k, v in vars(args).items() if k != "config_path"})
    assert cfg.train.learning_rate == 0.001
    assert cfg.data.batch_size == 8


def test_validation_serve_rollout_and_session_cache():
    from distegnn_tpu.config import validate_config

    cfg = load_config(CFG)
    cfg.serve.session_cache = -1
    with pytest.raises(ValueError, match="session_cache"):
        validate_config(cfg)
    cfg.serve.session_cache = 0          # 0 disables — valid
    cfg.serve.rollout = "radius=0.35"    # must be a mapping, not a string
    with pytest.raises(ValueError, match="rollout"):
        validate_config(cfg)
    cfg.serve.rollout = {"radius": 0.0, "max_degree": 32}
    with pytest.raises(ValueError, match="radius"):
        validate_config(cfg)
    cfg.serve.rollout = {"radius": 0.35, "max_degree": 0}
    with pytest.raises(ValueError, match="max_degree"):
        validate_config(cfg)
    cfg.serve.rollout = {"radius": 0.35, "max_degree": 32, "max_per_cell": 0}
    with pytest.raises(ValueError, match="max_per_cell"):
        validate_config(cfg)
    # max_degree * edge_block must tile the 512-wide kernel chunk
    cfg.serve.rollout = {"radius": 0.35, "max_degree": 3, "edge_block": 256}
    with pytest.raises(ValueError, match="multiple of 512"):
        validate_config(cfg)
    cfg.serve.rollout = {"radius": 0.35, "max_degree": 32,
                         "max_per_cell": 64, "edge_block": 256}
    validate_config(cfg)                 # the serve_bench default: valid


def test_configdict_attribute_access():
    c = ConfigDict({"a": {"b": 1}})
    assert c.a.b == 1
    c.a.b = 2
    assert c["a"]["b"] == 2
    assert c.to_dict() == {"a": {"b": 2}}
