"""Fused edge-pipeline kernel (ops/edge_pipeline.py): layout validation,
in-window/remote partition exactness, and interpret-mode forward + grad
parity against a plain dense reference of the same math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distegnn_tpu.ops.edge_pipeline import (EdgeWeights, OH_CHUNK,
                                            build_edge_blocks,
                                            fused_edge_layer,
                                            split_remote_edges)

T = OH_CHUNK          # smallest legal block (512)
H = 16


def _blocked_graph(rng, nb=3, epb=None, fill=0.6, empty_tail_blocks=0):
    """Random blocked-layout edge arrays: nb node blocks of T nodes, each
    owning an epb-slot edge slice; ``fill`` of the slots hold real edges
    (rows inside the block, cols anywhere), the rest are mask-0 padding.
    The last ``empty_tail_blocks`` node blocks get NO real edges — the
    trailing-empty-block regression of ADVICE #1."""
    epb = epb or T
    n_nodes = nb * T
    E = nb * epb
    row = np.zeros(E, np.int64)
    col = np.zeros(E, np.int64)
    emask = np.zeros(E, np.float32)
    for b in range(nb):
        sl = slice(b * epb, (b + 1) * epb)
        k = 0 if b >= nb - empty_tail_blocks else int(fill * epb)
        r = np.sort(rng.integers(b * T, (b + 1) * T, size=epb))
        row[sl] = r
        col[sl] = rng.integers(0, n_nodes, size=epb)
        emask[sl][:0] = 0  # noop, clarity
        emask[b * epb: b * epb + k] = 1.0
    ea = np.zeros((E, 2), np.float32)
    ea[:, 0] = np.arange(E)              # unique id -> maps split output back
    ea[:, 1] = rng.normal(size=E).astype(np.float32)
    return row, col, ea, emask, n_nodes


# ------------------------------------------------------------- validation

def test_build_edge_blocks_rejects_small_or_ragged_block():
    rng = np.random.default_rng(0)
    row, col, ea, em, n = _blocked_graph(rng, nb=3)
    with pytest.raises(ValueError, match="OH_CHUNK"):
        build_edge_blocks(jnp.asarray(row), jnp.asarray(col), jnp.asarray(ea),
                          jnp.asarray(em), block=256, n_nodes=n)
    with pytest.raises(ValueError, match="OH_CHUNK"):
        build_edge_blocks(jnp.asarray(row), jnp.asarray(col), jnp.asarray(ea),
                          jnp.asarray(em), block=OH_CHUNK + OH_CHUNK // 2,
                          n_nodes=n)


def test_fused_layer_rejects_fewer_than_three_blocks():
    rng = np.random.default_rng(1)
    row, col, ea, em, n = _blocked_graph(rng, nb=3)
    arrs = build_edge_blocks(jnp.asarray(row), jnp.asarray(col),
                             jnp.asarray(ea), jnp.asarray(em),
                             block=T, n_nodes=n)
    w = _weights(np.random.default_rng(2))
    x = jnp.zeros((2 * T, 3), jnp.float32)       # only 2 node blocks
    h = jnp.zeros((2 * T, H), jnp.float32)
    with pytest.raises(ValueError, match="3 node blocks"):
        fused_edge_layer(x, h, h, *arrs, w, T, "f32")


def test_split_remote_edges_requires_aligned_n_nodes():
    ei = np.zeros((2, 4), np.int64)
    with pytest.raises(ValueError, match="multiple of block"):
        split_remote_edges(ei, np.zeros((4, 2), np.float32), block=T,
                           n_nodes=T + 1)


# ------------------------------------------------------------- partition

@pytest.mark.parametrize("empty_tail_blocks", [0, 2])
def test_window_and_remote_exactly_partition(empty_tail_blocks):
    """Every real edge is in-window (build_edge_blocks mask) XOR remote
    (split_remote_edges) — no double-count, no drop — including with
    trailing node blocks that receive no edges (the nb-inference bug)."""
    rng = np.random.default_rng(3)
    nb = 5
    row, col, ea, em, n = _blocked_graph(rng, nb=nb,
                                         empty_tail_blocks=empty_tail_blocks)
    _, _, _, scal = build_edge_blocks(
        jnp.asarray(row), jnp.asarray(col), jnp.asarray(ea), jnp.asarray(em),
        block=T, n_nodes=n)
    in_window = np.asarray(scal[:, 2]) > 0

    # compact real-edge list (what a loader would feed split_remote_edges)
    real = em > 0
    ei_real = np.stack([row[real], col[real]])
    _, rea, rm = split_remote_edges(ei_real, ea[real], block=T, n_nodes=n)
    remote_ids = set(rea[rm > 0, 0].astype(np.int64).tolist())
    window_ids = set(ea[in_window & real, 0].astype(np.int64).tolist())
    all_ids = set(ea[real, 0].astype(np.int64).tolist())

    assert remote_ids.isdisjoint(window_ids), "edge counted by both paths"
    assert remote_ids | window_ids == all_ids, "edge dropped by both paths"
    # sanity: this workload genuinely exercises both paths
    assert remote_ids and window_ids


# ------------------------------------------------------------- parity

def _weights(rng):
    s = 0.3 / np.sqrt(H)
    def m(*shape):
        return jnp.asarray(rng.normal(size=shape).astype(np.float32) * s)
    return EdgeWeights(ws=m(3, H), b1=m(1, H), w2=m(H, H), b2=m(1, H),
                       w3=m(H, H), b3=m(1, H), w4=m(1, H))


def _silu(x):
    return x * jax.nn.sigmoid(x)


def _reference(x, hr, hc, row, col, ea, mask, w, n_nodes):
    """Plain dense path of the exact kernel math (reference FastEGNN phi_e /
    phi_x semantics): gather -> two-layer edge MLP -> coord scalar ->
    masked segment sums by receiver row."""
    m = mask[:, None]
    cd = (x[row] - x[col]) * m
    radial = jnp.sum(cd * cd, axis=1, keepdims=True)
    sfeat = jnp.concatenate([radial, ea[:, :2]], axis=1)
    t1 = hr[row] + hc[col] + sfeat @ w.ws + w.b1
    y1 = _silu(t1)
    ef = _silu(y1 @ w.w2 + w.b2)
    y2 = _silu(ef @ w.w3 + w.b3)
    g = jnp.sum(y2 * w.w4, axis=1, keepdims=True) * m
    trans = cd * g
    seg = lambda v: jax.ops.segment_sum(v * m, row, num_segments=n_nodes)
    return (seg(trans), jax.ops.segment_sum(mask, row, num_segments=n_nodes),
            seg(ef))


def _parity_setup():
    rng = np.random.default_rng(7)
    row, col, ea, em, n = _blocked_graph(rng, nb=3, fill=0.5)
    ea[:, 0] = rng.normal(size=ea.shape[0]).astype(np.float32)  # real attrs
    arrs = build_edge_blocks(jnp.asarray(row), jnp.asarray(col),
                             jnp.asarray(ea), jnp.asarray(em),
                             block=T, n_nodes=n)
    mask = np.asarray(arrs[3][:, 2])     # in-window AND real
    x = jnp.asarray(rng.uniform(0, 1, size=(n, 3)).astype(np.float32))
    hr = jnp.asarray(rng.normal(size=(n, H)).astype(np.float32) * 0.5)
    hc = jnp.asarray(rng.normal(size=(n, H)).astype(np.float32) * 0.5)
    w = _weights(rng)
    ref_args = (jnp.asarray(row), jnp.asarray(col), jnp.asarray(ea),
                jnp.asarray(mask))
    return x, hr, hc, arrs, w, ref_args, n


def test_fused_forward_matches_reference_interpret():
    x, hr, hc, arrs, w, (row, col, ea, mask), n = _parity_setup()
    trans, count, ef_sum = fused_edge_layer(x, hr, hc, *arrs, w, T, "f32")
    trans_r, count_r, ef_r = _reference(x, hr, hc, row, col, ea, mask, w, n)
    np.testing.assert_allclose(np.asarray(count), np.asarray(count_r),
                               atol=1e-6, rtol=0)
    # trans rides the exact 2-term bf16 split (~16 mantissa bits)
    np.testing.assert_allclose(np.asarray(trans), np.asarray(trans_r[:, :3]),
                               atol=2e-4, rtol=1e-4)
    # ef is aggregated through a single bf16 stream (f32 accumulation)
    np.testing.assert_allclose(np.asarray(ef_sum), np.asarray(ef_r),
                               atol=2e-2, rtol=2e-2)


def test_fused_grad_matches_reference_interpret():
    x, hr, hc, arrs, w, (row, col, ea, mask), n = _parity_setup()
    k1, k2 = jax.random.split(jax.random.PRNGKey(11))
    wt = jax.random.normal(k1, (n, 3)) * 0.1
    we = jax.random.normal(k2, (n, H)) * 0.1

    def loss_fused(x, hr, hc, w):
        t, _, e = fused_edge_layer(x, hr, hc, *arrs, w, T, "f32")
        return jnp.sum(t * wt) + jnp.sum(e * we)

    def loss_ref(x, hr, hc, w):
        t, _, e = _reference(x, hr, hc, row, col, ea, mask, w, n)
        return jnp.sum(t[:, :3] * wt) + jnp.sum(e * we)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, hr, hc, w)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, hr, hc, w)

    names = ["d_x", "d_hr", "d_hc"]
    for name, a, b in zip(names, gf[:3], gr[:3]):
        a, b = np.asarray(a), np.asarray(b)
        scale = max(np.abs(b).max(), 1e-3)
        np.testing.assert_allclose(a, b, atol=2e-2 * scale, rtol=0,
                                   err_msg=name)
    for name, a, b in zip(EdgeWeights._fields, gf[3], gr[3]):
        a, b = np.asarray(a), np.asarray(b)
        scale = max(np.abs(b).max(), 1e-3)
        np.testing.assert_allclose(a, b, atol=2e-2 * scale, rtol=0,
                                   err_msg=f"d_{name}")
