"""Out-of-core streamed pipeline gates (distegnn_tpu/data/stream.py).

The contract under test: a streamed epoch is BITWISE-identical to the
in-memory epoch (same seed, same order, same padded batches) while host
residency stays bounded by the shard LRU; a prefetch producer crash reaches
the trainer as a typed error, never a hang; the skew-balance partition pass
caps the measured work imbalance; and a truncated read (the torn-NFS shape)
is healed by the full-read retry instead of escaping it.
"""

from __future__ import annotations

import json
import os
import pickle
import subprocess
import sys

import jax
import numpy as np
import pytest

from distegnn_tpu.data import (
    GraphDataset,
    GraphLoader,
    PrefetchCrashError,
    PrefetchLoader,
    ShardChecksumError,
    StreamedGraphDataset,
    open_dataset,
    write_shards,
)
from distegnn_tpu.ops.radius import radius_graph_np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_graphs(n_graphs=10, n_lo=20, n_hi=48, seed=0, with_optional=True):
    rng = np.random.default_rng(seed)
    graphs = []
    for _ in range(n_graphs):
        n = int(rng.integers(n_lo, n_hi))
        loc = rng.normal(size=(n, 3)).astype(np.float32)
        vel = rng.normal(size=(n, 3)).astype(np.float32)
        ei = radius_graph_np(loc, 1.5).astype(np.int32)
        dist = np.linalg.norm(loc[ei[0]] - loc[ei[1]], axis=1)
        graphs.append({
            "node_feat": np.linalg.norm(vel, axis=1, keepdims=True).astype(np.float32),
            "node_attr": (rng.normal(size=(n, 2)).astype(np.float32)
                          if with_optional else None),
            "loc": loc,
            "vel": vel,
            "target": (loc + 0.1 * vel if with_optional else None),
            "loc_mean": loc.mean(axis=0),
            "edge_index": ei,
            "edge_attr": np.repeat(dist[:, None], 2, axis=1).astype(np.float32),
        })
    return graphs


def _assert_graph_equal(a, b):
    for k in ("node_feat", "node_attr", "loc", "vel", "target", "loc_mean",
              "edge_index", "edge_attr"):
        if b.get(k) is None:
            assert a.get(k) is None, k
        else:
            np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]), err_msg=k)


@pytest.mark.io
def test_manifest_round_trip_bitwise(tmp_path):
    graphs = _make_graphs(10)
    manifest = write_shards(graphs, str(tmp_path), shard_size=3)
    assert manifest["n_graphs"] == 10
    assert len(manifest["shards"]) == 4          # 3+3+3+1
    assert manifest["shards"][-1]["n_graphs"] == 1
    on_disk = json.load(open(tmp_path / "manifest.json"))
    assert on_disk == manifest
    ds = StreamedGraphDataset(str(tmp_path))
    assert len(ds) == 10
    assert ds.size_maxima() == GraphDataset(graphs).size_maxima()
    for i in range(10):
        _assert_graph_equal(ds[i], graphs[i])


@pytest.mark.io
def test_optional_fields_absent_round_trip(tmp_path):
    graphs = _make_graphs(4, with_optional=False)
    write_shards(graphs, str(tmp_path), shard_size=2)
    ds = StreamedGraphDataset(str(tmp_path))
    for i in range(4):
        assert ds[i]["node_attr"] is None and ds[i]["target"] is None
        _assert_graph_equal(ds[i], graphs[i])


@pytest.mark.io
def test_nonuniform_optional_fields_rejected(tmp_path):
    graphs = _make_graphs(4)
    graphs[2]["target"] = None
    with pytest.raises(ValueError, match="present in some graphs"):
        write_shards(graphs, str(tmp_path))


@pytest.mark.io
def test_checksum_reject(tmp_path):
    graphs = _make_graphs(6)
    write_shards(graphs, str(tmp_path), shard_size=2)
    shard = tmp_path / "shard_00001.npz"
    data = bytearray(shard.read_bytes())
    data[len(data) // 2] ^= 0xFF  # one flipped bit deep in the payload
    shard.write_bytes(bytes(data))
    ds = StreamedGraphDataset(str(tmp_path))
    _assert_graph_equal(ds[0], graphs[0])  # shard 0 untouched
    with pytest.raises(ShardChecksumError):
        ds[2]  # first graph of the corrupted shard
    clean = StreamedGraphDataset(str(tmp_path), verify=False)
    assert clean.manifest["format"] == "distegnn-shards-v1"


@pytest.mark.io
def test_shard_lru_bound_random_access(tmp_path):
    graphs = _make_graphs(12)
    write_shards(graphs, str(tmp_path), shard_size=2)  # 6 shards
    ds = StreamedGraphDataset(str(tmp_path), cache_shards=2)
    rng = np.random.default_rng(3)
    for i in rng.integers(0, 12, size=60):
        _assert_graph_equal(ds[int(i)], graphs[int(i)])
        assert ds.open_shards <= 2  # RSS proxy: never more than the cache
    assert ds.open_shards == 2


@pytest.mark.io
def test_streamed_epoch_bitwise_parity(tmp_path):
    """Full shuffled epoch (two epochs) through GraphLoader: streamed batches
    must be bitwise-identical to in-memory batches — the epoch order lives in
    the seeded permutation, not the residency model."""
    graphs = _make_graphs(10)
    write_shards(graphs, str(tmp_path), shard_size=3)
    mem = GraphLoader(GraphDataset(graphs), 2, shuffle=True, seed=7)
    st = GraphLoader(StreamedGraphDataset(str(tmp_path), cache_shards=2),
                     2, shuffle=True, seed=7)
    assert len(mem) == len(st) == 5
    for epoch in range(2):
        mem.set_epoch(epoch)
        st.set_epoch(epoch)
        for a, b in zip(mem, st):
            jax.tree.map(np.testing.assert_array_equal, a, b)


@pytest.mark.io
@pytest.mark.slow
def test_streamed_epoch_parity_blocked_split_remote(tmp_path):
    """The expensive lane: blocked layout + split_remote (the fused
    pipeline's batch shape) over a streamed dataset — the loader's dataset
    scans (edges-per-block, remote width) and blockify must see identical
    graphs through the LRU."""
    graphs = _make_graphs(8, n_lo=40, n_hi=80, seed=1)
    write_shards(graphs, str(tmp_path), shard_size=2)
    kw = dict(batch_size=2, shuffle=True, seed=11, edge_block=8, edge_tile=8)
    mem = GraphLoader(GraphDataset(graphs), **kw)
    st = GraphLoader(StreamedGraphDataset(str(tmp_path), cache_shards=2), **kw)
    for epoch in range(2):
        mem.set_epoch(epoch)
        st.set_epoch(epoch)
        for a, b in zip(mem, st):
            jax.tree.map(np.testing.assert_array_equal, a, b)


@pytest.mark.io
def test_open_dataset_routes_by_source(tmp_path):
    graphs = _make_graphs(4)
    write_shards(graphs, str(tmp_path / "shards"), shard_size=2)
    pkl = tmp_path / "data.pkl"
    pkl.write_bytes(pickle.dumps(graphs))
    assert isinstance(open_dataset(str(tmp_path / "shards")), StreamedGraphDataset)
    assert isinstance(open_dataset(str(pkl)), GraphDataset)
    assert isinstance(open_dataset(graphs), GraphDataset)


@pytest.mark.io
def test_in_memory_list_adopted_without_copy():
    graphs = _make_graphs(3)
    ds = GraphDataset(graphs)
    assert ds.graphs is graphs  # the double-memory spike fix
    # morton still must not mutate the caller's list
    ds2 = GraphDataset(graphs, node_order="morton")
    assert ds2.graphs is not graphs
    _assert_graph_equal(graphs[0], _make_graphs(3)[0])


@pytest.mark.io
def test_host_bytes_gauge_logged():
    from distegnn_tpu import obs

    gauge = obs.get_registry().gauge("data/host_bytes")
    before = gauge.value
    graphs = _make_graphs(3)
    GraphDataset(graphs)
    expected = sum(v.nbytes for g in graphs for v in g.values()
                   if isinstance(v, np.ndarray))
    assert gauge.value >= before + expected


@pytest.mark.io
def test_prefetch_bitwise_parity_and_gauges(tmp_path):
    from distegnn_tpu import obs

    graphs = _make_graphs(8)
    write_shards(graphs, str(tmp_path), shard_size=3)
    ds = StreamedGraphDataset(str(tmp_path), cache_shards=2)
    plain = GraphLoader(GraphDataset(graphs), 2, shuffle=True, seed=5)
    pf = PrefetchLoader(GraphLoader(ds, 2, shuffle=True, seed=5), depth=2)
    assert len(pf) == len(plain)
    pf.set_epoch(1)
    plain.set_epoch(1)
    got = list(pf)
    want = list(plain)
    assert len(got) == len(want) == 4
    for a, b in zip(want, got):
        jax.tree.map(np.testing.assert_array_equal, a, b)
    assert obs.get_registry().gauge("data/prefetch_depth").value == 2
    # depth=0 degrades to the synchronous blocking path, same batches
    pf0 = PrefetchLoader(GraphLoader(ds, 2, shuffle=True, seed=5), depth=0)
    pf0.set_epoch(1)
    for a, b in zip(want, pf0):
        jax.tree.map(np.testing.assert_array_equal, a, b)


@pytest.mark.io
def test_prefetch_crash_is_typed_not_hang():
    class DyingLoader:
        def set_epoch(self, e):
            pass

        def __len__(self):
            return 3

        def __iter__(self):
            yield {"x": np.zeros(2)}
            raise OSError("disk fell off mid-epoch")

    it = iter(PrefetchLoader(DyingLoader(), depth=2))
    next(it)  # the batch produced before the crash still arrives
    with pytest.raises(PrefetchCrashError) as ei:
        next(it)
    assert isinstance(ei.value.__cause__, OSError)


@pytest.mark.io
def test_prefetch_abandoned_iteration_joins_producer(tmp_path):
    import threading

    graphs = _make_graphs(8)
    write_shards(graphs, str(tmp_path), shard_size=2)
    loader = GraphLoader(StreamedGraphDataset(str(tmp_path)), 1, shuffle=False)
    before = threading.active_count()
    it = iter(PrefetchLoader(loader, depth=1))
    next(it)
    it.close()  # trainer bails mid-epoch (early stop, crash, ^C)
    assert threading.active_count() <= before + 1  # producer joined, not leaked


@pytest.mark.io
def test_partition_balance_on_skewed_graph():
    """Dense cluster + sparse halo: the spatial partitioners hand one part
    the hot spot; the balance pass must bring max/mean work under 1.15."""
    from distegnn_tpu.data.partition import (
        balance_partitions, assign_partitions, imbalance_ratio, node_work,
        partition_work, split_graph,
    )

    rng = np.random.default_rng(0)
    dense = rng.normal(scale=0.15, size=(1200, 3))
    sparse = rng.uniform(-4, 4, size=(1800, 3))
    pos = np.concatenate([dense, sparse]).astype(np.float32)
    inner = 0.35
    labels = assign_partitions(pos, 8, "metis", outer_radius=1.0, seed=0)
    work = node_work(pos, inner)
    before = imbalance_ratio(partition_work(labels, work, 8))
    assert before > 1.15  # the skew is real, or the gate proves nothing
    balanced, b, a = balance_partitions(pos, labels, 8, inner)
    assert b == pytest.approx(before)
    assert a <= 1.15
    after = imbalance_ratio(partition_work(balanced, work, 8))
    assert after == pytest.approx(a)
    # end to end through split_graph: measured LOCAL work (nodes + rebuilt
    # edges) also lands under the gate
    g = {
        "node_feat": np.ones((pos.shape[0], 1), np.float32),
        "node_attr": None, "loc": pos,
        "vel": np.zeros_like(pos), "target": None,
        "loc_mean": pos.mean(0),
        "edge_index": np.zeros((2, 0), np.int32),
        "edge_attr": np.zeros((0, 2), np.float32),
    }
    parts = split_graph(g, 8, "metis", inner_radius=inner, outer_radius=1.0,
                        seed=0, balance=True)
    local = np.array([p["loc"].shape[0] + p["edge_index"].shape[1]
                      for p in parts], np.float64)
    assert imbalance_ratio(local) <= 1.15


@pytest.mark.io
def test_truncated_read_healed_by_retry(tmp_path):
    """The torn-NFS shape: open() succeeds, the payload is short. One bad
    read must heal inside the bounded retry; persistent truncation must
    still fail hard with the underlying error."""
    from distegnn_tpu.data.loader import _OPEN_ATTEMPTS
    from distegnn_tpu.testing.faults import truncated_read

    graphs = _make_graphs(4)
    pkl = tmp_path / "data.pkl"
    pkl.write_bytes(pickle.dumps(graphs))
    with truncated_read(fail_times=1) as calls:
        ds = GraphDataset(str(pkl))
    assert calls["n"] >= 2  # one truncated read + one clean retry
    _assert_graph_equal(ds[1], graphs[1])
    with truncated_read(fail_times=_OPEN_ATTEMPTS * 2):
        with pytest.raises((EOFError, pickle.UnpicklingError, ValueError, OSError)):
            GraphDataset(str(pkl))


@pytest.mark.io
def test_truncated_shard_read_healed_by_retry(tmp_path):
    from distegnn_tpu.testing.faults import truncated_read

    graphs = _make_graphs(4)
    write_shards(graphs, str(tmp_path), shard_size=2)
    ds = StreamedGraphDataset(str(tmp_path), cache_shards=1)
    with truncated_read(fail_times=1) as calls:
        _assert_graph_equal(ds[0], graphs[0])
    assert calls["n"] >= 2  # CRC caught the short read, retry healed it


@pytest.mark.io
def test_shard_dataset_script_round_trip(tmp_path):
    graphs = _make_graphs(5)
    pkl = tmp_path / "processed.pkl"
    pkl.write_bytes(pickle.dumps(graphs))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "shard_dataset.py"),
         "--input", str(pkl), "--out", str(tmp_path / "shards"),
         "--shard-size", "2"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stderr[-800:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["n_graphs"] == 5 and rec["n_shards"] == 3
    ds = open_dataset(str(tmp_path / "shards"))
    assert isinstance(ds, StreamedGraphDataset)
    for i in range(5):
        _assert_graph_equal(ds[i], graphs[i])
