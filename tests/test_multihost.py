"""Real multi-host execution test: two OS processes, each owning 4 CPU
devices, joined with jax.distributed.initialize into one 8-device world
running the (data=2, graph=4) mesh — the pod execution model without a pod
(VERDICT r1 item 3: multi-host must be code, not a docstring claim).

Checks: both processes produce identical losses (replicated state invariant,
the reference's check_model_parameters analog, reference main.py:40-55), and
they match THIS process's single-process 8-device run of the same problem
bit-close — multi-host == single-process.
"""

from __future__ import annotations

import importlib.util
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _load_worker():
    spec = importlib.util.spec_from_file_location("multihost_worker", _WORKER)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_workers(*extra_args):
    """Launch the two-process world and return parsed per-process results.
    PYTHONPATH is repo root only: site-packages come from the interpreter
    itself, and any extra PJRT plugin dirs on the inherited path (e.g. an
    unreachable TPU tunnel plugin) would register during
    jax.distributed.initialize and hang the CPU-only workers."""
    port = _free_port()
    env = dict(os.environ)
    env.pop("PYTHONWARNINGS", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(_WORKER))
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(pid), *extra_args],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)
        for pid in (0, 1)
    ]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}"
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT "):
                _, pid, loss, ev, cons = line.split()
                results[int(pid)] = (float(loss), float(ev), float(cons))
    assert set(results) == {0, 1}, f"missing results: {outs}"
    return results


@pytest.mark.slow
def test_two_process_world_matches_single_process():
    results = _run_workers()

    # replicated-state invariant: both processes computed identical numbers
    np.testing.assert_allclose(results[0], results[1], rtol=0, atol=0)

    # clean data -> the in-step consistency residual is exactly zero
    assert results[0][2] == 0.0

    # multi-host == single-process on the same 8-device problem
    worker = _load_worker()
    loss_sp, ev_sp, cons_sp = worker.run()
    np.testing.assert_allclose(results[0][:2], (loss_sp, ev_sp), rtol=1e-6)
    assert np.isfinite(loss_sp) and np.isfinite(ev_sp) and cons_sp == 0.0


@pytest.mark.slow
def test_two_process_detects_injected_batch_mismatch():
    """Negative path (VERDICT r2 weak #6): when one host feeds drifted data,
    the traced in-step check must DETECT it — a nonzero residual on every
    process, where the clean run's is exactly zero."""
    results = _run_workers("corrupt")
    # the collective makes the residual global: BOTH processes see it
    assert results[0][2] > 0.1 and results[1][2] > 0.1, results
