"""End-to-end Water-3D distribute run: the REAL configs/simulation_distegnn.yaml
through run_distributed — synthetic h5 trajectories, METIS partitioning,
ShardedGraphLoader, grad accumulation (4), MMD, 8-device CPU mesh, 2 epochs.
This is the last reference config that had only preprocessing-level coverage
(VERDICT r1 weak #4); mirrors the reference Water-3D distribute flow
(datasets/process_dataset.py:308-438 + utils/train.py)."""

from __future__ import annotations

import os

import numpy as np
import pytest

N_PART = 600
T_FRAMES = 30
RADIUS = 0.12


@pytest.fixture(scope="module")
def water3d_dataset(tmp_path_factory):
    from tests.conftest import make_water3d_h5

    return make_water3d_h5(tmp_path_factory.mktemp("w3d_e2e"),
                           N_PART, T_FRAMES, step_scale=0.002, seed=7)


@pytest.mark.slow
def test_simulation_yaml_runs_distributed_metis(water3d_dataset, tmp_path):
    from distegnn_tpu.config import load_config
    from distegnn_tpu.parallel.launch import run_distributed

    config = load_config(os.path.join(os.path.dirname(__file__), "..",
                                      "configs", "simulation_distegnn.yaml"))
    config.data.data_dir = water3d_dataset
    # 8 samples / batch 4 = 2 steps/epoch x 4 epochs = 8 accumulation
    # mini-steps -> TWO full optax.MultiSteps cycles (accumulation_steps=4):
    # the optimizer genuinely applies updates, unlike a config where
    # steps < accumulation_steps would leave params at init
    config.data.max_samples = 8
    config.data.world_size = 8
    config.data.outer_radius = RADIUS   # scaled for N_PART density
    config.data.inner_radius = RADIUS
    config.data.delta_t = 5
    config.train.epochs = 4
    config.log.log_dir = str(tmp_path)
    assert config.data.split_mode == "metis"           # the yaml's real value
    assert config.train.accumulation_steps == 4        # exercises MultiSteps

    best = run_distributed(config)
    assert np.isfinite(best["loss_valid"]) and np.isfinite(best["loss_test"])

    # log.json artifact written by the shared trainer
    from tests.conftest import assert_run_artifacts

    assert_run_artifacts(tmp_path)
