"""Scatter-free cumsum segment lowering (ops/segment.py cumsum block,
EdgeOps seg_impl='cumsum') — parity with the exact scatter path, forward and
gradients, op-level and through FastEGNN."""

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np
import pytest

from distegnn_tpu.ops.blocked import EdgeOps
from distegnn_tpu.ops.graph import pad_graphs
from distegnn_tpu.ops.segment import (gather_rows_cs, paired_gather_cols_cs,
                                      segment_mean, segment_mean_cs,
                                      segment_sum, segment_sum_cs)

E, N, F = 400, 37, 5


@pytest.fixture
def seg_data(rng):
    ids = np.sort(rng.integers(0, N, size=E)).astype(np.int32)
    data = rng.standard_normal((E, F)).astype(np.float32)
    mask = (rng.random(E) < 0.8).astype(np.float32)
    return jnp.asarray(data), jnp.asarray(ids), jnp.asarray(mask)


def test_segment_sum_cs_matches_scatter(seg_data):
    data, ids, mask = seg_data
    ref = segment_sum(data, ids, N, mask=mask, indices_are_sorted=True)
    out = segment_sum_cs(data, ids, N, mask=mask)
    np.testing.assert_allclose(out, ref, atol=2e-5)
    # no mask
    np.testing.assert_allclose(segment_sum_cs(data, ids, N),
                               segment_sum(data, ids, N), atol=2e-5)


def test_segment_mean_cs_matches_scatter(seg_data):
    data, ids, mask = seg_data
    ref = segment_mean(data, ids, N, mask=mask, indices_are_sorted=True)
    out = segment_mean_cs(data, ids, N, mask=mask)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_segment_sum_cs_empty_and_boundary_segments(rng):
    # first and last segments empty, a middle segment owning everything
    ids = jnp.asarray(np.full(20, 3, np.int32))
    data = jnp.asarray(rng.standard_normal((20, 2)).astype(np.float32))
    out = segment_sum_cs(data, ids, 7)
    ref = segment_sum(data, ids, 7)
    np.testing.assert_allclose(out, ref, atol=1e-5)
    assert np.abs(np.asarray(out)[[0, 1, 2, 4, 5, 6]]).max() == 0.0


def test_segment_sum_cs_gradient_is_exact_gather(seg_data):
    """The custom VJP is a gather — exact, no cumsum rounding."""
    data, ids, mask = seg_data
    w = jnp.asarray(np.random.default_rng(1).standard_normal((N, F)).astype(np.float32))

    g_cs = jax.grad(lambda d: (segment_sum_cs(d, ids, N, mask=mask) * w).sum())(data)
    g_ref = jax.grad(lambda d: (segment_sum(d, ids, N, mask=mask,
                                            indices_are_sorted=True) * w).sum())(data)
    np.testing.assert_allclose(g_cs, g_ref, atol=1e-6)


def test_gather_rows_cs_matches_take_fwd_and_bwd(seg_data, rng):
    data, ids, _ = seg_data
    h = jnp.asarray(rng.standard_normal((N, F)).astype(np.float32))
    np.testing.assert_array_equal(gather_rows_cs(h, ids), jnp.take(h, ids, axis=0))
    w = jnp.asarray(rng.standard_normal((E, F)).astype(np.float32))
    g_cs = jax.grad(lambda hh: (gather_rows_cs(hh, ids) * w).sum())(h)
    g_ref = jax.grad(lambda hh: (jnp.take(hh, ids, axis=0) * w).sum())(h)
    np.testing.assert_allclose(g_cs, g_ref, atol=2e-5)


def _nbody_graph(rng, n=24):
    from distegnn_tpu.data import build_nbody_graph

    loc = rng.normal(size=(n, 3))
    vel = rng.normal(size=(n, 3))
    charges = rng.choice([1.0, -1.0], size=(n, 1))
    return build_nbody_graph(loc, vel, charges, loc + 0.1 * vel, radius=-1.0)


@pytest.fixture
def paired_batch(rng):
    """Plain row-sorted batch of 2 uneven graphs WITH the reverse pairing."""
    return pad_graphs([_nbody_graph(rng, 24), _nbody_graph(rng, 17)],
                      compute_pair=True)


def test_plain_pairing_attached_and_valid(paired_batch):
    g = paired_batch
    assert g.edge_pair is not None and g.edges_sorted
    for b in range(g.row.shape[0]):
        r, c, p = (np.asarray(g.row[b]), np.asarray(g.col[b]),
                   np.asarray(g.edge_pair[b]))
        np.testing.assert_array_equal(r[p], c)
        np.testing.assert_array_equal(c[p], r)


def test_paired_gather_cols_cs_fwd_bwd(paired_batch, rng):
    g = paired_batch
    b = 0
    cols, pair, rows, em = g.col[b], g.edge_pair[b], g.row[b], g.edge_mask[b]
    h = jnp.asarray(rng.standard_normal((g.max_nodes, F)).astype(np.float32))
    out = paired_gather_cols_cs(h, cols, pair, rows, em)
    np.testing.assert_array_equal(out, jnp.take(h, cols, axis=0))
    # cotangents masked like the model's (zero on padded edges)
    w = jnp.asarray(rng.standard_normal(out.shape).astype(np.float32)) * em[:, None]
    g_cs = jax.grad(lambda hh: (paired_gather_cols_cs(hh, cols, pair, rows, em)
                                * w).sum())(h)
    g_ref = jax.grad(lambda hh: (jnp.take(hh, cols, axis=0) * w).sum())(h)
    np.testing.assert_allclose(g_cs, g_ref, atol=2e-5)


def test_edgeops_cumsum_matches_scatter(paired_batch, rng):
    g = paired_batch
    ops_sc = EdgeOps(g)
    ops_cs = EdgeOps(g, seg_impl="cumsum")
    assert ops_cs.cumsum
    data = jnp.asarray(rng.standard_normal(
        (g.row.shape[0], g.row.shape[1], F)).astype(np.float32))
    h = jnp.asarray(rng.standard_normal(
        (g.row.shape[0], g.max_nodes, F)).astype(np.float32))
    np.testing.assert_allclose(ops_cs.agg_rows_sum(data), ops_sc.agg_rows_sum(data),
                               atol=2e-5)
    np.testing.assert_allclose(ops_cs.agg_rows_mean(data), ops_sc.agg_rows_mean(data),
                               atol=2e-5)
    np.testing.assert_array_equal(ops_cs.gather_rows(h), ops_sc.gather_rows(h))
    np.testing.assert_array_equal(ops_cs.gather_cols(h), ops_sc.gather_cols(h))


def test_edgeops_cumsum_falls_back_when_unsorted(paired_batch):
    g = paired_batch.replace(edges_sorted=False)
    assert not EdgeOps(g, seg_impl="cumsum").cumsum


def test_fastegnn_cumsum_parity(paired_batch, rng):
    """Full model forward + gradients: cumsum lowering vs scatter lowering on
    the same plain batch (the pattern of tests/test_blocked.py)."""
    from distegnn_tpu.models.fast_egnn import FastEGNN

    g = paired_batch
    kw = dict(node_feat_nf=2, edge_attr_nf=2, hidden_nf=16, virtual_channels=3,
              n_layers=2)
    m_sc = FastEGNN(**kw)
    m_cs = FastEGNN(**kw, segment_impl="cumsum")
    params = m_sc.init(jax.random.PRNGKey(0), g)

    out_sc = m_sc.apply(params, g)
    out_cs = m_cs.apply(params, g)
    np.testing.assert_allclose(out_cs[0], out_sc[0], atol=5e-5)
    np.testing.assert_allclose(out_cs[1], out_sc[1], atol=5e-5)

    def loss(m):
        def f(p):
            loc, X = m.apply(p, g)
            return jnp.sum((loc - g.target) ** 2 * g.node_mask[..., None])
        return f

    g_sc = jax.grad(loss(m_sc))(params)
    g_cs = jax.grad(loss(m_cs))(params)
    flat_sc, _ = jax.flatten_util.ravel_pytree(g_sc)
    flat_cs, _ = jax.flatten_util.ravel_pytree(g_cs)
    np.testing.assert_allclose(np.asarray(flat_cs), np.asarray(flat_sc),
                               rtol=2e-3, atol=2e-4)


def test_fastegnn_cumsum_without_pair(rng):
    """No edge_pair attached: cumsum path still works (col-gather falls back
    to the plain take with scatter transpose)."""
    from distegnn_tpu.models.fast_egnn import FastEGNN

    g = pad_graphs([_nbody_graph(rng, 20)])  # compute_pair auto-off for plain
    assert g.edge_pair is None
    kw = dict(node_feat_nf=2, edge_attr_nf=2, hidden_nf=16, virtual_channels=3,
              n_layers=2)
    params = FastEGNN(**kw).init(jax.random.PRNGKey(0), g)
    out_sc = FastEGNN(**kw).apply(params, g)
    out_cs = FastEGNN(**kw, segment_impl="cumsum").apply(params, g)
    np.testing.assert_allclose(out_cs[0], out_sc[0], atol=5e-5)


def test_prefix_sum_pallas_matches_xla(rng):
    """ops/cumsum.py: the sequential Pallas kernel (interpret mode on CPU)
    equals XLA's cumsum, including the tile-boundary carry and ragged tail."""
    from distegnn_tpu.ops.cumsum import _TILE, prefix_sum, _prefix_pallas

    for rows in (5, _TILE, _TILE + 7, 3 * _TILE - 1):
        x = jnp.asarray(rng.standard_normal((rows, 3)).astype(np.float32))
        np.testing.assert_allclose(_prefix_pallas(x, tile=min(rows, 64)),
                                   prefix_sum(x, impl="xla"),
                                   rtol=1e-5, atol=1e-4)
    # bf16 input accumulates in f32
    xb = jnp.asarray(rng.standard_normal((100, 2)).astype(np.float32)).astype(
        jnp.bfloat16)
    out = _prefix_pallas(xb, tile=32)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(out, prefix_sum(xb, impl="xla"), rtol=2e-2, atol=1e-1)


def test_prefix_sum_pallas_under_vmap(rng):
    from distegnn_tpu.ops.cumsum import _prefix_pallas, prefix_sum

    x = jnp.asarray(rng.standard_normal((4, 130, 3)).astype(np.float32))
    out = jax.vmap(lambda xx: _prefix_pallas(xx, tile=64))(x)
    ref = jax.vmap(lambda xx: prefix_sum(xx, impl="xla"))(x)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-4)


def test_prefix_sum_pallas_vjp_matches_xla(rng, monkeypatch):
    """The pallas path is differentiable via its custom VJP (suffix sum):
    pallas_call itself has no JVP rule — first hit timing vjp(cumsum_diff)
    on hardware 2026-08-02 (AssertionError in _pallas_call_jvp_rule)."""
    from distegnn_tpu.ops import cumsum as C

    x = jnp.asarray(rng.standard_normal((300, 4)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((300, 4)).astype(np.float32))

    def loss(impl):
        monkeypatch.setenv("DISTEGNN_PREFIX_IMPL", impl)
        return jax.value_and_grad(lambda a: (C.prefix_sum(a) * w).sum())(x)

    # small rows would route 'pallas' to XLA via the auto threshold, so pin
    # the impl through the env override both ways
    v_pl, g_pl = loss("pallas")
    v_xla, g_xla = loss("xla")
    np.testing.assert_allclose(v_pl, v_xla, rtol=1e-5)
    np.testing.assert_allclose(g_pl, g_xla, rtol=1e-5, atol=1e-5)


# ------------------------------------------------------------------ ELL ---

def test_segment_sum_ell_matches_scatter(seg_data):
    from distegnn_tpu.ops.segment import segment_mean_ell, segment_sum_ell

    data, ids, mask = seg_data
    dmax = int(np.bincount(np.asarray(ids), minlength=N).max())
    ref_s = segment_sum(data, ids, N, mask=mask, indices_are_sorted=True)
    ref_m = segment_mean(data, ids, N, mask=mask, indices_are_sorted=True)
    np.testing.assert_allclose(segment_sum_ell(data, ids, N, dmax, mask=mask),
                               ref_s, atol=1e-6)
    np.testing.assert_allclose(segment_mean_ell(data, ids, N, dmax, mask=mask),
                               ref_m, atol=1e-6)
    # oversized D changes nothing; no mask also matches
    np.testing.assert_allclose(segment_sum_ell(data, ids, N, dmax + 5),
                               segment_sum(data, ids, N), atol=1e-6)


def test_segment_sum_ell_gradient_is_exact_gather(seg_data):
    from distegnn_tpu.ops.segment import segment_sum_ell

    data, ids, mask = seg_data
    dmax = int(np.bincount(np.asarray(ids), minlength=N).max())
    w = jnp.asarray(np.random.default_rng(1).standard_normal((N, F)).astype(np.float32))
    g_el = jax.grad(lambda d: (segment_sum_ell(d, ids, N, dmax, mask=mask) * w).sum())(data)
    g_ref = jax.grad(lambda d: (segment_sum(d, ids, N, mask=mask,
                                            indices_are_sorted=True) * w).sum())(data)
    np.testing.assert_allclose(g_el, g_ref, atol=1e-6)


def test_edgeops_ell_matches_scatter(paired_batch, rng):
    g = paired_batch
    assert g.max_in_degree > 0  # pad_graphs computed it with the pairing
    ops_sc = EdgeOps(g)
    ops_el = EdgeOps(g, seg_impl="ell")
    assert ops_el.ell
    data = jnp.asarray(rng.standard_normal(
        (g.row.shape[0], g.row.shape[1], F)).astype(np.float32))
    h = jnp.asarray(rng.standard_normal(
        (g.row.shape[0], g.max_nodes, F)).astype(np.float32))
    np.testing.assert_allclose(ops_el.agg_rows_sum(data), ops_sc.agg_rows_sum(data),
                               atol=1e-5)
    np.testing.assert_allclose(ops_el.agg_rows_mean(data), ops_sc.agg_rows_mean(data),
                               atol=1e-5)
    np.testing.assert_array_equal(ops_el.gather_rows(h), ops_sc.gather_rows(h))
    np.testing.assert_array_equal(ops_el.gather_cols(h), ops_sc.gather_cols(h))


def test_fastegnn_ell_parity(paired_batch, rng):
    from distegnn_tpu.models.fast_egnn import FastEGNN

    g = paired_batch
    kw = dict(node_feat_nf=2, edge_attr_nf=2, hidden_nf=16, virtual_channels=3,
              n_layers=2)
    params = FastEGNN(**kw).init(jax.random.PRNGKey(0), g)
    out_sc = FastEGNN(**kw).apply(params, g)
    out_el = FastEGNN(**kw, segment_impl="ell").apply(params, g)
    # ELL is exact arithmetic — tighter tolerance than the cumsum lowering
    np.testing.assert_allclose(out_el[0], out_sc[0], atol=1e-5)
    np.testing.assert_allclose(out_el[1], out_sc[1], atol=1e-5)

    def loss(m):
        def f(p):
            loc, X = m.apply(p, g)
            return jnp.sum((loc - g.target) ** 2 * g.node_mask[..., None])
        return f

    g_sc = jax.grad(loss(FastEGNN(**kw)))(params)
    g_el = jax.grad(loss(FastEGNN(**kw, segment_impl="ell")))(params)
    flat_sc, _ = jax.flatten_util.ravel_pytree(g_sc)
    flat_el, _ = jax.flatten_util.ravel_pytree(g_el)
    np.testing.assert_allclose(np.asarray(flat_el), np.asarray(flat_sc),
                               rtol=1e-4, atol=1e-5)
