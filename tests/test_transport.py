"""HTTP transport front-end (distegnn_tpu/serve/transport.py + registry.py):
predict parity over a REAL socket (ephemeral port), multi-model routing,
layered admission control (429/413/504), /metrics Prometheus scrape,
readiness across warmup and drain, and the queue's stop/hard-deadline
hardening — all CPU, in-process server threads."""

import base64
import json
import re
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from distegnn_tpu.models.fast_egnn import FastEGNN
from distegnn_tpu.obs.metrics import MetricsRegistry
from distegnn_tpu.ops.graph import pad_graphs
from distegnn_tpu.serve import (BucketLadder, InferenceEngine, RequestQueue,
                                RequestTimeoutError, ServeMetrics,
                                synthetic_graph)
from distegnn_tpu.serve.registry import ModelRegistry
from distegnn_tpu.serve.transport import (Gateway, PayloadError,
                                          graph_from_payload)

pytestmark = pytest.mark.serve


def _model():
    return FastEGNN(node_feat_nf=1, edge_attr_nf=2, hidden_nf=16,
                    virtual_channels=2, n_layers=2)


def _init(model, graph):
    tight = pad_graphs([graph], node_bucket=1, edge_bucket=1)
    return model.init(jax.random.PRNGKey(0), tight)


def _reference(model, params, graph):
    tight = pad_graphs([graph], node_bucket=1, edge_bucket=1)
    x, _ = model.apply(params, tight)
    return np.asarray(x[0])


def _get(url, timeout=30.0):
    """GET returning (status, parsed-or-text body) without raising on 4xx."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            body = r.read().decode()
            status = r.status
    except urllib.error.HTTPError as e:
        body = e.read().decode()
        status = e.code
    try:
        return status, json.loads(body)
    except json.JSONDecodeError:
        return status, body


def _post(url, payload, timeout=60.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def _payload(g, encoding="list"):
    if encoding == "b64":
        def f32(a):
            a = np.ascontiguousarray(a, dtype="<f4")
            return {"b64": base64.b64encode(a.tobytes()).decode(),
                    "shape": list(a.shape)}

        ei = np.ascontiguousarray(g["edge_index"], dtype="<i4")
        return {"positions": f32(g["loc"]), "velocities": f32(g["vel"]),
                "node_feat": f32(g["node_feat"]),
                "edge_attr": f32(g["edge_attr"]),
                "edge_index": {"b64": base64.b64encode(ei.tobytes()).decode(),
                               "shape": list(ei.shape)},
                "encoding": "b64"}
    return {"positions": g["loc"].tolist(), "velocities": g["vel"].tolist(),
            "node_feat": g["node_feat"].tolist(),
            "edge_index": g["edge_index"].tolist(),
            "edge_attr": g["edge_attr"].tolist()}


class _Live:
    """One warmed single-model gateway on an ephemeral port (shared by the
    read-mostly tests; admission/drain tests build their own)."""

    def __init__(self):
        self.model = _model()
        self.graph = synthetic_graph(28, seed=3)
        self.params = _init(self.model, self.graph)
        self.metrics = ServeMetrics()
        self.engine = InferenceEngine(
            self.model, self.params, max_batch=4, metrics=self.metrics,
            rollout_opts={"radius": 0.35, "max_degree": 64,
                          "max_per_cell": 64},
            session_cache=8)
        self.queue = RequestQueue(self.engine, batch_deadline_ms=30.0,
                                  queue_capacity=64,
                                  request_timeout_ms=60_000.0,
                                  metrics=self.metrics)
        self.registry = ModelRegistry.single("nbody", self.engine, self.queue,
                                             feat_nf=1, edge_attr_nf=2)
        self.registry.start()
        self.registry.warmup([28])
        self.gw = Gateway(self.registry, port=0, max_inflight=32,
                          metrics_registry=MetricsRegistry())
        self.thread = threading.Thread(target=self.gw.serve_forever,
                                       daemon=True)
        self.thread.start()
        self.url = self.gw.url

    def close(self):
        self.gw.drain()
        self.thread.join(timeout=30.0)
        self.gw.close()


@pytest.fixture(scope="module")
def live():
    env = _Live()
    yield env
    env.close()


# ------------------------------------------------------------- predict API

@pytest.mark.parametrize("encoding", ["list", "b64"])
def test_predict_parity_over_socket(live, encoding):
    """The tentpole acceptance: a socket round-trip returns the SAME numbers
    as direct model.apply on the unpadded graph, plus timing/bucket meta."""
    status, resp = _post(live.url("/v1/models/nbody/predict"),
                         _payload(live.graph, encoding))
    assert status == 200
    if encoding == "b64":
        raw = base64.b64decode(resp["prediction"]["b64"])
        pred = np.frombuffer(raw, "<f4").reshape(resp["prediction"]["shape"])
    else:
        pred = np.asarray(resp["prediction"], np.float32)
    ref = _reference(live.model, live.params, live.graph)
    np.testing.assert_allclose(pred, ref, atol=1e-4, rtol=0)
    assert resp["model"] == "nbody" and resp["n"] == 28
    assert resp["bucket"]["n"] >= 28 and resp["bucket"]["e"] >= 1
    assert resp["queue_ms"] >= 0 and resp["compute_ms"] > 0
    assert 1 <= resp["batch_filled"] <= 4
    assert resp["total_ms"] >= resp["compute_ms"]


def test_predict_server_side_radius_graph(live):
    """positions + radius only: the gateway builds the radius graph and
    defaults node_feat/edge_attr — the minimal client contract."""
    status, resp = _post(live.url("/v1/models/nbody/predict"),
                         {"positions": live.graph["loc"].tolist(),
                          "radius": 0.8})
    assert status == 200
    assert np.asarray(resp["prediction"]).shape == (28, 3)


def test_unknown_model_404(live):
    status, resp = _post(live.url("/v1/models/nope/predict"),
                         _payload(live.graph))
    assert status == 404 and resp["type"] == "UnknownModel"


@pytest.mark.parametrize("body", [
    {},                                                       # no positions
    {"positions": [[0.0, 0.0], [1.0, 1.0]]},                  # not [n, 3]
    {"positions": [[0, 0, 0], [1, 1, 1]],
     "edge_index": [[0, 5], [1, 0]]},                         # node 5 of 2
    {"positions": [[0, 0, 0], [1, 1, 1]],
     "edge_index": [[0], [1]],
     "velocities": [[0, 0, 0]]},                              # vel shape
    {"positions": {"b64": "!!!not-base64!!!"}},               # bad b64
])
def test_bad_payloads_400(live, body):
    status, resp = _post(live.url("/v1/models/nbody/predict"), body)
    assert status == 400 and resp["type"] == "PayloadError"


def test_oversize_graph_413():
    """A graph beyond the ladder caps is rejected at admission with 413,
    not a 500 — the overflow contract crosses the transport intact."""
    model = _model()
    g = synthetic_graph(24, seed=4)
    eng = InferenceEngine(model, _init(model, g), max_batch=2,
                          ladder=BucketLadder(max_nodes=64, max_edges=4096))
    q = RequestQueue(eng, request_timeout_ms=5_000.0)
    reg = ModelRegistry.single("tiny", eng, q)
    reg.start()
    gw = Gateway(reg, port=0, metrics_registry=MetricsRegistry())
    t = threading.Thread(target=gw.serve_forever, daemon=True)
    t.start()
    try:
        big = synthetic_graph(200, seed=5)
        status, resp = _post(gw.url("/v1/models/tiny/predict"), _payload(big))
        assert status == 413 and resp["type"] == "BucketOverflow"
    finally:
        gw.drain()
        t.join(timeout=30.0)
        gw.close()


# ------------------------------------------------------------- rollout API

def test_rollout_over_socket_matches_engine(live):
    """POST /rollout returns the same trajectory as the engine's direct
    rollout — the batched executable behind the socket changes latency,
    never numbers."""
    status, resp = _post(live.url("/v1/models/nbody/rollout"),
                         {"positions": live.graph["loc"].tolist(),
                          "velocities": live.graph["vel"].tolist(),
                          "steps": 3})
    assert status == 200
    traj = np.asarray(resp["trajectory"], np.float32)
    assert traj.shape == (3, 28, 3)
    ref = live.engine.rollout(live.graph["loc"], live.graph["vel"], 3)
    np.testing.assert_allclose(traj, ref, atol=1e-6, rtol=0)
    assert resp["model"] == "nbody" and resp["n"] == 28
    assert resp["steps"] == 3 and resp["bucket"]["n"] >= 28
    assert resp["queue_ms"] >= 0 and resp["compute_ms"] > 0
    assert resp["total_ms"] >= resp["compute_ms"]


def test_rollout_bad_steps_400(live):
    for steps in (0, -1, "three", None):
        status, resp = _post(live.url("/v1/models/nbody/rollout"),
                             {"positions": live.graph["loc"].tolist(),
                              "steps": steps})
        assert status == 400 and resp["type"] == "PayloadError"


def test_rollout_disabled_501():
    """A model serving without serve.rollout configured answers 501, not a
    500 — the capability gap is part of the API, not an internal error."""
    model = _model()
    g = synthetic_graph(24, seed=6)
    eng = InferenceEngine(model, _init(model, g), max_batch=2)
    q = RequestQueue(eng, request_timeout_ms=30_000.0)
    reg = ModelRegistry.single("noroll", eng, q)
    reg.start()
    gw = Gateway(reg, port=0, metrics_registry=MetricsRegistry())
    t = threading.Thread(target=gw.serve_forever, daemon=True)
    t.start()
    try:
        status, resp = _post(gw.url("/v1/models/noroll/rollout"),
                             {"positions": g["loc"].tolist(), "steps": 2})
        assert status == 501 and resp["type"] == "RolloutDisabled"
    finally:
        gw.drain()
        t.join(timeout=30.0)
        gw.close()


# --------------------------------------------------------- sessions

def test_predict_session_cache_hit_parity_and_metrics(live):
    """A session_id predict pays prep once: the second request is a cache
    hit, returns bitwise-identical numbers, and the hit counter lands in
    GET /metrics."""
    p = _payload(live.graph)
    p["session_id"] = "sess-parity"
    s1, r1 = _post(live.url("/v1/models/nbody/predict"), p)
    s2, r2 = _post(live.url("/v1/models/nbody/predict"), p)
    assert s1 == 200 and s2 == 200
    assert r1["session"]["hit"] is False
    assert r2["session"]["hit"] is True
    assert r1["session"]["id"] == r2["session"]["id"] == "sess-parity"
    assert r2["session"]["prep_ms"] >= 0.0   # warm hit: gather-only replay
    np.testing.assert_array_equal(np.asarray(r1["prediction"], np.float32),
                                  np.asarray(r2["prediction"], np.float32))
    # parity with the sessionless path: the cache changes latency, never
    # results
    s0, r0 = _post(live.url("/v1/models/nbody/predict"),
                   _payload(live.graph))
    assert s0 == 200 and "session" not in r0
    np.testing.assert_array_equal(np.asarray(r0["prediction"], np.float32),
                                  np.asarray(r1["prediction"], np.float32))
    status, text = _get(live.url("/metrics"))
    assert status == 200
    hits = re.search(
        r"(?m)^distegnn_model_nbody_serve_session_hits (\S+)$", text)
    misses = re.search(
        r"(?m)^distegnn_model_nbody_serve_session_misses (\S+)$", text)
    assert hits and float(hits.group(1)) >= 1
    assert misses and float(misses.group(1)) >= 1


# --------------------------------------------------------- operational API

def test_healthz_models_and_unknown_route(live):
    assert _get(live.url("/healthz"))[0] == 200
    status, listing = _get(live.url("/v1/models"))
    assert status == 200
    (m,) = listing["models"]
    assert m["name"] == "nbody" and m["state"] == "ready"
    assert m["dispatcher_alive"] and m["warmed_rungs"]
    assert _get(live.url("/no/such/route"))[0] == 404


def test_metrics_prometheus_parses_with_gateway_series(live):
    _post(live.url("/v1/models/nbody/predict"), _payload(live.graph))
    status, text = _get(live.url("/metrics"))
    assert status == 200
    names = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        # well-formed exposition: name, optional {labels}, float value
        m = re.fullmatch(
            r"([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})? (\S+)", line)
        assert m, f"unparseable metrics line: {line!r}"
        names[m.group(1)] = float(m.group(3))
    assert names["distegnn_gateway_requests_total"] >= 1
    assert names["distegnn_gateway_predict_ok"] >= 1
    assert "distegnn_gateway_inflight" in names
    assert names["distegnn_gateway_ready"] == 1.0
    # per-model serve series render under a per-model name prefix
    assert names["distegnn_model_nbody_serve_requests_completed"] >= 1
    assert any(n.startswith("distegnn_gateway_http_predict_ms") for n in names)


def test_concurrent_clients_share_micro_batches(live):
    """Co-arriving same-bucket requests from independent sockets coalesce
    into shared micro-batches — the whole point of the serving stack."""
    n_req, results = 12, [None] * 12
    barrier = threading.Barrier(n_req)

    def post(i):
        barrier.wait()
        results[i] = _post(live.url("/v1/models/nbody/predict"),
                           _payload(live.graph))

    threads = [threading.Thread(target=post, args=(i,)) for i in range(n_req)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120.0)
    assert all(r is not None and r[0] == 200 for r in results)
    fills = [r[1]["batch_filled"] for r in results]
    assert max(fills) > 1, f"no micro-batch formed (fills={fills})"
    ref = _reference(live.model, live.params, live.graph)
    for _, resp in results:
        np.testing.assert_allclose(np.asarray(resp["prediction"]), ref,
                                   atol=1e-4, rtol=0)


# -------------------------------------------------------- admission control

def test_gateway_sheds_at_max_inflight(live):
    """max_inflight=0 sheds EVERY predict with 429 before touching a queue
    (operational endpoints stay up — shedding is for compute only)."""
    gw = Gateway(live.registry, port=0, max_inflight=0,
                 metrics_registry=MetricsRegistry())
    t = threading.Thread(target=gw.serve_forever, daemon=True)
    t.start()
    try:
        status, resp = _post(gw.url("/v1/models/nbody/predict"),
                             _payload(live.graph))
        assert status == 429 and resp["type"] == "Overloaded"
        assert _get(gw.url("/healthz"))[0] == 200
    finally:
        # don't drain: that would stop the module fixture's shared queue
        gw._accepting = False
        gw.httpd.shutdown()
        t.join(timeout=30.0)
        gw.close()


def test_queue_full_429_and_wedged_dispatcher_504():
    """A wedged dispatcher (started flag, no thread): capacity-1 ingress
    429s the second request, while the first one's no-timeout result() is
    bounded by the hard deadline and surfaces as 504 — never a hung socket."""
    model = _model()
    g = synthetic_graph(20, seed=6)
    eng = InferenceEngine(model, _init(model, g), max_batch=2)
    q = RequestQueue(eng, queue_capacity=1, request_timeout_ms=150.0,
                     result_margin_s=0.4)
    q._started = True            # no dispatcher: nothing ever drains
    reg = ModelRegistry.single("wedged", eng, q)
    gw = Gateway(reg, port=0, metrics_registry=MetricsRegistry())
    t = threading.Thread(target=gw.serve_forever, daemon=True)
    t.start()
    try:
        first = {}

        def slow_post():
            first["resp"] = _post(gw.url("/v1/models/wedged/predict"),
                                  _payload(g), timeout=30.0)

        th = threading.Thread(target=slow_post)
        th.start()
        deadline = time.monotonic() + 5.0
        while q.depth() < 1 and time.monotonic() < deadline:
            time.sleep(0.01)     # wait for request 1 to occupy the ingress
        status, resp = _post(gw.url("/v1/models/wedged/predict"), _payload(g))
        assert status == 429 and resp["type"] == "QueueFull"
        th.join(timeout=30.0)
        assert first["resp"][0] == 504
        assert first["resp"][1]["type"] == "RequestTimeout"
    finally:
        q._started = False
        gw._accepting = False
        gw.httpd.shutdown()
        t.join(timeout=30.0)
        gw.close()


# ------------------------------------------------------------ ready + drain

def test_readyz_flips_across_warmup_and_drain():
    """/readyz: 503 cold -> 200 warmed -> 503 while draining (and predicts
    get 503 Draining, not a hang); after drain the dispatcher is down."""
    model = _model()
    g = synthetic_graph(20, seed=7)
    eng = InferenceEngine(model, _init(model, g), max_batch=2)
    q = RequestQueue(eng, request_timeout_ms=10_000.0)
    reg = ModelRegistry.single("m", eng, q)
    gw = Gateway(reg, port=0, metrics_registry=MetricsRegistry())
    t = threading.Thread(target=gw.serve_forever, daemon=True)
    t.start()
    entered, release = threading.Event(), threading.Event()
    try:
        status, resp = _get(gw.url("/readyz"))
        assert status == 503 and resp["ready"] is False   # cold, not started

        reg.start()
        reg.warmup([20])
        assert _get(gw.url("/readyz"))[0] == 200

        # hold the drain open mid-flight so the 503 window is observable
        orig_stop = reg.stop

        def held_stop(drain=True):
            entered.set()
            release.wait(timeout=10.0)
            orig_stop(drain=drain)

        reg.stop = held_stop
        drainer = threading.Thread(target=gw.drain, daemon=True)
        drainer.start()
        assert entered.wait(timeout=10.0)
        status, resp = _get(gw.url("/readyz"))
        assert status == 503 and resp["reason"] == "draining"
        status, resp = _post(gw.url("/v1/models/m/predict"), _payload(g))
        assert status == 503 and resp["type"] == "Draining"
        release.set()
        drainer.join(timeout=30.0)
        t.join(timeout=30.0)
        assert not t.is_alive()        # accept loop exited after the drain
        assert not q.alive() and not gw.ready()
        gw.drain()                     # idempotent: second drain is a no-op
    finally:
        release.set()
        gw.close()


# ----------------------------------------------- queue hardening satellites

def test_queue_stop_idempotent_and_signal_safe():
    """stop() never raises or deadlocks: before start, double, concurrent
    (the SIGTERM drain racing a with-block exit), and across a restart."""
    model = _model()
    g = synthetic_graph(20, seed=8)
    eng = InferenceEngine(model, _init(model, g), max_batch=2)
    q = RequestQueue(eng, request_timeout_ms=10_000.0)
    q.stop()                     # stop before start: no-op
    q.stop(drain=False)

    q.start()
    fut = q.submit(g)
    assert fut.result(timeout=60.0).shape == (20, 3)
    stoppers = [threading.Thread(target=q.stop) for _ in range(4)]
    for t in stoppers:
        t.start()
    for t in stoppers:
        t.join(timeout=30.0)
    assert not any(t.is_alive() for t in stoppers)
    assert not q.alive()
    with pytest.raises(RuntimeError):
        q.submit(g)              # stopped queue rejects, never silently drops

    q.start()                    # restartable after a full stop
    assert q.submit(g).result(timeout=60.0).shape == (20, 3)
    q.stop()
    q.stop()


def test_future_hard_deadline_bounds_blocking_result():
    """A no-timeout result() on a wedged queue raises the typed timeout at
    request_timeout + result_margin — the gateway's 504, not a hang."""
    model = _model()
    g = synthetic_graph(20, seed=9)
    eng = InferenceEngine(model, _init(model, g), max_batch=2)
    q = RequestQueue(eng, request_timeout_ms=100.0, result_margin_s=0.3)
    q._started = True            # wedged: no dispatcher will ever resolve it
    fut = q.submit(g)
    t0 = time.monotonic()
    with pytest.raises(RequestTimeoutError):
        fut.result()             # NO timeout arg: the hard deadline bounds it
    assert time.monotonic() - t0 < 5.0
    q._started = False
    q._fail_all(RequestTimeoutError("cleanup"))


# ----------------------------------------------------------- payload parsing

def test_graph_from_payload_defaults_and_validation():
    g = synthetic_graph(10, seed=10)
    out = graph_from_payload({"positions": g["loc"].tolist(),
                              "edge_index": g["edge_index"].tolist()},
                             feat_nf=1, edge_attr_nf=2)
    assert out["loc"].shape == (10, 3) and out["vel"].shape == (10, 3)
    assert out["node_feat"].shape == (10, 1)
    assert out["edge_attr"].shape == (g["edge_index"].shape[1], 2)
    assert out["edge_index"].dtype == np.int32
    with pytest.raises(PayloadError):
        graph_from_payload({"positions": g["loc"].tolist()}, 1, 2)  # no edges
    with pytest.raises(PayloadError):
        graph_from_payload({"positions": g["loc"].tolist(),
                            "edge_index": g["edge_index"].tolist(),
                            "node_feat": [[1.0]] * 3}, 1, 2)  # wrong n


# ------------------------------------------------------- multi-model config

def test_registry_from_config_multi_model_routing():
    """serve.models: two independently-overridden models behind one gateway,
    each owning its engine/queue/warmup; /v1/models lists both and predicts
    route to DIFFERENT weights (the responses must differ)."""
    from distegnn_tpu.config import ConfigDict, _DEFAULTS

    cfg = ConfigDict(_DEFAULTS)
    cfg.model.update(model_name="FastEGNN", hidden_nf=16, n_layers=2,
                     virtual_channels=2, node_feat_nf=1, edge_attr_nf=2)
    cfg.serve.models = [
        {"name": "a"},
        {"name": "b", "overrides": {"model": {"hidden_nf": 8}, "seed": 7}},
    ]
    reg = ModelRegistry.from_config(cfg)
    assert reg.names() == ["a", "b"]
    assert reg.get("b").config.model.hidden_nf == 8
    reg.start()
    reg.warmup([20])
    assert reg.ready()
    gw = Gateway(reg, port=0, metrics_registry=MetricsRegistry())
    t = threading.Thread(target=gw.serve_forever, daemon=True)
    t.start()
    try:
        status, listing = _get(gw.url("/v1/models"))
        assert status == 200
        assert [m["name"] for m in listing["models"]] == ["a", "b"]
        assert all(m["state"] == "ready" for m in listing["models"])
        g = synthetic_graph(20, seed=12)
        preds = {}
        for name in ("a", "b"):
            status, resp = _post(gw.url(f"/v1/models/{name}/predict"),
                                 _payload(g))
            assert status == 200 and resp["model"] == name
            preds[name] = np.asarray(resp["prediction"])
        # different widths + seeds: same input, different weights
        assert not np.allclose(preds["a"], preds["b"])
        _, text = _get(gw.url("/metrics"))
        assert "distegnn_model_a_serve_requests_completed" in text
        assert "distegnn_model_b_serve_requests_completed" in text
    finally:
        gw.drain()
        t.join(timeout=30.0)
        gw.close()


# ------------------------------------------------------- request tracing

def _post_traced(url, payload, rid=None, timeout=60.0):
    """POST keeping the response headers (the X-Request-Id echo)."""
    headers = {"Content-Type": "application/json"}
    if rid is not None:
        headers["X-Request-Id"] = rid
    req = urllib.request.Request(url, data=json.dumps(payload).encode(),
                                 headers=headers, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.load(r), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e), dict(e.headers)


def test_request_id_minted_and_echoed(live):
    """X-Request-Id: supplied ids echo back verbatim (sanitized); absent
    ids get minted at the edge — either way the id is in the body too."""
    s, body, hdrs = _post_traced(live.url("/v1/models/nbody/predict"),
                                 _payload(live.graph), rid="client-rid-7")
    assert s == 200
    assert hdrs["X-Request-Id"] == "client-rid-7"
    assert body["request_id"] == "client-rid-7"
    s, body, hdrs = _post_traced(live.url("/v1/models/nbody/predict"),
                                 _payload(live.graph))
    assert s == 200
    minted = hdrs["X-Request-Id"]
    assert len(minted) == 16 and body["request_id"] == minted


def test_concurrent_clients_traced_end_to_end(live, tmp_path):
    """The tracing satellite: N concurrent clients, every accepted
    request's id lands on >=3 records (serve/http span, serve/batch event,
    serve/execute span), and the stitched queue+prep+compute timeline is
    bounded by the transport's reported total_ms."""
    from distegnn_tpu.obs import report, trace

    n_req = 8
    results = [None] * n_req
    barrier = threading.Barrier(n_req)
    trace.configure(log_dir=str(tmp_path))
    try:
        def post(i):
            barrier.wait()
            results[i] = _post_traced(live.url("/v1/models/nbody/predict"),
                                      _payload(live.graph), rid=f"conc-{i}")

        threads = [threading.Thread(target=post, args=(i,))
                   for i in range(n_req)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        # the serve/http span exits AFTER the response bytes hit the socket,
        # so its record can trail the clients' joins — poll until every
        # waterfall is complete instead of flushing once
        deadline = time.monotonic() + 20.0
        while True:
            trace.flush()
            events = report.load_events(str(tmp_path / "events.jsonl"))[0]
            if all(report.stitch_request(events, f"conc-{i}")["complete"]
                   for i in range(n_req)) or time.monotonic() >= deadline:
                break
            time.sleep(0.2)
    finally:
        trace.configure(log_dir=None)

    assert all(r is not None and r[0] == 200 for r in results)
    for i, (status, body, hdrs) in enumerate(results):
        rid = f"conc-{i}"
        assert hdrs["X-Request-Id"] == rid
        stitched = report.stitch_request(events, rid)
        names = [r["name"] for r in stitched["records"]]
        assert "serve/http" in names, names
        assert "serve/batch" in names, names
        assert "serve/execute" in names, names
        assert len(stitched["records"]) >= 3
        assert stitched["complete"], (rid, stitched["phases"])
        # the stitched timeline is the inside view of total_ms: it must
        # never exceed it, and on a sane host it accounts for most of it
        total = float(body["total_ms"])
        slack = max(50.0, 0.5 * total)       # CI-host tolerance
        assert stitched["stitched_ms"] <= total + slack
        assert total - stitched["stitched_ms"] <= slack, (
            rid, total, stitched["phases"])
    # batch-level records list their member ids: the concurrent burst
    # must have coalesced at least two traced requests into one batch
    batch_members = [e.get("request_ids") or [] for e in events
                     if e.get("name") == "serve/batch"]
    assert any(len([r for r in ids if r.startswith("conc-")]) > 1
               for ids in batch_members), batch_members


# ------------------------------------------------------------------- bench

def test_serve_bench_http_transport_one_json_line(capsys):
    """--transport http: the SAME open loop through a real socket still
    emits exactly one BENCH JSON line on stdout."""
    from scripts.serve_bench import main as bench_main

    rc = bench_main(["--requests", "8", "--rate", "500", "--sizes", "24",
                     "--seed", "11", "--transport", "http", "--obs-dir", ""])
    assert rc == 0
    lines = [ln for ln in capsys.readouterr().out.strip().splitlines() if ln]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["metric"] == "serve_throughput"
    assert rec["transport"] == "http"
    assert rec["value"] > 0
    assert rec["snapshot"]["requests_completed"] == 8
