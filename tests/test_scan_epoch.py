"""ScanEpochRunner (train/scan_epoch.py): the scanned epoch must be the SAME
training run as the host loop — identical permutations, PRNG keys, and
therefore identical parameters and losses."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from distegnn_tpu.data.loader import GraphDataset, GraphLoader
from distegnn_tpu.models.fast_egnn import FastEGNN
from distegnn_tpu.train import TrainState, make_eval_step, make_optimizer, make_train_step
from distegnn_tpu.train.scan_epoch import ScanEpochRunner
from distegnn_tpu.train.trainer import run_epoch_eval, run_epoch_train


def _toy_dataset(rng, n_graphs=12, n=16):
    graphs = []
    for _ in range(n_graphs):
        loc = rng.normal(size=(n, 3)).astype(np.float32)
        vel = rng.normal(size=(n, 3)).astype(np.float32)
        row, col = np.nonzero(~np.eye(n, dtype=bool))
        graphs.append({
            "node_feat": np.linalg.norm(vel, axis=1, keepdims=True).astype(np.float32),
            "loc": loc, "vel": vel, "target": loc + 0.1 * vel,
            "edge_index": np.stack([row, col]).astype(np.int64),
            "edge_attr": np.ones((row.size, 2), np.float32),
        })
    return GraphDataset(graphs)


def test_scan_epoch_matches_host_loop():
    rng = np.random.default_rng(7)
    ds = _toy_dataset(rng)
    mk = lambda shuffle: GraphLoader(ds, batch_size=4, shuffle=shuffle, seed=11)

    model = FastEGNN(node_feat_nf=1, edge_attr_nf=2, hidden_nf=8,
                     virtual_channels=2, n_layers=2)
    tx = make_optimizer(1e-3, weight_decay=0.0, clip_norm=0.3)
    params = model.init(jax.random.PRNGKey(0), next(iter(mk(False))))
    train_step = jax.jit(make_train_step(model, tx, mmd_weight=0.01,
                                         mmd_sigma=1.5, mmd_samples=2))
    eval_step = jax.jit(make_eval_step(model))

    # host loop
    state_a = TrainState.create(params, tx)
    loader_a = mk(True)
    losses_a = []
    for epoch in (1, 2, 3):
        state_a, loss = run_epoch_train(train_step, state_a, loader_a, 11, epoch)
        losses_a.append(loss)
    eval_a = run_epoch_eval(eval_step, state_a.params, mk(False))

    # scanned
    state_b = TrainState.create(params, tx)
    runner = ScanEpochRunner(train_step, eval_step, mk(True), 11,
                             loader_valid=mk(False), loader_test=mk(False))
    losses_b = []
    for epoch in (1, 2, 3):
        state_b, loss = runner.train_epoch(state_b, epoch)
        losses_b.append(float(loss))
    eval_b = runner.eval_epoch(state_b.params, "valid")

    np.testing.assert_allclose(losses_b, losses_a, rtol=1e-5)
    np.testing.assert_allclose(eval_b, eval_a, rtol=1e-5)
    fa = ravel_pytree(state_a.params)[0]
    fb = ravel_pytree(state_b.params)[0]
    np.testing.assert_allclose(fb, fa, atol=1e-5)
