"""ScanEpochRunner (train/scan_epoch.py): the scanned epoch must be the SAME
training run as the host loop — identical permutations, PRNG keys, and
therefore identical parameters and losses."""

import numpy as np
import jax
import pytest
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from distegnn_tpu.data.loader import GraphDataset, GraphLoader
from distegnn_tpu.models.fast_egnn import FastEGNN
from distegnn_tpu.train import TrainState, make_eval_step, make_optimizer, make_train_step
from distegnn_tpu.train.scan_epoch import ScanEpochRunner
from distegnn_tpu.train.trainer import run_epoch_eval, run_epoch_train


def _toy_dataset(rng, n_graphs=12, n=16):
    graphs = []
    for _ in range(n_graphs):
        loc = rng.normal(size=(n, 3)).astype(np.float32)
        vel = rng.normal(size=(n, 3)).astype(np.float32)
        row, col = np.nonzero(~np.eye(n, dtype=bool))
        graphs.append({
            "node_feat": np.linalg.norm(vel, axis=1, keepdims=True).astype(np.float32),
            "loc": loc, "vel": vel, "target": loc + 0.1 * vel,
            "edge_index": np.stack([row, col]).astype(np.int64),
            "edge_attr": np.ones((row.size, 2), np.float32),
        })
    return GraphDataset(graphs)


def test_scan_epoch_matches_host_loop():
    rng = np.random.default_rng(7)
    ds = _toy_dataset(rng)
    mk = lambda shuffle: GraphLoader(ds, batch_size=4, shuffle=shuffle, seed=11)

    model = FastEGNN(node_feat_nf=1, edge_attr_nf=2, hidden_nf=8,
                     virtual_channels=2, n_layers=2)
    tx = make_optimizer(1e-3, weight_decay=0.0, clip_norm=0.3)
    params = model.init(jax.random.PRNGKey(0), next(iter(mk(False))))
    train_step = jax.jit(make_train_step(model, tx, mmd_weight=0.01,
                                         mmd_sigma=1.5, mmd_samples=2))
    eval_step = jax.jit(make_eval_step(model))

    # host loop
    state_a = TrainState.create(params, tx)
    loader_a = mk(True)
    losses_a = []
    for epoch in (1, 2, 3):
        state_a, loss = run_epoch_train(train_step, state_a, loader_a, 11, epoch)
        losses_a.append(loss)
    eval_a = run_epoch_eval(eval_step, state_a.params, mk(False))

    # scanned
    state_b = TrainState.create(params, tx)
    runner = ScanEpochRunner(train_step, eval_step, mk(True), 11,
                             loader_valid=mk(False), loader_test=mk(False))
    losses_b = []
    for epoch in (1, 2, 3):
        state_b, loss = runner.train_epoch(state_b, epoch)
        losses_b.append(float(loss))
    eval_b = runner.eval_epoch(state_b.params, "valid")

    np.testing.assert_allclose(losses_b, losses_a, rtol=1e-5)
    np.testing.assert_allclose(eval_b, eval_a, rtol=1e-5)
    fa = ravel_pytree(state_a.params)[0]
    fb = ravel_pytree(state_b.params)[0]
    np.testing.assert_allclose(fb, fa, atol=1e-5)


def _part_datasets(rng, n_parts=2, n_graphs=8, n=12):
    """Independent per-partition toy shards (parity needs identical inputs on
    both paths, not a physically meaningful partitioning) — except loc_mean,
    which partitions of one graph genuinely share (it is the GLOBAL mean;
    the in-step consistency check asserts exactly that)."""
    dss = [_toy_dataset(rng, n_graphs=n_graphs, n=n) for _ in range(n_parts)]
    for i in range(n_graphs):
        mean = np.mean([ds.graphs[i]["loc"] for ds in dss], axis=(0, 1))
        for ds in dss:
            ds.graphs[i]["loc_mean"] = mean.astype(np.float32)
    return dss


@pytest.mark.parametrize("dp", [1, 2])
def test_distributed_scan_matches_per_step_loop(dp):
    """DistributedScanRunner == per-step shard_map loop: same permutations,
    same PRNG keys, same parameters — on the 1-D graph mesh and the 2-D
    data x graph mesh (VERDICT r2 weak #4)."""
    from distegnn_tpu.data.loader import ShardedGraphLoader
    from distegnn_tpu.parallel.launch import (
        global_batch_putter, make_device_steps, make_distributed_steps)
    from distegnn_tpu.parallel.mesh import make_mesh
    from distegnn_tpu.train.scan_epoch import DistributedScanRunner

    n_parts, seed = 2, 13
    rng = np.random.default_rng(21)
    datasets = _part_datasets(rng, n_parts=n_parts)
    mesh = make_mesh(n_graph=n_parts, n_data=dp,
                     devices=jax.devices()[: n_parts * dp])
    mk = lambda shuffle: ShardedGraphLoader(
        datasets, batch_size=2, shuffle=shuffle, seed=seed, data_parallel=dp)

    model = FastEGNN(node_feat_nf=1, edge_attr_nf=2, hidden_nf=8,
                     virtual_channels=2, n_layers=2, axis_name="graph")
    tx = make_optimizer(1e-3, weight_decay=0.0, clip_norm=0.3,
                        accumulation_steps=2)
    sample = next(iter(mk(False)))
    strip = (lambda x: x[0, 0]) if dp > 1 else (lambda x: x[0])
    params = model.copy(axis_name=None).init(
        jax.random.PRNGKey(0), jax.tree.map(strip, sample))

    # per-step loop (the proven path)
    step_ps, eval_ps = make_distributed_steps(
        model, tx, mesh, mmd_weight=0.01, mmd_sigma=1.5, mmd_samples=2)
    put = global_batch_putter(mesh)
    state_a = TrainState.create(params, tx)
    losses_a = []
    for epoch in (1, 2):
        loader = mk(True)
        loader.set_epoch(epoch)
        total = 0.0
        for step_idx, batch in enumerate(loader):
            key = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(seed), epoch), step_idx)
            state_a, metrics = step_ps(state_a, put(batch), key)
            total += float(metrics["loss"])
        losses_a.append(total / len(loader))
    eval_loader = mk(False)
    eval_a = np.mean([float(eval_ps(state_a.params, put(b))) for b in eval_loader])

    # scanned path
    dstep, dev = make_device_steps(
        model, tx, mesh, mmd_weight=0.01, mmd_sigma=1.5, mmd_samples=2)
    runner = DistributedScanRunner(dstep, dev, mesh, mk(True), seed,
                                   loader_valid=mk(False), loader_test=mk(False))
    state_b = TrainState.create(params, tx)
    losses_b = []
    for epoch in (1, 2):
        state_b, loss = runner.train_epoch(state_b, epoch)
        losses_b.append(float(loss))
    eval_b = runner.eval_epoch(state_b.params, "valid")

    np.testing.assert_allclose(losses_b, losses_a, rtol=1e-5)
    np.testing.assert_allclose(eval_b, eval_a, rtol=1e-5)
    fa = ravel_pytree(state_a.params)[0]
    fb = ravel_pytree(state_b.params)[0]
    np.testing.assert_allclose(fb, fa, atol=1e-5)


def test_stack_sharded_drops_pair_on_asymmetric_partition():
    """If any partition's pairing fails (asymmetric edges), the stacked
    dataset drops edge_pair everywhere — the dataset-level analog of
    ShardedGraphLoader.__iter__'s per-step all-or-nothing rule — instead of
    raising at runner construction."""
    from distegnn_tpu.data.loader import ShardedGraphLoader
    from distegnn_tpu.parallel.mesh import make_mesh
    from distegnn_tpu.train.scan_epoch import stack_sharded_dataset

    rng = np.random.default_rng(3)
    sym = _toy_dataset(rng, n_graphs=4, n=8)
    asym_graphs = []
    for g in _toy_dataset(rng, n_graphs=4, n=8).graphs:
        g = dict(g)
        g["edge_index"] = g["edge_index"][:, :-1]  # break one reverse edge
        g["edge_attr"] = g["edge_attr"][:-1]
        asym_graphs.append(g)
    from distegnn_tpu.data.loader import GraphDataset

    sharded = ShardedGraphLoader([sym, GraphDataset(asym_graphs)],
                                 batch_size=2, seed=0, pairing=True)
    mesh = make_mesh(n_graph=2, devices=jax.devices()[:2])
    data = stack_sharded_dataset(sharded, mesh)
    assert data.edge_pair is None
    assert data.loc.shape[:2] == (2, 4)   # [P, G, ...]


def _assert_resume_equivalent(make_runner, params, tx):
    """4 scanned epochs == 2 epochs + checkpoint round-trip into a FRESH
    runner + 2 more — the staged TPU convergence protocol
    (scripts/convergence_session.sh: scan_epochs on, resume from
    last_model.ckpt between stages)."""
    import os
    import tempfile

    from distegnn_tpu.train.checkpoint import restore_checkpoint, save_checkpoint

    runner = make_runner()
    state_a = TrainState.create(params, tx)
    for epoch in (1, 2, 3, 4):
        state_a, _ = runner.train_epoch(state_a, epoch)

    state_b = TrainState.create(params, tx)
    for epoch in (1, 2):
        state_b, _ = runner.train_epoch(state_b, epoch)
    with tempfile.TemporaryDirectory() as d:
        ckpt = os.path.join(d, "last.ckpt")
        save_checkpoint(ckpt, state_b, epoch=2)
        state_c = TrainState.create(params, tx)
        state_c, start_epoch, _ = restore_checkpoint(ckpt, state_c)
    assert start_epoch == 2
    runner2 = make_runner()
    for epoch in (3, 4):
        state_c, _ = runner2.train_epoch(state_c, epoch)

    fa = ravel_pytree(state_a.params)[0]
    fc = ravel_pytree(state_c.params)[0]
    np.testing.assert_array_equal(np.asarray(fc), np.asarray(fa))


def test_scan_resume_equals_uninterrupted():
    rng = np.random.default_rng(17)
    ds = _toy_dataset(rng)
    mk = lambda shuffle: GraphLoader(ds, batch_size=4, shuffle=shuffle, seed=9)
    model = FastEGNN(node_feat_nf=1, edge_attr_nf=2, hidden_nf=8,
                     virtual_channels=2, n_layers=2)
    tx = make_optimizer(1e-3, weight_decay=0.0, clip_norm=0.3)
    params = model.init(jax.random.PRNGKey(0), next(iter(mk(False))))
    train_step = jax.jit(make_train_step(model, tx, mmd_weight=0.01,
                                         mmd_sigma=1.5, mmd_samples=2))
    _assert_resume_equivalent(
        lambda: ScanEpochRunner(train_step, None, mk(True), 9), params, tx)


def test_distributed_scan_resume_equals_uninterrupted():
    from distegnn_tpu.data.loader import ShardedGraphLoader
    from distegnn_tpu.parallel.launch import make_device_steps
    from distegnn_tpu.parallel.mesh import make_mesh
    from distegnn_tpu.train.scan_epoch import DistributedScanRunner

    rng = np.random.default_rng(23)
    datasets = _part_datasets(rng, n_parts=2)
    mesh = make_mesh(n_graph=2, devices=jax.devices()[:2])
    mk = lambda: ShardedGraphLoader(datasets, batch_size=2, shuffle=True, seed=7)

    model = FastEGNN(node_feat_nf=1, edge_attr_nf=2, hidden_nf=8,
                     virtual_channels=2, n_layers=2, axis_name="graph")
    tx = make_optimizer(1e-3, weight_decay=0.0, clip_norm=0.3)
    sample = next(iter(mk()))
    params = model.copy(axis_name=None).init(
        jax.random.PRNGKey(0), jax.tree.map(lambda x: x[0], sample))
    dstep, _ = make_device_steps(model, tx, mesh, mmd_weight=0.01,
                                 mmd_sigma=1.5, mmd_samples=2)
    _assert_resume_equivalent(
        lambda: DistributedScanRunner(dstep, None, mesh, mk(), 7), params, tx)
