"""Packed per-layer aggregation (EdgeOps.agg_rows_pair, model fuse_agg):
one segment-sum pass carries coordinate translations + edge features +
count. Parity against the two-call path for every plain lowering, forward
and gradients, plus the opt-in bf16 stream (VERDICT r3 #1 prepared attack)."""

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np
import pytest

from distegnn_tpu.ops.blocked import EdgeOps
from distegnn_tpu.ops.graph import pad_graphs


def _graph(rng, n=24):
    from distegnn_tpu.data import build_nbody_graph

    loc = rng.normal(size=(n, 3))
    vel = rng.normal(size=(n, 3))
    charges = rng.choice([1.0, -1.0], size=(n, 1))
    return build_nbody_graph(loc, vel, charges, loc + 0.1 * vel, radius=-1.0)


@pytest.fixture
def batch(rng):
    return pad_graphs([_graph(rng, 24), _graph(rng, 17)], compute_pair=True,
                      max_in_degree=32)


@pytest.mark.parametrize("seg", ["scatter", "cumsum", "ell"])
@pytest.mark.parametrize("a_mean", [True, False])
def test_agg_rows_pair_matches_two_calls(batch, rng, seg, a_mean):
    ops = EdgeOps(batch, seg_impl=seg)
    B, E = batch.row.shape
    a = jnp.asarray(rng.standard_normal((B, E, 3)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((B, E, 7)).astype(np.float32))
    out_a, out_b = ops.agg_rows_pair(a, b, a_mean=a_mean)
    # reference: the existing two-call path (these mask internally)
    ref_a = ops.agg_rows_mean(a) if a_mean else ops.agg_rows_sum(
        a * batch.edge_mask[..., None])
    ref_b = ops.agg_rows_mean(b)
    np.testing.assert_allclose(out_a, ref_a, rtol=1e-5, atol=2e-5)
    np.testing.assert_allclose(out_b, ref_b, rtol=1e-5, atol=2e-5)


@pytest.mark.parametrize("seg", ["scatter", "cumsum", "ell"])
def test_agg_rows_pair_grads_match(batch, rng, seg):
    ops = EdgeOps(batch, seg_impl=seg)
    B, E = batch.row.shape
    a = jnp.asarray(rng.standard_normal((B, E, 3)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((B, E, 5)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal(
        (batch.max_nodes, 1)).astype(np.float32))

    def fused(a, b):
        oa, ob = ops.agg_rows_pair(a, b, a_mean=True)
        return jnp.sum(oa * w) + jnp.sum(ob * w)

    def ref(a, b):
        return (jnp.sum(ops.agg_rows_mean(a) * w)
                + jnp.sum(ops.agg_rows_mean(b) * w))

    ga = jax.grad(fused, argnums=(0, 1))(a, b)
    gr = jax.grad(ref, argnums=(0, 1))(a, b)
    for x, y in zip(ga, gr):
        np.testing.assert_allclose(x, y, rtol=1e-5, atol=2e-5)


def test_agg_rows_pair_bf16_stream(batch, rng):
    """bf16 packed stream: f32 accumulation keeps values at bf16 input-round
    accuracy (NOT bf16-accumulation accuracy)."""
    ops = EdgeOps(batch, seg_impl="scatter")
    B, E = batch.row.shape
    a = jnp.asarray(rng.standard_normal((B, E, 3)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((B, E, 7)).astype(np.float32))
    out_a, out_b = ops.agg_rows_pair(a, b, a_mean=True, agg_dtype="bf16")
    ref_a = ops.agg_rows_mean(a)
    ref_b = ops.agg_rows_mean(b)
    assert out_a.dtype == jnp.float32
    np.testing.assert_allclose(out_a, ref_a, rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(out_b, ref_b, rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("seg", ["scatter", "cumsum", "ell"])
def test_fastegnn_fuse_agg_parity(batch, rng, seg):
    """Full model: fuse_agg=True (default) vs fuse_agg=False, forward +
    gradients, per lowering."""
    from distegnn_tpu.models.fast_egnn import FastEGNN

    g = batch
    kw = dict(node_feat_nf=2, edge_attr_nf=2, hidden_nf=16, virtual_channels=3,
              n_layers=2, segment_impl=seg)
    m_f = FastEGNN(**kw)                    # fused (default)
    m_u = FastEGNN(**kw, fuse_agg=False)    # two-call path
    params = m_f.init(jax.random.PRNGKey(0), g)

    out_f = m_f.apply(params, g)
    out_u = m_u.apply(params, g)
    np.testing.assert_allclose(out_f[0], out_u[0], rtol=1e-5, atol=5e-5)
    np.testing.assert_allclose(out_f[1], out_u[1], rtol=1e-5, atol=5e-5)

    def loss(m):
        def f(p):
            loc, X = m.apply(p, g)
            return jnp.sum((loc - g.target) ** 2 * g.node_mask[..., None])
        return f

    g_f = jax.grad(loss(m_f))(params)
    g_u = jax.grad(loss(m_u))(params)
    flat_f, _ = jax.flatten_util.ravel_pytree(g_f)
    flat_u, _ = jax.flatten_util.ravel_pytree(g_u)
    np.testing.assert_allclose(np.asarray(flat_f), np.asarray(flat_u),
                               rtol=2e-3, atol=2e-4)


def test_fastegnn_blocked_batch_ignores_fuse(rng):
    """Blocked layouts keep their two-call path: fuse_agg must be a no-op."""
    from distegnn_tpu.models.fast_egnn import FastEGNN

    g = pad_graphs([_graph(rng, 24), _graph(rng, 17)], edge_block=8)
    kw = dict(node_feat_nf=2, edge_attr_nf=2, hidden_nf=16, virtual_channels=3,
              n_layers=2)
    params = FastEGNN(**kw).init(jax.random.PRNGKey(0), g)
    out_f = FastEGNN(**kw, fuse_agg=True).apply(params, g)
    out_u = FastEGNN(**kw, fuse_agg=False).apply(params, g)
    np.testing.assert_allclose(out_f[0], out_u[0], atol=0, rtol=0)


@pytest.mark.parametrize("seg", ["scatter", "cumsum"])
def test_fastschnet_fuse_agg_parity(batch, rng, seg):
    """FastSchNet applies the same per-layer aggregation fusion."""
    from distegnn_tpu.models.fast_schnet import FastSchNet

    g = batch
    kw = dict(node_feat_nf=2, edge_attr_nf=2, hidden_nf=16, virtual_channels=3,
              n_layers=2, segment_impl=seg)
    m_f = FastSchNet(**kw)
    m_u = FastSchNet(**kw, fuse_agg=False)
    params = m_f.init(jax.random.PRNGKey(0), g)
    out_f = m_f.apply(params, g)
    out_u = m_u.apply(params, g)
    np.testing.assert_allclose(out_f[0], out_u[0], rtol=1e-5, atol=5e-5)
    np.testing.assert_allclose(out_f[1], out_u[1], rtol=1e-5, atol=5e-5)


def test_fastegnn_fuse_agg_bf16_compute(batch, rng):
    """compute_dtype=bf16 models: the fused path accumulates f32 where the
    legacy path accumulated bf16, so outputs agree only to bf16 rounding —
    the documented (and precision-improving) numerics delta."""
    from distegnn_tpu.models.fast_egnn import FastEGNN

    g = batch
    kw = dict(node_feat_nf=2, edge_attr_nf=2, hidden_nf=16, virtual_channels=3,
              n_layers=2, compute_dtype="bf16")
    m_f = FastEGNN(**kw)
    m_u = FastEGNN(**kw, fuse_agg=False)
    params = m_f.init(jax.random.PRNGKey(0), g)
    out_f = m_f.apply(params, g)
    out_u = m_u.apply(params, g)
    np.testing.assert_allclose(np.asarray(out_f[0], np.float32),
                               np.asarray(out_u[0], np.float32),
                               rtol=3e-2, atol=3e-2)
    np.testing.assert_allclose(np.asarray(out_f[1], np.float32),
                               np.asarray(out_u[1], np.float32),
                               rtol=3e-2, atol=3e-2)
