"""Test harness: force an 8-virtual-device CPU platform so multi-chip sharding
is exercised without a pod (SURVEY.md §4: simulate the 8-way partition on CPU).

Note: a pytest plugin imports jax before this conftest runs, so env vars are
too late — use jax.config.update instead (valid until a backend initializes).
float32 matmuls run at 'highest' precision so equivariance tolerances (1e-4,
parity with reference equivariant_test.py:62) hold on any backend.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running test (multi-process spawns)")


@pytest.fixture
def rng():
    return np.random.default_rng(43)
