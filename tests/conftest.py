"""Test harness: force an 8-virtual-device CPU platform so multi-chip sharding
is exercised without a pod (SURVEY.md §4: simulate the 8-way partition on CPU).

Note: a pytest plugin imports jax before this conftest runs, so env vars are
too late — use jax.config.update instead (valid until a backend initializes).
float32 matmuls run at 'highest' precision so equivariance tolerances (1e-4,
parity with reference equivariant_test.py:62) hold on any backend.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax < 0.5: the option doesn't exist; the XLA flag is read when the CPU
    # backend initializes (first device use), which hasn't happened yet even
    # though jax is imported — so the env route still works here.
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running test (multi-process spawns)")
    # serve tests are tier-1 (NOT slow): CPU-only via JAX_PLATFORMS=cpu, the
    # queue/batcher exercised fully in-process — no network sockets
    config.addinivalue_line("markers", "serve: serving-stack tests (distegnn_tpu/serve)")
    # process-backed serving worker tests: each spawns at least one real
    # child interpreter (slow jax import). One smoke test stays tier-1; the
    # full matrix (chaos drill, swap-under-workers) is additionally `slow`.
    config.addinivalue_line(
        "markers", "process: spawns serving worker child processes")
    # io tests exercise the out-of-core streamed pipeline (data/stream.py);
    # the full-epoch blocked-layout parity sweep is additionally `slow`
    config.addinivalue_line(
        "markers", "io: input-pipeline tests (sharded datasets, prefetch)")


@pytest.fixture(autouse=True)
def _reap_worker_children():
    """Serving worker children must never outlive their test. The parent-side
    bookkeeping (worker._LIVE + atexit) covers interpreter exit; this covers
    the inter-test gap — a FAILED process-marked test can bail between spawn
    and terminate, and the next test must not inherit its children. Bounded:
    reap_live_workers escalates SIGTERM → SIGKILL and joins each child."""
    yield
    import sys

    wmod = sys.modules.get("distegnn_tpu.serve.worker")
    if wmod is not None:
        wmod.reap_live_workers(join_timeout_s=10.0)


@pytest.fixture
def rng():
    return np.random.default_rng(43)


def make_water3d_h5(base_dir, n_part, t_frames, step_scale, seed):
    """Synthetic Water-3D raw h5 (the reference's converted DeepMind layout:
    traj_<k>/position [T,N,3] + particle_type [N]) for train/valid/test —
    shared by the pipeline and e2e tests. (test_rollout.py keeps its own
    constant-velocity variant: rollout checks need a different trajectory
    model.) Returns the data_dir to pass to the processors."""
    import h5py

    rng = np.random.default_rng(seed)
    base = os.path.join(str(base_dir), "Water-3D")
    os.makedirs(base, exist_ok=True)
    for split in ("train", "valid", "test"):
        with h5py.File(os.path.join(base, f"{split}.h5"), "w") as f:
            for k in range(2):
                g = f.create_group(f"traj_{k}")
                g["particle_type"] = np.full((n_part,), 5.0)
                pos = rng.uniform(0, 0.5, size=(1, n_part, 3)).astype(np.float32)
                steps = rng.normal(
                    size=(t_frames - 1, n_part, 3)).astype(np.float32) * step_scale
                g["position"] = np.concatenate(
                    [pos, pos + np.cumsum(steps, axis=0)], axis=0)
    return str(base_dir)


def assert_run_artifacts(log_dir):
    """The shared trainer's on-disk contract: some run dir under log_dir has
    log/log.json (trainer.py log_dir layout)."""
    runs = os.listdir(str(log_dir))
    assert any(os.path.exists(os.path.join(str(log_dir), r, "log", "log.json"))
               for r in runs)
