"""Supervised replica pool (serve/replica.py + supervisor.py) and blue/green
hot-swap (registry.py): round-robin admission parity, at-most-once failover
on crash and wedge, exponential backoff + circuit breaker driven through
deterministic supervisor ticks, typed all-replicas-down shedding with
Retry-After, checksummed swap with NaN-canary rollback, and the queue's
windowed dispatcher-restart budget — all CPU, no sockets except the
per-model shed isolation test which drives a live gateway."""

import threading
import time
from types import SimpleNamespace

import jax
import numpy as np
import pytest

from distegnn_tpu.models.fast_egnn import FastEGNN
from distegnn_tpu.obs.metrics import MetricsRegistry
from distegnn_tpu.ops.graph import pad_graphs
from distegnn_tpu.serve import (InferenceEngine, ModelUnavailableError,
                                RequestQueue, ServeMetrics, synthetic_graph)
from distegnn_tpu.serve.registry import (ModelEntry, ModelRegistry, SwapError,
                                         SwapInProgressError)
from distegnn_tpu.serve.replica import Replica, ReplicaSet, _Tracked
from distegnn_tpu.serve.queue import ServeFuture
from distegnn_tpu.testing import corrupt_swap_checkpoint
from distegnn_tpu.train.checkpoint import save_checkpoint

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def tiny():
    model = FastEGNN(node_feat_nf=1, edge_attr_nf=2, hidden_nf=16,
                     virtual_channels=2, n_layers=2)
    graph = synthetic_graph(26, seed=5)
    tight = pad_graphs([graph], node_bucket=1, edge_bucket=1)
    params = model.init(jax.random.PRNGKey(0), tight)
    x, _ = model.apply(params, tight)
    return SimpleNamespace(model=model, graph=graph, params=params,
                           ref=np.asarray(x[0]))


def _mk_rset(tiny, n, sup=None, name="m", **q_kw):
    """N shared-nothing (engine, queue) replicas with one shared metrics;
    the supervisor heartbeat is parked at an hour so tests drive tick()
    with synthetic clocks instead of racing a background thread."""
    metrics = ServeMetrics()
    kw = dict(batch_deadline_ms=2.0, queue_capacity=32,
              request_timeout_ms=30_000.0, result_margin_s=30.0)
    kw.update(q_kw)
    pairs = []
    for _ in range(n):
        eng = InferenceEngine(tiny.model, tiny.params, max_batch=2,
                              metrics=metrics)
        pairs.append((eng, RequestQueue(eng, metrics=metrics, **kw)))
    opts = dict(heartbeat_s=3600.0)
    opts.update(sup or {})
    return ReplicaSet(name, pairs, supervisor_opts=opts)


def _g(tiny):
    return dict(tiny.graph)


# ---- admission & round robin ------------------------------------------------

def test_unsupervised_set_passes_through_queue_errors(tiny):
    """A never-started set surfaces replica 0's own admission error (the
    legacy single-queue contract tests and benches rely on)."""
    rset = _mk_rset(tiny, 2)
    with pytest.raises(RuntimeError, match="not started"):
        rset.submit(_g(tiny))


def test_round_robin_parity_across_replicas(tiny):
    rset = _mk_rset(tiny, 2).start()
    try:
        futs = [rset.submit(_g(tiny)) for _ in range(4)]
        for f in futs:
            np.testing.assert_allclose(f.result(timeout=60.0), tiny.ref,
                                       atol=1e-4, rtol=0)
        # both replicas actually served traffic
        assert {f.meta["replica"] for f in futs} == {0, 1}
        assert rset.available() == 2
    finally:
        rset.stop()


def test_untrack_claims_exactly_once(tiny):
    """The at-most-once protocol's core: compare-and-pop means exactly one
    of the competing claimers (done-callback vs supervisor drain) wins."""
    rset = _mk_rset(tiny, 1)
    rec = _Tracked("predict", {}, None, None, ServeFuture())
    r = rset.replicas[0]
    r.track(rec)
    assert r.untrack(rec) is True
    assert r.untrack(rec) is False
    assert r.drain_inflight() == []


# ---- failover ---------------------------------------------------------------

def test_failover_on_kill_is_at_most_once(tiny):
    """Killing the replica holding an in-flight request moves it to the
    survivor exactly once; the later supervisor pass claims nothing."""
    rset = _mk_rset(tiny, 2).start()
    try:
        # park both dispatchers so the request stays claimable in-flight
        for r in rset.replicas:
            r.queue.wedge(1.0)
        fut = rset.submit(_g(tiny))
        hit = next(r for r in rset.replicas if r.inflight_count() == 1)
        other = rset.replicas[1 - hit.idx]
        hit.queue.kill(reason="chaos test")
        out = fut.result(timeout=60.0)
        np.testing.assert_allclose(out, tiny.ref, atol=1e-4, rtol=0)
        assert fut.meta["replica"] == other.idx
        assert rset.metrics.snapshot()["requests_failed_over"] == 1
        # the supervisor's crash pass finds nothing left to claim
        rset.supervisor.tick()
        assert rset.metrics.snapshot()["requests_failed_over"] == 1
        assert hit.state in ("backoff", "broken")
        assert rset.available() == 1
    finally:
        rset.stop()


def test_wedge_detected_and_failed_over(tiny):
    """A dispatcher with queued work but no batch progress past the wedge
    deadline is abandoned: its in-flight request completes on the survivor
    and the wedged replica is scheduled for restart."""
    rset = _mk_rset(tiny, 2, sup=dict(wedge_timeout_s=0.4)).start()
    try:
        for r in rset.replicas:
            r.queue.wedge(30.0)
        fut = rset.submit(_g(tiny))
        hit = next(r for r in rset.replicas if r.inflight_count() == 1)
        other = rset.replicas[1 - hit.idx]
        other.queue.wedge(0.0)          # survivor resumes immediately
        time.sleep(0.6)                 # > wedge_timeout_s with no progress
        rset.supervisor.tick()
        out = fut.result(timeout=60.0)
        np.testing.assert_allclose(out, tiny.ref, atol=1e-4, rtol=0)
        assert fut.meta["replica"] == other.idx
        assert hit.state == "backoff" and hit.last_reason == "wedge"
        assert rset.metrics.snapshot()["requests_failed_over"] == 1
        assert not hit.queue.alive()    # killed, not left running wedged
    finally:
        rset.stop()


def test_all_replicas_down_sheds_typed_with_retry_hint(tiny):
    rset = _mk_rset(tiny, 1, sup=dict(backoff_base_s=0.5)).start()
    try:
        rset.replicas[0].queue.kill(reason="boom")
        with pytest.raises(ModelUnavailableError) as ei:
            rset.submit(_g(tiny))
        assert ei.value.model == "m"
        assert ei.value.retry_after_s == pytest.approx(1.0)  # not yet ticked
        rset.supervisor.tick()          # crash noticed, restart scheduled
        with pytest.raises(ModelUnavailableError) as ei:
            rset.submit(_g(tiny))
        assert 0.1 <= ei.value.retry_after_s <= 0.51
    finally:
        rset.stop()


# ---- supervisor state machine (synthetic clock) -----------------------------

def test_supervisor_backoff_doubles_then_breaker_opens(tiny):
    rset = _mk_rset(tiny, 1, sup=dict(backoff_base_s=0.5, backoff_max_s=8.0,
                                      breaker_threshold=3,
                                      breaker_cooldown_s=30.0,
                                      healthy_reset_s=5.0)).start()
    sup, r = rset.supervisor, rset.replicas[0]
    try:
        t = 100.0
        r.queue.kill(reason="c1")
        sup.tick(now=t)
        assert r.state == "backoff" and r.failures == 1
        assert r.next_restart_at == pytest.approx(t + 0.5)
        sup.tick(now=t + 0.4)           # backoff not elapsed: still down
        assert r.state == "backoff" and r.restarts == 0
        sup.tick(now=t + 0.6)           # restart on a FRESH queue
        assert r.state == "running" and r.restarts == 1 and r.queue.alive()
        np.testing.assert_allclose(
            rset.submit(_g(tiny)).result(timeout=60.0), tiny.ref,
            atol=1e-4, rtol=0)
        r.queue.kill(reason="c2")       # second failure: doubled backoff
        sup.tick(now=t + 1.0)
        assert r.failures == 2
        assert r.next_restart_at == pytest.approx(t + 2.0)  # 0.5 * 2^1 later
        sup.tick(now=t + 2.1)
        assert r.state == "running"
        r.queue.kill(reason="c3")       # third: breaker opens, long cooldown
        sup.tick(now=t + 4.0)
        assert r.state == "broken" and r.failures == 3
        assert r.next_restart_at == pytest.approx(t + 34.0)
        sup.tick(now=t + 33.9)
        assert r.state == "broken"
        sup.tick(now=t + 34.1)          # half-open attempt succeeds
        assert r.state == "running"
        sup.tick(now=t + 35.0)          # healthy but < healthy_reset_s
        assert r.failures == 3
        sup.tick(now=t + 40.0)          # healthy interval closes the breaker
        assert r.failures == 0
        assert rset.metrics.snapshot()["replica_restarts"] == 3
    finally:
        rset.stop()


def test_restart_aborts_when_stop_races_it(tiny):
    """A supervisor restart that completes AFTER shutdown began must not
    revive the queue: _restart rechecks _supervised once restart_queue
    returns (a worker spawn can block for seconds, ample time for stop()
    to start draining) and stops the fresh queue instead of marking the
    replica running. Regression for the stop()/restart race."""
    rset = _mk_rset(tiny, 1).start()
    sup, r = rset.supervisor, rset.replicas[0]
    r.queue.kill(reason="crash before shutdown")
    sup.tick(now=100.0)
    assert r.state == "backoff"
    # shutdown begins while the replica is still down: supervisor stopped,
    # drain about to run — then the in-flight restart attempt lands
    rset.begin_stop()
    sup._restart(r, now=101.0)
    assert r.state == "stopped"
    assert not r.queue.alive()
    rset.stop()  # idempotent; drains nothing
    assert r.state == "stopped"


# ---- blue/green hot-swap ----------------------------------------------------

def _mk_entry(tiny, n=2, name="m"):
    metrics = ServeMetrics()
    kw = dict(batch_deadline_ms=2.0, request_timeout_ms=30_000.0)
    engine = InferenceEngine(tiny.model, tiny.params, max_batch=2,
                             metrics=metrics)
    queue = RequestQueue(engine, metrics=metrics, **kw)
    extra = []
    for _ in range(n - 1):
        e2 = InferenceEngine(tiny.model, tiny.params, max_batch=2,
                             metrics=metrics)
        extra.append((e2, RequestQueue(e2, metrics=metrics, **kw)))
    return ModelEntry(name, engine, queue, feat_nf=1, edge_attr_nf=2,
                      extra_replicas=extra,
                      supervisor_opts=dict(heartbeat_s=3600.0))


def _save_params(path, params):
    save_checkpoint(str(path),
                    SimpleNamespace(params=params, opt_state={}, step=0),
                    epoch=0)


def test_swap_flips_every_replica_bitwise(tiny, tmp_path):
    """A successful swap serves the NEW checkpoint from every replica with
    predictions bitwise-identical to a cold-started engine on it."""
    entry = _mk_entry(tiny, n=2)
    entry.start()
    entry.warmup([26])
    try:
        params_b = jax.tree.map(lambda x: x * 1.0625, tiny.params)
        ck = tmp_path / "b.ckpt"
        _save_params(ck, params_b)
        info = entry.swap(str(ck))
        assert info["version"] == 1 and info["replicas"] == 2
        assert info["rungs_canaried"] >= 1
        assert entry.params_version == 1 and entry.checkpoint == str(ck)

        futs = [entry.queue.submit(_g(tiny)) for _ in range(2)]
        outs = [f.result(timeout=60.0) for f in futs]
        assert {f.meta["replica"] for f in futs} == {0, 1}

        m2 = ServeMetrics()
        cold_eng = InferenceEngine(tiny.model, params_b, max_batch=2,
                                   metrics=m2)
        with RequestQueue(cold_eng, batch_deadline_ms=2.0,
                          request_timeout_ms=30_000.0, metrics=m2) as cold_q:
            cold_out = cold_q.submit(_g(tiny)).result(timeout=60.0)
        for out in outs:
            np.testing.assert_array_equal(np.asarray(out),
                                          np.asarray(cold_out))
    finally:
        entry.stop()


def test_swap_corrupt_checkpoint_fails_at_restore(tiny, tmp_path):
    entry = _mk_entry(tiny, n=1)
    entry.start()
    entry.warmup([26])
    try:
        old = entry.engine.params
        ck = tmp_path / "bad.ckpt"
        _save_params(ck, tiny.params)
        corrupt_swap_checkpoint(str(ck), mode="garbage")
        with pytest.raises(SwapError) as ei:
            entry.swap(str(ck))
        assert ei.value.stage == "restore" and ei.value.rolled_back
        assert entry.engine.params is old and entry.params_version == 0
        np.testing.assert_allclose(
            entry.queue.submit(_g(tiny)).result(timeout=60.0), tiny.ref,
            atol=1e-4, rtol=0)
    finally:
        entry.stop()


def test_swap_nan_canary_rolls_back_flipped_replicas(tiny, tmp_path):
    entry = _mk_entry(tiny, n=2)
    entry.start()
    entry.warmup([26])
    try:
        old = entry.engine.params
        params_nan = jax.tree.map(lambda x: np.full_like(x, np.nan),
                                  tiny.params)
        ck = tmp_path / "nan.ckpt"
        _save_params(ck, params_nan)
        with pytest.raises(SwapError) as ei:
            entry.swap(str(ck))
        assert ei.value.stage == "canary" and ei.value.rolled_back
        assert entry.params_version == 0
        for r in entry.replicas.replicas:
            assert r.engine.params is old
        np.testing.assert_allclose(
            entry.queue.submit(_g(tiny)).result(timeout=60.0), tiny.ref,
            atol=1e-4, rtol=0)
    finally:
        entry.stop()


def test_swap_one_at_a_time(tiny, tmp_path):
    entry = _mk_entry(tiny, n=1)
    entry.start()
    entry.warmup([26])
    try:
        ck = tmp_path / "b.ckpt"
        _save_params(ck, tiny.params)
        assert entry._swap_lock.acquire(blocking=False)
        try:
            with pytest.raises(SwapInProgressError):
                entry.swap(str(ck))
        finally:
            entry._swap_lock.release()
    finally:
        entry.stop()


def test_scale_up_racing_swap_lands_on_live_version(tmp_path):
    """Registry claim (``ModelEntry.replica_factory`` docstring): a replica
    added while a blue/green swap is in flight comes up on the LIVE
    version. The factory snapshots the entry's params at build time, so a
    build that reads the pre-swap snapshot and appends after the flip loop
    finished would otherwise serve the retired version forever — the worst
    interleaving, forced deterministically here by gating the factory
    until the swap has fully landed."""
    from distegnn_tpu.config import ConfigDict, _DEFAULTS
    from distegnn_tpu.serve.autoscale import ReplicaAutoscaler
    from distegnn_tpu.serve.registry import ModelRegistry

    cfg = ConfigDict(_DEFAULTS)
    cfg.serve.replicas = 1
    registry = ModelRegistry.from_config(cfg)
    entry = registry.get("default")
    registry.start()
    try:
        entry.warmup([26])
        assert entry.replica_factory is not None

        orig = entry.replica_factory
        built = threading.Event()
        release = threading.Event()

        def gated(idx):
            rep = orig(idx)          # snapshots entry.engine.params NOW
            built.set()
            assert release.wait(60.0)
            return rep

        entry.replica_factory = gated
        auto = ReplicaAutoscaler(registry, config=dict(enable=True))
        grow_err = []

        def grow():
            try:
                auto._grow("default", entry, 1)
            except Exception as exc:
                grow_err.append(exc)

        t = threading.Thread(target=grow, daemon=True)
        t.start()
        assert built.wait(60.0), "replica factory never ran"

        # the swap runs to completion while the stale-built replica is
        # still unappended: its flip loop sees ONE replica
        params_b = jax.tree.map(lambda x: x * 1.0625, entry.engine.params)
        ck = tmp_path / "b.ckpt"
        _save_params(ck, params_b)
        info = entry.swap(str(ck))
        assert info["replicas"] == 1 and entry.params_version == 1

        release.set()
        t.join(timeout=120.0)
        assert not t.is_alive() and not grow_err, grow_err

        reps = entry.replicas.replicas
        assert len(reps) == 2
        # the late joiner was re-pinned to the live version, not left on
        # the snapshot it was built from
        for r in reps:
            assert r.engine.params is entry.engine.params
        # and both replicas actually serve it: round-robin pair agrees
        g = synthetic_graph(26, seed=5,
                            feat_nf=int(cfg.model.node_feat_nf),
                            edge_attr_nf=int(cfg.model.edge_attr_nf))
        futs = [entry.queue.submit(dict(g)) for _ in range(2)]
        outs = [f.result(timeout=120.0) for f in futs]
        assert {f.meta["replica"] for f in futs} == {0, 1}
        np.testing.assert_array_equal(np.asarray(outs[0]),
                                      np.asarray(outs[1]))
    finally:
        registry.stop(drain=False)


# ---- per-model shed isolation over a live socket ----------------------------

def test_gateway_sheds_only_the_dead_model(tiny):
    """With model 'a' fully down, its route 503s typed with Retry-After
    while model 'b' keeps serving; /readyz reports degraded; /metrics
    exposes the per-replica up gauges."""
    import json as _json
    import urllib.error
    import urllib.request

    from distegnn_tpu.serve.transport import Gateway

    ea = _mk_entry(tiny, n=1, name="a")
    eb = _mk_entry(tiny, n=1, name="b")
    reg = ModelRegistry({"a": ea, "b": eb})
    reg.start()
    reg.warmup([26])
    gw = Gateway(reg, port=0, max_inflight=16,
                 metrics_registry=MetricsRegistry())
    thread = threading.Thread(target=gw.serve_forever, daemon=True)
    thread.start()

    def post(path, payload):
        req = urllib.request.Request(
            gw.url(path), data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=60.0) as r:
                return r.status, dict(r.headers), _json.load(r)
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers), _json.load(e)

    g = tiny.graph
    payload = {"positions": g["loc"].tolist(),
               "velocities": g["vel"].tolist(),
               "node_feat": g["node_feat"].tolist(),
               "edge_index": g["edge_index"].tolist(),
               "edge_attr": g["edge_attr"].tolist()}
    try:
        ea.replicas.replicas[0].queue.kill(reason="chaos")
        status, headers, body = post("/v1/models/a/predict", payload)
        assert status == 503 and body["type"] == "ModelUnavailable"
        assert body["model"] == "a"
        assert float(headers["Retry-After"]) >= 0.1
        status, _, body = post("/v1/models/b/predict", payload)
        assert status == 200
        np.testing.assert_allclose(np.asarray(body["prediction"]), tiny.ref,
                                   atol=1e-4, rtol=0)
        with urllib.request.urlopen(gw.url("/readyz"), timeout=30.0) as r:
            rz = _json.load(r)
            assert r.status == 200
        assert rz["degraded"] is True
        assert rz["models"]["a"]["ready"] is False
        assert rz["models"]["a"]["replicas_available"] == 0
        assert rz["models"]["b"]["ready"] is True
        with urllib.request.urlopen(gw.url("/metrics"), timeout=30.0) as r:
            prom = r.read().decode()
        gauges = {ln.rsplit(" ", 1)[0]: float(ln.rsplit(" ", 1)[1])
                  for ln in prom.splitlines()
                  if ln and not ln.startswith("#")}
        up = {k: v for k, v in gauges.items() if "replica" in k}
        assert any(k.endswith("replica_a_0_up") and v == 0.0
                   for k, v in up.items())
        assert any(k.endswith("replica_b_0_up") and v == 1.0
                   for k, v in up.items())
        assert any(k.endswith("replicas_a_available") and v == 0.0
                   for k, v in up.items())
    finally:
        gw.drain()
        thread.join(timeout=30.0)
        gw.close()


# ---- the chaos drill: kill + live hot-swap under replayed traffic ----------

def test_chaos_drill_kill_and_swap_under_traffic(tmp_path):
    """The PR's acceptance drill, all from ONE ``traffic_gen --chaos`` run:
    with 2 replicas, a replica kill mid-replay loses ZERO accepted
    requests (failover + Retry-After retries absorb the blip inside the
    declared SLO bound), and a live blue/green hot-swap under that same
    traffic serves predictions bitwise-identical to a cold-started engine
    on the new checkpoint (asserted via the run's chaos/swap_probe
    event)."""
    import base64
    import json as _json
    import os
    import subprocess
    import sys

    from distegnn_tpu.config import ConfigDict, _DEFAULTS
    from distegnn_tpu.serve import engine_from_config
    from distegnn_tpu.serve.registry import ModelRegistry

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = ConfigDict(_DEFAULTS)
    # the same deterministic init path the in-process gateway runs (default
    # config + seed), so checkpoint B is structurally identical to the
    # params the subprocess gateway serves
    entry = ModelRegistry.from_config(cfg).get("default")
    params_b = jax.tree.map(lambda x: x * 1.0625, entry.engine.params)
    ck = tmp_path / "b.ckpt"
    _save_params(ck, params_b)
    spec = tmp_path / "slo.yaml"
    spec.write_text("slo:\n"
                    "  routes:\n"
                    "    predict:\n"
                    "      p99_ms: 60000\n"
                    "  error_rate_max: 0.0\n")
    obs_dir = tmp_path / "tg"

    r = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "traffic_gen.py"),
         "--requests", "24", "--rate", "40", "--mix", "predict=1.0",
         "--sizes", "24", "--replicas", "2", "--seed", "7",
         "--chaos", f"kill@0.25:replica=0;swap@0.9:ckpt={ck}",
         "--slo", str(spec), "--obs-dir", str(obs_dir)],
        capture_output=True, text=True, cwd=repo, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr
    lines = [ln for ln in r.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, r.stdout
    rec = _json.loads(lines[0])

    # zero accepted futures lost through the kill; error blip within bound
    assert rec["completed"] == 24 and rec["lost"] == 0
    assert rec["errors"] == 0
    assert rec["slo"]["pass"] is True, rec["slo"]
    by_action = {c["action"]: c for c in rec["chaos"]}
    assert by_action["kill"]["ok"] is True
    assert by_action["swap"]["ok"] is True
    assert by_action["swap"]["swap"]["version"] == 1

    # the probe prediction from the swapped live gateway, bit for bit
    probe = None
    with open(obs_dir / "obs" / "events.jsonl") as f:
        for line in f:
            e = _json.loads(line)
            if e.get("name") == "chaos/swap_probe":
                probe = e
    assert probe is not None, "swap probe never fired"
    pd = probe["prediction"]
    live = np.frombuffer(base64.b64decode(pd["b64"]),
                         dtype="<f4").reshape(pd["shape"])

    # cold-started engine on checkpoint B, fed the byte-identical probe
    g = synthetic_graph(24, seed=1234, feat_nf=int(cfg.model.node_feat_nf),
                        edge_attr_nf=int(cfg.model.edge_attr_nf))
    for k in ("loc", "vel", "node_feat", "edge_attr"):
        g[k] = np.ascontiguousarray(g[k], dtype="<f4")
    g["edge_index"] = np.ascontiguousarray(g["edge_index"], dtype="<i4")
    from distegnn_tpu.models.registry import get_model

    model = get_model(cfg.model, dataset_name=cfg.data.dataset_name)
    eng, q = engine_from_config(cfg, model, params=params_b)
    with q:
        cold = q.submit(g).result(timeout=120.0)
    np.testing.assert_array_equal(live, np.asarray(cold, dtype="<f4"))


# ---- queue restart budget (windowed) ---------------------------------------

class _CrashingMetrics(ServeMetrics):
    """set_queue_depth raises ``bombs`` times — a deterministic dispatcher
    loop crash (a bug, not an engine error, so the restart budget applies)."""

    def __init__(self, bombs=0):
        super().__init__()
        self.bombs = bombs

    def set_queue_depth(self, depth):
        if self.bombs > 0:
            self.bombs -= 1
            raise RuntimeError("injected dispatcher crash")
        super().set_queue_depth(depth)


class _FakeEngine:
    def __init__(self, metrics, max_batch=4):
        from distegnn_tpu.serve import BucketLadder

        self.ladder = BucketLadder(max_nodes=256, max_edges=1024)
        self.metrics = metrics
        self.max_batch = max_batch

    def predict_batch(self, graphs, bucket=None, request_ids=None):
        return [np.zeros((g["loc"].shape[0], 3)) for g in graphs]


def _fake_graph():
    return {"loc": np.zeros((10, 3)),
            "edge_index": np.zeros((2, 20), np.int32)}


def test_restart_budget_replenishes_after_quiet_interval(monkeypatch):
    """Crash bursts separated by a healthy interval never exhaust the
    dispatcher restart budget: only crashes inside the sliding window
    count, so transient crash clusters spread over time keep serving."""
    from distegnn_tpu.serve import queue as qmod

    monkeypatch.setattr(qmod, "_RESTART_WINDOW_S", 0.3)
    metrics = _CrashingMetrics(bombs=qmod._MAX_WORKER_RESTARTS)
    eng = _FakeEngine(metrics)
    q = RequestQueue(eng, batch_deadline_ms=5.0).start()
    try:
        out = q.submit(_fake_graph()).result(timeout=10.0)
        assert out.shape == (10, 3)     # survived a full burst of 3
        assert metrics.snapshot()["worker_restarts"] == 3
        time.sleep(0.4)                 # crash times age out of the window
        metrics.bombs = qmod._MAX_WORKER_RESTARTS
        out = q.submit(_fake_graph()).result(timeout=10.0)
        assert out.shape == (10, 3)     # replenished: a second burst of 3
        assert q.alive()
        assert metrics.snapshot()["worker_restarts"] == 6
    finally:
        q.stop()
