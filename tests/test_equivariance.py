"""SE(3) equivariance property tests — parity with reference
equivariant_test.py (atol 1e-4 on a random 10-node/20-edge graph)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distegnn_tpu.models.fast_egnn import FastEGNN
from distegnn_tpu.ops.graph import pad_graphs
from distegnn_tpu.utils.rotate import random_rotate


def _random_graph(rng, n=10, e=20, feat_nf=1, edge_nf=1):
    return dict(
        node_feat=rng.uniform(0, 10, size=(n, feat_nf)).astype(np.float32),
        loc=rng.uniform(0, 10, size=(n, 3)).astype(np.float32),
        vel=rng.uniform(0, 10, size=(n, 3)).astype(np.float32),
        target=np.zeros((n, 3), np.float32),
        edge_index=rng.integers(0, n, size=(2, e)),
        edge_attr=rng.uniform(0, 10, size=(e, edge_nf)).astype(np.float32),
    )


def _transform(g, R, t):
    out = dict(g)
    out["loc"] = (g["loc"] @ R + t).astype(np.float32)
    out["vel"] = (g["vel"] @ R).astype(np.float32)
    return out


@pytest.mark.parametrize("normalize", [False, True])
def test_fastegnn_se3_equivariance(rng, normalize):
    """Mirror of reference equivariant_test.py:12-62 (same sizes, atol 1e-4)."""
    model = FastEGNN(node_feat_nf=1, node_attr_nf=0, edge_attr_nf=1, hidden_nf=64,
                     virtual_channels=3, n_layers=4, normalize=normalize)
    g = _random_graph(rng)
    R = random_rotate(rng).astype(np.float32)
    t = (rng.normal(size=(3,)) * 5).astype(np.float32)

    gb = pad_graphs([g], node_bucket=1, edge_bucket=1)
    gb_r = pad_graphs([_transform(g, R, t)], node_bucket=1, edge_bucket=1)

    params = model.init(jax.random.PRNGKey(0), gb)
    out, vout = model.apply(params, gb)
    out_r, vout_r = model.apply(params, gb_r)

    np.testing.assert_allclose(np.asarray(out[0]) @ R + t, np.asarray(out_r[0]),
                               atol=1e-4, rtol=0)
    # virtual nodes are equivariant too: X' = R^T applied per channel
    np.testing.assert_allclose(
        np.einsum("dc,de->ec", np.asarray(vout[0]), R) + t[:, None],
        np.asarray(vout_r[0]), atol=1e-4, rtol=0)


def test_fastegnn_equivariance_with_padding(rng):
    """Padding must not break equivariance: same graph padded to N=16/E=64."""
    model = FastEGNN(node_feat_nf=1, node_attr_nf=0, edge_attr_nf=1, hidden_nf=32,
                     virtual_channels=3, n_layers=2)
    g = _random_graph(rng)
    R = random_rotate(rng).astype(np.float32)
    t = (rng.normal(size=(3,)) * 5).astype(np.float32)

    tight = pad_graphs([g], node_bucket=1, edge_bucket=1)
    padded = pad_graphs([g], max_nodes=16, max_edges=64)
    padded_r = pad_graphs([_transform(g, R, t)], max_nodes=16, max_edges=64)

    params = model.init(jax.random.PRNGKey(0), tight)
    out_tight, _ = model.apply(params, tight)
    out_pad, _ = model.apply(params, padded)
    # padding invariance on the real nodes
    np.testing.assert_allclose(np.asarray(out_tight[0]), np.asarray(out_pad[0, :10]),
                               atol=1e-5, rtol=0)
    # equivariance through the padded path
    out_pad_r, _ = model.apply(params, padded_r)
    np.testing.assert_allclose(np.asarray(out_pad[0, :10]) @ R + t,
                               np.asarray(out_pad_r[0, :10]), atol=1e-4, rtol=0)


def test_fastegnn_bf16_equivariance_and_parity(rng):
    """compute_dtype='bf16' keeps equivariance structurally exact (geometry
    stays f32; bf16 touches only invariant-channel MLPs) — tolerance loosened
    deliberately for bf16 rounding of the invariant inputs. Outputs must also
    track the f32 model closely (same params)."""
    kw = dict(node_feat_nf=1, node_attr_nf=0, edge_attr_nf=1, hidden_nf=64,
              virtual_channels=3, n_layers=4)
    model32 = FastEGNN(**kw)
    model16 = FastEGNN(**kw, compute_dtype="bf16")
    g = _random_graph(rng)
    R = random_rotate(rng).astype(np.float32)
    t = (rng.normal(size=(3,)) * 5).astype(np.float32)
    gb = pad_graphs([g], node_bucket=1, edge_bucket=1)
    gb_r = pad_graphs([_transform(g, R, t)], node_bucket=1, edge_bucket=1)

    params = model32.init(jax.random.PRNGKey(0), gb)  # same tree for both
    out32, _ = model32.apply(params, gb)
    out16, _ = model16.apply(params, gb)
    out16_r, _ = model16.apply(params, gb_r)

    scale = float(np.abs(np.asarray(out32)).max())
    np.testing.assert_allclose(np.asarray(out16), np.asarray(out32),
                               atol=3e-2 * scale, rtol=0)
    np.testing.assert_allclose(np.asarray(out16[0]) @ R + t, np.asarray(out16_r[0]),
                               atol=3e-2 * scale, rtol=0)


def test_fastegnn_bf16_loss_parity(rng):
    """Train-step loss under bf16 compute must track f32 (same params/batch)."""
    from distegnn_tpu.train import TrainState, make_optimizer, make_train_step

    kw = dict(node_feat_nf=1, node_attr_nf=0, edge_attr_nf=1, hidden_nf=32,
              virtual_channels=3, n_layers=2)
    g = _random_graph(rng, n=12, e=30)
    g["target"] = (g["loc"] + 0.1 * g["vel"]).astype(np.float32)
    gb = pad_graphs([g])
    losses = {}
    for name, dt in [("f32", None), ("bf16", "bf16")]:
        model = FastEGNN(**kw, compute_dtype=dt)
        params = FastEGNN(**kw).init(jax.random.PRNGKey(0), gb)
        tx = make_optimizer(1e-3)
        state = TrainState.create(params, tx)
        step = jax.jit(make_train_step(model, tx, mmd_weight=0.03, mmd_sigma=1.5,
                                       mmd_samples=2))
        state, m = step(state, gb, jax.random.PRNGKey(1))
        losses[name] = float(m["loss_with_mmd"])
    assert abs(losses["bf16"] - losses["f32"]) <= 0.05 * abs(losses["f32"]) + 1e-6, losses


def test_fastegnn_batched_forward_jits(rng):
    model = FastEGNN(node_feat_nf=2, node_attr_nf=0, edge_attr_nf=1, hidden_nf=16,
                     virtual_channels=2, n_layers=2)
    graphs = [_random_graph(rng, n=8, e=14, feat_nf=2) for _ in range(3)]
    gb = pad_graphs(graphs)
    params = model.init(jax.random.PRNGKey(1), gb)
    fwd = jax.jit(model.apply)
    out, vout = fwd(params, gb)
    assert out.shape == (3, gb.max_nodes, 3)
    assert vout.shape == (3, 3, 2)
    assert np.all(np.isfinite(np.asarray(out)))


def test_fastegnn_cumsum_equivariance(rng):
    """SE(3) equivariance holds through the scatter-free cumsum lowering
    (segment_impl='cumsum', ops/segment.py) at the reference tolerance —
    the prefix-difference rounding stays below atol 1e-4 at test scale."""
    from distegnn_tpu.data import build_nbody_graph

    n = 24
    loc = rng.normal(size=(n, 3))
    vel = rng.normal(size=(n, 3))
    charges = rng.choice([1.0, -1.0], size=(n, 1))
    g = build_nbody_graph(loc, vel, charges, loc + 0.1 * vel, radius=-1.0)
    R = random_rotate(rng).astype(np.float32)
    t = (rng.normal(size=(3,)) * 5).astype(np.float32)
    g_r = _transform(g, R, t)
    # _transform leaves auxiliary fields alone; the virtual-node seed
    # (loc_mean) must move with the frame or equivariance trivially breaks
    g_r["loc_mean"] = (g["loc_mean"] @ R + t).astype(np.float32)

    model = FastEGNN(node_feat_nf=2, node_attr_nf=0, edge_attr_nf=2,
                     hidden_nf=32, virtual_channels=3, n_layers=2,
                     segment_impl="cumsum")
    gb = pad_graphs([g], compute_pair=True)
    gb_r = pad_graphs([g_r], compute_pair=True)
    params = model.init(jax.random.PRNGKey(0), gb)
    out, _ = model.apply(params, gb)
    out_r, _ = model.apply(params, gb_r)
    np.testing.assert_allclose(np.asarray(out[0]) @ R + t, np.asarray(out_r[0]),
                               atol=1e-4, rtol=0)
