"""Water-3D / Fluid113K / protein pipeline tests on synthetic raw files
(the real datasets are multi-GB downloads; the formats are exercised
faithfully: h5 trajectories, zstd+msgpack-numpy shards, npz cache)."""

import os
import pickle

import numpy as np
import pytest

from distegnn_tpu.data import GraphDataset
from distegnn_tpu.data.fluid113k import SIM_SPLITS, process_large_fluid_distribute, read_sim
from distegnn_tpu.data.protein import TRAIN_VALID_TEST, process_protein_cutoff
from distegnn_tpu.data.water3d import process_water3d_cutoff, process_water3d_distribute

N_PART = 40
T_FRAMES = 40


@pytest.fixture(scope="module")
def water3d_dir(tmp_path_factory):
    from tests.conftest import make_water3d_h5

    return make_water3d_h5(tmp_path_factory.mktemp("w3d"),
                           N_PART, T_FRAMES, step_scale=0.003, seed=0)


def test_water3d_cutoff_pipeline(water3d_dir):
    paths = process_water3d_cutoff(water3d_dir, "Water-3D", max_samples=6,
                                   radius=0.1, delta_t=5, cutoff_rate=0.0, seed=1)
    ds = GraphDataset(paths[0])
    assert len(ds) == 6
    g = ds[0]
    assert g["node_feat"].shape == (N_PART, 2)
    assert g["loc"].shape == (N_PART, 3)
    assert g["edge_index"].shape[0] == 2 and g["edge_index"].shape[1] > 0
    # caching
    assert process_water3d_cutoff(water3d_dir, "Water-3D", max_samples=6,
                                  radius=0.1, delta_t=5, cutoff_rate=0.0, seed=1) == paths


def test_water3d_distribute_pipeline(water3d_dir):
    split_paths = process_water3d_distribute(
        water3d_dir, "Water-3D", world_size=4, max_samples=4,
        inner_radius=0.1, outer_radius=0.15, split_mode="kmeans", delta_t=5, seed=1)
    assert len(split_paths) == 3 and all(len(p) == 4 for p in split_paths)
    shards = [GraphDataset(p) for p in split_paths[0]]
    assert len({len(s) for s in shards}) == 1
    # all partitions of sample 0 share the global loc_mean; nodes sum to N
    lm = shards[0][0]["loc_mean"]
    total = 0
    for s in shards:
        np.testing.assert_allclose(s[0]["loc_mean"], lm, atol=1e-6)
        total += s[0]["loc"].shape[0]
    assert total == N_PART


@pytest.fixture(scope="module")
def fluid_dir(tmp_path_factory):
    import msgpack
    import zstandard as zstd

    def encode_np(o):
        if isinstance(o, np.ndarray):
            return {b"nd": True, b"type": o.dtype.str.encode(),
                    b"shape": list(o.shape), b"data": o.tobytes()}
        return o

    rng = np.random.default_rng(1)
    d = tmp_path_factory.mktemp("fluid")
    base = d / "Fluid113K"
    base.mkdir()
    frames_per_shard = 5
    from distegnn_tpu.data.fluid113k import SHARDS_PER_SIM

    for split, (lo, hi) in SIM_SPLITS.items():
        for idx in (lo, lo + 1):  # two sims per split
            pos = rng.uniform(0, 1, size=(N_PART, 3)).astype(np.float32)
            viscosity = np.full((N_PART,), 0.01, np.float32)
            mass = np.full((N_PART,), 0.1, np.float32)
            cctx = zstd.ZstdCompressor()
            for s in range(SHARDS_PER_SIM):
                frames = []
                for _ in range(frames_per_shard):
                    vel = rng.normal(size=(N_PART, 3)).astype(np.float32) * 0.01
                    pos = pos + vel
                    frames.append({"pos": pos, "vel": vel,
                                   "viscosity": viscosity, "m": mass})
                packed = msgpack.packb(frames, default=encode_np)
                with open(base / f"sim_{idx:04d}_{s:02d}.msgpack.zst", "wb") as f:
                    f.write(cctx.compress(packed))
    return str(d)


def test_fluid_read_sim_roundtrip(fluid_dir):
    pos, vel, viscosity, mass = read_sim(fluid_dir, "Fluid113K", SIM_SPLITS["train"][0])
    assert pos.shape == (80, N_PART, 3) and vel.shape == (80, N_PART, 3)
    assert viscosity.shape == (N_PART,) and mass.shape == (N_PART,)


def test_fluid_distribute_pipeline(fluid_dir):
    split_paths = process_large_fluid_distribute(
        fluid_dir, "Fluid113K", world_size=2, max_samples=4,
        inner_radius=0.4, outer_radius=0.5, split_mode="random", delta_t=3, seed=2)
    shards = [GraphDataset(p) for p in split_paths[0]]
    assert len(shards[0]) == len(shards[1]) == 4
    g = shards[0][0]
    assert g["node_feat"].shape[1] == 3      # [viscosity, mass, |v|]
    assert g["node_attr"].shape[1] == 2


@pytest.fixture(scope="module")
def protein_dir(tmp_path_factory):
    rng = np.random.default_rng(2)
    d = tmp_path_factory.mktemp("prot")
    base = d / "protein"
    base.mkdir()
    T, N = 4180, 30
    start = rng.uniform(0, 20, size=(1, N, 3)).astype(np.float32)
    steps = rng.normal(size=(T - 1, N, 3)).astype(np.float32) * 0.05
    positions = np.concatenate([start, start + np.cumsum(steps, axis=0)], axis=0)
    charges = rng.uniform(0.1, 1.0, size=(N,)).astype(np.float32)
    np.savez_compressed(base / "adk_backbone.npz", positions=positions, charges=charges)
    return str(d)


def test_protein_pipeline_and_split(protein_dir):
    paths = process_protein_cutoff(protein_dir, "protein", max_samples=10**9,
                                   radius=10.0, delta_t=5, cutoff_rate=0.0)
    names = dict(zip(("train", "valid", "test"), paths))
    ds = GraphDataset(names["valid"])
    assert len(ds) == TRAIN_VALID_TEST["valid"][1] - TRAIN_VALID_TEST["valid"][0]
    g = ds[0]
    assert g["node_feat"].shape == (30, 2)
    assert g["vel"].dtype == np.float32


def test_protein_test_rotation_injection(protein_dir):
    """test_rot rotates ONLY the test split (reference empirical-equivariance
    eval, process_dataset.py:162-174): targets move coherently with inputs."""
    paths = process_protein_cutoff(protein_dir, "protein", max_samples=50,
                                   radius=10.0, delta_t=5, cutoff_rate=0.0,
                                   test_rot=True, seed=3)
    base_paths = process_protein_cutoff(protein_dir, "protein", max_samples=50,
                                        radius=10.0, delta_t=5, cutoff_rate=0.0)
    rot, base = GraphDataset(paths[2]), GraphDataset(base_paths[2])
    # rotation preserves pairwise distances but changes coordinates
    g_r, g_b = rot[0], base[0]
    assert not np.allclose(g_r["loc"], g_b["loc"], atol=1e-3)
    d_r = np.linalg.norm(g_r["loc"][0] - g_r["loc"][1])
    d_b = np.linalg.norm(g_b["loc"][0] - g_b["loc"][1])
    np.testing.assert_allclose(d_r, d_b, rtol=1e-4)
